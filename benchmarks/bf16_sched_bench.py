"""Time the SHIPPED bf16-warmup schedule end-to-end on TPU.

proto_bf16_master.py measures the raw pass; this measures what users get:
``glm_fit(engine="fused")`` vs ``glm_fit(engine="fused",
config=NumericConfig(bf16_warmup=True))`` on the 2M x 512 logistic
headline shape, device-resident data, full fits to tol=1e-8 — plus the
coefficient agreement between the two (the accuracy contract).

Writes benchmarks/bf16_sched_r05.json incrementally.  ONE tunnel client
at a time (tpu_when_alive.sh).
"""
import json
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402

import sparkglm_tpu as sg  # noqa: E402
from sparkglm_tpu.config import NumericConfig  # noqa: E402

from _capture import dump_atomic, out_path  # noqa: E402

OUT = out_path("bf16_sched")


def main():
    res = {"device": str(jax.devices()[0])}
    n, p = 2_097_152, 512
    kx, kb = jax.random.split(jax.random.PRNGKey(0))

    @jax.jit
    def gen():
        X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
        bt = jax.random.normal(kb, (p,), jnp.float32) / (2.0 * p ** 0.5)
        y = (jax.random.uniform(jax.random.PRNGKey(1), (n,))
             < jax.nn.sigmoid(X @ bt)).astype(jnp.float32)
        return X, y

    X, y = gen()
    jax.block_until_ready(y)
    mesh = sg.make_mesh()
    kw = dict(family="binomial", tol=1e-8, criterion="relative",
              engine="fused", mesh=mesh)

    def fit_time(tag, **extra):
        t = []
        m = None
        for rep in range(3):
            t0 = time.perf_counter()
            m = sg.glm_fit(X, y, **kw, **extra)
            t.append(time.perf_counter() - t0)
        res[f"{tag}_fit_s"] = min(t[1:])  # rep 0 pays compile
        res[f"{tag}_compile_s"] = t[0]
        res[f"{tag}_iters"] = int(m.iterations)
        res[f"{tag}_ms_per_iter"] = 1e3 * min(t[1:]) / max(1, m.iterations)
        dump_atomic(res, OUT)
        print(tag, res[f"{tag}_fit_s"], "s,", m.iterations, "iters", flush=True)
        return m

    m32 = fit_time("fused_f32")
    mbf = fit_time("fused_bf16_warmup", config=NumericConfig(bf16_warmup=True))
    res["coef_maxdiff"] = float(np.max(np.abs(
        m32.coefficients - mbf.coefficients)))
    res["speedup"] = res["fused_f32_fit_s"] / res["fused_bf16_warmup_fit_s"]
    res["complete"] = True  # watchdog guard: partial dumps lack this
    dump_atomic(res, OUT)
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
