"""Time the SHIPPED bf16-warmup schedule end-to-end on TPU.

proto_bf16_master.py measures the raw pass; this measures what users get:
``glm_fit(engine="fused")`` vs ``glm_fit(engine="fused",
config=NumericConfig(bf16_warmup=True))`` — the full user entry point
including H2D upload and host-f64 statistics — on a 1M x 512 logistic
slice of the headline shape, full fits to tol=1e-8, plus the coefficient
agreement between the two (the accuracy contract).

Data lives in HOST numpy from the start: generating on device and letting
glm_fit's ``np.asarray`` pull 4.3 GB back D2H is exactly the tunnel
operation that wedged round 3 (R4_RESPONSE.md) and hung this bench's first
r5 window for its whole 900 s timeout.  1M x 512 (2.1 GB) keeps each
per-fit H2D upload ~20 s over the tunnel; on a real TPU VM this script is
IO-trivial.  The *kernel-level* schedule timing at the full 2M x 512 rides
bench.py's ``headline_fused_bf16`` record — the two together execute
BF16_SCHEDULE_r04.md's decision rule.

Writes benchmarks/bf16_sched_r05.json incrementally.  ONE tunnel client
at a time (tpu_when_alive.sh).
"""
import json
import sys
import time

import jax

sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402

import sparkglm_tpu as sg  # noqa: E402
from sparkglm_tpu.config import NumericConfig  # noqa: E402

from _capture import dump_atomic, out_path  # noqa: E402

OUT = out_path("bf16_sched")
SOFT_DEADLINE_S = 780.0  # dump what we have before the watchdog's 900 s


def main():
    t_start = time.perf_counter()
    res = {"device": str(jax.devices()[0])}
    n, p = 1_048_576, 512
    res["n"], res["p"] = n, p
    rng = np.random.default_rng(7)
    X = rng.standard_normal((n, p)).astype(np.float32)
    X[:, 0] = 1.0
    bt = (rng.standard_normal(p) / (2.0 * p ** 0.5)).astype(np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-(X @ bt)))).astype(np.float32)
    print(f"host data ready at {time.perf_counter() - t_start:.1f}s",
          flush=True)
    mesh = sg.make_mesh()
    kw = dict(family="binomial", tol=1e-8, criterion="relative",
              engine="fused", mesh=mesh)

    def fit_time(tag, reps=3, **extra):
        t = []
        m = None
        for rep in range(reps):
            if time.perf_counter() - t_start > SOFT_DEADLINE_S and m is not None:
                print(f"{tag}: soft deadline, stopping at rep {rep}",
                      flush=True)
                break
            t0 = time.perf_counter()
            m = sg.glm_fit(X, y, **kw, **extra)
            t.append(time.perf_counter() - t0)
            print(f"{tag} rep{rep}: {t[-1]:.2f}s ({m.iterations} iters)",
                  flush=True)
        best = min(t[1:]) if len(t) > 1 else t[0]
        res[f"{tag}_fit_s"] = best
        if len(t) == 1:
            # deadline-truncated: the single rep paid JIT compile, so this
            # fit_s is NOT comparable to a warm one — flag it in the record
            res[f"{tag}_truncated_compile_inclusive"] = True
        res[f"{tag}_compile_s"] = t[0]
        res[f"{tag}_iters"] = int(m.iterations)
        res[f"{tag}_ms_per_iter"] = 1e3 * best / max(1, m.iterations)
        dump_atomic(res, OUT)
        return m

    m32 = fit_time("fused_f32", reps=2)
    mbf = fit_time("fused_bf16_warmup", reps=2,
                   config=NumericConfig(bf16_warmup=True))
    res["coef_maxdiff"] = float(np.max(np.abs(
        m32.coefficients - mbf.coefficients)))
    res["speedup_end_to_end"] = (res["fused_f32_fit_s"]
                                 / res["fused_bf16_warmup_fit_s"])
    res["note"] = ("certifies the SHIPPED entry point runs the schedule on "
                   "TPU and the coefficient contract; end-to-end times are "
                   "tunnel-upload-dominated here, so the schedule SPEEDUP of "
                   "record is bench_detail_latest.json headline_fused vs "
                   "headline_fused_bf16 (device-resident kernel)")
    res["complete"] = True  # watchdog guard: partial dumps lack this
    dump_atomic(res, OUT)
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
