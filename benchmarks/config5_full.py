"""BASELINE config 5 at FULL scale: 50M x 500 gamma, prior weights + offset.

VERDICT r2 #3: the r02 capture streamed 1.8M rows from CSV and was
tunnel-H2D-bound (~100-200 MB/s); the extrapolation to 50M was never
measured.  This harness measures the real thing per-chip through the
PUBLIC streaming engine (models/streaming.py::glm_fit_streaming): the
source yields DEVICE chunks — jitted RNG, zero host->device traffic
(the engine's device-chunk passthrough) — and each IRLS iteration sweeps
the full 100 GB synthetic design through HBM via the per-chunk fused
Fisher pass with host-float64 accumulation.  The reported statistics are
the engine's own (host-f64 from on-device X@beta pulls of (n,) vectors).

Writes measured iterations, s/iteration, convergence, and the implied
HBM sweep bandwidth to benchmarks/config5_r05.json.  Chunks are
regenerated per pass (100 GB does not fit in 16 GB HBM): generation is a
cheap RNG kernel per chunk, so cache="none" keeps the measurement clean.

Run with the tunnel alive, ONE TPU client at a time.
"""
import json
import sys
import time

import os

import jax
import jax.numpy as jnp
import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))
from sparkglm_tpu.models.streaming import glm_fit_streaming

from _capture import dump_atomic, out_path  # noqa: E402

N_TOTAL = 50_000_000
P = 500
CHUNK = 2_000_000           # 4 GB f32 per chunk: generate, sweep, discard
BETA_SCALE = 0.05


@jax.jit
def _gen(i):
    """Chunk i: X, y ~ Gamma(shape=3, mean=mu), weights in [0.5, 2.5],
    offset = log exposure in [-0.7, 1.1]; fixed true beta."""
    key = jax.random.fold_in(jax.random.PRNGKey(42), i)
    kx, kw, ke, kg = jax.random.split(key, 4)
    X = jax.random.normal(kx, (CHUNK, P), jnp.float32).at[:, 0].set(1.0)
    bt = (jax.random.normal(jax.random.PRNGKey(7), (P,), jnp.float32)
          * BETA_SCALE).at[0].set(0.4)
    off = jax.random.uniform(ke, (CHUNK,), jnp.float32, -0.7, 1.1)
    wt = jax.random.uniform(kw, (CHUNK,), jnp.float32, 0.5, 2.5)
    mu = jnp.exp(jnp.clip(X @ bt + off, -8, 8))
    y = jax.random.gamma(kg, 3.0, (CHUNK,), jnp.float32) * (mu / 3.0)
    return X, y, wt, off


def main():
    dev = jax.devices()[0]
    assert dev.platform == "tpu", dev
    n_chunks = N_TOTAL // CHUNK

    def source():
        for i in range(n_chunks):
            yield lambda i=i: _gen(i)  # thunks: lazy per-chunk generation

    pass_times = []

    def on_iteration(it, beta, dev_):
        now = time.perf_counter()
        pass_times.append(now - on_iteration.t0)
        print(f"iter {it}  deviance {dev_:.8g}  pass {pass_times[-1]:.1f}s",
              flush=True)
        on_iteration.t0 = now

    t_start = time.perf_counter()
    on_iteration.t0 = t_start
    model = glm_fit_streaming(
        source, family="gamma", link="log", criterion="relative", tol=1e-8,
        max_iter=30, cache="none", on_iteration=on_iteration)
    total_s = time.perf_counter() - t_start
    # total - IRLS = family-init pass + host-f64 stats pass + the nested
    # intercept-only null-model IRLS (intercept+offset config) — all of
    # which also sweep the source; attribute them instead of hiding them
    post_and_init_s = total_s - sum(pass_times)

    gb_per_pass = N_TOTAL * P * 4 / 1e9
    s_iter = float(np.median(pass_times[1:])) if len(pass_times) > 1 \
        else float(pass_times[0])
    bt = np.asarray(jax.random.normal(jax.random.PRNGKey(7), (P,),
                                      jnp.float32) * BETA_SCALE, np.float64)
    bt[0] = 0.4
    res = {
        "config": "BASELINE #5 gamma log, weights+offset",
        "n": N_TOTAL, "p": P, "chunk_rows": CHUNK,
        "chunks_per_pass": n_chunks, "device": str(dev),
        "engine": "public glm_fit_streaming, device-chunk source "
                  "(zero H2D; HIGHEST-precision chunk Gramians)",
        "iterations": model.iterations, "converged": bool(model.converged),
        "deviance": model.deviance, "aic": model.aic,
        "dispersion": model.dispersion,
        "s_per_iter": round(s_iter, 2), "total_s": round(total_s, 2),
        "init_stats_and_null_model_s": round(post_and_init_s, 2),
        "pass_times_s": [round(t, 2) for t in pass_times],
        "timing_note": "pass_times_s[0] includes jit compile; s_per_iter "
                       "is the median of the later passes",
        "design_GB_swept_per_pass": round(gb_per_pass, 1),
        "eff_sweep_GBps": round(gb_per_pass / s_iter, 1),
        "max_abs_beta_err": float(np.max(np.abs(model.coefficients - bt))),
    }
    print(json.dumps(res, indent=1))
    dump_atomic(res, out_path("config5"))


if __name__ == "__main__":
    main()
