"""BASELINE config 5 at FULL scale: 50M x 500 gamma, prior weights + offset.

VERDICT r2 #3: the r02 capture streamed 1.8M rows from CSV and was
tunnel-H2D-bound (~100-200 MB/s); the extrapolation to 50M was never
measured.  This harness measures the real thing per-chip by generating
each chunk ON DEVICE (jitted RNG — zero host->device traffic) and driving
the streaming engine's own compute path: the per-chunk fused Fisher pass
(models/streaming.py::_glm_chunk_pass — HIGHEST-precision Gramian, the
engine's production setting) with host-float64 cross-chunk accumulation
and the engine's equilibrated host solve (_solve64), i.e. one IRLS
iteration = one full 100 GB sweep of the synthetic design through HBM.

Reports measured iterations, s/iteration, convergence, and the implied
HBM sweep bandwidth to benchmarks/results_r03_config5.json.  The chunks
are regenerated per pass (50M x 500 f32 = 100 GB does not fit in 16 GB
HBM) — generation is a ~2 GFLOP RNG kernel per chunk, <1% of the pass.

Run with the tunnel alive, ONE TPU client at a time.
"""
import json
import sys
import time

import os

import jax
import jax.numpy as jnp
import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(HERE))
from sparkglm_tpu.models.streaming import _glm_chunk_pass, _solve64
from sparkglm_tpu.families.families import resolve
from sparkglm_tpu.config import effective_tol

N_TOTAL = 50_000_000
P = 500
CHUNK = 2_000_000           # 4 GB f32 per chunk: generate, sweep, discard
BETA_SCALE = 0.05


def chunk_fn():
    """Jitted generator for chunk i: X, y ~ Gamma(shape=3, mean=mu),
    weights in [0.5, 2.5], offset = log exposure in [-0.7, 1.1]."""
    fam, lnk = resolve("gamma", "log")

    @jax.jit
    def gen(i):
        key = jax.random.fold_in(jax.random.PRNGKey(42), i)
        kx, kb, kw, ke, kg = jax.random.split(key, 5)
        X = jax.random.normal(kx, (CHUNK, P), jnp.float32).at[:, 0].set(1.0)
        # fixed true beta (same key every chunk)
        bt = (jax.random.normal(jax.random.PRNGKey(7), (P,), jnp.float32)
              * BETA_SCALE).at[0].set(0.4)
        off = jax.random.uniform(ke, (CHUNK,), jnp.float32, -0.7, 1.1)
        wt = jax.random.uniform(kw, (CHUNK,), jnp.float32, 0.5, 2.5)
        mu = jnp.exp(jnp.clip(X @ bt + off, -8, 8))
        y = jax.random.gamma(kg, 3.0, (CHUNK,), jnp.float32) * (mu / 3.0)
        return X, y, wt, off

    return gen, fam, lnk


def main():
    dev = jax.devices()[0]
    assert dev.platform == "tpu", dev
    gen, fam, lnk = chunk_fn()
    n_chunks = N_TOTAL // CHUNK
    tol = effective_tol(1e-8, "relative", jnp.float32)

    def full_pass(beta, first):
        XtWX = XtWz = None
        dev_sum = 0.0
        pending = None

        def drain(res):
            nonlocal XtWX, XtWz, dev_sum
            A, v, dv = res
            A = np.asarray(A, np.float64)
            v = np.asarray(v, np.float64)
            XtWX = A if XtWX is None else XtWX + A
            XtWz = v if XtWz is None else XtWz + v
            dev_sum += float(dv)

        for i in range(n_chunks):
            X, y, wt, off = gen(i)
            b = (jnp.zeros((P,), jnp.float32) if beta is None
                 else jnp.asarray(beta, jnp.float32))
            fut = _glm_chunk_pass(X, y, wt, off, b, family=fam, link=lnk,
                                  first=first)
            if pending is not None:
                drain(pending)
            pending = fut
        drain(pending)
        return XtWX, XtWz, dev_sum

    res = {"config": "BASELINE #5 gamma log, weights+offset",
           "n": N_TOTAL, "p": P, "chunk_rows": CHUNK,
           "chunks_per_pass": n_chunks, "device": str(dev),
           "engine": "streaming _glm_chunk_pass (HIGHEST Gramian) + "
                     "host-f64 accumulation + equilibrated host solve",
           "data": "synthetic, generated on device per chunk (no H2D)"}

    t0 = time.perf_counter()
    XtWX, XtWz, dev_prev = full_pass(None, True)
    t_init = time.perf_counter() - t0
    beta, cho, pivot = _solve64(XtWX, XtWz, 0.0)
    min_pivot = pivot
    res["init_pass_s"] = round(t_init, 2)

    iters = 0
    converged = False
    pass_times = []
    for it in range(30):
        t0 = time.perf_counter()
        XtWX, XtWz, dev_cur = full_pass(beta, False)
        beta, cho, pivot = _solve64(XtWX, XtWz, 0.0)
        min_pivot = min(min_pivot, pivot)  # min over ALL iterations
        pass_times.append(time.perf_counter() - t0)
        ddev = abs(dev_cur - dev_prev)
        crit = ddev / (abs(dev_cur) + 0.1)
        print(f"iter {it + 1}  dev {dev_cur:.8g}  rel-ddev {crit:.3g}  "
              f"pass {pass_times[-1]:.1f}s", flush=True)
        dev_prev = dev_cur
        iters = it + 1
        if crit <= tol:
            converged = True
            break

    gb_per_pass = N_TOTAL * P * 4 / 1e9
    s_iter = float(np.median(pass_times))
    res.update(
        iterations=iters, converged=converged,
        deviance=dev_prev, min_equilibrated_pivot=min_pivot,
        s_per_iter=round(s_iter, 2),
        total_s=round(t_init + sum(pass_times), 2),
        pass_times_s=[round(t, 2) for t in pass_times],
        design_GB_swept_per_pass=round(gb_per_pass, 1),
        eff_sweep_GBps=round(gb_per_pass / s_iter, 1),
        beta_err_note="true beta recoverable: max|beta-bt| reported below")
    bt = np.asarray(jax.random.normal(jax.random.PRNGKey(7), (P,),
                                      jnp.float32) * BETA_SCALE, np.float64)
    bt[0] = 0.4
    res["max_abs_beta_err"] = float(np.max(np.abs(beta - bt)))

    print(json.dumps(res, indent=1))
    with open(os.path.join(HERE, "results_r03_config5.json"), "w") as f:
        json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
