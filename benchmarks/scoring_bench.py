"""Scoring-path benchmark (VERDICT r2 #4): a 10M-row sharded predict pass.

Uses device-resident X (same convention as the fit benchmarks — the axon
tunnel's H2D is ~100-200 MB/s sustained and would swamp any kernel
measurement; memory: engine-and-precision-findings #4) and times
models/scoring._score_kernel — the exact jitted pass ``predict_sharded``
runs after ``device_put``.  Slope timing (K enqueues + scalar fetch).
One TPU client at a time.
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from sparkglm_tpu.models.scoring import _score_kernel
from sparkglm_tpu.families.links import get_link
from sparkglm_tpu.parallel import mesh as meshlib

from _capture import dump_atomic, out_path  # noqa: E402


def _fetch(out):
    return float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])


def timeit(fn, *args, reps=10):
    out = fn(*args)
    _fetch(out)

    def run(k):
        t0 = time.perf_counter()
        for _ in range(k):
            out = fn(*args)
        _fetch(out)
        return time.perf_counter() - t0

    t1 = min(run(2), run(2))
    t2 = min(run(2 + reps), run(2 + reps))
    return max((t2 - t1) / reps, 0.0)


def bench(n, p, se_fit, response):
    mesh = meshlib.make_mesh()
    key = jax.random.PRNGKey(0)
    X = jax.device_put(
        jax.random.normal(key, (n, p), jnp.float32),
        jax.sharding.NamedSharding(mesh, meshlib.row_spec(2)))
    beta = jnp.zeros((p,), jnp.float32).at[0].set(0.3)
    off = jnp.zeros((1,), jnp.float32)  # dummy: has_offset=False
    V = (jnp.eye(p, dtype=jnp.float32) * 1e-4 if se_fit
         else jnp.zeros((1, 1), jnp.float32))
    lnk = get_link("logit")

    def run(X, beta, off, V):
        return _score_kernel(X, beta, off, V, inverse=lnk.inverse,
                             deriv=lnk.deriv, want_se=se_fit,
                             response=response, has_offset=False,
                             quad_precision=None)

    t = timeit(run, X, beta, off, V)
    gb = n * p * 4 / 1e9
    return {"n": n, "p": p, "se_fit": se_fit, "response": response,
            "seconds": t, "rows_per_s": n / t, "GB_read": gb,
            "eff_GBps": gb * (2 if se_fit else 1) / t}


def main():
    res = {"device": str(jax.devices()[0])}
    res["predict_10Mx100_response"] = bench(10_000_000, 100, False, True)
    res["predict_10Mx100_se_fit"] = bench(10_000_000, 100, True, True)
    res["predict_2Mx512_response"] = bench(2_097_152, 512, False, True)
    res["predict_2Mx512_se_fit"] = bench(2_097_152, 512, True, True)
    print(json.dumps(res, indent=1))
    dump_atomic(res, out_path("scoring"))


if __name__ == "__main__":
    main()
