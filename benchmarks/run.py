"""Benchmark harness for the five BASELINE.json capability configs.

    1. Gaussian/identity lm() 10k x 20        (OLS closed form)
    2. Binomial/logit glm() 1M x 100          (logistic)
    3. Poisson/log glm() 1M x 100             (counts)
    4. Binomial/logit glm() 2M x 512          (Gramian stress; 10M x 1000
       needs v5e-8 HBM — scaled to one chip, extrapolation printed)
    5. Gamma/inverse glm() + prior weights + offset, streamed
       (50M x 500 is ~100 GB — run via glm_fit_streaming on a synthetic
       chunk generator; row count scaled by --scale)

Usage::

    python benchmarks/run.py [--scale S] [--cpu] [--json PATH]

``--scale`` multiplies row counts (default 1.0; use e.g. 0.01 for a smoke
run).  Each config reports seconds (min of 3 runs for resident fits, single
run for streaming) plus iterations, as JSON lines.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _capture import dump_atomic  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--json", default=None)
    ap.add_argument("--only", default=None,
                    help="comma-separated subset of configs 1-5 to run")
    ap.add_argument("--merge", action="store_true",
                    help="merge into an existing --json file instead of "
                         "overwriting; an incoming record replaces any prior "
                         "record of the same config family (the 'config' name "
                         "with its trailing _NxP dimensions stripped, so a "
                         "re-run at a different --scale supersedes)")
    args = ap.parse_args()
    only = (set(int(s) for s in args.only.split(",")) if args.only
            else {1, 2, 3, 4, 5})

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import sparkglm_tpu as sg
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.models.glm import _irls_kernel
    from sparkglm_tpu.models.lm import _lm_kernel
    from sparkglm_tpu.parallel import mesh as meshlib

    mesh = sg.make_mesh()
    row_s = NamedSharding(mesh, P(meshlib.DATA_AXIS))
    mat_s = NamedSharding(mesh, P(meshlib.DATA_AXIS, None))
    results = []
    if args.merge and args.json and os.path.exists(args.json):
        with open(args.json) as f:
            results = json.load(f)
        # a prior run's completion sentinel must not survive into this
        # run's incremental dumps — finish() re-stamps it only if earned
        results = [r for r in results if r.get("config") != "_complete"]

    def emit(rec):
        base = lambda name: re.sub(r"_\d+x\d+$", "", name)
        results[:] = [r for r in results
                      if base(r["config"]) != base(rec["config"])]
        results.append(rec)
        print(json.dumps(rec), flush=True)
        # write incrementally (and atomically: a SIGTERM mid-dump must not
        # truncate the file) so a timeout mid-harness keeps earlier configs
        if args.json:
            dump_atomic(results, args.json)

    def rows(base: int) -> int:
        return max(4096, int(base * args.scale))

    def make_xy(key, n, p, kind):
        """Generate (X, y) on device; returns sharded device arrays."""
        @jax.jit
        def gen(key):
            kx, kb, ku = jax.random.split(key, 3)
            X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
            bt = jax.random.normal(kb, (p,), jnp.float32) / (2.0 * p ** 0.5)
            eta = X @ bt
            if kind == "gaussian":
                y = eta + 0.3 * jax.random.normal(ku, (n,), jnp.float32)
            elif kind == "logistic":
                y = (jax.random.uniform(ku, (n,))
                     < jax.nn.sigmoid(eta)).astype(jnp.float32)
            elif kind == "poisson":
                y = jax.random.poisson(ku, jnp.exp(0.5 * eta)).astype(jnp.float32)
            else:
                raise ValueError(kind)
            return jax.device_put(X, mat_s), jax.device_put(y, row_s)
        return gen(jax.random.PRNGKey(0))

    def timed(fn, reps=3):
        fn()  # warm-up/compile
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            ts.append(time.perf_counter() - t0)
        return min(ts), out

    ones = lambda n: jnp.ones((n,), jnp.float32)
    zeros = lambda n: jnp.zeros((n,), jnp.float32)

    # ---- 1. OLS 10k x 20 ---------------------------------------------------
    if 1 in only:
        n, p = rows(10_000), 20
        X, y = make_xy(jax.random.PRNGKey(1), n, p, "gaussian")
        w = ones(n)

        def run_ols():
            out = _lm_kernel(X, y, w, jnp.float32(0.0), refine_steps=1)
            float(out["sse"])
            return out
        t, _ = timed(run_ols)
        emit({"config": f"ols_gaussian_{n}x{p}", "seconds": round(t, 5)})

    # ---- 2/3/4: resident IRLS configs --------------------------------------
    irls_cfgs = [
        (2, "logistic", rows(1_000_000), 100, "logistic", "binomial", "logit"),
        (3, "poisson", rows(1_000_000), 100, "poisson", "poisson", "log"),
        (4, "logistic_gramian_stress", rows(2_000_000), 512, "logistic",
         "binomial", "logit"),
    ]
    for idx, label, n, p, kind, famname, linkname in irls_cfgs:
        if idx not in only:
            continue
        name = f"{label}_{n}x{p}"
        X, y = make_xy(jax.random.PRNGKey(2), n, p, kind)
        w, o = ones(n), zeros(n)
        fam, lnk = resolve(famname, linkname)

        def run_irls():
            out = _irls_kernel(X, y, w, o, jnp.float32(1e-8), jnp.int32(25),
                               jnp.float32(0.0), family=fam, link=lnk,
                               criterion="relative", refine_steps=1)
            float(out["dev"])
            return out
        t, out = timed(run_irls)
        emit({"config": name, "seconds": round(t, 4),
              "iters": int(out["iters"]), "converged": bool(out["converged"])})
        del X, y

    # ---- 5. Gamma + prior weights + offset, streamed -----------------------
    # full config is 50M x 500 (~100 GB, beyond any single host's run
    # budget here); measure a 2M-row slice of the identical pipeline and
    # report rows/s — wall-clock for the full 50M is linear in rows.
    # Chunks are pre-generated and held in host RAM (2M x 500 f32 = 4 GB)
    # so the measurement is the streaming pipeline (H2D + device compute +
    # host-f64 stats), not numpy's RNG throughput.
    if 5 not in only:
        return finish(args, results, jax, only)
    p5 = 500
    chunk = 1_048_576 // 4
    n5 = rows(2_000_000)
    n_chunks = max(1, n5 // chunk)
    bt5 = np.linspace(-0.2, 0.2, p5); bt5[0] = 1.5  # keep eta > 0 for inverse link

    cached = []
    for i in range(n_chunks):
        r = np.random.default_rng(1000 + i)
        Xc = r.standard_normal((chunk, p5)).astype(np.float32) * 0.02
        Xc[:, 0] = 1.0
        eta = Xc @ bt5 + 0.05
        mu = 1.0 / np.maximum(eta, 0.1)
        yc = r.gamma(2.0, mu / 2.0).astype(np.float32) + 1e-3
        wc = r.uniform(0.5, 2.0, chunk).astype(np.float32)
        oc = np.full(chunk, 0.05, np.float32)
        cached.append((Xc, yc, wc, oc))

    def source():
        yield from cached

    # cache="auto" pins chunks in HBM on the first pass (the .persist() the
    # reference lacks): later IRLS iterations are HBM-bound, not H2D-bound.
    # Over the axon tunnel this matters enormously (sustained H2D throttles
    # to ~100-200 MB/s after ~1 GB); on a real v5e host it still removes
    # ~iters x dataset-size of PCIe traffic per fit.
    t0 = time.perf_counter()
    m = sg.glm_fit_streaming(source, family="gamma", link="inverse",
                             tol=1e-8, criterion="relative", max_iter=25,
                             chunk_rows=chunk, mesh=mesh, cache="auto")
    t5 = time.perf_counter() - t0
    n5_real = n_chunks * chunk
    # wall-clock includes the intercept-only null-model streaming IRLS the
    # offset triggers (R semantics), so per-pass throughput is not derivable
    # here; the 50M estimate is valid because every component is linear in
    # rows
    emit({"config": f"gamma_weights_offset_streamed_{n5_real}x{p5}",
          "seconds": round(t5, 2), "iters": m.iterations,
          "converged": bool(m.converged),
          "est_50Mx500_s": round(t5 * 50_000_000 / n5_real, 1),
          "note": "wall-clock includes one-time H2D over the axon tunnel "
                  "(throttles to ~100-200 MB/s sustained) + R-semantics "
                  "null-model IRLS; chunk cache makes iterations HBM-bound"})
    finish(args, results, jax, only)


def finish(args, results, jax, only) -> None:
    # emit() already persists incrementally after every record; stamp a
    # sentinel record so a timeout-killed partial file is distinguishable
    # from a finished harness (the tpu_when_alive.sh guard greps for it).
    # Only a FULL five-config run on real TPU earns the sentinel — a
    # --only smoke or a CPU run must never satisfy the round's capture
    # guard (it would permanently skip the real refresh).
    full_tpu = (only == {1, 2, 3, 4, 5}
                and jax.default_backend() == "tpu")
    if args.json and full_tpu:
        results[:] = [r for r in results if r.get("config") != "_complete"]
        results.append({"config": "_complete", "complete": True})
        dump_atomic(results, args.json)
    print(f"platform={jax.default_backend()} devices={len(jax.devices())}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
