"""f32 coefficient-parity sweep vs the float64 oracle (VERDICT r2 item #3).

Quantifies SURVEY.md §7 hard part #1 — "match R glm() coefficients to 1e-6
at TPU dtype" — by fitting float32 designs of controlled conditioning
against tests/oracle.py's independent f64 IRLS and reporting max |Δβ|, with
``refine_steps`` (iterative refinement of the normal-equations solve) as the
lever.  Prints a markdown table (pasted into PARITY.md) plus a JSON record.

Run on CPU (x64 available for the oracle) or TPU:
    python benchmarks/parity_sweep.py [--json PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests"))


def conditioned_design(rng, n, p, kappa):
    """X with singular values log-spaced over [1, 1/kappa] (plus an
    intercept), so the Gramian's condition number is ~kappa^2."""
    Z = rng.standard_normal((n, p - 1))
    # mix columns through a spectrum-shaping matrix: Z V diag(s) V'
    V, _ = np.linalg.qr(rng.standard_normal((p - 1, p - 1)))
    s = np.logspace(0, -np.log10(kappa), p - 1)
    X = np.column_stack([np.ones(n), (Z @ V) * s @ V.T])
    return X


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if jax.default_backend() == "cpu":
        jax.config.update("jax_enable_x64", True)  # oracle + f64 control runs

    import sparkglm_tpu as sg
    from sparkglm_tpu.config import NumericConfig
    from oracle import irls_np, ols_np

    rng = np.random.default_rng(99)
    rows = []

    def record(config, family, link, X, y, kappa, refine, extra="",
               polish=None, engine="auto"):
        cfg = NumericConfig(dtype="float32", refine_steps=refine,
                            polish=polish)
        try:
            m = sg.glm_fit(X.astype(np.float32), y.astype(np.float32),
                           family=family, link=link, tol=1e-12,
                           criterion="relative", max_iter=100, config=cfg,
                           engine=engine)
        except np.linalg.LinAlgError:
            # the f32 solver refuses Gramians with kappa^2 beyond f32 range
            # (ops/solve.py::factor_singular) instead of returning garbage
            rows.append(dict(config=config, family=family, n=X.shape[0],
                             p=X.shape[1], kappa=kappa, refine_steps=refine,
                             max_abs_dbeta=None, max_rel_dbeta=None,
                             note="refused: singular at f32 (use float64/x64)"))
            print(f"  {config}: refused (singular at f32)", file=sys.stderr)
            return
        beta64, _, _, _ = irls_np(X, y, family if family != "gaussian" else "gaussian",
                                  link, tol=1e-14)
        err = float(np.max(np.abs(m.coefficients - beta64)))
        rel = float(np.max(np.abs(m.coefficients - beta64)
                           / np.maximum(np.abs(beta64), 1e-3)))
        rows.append(dict(config=config, family=family, n=X.shape[0],
                         p=X.shape[1], kappa=kappa, refine_steps=refine,
                         max_abs_dbeta=err, max_rel_dbeta=rel, note=extra))
        print(f"  {config}: max|dβ|={err:.3g} rel={rel:.3g}", file=sys.stderr)

    def logistic_y(X, scale=1.0):
        bt = rng.standard_normal(X.shape[1]) * scale / np.sqrt(X.shape[1])
        return (rng.random(X.shape[0]) < 1 / (1 + np.exp(-(X @ bt)))).astype(float), bt

    # 1-2: well-conditioned logistic, growing n
    for n in (50_000, 500_000):
        X = np.column_stack([np.ones(n), rng.standard_normal((n, 19))])
        y, _ = logistic_y(X)
        record(f"logistic_{n//1000}kx20_k1e0", "binomial", "logit", X, y, 1, 1)

    # 3: wide logistic
    X = np.column_stack([np.ones(20_000), rng.standard_normal((20_000, 199))])
    y, _ = logistic_y(X)
    record("logistic_20kx200_k1e0", "binomial", "logit", X, y, 1, 1)

    # 4-7: ill-conditioned designs; refine and csne-polish levers
    for kappa in (1e3, 1e5):
        X = conditioned_design(rng, 100_000, 20, kappa)
        y, _ = logistic_y(X)
        for refine in (0, 1):
            record(f"logistic_100kx20_k{kappa:.0e}_r{refine}",
                   "binomial", "logit", X, y, kappa, refine)
        record(f"logistic_100kx20_k{kappa:.0e}_csne",
               "binomial", "logit", X, y, kappa, 1, polish="csne",
               extra="polish=csne")
        record(f"logistic_100kx20_k{kappa:.0e}_qr",
               "binomial", "logit", X, y, kappa, 1, engine="qr",
               extra="engine=qr")

    # 8: poisson
    X = np.column_stack([np.ones(100_000), rng.standard_normal((100_000, 19))])
    bt = rng.standard_normal(20) / 10
    y = rng.poisson(np.exp(np.clip(X @ bt, -4, 4))).astype(float)
    record("poisson_100kx20_k1e0", "poisson", "log", X, y, 1, 1)

    # 9: gaussian OLS, moderately ill-conditioned
    X = conditioned_design(rng, 100_000, 20, 1e4)
    bt = rng.standard_normal(20)
    y = X @ bt + 0.1 * rng.standard_normal(100_000)
    cfg = NumericConfig(dtype="float32", refine_steps=1)
    m = sg.lm_fit(X.astype(np.float32), y.astype(np.float32), config=cfg)
    beta64 = ols_np(X, y)
    err = float(np.max(np.abs(m.coefficients - beta64)))
    rows.append(dict(config="ols_100kx20_k1e4", family="gaussian",
                     n=100_000, p=20, kappa=1e4, refine_steps=1,
                     max_abs_dbeta=err,
                     max_rel_dbeta=float(np.max(np.abs(m.coefficients - beta64)
                                                / np.maximum(np.abs(beta64), 1e-3))),
                     note=""))
    print(f"  ols_100kx20_k1e4: max|dβ|={err:.3g}", file=sys.stderr)

    # 10: streaming lm 1M x 100 (f32 chunks, host-f64 accumulation)
    n, p = 1_000_000, 100
    bt = rng.standard_normal(p)
    chunk = 131_072

    def source():
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            r2 = np.random.default_rng(lo)
            Xc = np.column_stack([np.ones(hi - lo),
                                  r2.standard_normal((hi - lo, p - 1))]).astype(np.float32)
            yc = (Xc @ bt + 0.1 * r2.standard_normal(hi - lo)).astype(np.float32)
            yield Xc, yc, None, None

    ms = sg.lm_fit_streaming(source, chunk_rows=chunk)
    Xfull = np.concatenate([c[0] for c in source()]).astype(np.float64)
    yfull = np.concatenate([c[1] for c in source()]).astype(np.float64)
    beta64 = ols_np(Xfull, yfull)
    err = float(np.max(np.abs(ms.coefficients - beta64)))
    rows.append(dict(config="ols_streaming_1Mx100", family="gaussian",
                     n=n, p=p, kappa=1, refine_steps=1, max_abs_dbeta=err,
                     max_rel_dbeta=float(np.max(
                         np.abs(ms.coefficients - beta64)
                         / np.maximum(np.abs(beta64), 1e-3))),
                     note="f32 chunks, host-f64 accumulation"))
    print(f"  ols_streaming_1Mx100: max|dβ|={err:.3g}", file=sys.stderr)

    import jax
    print("\n| config | n | p | κ(X) | refine | max \\|Δβ\\| | max rel Δβ |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        if r["max_abs_dbeta"] is None:
            err_s = rel_s = "refused (singular at f32)"
        else:
            err_s = f"{r['max_abs_dbeta']:.2e}"
            rel_s = f"{r['max_rel_dbeta']:.2e}"
        print(f"| {r['config']} | {r['n']:,} | {r['p']} | {r['kappa']:.0e} "
              f"| {r['refine_steps']} | {err_s} | {rel_s} |")
    out = dict(platform=jax.default_backend(), rows=rows)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
