"""Round-5 hot-loop decomposition on the real chip.

bench_detail_latest (r5) measures the fused FIT at ~29 ms/iter while the
raw fused PASS costs ~16 ms (proto_bf16_r05) — ~13 ms/iter of overhead
around the data pass.  Decompose one IRLS iteration ON DEVICE to find it.

Tunnel methodology (hard-won):
  * single dispatches cost ~65 ms RTT — EVERY timing must amortize many
    repetitions inside ONE jitted call (chained lax.scan, k=1 vs k=K
    marginal), like proto_bf16_master does;
  * never close a jit over a device-resident design matrix — the 4.3 GB
    gets captured as an HLO CONSTANT and serialized over the tunnel
    (first attempt of this script died doing exactly that).  Pass
    operands as arguments.

Also validates the NEW Mosaic traced-theta path (negbin fam_param as a
(1,1) SMEM operand) on real hardware.  ONE tunnel client at a time.
Writes benchmarks/hotloop_r05.json.
"""
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

import sys

sys.path.insert(0, "/root/repo")

import numpy as np  # noqa: E402

OUT = "/root/repo/benchmarks/hotloop_r05.json"
res = {"device": None}


def dump():
    import os
    with open(OUT + ".tmp", "w") as f:
        json.dump(res, f, indent=1)
    os.replace(OUT + ".tmp", OUT)


def timed(fn, *args, reps=4):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.models.glm import _irls_fused_kernel, _irls_kernel
    from sparkglm_tpu.ops.fused import fused_fisher_pass, fused_fisher_pass_ref
    from sparkglm_tpu.ops.solve import solve_normal
    import sparkglm_tpu as sg

    res["device"] = str(jax.devices()[0])
    mesh = sg.make_mesh()
    fam, lnk = resolve("binomial", "logit")
    n, p = 2_097_152, 512

    # ---- 0. traced-theta Mosaic validation (small, real chip) ------------
    nb_fam, nb_lnk = resolve("negative_binomial(2.0)", "log")
    rngh = np.random.default_rng(5)
    Xs = rngh.standard_normal((4096, 64)).astype(np.float32)
    Xs[:, 0] = 1.0
    mu_s = np.exp(np.clip(Xs @ np.full(64, 0.03), -3, 3))
    ys = rngh.negative_binomial(2.0, 2.0 / (2.0 + mu_s)).astype(np.float32)
    a = (jnp.asarray(Xs), jnp.asarray(ys), jnp.ones(4096, jnp.float32),
         jnp.zeros(4096, jnp.float32), jnp.full((64,), 0.01, jnp.float32))
    for th in (0.8, 2.0, 5.0):
        fp = jnp.float32(th)
        got = fused_fisher_pass(*a, family=nb_fam, link=nb_lnk, first=False,
                                block_rows=512, fam_param=fp)
        ref = fused_fisher_pass_ref(*a, family=nb_fam, link=nb_lnk,
                                    first=False, block_rows=512, fam_param=fp)
        rel = max(float(jnp.max(jnp.abs(g - r))
                        / jnp.maximum(jnp.max(jnp.abs(r)), 1e-30))
                  for g, r in zip(got, ref))
        res[f"nb_theta_{th}_mosaic_vs_ref_rel"] = rel
    mnb = sg.glm_fit(Xs, ys, family="negative_binomial(2.0)", link="log",
                     engine="fused", tol=1e-8, criterion="relative")
    mne = sg.glm_fit(Xs, ys, family="negative_binomial(2.0)", link="log",
                     engine="einsum", tol=1e-8, criterion="relative")
    res["nb_fused_vs_einsum_beta_maxdiff"] = float(
        np.max(np.abs(mnb.coefficients - mne.coefficients)))
    res["nb_fused_converged"] = bool(mnb.converged)
    dump()
    print("negbin mosaic validated", flush=True)

    # ---- 1. device-resident data -----------------------------------------
    @jax.jit
    def gen(key):
        kx, kb, ku = jax.random.split(key, 3)
        X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
        bt = jax.random.normal(kb, (p,), jnp.float32) / (2.0 * p ** 0.5)
        y = (jax.random.uniform(ku, (n,))
             < jax.nn.sigmoid(X @ bt)).astype(jnp.float32)
        return X, y
    X, y = gen(jax.random.PRNGKey(7))
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    beta = jnp.zeros((p,), jnp.float32)
    jax.block_until_ready(y)

    # ---- 2. chained-scan marginals: pass / solve / pass+solve ------------
    @partial(jax.jit, static_argnames=("k", "with_solve"))
    def chain(X, y, wt, off, b0, k, with_solve):
        def body(b, _):
            A, z, dev = fused_fisher_pass(X, y, wt, off, b, family=fam,
                                          link=lnk, first=False,
                                          block_rows=1024)
            if with_solve:
                bb, _ = solve_normal(A, z, jitter=jnp.float32(0.0),
                                     refine_steps=1)
                return bb, dev
            # data dependency without a solve (prevents CSE/hoisting)
            return b + 1e-12 * z, dev
        bout, devs = lax.scan(body, b0, None, length=k)
        return bout, devs[-1]

    for tag, ws in (("pass", False), ("pass_plus_solve", True)):
        t1 = timed(chain, X, y, wt, off, beta, 1, ws)
        t9 = timed(chain, X, y, wt, off, beta, 9, ws)
        res[f"{tag}_marginal_ms"] = 1e3 * (t9 - t1) / 8
        res[f"{tag}_k1_ms"] = 1e3 * t1
        dump()
        print(tag, res[f"{tag}_marginal_ms"], flush=True)

    # solve-only marginal: vary A slightly each step to defeat hoisting
    Afull, zfull, _ = fused_fisher_pass(X, y, wt, off, beta, family=fam,
                                        link=lnk, first=False,
                                        block_rows=1024)

    @partial(jax.jit, static_argnames=("k",))
    def solve_chain(A, z, k):
        def body(carry, _):
            b, s = carry
            Ak = A + (1e-7 * s) * jnp.eye(A.shape[0], dtype=A.dtype)
            bb, _ = solve_normal(Ak, z + 1e-6 * b, jitter=jnp.float32(0.0),
                                 refine_steps=1)
            return (bb, s + 1.0), bb[0]
        (bb, _), _ = lax.scan(body, (jnp.zeros_like(z), jnp.float32(1.0)),
                              None, length=k)
        return bb
    t1 = timed(solve_chain, Afull, zfull, 1)
    t9 = timed(solve_chain, Afull, zfull, 9)
    res["solve_p512_marginal_ms"] = 1e3 * (t9 - t1) / 8
    dump()
    print("solve marginal", res["solve_p512_marginal_ms"], flush=True)

    # ---- 3. full kernels at forced iteration counts ----------------------
    def fit_k(k):
        def run():
            return _irls_fused_kernel(
                X, y, wt, off, jnp.float32(0.0), jnp.int32(k),
                jnp.float32(0.0), family=fam, link=lnk,
                criterion="relative", refine_steps=1, mesh=mesh,
                block_rows=1024, use_pallas=True, precision=None)
        out = run()
        jax.block_until_ready(out["beta"])
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            out = run()
            jax.block_until_ready(out["beta"])
            ts.append(time.perf_counter() - t0)
        return min(ts), int(out["iters"])

    t1, i1 = fit_k(1)
    t5, i5 = fit_k(5)
    res["fit_1iter_ms"] = 1e3 * t1
    res["fit_5iter_ms"] = 1e3 * t5
    res["fit_marginal_per_iter_ms"] = 1e3 * (t5 - t1) / max(1, i5 - i1)
    dump()
    print("fit marginal/iter", res["fit_marginal_per_iter_ms"], flush=True)

    def efit_k(k):
        def run():
            return _irls_kernel(X, y, wt, off, jnp.float32(0.0),
                                jnp.int32(k), jnp.float32(0.0), family=fam,
                                link=lnk, criterion="relative",
                                refine_steps=1)
        out = run()
        jax.block_until_ready(out["beta"])
        ts = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = run()
            jax.block_until_ready(out["beta"])
            ts.append(time.perf_counter() - t0)
        return min(ts), int(out["iters"])

    e1, j1 = efit_k(1)
    e5, j5 = efit_k(5)
    res["einsum_1iter_ms"] = 1e3 * e1
    res["einsum_5iter_ms"] = 1e3 * e5
    res["einsum_marginal_per_iter_ms"] = 1e3 * (e5 - e1) / max(1, j5 - j1)
    res["complete"] = True
    dump()
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
