"""Round 2 of single-pass kernel tuning (logistic 2Mx512, block 1024).

  w0: Gramian only, symmetric form — Xs = X*sqrt(w), ONE explicit bf16 cast,
      dot(Xs_bf, Xs_bf) -> f32.  (v0 was 11.5 ms with two implicit casts.)
  w1: w0 + eta via MXU: dot(X, B) where B = (p, 128) with beta in column 0,
      precision HIGH (bf16x3 ~ f32 accuracy); eta = result[:, :1].
  w2: full kernel: eta-MXU + mu/z/w/dev elementwise + symmetric Gramian
      + VPU f32 XtWz.  The candidate replacement for ops/fused.py.
  w3: w2 but eta via VPU lane-reduce (accuracy-conservative fallback).
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")


def _fetch(out):
    return float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])


def timeit(fn, *args, reps=12):
    out = fn(*args)
    _fetch(out)

    def run(k):
        t0 = time.perf_counter()
        for _ in range(k):
            out = fn(*args)
        _fetch(out)
        return time.perf_counter() - t0

    t1 = min(run(2), run(2))
    t2 = min(run(2 + reps), run(2 + reps))
    return max((t2 - t1) / reps, 0.0)


P_DEF = jax.lax.Precision.DEFAULT
P_HIGH = jax.lax.Precision.HIGH


def build(variant, block_rows, p):
    def kern(*refs):
        if variant == "w0":
            x_ref, z_ref, w_ref, xtwx_ref, xtwz_ref, dev_ref = refs
        else:
            x_ref, y_ref, wt_ref, off_ref, beta_ref, xtwx_ref, xtwz_ref, dev_ref = refs
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            xtwx_ref[:] = jnp.zeros_like(xtwx_ref)
            xtwz_ref[:] = jnp.zeros_like(xtwz_ref)
            dev_ref[:] = jnp.zeros_like(dev_ref)

        X = x_ref[:]
        if variant == "w0":
            z, w = z_ref[:], w_ref[:]
            dev = jnp.zeros((1, 1), jnp.float32)
        else:
            y, wt, off = y_ref[:], wt_ref[:], off_ref[:]
            valid = wt > 0.0
            if variant in ("w1", "w2"):
                B = beta_ref[:]          # (p, 128), beta in column 0
                etaM = jax.lax.dot_general(
                    X, B, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32, precision=P_HIGH)
                eta = etaM[:, :1] + off
            else:
                beta_row = beta_ref[:]   # (1, p)
                eta = jnp.sum(X * beta_row, axis=1, keepdims=True) + off
            mu = jnp.where(valid, jax.nn.sigmoid(eta), 0.5)
            v = jnp.maximum(mu * (1.0 - mu), 1e-30)
            w = jnp.where(valid, wt * v, 0.0)
            z = jnp.where(valid, eta - off + (y - mu) / v, 0.0)
            ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y / mu, 1e-30)), 0.0)
            y1 = jnp.where(y < 1, (1 - y) * jnp.log(jnp.maximum((1 - y) / (1 - mu), 1e-30)), 0.0)
            dev = jnp.sum(jnp.where(valid, 2.0 * wt * (ylog + y1), 0.0)).reshape(1, 1)
        s = jnp.sqrt(w)
        Xs = (X * s).astype(jnp.bfloat16)
        xtwx_ref[:] += jax.lax.dot_general(
            Xs, Xs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=P_DEF)
        # XtWz in f32 on the VPU: Xs is scaled by sqrt(w), so use X*w*z directly
        xtwz_ref[:] += jnp.sum((X * (w * z)), axis=0, keepdims=True)
        dev_ref[:] += dev

    vec = lambda: pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)
    xspec = pl.BlockSpec((block_rows, p), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    outspecs = [
        pl.BlockSpec((p, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    outshape = [
        jax.ShapeDtypeStruct((p, p), jnp.float32),
        jax.ShapeDtypeStruct((1, p), jnp.float32),
        jax.ShapeDtypeStruct((1, 1), jnp.float32),
    ]

    if variant == "w0":
        @jax.jit
        def run(X, z, w):
            n = X.shape[0]
            return pl.pallas_call(
                kern, grid=(n // block_rows,),
                in_specs=[xspec, vec(), vec()],
                out_specs=outspecs, out_shape=outshape,
                cost_estimate=pl.CostEstimate(
                    flops=2 * n * p * (p + 2),
                    bytes_accessed=4 * (n * p + 2 * n + p * p + p),
                    transcendentals=0),
            )(X, z.reshape(n, 1), w.reshape(n, 1))
    else:
        bspec = (pl.BlockSpec((p, 128), lambda i: (0, 0), memory_space=pltpu.VMEM)
                 if variant in ("w1", "w2")
                 else pl.BlockSpec((1, p), lambda i: (0, 0), memory_space=pltpu.VMEM))

        @jax.jit
        def run(X, y, wt, off, beta):
            n = X.shape[0]
            if variant in ("w1", "w2"):
                B = jnp.zeros((p, 128), jnp.float32).at[:, 0].set(beta)
            else:
                B = beta.reshape(1, p)
            return pl.pallas_call(
                kern, grid=(n // block_rows,),
                in_specs=[xspec, vec(), vec(), vec(), bspec],
                out_specs=outspecs, out_shape=outshape,
                cost_estimate=pl.CostEstimate(
                    flops=2 * n * p * (p + 2),
                    bytes_accessed=4 * (n * p + 4 * n + p * p + 2 * p),
                    transcendentals=4 * n),
            )(X, y.reshape(n, 1), wt.reshape(n, 1), off.reshape(n, 1), B)
    return run


def main():
    n, p = 2_097_152, 512
    kx, kb = jax.random.split(jax.random.PRNGKey(0))
    X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
    beta_t = jax.random.normal(kb, (p,), jnp.float32) * 0.1
    eta = X @ beta_t
    mu = jax.nn.sigmoid(eta)
    y = (jax.random.uniform(jax.random.PRNGKey(1), (n,)) < mu).astype(jnp.float32)
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    v = jnp.maximum(mu * (1 - mu), 1e-30)
    w = wt * v
    z = eta + (y - mu) / v
    res = {"n": n, "p": p}

    # f64-ish oracle on host for accuracy of the full kernel
    import numpy as np
    Xn = np.asarray(X, np.float64)
    wn = np.asarray(w, np.float64)
    zn = np.asarray(z, np.float64)
    G64 = (Xn * wn[:, None]).T @ Xn
    b64 = (Xn * wn[:, None]).T @ zn
    scale = np.max(np.abs(G64))

    for variant in ("w0", "w1", "w2", "w3"):
        for blk in (1024,):
            tag = f"{variant}_b{blk}"
            try:
                k = build(variant, blk, p)
                args = (X, z, w) if variant == "w0" else (X, y, wt, off, beta_t)
                res[f"{tag}_ms"] = timeit(k, *args) * 1e3
                G, b, d = k(*args)
                res[f"{tag}_G_relerr"] = float(
                    np.max(np.abs(np.asarray(G, np.float64) - G64)) / scale)
                res[f"{tag}_b_relerr"] = float(
                    np.max(np.abs(np.asarray(b, np.float64) - b64)) / np.max(np.abs(b64)))
            except Exception as e:
                res[f"{tag}_error"] = str(e).split("\n")[0][:120]
            print(tag, res.get(f"{tag}_ms", res.get(f"{tag}_error")), flush=True)

    print(json.dumps(res, indent=1))
    with open("/root/repo/benchmarks/proto_fused2_r03.json", "w") as f:
        json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
