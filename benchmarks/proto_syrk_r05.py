"""Prototype: symmetric (syrk-style) Gramian — can exploiting X'WX's
symmetry beat XLA's full-GEMM einsum Gramian on the chip?

The IRLS pass is roughly balanced between the HBM read of X (~5-6 ms at
2M x 512 near peak) and the MXU Gramian (~5.6 ms at DEFAULT precision);
the full GEMM computes both triangles.  A panel-wise kernel that computes
only the LOWER triangle does ~half the MXU MACs for the same HBM read:
for each 128-wide output-column panel j it contracts

    G[j*128:, j*128:(j+1)*128] += Xw[:, j*128:]^T @ X[:, j*128:(j+1)*128]

(a static Python loop over panels inside the kernel; panel shapes shrink
as j grows).  Timings are dispatch-cancelled k-marginals with a D2H
fetch (HOTLOOP_r05.md methodology); the chain feeds a scalar weight
derived from the previous Gramian back into the next one.  CAVEAT found
on the first run: a SCALAR chain does NOT protect the einsum mode — XLA
rewrites (sX)'X = s*(X'X) and hoists the loop-invariant X'X, so the
einsum row is emitted with an `_invalid` marker; only the two Pallas
rows (opaque to the rewrite) are comparable.

Writes proto_syrk_r{ROUND}.json via _capture.  ONE tunnel client at a
time.
"""
import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo/benchmarks")

from _capture import dump_atomic, out_path  # noqa: E402

OUT = out_path("proto_syrk")
res: dict = {}


def dump():
    dump_atomic(res, OUT)


PANEL = 128


def _gram_kernel(x_ref, s_ref, out_ref, *, lower_only: bool, p: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    X = x_ref[:]
    Xw = X * s_ref[0, 0]
    if not lower_only:
        out_ref[:] += jax.lax.dot_general(
            Xw, X, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        return
    for j in range(p // PANEL):
        lo = j * PANEL
        out_ref[lo:, lo:lo + PANEL] += jax.lax.dot_general(
            Xw[:, lo:], X[:, lo:lo + PANEL], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)


@partial(jax.jit, static_argnames=("block_rows", "lower_only"))
def pallas_gram(X, s, block_rows=1024, lower_only=False):
    n, p = X.shape
    assert n % block_rows == 0 and p % PANEL == 0, (
        "pallas_gram needs n divisible by block_rows and p by the panel "
        "width; a partial trailing block would be silently dropped")
    return pl.pallas_call(
        partial(_gram_kernel, lower_only=lower_only, p=p),
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, p), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((p, p), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((p, p), jnp.float32),
    )(X, s.reshape(1, 1))


def main():
    res["device"] = str(jax.devices()[0])
    n, p = 2_097_152, 512
    res["n"], res["p"] = n, p

    @jax.jit
    def gen(key):
        return jax.random.normal(key, (n, p), jnp.float32)
    X = gen(jax.random.PRNGKey(3))
    jax.block_until_ready(X)

    # ---- correctness first --------------------------------------------------
    s1 = jnp.float32(1.0)
    Gf = pallas_gram(X[:4096], s1, lower_only=False)
    Gl = pallas_gram(X[:4096], s1, lower_only=True)
    tril = jnp.tril(jnp.ones((p, p), bool))
    err = float(jnp.max(jnp.abs(jnp.where(tril, Gl - Gf, 0.0))))
    scale = float(jnp.max(jnp.abs(Gf)))
    res["lower_vs_full_maxdiff_rel"] = err / scale
    dump()
    print("parity rel:", res["lower_vs_full_maxdiff_rel"], flush=True)

    # ---- chained marginals --------------------------------------------------
    @partial(jax.jit, static_argnames=("k", "mode"))
    def chain(X, k, mode):
        def body(c, _):
            s = 1.0 + 1e-12 * c
            if mode == "einsum":
                Xw = X * s
                G = jnp.einsum("np,nq->pq", Xw, X,
                               precision=jax.lax.Precision.DEFAULT,
                               preferred_element_type=jnp.float32)
            elif mode == "pallas_full":
                G = pallas_gram(X, jnp.float32(s), lower_only=False)
            else:
                G = pallas_gram(X, jnp.float32(s), lower_only=True)
            return G[0, 0], G[1, 0]
        c, _ = lax.scan(body, jnp.float32(0.0), None, length=k)
        return c

    def timed(fn, *args, reps=4):
        float(np.asarray(fn(*args)))  # warm + D2H barrier
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(np.asarray(fn(*args)))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    for mode in ("einsum", "pallas_full", "pallas_lower"):
        t2 = timed(chain, X, 2, mode)
        t6 = timed(chain, X, 6, mode)
        res[f"{mode}_marginal_ms"] = 1e3 * (t6 - t2) / 4
        if mode == "einsum":
            # XLA factors the scalar out and hoists X'X across the scan —
            # this row measures almost nothing (see module docstring)
            res["einsum_marginal_invalid"] = True
        dump()
        print(mode, res[f"{mode}_marginal_ms"], flush=True)

    res["complete"] = True
    dump()
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
