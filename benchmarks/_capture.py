"""Shared plumbing for the TPU capture harnesses.

One source of truth for (a) the round-tagged output path — the round
number comes from the ROUND env var that benchmarks/tpu_when_alive.sh
exports, so bumping it there retargets every writer at once — and
(b) atomic JSON dumps: the watchdog's `timeout` can SIGTERM a writer at
any instant, and a truncate-then-write that dies mid-dump would leave
unparseable JSON whose cleanup discards every accumulated measurement.
"""

from __future__ import annotations

import json
import os

ROUND = os.environ.get("ROUND", "5").zfill(2)
_HERE = os.path.dirname(os.path.abspath(__file__))


def out_path(stem: str) -> str:
    return os.path.join(_HERE, f"{stem}_r{ROUND}.json")


def dump_atomic(obj, path: str) -> None:
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=1)
    os.replace(tmp, path)
