"""Part 2 of the r5 hot-loop decomposition: solve-only marginal and the
FULL-kernel per-iteration marginals (t5 - t1 cancels every per-call cost,
incl. the ~65 ms tunnel RTT that inflates bench.py's per-call numbers).
Merges into benchmarks/hotloop_r05.json.  ONE tunnel client at a time."""
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

import sys

sys.path.insert(0, "/root/repo")

OUT = "/root/repo/benchmarks/hotloop_r05.json"
with open(OUT) as f:
    res = json.load(f)


def dump():
    import os
    with open(OUT + ".tmp", "w") as f:
        json.dump(res, f, indent=1)
    os.replace(OUT + ".tmp", OUT)


def main():
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.models.glm import _irls_fused_kernel, _irls_kernel
    from sparkglm_tpu.ops.fused import fused_fisher_pass
    from sparkglm_tpu.ops.solve import solve_normal
    import sparkglm_tpu as sg

    mesh = sg.make_mesh()
    fam, lnk = resolve("binomial", "logit")
    n, p = 2_097_152, 512

    @jax.jit
    def gen(key):
        kx, kb, ku = jax.random.split(key, 3)
        X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
        bt = jax.random.normal(kb, (p,), jnp.float32) / (2.0 * p ** 0.5)
        y = (jax.random.uniform(ku, (n,))
             < jax.nn.sigmoid(X @ bt)).astype(jnp.float32)
        return X, y
    X, y = gen(jax.random.PRNGKey(7))
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    jax.block_until_ready(y)

    def timed(fn, *args, reps=4):
        out = fn(*args)
        jax.block_until_ready(out)
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        return min(ts)

    beta = jnp.zeros((p,), jnp.float32)
    Afull, zfull, _ = fused_fisher_pass(X, y, wt, off, beta, family=fam,
                                        link=lnk, first=False,
                                        block_rows=1024)

    @partial(jax.jit, static_argnames=("k",))
    def solve_chain(A, z, k):
        def body(carry, _):
            b, s = carry
            Ak = A + (1e-7 * s) * jnp.eye(A.shape[0], dtype=A.dtype)
            bb, _ = solve_normal(Ak, z + 1e-6 * b, jitter=jnp.float32(0.0),
                                 refine_steps=1)
            return (bb, s + 1.0), bb[0]
        (bb, _), _ = lax.scan(body, (jnp.zeros_like(z), jnp.float32(1.0)),
                              None, length=k)
        return bb
    t1 = timed(solve_chain, Afull, zfull, 1)
    t9 = timed(solve_chain, Afull, zfull, 9)
    res["solve_p512_marginal_ms"] = 1e3 * (t9 - t1) / 8
    dump()
    print("solve marginal", res["solve_p512_marginal_ms"], flush=True)

    def fit_k(kernel, k, **kw):
        def run():
            return kernel(X, y, wt, off, jnp.float32(0.0), jnp.int32(k),
                          jnp.float32(0.0), family=fam, link=lnk,
                          criterion="relative", refine_steps=1, **kw)
        out = run()
        jax.block_until_ready(out["beta"])
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            out = run()
            jax.block_until_ready(out["beta"])
            ts.append(time.perf_counter() - t0)
        return min(ts), int(out["iters"])

    fkw = dict(mesh=mesh, block_rows=1024, use_pallas=True, precision=None)
    t1, i1 = fit_k(_irls_fused_kernel, 1, **fkw)
    t5, i5 = fit_k(_irls_fused_kernel, 5, **fkw)
    res["fit_1iter_ms"] = 1e3 * t1
    res["fit_5iter_ms"] = 1e3 * t5
    res["fit_marginal_per_iter_ms"] = 1e3 * (t5 - t1) / max(1, i5 - i1)
    dump()
    print("fused fit marginal/iter", res["fit_marginal_per_iter_ms"],
          flush=True)

    e1, j1 = fit_k(_irls_kernel, 1)
    e5, j5 = fit_k(_irls_kernel, 5)
    res["einsum_1iter_ms"] = 1e3 * e1
    res["einsum_5iter_ms"] = 1e3 * e5
    res["einsum_marginal_per_iter_ms"] = 1e3 * (e5 - e1) / max(1, j5 - j1)
    res["complete"] = True
    dump()
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
