#!/bin/bash
# Probe the axon TPU tunnel; when it answers, run the queued TPU captures
# in sequence, highest-value-first (the tunnel can wedge again at any
# moment — never re-spend tunnel time on a capture that already exists).
# Safe to re-run: each step is guarded by a VALID output file (partial
# JSON from a timeout kill is removed, not trusted; the three incremental
# writers additionally stamp "complete": true on their final dump, so a
# partial file is kept but never satisfies the guard).
# IMPORTANT: run ONE tpu process at a time — concurrent clients wedge the
# tunnel (observed in r1, r2, and again in r3 when a D2H pull was
# SIGTERM'd mid-transfer).
#
# Queue order (VERDICT r4 "next round" #1 and #2), highest value first:
#   1. engine sweep      — hardware re-cert of the fused-vs-einsum
#                          crossover + shipped-kernel timing table
#   2. headline bench.py — the engine-tagged number of record
#                          (bench_detail_latest.json)
#   3. bf16 sched bench  — the SHIPPED bf16-warmup schedule end-to-end
#                          (executes BF16_SCHEDULE_r04.md's decision rule)
#   4. bf16 master proto — the roofline lever prototype
#   5. scoring bench     — 10M-row sharded predict
#   6. five-config refresh (results_r05.json, configs 1-5 at scale 1)
#   7. config 5 at FULL 50M x 500 -> config5_rNN.json (longest; last
#      so a wedge costs least)
#
# DEADLINE: checked before EVERY step, not just per probe pass — a queue
# entered seconds before the deadline must not run hours past it into the
# driver's end-of-round bench.py (r3's stale watchdog caused exactly that
# collision — R4_RESPONSE.md).
set -u
cd "$(dirname "$0")/.."

export ROUND=5   # bench.py + benchmarks/_capture.py read this — one source
R2=$(printf "%02d" "$ROUND")   # matches _capture.py's ROUND.zfill(2)
DEADLINE_EPOCH="${DEADLINE_EPOCH:-$(( $(date +%s) + 34200 ))}"   # default 9.5h

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu'
print(float((jnp.ones((128,128))@jnp.ones((128,128)))[0,0]))" >/dev/null 2>&1
}

before_deadline() { [ "$(date +%s)" -lt "$DEADLINE_EPOCH" ]; }

# STEPS: "output-file|required-marker|timeout|command"
# required-marker: grep pattern the file must contain beyond parsing
# (empty = parseable is enough).  bench_detail_latest must be THIS round's
# capture; the incremental writers must have reached their final dump.
STEPS=(
  "benchmarks/engine_sweep_r${R2}.json||560|python -u benchmarks/tpu_validate.py"
  "benchmarks/bench_detail_latest.json|\"round\": ${ROUND}|560|python bench.py"
  "benchmarks/bf16_sched_r${R2}.json|\"complete\": true|900|python -u benchmarks/bf16_sched_bench.py"
  "benchmarks/proto_bf16_r${R2}.json|\"complete\": true|560|python -u benchmarks/proto_bf16_master.py"
  "benchmarks/scoring_r${R2}.json||560|python -u benchmarks/scoring_bench.py"
  "benchmarks/results_r${R2}.json|\"complete\": true|1500|python -u benchmarks/run.py --merge --json benchmarks/results_r${R2}.json"
  "benchmarks/config5_r${R2}.json||3000|python -u benchmarks/config5_full.py"
)

capture_ok() {  # $1=file $2=marker: non-empty, parseable, marker present
  [ -s "$1" ] || return 1
  python -c "import json,sys; json.load(open(sys.argv[1]))" "$1" >/dev/null 2>&1 || return 1
  [ -z "$2" ] || grep -q "$2" "$1"
}

run_queue() {
  local spec file marker tmo cmd log left
  for spec in "${STEPS[@]}"; do
    IFS='|' read -r file marker tmo cmd <<<"$spec"
    capture_ok "$file" "$marker" && continue
    # clamp the step budget to the remaining deadline window: a step
    # entered seconds before the deadline must not hold the tunnel for
    # its full timeout into the driver's end-of-round bench.py
    left=$(( DEADLINE_EPOCH - $(date +%s) ))
    if [ "$left" -lt 120 ]; then
      echo "[$(date +%H:%M:%S)] <120s to deadline; skipping $file"
      return 1
    fi
    [ "$tmo" -gt "$left" ] && tmo=$left
    log="/tmp/$(basename "$file" .json).log"
    echo "== $cmd  (-> $file, timeout ${tmo}s)"
    timeout "$tmo" $cmd >"$log" 2>&1 \
      || { echo "   step failed (rc=$?)"; tail -5 "$log"; }
    # a partial/invalid capture must not satisfy the guard next pass —
    # EXCEPT incremental writers, whose partial dumps (parseable, no
    # "complete" marker) are kept for inspection; the step still re-runs
    # from scratch next pass (the writers have no resume logic)
    if ! capture_ok "$file" "$marker"; then
      python -c "import json,sys; json.load(open(sys.argv[1]))" "$file" >/dev/null 2>&1 \
        || rm -f "$file"
    fi
  done
}

all_done() {
  local spec file marker _
  for spec in "${STEPS[@]}"; do
    IFS='|' read -r file marker _ <<<"$spec"
    capture_ok "$file" "$marker" || return 1
  done
}

i=0
while before_deadline; do
  i=$((i+1))
  if probe; then
    echo "[$(date +%H:%M:%S)] tunnel alive (probe $i) — running queue"
    run_queue; queue_rc=$?
    if all_done; then
      echo "[$(date +%H:%M:%S)] ALL CAPTURES COMPLETE"
      exit 0
    fi
    [ "$queue_rc" -ne 0 ] && break   # deadline hit mid-queue: exit now
    echo "[$(date +%H:%M:%S)] queue pass ended (captures missing); re-probing in 120s"
    sleep 120
  else
    echo "[$(date +%H:%M:%S)] probe $i: tunnel wedged; sleeping 240s"
    sleep 240
  fi
done
echo "[$(date +%H:%M:%S)] deadline reached; exiting so the driver's bench.py has the tunnel to itself"
all_done && exit 0 || exit 1
