#!/bin/bash
# Probe the axon TPU tunnel; when it answers, run the queued TPU captures
# in sequence (five-config harness, engine sweep, headline bench).  Safe to
# re-run: each step skips itself if its output already exists and is fresh.
# IMPORTANT: run ONE tpu process at a time — concurrent clients wedge the
# tunnel (observed twice in r2).
set -u
cd "$(dirname "$0")/.."

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu'
print(float((jnp.ones((128,128))@jnp.ones((128,128)))[0,0]))" >/dev/null 2>&1
}

for i in $(seq 1 "${PROBES:-8}"); do
  if probe; then
    echo "tunnel alive (probe $i)"
    if [ ! -s benchmarks/results_r02.json ]; then
      echo "== five-config harness"
      timeout 560 python -u benchmarks/run.py --json benchmarks/results_r02.json 2>&1 | grep -v WARNING
    fi
    if [ ! -s benchmarks/engine_sweep_r02.json ]; then
      echo "== engine sweep"
      timeout 560 python -u benchmarks/tpu_validate.py > benchmarks/engine_sweep_r02.json 2>/tmp/sweep_err.log \
        || { echo "sweep failed"; rm -f benchmarks/engine_sweep_r02.json; tail -5 /tmp/sweep_err.log; }
    fi
    echo "== headline bench"
    timeout 560 python bench.py 2>/tmp/bench_late.log
    exit 0
  fi
  echo "probe $i: tunnel wedged; sleeping 45s"
  sleep 45
done
echo "tunnel never answered"
exit 1
