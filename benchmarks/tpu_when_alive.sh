#!/bin/bash
# Probe the axon TPU tunnel; when it answers, run the queued TPU captures
# in sequence, highest-value-first (the tunnel can wedge again at any
# moment — never re-spend tunnel time on a capture that already exists).
# Safe to re-run: each step is guarded by a VALID output file (partial
# JSON from a timeout kill is removed, not trusted).
# IMPORTANT: run ONE tpu process at a time — concurrent clients wedge the
# tunnel (observed in r1, r2, and again in r3 when a D2H pull was
# SIGTERM'd mid-transfer).
#
# r04 queue order (VERDICT r3 "next round" #1 and #2):
#   1. engine sweep      — hardware re-cert of the fused-vs-einsum
#                          crossover + shipped-kernel timing table
#   2. headline bench.py — the engine-tagged number of record
#                          (bench_detail_latest.json)
#   3. bf16 master proto — the one untried roofline lever (proto_bf16_r04)
#   4. scoring bench     — 10M-row sharded predict
#   5. five-config refresh (results_r04.json, configs 1-5 at scale 1)
#   6. config 5 at FULL 50M x 500 (longest; last so a wedge costs least)
set -u
cd "$(dirname "$0")/.."

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform == 'tpu'
print(float((jnp.ones((128,128))@jnp.ones((128,128)))[0,0]))" >/dev/null 2>&1
}

valid_json() {  # non-empty AND parseable
  [ -s "$1" ] && python -c "import json,sys; json.load(open(sys.argv[1]))" "$1" >/dev/null 2>&1
}

for i in $(seq 1 "${PROBES:-8}"); do
  if probe; then
    echo "tunnel alive (probe $i)"
    if ! valid_json benchmarks/engine_sweep_r03.json; then
      echo "== engine sweep (hardware re-cert, DEFAULT-precision fused kernel)"
      timeout 560 python -u benchmarks/tpu_validate.py >/tmp/sweep_out.log 2>/tmp/sweep_err.log \
        || { echo "sweep failed"; tail -5 /tmp/sweep_err.log; }
      valid_json benchmarks/engine_sweep_r03.json || rm -f benchmarks/engine_sweep_r03.json
    fi
    if ! { valid_json benchmarks/bench_detail_latest.json \
           && grep -q '"engine"' benchmarks/bench_detail_latest.json; }; then
      echo "== headline bench (fused vs einsum, engine-tagged number of record)"
      timeout 560 python bench.py 2>/tmp/bench_late.log \
        || { echo "headline failed"; tail -5 /tmp/bench_late.log; }
      valid_json benchmarks/bench_detail_latest.json \
        || rm -f benchmarks/bench_detail_latest.json
    fi
    if ! valid_json benchmarks/proto_bf16_r04.json; then
      echo "== bf16 master-copy prototype (roofline lever, VERDICT r3 #2)"
      timeout 560 python -u benchmarks/proto_bf16_master.py >/tmp/bf16_out.log 2>&1 \
        || { echo "bf16 proto failed"; tail -5 /tmp/bf16_out.log; }
      valid_json benchmarks/proto_bf16_r04.json || rm -f benchmarks/proto_bf16_r04.json
    fi
    if ! valid_json benchmarks/bf16_sched_r04.json; then
      echo "== SHIPPED bf16-warmup schedule end-to-end (fused vs fused+warmup)"
      timeout 900 python -u benchmarks/bf16_sched_bench.py >/tmp/bf16_sched.log 2>&1 \
        || { echo "bf16 sched bench failed"; tail -5 /tmp/bf16_sched.log; }
      valid_json benchmarks/bf16_sched_r04.json || rm -f benchmarks/bf16_sched_r04.json
    fi
    if ! valid_json benchmarks/scoring_r03.json; then
      echo "== 10M-row scoring bench"
      timeout 560 python -u benchmarks/scoring_bench.py >/tmp/score_out.log 2>&1 \
        || { echo "scoring bench failed"; tail -5 /tmp/score_out.log; }
      valid_json benchmarks/scoring_r03.json || rm -f benchmarks/scoring_r03.json
    fi
    if ! valid_json benchmarks/results_r04.json; then
      echo "== five-config refresh (results_r04.json)"
      timeout 1500 python -u benchmarks/run.py --json benchmarks/results_r04.json \
        >/tmp/run_r04.log 2>&1 \
        || { echo "five-config failed"; tail -5 /tmp/run_r04.log; }
      valid_json benchmarks/results_r04.json || rm -f benchmarks/results_r04.json
    fi
    if ! valid_json benchmarks/results_r03_config5.json; then
      echo "== BASELINE config 5 at FULL 50M x 500 (several minutes)"
      timeout 3000 python -u benchmarks/config5_full.py 2>&1 | tail -20
      valid_json benchmarks/results_r03_config5.json || rm -f benchmarks/results_r03_config5.json
    fi
    exit 0
  fi
  echo "probe $i: tunnel wedged; sleeping 45s"
  sleep 45
done
echo "tunnel never answered"
exit 1
