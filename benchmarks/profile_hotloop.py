"""Decompose the einsum-IRLS iteration at 2Mx512 into its component costs.

VERDICT r2 #1: headline 40 ms/iter at MFU 0.14 with an unexplained ~25 ms.
Hypotheses to measure, each timed as an isolated jitted op on the real chip:

  H1  the Gramian einsum pair itself (default precision)      ~5-10 ms
  H2  materialising Xw = X * w[:, None] costs an extra        ~10 ms
      write+read pass vs the symmetric sqrt(w) form
  H3  the eta matvec X @ beta                                  ~5 ms
  H4  elementwise z/w/deviance                                 ~1 ms
  H5  cho_factor (p=512, replicated)                           ?
  H6  inv_from_cho = cho_solve against eye(p) EVERY iteration  ?  <-- suspect
  H7  solve_normal incl. refine_steps=1                        ?

Run exactly one TPU client at a time (memory: tpu-tunnel-fragility).
"""
import json
import time
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
from sparkglm_tpu.ops.gramian import weighted_gramian
from sparkglm_tpu.ops.solve import solve_normal, inv_from_cho, cho_factor, cho_solve  # noqa


def _fetch_scalar(out):
    """Force completion of everything enqueued so far (device executes
    in-order; a host fetch of any later result waits for all of it)."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.asarray(leaf).ravel()[0])


def timeit(fn, *args, reps=12):
    """Slope timing: the axon tunnel's block_until_ready is a no-op and a
    per-call device_get pays ~200 ms RPC latency, so time K enqueues + one
    scalar fetch at two K values and difference out the constant RPC cost."""
    out = fn(*args)
    _fetch_scalar(out)  # warm compile

    def run(k):
        t0 = time.perf_counter()
        for _ in range(k):
            out = fn(*args)
        _fetch_scalar(out)
        return time.perf_counter() - t0

    k1, k2 = 2, 2 + reps
    t1 = min(run(k1), run(k1))
    t2 = min(run(k2), run(k2))
    return max((t2 - t1) / (k2 - k1), 0.0)


def main():
    n, p = 2_097_152, 512
    key = jax.random.PRNGKey(0)
    kx, kb = jax.random.split(key)
    X = jax.random.normal(kx, (n, p), jnp.float32)
    X = X.at[:, 0].set(1.0)
    beta_true = jax.random.normal(kb, (p,), jnp.float32) * 0.1
    eta = X @ beta_true
    mu = jax.nn.sigmoid(eta)
    y = (jax.random.uniform(jax.random.PRNGKey(1), (n,)) < mu).astype(jnp.float32)
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    beta = jnp.zeros((p,), jnp.float32)
    jax.block_until_ready((X, y))

    res = {"n": n, "p": p, "device": str(jax.devices()[0])}

    # H1: gramian pair as shipped (Xw materialised form)
    g_asis = jax.jit(lambda X, z, w: weighted_gramian(X, z, w))
    res["gramian_asis_ms"] = timeit(g_asis, X, eta, wt) * 1e3

    # H2: symmetric sqrt(w) form — same operand twice
    @jax.jit
    def g_sym(X, z, w):
        s = jnp.sqrt(w)
        Xs = X * s[:, None]
        G = jnp.einsum("np,nq->pq", Xs, Xs, preferred_element_type=jnp.float32)
        b = jnp.einsum("np,n->p", Xs, s * z, preferred_element_type=jnp.float32)
        return G, b

    res["gramian_sym_ms"] = timeit(g_sym, X, eta, wt) * 1e3

    # H3: eta matvec
    mv = jax.jit(lambda X, b, o: X @ b + o)
    res["matvec_ms"] = timeit(mv, X, beta_true, off) * 1e3

    # H4: elementwise z/w/dev for logistic
    @jax.jit
    def elem(eta, y, wt):
        mu = jax.nn.sigmoid(eta)
        g = 1.0 / jnp.maximum(mu * (1 - mu), 1e-30)
        w = wt / jnp.maximum((mu * (1 - mu)) * g * g, 1e-30)
        z = eta + (y - mu) * g
        ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y / mu, 1e-30)), 0.0)
        y1 = jnp.where(y < 1, (1 - y) * jnp.log(jnp.maximum((1 - y) / (1 - mu), 1e-30)), 0.0)
        dev = 2.0 * jnp.sum(wt * (ylog + y1))
        return z, w, dev

    res["elementwise_ms"] = timeit(elem, eta, y, wt) * 1e3

    # H5-H7: the p x p solve chain
    G, b = g_asis(X, eta, wt)
    jax.block_until_ready((G, b))

    chof = jax.jit(lambda A: cho_factor(A))
    res["cho_factor_ms"] = timeit(chof, G) * 1e3

    cmat, lower = cho_factor(G)
    jax.block_until_ready(cmat)
    inv_eye = jax.jit(lambda c: cho_solve((c, lower), jnp.eye(p, dtype=jnp.float32)))
    res["cho_solve_eye_ms"] = timeit(inv_eye, cmat) * 1e3
    solve1 = jax.jit(lambda c, b: cho_solve((c, lower), b))
    res["cho_solve_1rhs_ms"] = timeit(solve1, cmat, b) * 1e3

    sn0 = jax.jit(lambda G, b: solve_normal(G, b, refine_steps=0)[0])
    res["solve_normal_r0_ms"] = timeit(sn0, G, b) * 1e3
    sn1 = jax.jit(lambda G, b: solve_normal(G, b, refine_steps=1)[0])
    res["solve_normal_r1_ms"] = timeit(sn1, G, b) * 1e3

    @jax.jit
    def solve_plus_inv(G, b):
        beta, cho = solve_normal(G, b, refine_steps=1)
        return beta, inv_from_cho(cho, p, jnp.float32)

    res["solve_plus_inv_ms"] = timeit(solve_plus_inv, G, b) * 1e3

    # full shipped body equivalent, one iteration (gramian + solve + inv +
    # matvec + elementwise + dev)
    @jax.jit
    def body(X, y, wt, off, beta):
        eta = X @ beta + off
        mu = jax.nn.sigmoid(eta)
        gd = 1.0 / jnp.maximum(mu * (1 - mu), 1e-30)
        w = wt / jnp.maximum((mu * (1 - mu)) * gd * gd, 1e-30)
        z = eta - off + (y - mu) * gd
        G, bb = weighted_gramian(X, z, w)
        beta_n, cho = solve_normal(G, bb, refine_steps=1)
        cov = inv_from_cho(cho, p, jnp.float32)
        eta_n = X @ beta_n + off
        mu_n = jax.nn.sigmoid(eta_n)
        ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y / mu_n, 1e-30)), 0.0)
        y1 = jnp.where(y < 1, (1 - y) * jnp.log(jnp.maximum((1 - y) / (1 - mu_n), 1e-30)), 0.0)
        dev = 2.0 * jnp.sum(wt * (ylog + y1))
        return beta_n, cov, dev

    res["full_body_ms"] = timeit(body, X, y, wt, off, beta) * 1e3

    # body without the in-loop inverse (factor carried; cov post-loop)
    @jax.jit
    def body_noinv(X, y, wt, off, beta):
        eta = X @ beta + off
        mu = jax.nn.sigmoid(eta)
        gd = 1.0 / jnp.maximum(mu * (1 - mu), 1e-30)
        w = wt / jnp.maximum((mu * (1 - mu)) * gd * gd, 1e-30)
        z = eta - off + (y - mu) * gd
        s = jnp.sqrt(w)
        Xs = X * s[:, None]
        G = jnp.einsum("np,nq->pq", Xs, Xs, preferred_element_type=jnp.float32)
        bb = jnp.einsum("np,n->p", Xs, s * z, preferred_element_type=jnp.float32)
        beta_n, cho = solve_normal(G, bb, refine_steps=0)
        ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y / mu, 1e-30)), 0.0)
        y1 = jnp.where(y < 1, (1 - y) * jnp.log(jnp.maximum((1 - y) / (1 - mu), 1e-30)), 0.0)
        dev = 2.0 * jnp.sum(wt * (ylog + y1))
        return beta_n, dev

    try:
        res["body_noinv_ms"] = timeit(body_noinv, X, y, wt, off, beta_true) * 1e3
    except Exception as e:  # pragma: no cover
        res["body_noinv_error"] = str(e)

    print(json.dumps(res, indent=1))
    with open("/root/repo/benchmarks/hotloop_decomp_r03.json", "w") as f:
        json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
