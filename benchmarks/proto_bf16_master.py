"""Prototype: bf16 master copy of X for the single-pass Fisher kernel.

VERDICT r3 weak #3 / next-round #2: HOTLOOP_r03.md's roofline puts the
floor at ~6-8 ms/iter at 2M x 512 vs the shipped fused kernel's ~16 ms,
and names one untried lever — storing X in bfloat16 so the dominant HBM
read halves (n*p*4 -> n*p*2 bytes) — before calling 14-16 ms structural.
This measures that lever with the accuracy contract attached:

  * f32_default        — the shipped r3 kernel (baseline, ~16 ms)
  * bf16_upcast        — X stored bf16, upcast to f32 in VMEM; identical
                         arithmetic to the shipped kernel thereafter (the
                         MXU sees the same bf16 multiplicands DEFAULT
                         precision would produce; only input storage
                         rounding is added)
  * bf16_native        — X stored bf16, VPU elementwise kept in bf16
                         where legal (Xw product), MXU fed bf16 directly;
                         tests whether bf16 VPU lanes shave the ~8 ms of
                         vector work that cannot overlap the MXU

Accuracy is reported as (a) max relerr of the Gramian vs an f32 HIGHEST
reference, and (b) the end-to-end contract that matters: relerr of the
solved Newton step beta = G^{-1} b vs the reference step.

Writes benchmarks/proto_bf16_r05.json.  Run ONE process at a time on the
tunnel (see tpu_when_alive.sh).
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")

from _capture import dump_atomic, out_path  # noqa: E402


def _fetch(out):
    return float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])


def timeit(fn, *args, reps=12):
    out = fn(*args)
    _fetch(out)

    def run(k):
        t0 = time.perf_counter()
        for _ in range(k):
            out = fn(*args)
        _fetch(out)
        return time.perf_counter() - t0

    t1 = min(run(2), run(2))
    t2 = min(run(2 + reps), run(2 + reps))
    return max((t2 - t1) / reps, 0.0)


def make_kernel(mode, block_rows, p, precision=jax.lax.Precision.DEFAULT):
    """mode: f32 | bf16_upcast | bf16_native.  Logistic Fisher pass."""
    x_dtype = jnp.float32 if mode == "f32" else jnp.bfloat16

    def kern(x_ref, y_ref, wt_ref, off_ref, beta_ref,
             xtwx_ref, xtwz_ref, dev_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            xtwx_ref[:] = jnp.zeros_like(xtwx_ref)
            xtwz_ref[:] = jnp.zeros_like(xtwz_ref)
            dev_ref[:] = jnp.zeros_like(dev_ref)

        Xs = x_ref[:]                      # stored dtype (f32 or bf16)
        X = Xs.astype(jnp.float32)
        y = y_ref[:]
        wt = wt_ref[:]
        off = off_ref[:]
        beta_row = beta_ref[:]
        valid = wt > 0.0
        eta = jnp.sum(X * beta_row, axis=1, keepdims=True) + off
        mu = jnp.where(valid, jax.nn.sigmoid(eta), 0.5)
        v = jnp.maximum(mu * (1.0 - mu), 1e-30)
        g = 1.0 / v
        w = jnp.where(valid, wt * v, 0.0)
        z = jnp.where(valid, eta - off + (y - mu) * g, 0.0)
        ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y / mu, 1e-30)), 0.0)
        y1 = jnp.where(y < 1, (1 - y) * jnp.log(
            jnp.maximum((1 - y) / (1 - mu), 1e-30)), 0.0)
        dev = jnp.sum(jnp.where(valid, 2.0 * wt * (ylog + y1), 0.0)).reshape(1, 1)
        if mode == "bf16_native":
            # keep the rank-2 elementwise product on bf16 VPU lanes; the
            # MXU consumes bf16 directly either way under DEFAULT
            Xw = Xs * w.astype(jnp.bfloat16)
            xtwx_ref[:] += jax.lax.dot_general(
                Xw, Xs, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=precision)
            xtwz_ref[:] += jax.lax.dot_general(
                z.reshape(1, -1).astype(jnp.bfloat16), Xw,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=precision)
        else:
            Xw = X * w
            xtwx_ref[:] += jax.lax.dot_general(
                Xw, X, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=precision)
            xtwz_ref[:] += jnp.sum(Xw * z, axis=0, keepdims=True)
        dev_ref[:] += dev

    itemsize = 4 if mode == "f32" else 2

    @jax.jit
    def run(X, y, wt, off, beta):
        n = X.shape[0]
        yc, wc, oc = (a.reshape(n, 1) for a in (y, wt, off))
        vec = lambda: pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kern,
            grid=(n // block_rows,),
            in_specs=[
                pl.BlockSpec((block_rows, p), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                vec(), vec(), vec(),
                pl.BlockSpec((1, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((p, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((p, p), jnp.float32),
                jax.ShapeDtypeStruct((1, p), jnp.float32),
                jax.ShapeDtypeStruct((1, 1), jnp.float32),
            ],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * p * (p + 2),
                bytes_accessed=itemsize * n * p + 4 * (4 * n + p * p + 2 * p),
                transcendentals=4 * n,
            ),
            interpret=os.environ.get("PALLAS_INTERPRET") == "1",
        )(X, yc, wc, oc, beta.reshape(1, p))

    return run


def main():
    n, p = 2_097_152, 512
    kx, kb = jax.random.split(jax.random.PRNGKey(0))
    X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
    beta_t = jax.random.normal(kb, (p,), jnp.float32) * 0.1
    eta = X @ beta_t
    mu = jax.nn.sigmoid(eta)
    y = (jax.random.uniform(jax.random.PRNGKey(1), (n,)) < mu).astype(jnp.float32)
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    Xb = X.astype(jnp.bfloat16)
    res = {"n": n, "p": p}

    ref = make_kernel("f32", 512, p, jax.lax.Precision.HIGHEST)
    Gr, br, dr = ref(X, y, wt, off, beta_t)
    lam = 1e-6 * jnp.trace(Gr) / p
    step_ref = jax.scipy.linalg.cho_solve(
        jax.scipy.linalg.cho_factor(Gr + lam * jnp.eye(p)), br.ravel())

    def record(tag, k, Xin):
        try:
            t = timeit(k, Xin, y, wt, off, beta_t)
            G, b, d = k(Xin, y, wt, off, beta_t)
            step = jax.scipy.linalg.cho_solve(
                jax.scipy.linalg.cho_factor(G + lam * jnp.eye(p)), b.ravel())
            res[f"{tag}_ms"] = t * 1e3
            res[f"{tag}_gram_relerr"] = float(
                jnp.max(jnp.abs(G - Gr)) / jnp.max(jnp.abs(Gr)))
            res[f"{tag}_step_relerr"] = float(
                jnp.linalg.norm(step - step_ref) / jnp.linalg.norm(step_ref))
        except Exception as e:
            res[f"{tag}_error"] = str(e).split("\n")[0][:160]
        print(tag, res.get(f"{tag}_ms", res.get(f"{tag}_error")),
              res.get(f"{tag}_step_relerr", ""), flush=True)
        # dump incrementally: a tunnel wedge / timeout kill mid-sweep keeps
        # every completed measurement (tunnel time is never re-spent)
        dump_atomic(res, out_path("proto_bf16"))

    for br_rows in (256, 512, 1024):
        record(f"f32_default_b{br_rows}",
               make_kernel("f32", br_rows, p), X)
        record(f"bf16_upcast_b{br_rows}",
               make_kernel("bf16_upcast", br_rows, p), Xb)
        record(f"bf16_native_b{br_rows}",
               make_kernel("bf16_native", br_rows, p), Xb)

    res["complete"] = True  # watchdog guard: partial dumps lack this
    print(json.dumps(res, indent=1))
    dump_atomic(res, out_path("proto_bf16"))


if __name__ == "__main__":
    main()
