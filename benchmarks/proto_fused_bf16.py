"""Prototype: single-pass Pallas Fisher kernel with DEFAULT (bf16-multiply,
f32-accumulate) Gramian precision and larger row blocks — measures whether the
one-HBM-pass structure can beat the einsum engine's ~26-40 ms/iter at 2Mx512
once the 6-pass HIGHEST precision penalty is removed (VERDICT r2 #2)."""
import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")


def _fetch(out):
    return float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])


def timeit(fn, *args, reps=12):
    out = fn(*args)
    _fetch(out)

    def run(k):
        t0 = time.perf_counter()
        for _ in range(k):
            out = fn(*args)
        _fetch(out)
        return time.perf_counter() - t0

    t1 = min(run(2), run(2))
    t2 = min(run(2 + reps), run(2 + reps))
    return max((t2 - t1) / reps, 0.0)


def make_kernel(precision, block_rows, p):
    def kern(x_ref, y_ref, wt_ref, off_ref, beta_ref,
             xtwx_ref, xtwz_ref, dev_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            xtwx_ref[:] = jnp.zeros_like(xtwx_ref)
            xtwz_ref[:] = jnp.zeros_like(xtwz_ref)
            dev_ref[:] = jnp.zeros_like(dev_ref)

        X = x_ref[:]
        y = y_ref[:]
        wt = wt_ref[:]
        off = off_ref[:]
        beta_row = beta_ref[:]
        valid = wt > 0.0
        eta = jnp.sum(X * beta_row, axis=1, keepdims=True) + off
        mu = jnp.where(valid, jax.nn.sigmoid(eta), 0.5)
        v = jnp.maximum(mu * (1.0 - mu), 1e-30)
        g = 1.0 / v
        w = jnp.where(valid, wt * v, 0.0)  # wt / (v*g^2) = wt*v for logit
        z = jnp.where(valid, eta - off + (y - mu) * g, 0.0)
        ylog = jnp.where(y > 0, y * jnp.log(jnp.maximum(y / mu, 1e-30)), 0.0)
        y1 = jnp.where(y < 1, (1 - y) * jnp.log(jnp.maximum((1 - y) / (1 - mu), 1e-30)), 0.0)
        dev = jnp.sum(jnp.where(valid, 2.0 * wt * (ylog + y1), 0.0)).reshape(1, 1)
        Xw = X * w
        xtwx_ref[:] += jax.lax.dot_general(
            Xw, X, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)
        xtwz_ref[:] += jnp.sum(Xw * z, axis=0, keepdims=True)
        dev_ref[:] += dev

    @jax.jit
    def run(X, y, wt, off, beta):
        n = X.shape[0]
        yc, wc, oc = (a.reshape(n, 1) for a in (y, wt, off))
        vec = lambda: pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kern,
            grid=(n // block_rows,),
            in_specs=[
                pl.BlockSpec((block_rows, p), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                vec(), vec(), vec(),
                pl.BlockSpec((1, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((p, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, p), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((p, p), jnp.float32),
                jax.ShapeDtypeStruct((1, p), jnp.float32),
                jax.ShapeDtypeStruct((1, 1), jnp.float32),
            ],
            cost_estimate=pl.CostEstimate(
                flops=2 * n * p * (p + 2),
                bytes_accessed=4 * (n * p + 4 * n + p * p + 2 * p),
                transcendentals=4 * n,
            ),
        )(X, yc, wc, oc, beta.reshape(1, p))

    return run


def main():
    n, p = 2_097_152, 512
    kx, kb = jax.random.split(jax.random.PRNGKey(0))
    X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
    beta_t = jax.random.normal(kb, (p,), jnp.float32) * 0.1
    eta = X @ beta_t
    mu = jax.nn.sigmoid(eta)
    y = (jax.random.uniform(jax.random.PRNGKey(1), (n,)) < mu).astype(jnp.float32)
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    res = {"n": n, "p": p}

    # reference values at HIGHEST for accuracy comparison
    ref = make_kernel(jax.lax.Precision.HIGHEST, 512, p)
    Gr, br, dr = ref(X, y, wt, off, beta_t)
    Gr64 = jnp.asarray(Gr)

    for prec, pname in [(jax.lax.Precision.HIGHEST, "highest"),
                        (jax.lax.Precision.DEFAULT, "default")]:
        for br_rows in (256, 512, 1024):
            tag = f"{pname}_b{br_rows}"
            try:
                k = make_kernel(prec, br_rows, p)
                t = timeit(k, X, y, wt, off, beta_t)
                G, b, d = k(X, y, wt, off, beta_t)
                rel = float(jnp.max(jnp.abs(G - Gr)) / jnp.max(jnp.abs(Gr)))
                res[f"{tag}_ms"] = t * 1e3
                res[f"{tag}_relerr"] = rel
            except Exception as e:
                res[f"{tag}_error"] = str(e).split("\n")[0][:160]
            print(tag, res.get(f"{tag}_ms", res.get(f"{tag}_error")), flush=True)

    print(json.dumps(res, indent=1))
    with open("/root/repo/benchmarks/proto_fused_r03.json", "w") as f:
        json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
