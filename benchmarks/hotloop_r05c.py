"""Part 3: isolate WHERE the fused kernel loses ~20 ms/iter vs the chained
pass+solve floor (19 ms).  Candidates: the per-iteration shard_map
entry/exit (the kernel wraps EACH pass in shard_map and runs the
while_loop outside), the while_loop itself, or the carried-state plumbing.

Variants timed as k-marginals (k=2 vs k=6 — the k=1 endpoint behaved
anomalously over the tunnel):
  A. plain chained scan of pass+solve (baseline floor, re-measured)
  B. A wrapped in ONE shard_map around the whole scan (psum inside) —
     the "loop inside shard_map" restructure candidate
  C. scan where each step calls a shard_map'd pass (the CURRENT kernel
     shape: shard_map per iteration)
Merges into benchmarks/hotloop_r05.json."""
import json
import time
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

import sys

sys.path.insert(0, "/root/repo")

OUT = "/root/repo/benchmarks/hotloop_r05.json"
with open(OUT) as f:
    res = json.load(f)


def dump():
    import os
    with open(OUT + ".tmp", "w") as f:
        json.dump(res, f, indent=1)
    os.replace(OUT + ".tmp", OUT)


def main():
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.ops.fused import fused_fisher_pass
    from sparkglm_tpu.ops.solve import solve_normal
    from sparkglm_tpu.parallel import mesh as meshlib
    import sparkglm_tpu as sg

    mesh = sg.make_mesh()
    fam, lnk = resolve("binomial", "logit")
    n, p = 2_097_152, 512

    @jax.jit
    def gen(key):
        kx, kb, ku = jax.random.split(key, 3)
        X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
        bt = jax.random.normal(kb, (p,), jnp.float32) / (2.0 * p ** 0.5)
        y = (jax.random.uniform(ku, (n,))
             < jax.nn.sigmoid(X @ bt)).astype(jnp.float32)
        return X, y
    X, y = gen(jax.random.PRNGKey(7))
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    b0 = jnp.zeros((p,), jnp.float32)
    jax.block_until_ready(y)

    import numpy as _np

    def force(out):
        # block_until_ready over the axon tunnel returns early for small
        # outputs (observed: 0.02 ms for a 6-pass chain) — force a real
        # synchronous D2H value fetch instead; its ~RTT cost cancels in
        # the k-marginals
        return float(_np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])

    def timed(fn, *args, reps=4):
        force(fn(*args))
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            force(fn(*args))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    def iter_body(Xs, ys, ws, os_, b, *, spmd):
        A, z, dev = fused_fisher_pass(Xs, ys, ws, os_, b, family=fam,
                                      link=lnk, first=False, block_rows=1024)
        if spmd:
            A = lax.psum(A, meshlib.DATA_AXIS)
            z = lax.psum(z, meshlib.DATA_AXIS)
            dev = lax.psum(dev, meshlib.DATA_AXIS)
        bb, _ = solve_normal(A, z, jitter=jnp.float32(0.0), refine_steps=1)
        return bb, dev

    # A. plain chained scan (floor)
    @partial(jax.jit, static_argnames=("k",))
    def chainA(X, y, wt, off, b, k):
        def body(b, _):
            return iter_body(X, y, wt, off, b, spmd=False)
        return lax.scan(body, b, None, length=k)[0]

    # B. ONE shard_map around the whole scan (loop inside shard_map)
    d = meshlib.DATA_AXIS

    @partial(jax.jit, static_argnames=("k",))
    def chainB(X, y, wt, off, b, k):
        def inner(Xs, ys, ws, os_, b):
            def body(b, _):
                return iter_body(Xs, ys, ws, os_, b, spmd=True)
            return lax.scan(body, b, None, length=k)[0]
        return jax.shard_map(
            inner, mesh=mesh, in_specs=(P(d, None), P(d), P(d), P(d), P()),
            out_specs=P(), check_vma=False)(X, y, wt, off, b)

    # C. shard_map PER iteration (the current kernel shape)
    @partial(jax.jit, static_argnames=("k",))
    def chainC(X, y, wt, off, b, k):
        def one(Xs, ys, ws, os_, b):
            A, z, dev = fused_fisher_pass(Xs, ys, ws, os_, b, family=fam,
                                          link=lnk, first=False,
                                          block_rows=1024)
            return (lax.psum(A, d), lax.psum(z, d), lax.psum(dev, d))
        sm = jax.shard_map(
            one, mesh=mesh, in_specs=(P(d, None), P(d), P(d), P(d), P()),
            out_specs=(P(), P(), P()), check_vma=False)

        def body(b, _):
            A, z, dev = sm(X, y, wt, off, b)
            bb, _ = solve_normal(A, z, jitter=jnp.float32(0.0),
                                 refine_steps=1)
            return bb, dev
        return lax.scan(body, b, None, length=k)[0]

    for tag, fn in (("A_plain", chainA), ("B_loop_inside_shardmap", chainB),
                    ("C_shardmap_per_iter", chainC)):
        t2 = timed(fn, X, y, wt, off, b0, 2)
        t6 = timed(fn, X, y, wt, off, b0, 6)
        res[f"{tag}_marginal_ms"] = 1e3 * (t6 - t2) / 4
        res[f"{tag}_k2_ms"] = 1e3 * t2
        dump()
        print(tag, res[f"{tag}_marginal_ms"], flush=True)

    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
