"""Prototype: can the fused pass hide its VPU chain under the MXU?

HOTLOOP_r05.md: the fused pass costs ~13.9 ms of which the Gramian GEMM
is ~7 — the rest is the per-block VPU chain (eta/mu/z/w, XtWz sublane
sum, deviance) executing SEQUENTIALLY with the MXU dot of the same
block (a real data dependency).  Hypothesis: splitting each grid step
into two half-blocks creates INDEPENDENT VPU/MXU work the instruction
scheduler may interleave — half B's VPU math can run while half A's dot
occupies the MXU:

    Xw_a, z_a = vpu(a); acc += dot(Xw_a)   # MXU busy...
    Xw_b, z_b = vpu(b); acc += dot(Xw_b)   # ...while this VPU runs?

Variants (k-marginals, D2H barrier — HOTLOOP_r05.md methodology):
  mono   the production kernel shape (one 1024-row block per grid step)
  split2 same 1024 rows per grid step, two interleaved 512-row halves

Writes proto_overlap_r{ROUND}.json via _capture.  ONE tunnel client.
"""
import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/benchmarks")

from _capture import dump_atomic, out_path  # noqa: E402

OUT = out_path("proto_overlap")
res: dict = {}


def dump():
    dump_atomic(res, OUT)


def main():
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.ops.fused import _step_math

    fam, lnk = resolve("binomial", "logit")
    res["device"] = str(jax.devices()[0])
    n, p = 2_097_152, 512
    res["n"], res["p"] = n, p

    def kernel(x_ref, y_ref, wt_ref, off_ref, beta_ref,
               xtwx_ref, xtwz_ref, dev_ref, *, halves, block_rows):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            xtwx_ref[:] = jnp.zeros_like(xtwx_ref)
            xtwz_ref[:] = jnp.zeros_like(xtwz_ref)
            dev_ref[:] = jnp.zeros_like(dev_ref)

        h = block_rows // halves
        for a in range(halves):
            sl = slice(a * h, (a + 1) * h)
            Xw, z, _, dev = _step_math(
                x_ref[sl, :], y_ref[sl, :], wt_ref[sl, :], off_ref[sl, :],
                beta_ref[:], family=fam, link=lnk, first=False)
            xtwx_ref[:] += jax.lax.dot_general(
                Xw, x_ref[sl, :], (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT)
            xtwz_ref[:] += jnp.sum(Xw * z, axis=0, keepdims=True)
            dev_ref[:] += dev

    @partial(jax.jit, static_argnames=("halves", "block_rows"))
    def fpass(X, y, wt, off, beta, halves=1, block_rows=1024):
        nn, pp = X.shape
        vec = lambda: pl.BlockSpec((block_rows, 1), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)
        XtWX, XtWz, dev = pl.pallas_call(
            partial(kernel, halves=halves, block_rows=block_rows),
            grid=(nn // block_rows,),
            in_specs=[
                pl.BlockSpec((block_rows, pp), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                vec(), vec(), vec(),
                pl.BlockSpec((1, pp), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((pp, pp), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, pp), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((pp, pp), jnp.float32),
                jax.ShapeDtypeStruct((1, pp), jnp.float32),
                jax.ShapeDtypeStruct((1, 1), jnp.float32),
            ],
        )(X, y.reshape(nn, 1), wt.reshape(nn, 1), off.reshape(nn, 1),
          beta.reshape(1, pp))
        return XtWX, XtWz[0], dev[0, 0]

    @jax.jit
    def gen(key):
        kx, kb, ku = jax.random.split(key, 3)
        X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
        bt = jax.random.normal(kb, (p,), jnp.float32) / (2.0 * p ** 0.5)
        y = (jax.random.uniform(ku, (n,))
             < jax.nn.sigmoid(X @ bt)).astype(jnp.float32)
        return X, y
    X, y = gen(jax.random.PRNGKey(7))
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    b0 = jnp.full((p,), 0.01, jnp.float32)
    jax.block_until_ready(y)

    # parity of the variants
    A1, z1, d1 = fpass(X[:8192], y[:8192], wt[:8192], off[:8192], b0,
                       halves=1)
    A2, z2, d2 = fpass(X[:8192], y[:8192], wt[:8192], off[:8192], b0,
                       halves=2)
    res["split_vs_mono_rel"] = float(
        jnp.max(jnp.abs(A1 - A2)) / jnp.max(jnp.abs(A1)))
    dump()
    print("parity:", res["split_vs_mono_rel"], flush=True)

    @partial(jax.jit, static_argnames=("k", "halves", "block_rows"))
    def chain(X, y, wt, off, b, k, halves, block_rows=1024):
        def body(b, _):
            A, z, dev = fpass(X, y, wt, off, b, halves=halves,
                              block_rows=block_rows)
            # cheap data dependency; no solve (isolates the pass)
            return b + 1e-12 * z, dev
        bb, _ = lax.scan(body, b, None, length=k)
        return bb

    def timed(fn, *args, reps=4, **kw):
        float(np.asarray(fn(*args, **kw)).ravel()[0])  # warm + D2H barrier
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            float(np.asarray(fn(*args, **kw)).ravel()[0])
            ts.append(time.perf_counter() - t0)
        return min(ts)

    # NOTE: a 2048-row block OOMs scoped VMEM (21.2M > 16M limit) — halves
    # subdivide WITHIN the 1024-row budget
    for tag, halves, br in (("mono_b1024", 1, 1024),
                            ("split2_b1024", 2, 1024),
                            ("split4_b1024", 4, 1024)):
        t2 = timed(chain, X, y, wt, off, b0, k=2, halves=halves,
                   block_rows=br)
        t6 = timed(chain, X, y, wt, off, b0, k=6, halves=halves,
                   block_rows=br)
        res[f"{tag}_marginal_ms"] = 1e3 * (t6 - t2) / 4
        dump()
        print(tag, res[f"{tag}_marginal_ms"], flush=True)

    res["complete"] = True
    dump()
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
