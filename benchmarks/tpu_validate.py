"""On-TPU validation + engine timing: Pallas fused kernel vs einsum engine.

Run on real TPU hardware (axon tunnel).  Produces JSON on stdout:
  - pallas_vs_ref: max abs diff of (XtWX, XtWz, dev) Pallas vs XLA twin
  - fused_vs_einsum_beta: coefficient parity of full fits at f32
  - timing table per p in {32, 128, 512, 1024} on DEVICE-RESIDENT data,
    three variants per row: "fused" (Pallas kernel), "fused_xla" (the
    kernel's XLA twin) and "einsum" (GSPMD einsum engine) — the data behind
    engine="auto" (models/glm.py).  r02 verdict (kernel crippled at
    Precision.HIGHEST): einsum won at every p.  r03: the kernel runs
    DEFAULT (bf16-multiply) Gramian precision in the large-n regime
    (benchmarks/HOTLOOP_r03.md) — this sweep re-decides the crossover.
    Writes benchmarks/engine_sweep_r05.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import sparkglm_tpu as sg
from sparkglm_tpu.families.families import resolve
from sparkglm_tpu.models import glm as glm_mod
from sparkglm_tpu.ops.fused import fused_fisher_pass, fused_fisher_pass_ref

from _capture import dump_atomic, out_path  # noqa: E402

OUT = {}


def make_logistic(n, p, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, p)).astype(np.float32)
    X[:, 0] = 1.0
    beta = (rng.standard_normal(p) / (2 * np.sqrt(p))).astype(np.float32)
    prob = 1.0 / (1.0 + np.exp(-(X @ beta)))
    y = (rng.random(n) < prob).astype(np.float32)
    return X, y


def main():
    dev = jax.devices()[0]
    OUT["platform"] = dev.platform
    OUT["device"] = str(dev)
    fam, lnk = resolve("binomial", "logit")

    # ---- 1. Pallas kernel vs XLA twin, raw pass parity ----
    n, p = 8192, 128
    X, y = make_logistic(n, p)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    beta = jnp.zeros((p,), jnp.float32)
    for first in (True, False):
        b = beta if first else jnp.full((p,), 0.01, jnp.float32)
        a = fused_fisher_pass(Xj, yj, wt, off, b, family=fam, link=lnk,
                              first=first, block_rows=512)
        r = fused_fisher_pass_ref(Xj, yj, wt, off, b, family=fam, link=lnk,
                                  first=first, block_rows=512)
        diffs = [float(jnp.max(jnp.abs(x - z))) for x, z in zip(a, r)]
        rel = [d / max(1.0, float(jnp.max(jnp.abs(z))))
               for d, z in zip(diffs, r)]
        OUT[f"pallas_vs_ref_first={first}"] = {
            "abs": [round(d, 8) for d in diffs],
            "rel": [round(d, 10) for d in rel]}

    # ---- 2. full-fit coefficient parity: fused vs einsum at f32 ----
    n2, p2 = 262_144, 64
    X2, y2 = make_logistic(n2, p2, seed=11)
    m_fused = glm_mod.fit(X2, y2, family="binomial", engine="fused",
                          criterion="relative", tol=1e-8)
    m_eins = glm_mod.fit(X2, y2, family="binomial", engine="einsum",
                         criterion="relative", tol=1e-8)
    OUT["fused_vs_einsum_beta_maxdiff"] = float(
        np.max(np.abs(m_fused.coefficients - m_eins.coefficients)))
    OUT["fused_iters"] = m_fused.iterations
    OUT["einsum_iters"] = m_eins.iterations

    # ---- 3. engine timing sweep: n chosen so n*p^2 work stays ~5e11 ----
    # Data is generated ON DEVICE and stays resident, and the jitted IRLS
    # kernels are timed directly — over the axon tunnel, fitting host arrays
    # would time the (throttled) H2D transfer instead of the engine, and on
    # real hardware a resident measurement is what the engine="auto"
    # crossover needs anyway.
    timing = {}
    from functools import partial as _partial

    from sparkglm_tpu.models.glm import (_fused_block_rows, _irls_fused_kernel,
                                         _irls_kernel)

    def kernel_variant(label, mesh, block_rows):
        if label == "fused":
            return _partial(_irls_fused_kernel, mesh=mesh,
                            block_rows=block_rows, use_pallas=True)
        if label == "fused_xla":
            return _partial(_irls_fused_kernel, mesh=mesh,
                            block_rows=block_rows, use_pallas=False)
        return _irls_kernel  # "einsum"

    mesh = sg.make_mesh()

    @_partial(jax.jit, static_argnums=(1, 2))
    def gen_dev(key, n, p):
        kx, kb, ku = jax.random.split(key, 3)
        X = jax.random.normal(kx, (n, p), jnp.float32).at[:, 0].set(1.0)
        bt = jax.random.normal(kb, (p,), jnp.float32) / (2.0 * p ** 0.5)
        y = (jax.random.uniform(ku, (n,))
             < jax.nn.sigmoid(X @ bt)).astype(jnp.float32)
        return X, y

    for p3 in (32, 128, 512, 1024):
        n3 = int(min(4_194_304, max(262_144, 5e11 / p3 ** 2)))
        block_rows = _fused_block_rows(p3)
        n3 = (n3 // (block_rows * 8)) * block_rows * 8 or block_rows * 8
        from jax.sharding import NamedSharding, PartitionSpec as P

        from sparkglm_tpu.parallel import mesh as meshlib
        row_s = NamedSharding(mesh, P(meshlib.DATA_AXIS))
        mat_s = NamedSharding(mesh, P(meshlib.DATA_AXIS, None))
        X3, y3 = gen_dev(jax.random.PRNGKey(p3), n3, p3)
        # identical row sharding for every engine variant — the einsum
        # kernel GSPMD-autoshards from the input sharding, the fused kernel
        # shard_maps over the same mesh; on a multi-device host both then
        # use all chips (apples-to-apples)
        X3 = jax.device_put(X3, mat_s)
        y3 = jax.device_put(y3, row_s)
        jax.block_until_ready((X3, y3))
        w3 = jax.device_put(jnp.ones((n3,), jnp.float32), row_s)
        o3 = jax.device_put(jnp.zeros((n3,), jnp.float32), row_s)
        row = {}
        for label in ("fused", "fused_xla", "einsum"):
            kern = kernel_variant(label, mesh, block_rows)
            try:
                def run():
                    out = kern(X3, y3, w3, o3, jnp.float32(1e-8),
                               jnp.int32(8), jnp.float32(0.0), family=fam,
                               link=lnk, criterion="relative", refine_steps=1)
                    float(out["dev"])  # block
                    return out
                t0 = time.perf_counter(); out = run()
                warm = time.perf_counter() - t0
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter(); out = run()
                    ts.append(time.perf_counter() - t0)
                hot = min(ts)
                iters = int(out["iters"])
                row[label] = {"hot_s": round(hot, 4), "warm_s": round(warm, 4),
                              "iters": iters,
                              "s_per_iter": round(hot / max(1, iters), 5)}
            except Exception as e:  # noqa: BLE001
                row[label] = {"error": repr(e)[:200]}
        timing[f"n={n3},p={p3}"] = row
        print(f"  timed p={p3}: {row}", file=sys.stderr)
        del X3, y3, w3, o3
    OUT["timing"] = timing
    print(json.dumps(OUT, indent=1))
    dump_atomic(OUT, out_path("engine_sweep"))


if __name__ == "__main__":
    main()
