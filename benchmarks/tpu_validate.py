"""On-TPU validation + engine timing: Pallas fused kernel vs einsum engine.

Run on real TPU hardware (axon tunnel).  Produces JSON on stdout:
  - pallas_vs_ref: max abs diff of (XtWX, XtWz, dev) Pallas vs XLA twin
  - fused_vs_einsum_beta: coefficient parity of full fits at f32
  - timing table per p in {32, 128, 512, 1024}, three variants per row:
    "fused" (Pallas), "einsum" (default f32 precision) and "einsum_high"
    (matmul_precision="high", ~bf16x3 on the MXU) — the data for setting
    engine="auto"'s crossover and the precision/speed trade.
"""
from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import sparkglm_tpu as sg
from sparkglm_tpu.families.families import resolve
from sparkglm_tpu.models import glm as glm_mod
from sparkglm_tpu.ops.fused import fused_fisher_pass, fused_fisher_pass_ref

OUT = {}


def make_logistic(n, p, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, p)).astype(np.float32)
    X[:, 0] = 1.0
    beta = (rng.standard_normal(p) / (2 * np.sqrt(p))).astype(np.float32)
    prob = 1.0 / (1.0 + np.exp(-(X @ beta)))
    y = (rng.random(n) < prob).astype(np.float32)
    return X, y


def main():
    dev = jax.devices()[0]
    OUT["platform"] = dev.platform
    OUT["device"] = str(dev)
    fam, lnk = resolve("binomial", "logit")

    # ---- 1. Pallas kernel vs XLA twin, raw pass parity ----
    n, p = 8192, 128
    X, y = make_logistic(n, p)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    wt = jnp.ones((n,), jnp.float32)
    off = jnp.zeros((n,), jnp.float32)
    beta = jnp.zeros((p,), jnp.float32)
    for first in (True, False):
        b = beta if first else jnp.full((p,), 0.01, jnp.float32)
        a = fused_fisher_pass(Xj, yj, wt, off, b, family=fam, link=lnk,
                              first=first, block_rows=512)
        r = fused_fisher_pass_ref(Xj, yj, wt, off, b, family=fam, link=lnk,
                                  first=first, block_rows=512)
        diffs = [float(jnp.max(jnp.abs(x - z))) for x, z in zip(a, r)]
        rel = [d / max(1.0, float(jnp.max(jnp.abs(z))))
               for d, z in zip(diffs, r)]
        OUT[f"pallas_vs_ref_first={first}"] = {
            "abs": [round(d, 8) for d in diffs],
            "rel": [round(d, 10) for d in rel]}

    # ---- 2. full-fit coefficient parity: fused vs einsum at f32 ----
    n2, p2 = 262_144, 64
    X2, y2 = make_logistic(n2, p2, seed=11)
    m_fused = glm_mod.fit(X2, y2, family="binomial", engine="fused",
                          criterion="relative", tol=1e-8)
    m_eins = glm_mod.fit(X2, y2, family="binomial", engine="einsum",
                         criterion="relative", tol=1e-8)
    OUT["fused_vs_einsum_beta_maxdiff"] = float(
        np.max(np.abs(m_fused.coefficients - m_eins.coefficients)))
    OUT["fused_iters"] = m_fused.iterations
    OUT["einsum_iters"] = m_eins.iterations

    # ---- 3. engine timing sweep: n chosen so n*p^2 work stays ~5e11 ----
    timing = {}
    from sparkglm_tpu.config import NumericConfig
    variants = [("fused", "fused", {}), ("einsum", "einsum", {}),
                ("einsum_high", "einsum",
                 dict(config=NumericConfig(matmul_precision="high")))]
    for p3 in (32, 128, 512, 1024):
        n3 = int(min(4_194_304, max(262_144, 5e11 / p3 ** 2)))
        n3 = (n3 // 4096) * 4096
        X3, y3 = make_logistic(n3, p3, seed=p3)
        row = {}
        for label, engine, extra in variants:
            try:
                t0 = time.perf_counter()
                m = glm_mod.fit(X3, y3, family="binomial", engine=engine,
                                criterion="relative", tol=1e-8, max_iter=8,
                                **extra)
                warm = time.perf_counter() - t0
                t0 = time.perf_counter()
                m = glm_mod.fit(X3, y3, family="binomial", engine=engine,
                                criterion="relative", tol=1e-8, max_iter=8,
                                **extra)
                hot = time.perf_counter() - t0
                row[label] = {"hot_s": round(hot, 4), "warm_s": round(warm, 4),
                              "iters": m.iterations,
                              "s_per_iter": round(hot / max(1, m.iterations), 5)}
            except Exception as e:  # noqa: BLE001
                row[label] = {"error": repr(e)[:200]}
        timing[f"n={n3},p={p3}"] = row
        print(f"  timed p={p3}: {row}", file=sys.stderr)
    OUT["timing"] = timing
    print(json.dumps(OUT, indent=1))


if __name__ == "__main__":
    main()
