# sparkglm-tpu build glue — the deployment-story analogue of the reference's
# Makefile (sbt assembly + R CMD INSTALL, /root/reference/Makefile:17-25).
# The Python package needs no build step; `native` compiles the C++ IO layer
# (it is also auto-built on first use by sparkglm_tpu/data/io.py).

CXX ?= g++
CXXFLAGS ?= -O3 -std=c++17 -Wall -Wextra
SO := sparkglm_tpu/data/_libsparkglm_io.so

.PHONY: all native test bench robust obs pipeline serve serve_async \
        categorical penalized elastic sketch fleet fleet_lattice hotloop \
        online obsplane chaos elastic_tenancy observatory ingest robustreg \
        clean

all: native

native: $(SO)

$(SO): native/loader.cpp
	$(CXX) $(CXXFLAGS) -shared -fPIC -o $@ $<

test: native
	python -m pytest tests/ -q

bench: native
	python bench.py

# fault-tolerance suite (sparkglm_tpu/robust): injected transients,
# checkpoint/resume, step-halving — deterministic, CPU-only, fast
robust:
	JAX_PLATFORMS=cpu python -m pytest tests/test_robust.py -q

# observability suite (sparkglm_tpu/obs): trace events, metrics registry,
# device-aware spans, traced-vs-untraced bit-identity — CPU-only, fast
obs:
	JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q

# pipelined streaming engine (sparkglm_tpu/data/pipeline.py): prefetch
# producer, fixed-shape buckets, pipelined-vs-sequential bit-identity,
# one-compile-per-flavor — CPU-only, fast
pipeline:
	JAX_PLATFORMS=cpu python -m pytest tests/test_pipeline.py -q

# online serving suite (sparkglm_tpu/serve): registry deploy/rollback,
# served-vs-offline bit-identity across every padding bucket, zero
# steady-state recompiles, micro-batch coalescing + typed backpressure
serve:
	JAX_PLATFORMS=cpu python -m pytest tests/test_serve.py -q

# async replicated serving (sparkglm_tpu/serve/async_engine.py):
# continuous batching, deficit-round-robin fairness, recompile-free
# deploy/rollback under load, f64 bit-identity + the bf16 tier bound
serve_async:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m asyncio

# factor-aware Gramian engine (sparkglm_tpu/ops/factor_gramian.py): the
# structured test suite plus the categorical_gramian bench block (dense
# one-hot vs segment-sum s/iter + coefficient agreement)
categorical: native
	JAX_PLATFORMS=cpu python -m pytest tests/test_structured.py -q
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# penalized GLM subsystem (sparkglm_tpu/penalized): glmnet-golden parity,
# the one-executable lambda-path contract, warm-start determinism,
# select/serialize/serve round-trips, streaming path parity — plus the
# regularization_path bench block (path-vs-refit speedup, <= 2 executables)
penalized:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m penalized
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# elastic shard-parallel fitting (sparkglm_tpu/elastic): preemptible
# workers, one-shot combine, graceful degraded convergence — plus the
# elastic_recovery bench block (kill-one-worker overhead vs undisturbed)
elastic:
	JAX_PLATFORMS=cpu python -m pytest tests/test_elastic.py -q
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# sketched-IRLS engine + sparse designs (sparkglm_tpu/ops/sketch.py,
# data/sparse.py): seeded determinism, golden sketch-vs-exact parity,
# engine-combination guards — plus the sketch_solve bench block (sketched
# vs exact-dense s/iter + coef maxdiff at the ultra-wide sparse shape)
sketch:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m sketch
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# fleet fitting + model-family serving (sparkglm_tpu/fleet, serve): fleet-
# vs-solo bit-identity, the one-executable/warm-refit contracts, grouped
# ingestion, family deploy/rollback + batched (tenant, x) scoring — plus
# the fleet_fit bench block (fleet vs K sequential solo fits s/model)
fleet:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m fleet
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# the capability lattice + PR 20 fleet scale axes (sparkglm_tpu/
# capabilities.py, fleet/path.py, fleet/kernel.py): exhaustive
# fit-or-pointed-error walk of every design x engine x penalty x execution
# cell, penalized-fleet bit-identity vs solo lambda paths, sketch-fleet
# seed parity, mesh-fleet bit-identity + serialization byte-identity —
# plus the fleet_lambda_path and fleet_mesh_scaling bench blocks
fleet_lattice:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m fleet_lattice
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# resident IRLS hot loop (sparkglm_tpu/ops/fused.py v2 + ops/autotune.py):
# fused-v2 vs einsum f64 bit-identity of coefficients AND iteration counts,
# the engine="auto" autotuner selection contract, the bf16-schedule bound —
# plus the hotloop_mfu bench block (engine sweep einsum vs fused-v2 vs
# fused-v2-bf16: marginal MFU on TPU, s/iter + coef parity on the CPU
# fallback, iteration-count equality either way)
hotloop:
	JAX_PLATFORMS=cpu python -m pytest tests/test_fused.py \
		tests/test_fused_v2_parity.py -q
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# online continuous learning (sparkglm_tpu/online): decayed-suffstat
# closed-form refresh vs full-refit parity, drift-gated auto-deploy with
# zero steady-state recompiles, regression auto-rollback, resume
# bit-identity — plus the online_refresh bench block (chunks/s, refresh
# latency, steady-state executable count == 0)
online:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m online
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# runtime observability plane (sparkglm_tpu/obs: trace/context/slo/export):
# request-scoped span chains under seeded 64-tenant load, SLO flight
# recorder (one record per violation/drift episode), ring determinism
# under wraparound + concurrent writers, Prometheus/JSONL export — plus
# the serving_trace_overhead bench block (full plane on vs off through
# the shared paired-run gate; zero kernel-cache growth)
obsplane:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m obsplane
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# self-healing serving plane + crash-durable online learning (serve/health,
# async_engine dispatch protection, online/journal): replica ejection/
# recovery state machine, deadlines + hedged dispatch, kill-one-replica
# bit-identity with zero recompiles, SIGKILL-resume of the online loop from
# the write-ahead journal — plus the serving_fault_recovery bench block
# (600-request load with one replica killed: zero lost requests, overhead
# vs healthy, recompile count)
chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m selfheal
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# elastic tenancy under fire (serve/growth, serve/pool, online/sharding):
# bucket-crossing family growth under live traffic (zero lost requests,
# zero steady-state recompiles, byte-identical old-tenant scoring),
# engine-death resubmit in the multi-engine pool, SIGKILL-resume of the
# sharded online plane (per-shard WALs, combined digest bit-identical to
# the unsharded control), growth-boundary serialization round-trip —
# plus the tenant_growth_chaos bench block
elastic_tenancy:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m tenancy
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# performance & capacity observatory (obs/profile, obs/aggregate,
# obs/history): cost-model MFU/bandwidth gauges, memory + compile
# ledgers, cross-process spool merge with real OS subprocesses,
# longitudinal bench-regression gate over BENCH_r*.json — plus the
# capacity_observatory bench block (paired overhead gate, zero
# steady-state compiles during serving) and the history report
observatory:
	JAX_PLATFORMS=cpu python -m pytest tests/test_observatory.py -q
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py
	python -m sparkglm_tpu.obs.history .

# process-parallel sharded ingest (sparkglm_tpu/data/ingest.py + the
# multi-file _stream_io front-ends): bit-identical coefficients across
# ingest_workers ∈ {0,1,4}, resume fingerprinting on sharded sources,
# column pruning to design-referenced variables, worker-death reread —
# plus the streaming_pipeline + ingest_throughput bench blocks
# (sequential vs thread-prefetch vs process-ingest, delivered bandwidth)
ingest:
	JAX_PLATFORMS=cpu python -m pytest tests/test_ingest.py \
		tests/test_pipeline.py -q
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

# robust & private fitting (sparkglm_tpu/robustreg): quantile/Huber/l1/linf
# pseudo-families through IRLS, the batched tau path, DP Gramians with the
# zCDP accountant, privacy=None bit-identity, fleet/online composition —
# plus the quantile_tau_path + dp_overhead bench blocks.  DISTINCT from
# `robust` above (the fault-tolerance suite).
robustreg:
	JAX_PLATFORMS=cpu python -m pytest tests/test_robustreg.py -q
	SPARKGLM_BENCH_NO_TUNNEL=1 BENCH_FORCE_CPU=1 python bench.py

clean:
	rm -f $(SO)
	find . -name __pycache__ -type d -exec rm -rf {} +
