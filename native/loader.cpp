// sparkglm-tpu native IO: CSV loader with single-scan categorical level
// discovery and shard-aware byte-range splitting.
//
// Role parity: the reference ingests data through Spark DataFrames (JSON/CSV
// readers feeding row partitions; SURVEY.md §2.3 "Spark core/SQL JARs") and
// discovers categorical levels with one distinct.collect Spark action PER
// COLUMN on the driver (/root/reference/src/main/scala/com/Alteryx/sparkGLM/
// modelMatrix.scala:56-58).  Here the loader makes two streaming passes over
// its byte range — one to infer column kinds and count rows, one to fill
// contiguous buffers (numeric columns into double arrays, string columns
// dictionary-encoded into int32 codes + a level table) — so level discovery
// for ALL categorical columns rides the same scan, and peak memory is the
// output buffers only.  A (shard_index, num_shards) byte-range split aligned
// to newlines lets each host of a multi-host pod read just its slice; no
// driver collect anywhere.
//
// C ABI (consumed by sparkglm_tpu/data/io.py via ctypes):
//   sgio_read_csv(path, shard_index, num_shards) -> SgioTable*
//   sgio_error / sgio_n_rows / sgio_n_cols / sgio_col_* accessors
//   sgio_free(table)
//
// Missing values: empty fields, "NA", "NaN", "nan", "null", "NULL" become
// NaN (numeric) or code -1 (categorical) — the front-end's omit_na treats
// both as missing (R's na.omit semantics, R/pkg/R/utils.R:24-27).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Column {
  std::string name;
  bool is_categorical = false;
  std::vector<double> nums;
  std::vector<int32_t> codes;
  std::vector<std::string> levels;
  std::unordered_map<std::string, int32_t> level_ids;

  int32_t intern(const char* b, size_t len) {
    std::string s(b, len);
    auto it = level_ids.find(s);
    if (it != level_ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(levels.size());
    levels.push_back(s);
    level_ids.emplace(std::move(s), id);
    return id;
  }
};

struct Table {
  std::vector<Column> cols;
  int64_t n_rows = 0;
  std::string error;
};

bool is_missing(const char* b, size_t len) {
  if (len == 0) return true;
  if (len == 2 && std::memcmp(b, "NA", 2) == 0) return true;
  if (len == 3 && (std::memcmp(b, "NaN", 3) == 0 || std::memcmp(b, "nan", 3) == 0)) return true;
  if (len == 4 && (std::memcmp(b, "null", 4) == 0 || std::memcmp(b, "NULL", 4) == 0)) return true;
  return false;
}

bool parse_double(const char* b, size_t len, double* out) {
  char buf[64];  // strtod needs NUL termination; CSV fields are tiny
  if (len == 0 || len >= sizeof(buf)) return false;
  // strtod accepts hex floats ("0x1A"); Python float() does not — reject so
  // both loaders type such columns identically (categorical)
  for (size_t i = 0; i + 1 < len; ++i) {
    if (b[i] == '0' && (b[i + 1] == 'x' || b[i + 1] == 'X')) return false;
  }
  std::memcpy(buf, b, len);
  buf[len] = '\0';
  char* end = nullptr;
  errno = 0;
  double v = std::strtod(buf, &end);
  if (end != buf + len || errno == ERANGE) return false;
  *out = v;
  return true;
}

// Trim -> unquote -> collapse RFC-4180 escaped quotes ("" -> ").  The
// Python fallback's _clean_field mirrors these steps exactly; a quoted CSV
// must parse identically whether or not the .so builds.  scratch backs the
// (rare) collapsed copy until the next call.
void clean_field(const char*& b, size_t& len, std::string& scratch) {
  while (len && (*b == ' ' || *b == '\t' || *b == '\r')) { ++b; --len; }
  while (len && (b[len - 1] == ' ' || b[len - 1] == '\t' || b[len - 1] == '\r')) --len;
  if (len >= 2 && b[0] == '"' && b[len - 1] == '"') {
    ++b;
    len -= 2;
    if (std::memchr(b, '"', len)) {
      scratch.clear();
      for (size_t i = 0; i < len; ++i) {
        scratch.push_back(b[i]);
        if (b[i] == '"' && i + 1 < len && b[i + 1] == '"') ++i;
      }
      b = scratch.data();
      len = scratch.size();
    }
  }
}

// Stream [begin, end_pos) of f in chunks, calling on_line(ptr, len) for each
// newline-terminated (or final partial) line.
template <typename F>
void for_each_line(FILE* f, int64_t begin, int64_t end_pos, F&& on_line) {
  std::fseek(f, begin, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(
      std::min<int64_t>(std::max<int64_t>(end_pos - begin, 1), 8 << 20)));
  std::string carry;
  int64_t pos = begin;
  while (pos < end_pos) {
    size_t want = static_cast<size_t>(std::min<int64_t>(
        end_pos - pos, static_cast<int64_t>(buf.size())));
    size_t got = std::fread(buf.data(), 1, want, f);
    if (got == 0) break;
    pos += static_cast<int64_t>(got);
    const char* b = buf.data();
    const char* bend = b + got;
    while (b < bend) {
      const char* nl = static_cast<const char*>(
          std::memchr(b, '\n', static_cast<size_t>(bend - b)));
      if (!nl) {
        carry.append(b, static_cast<size_t>(bend - b));
        break;
      }
      if (!carry.empty()) {
        carry.append(b, static_cast<size_t>(nl - b));
        on_line(carry.data(), carry.size());
        carry.clear();
      } else {
        on_line(b, static_cast<size_t>(nl - b));
      }
      b = nl + 1;
    }
  }
  if (!carry.empty()) on_line(carry.data(), carry.size());
}

// Call on_field(col_idx, ptr, len) for every field of a line, padding short
// rows with empty (missing) trailing fields.  Double-quoted fields may
// contain commas (embedded newlines are not supported — they would defeat
// byte-range sharding).  Returns false for blank lines.
template <typename F>
bool for_each_field(const char* lb, size_t llen, size_t ncol, F&& on_field) {
  if (llen == 0 || (llen == 1 && lb[0] == '\r')) return false;
  const char* b = lb;
  const char* lend = lb + llen;
  size_t col = 0;
  std::string scratch;
  while (col < ncol) {
    const char* q = b;
    bool in_quote = false;
    while (q < lend && (in_quote || *q != ',')) {
      if (*q == '"') in_quote = !in_quote;
      ++q;
    }
    const char* fb = b;
    size_t len = static_cast<size_t>(q - b);
    clean_field(fb, len, scratch);
    on_field(col, fb, len);
    ++col;
    if (q >= lend) break;
    b = q + 1;
  }
  for (; col < ncol; ++col) on_field(col, "", 0);
  return true;
}

}  // namespace

extern "C" {

struct SgioTable;  // opaque

// kinds: optional per-column override, -1 = infer, 0 = numeric,
// 1 = categorical (pass nullptr or n_kinds=0 to infer everything).  Fixing
// kinds from a schema scan keeps multi-host sharded reads consistent when a
// shard's slice would infer differently.  schema_only skips the fill pass —
// the cheap way to learn global kinds before sharded reads.
SgioTable* sgio_read_csv(const char* path, int64_t shard_index,
                         int64_t num_shards, const int32_t* kinds,
                         int64_t n_kinds, int32_t schema_only) {
  auto* t = new Table();
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    t->error = std::string("cannot open ") + path;
    return reinterpret_cast<SgioTable*>(t);
  }
  std::fseek(f, 0, SEEK_END);
  const int64_t fsize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);

  // ---- header (always read from byte 0) -----------------------------------
  std::string header;
  {
    int ch;
    while ((ch = std::fgetc(f)) != EOF && ch != '\n') header.push_back((char)ch);
  }
  const int64_t data_start = std::ftell(f);
  {
    const char* b = header.data();
    const char* hend = b + header.size();
    std::string scratch;
    while (true) {
      const char* q = b;
      bool in_quote = false;
      while (q < hend && (in_quote || *q != ',')) {
        if (*q == '"') in_quote = !in_quote;
        ++q;
      }
      const char* fb = b;
      size_t len = static_cast<size_t>(q - b);
      clean_field(fb, len, scratch);
      Column c;
      c.name.assign(fb, len);
      t->cols.push_back(std::move(c));
      if (q >= hend) break;
      b = q + 1;
    }
  }
  const size_t ncol = t->cols.size();
  if (ncol == 0) {
    t->error = "empty header";
    std::fclose(f);
    return reinterpret_cast<SgioTable*>(t);
  }

  // ---- shard byte range, aligned forward to newline boundaries ------------
  if (num_shards < 1) num_shards = 1;
  if (shard_index < 0 || shard_index >= num_shards) {
    t->error = "shard_index out of range";
    std::fclose(f);
    return reinterpret_cast<SgioTable*>(t);
  }
  const int64_t span = fsize - data_start;
  auto align_forward = [&](int64_t pos) -> int64_t {
    if (pos <= data_start) return data_start;
    if (pos >= fsize) return fsize;
    std::fseek(f, pos - 1, SEEK_SET);  // scan from pos-1 to the next newline
    int ch;
    while ((ch = std::fgetc(f)) != EOF && ch != '\n') {}
    return std::ftell(f);
  };
  const int64_t begin = align_forward(data_start + span * shard_index / num_shards);
  const int64_t end_pos =
      align_forward(data_start + span * (shard_index + 1) / num_shards);

  // ---- pass 1: row count + kind inference ---------------------------------
  std::vector<char> numeric_ok(ncol, 1);
  std::vector<char> fixed(ncol, 0);
  for (size_t i = 0; i < ncol && static_cast<int64_t>(i) < n_kinds; ++i) {
    if (kinds && kinds[i] >= 0) {
      fixed[i] = 1;
      numeric_ok[i] = kinds[i] == 0;
    }
  }
  int64_t n_rows = 0;
  for_each_line(f, begin, end_pos, [&](const char* lb, size_t llen) {
    double v;
    bool any = for_each_field(lb, llen, ncol,
        [&](size_t col, const char* b, size_t len) {
          if (!fixed[col] && numeric_ok[col] && !is_missing(b, len) &&
              !parse_double(b, len, &v)) {
            numeric_ok[col] = 0;
          }
        });
    if (any) ++n_rows;
  });
  for (size_t i = 0; i < ncol; ++i) {
    t->cols[i].is_categorical = !numeric_ok[i];
  }
  if (schema_only) {
    t->n_rows = n_rows;
    std::fclose(f);
    return reinterpret_cast<SgioTable*>(t);
  }

  // ---- pass 2: fill contiguous buffers ------------------------------------
  for (size_t i = 0; i < ncol; ++i) {
    if (numeric_ok[i]) t->cols[i].nums.reserve(static_cast<size_t>(n_rows));
    else t->cols[i].codes.reserve(static_cast<size_t>(n_rows));
  }
  for_each_line(f, begin, end_pos, [&](const char* lb, size_t llen) {
    bool any = for_each_field(lb, llen, ncol,
        [&](size_t col, const char* b, size_t len) {
          Column& c = t->cols[col];
          if (!c.is_categorical) {
            double v;
            if (is_missing(b, len) || !parse_double(b, len, &v)) {
              v = std::numeric_limits<double>::quiet_NaN();
            }
            c.nums.push_back(v);
          } else if (is_missing(b, len)) {
            c.codes.push_back(-1);
          } else {
            c.codes.push_back(c.intern(b, len));
          }
        });
    if (any) ++t->n_rows;
  });
  std::fclose(f);
  return reinterpret_cast<SgioTable*>(t);
}

const char* sgio_error(SgioTable* h) {
  auto* t = reinterpret_cast<Table*>(h);
  return t->error.empty() ? nullptr : t->error.c_str();
}

int64_t sgio_n_rows(SgioTable* h) {
  return reinterpret_cast<Table*>(h)->n_rows;
}

int64_t sgio_n_cols(SgioTable* h) {
  return static_cast<int64_t>(reinterpret_cast<Table*>(h)->cols.size());
}

const char* sgio_col_name(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].name.c_str();
}

// 0 = numeric (double buffer), 1 = categorical (int32 codes + levels)
int32_t sgio_col_kind(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].is_categorical ? 1 : 0;
}

const double* sgio_col_data(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].nums.data();
}

const int32_t* sgio_col_codes(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].codes.data();
}

int64_t sgio_col_n_levels(SgioTable* h, int64_t i) {
  return static_cast<int64_t>(
      reinterpret_cast<Table*>(h)->cols[i].levels.size());
}

const char* sgio_col_level(SgioTable* h, int64_t i, int64_t j) {
  return reinterpret_cast<Table*>(h)->cols[i].levels[j].c_str();
}

void sgio_free(SgioTable* h) { delete reinterpret_cast<Table*>(h); }

}  // extern "C"
