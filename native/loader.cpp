// sparkglm-tpu native IO: CSV loader with single-scan categorical level
// discovery and shard-aware byte-range splitting.
//
// Role parity: the reference ingests data through Spark DataFrames (JSON/CSV
// readers feeding row partitions; SURVEY.md §2.3 "Spark core/SQL JARs") and
// discovers categorical levels with one distinct.collect Spark action PER
// COLUMN on the driver (/root/reference/src/main/scala/com/Alteryx/sparkGLM/
// modelMatrix.scala:56-58).  Here the loader makes two streaming passes over
// its byte range — one to infer column kinds and count rows, one to fill
// contiguous buffers (numeric columns into double arrays, string columns
// dictionary-encoded into int32 codes + a level table) — so level discovery
// for ALL categorical columns rides the same scan, and peak memory is the
// output buffers only.  A (shard_index, num_shards) byte-range split aligned
// to newlines lets each host of a multi-host pod read just its slice; no
// driver collect anywhere.
//
// C ABI (consumed by sparkglm_tpu/data/io.py via ctypes):
//   sgio_read_csv(path, shard_index, num_shards) -> SgioTable*
//   sgio_error / sgio_n_rows / sgio_n_cols / sgio_col_* accessors
//   sgio_free(table)
//
// Missing values: empty fields, "NA", "NaN", "nan", "null", "NULL" become
// NaN (numeric) or code -1 (categorical) — the front-end's omit_na treats
// both as missing (R's na.omit semantics, R/pkg/R/utils.R:24-27).

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Column {
  std::string name;
  bool is_categorical = false;
  std::vector<double> nums;
  std::vector<int32_t> codes;
  std::vector<std::string> levels;
  std::unordered_map<std::string, int32_t> level_ids;

  int32_t intern(const char* b, size_t len) {
    std::string s(b, len);
    auto it = level_ids.find(s);
    if (it != level_ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(levels.size());
    levels.push_back(s);
    level_ids.emplace(std::move(s), id);
    return id;
  }
};

struct Table {
  std::vector<Column> cols;
  int64_t n_rows = 0;
  std::string error;
};

bool is_missing(const char* b, size_t len) {
  if (len == 0) return true;
  if (len == 2 && std::memcmp(b, "NA", 2) == 0) return true;
  if (len == 3 && (std::memcmp(b, "NaN", 3) == 0 || std::memcmp(b, "nan", 3) == 0)) return true;
  if (len == 4 && (std::memcmp(b, "null", 4) == 0 || std::memcmp(b, "NULL", 4) == 0)) return true;
  return false;
}

bool parse_double(const char* b, size_t len, double* out) {
  char buf[64];  // strtod needs NUL termination; fields are usually tiny
  std::string big;  // high-precision serializers emit 60+ char literals
  if (len == 0) return false;
  // strtod accepts hex floats ("0x1A"); Python float() does not — reject so
  // both loaders type such columns identically (categorical)
  for (size_t i = 0; i + 1 < len; ++i) {
    if (b[i] == '0' && (b[i + 1] == 'x' || b[i + 1] == 'X')) return false;
  }
  const char* src;
  if (len < sizeof(buf)) {
    std::memcpy(buf, b, len);
    buf[len] = '\0';
    src = buf;
  } else {
    big.assign(b, len);
    src = big.c_str();
  }
  char* end = nullptr;
  errno = 0;
  double v = std::strtod(src, &end);
  if (end != src + len || errno == ERANGE) return false;
  *out = v;
  return true;
}

// Python float(str) lexical parity for STRINGS coerced into numeric
// columns: leading/trailing whitespace is stripped and single underscores
// BETWEEN digits are removed (PEP 515) before the strict parse — Python's
// float("1_0") is 10.0 and float(" 1.5 ") is 1.5 where bare strtod fails.
// Divergence would break the multi-host identical-design contract between
// a host with the .so and one on the Python fallback (review r4).
bool py_float_parse(const char* b, size_t len, double* out) {
  auto sp = [](char c) {
    return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' ||
           c == '\v';
  };
  while (len > 0 && sp(b[0])) { ++b; --len; }
  while (len > 0 && sp(b[len - 1])) --len;
  if (len == 0) return false;
  bool has_us = false;
  for (size_t i = 0; i < len; ++i) {
    if (b[i] == '_') { has_us = true; break; }
  }
  if (!has_us) return parse_double(b, len, out);
  std::string clean;
  clean.reserve(len);
  for (size_t i = 0; i < len; ++i) {
    if (b[i] == '_') {
      // PEP 515: an underscore is valid only between two digits
      if (i == 0 || i + 1 >= len ||
          !std::isdigit(static_cast<unsigned char>(b[i - 1])) ||
          !std::isdigit(static_cast<unsigned char>(b[i + 1]))) {
        return false;
      }
      continue;
    }
    clean.push_back(b[i]);
  }
  return parse_double(clean.data(), clean.size(), out);
}

// Trim -> unquote -> collapse RFC-4180 escaped quotes ("" -> ").  The
// Python fallback's _clean_field mirrors these steps exactly; a quoted CSV
// must parse identically whether or not the .so builds.  scratch backs the
// (rare) collapsed copy until the next call.
void clean_field(const char*& b, size_t& len, std::string& scratch) {
  while (len && (*b == ' ' || *b == '\t' || *b == '\r')) { ++b; --len; }
  while (len && (b[len - 1] == ' ' || b[len - 1] == '\t' || b[len - 1] == '\r')) --len;
  if (len >= 2 && b[0] == '"' && b[len - 1] == '"') {
    ++b;
    len -= 2;
    if (std::memchr(b, '"', len)) {
      scratch.clear();
      for (size_t i = 0; i < len; ++i) {
        scratch.push_back(b[i]);
        if (b[i] == '"' && i + 1 < len && b[i + 1] == '"') ++i;
      }
      b = scratch.data();
      len = scratch.size();
    }
  }
}

// Stream [begin, end_pos) of f in chunks, calling on_line(ptr, len) for each
// newline-terminated (or final partial) line.
template <typename F>
void for_each_line(FILE* f, int64_t begin, int64_t end_pos, F&& on_line) {
  std::fseek(f, begin, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(
      std::min<int64_t>(std::max<int64_t>(end_pos - begin, 1), 8 << 20)));
  std::string carry;
  int64_t pos = begin;
  while (pos < end_pos) {
    size_t want = static_cast<size_t>(std::min<int64_t>(
        end_pos - pos, static_cast<int64_t>(buf.size())));
    size_t got = std::fread(buf.data(), 1, want, f);
    if (got == 0) break;
    pos += static_cast<int64_t>(got);
    const char* b = buf.data();
    const char* bend = b + got;
    while (b < bend) {
      const char* nl = static_cast<const char*>(
          std::memchr(b, '\n', static_cast<size_t>(bend - b)));
      if (!nl) {
        carry.append(b, static_cast<size_t>(bend - b));
        break;
      }
      if (!carry.empty()) {
        carry.append(b, static_cast<size_t>(nl - b));
        on_line(carry.data(), carry.size());
        carry.clear();
      } else {
        on_line(b, static_cast<size_t>(nl - b));
      }
      b = nl + 1;
    }
  }
  if (!carry.empty()) on_line(carry.data(), carry.size());
}

// Call on_field(col_idx, ptr, len) for every field of a line, padding short
// rows with empty (missing) trailing fields.  Double-quoted fields may
// contain commas (embedded newlines are not supported — they would defeat
// byte-range sharding).  Returns false for blank lines.
template <typename F>
bool for_each_field(const char* lb, size_t llen, size_t ncol, F&& on_field) {
  if (llen == 0 || (llen == 1 && lb[0] == '\r')) return false;
  const char* b = lb;
  const char* lend = lb + llen;
  size_t col = 0;
  std::string scratch;
  while (col < ncol) {
    const char* q = b;
    bool in_quote = false;
    while (q < lend && (in_quote || *q != ',')) {
      if (*q == '"') in_quote = !in_quote;
      ++q;
    }
    const char* fb = b;
    size_t len = static_cast<size_t>(q - b);
    clean_field(fb, len, scratch);
    on_field(col, fb, len);
    ++col;
    if (q >= lend) break;
    b = q + 1;
  }
  for (; col < ncol; ++col) on_field(col, "", 0);
  return true;
}

}  // namespace

// ---------------------------------------------------------------------------
// Flat NDJSON (one JSON object per line) — the reference's own fixture
// format (testData.scala:10-15 loads test data with Spark's JSON reader).
// Spark-JSON semantics shared with the Python twin (data/json.py): columns
// are the UNION of keys, a record missing a key contributes a missing
// value, a key that is ever a string is categorical everywhere, booleans
// read as 0/1 indicators, nested objects/arrays are rejected.
// ---------------------------------------------------------------------------

#include <charconv>
#include <cmath>

namespace {

enum class JKind { Str, Num, Bool, Null, Err };

struct JValue {
  JKind kind = JKind::Null;
  double num = 0.0;
  bool is_int = false;
  std::string str;
  std::string raw;  // the Num token verbatim (exact str(int) interning)
};

// Strict JSON number grammar: '-'? ('0'|[1-9][0-9]*) ('.'[0-9]+)?
// ([eE][+-]?[0-9]+)? — strtod alone would also accept ".5", "+5", "01",
// which python's json.loads rejects; file validity must not depend on
// whether the .so built.
bool valid_json_number(const char* b, size_t len) {
  size_t i = 0;
  auto digit = [&](size_t k) { return k < len && b[k] >= '0' && b[k] <= '9'; };
  if (i < len && b[i] == '-') ++i;
  if (!digit(i)) return false;
  if (b[i] == '0') {
    ++i;
  } else {
    while (digit(i)) ++i;
  }
  if (i < len && b[i] == '.') {
    ++i;
    if (!digit(i)) return false;
    while (digit(i)) ++i;
  }
  if (i < len && (b[i] == 'e' || b[i] == 'E')) {
    ++i;
    if (i < len && (b[i] == '+' || b[i] == '-')) ++i;
    if (!digit(i)) return false;
    while (digit(i)) ++i;
  }
  return i == len;
}

struct JLine {
  const char* p;
  const char* end;
  std::string err;

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  }

  bool fail(const char* msg) {
    if (err.empty()) err = msg;
    return false;
  }

  static void utf8_append(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out.push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }

  bool hex4(uint32_t* out) {
    if (end - p < 4) return fail("bad \\u escape");
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= static_cast<uint32_t>(c - '0');
      else if (c >= 'a' && c <= 'f') v |= static_cast<uint32_t>(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= static_cast<uint32_t>(c - 'A' + 10);
      else return fail("bad \\u escape");
    }
    p += 4;
    *out = v;
    return true;
  }

  bool parse_string(std::string& out) {
    out.clear();
    if (p >= end || *p != '"') return fail("expected string");
    ++p;
    while (p < end && *p != '"') {
      char c = *p++;
      if (c != '\\') {
        out.push_back(c);
        continue;
      }
      if (p >= end) return fail("bad escape");
      char e = *p++;
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'n': out.push_back('\n'); break;
        case 'r': out.push_back('\r'); break;
        case 't': out.push_back('\t'); break;
        case 'u': {
          uint32_t cp;
          if (!hex4(&cp)) return false;
          if (cp >= 0xD800 && cp <= 0xDBFF) {
            // high surrogate MUST pair with a following low surrogate;
            // python json.loads tolerates lone surrogates, but their
            // CESU-8 bytes would crash the ctypes .decode() later — fail
            // loudly here instead of corrupting level strings
            if (end - p < 6 || p[0] != '\\' || p[1] != 'u') {
              return fail("unpaired surrogate escape");
            }
            p += 2;
            uint32_t lo;
            if (!hex4(&lo)) return false;
            if (lo < 0xDC00 || lo > 0xDFFF) {
              return fail("unpaired surrogate escape");
            }
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
            return fail("unpaired surrogate escape");
          }
          utf8_append(out, cp);
          break;
        }
        default: return fail("bad escape");
      }
    }
    if (p >= end) return fail("unterminated string");
    ++p;  // closing quote
    return true;
  }

  bool parse_value(JValue& v) {
    skip_ws();
    if (p >= end) return fail("truncated value");
    char c = *p;
    if (c == '"') {
      v.kind = JKind::Str;
      return parse_string(v.str);
    }
    if (c == '{' || c == '[') {
      return fail("nested JSON value is not a flat model-frame column");
    }
    if (c == 't' && end - p >= 4 && std::memcmp(p, "true", 4) == 0) {
      p += 4;
      v.kind = JKind::Bool;
      v.num = 1.0;
      return true;
    }
    if (c == 'f' && end - p >= 5 && std::memcmp(p, "false", 5) == 0) {
      p += 5;
      v.kind = JKind::Bool;
      v.num = 0.0;
      return true;
    }
    if (c == 'n' && end - p >= 4 && std::memcmp(p, "null", 4) == 0) {
      p += 4;
      v.kind = JKind::Null;
      return true;
    }
    // python json.loads accepts these non-standard literals by default
    if (c == 'N' && end - p >= 3 && std::memcmp(p, "NaN", 3) == 0) {
      p += 3;
      v.kind = JKind::Num;
      v.num = std::numeric_limits<double>::quiet_NaN();
      v.is_int = false;
      return true;
    }
    if (c == 'I' && end - p >= 8 && std::memcmp(p, "Infinity", 8) == 0) {
      p += 8;
      v.kind = JKind::Num;
      v.num = std::numeric_limits<double>::infinity();
      v.is_int = false;
      return true;
    }
    if (c == '-' && end - p >= 9 && std::memcmp(p, "-Infinity", 9) == 0) {
      p += 9;
      v.kind = JKind::Num;
      v.num = -std::numeric_limits<double>::infinity();
      v.is_int = false;
      return true;
    }
    const char* q = p;
    bool integral = true;
    while (q < end && (std::strchr("+-0123456789.eE", *q) != nullptr)) {
      if (*q == '.' || *q == 'e' || *q == 'E') integral = false;
      ++q;
    }
    double d;
    const size_t tlen = static_cast<size_t>(q - p);
    if (tlen > 0 && valid_json_number(p, tlen) && parse_double(p, tlen, &d)) {
      v.kind = JKind::Num;
      v.num = d;
      // python json.loads types a '.'-/'e'-free token as int; str(int)
      // is the token VERBATIM (arbitrary precision — no 2^53 cap), so
      // categorical interning keeps the raw token for integral literals
      v.is_int = integral;
      v.raw.assign(p, tlen);
      // python str(json.loads("-0")) is "0" (int parse), not the raw
      // token — "-0" is the only integral JSON literal whose str differs
      // from its spelling (leading zeros are invalid JSON)
      if (integral && v.raw == "-0") v.raw = "0";
      p = q;
      return true;
    }
    return fail("bad JSON value");
  }
};

// Python str(float) formatting, so a numeric value landing in a
// CATEGORICAL column interns the same level string as the Python twin's
// str(v): shortest round-trip digits (to_chars scientific), then CPython
// repr's fixed/scientific choice — fixed iff -4 <= exp10 < 16, with ".0"
// appended to integral magnitudes; otherwise "d[.ddd]e±XX".
std::string py_float_str(double v) {
  if (std::isnan(v)) return "nan";
  if (std::isinf(v)) return v < 0 ? "-inf" : "inf";
  char buf[40];
  auto [ptr, ec] = std::to_chars(buf, buf + sizeof(buf), v,
                                 std::chars_format::scientific);
  std::string s(buf, ptr);
  size_t epos = s.find('e');
  std::string mant = s.substr(0, epos);
  int exp = std::atoi(s.c_str() + epos + 1);
  bool neg = !mant.empty() && mant[0] == '-';
  if (neg) mant.erase(0, 1);
  std::string digits;
  for (char c : mant) {
    if (c != '.') digits.push_back(c);
  }
  while (digits.size() > 1 && digits.back() == '0') digits.pop_back();
  std::string out;
  if (exp >= -4 && exp < 16) {
    if (exp >= 0) {
      if (static_cast<size_t>(exp) + 1 >= digits.size()) {
        out = digits + std::string(exp + 1 - digits.size(), '0') + ".0";
      } else {
        out = digits.substr(0, exp + 1) + "." + digits.substr(exp + 1);
      }
    } else {
      out = "0." + std::string(-exp - 1, '0') + digits;
    }
  } else {
    out = digits.substr(0, 1);
    if (digits.size() > 1) out += "." + digits.substr(1);
    char eb[8];
    std::snprintf(eb, sizeof(eb), "e%+03d", exp);
    out += eb;
  }
  return neg ? "-" + out : out;
}

// Parse one NDJSON object line into (key, value) callbacks; returns false
// (with err set) on malformed lines.
template <typename F>
bool parse_json_object(const char* lb, size_t llen, std::string* err,
                       F&& on_pair) {
  JLine jl{lb, lb + llen, {}};
  // a record must be ONE object per line — trailing content after '}' is
  // python's JSONDecodeError "Extra data", never silently dropped
  auto finish = [&]() {
    ++jl.p;  // consume '}'
    jl.skip_ws();
    if (jl.p < jl.end) {
      *err = "Extra data after JSON object";
      return false;
    }
    return true;
  };
  jl.skip_ws();
  if (jl.p >= jl.end) return false;  // blank line: skip silently
  if (*jl.p != '{') {
    *err = "NDJSON lines must be objects";
    return false;
  }
  ++jl.p;
  jl.skip_ws();
  if (jl.p < jl.end && *jl.p == '}') return finish();  // empty object: a row
  std::string key;
  JValue val;
  while (true) {
    jl.skip_ws();
    if (!jl.parse_string(key)) { *err = jl.err; return false; }
    jl.skip_ws();
    if (jl.p >= jl.end || *jl.p != ':') { *err = "expected ':'"; return false; }
    ++jl.p;
    if (!jl.parse_value(val)) { *err = jl.err; return false; }
    on_pair(key, val);
    jl.skip_ws();
    if (jl.p < jl.end && *jl.p == ',') { ++jl.p; continue; }
    if (jl.p < jl.end && *jl.p == '}') return finish();
    *err = "expected ',' or '}'";
    return false;
  }
}

}  // namespace

extern "C" {

struct SgioTable;  // opaque

// kinds: optional per-column override, -1 = infer, 0 = numeric,
// 1 = categorical (pass nullptr or n_kinds=0 to infer everything).  Fixing
// kinds from a schema scan keeps multi-host sharded reads consistent when a
// shard's slice would infer differently.  schema_only skips the fill pass —
// the cheap way to learn global kinds before sharded reads.
SgioTable* sgio_read_csv(const char* path, int64_t shard_index,
                         int64_t num_shards, const int32_t* kinds,
                         int64_t n_kinds, int32_t schema_only) {
  auto* t = new Table();
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    t->error = std::string("cannot open ") + path;
    return reinterpret_cast<SgioTable*>(t);
  }
  std::fseek(f, 0, SEEK_END);
  const int64_t fsize = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);

  // ---- header (always read from byte 0) -----------------------------------
  std::string header;
  {
    int ch;
    while ((ch = std::fgetc(f)) != EOF && ch != '\n') header.push_back((char)ch);
  }
  const int64_t data_start = std::ftell(f);
  {
    const char* b = header.data();
    const char* hend = b + header.size();
    std::string scratch;
    while (true) {
      const char* q = b;
      bool in_quote = false;
      while (q < hend && (in_quote || *q != ',')) {
        if (*q == '"') in_quote = !in_quote;
        ++q;
      }
      const char* fb = b;
      size_t len = static_cast<size_t>(q - b);
      clean_field(fb, len, scratch);
      Column c;
      c.name.assign(fb, len);
      t->cols.push_back(std::move(c));
      if (q >= hend) break;
      b = q + 1;
    }
  }
  const size_t ncol = t->cols.size();
  if (ncol == 0) {
    t->error = "empty header";
    std::fclose(f);
    return reinterpret_cast<SgioTable*>(t);
  }

  // ---- shard byte range, aligned forward to newline boundaries ------------
  if (num_shards < 1) num_shards = 1;
  if (shard_index < 0 || shard_index >= num_shards) {
    t->error = "shard_index out of range";
    std::fclose(f);
    return reinterpret_cast<SgioTable*>(t);
  }
  const int64_t span = fsize - data_start;
  auto align_forward = [&](int64_t pos) -> int64_t {
    if (pos <= data_start) return data_start;
    if (pos >= fsize) return fsize;
    std::fseek(f, pos - 1, SEEK_SET);  // scan from pos-1 to the next newline
    int ch;
    while ((ch = std::fgetc(f)) != EOF && ch != '\n') {}
    return std::ftell(f);
  };
  const int64_t begin = align_forward(data_start + span * shard_index / num_shards);
  const int64_t end_pos =
      align_forward(data_start + span * (shard_index + 1) / num_shards);

  // ---- pass 1: row count + kind inference ---------------------------------
  std::vector<char> numeric_ok(ncol, 1);
  std::vector<char> fixed(ncol, 0);
  for (size_t i = 0; i < ncol && static_cast<int64_t>(i) < n_kinds; ++i) {
    if (kinds && kinds[i] >= 0) {
      fixed[i] = 1;
      numeric_ok[i] = kinds[i] == 0;
    }
  }
  int64_t n_rows = 0;
  for_each_line(f, begin, end_pos, [&](const char* lb, size_t llen) {
    double v;
    bool any = for_each_field(lb, llen, ncol,
        [&](size_t col, const char* b, size_t len) {
          if (!fixed[col] && numeric_ok[col] && !is_missing(b, len) &&
              !parse_double(b, len, &v)) {
            numeric_ok[col] = 0;
          }
        });
    if (any) ++n_rows;
  });
  for (size_t i = 0; i < ncol; ++i) {
    t->cols[i].is_categorical = !numeric_ok[i];
  }
  if (schema_only) {
    t->n_rows = n_rows;
    std::fclose(f);
    return reinterpret_cast<SgioTable*>(t);
  }

  // ---- pass 2: fill contiguous buffers ------------------------------------
  for (size_t i = 0; i < ncol; ++i) {
    if (numeric_ok[i]) t->cols[i].nums.reserve(static_cast<size_t>(n_rows));
    else t->cols[i].codes.reserve(static_cast<size_t>(n_rows));
  }
  for_each_line(f, begin, end_pos, [&](const char* lb, size_t llen) {
    bool any = for_each_field(lb, llen, ncol,
        [&](size_t col, const char* b, size_t len) {
          Column& c = t->cols[col];
          if (!c.is_categorical) {
            double v;
            if (is_missing(b, len) || !parse_double(b, len, &v)) {
              v = std::numeric_limits<double>::quiet_NaN();
            }
            c.nums.push_back(v);
          } else if (is_missing(b, len)) {
            c.codes.push_back(-1);
          } else {
            c.codes.push_back(c.intern(b, len));
          }
        });
    if (any) ++t->n_rows;
  });
  std::fclose(f);
  return reinterpret_cast<SgioTable*>(t);
}

const char* sgio_error(SgioTable* h) {
  auto* t = reinterpret_cast<Table*>(h);
  return t->error.empty() ? nullptr : t->error.c_str();
}

int64_t sgio_n_rows(SgioTable* h) {
  return reinterpret_cast<Table*>(h)->n_rows;
}

int64_t sgio_n_cols(SgioTable* h) {
  return static_cast<int64_t>(reinterpret_cast<Table*>(h)->cols.size());
}

const char* sgio_col_name(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].name.c_str();
}

// 0 = numeric (double buffer), 1 = categorical (int32 codes + levels)
int32_t sgio_col_kind(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].is_categorical ? 1 : 0;
}

const double* sgio_col_data(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].nums.data();
}

const int32_t* sgio_col_codes(SgioTable* h, int64_t i) {
  return reinterpret_cast<Table*>(h)->cols[i].codes.data();
}

int64_t sgio_col_n_levels(SgioTable* h, int64_t i) {
  return static_cast<int64_t>(
      reinterpret_cast<Table*>(h)->cols[i].levels.size());
}

const char* sgio_col_level(SgioTable* h, int64_t i, int64_t j) {
  return reinterpret_cast<Table*>(h)->cols[i].levels[j].c_str();
}

// Flat NDJSON reader sharing the Table ABI.  ``kind_names``/``kinds`` fix
// column kinds BY NAME (JSON has no column order; a shard's local key order
// cannot index a global schema positionally): with n_kinds > 0 the output
// columns are exactly the named set in that order — keys outside it are
// ignored, absent keys yield all-missing columns — so every host of a
// sharded read types and aligns identically.  schema_only skips the fill.
SgioTable* sgio_read_json(const char* path, int64_t shard_index,
                          int64_t num_shards,
                          const char* const* kind_names,
                          const int32_t* kinds, int64_t n_kinds,
                          int32_t schema_only) {
  auto* t = new Table();
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    t->error = std::string("cannot open ") + path;
    return reinterpret_cast<SgioTable*>(t);
  }
  std::fseek(f, 0, SEEK_END);
  const int64_t fsize = std::ftell(f);
  if (num_shards < 1) num_shards = 1;
  if (shard_index < 0 || shard_index >= num_shards) {
    t->error = "shard_index out of range";
    std::fclose(f);
    return reinterpret_cast<SgioTable*>(t);
  }
  auto align_forward = [&](int64_t pos) -> int64_t {
    if (pos <= 0) return 0;
    if (pos >= fsize) return fsize;
    std::fseek(f, pos - 1, SEEK_SET);
    int ch;
    while ((ch = std::fgetc(f)) != EOF && ch != '\n') {}
    return std::ftell(f);
  };
  const int64_t begin = align_forward(fsize * shard_index / num_shards);
  const int64_t end_pos = align_forward(fsize * (shard_index + 1) / num_shards);

  std::unordered_map<std::string, size_t> index;
  const bool fixed = n_kinds > 0;
  for (int64_t i = 0; i < n_kinds; ++i) {
    Column c;
    c.name = kind_names[i];
    c.is_categorical = kinds[i] != 0;
    index.emplace(c.name, t->cols.size());
    t->cols.push_back(std::move(c));
  }

  auto col_size = [](const Column& c) -> int64_t {
    return static_cast<int64_t>(c.is_categorical ? c.codes.size()
                                                 : c.nums.size());
  };
  auto push_missing = [](Column& c) {
    if (c.is_categorical) c.codes.push_back(-1);
    else c.nums.push_back(std::numeric_limits<double>::quiet_NaN());
  };

  if (!fixed || schema_only) {
    // discovery pass: union of keys, categorical iff a STRING appears
    // anywhere (data/json.py::scan_json_schema semantics), row count.
    // Duplicate keys within a record: last wins BEFORE kind merging, as
    // python's json.loads dict would present them
    int64_t rows = 0;
    std::vector<std::pair<std::string, JKind>> line_pairs;
    for_each_line(f, begin, end_pos, [&](const char* lb, size_t llen) {
      if (!t->error.empty()) return;
      std::string perr;
      line_pairs.clear();
      bool ok = parse_json_object(lb, llen, &perr,
          [&](const std::string& key, const JValue& v) {
            for (auto& kv : line_pairs) {
              if (kv.first == key) {
                kv.second = v.kind;
                return;
              }
            }
            line_pairs.emplace_back(key, v.kind);
          });
      if (!perr.empty()) {
        t->error = perr;
        return;
      }
      if (!ok) return;
      ++rows;
      for (const auto& kv : line_pairs) {
        auto it = index.find(kv.first);
        size_t idx;
        if (it == index.end()) {
          if (fixed) continue;  // schema_only with fixed kinds: count only
          Column c;
          c.name = kv.first;
          idx = t->cols.size();
          index.emplace(kv.first, idx);
          t->cols.push_back(std::move(c));
        } else {
          idx = it->second;
        }
        if (!fixed && kv.second == JKind::Str) {
          t->cols[idx].is_categorical = true;
        }
      }
    });
    if (!t->error.empty() || schema_only) {
      t->n_rows = rows;
      std::fclose(f);
      return reinterpret_cast<SgioTable*>(t);
    }
  }

  // fill pass (single pass when kinds came fixed from the global scan)
  int64_t row = 0;
  for_each_line(f, begin, end_pos, [&](const char* lb, size_t llen) {
    if (!t->error.empty()) return;
    std::string perr;
    bool ok = parse_json_object(lb, llen, &perr,
        [&](const std::string& key, const JValue& v) {
          auto it = index.find(key);
          if (it == index.end()) return;  // key outside the fixed schema
          Column& c = t->cols[it->second];
          while (col_size(c) < row) push_missing(c);
          if (col_size(c) > row) {  // duplicate key: python dict keeps last
            if (c.is_categorical) c.codes.pop_back();
            else c.nums.pop_back();
          }
          switch (v.kind) {
            case JKind::Null:
              push_missing(c);
              break;
            case JKind::Num:
            case JKind::Bool:
              if (c.is_categorical) {
                // match the Python twin's str(v) of the json-typed value:
                // ints keep their token verbatim (arbitrary precision)
                std::string s =
                    v.kind == JKind::Bool ? (v.num != 0.0 ? "True" : "False")
                    : v.is_int ? v.raw
                               : py_float_str(v.num);
                c.codes.push_back(c.intern(s.data(), s.size()));
              } else {
                c.nums.push_back(v.num);
              }
              break;
            case JKind::Str: {
              if (c.is_categorical) {
                c.codes.push_back(c.intern(v.str.data(), v.str.size()));
              } else {
                double d;
                // python-float lexing: the twin coerces with float(str)
                if (py_float_parse(v.str.data(), v.str.size(), &d)) {
                  c.nums.push_back(d);
                } else {
                  t->error = "could not convert string to float: '" + v.str +
                             "' in numeric column '" + c.name + "'";
                }
              }
              break;
            }
            default:
              break;
          }
        });
    if (!perr.empty()) t->error = perr;
    else if (ok) ++row;
  });
  for (auto& c : t->cols) {
    while (col_size(c) < row) push_missing(c);
  }
  t->n_rows = row;
  std::fclose(f);
  return reinterpret_cast<SgioTable*>(t);
}

void sgio_free(SgioTable* h) { delete reinterpret_cast<Table*>(h); }

}  // extern "C"
