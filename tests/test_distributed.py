"""Multi-host glue (single-process degradation + shard assembly)."""

import numpy as np

import sparkglm_tpu as sg
from sparkglm_tpu.parallel import distributed as dist


def test_initialize_noop_single_process():
    dist.initialize()  # must not raise
    assert dist.process_count() == 1
    assert dist.process_index() == 0


def test_global_mesh_covers_all_devices():
    mesh = dist.global_mesh()
    assert mesh.devices.size == 8  # conftest forces 8 CPU devices


def test_host_shard_to_global_roundtrip(rng):
    mesh = dist.global_mesh()
    X = rng.normal(size=(64, 3))
    Xg = dist.host_shard_to_global(X, mesh)
    np.testing.assert_allclose(np.asarray(Xg), X)
    y = rng.normal(size=64)
    yg = dist.host_shard_to_global(y, mesh)
    np.testing.assert_allclose(np.asarray(yg), y)


def test_pad_host_shard(rng):
    X = rng.normal(size=(10, 2))
    Xp, wp = dist.pad_host_shard(X, 16)
    assert Xp.shape == (16, 2)
    np.testing.assert_allclose(wp, [1.0] * 10 + [0.0] * 6)
    # padded rows are inert in a fit
    y = X @ [0.5, -0.3] + 0.01 * rng.normal(size=10)
    yp = np.concatenate([y, np.zeros(6)])
    mesh = dist.global_mesh()
    m1 = sg.lm_fit(X, y, mesh=mesh)
    m2 = sg.lm_fit(Xp, yp, weights=wp, mesh=mesh)
    np.testing.assert_allclose(m1.coefficients, m2.coefficients, rtol=1e-8)


def test_full_fit_through_global_shard(rng):
    """The documented multi-host flow, single-process edition."""
    mesh = dist.global_mesh()
    n = 4000
    X = rng.normal(size=(n, 4)); X[:, 0] = 1.0
    bt = np.array([0.3, 0.5, -0.2, 0.1])
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    Xg = dist.host_shard_to_global(X, mesh)
    yg = dist.host_shard_to_global(y, mesh)
    m = sg.glm_fit(np.asarray(Xg), np.asarray(yg), family="binomial",
                   mesh=mesh, tol=1e-10)
    assert m.converged
    assert np.abs(m.coefficients - bt).max() < 0.3
