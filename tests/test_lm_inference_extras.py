"""LM logLik/AIC/BIC and predict intervals — R's stats verbs."""

import numpy as np
import pytest
from scipy import stats

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig

F64 = NumericConfig(dtype="float64")


@pytest.fixture
def dat(rng):
    n = 300
    x = rng.standard_normal(n)
    y = 1.0 + 2.0 * x + 0.5 * rng.standard_normal(n)
    return {"y": y, "x": x}


def test_lm_loglik_aic_bic_match_gaussian_glm(dat):
    """logLik.lm/AIC/BIC vs the INDEPENDENT host-f64 gaussian GLM logLik
    (models/hoststats.py): same model, two implementations."""
    ml = sg.lm("y ~ x", dat, config=F64)
    mg = sg.glm("y ~ x", dat, family="gaussian", link="identity", config=F64)
    assert ml.loglik() == pytest.approx(mg.loglik, rel=1e-9)
    assert ml.aic() == pytest.approx(mg.aic, rel=1e-9)
    n, k = ml.n_obs, ml.n_params + 1
    assert ml.bic() == pytest.approx(ml.aic() - 2 * k + np.log(n) * k,
                                     rel=1e-12)
    assert mg.bic() == pytest.approx(ml.bic(), rel=1e-9)


def test_weighted_lm_loglik_needs_weights(dat, rng):
    w = rng.uniform(0.5, 2.0, len(dat["x"]))
    d = dict(dat, w=w)
    ml = sg.lm("y ~ x", d, weights="w", config=F64)
    with pytest.raises(ValueError, match="weights"):
        ml.loglik()
    mg = sg.glm("y ~ x", d, family="gaussian", link="identity",
                weights="w", config=F64)
    assert ml.loglik_weighted(w) == pytest.approx(mg.loglik, rel=1e-9)


def test_glm_bic_quasi_nan(rng):
    x = rng.standard_normal(200)
    y = rng.poisson(np.exp(0.3 + 0.5 * x)).astype(float)
    m = sg.glm("y ~ x", {"y": y, "x": x}, family="quasipoisson", config=F64)
    assert np.isnan(m.bic())


def test_predict_intervals(dat):
    m = sg.lm("y ~ x", dat, config=F64)
    from sparkglm_tpu.data.model_matrix import transform
    Xn = transform({"x": np.array([-1.0, 0.0, 2.0])}, m.terms,
                   dtype=np.float64)
    ci = m.predict(Xn, interval="confidence")
    pi = m.predict(Xn, interval="prediction")
    assert ci.shape == (3, 3) and pi.shape == (3, 3)
    fit, se = m.predict(Xn, se_fit=True)
    t = stats.t.ppf(0.975, m.df_resid)
    np.testing.assert_allclose(ci[:, 0], fit, rtol=1e-12)
    np.testing.assert_allclose(ci[:, 1], fit - t * se, rtol=1e-10)
    np.testing.assert_allclose(pi[:, 2],
                               fit + t * np.sqrt(se**2 + m.sigma**2),
                               rtol=1e-10)
    # prediction bands are strictly wider, both contain the fit
    assert np.all(pi[:, 1] < ci[:, 1]) and np.all(pi[:, 2] > ci[:, 2])
    # se.fit returned alongside an interval is the MEAN's se (R semantics)
    out, se2 = m.predict(Xn, interval="prediction", se_fit=True)
    np.testing.assert_allclose(se2, se, rtol=1e-12)
    with pytest.raises(ValueError, match="interval"):
        m.predict(Xn, interval="bogus")
    # through the formula front-end
    ci2 = sg.predict(m, {"x": np.array([-1.0, 0.0, 2.0])},
                     interval="confidence")
    np.testing.assert_allclose(ci2, ci, rtol=1e-6)


def test_prediction_interval_coverage(rng):
    """~95% of NEW observations fall inside the 95% prediction band."""
    n = 2000
    x = rng.standard_normal(n)
    y = 0.5 + 1.5 * x + 0.7 * rng.standard_normal(n)
    m = sg.lm("y ~ x", {"y": y[:1000], "x": x[:1000]}, config=F64)
    from sparkglm_tpu.data.model_matrix import transform
    Xn = transform({"x": x[1000:]}, m.terms, dtype=np.float64)
    pi = m.predict(Xn, interval="prediction")
    cover = np.mean((y[1000:] >= pi[:, 1]) & (y[1000:] <= pi[:, 2]))
    assert 0.92 < cover < 0.98


def test_weighted_prediction_interval_weights(dat, rng):
    """R's predict.lm: weighted fits warn when prediction variance is
    assumed constant; pred_weights gives per-row variance sigma^2/w."""
    w = rng.uniform(0.5, 2.0, len(dat["x"]))
    d = dict(dat, w=w)
    m = sg.lm("y ~ x", d, weights="w", config=F64)
    from sparkglm_tpu.data.model_matrix import transform
    Xn = transform({"x": np.array([0.0, 1.0])}, m.terms, dtype=np.float64)
    with pytest.warns(UserWarning, match="constant prediction|constant variance"):
        m.predict(Xn, interval="prediction")
    pw = np.array([4.0, 0.25])
    pi = m.predict(Xn, interval="prediction", pred_weights=pw)
    fit, se = m.predict(Xn, se_fit=True)
    t = stats.t.ppf(0.975, m.df_resid)
    np.testing.assert_allclose(
        pi[:, 2], fit + t * np.sqrt(se**2 + m.sigma**2 / pw), rtol=1e-10)
    # zero-weight rows drop out of logLik like R
    w0 = w.copy(); w0[:10] = 0.0
    m0 = sg.lm("y ~ x", dict(dat, w=w0), weights="w", config=F64)
    ll = m0.loglik(weights=w0)
    assert np.isfinite(ll)
    assert np.isfinite(m0.aic(weights=w0)) and np.isfinite(m0.bic(weights=w0))


def test_lm_offset_r_semantics(rng):
    """R's lm(offset=): coefficients solve the y-offset regression; fitted
    values include the offset; R^2/F use summary.lm's fitted-based mss."""
    from oracle import ols_np

    n = 400
    x = rng.standard_normal(n)
    off = rng.uniform(-1, 1, n)
    y = 2.0 + 1.5 * x + off + 0.3 * rng.standard_normal(n)
    d = {"y": y, "x": x, "off": off}
    m = sg.lm("y ~ x + offset(off)", d, config=F64)
    b64 = ols_np(np.column_stack([np.ones(n), x]), y - off)
    np.testing.assert_allclose(m.coefficients, b64, rtol=1e-9)
    assert m.has_offset and m.offset_col == "off"

    # fitted values include the offset; residuals match
    fit = sg.predict(m, d)
    np.testing.assert_allclose(
        fit, np.column_stack([np.ones(n), x]) @ b64 + off,
        rtol=1e-6, atol=1e-6)  # scoring design materialises at f32
    # R^2 = mss/(mss+rss) with f including the offset
    r = y - fit
    rss = float(np.sum(r * r))
    mss = float(np.sum((fit - fit.mean()) ** 2))
    assert m.r_squared == pytest.approx(mss / (mss + rss), rel=1e-5)
    assert m.f_statistic == pytest.approx(
        (mss / m.df_model) / (rss / m.df_resid), rel=1e-5)

    # update() carries the offset() term; drop1 runs
    m2 = sg.update(m, "~ . ", d)
    # update refits at the DEFAULT config (f32 design) — config is a fit
    # argument, not model state
    np.testing.assert_allclose(m2.coefficients, m.coefficients, rtol=1e-6)
    from sparkglm_tpu.models.anova import drop1
    t = drop1(m, d)
    assert t.row_names == ("<none>", "x")

    # an offset= ARRAY cannot be recovered at scoring: predict refuses
    ma = sg.lm("y ~ x", d, offset=off, config=F64)
    with pytest.raises(ValueError, match="offset"):
        sg.predict(ma, d)
    np.testing.assert_allclose(ma.coefficients, m.coefficients, rtol=1e-9)


def test_lm_offset_weighted_no_intercept(rng):
    n = 300
    x = rng.uniform(0.5, 2.0, n)
    off = 0.3 * rng.standard_normal(n)
    w = rng.uniform(0.5, 2.0, n)
    y = 2.0 * x + off + 0.2 * rng.standard_normal(n) / np.sqrt(w)
    d = {"y": y, "x": x, "off": off, "w": w}
    m = sg.lm("y ~ x + offset(off) - 1", d, weights="w", config=F64)
    # weighted closed form on the adjusted response
    b = float(np.sum(w * x * (y - off)) / np.sum(w * x * x))
    assert m.coefficients[0] == pytest.approx(b, rel=1e-9)
    # no-intercept R^2: mss = sum(w f^2) (uncentered), f incl. offset
    f = b * x + off
    rss = float(np.sum(w * (y - f) ** 2))
    mss = float(np.sum(w * f * f))
    assert m.r_squared == pytest.approx(mss / (mss + rss), rel=1e-6)


def test_summary_residual_quantiles():
    """R's summary.lm 'Residuals:' five-number block (the lm.D9 example's
    printed values: -1.0710 -0.4938 0.0685 0.2462 1.3690), rendered when
    the residuals are passed back in."""
    ctl = [4.17, 5.58, 5.18, 6.11, 4.50, 4.61, 5.17, 4.53, 5.33, 5.14]
    trt = [4.81, 4.17, 4.41, 3.59, 5.87, 3.83, 6.03, 4.89, 4.32, 4.69]
    d = {"weight": np.array(ctl + trt), "group": ["Ctl"] * 10 + ["Trt"] * 10}
    m = sg.lm("weight ~ group", d, config=F64)
    from sparkglm_tpu.data.model_matrix import transform
    X = transform(d, m.terms, dtype=np.float64)
    s = m.summary(residuals=m.residuals(X, d["weight"]))
    q = s.residual_quantiles()
    np.testing.assert_allclose(
        [q["Min"], q["1Q"], q["Median"], q["3Q"], q["Max"]],
        [-1.0710, -0.49375, 0.0685, 0.24625, 1.3690], atol=1e-4)
    text = str(s)
    assert "Residuals:" in text and "-1.0710" in text and "1.3690" in text
    # without residuals the block is absent (models retain no data)
    assert "Residuals:" not in str(m.summary())
