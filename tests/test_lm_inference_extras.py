"""LM logLik/AIC/BIC and predict intervals — R's stats verbs."""

import numpy as np
import pytest
from scipy import stats

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig

F64 = NumericConfig(dtype="float64")


@pytest.fixture
def dat(rng):
    n = 300
    x = rng.standard_normal(n)
    y = 1.0 + 2.0 * x + 0.5 * rng.standard_normal(n)
    return {"y": y, "x": x}


def test_lm_loglik_aic_bic_match_gaussian_glm(dat):
    """logLik.lm/AIC/BIC vs the INDEPENDENT host-f64 gaussian GLM logLik
    (models/hoststats.py): same model, two implementations."""
    ml = sg.lm("y ~ x", dat, config=F64)
    mg = sg.glm("y ~ x", dat, family="gaussian", link="identity", config=F64)
    assert ml.loglik() == pytest.approx(mg.loglik, rel=1e-9)
    assert ml.aic() == pytest.approx(mg.aic, rel=1e-9)
    n, k = ml.n_obs, ml.n_params + 1
    assert ml.bic() == pytest.approx(ml.aic() - 2 * k + np.log(n) * k,
                                     rel=1e-12)
    assert mg.bic() == pytest.approx(ml.bic(), rel=1e-9)


def test_weighted_lm_loglik_needs_weights(dat, rng):
    w = rng.uniform(0.5, 2.0, len(dat["x"]))
    d = dict(dat, w=w)
    ml = sg.lm("y ~ x", d, weights="w", config=F64)
    with pytest.raises(ValueError, match="weights"):
        ml.loglik()
    mg = sg.glm("y ~ x", d, family="gaussian", link="identity",
                weights="w", config=F64)
    assert ml.loglik_weighted(w) == pytest.approx(mg.loglik, rel=1e-9)


def test_glm_bic_quasi_nan(rng):
    x = rng.standard_normal(200)
    y = rng.poisson(np.exp(0.3 + 0.5 * x)).astype(float)
    m = sg.glm("y ~ x", {"y": y, "x": x}, family="quasipoisson", config=F64)
    assert np.isnan(m.bic())


def test_predict_intervals(dat):
    m = sg.lm("y ~ x", dat, config=F64)
    from sparkglm_tpu.data.model_matrix import transform
    Xn = transform({"x": np.array([-1.0, 0.0, 2.0])}, m.terms,
                   dtype=np.float64)
    ci = m.predict(Xn, interval="confidence")
    pi = m.predict(Xn, interval="prediction")
    assert ci.shape == (3, 3) and pi.shape == (3, 3)
    fit, se = m.predict(Xn, se_fit=True)
    t = stats.t.ppf(0.975, m.df_resid)
    np.testing.assert_allclose(ci[:, 0], fit, rtol=1e-12)
    np.testing.assert_allclose(ci[:, 1], fit - t * se, rtol=1e-10)
    np.testing.assert_allclose(pi[:, 2],
                               fit + t * np.sqrt(se**2 + m.sigma**2),
                               rtol=1e-10)
    # prediction bands are strictly wider, both contain the fit
    assert np.all(pi[:, 1] < ci[:, 1]) and np.all(pi[:, 2] > ci[:, 2])
    # se.fit returned alongside an interval is the MEAN's se (R semantics)
    out, se2 = m.predict(Xn, interval="prediction", se_fit=True)
    np.testing.assert_allclose(se2, se, rtol=1e-12)
    with pytest.raises(ValueError, match="interval"):
        m.predict(Xn, interval="bogus")
    # through the formula front-end
    ci2 = sg.predict(m, {"x": np.array([-1.0, 0.0, 2.0])},
                     interval="confidence")
    np.testing.assert_allclose(ci2, ci, rtol=1e-6)


def test_prediction_interval_coverage(rng):
    """~95% of NEW observations fall inside the 95% prediction band."""
    n = 2000
    x = rng.standard_normal(n)
    y = 0.5 + 1.5 * x + 0.7 * rng.standard_normal(n)
    m = sg.lm("y ~ x", {"y": y[:1000], "x": x[:1000]}, config=F64)
    from sparkglm_tpu.data.model_matrix import transform
    Xn = transform({"x": x[1000:]}, m.terms, dtype=np.float64)
    pi = m.predict(Xn, interval="prediction")
    cover = np.mean((y[1000:] >= pi[:, 1]) & (y[1000:] <= pi[:, 2]))
    assert 0.92 < cover < 0.98


def test_weighted_prediction_interval_weights(dat, rng):
    """R's predict.lm: weighted fits warn when prediction variance is
    assumed constant; pred_weights gives per-row variance sigma^2/w."""
    w = rng.uniform(0.5, 2.0, len(dat["x"]))
    d = dict(dat, w=w)
    m = sg.lm("y ~ x", d, weights="w", config=F64)
    from sparkglm_tpu.data.model_matrix import transform
    Xn = transform({"x": np.array([0.0, 1.0])}, m.terms, dtype=np.float64)
    with pytest.warns(UserWarning, match="constant prediction|constant variance"):
        m.predict(Xn, interval="prediction")
    pw = np.array([4.0, 0.25])
    pi = m.predict(Xn, interval="prediction", pred_weights=pw)
    fit, se = m.predict(Xn, se_fit=True)
    t = stats.t.ppf(0.975, m.df_resid)
    np.testing.assert_allclose(
        pi[:, 2], fit + t * np.sqrt(se**2 + m.sigma**2 / pw), rtol=1e-10)
    # zero-weight rows drop out of logLik like R
    w0 = w.copy(); w0[:10] = 0.0
    m0 = sg.lm("y ~ x", dict(dat, w=w0), weights="w", config=F64)
    ll = m0.loglik(weights=w0)
    assert np.isfinite(ll)
    assert np.isfinite(m0.aic(weights=w0)) and np.isfinite(m0.bic(weights=w0))
