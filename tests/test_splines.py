"""bs(col, df) / ns(col, df) — R's splines::bs/ns regression bases."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig
from sparkglm_tpu.data.model_matrix import _spline_eval, _spline_fit_knots

F64 = NumericConfig(dtype="float64")


def test_bs_basis_shape_and_partition(rng):
    x = rng.uniform(0, 10, 300)
    c = _spline_fit_knots(x, 6, "bs")
    assert len(c["interior"]) == 3 and c["df"] == 6
    B = _spline_eval(x, "bs", c)
    assert B.shape == (300, 6)
    # B-splines partition unity: the full basis (incl. the dropped first
    # column) sums to 1, so the kept columns sum to 1 - B0 in [0, 1]
    s = B.sum(axis=1)
    assert np.all((s > -1e-9) & (s < 1 + 1e-9))
    # inside the range the basis is local: values in [0, 1]
    assert B.min() > -1e-9 and B.max() <= 1 + 1e-9


def test_ns_second_derivative_zero_at_boundaries(rng):
    """The natural constraint: every ns basis column has zero second
    derivative at the boundary knots (checked numerically)."""
    x = rng.uniform(-2, 3, 400)
    c = _spline_fit_knots(x, 4, "ns")
    lo, hi = c["boundary"]
    h = 1e-5 * (hi - lo)

    def d2(z):
        pts = np.array([z - h, z, z + h])
        B = _spline_eval(pts, "ns", c)
        return (B[0] - 2 * B[1] + B[2]) / h ** 2
    np.testing.assert_allclose(d2(lo + 2 * h), 0.0, atol=1e-2)
    np.testing.assert_allclose(d2(hi - 2 * h), 0.0, atol=1e-2)


def test_spline_fit_matches_raw_cubic_span(rng):
    """With NO interior knots, bs(x, 3) spans the cubic polynomials:
    identical fit to y ~ x + I(x^2) + I(x^3)."""
    n = 400
    x = rng.uniform(0.5, 4, n)
    y = 1 + x - 0.4 * x ** 2 + 0.05 * x ** 3 + 0.1 * rng.standard_normal(n)
    d = {"y": y, "x": x}
    mb = sg.lm("y ~ bs(x, 3)", d, config=F64)
    mr = sg.lm("y ~ x + I(x^2) + I(x^3)", d, config=F64)
    assert mb.xnames == ("intercept", "bs(x, 3)1", "bs(x, 3)2", "bs(x, 3)3")
    assert mb.sse == pytest.approx(mr.sse, rel=1e-9)


def test_ns_glm_fit_and_scoring_stability(rng):
    n = 600
    x = rng.uniform(0, 6, n)
    mu = np.exp(0.5 + np.sin(x))
    y = rng.poisson(mu).astype(float)
    m = sg.glm("y ~ ns(x, 5)", {"y": y, "x": x}, family="poisson",
               config=F64)
    assert m.converged and m.n_params == 6
    # the fitted spline tracks the truth inside the range
    xs = np.linspace(0.5, 5.5, 50)
    eta = sg.predict(m, {"x": xs}, type="link")
    assert np.corrcoef(eta, 0.5 + np.sin(xs))[0, 1] > 0.98
    # scoring uses the TRAINING knots: save/load scores identically
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        m.save(f.name)
        m2 = sg.load_model(f.name)
    np.testing.assert_allclose(sg.predict(m2, {"x": xs}, type="link"),
                               eta, rtol=1e-12)


def test_spline_outside_boundary_warns(rng):
    x = rng.uniform(0, 1, 200)
    y = x + 0.05 * rng.standard_normal(200)
    m = sg.lm("y ~ ns(x, 3)", {"y": y, "x": x}, config=F64)
    with pytest.warns(UserWarning, match="boundary knots"):
        sg.predict(m, {"x": np.array([-0.5, 0.5, 1.5])})


def test_spline_in_drop1_and_terms(rng):
    n = 300
    x = rng.uniform(0, 5, n)
    z = rng.standard_normal(n)
    y = np.sin(x) + 0.3 * z + 0.1 * rng.standard_normal(n)
    d = {"y": y, "x": x, "z": z}
    m = sg.lm("y ~ ns(x, 4) + z", d, config=F64)
    from sparkglm_tpu.models.anova import drop1
    t = drop1(m, d)
    assert t.row_names == ("<none>", "ns(x, 4)", "z")
    tp = sg.predict(m, d, type="terms")
    assert tp.columns == ("ns(x, 4)", "z")
    np.testing.assert_allclose(tp.matrix.sum(axis=1) + tp.constant,
                               sg.predict(m, d), rtol=1e-5, atol=1e-7)


def test_spline_validation(rng):
    x = rng.uniform(0, 1, 50)
    with pytest.raises(ValueError, match="degrees of freedom"):
        sg.lm("y ~ bs(x)", {"y": x, "x": x})
    with pytest.raises(ValueError, match="3 <= df"):
        sg.lm("y ~ bs(x, 2)", {"y": x, "x": x})
    with pytest.raises(ValueError, match="non-constant"):
        sg.lm("y ~ ns(x, 3)", {"y": x, "x": np.ones(50)})


def test_spline_rejected_from_csv(tmp_path, rng):
    p = tmp_path / "d.csv"
    with open(p, "w") as fh:
        fh.write("y,x\n")
        for i in range(50):
            fh.write(f"{rng.random()},{rng.random()}\n")
    with pytest.raises(ValueError, match="basis"):
        sg.lm_from_csv("y ~ ns(x, 3)", str(p))
