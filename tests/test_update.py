"""update(model, formula, data) — R's refit verb with '.' expansion."""

import numpy as np
import pytest

import sparkglm_tpu as sg


@pytest.fixture()
def d(rng):
    n = 800
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    lam = np.exp(0.3 + 0.5 * x + 0.4 * (grp == "b"))
    return {"x": x, "z": z, "grp": grp,
            "y": rng.poisson(lam).astype(float),
            "y2": rng.poisson(lam).astype(float)}


def test_update_add_remove(d):
    m = sg.glm("y ~ x + grp", d, family="poisson")
    m_add = sg.update(m, "~ . + z", d)
    assert m_add.formula == "y ~ x + grp + z"
    direct = sg.glm("y ~ x + grp + z", d, family="poisson")
    np.testing.assert_array_equal(m_add.coefficients, direct.coefficients)
    m_rm = sg.update(m_add, "~ . - z", d)
    np.testing.assert_array_equal(m_rm.coefficients, m.coefficients)
    # identical refit
    m_same = sg.update(m, "~ .", d)
    np.testing.assert_array_equal(m_same.coefficients, m.coefficients)
    assert m_same.family == "poisson"  # family carried


def test_update_response_intercept_offset(d, rng):
    m = sg.glm("y ~ x", d, family="poisson")
    m2 = sg.update(m, "y2 ~ .", d)
    assert m2.formula == "y2 ~ x" and m2.yname == "y2"
    m3 = sg.update(m, "~ . - 1", d)
    assert not m3.has_intercept and m3.xnames == ("x",)
    # offset() terms carry through '.' and can be added
    d["lt"] = rng.uniform(0.1, 0.5, size=len(d["x"]))
    mo = sg.glm("y ~ x + offset(lt)", d, family="poisson")
    mo2 = sg.update(mo, "~ . + z", d)
    assert mo2.formula == "y ~ x + z + offset(lt)"


def test_update_interaction_and_lm(d):
    m = sg.lm("y ~ x + z", d)
    m2 = sg.update(m, "~ . + x:z", d)
    assert m2.xnames == ("intercept", "x", "z", "x:z")
    assert type(m2) is type(m)
    with pytest.raises(ValueError, match="remove the individual"):
        sg.update(m, "~ . - x*z", d)


def test_update_nb_reestimates_theta(rng):
    n = 3000
    x = rng.normal(size=n) * 0.4
    mu = np.exp(0.6 + 0.5 * x)
    d = {"x": x, "z": rng.normal(size=n),
         "y": rng.poisson(rng.gamma(2.0, mu / 2.0)).astype(float)}
    m = sg.glm_nb("y ~ x", d)
    m2 = sg.update(m, "~ . + z", d)
    assert m2.family.startswith("negative_binomial(")
    # theta was re-estimated for the new model, not frozen at the old value
    direct = sg.glm_nb("y ~ x + z", d)
    np.testing.assert_allclose(sg.theta_of(m2), sg.theta_of(direct),
                               rtol=1e-6)


def test_update_carries_fit_time_offset(d, rng):
    """An offset= COLUMN from the original fit rides along as an offset()
    term; an array offset is refused like predict()."""
    n = len(d["x"])
    d["lt"] = rng.uniform(0.1, 0.5, size=n)
    m = sg.glm("y ~ x", d, family="poisson", offset="lt")
    m2 = sg.update(m, "~ . + z", d)
    assert "offset(lt)" in m2.formula
    direct = sg.glm("y ~ x + z", d, family="poisson", offset="lt")
    np.testing.assert_allclose(m2.coefficients, direct.coefficients,
                               rtol=1e-10)
    m_arr = sg.glm("y ~ x", d, family="poisson", offset=d["lt"])
    with pytest.raises(ValueError, match="array offset"):
        sg.update(m_arr, "~ . + z", d)


def test_update_quasi_and_custom_family(d):
    mq = sg.glm("y ~ x", d, family=sg.quasi("mu"), link="log")
    m2 = sg.update(mq, "~ . + z", d)  # quasi(...) names round-trip
    assert m2.family == "quasi(mu)"
    # a family name the registry cannot re-parse fails early with a clear
    # message instead of deep inside the refit
    import dataclasses
    mc = dataclasses.replace(mq, family="mystery")
    with pytest.raises(ValueError, match="reconstruct family"):
        sg.update(mc, "~ . + z", d)


def test_update_validation(d):
    m = sg.glm("y ~ x", d, family="poisson")
    with pytest.raises(ValueError, match="training data"):
        sg.update(m, "~ . + z")
    with pytest.raises(ValueError, match="unsupported update syntax"):
        sg.update(m, "~ . + (x + z)", d)
    # transforms are legal in updates since they are legal in formulas
    m_t = sg.update(m, "~ . + I(x^2)", d)
    assert "I(x^2)" in m_t.xnames
    mm = sg.glm_fit(np.c_[np.ones(10), np.arange(10.)],
                    np.arange(10.) % 2, family="binomial")
    with pytest.raises(ValueError, match="formula-fitted"):
        sg.update(mm, "~ .", d)
