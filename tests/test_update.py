"""update(model, formula, data) — R's refit verb with '.' expansion."""

import numpy as np
import pytest

import sparkglm_tpu as sg


@pytest.fixture()
def d(rng):
    n = 800
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    lam = np.exp(0.3 + 0.5 * x + 0.4 * (grp == "b"))
    return {"x": x, "z": z, "grp": grp,
            "y": rng.poisson(lam).astype(float),
            "y2": rng.poisson(lam).astype(float)}


def test_update_add_remove(d):
    m = sg.glm("y ~ x + grp", d, family="poisson")
    m_add = sg.update(m, "~ . + z", d)
    assert m_add.formula == "y ~ x + grp + z"
    direct = sg.glm("y ~ x + grp + z", d, family="poisson")
    np.testing.assert_array_equal(m_add.coefficients, direct.coefficients)
    m_rm = sg.update(m_add, "~ . - z", d)
    np.testing.assert_array_equal(m_rm.coefficients, m.coefficients)
    # identical refit
    m_same = sg.update(m, "~ .", d)
    np.testing.assert_array_equal(m_same.coefficients, m.coefficients)
    assert m_same.family == "poisson"  # family carried


def test_update_response_intercept_offset(d, rng):
    m = sg.glm("y ~ x", d, family="poisson")
    m2 = sg.update(m, "y2 ~ .", d)
    assert m2.formula == "y2 ~ x" and m2.yname == "y2"
    m3 = sg.update(m, "~ . - 1", d)
    assert not m3.has_intercept and m3.xnames == ("x",)
    # offset() terms carry through '.' and can be added
    d["lt"] = rng.uniform(0.1, 0.5, size=len(d["x"]))
    mo = sg.glm("y ~ x + offset(lt)", d, family="poisson")
    mo2 = sg.update(mo, "~ . + z", d)
    assert mo2.formula == "y ~ x + z + offset(lt)"


def test_update_interaction_and_lm(d):
    m = sg.lm("y ~ x + z", d)
    m2 = sg.update(m, "~ . + x:z", d)
    assert m2.xnames == ("intercept", "x", "z", "x:z")
    assert type(m2) is type(m)
    with pytest.raises(ValueError, match="remove the individual"):
        sg.update(m, "~ . - x*z", d)


def test_update_nb_reestimates_theta(rng):
    n = 3000
    x = rng.normal(size=n) * 0.4
    mu = np.exp(0.6 + 0.5 * x)
    d = {"x": x, "z": rng.normal(size=n),
         "y": rng.poisson(rng.gamma(2.0, mu / 2.0)).astype(float)}
    m = sg.glm_nb("y ~ x", d)
    m2 = sg.update(m, "~ . + z", d)
    assert m2.family.startswith("negative_binomial(")
    # theta was re-estimated for the new model, not frozen at the old value
    direct = sg.glm_nb("y ~ x + z", d)
    np.testing.assert_allclose(sg.theta_of(m2), sg.theta_of(direct),
                               rtol=1e-6)


def test_update_carries_fit_time_offset(d, rng):
    """An offset= COLUMN from the original fit rides along as an offset()
    term; an array offset is refused like predict()."""
    n = len(d["x"])
    d["lt"] = rng.uniform(0.1, 0.5, size=n)
    m = sg.glm("y ~ x", d, family="poisson", offset="lt")
    m2 = sg.update(m, "~ . + z", d)
    assert "offset(lt)" in m2.formula
    direct = sg.glm("y ~ x + z", d, family="poisson", offset="lt")
    np.testing.assert_allclose(m2.coefficients, direct.coefficients,
                               rtol=1e-10)
    m_arr = sg.glm("y ~ x", d, family="poisson", offset=d["lt"])
    with pytest.raises(ValueError, match="array offset"):
        sg.update(m_arr, "~ . + z", d)


def test_update_quasi_and_custom_family(d):
    mq = sg.glm("y ~ x", d, family=sg.quasi("mu"), link="log")
    m2 = sg.update(mq, "~ . + z", d)  # quasi(...) names round-trip
    assert m2.family == "quasi(mu)"
    # a family name the registry cannot re-parse fails early with a clear
    # message instead of deep inside the refit
    import dataclasses
    mc = dataclasses.replace(mq, family="mystery")
    with pytest.raises(ValueError, match="reconstruct family"):
        sg.update(mc, "~ . + z", d)


def test_update_validation(d):
    m = sg.glm("y ~ x", d, family="poisson")
    with pytest.raises(ValueError, match="training data"):
        sg.update(m, "~ . + z")
    with pytest.raises(ValueError, match="unsupported update syntax"):
        sg.update(m, "~ . + (x + z)", d)
    # transforms are legal in updates since they are legal in formulas
    m_t = sg.update(m, "~ . + I(x^2)", d)
    assert "I(x^2)" in m_t.xnames
    mm = sg.glm_fit(np.c_[np.ones(10), np.arange(10.)],
                    np.arange(10.) % 2, family="binomial")
    with pytest.raises(ValueError, match="formula-fitted"):
        sg.update(mm, "~ .", d)


def test_update_carries_named_weights_and_m(d, rng):
    """ADVICE r2: R's update() re-evaluates the original call including
    weights= — a by-NAME weights column travels with the model."""
    d = dict(d)
    d["w"] = rng.uniform(0.5, 2.0, len(d["x"]))
    m = sg.glm("y ~ x", d, family="poisson", weights="w")
    assert m.weights_col == "w" and m.has_weights
    m2 = sg.update(m, "~ . + z", d)
    direct = sg.glm("y ~ x + z", d, family="poisson", weights="w")
    np.testing.assert_array_equal(m2.coefficients, direct.coefficients)
    # grouped binomial with by-name m carries too
    d["succ"] = rng.integers(0, 5, len(d["x"])).astype(float)
    d["tot"] = d["succ"] + rng.integers(1, 5, len(d["x"]))
    mb = sg.glm("succ ~ x", d, family="binomial", m="tot")
    assert mb.m_col == "tot"
    mb2 = sg.update(mb, "~ . + z", d)
    directb = sg.glm("succ ~ x + z", d, family="binomial", m="tot")
    np.testing.assert_array_equal(mb2.coefficients, directb.coefficients)
    # lm weights carry
    ml = sg.lm("y ~ x", d, weights="w")
    ml2 = sg.update(ml, "~ . + z", d)
    directl = sg.lm("y ~ x + z", d, weights="w")
    np.testing.assert_array_equal(ml2.coefficients, directl.coefficients)


def test_update_refuses_dropped_array_weights(d, rng):
    """An array weights= cannot be recovered from new data: update must
    refuse rather than silently refit unweighted (ADVICE r2)."""
    w = rng.uniform(0.5, 2.0, len(d["x"]))
    m = sg.glm("y ~ x", d, family="poisson", weights=w)
    assert m.has_weights and m.weights_col is None
    with pytest.raises(ValueError, match="array weights"):
        sg.update(m, "~ . + z", d)
    # re-passing restores the refit
    m2 = sg.update(m, "~ . + z", d, weights=w)
    direct = sg.glm("y ~ x + z", d, family="poisson", weights=w)
    np.testing.assert_array_equal(m2.coefficients, direct.coefficients)


def test_saturated_fit_p_values_nan(rng):
    """df_residual == 0 with estimated dispersion: R prints NaN, not df=1
    p-values (ADVICE r2)."""
    X = np.column_stack([np.ones(3), np.array([1.0, 2.0, 4.0]),
                         np.array([1.0, 4.0, 16.0])])
    y = np.array([1.0, 2.0, 5.0])
    with np.testing.suppress_warnings() as sup:
        sup.filter(UserWarning)
        m = sg.glm_fit(X, y, family="gaussian", link="identity")
    assert m.df_residual == 0
    assert np.all(np.isnan(m.p_values()))
