"""poly(col, k) — R's stats::poly orthogonal polynomial basis."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig
from sparkglm_tpu.data.model_matrix import (_poly_eval, _poly_fit_coefs,
                                            build_terms, transform)

F64 = NumericConfig(dtype="float64")


def test_poly_basis_orthonormal_and_centered(rng):
    x = rng.uniform(-3, 5, 400)
    alpha, norm2 = _poly_fit_coefs(x, 4)
    Z = _poly_eval(x, alpha, norm2)
    assert Z.shape == (400, 4)
    # R's poly: columns are orthonormal and orthogonal to the constant
    np.testing.assert_allclose(Z.T @ Z, np.eye(4), atol=1e-10)
    np.testing.assert_allclose(Z.sum(axis=0), 0.0, atol=1e-9)
    # first column is the standardised x (up to sign convention: R's is
    # proportional to x - mean(x) with positive slope)
    c = np.corrcoef(Z[:, 0], x)[0, 1]
    assert c == pytest.approx(1.0, abs=1e-12)


def test_poly_recurrence_reproduces_training_basis(rng):
    """Evaluating the stored coefs on the TRAINING x must reproduce the
    QR-derived basis — the property R's predict.poly depends on."""
    x = rng.standard_normal(257) * 2.5 + 1.0
    alpha, norm2 = _poly_fit_coefs(x, 5)
    Z = _poly_eval(x, alpha, norm2)
    # independent check: Z spans the centered raw polynomials (Z excludes
    # the constant, so project the column-centered Vandermonde)
    V = np.vander(x - x.mean(), 6, increasing=True)[:, 1:]
    Vc = V - V.mean(axis=0)
    proj = Z @ (Z.T @ Vc)
    np.testing.assert_allclose(proj, Vc, rtol=1e-7, atol=1e-8)


def test_poly_formula_same_fit_as_raw_powers(rng):
    """y ~ poly(x, 3) spans the same space as y ~ x + I(x^2) + I(x^3):
    identical fitted values, deviance, and R^2 (coefficients differ — the
    basis is orthogonal)."""
    n = 500
    x = rng.uniform(0.5, 4.0, n)
    y = 1.0 + 0.8 * x - 0.3 * x ** 2 + 0.05 * x ** 3 \
        + 0.2 * rng.standard_normal(n)
    d = {"y": y, "x": x}
    mp = sg.lm("y ~ poly(x, 3)", d, config=F64)
    mr = sg.lm("y ~ x + I(x^2) + I(x^3)", d, config=F64)
    assert mp.xnames == ("intercept", "poly(x, 3)1", "poly(x, 3)2",
                         "poly(x, 3)3")
    assert mp.sse == pytest.approx(mr.sse, rel=1e-10)
    assert mp.r_squared == pytest.approx(mr.r_squared, rel=1e-10)
    X = transform(d, mp.terms, dtype=np.float64)
    np.testing.assert_allclose(mp.predict(X), mr.predict(
        transform(d, mr.terms, dtype=np.float64)), rtol=1e-9)


def test_poly_scoring_uses_training_basis(rng):
    """predict() on NEW data evaluates the TRAINING basis (stored coefs),
    not a re-fit one — R's predict.poly contract."""
    n = 400
    x = rng.uniform(0, 3, n)
    mu = np.exp(0.3 + 0.6 * x - 0.15 * x ** 2)
    y = rng.poisson(mu).astype(float)
    m = sg.glm("y ~ poly(x, 2)", {"y": y, "x": x}, family="poisson",
               config=F64)
    xn = np.array([0.1, 1.5, 2.9])
    got = sg.predict(m, {"x": xn}, type="link")
    # manual: evaluate the stored basis at xn
    c = m.terms.poly["poly(x, 2)"]
    Zn = _poly_eval(xn, c["alpha"], c["norm2"])
    want = m.coefficients[0] + Zn @ m.coefficients[1:]
    # api.predict materialises the scoring design at f32 (the framework's
    # storage dtype); compare at that precision
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # and a model round-tripped through save/load scores identically
    import tempfile
    with tempfile.NamedTemporaryFile(suffix=".npz") as f:
        m.save(f.name)
        m2 = sg.load_model(f.name)
    np.testing.assert_allclose(sg.predict(m2, {"x": xn}, type="link"),
                               got, rtol=1e-12)


def test_poly_in_interaction_with_factor(rng):
    n = 600
    x = rng.uniform(-1, 1, n)
    g = np.array(["a", "b"])[rng.integers(0, 2, n)]
    y = (1 + x + 0.5 * x ** 2 + (g == "b") * (0.5 - 0.8 * x)
         + 0.1 * rng.standard_normal(n))
    m = sg.lm("y ~ poly(x, 2) * g", {"y": y, "x": x, "g": g}, config=F64)
    assert m.xnames == ("intercept", "poly(x, 2)1", "poly(x, 2)2", "g_b",
                        "poly(x, 2)1:g_b", "poly(x, 2)2:g_b")
    # same span as the raw-power interaction model
    mr = sg.lm("y ~ x + I(x^2) + g + x:g + I(x^2):g",
               {"y": y, "x": x, "g": g}, config=F64)
    assert m.sse == pytest.approx(mr.sse, rel=1e-9)


def test_poly_update_and_drop1(rng):
    n = 300
    x = rng.uniform(0, 2, n)
    z = rng.standard_normal(n)
    y = 1 + x - 0.4 * x ** 2 + 0.3 * z + 0.1 * rng.standard_normal(n)
    d = {"y": y, "x": x, "z": z}
    m = sg.lm("y ~ poly(x, 2)", d, config=F64)
    m2 = sg.update(m, "~ . + z", d, config=F64)
    assert "poly(x, 2)" in m2.formula and "z" in m2.formula
    direct = sg.lm("y ~ poly(x, 2) + z", d, config=F64)
    np.testing.assert_allclose(m2.coefficients, direct.coefficients,
                               rtol=1e-9)


def test_poly_validation():
    x = np.array([1.0, 1.0, 1.0, 2.0])
    with pytest.raises(ValueError, match="unique"):
        _poly_fit_coefs(x, 2)
    with pytest.raises(ValueError, match="degree"):
        sg.lm("y ~ poly(x)", {"y": x, "x": x})
    with pytest.raises(ValueError, match="1 <= k <= 9"):
        sg.lm("y ~ poly(x, 12)", {"y": x, "x": x})


def test_poly_rejected_from_csv(tmp_path, rng):
    p = tmp_path / "d.csv"
    with open(p, "w") as fh:
        fh.write("y,x\n")
        for i in range(50):
            fh.write(f"{rng.random()},{rng.random()}\n")
    with pytest.raises(ValueError, match="poly"):
        sg.lm_from_csv("y ~ poly(x, 2)", str(p))
