"""glm_from_csv / lm_from_csv — the end-to-end out-of-memory path:
global schema+level scans, byte-range chunking, streaming IRLS.  The
reference's only ingestion is a full driver collect (dfToDenseMatrix,
utils.scala:42-49); it has no out-of-memory story (SURVEY.md §7 #4)."""

import csv as csv_mod

import numpy as np
import pytest

import sparkglm_tpu as sg


def _write_csv(path, cols):
    names = list(cols)
    n = len(cols[names[0]])
    with open(path, "w", newline="") as fh:
        w = csv_mod.writer(fh)
        w.writerow(names)
        for i in range(n):
            w.writerow([cols[nm][i] for nm in names])


@pytest.fixture()
def csv_data(tmp_path, rng):
    n = 2000
    x = rng.normal(size=n)
    grp = rng.choice(["a", "b", "c"], size=n)
    lt = rng.uniform(0.2, 0.8, size=n)
    lam = np.exp(0.3 + 0.5 * x - 0.4 * (grp == "b") + lt)
    y = rng.poisson(lam).astype(float)
    w = rng.uniform(0.5, 2.0, size=n)
    cols = {"y": y, "x": np.round(x, 6), "grp": grp,
            "lt": np.round(lt, 6), "w": np.round(w, 6)}
    p = tmp_path / "d.csv"
    _write_csv(p, cols)
    # reload through the csv text so float rounding matches exactly
    data = sg.read_csv(str(p))
    return str(p), data


def test_glm_from_csv_matches_in_memory(csv_data, mesh8):
    path, data = csv_data
    kw = dict(family="poisson", tol=1e-10, criterion="relative",
              weights="w", offset="lt", mesh=mesh8)
    m_csv = sg.glm_from_csv("y ~ x + grp + offset(lt)", path,
                            chunk_bytes=16 << 10, weights="w",
                            tol=1e-10, criterion="relative", mesh=mesh8,
                            family="poisson")
    m_mem = sg.glm("y ~ x + grp", data, **kw)
    # resident (single f32 reduction) vs streaming (f32 chunk passes,
    # f64 host accumulation) differ by f32 accumulation order: ~1e-5
    np.testing.assert_allclose(m_csv.coefficients, m_mem.coefficients,
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(m_csv.deviance, m_mem.deviance, rtol=1e-6)
    np.testing.assert_allclose(m_csv.null_deviance, m_mem.null_deviance,
                               rtol=1e-6)
    np.testing.assert_allclose(m_csv.loglik, m_mem.loglik, rtol=1e-6)
    np.testing.assert_allclose(m_csv.std_errors, m_mem.std_errors, rtol=1e-5)
    assert m_csv.xnames == m_mem.xnames
    assert m_csv.n_obs == m_mem.n_obs == 2000
    # the fitted model scores new data through its Terms + stored offset
    new = {"x": np.zeros(2), "grp": np.array(["a", "b"]),
           "lt": np.array([0.5, 0.5])}
    np.testing.assert_allclose(sg.predict(m_csv, new), sg.predict(m_mem, new),
                               rtol=1e-6)


def test_glm_from_csv_python_loader_parity(csv_data, mesh8):
    """native=False must give the identical fit (loader parity)."""
    path, _ = csv_data
    kw = dict(family="poisson", tol=1e-10, chunk_bytes=16 << 10, mesh=mesh8)
    m_auto = sg.glm_from_csv("y ~ x + grp", path, **kw)
    m_py = sg.glm_from_csv("y ~ x + grp", path, native=False, **kw)
    np.testing.assert_array_equal(m_py.coefficients, m_auto.coefficients)


def test_glm_from_csv_factor_levels_span_chunks(tmp_path, mesh8, rng):
    """A level confined to the tail of the file must still be coded in
    every chunk (global level scan)."""
    n = 600
    x = rng.normal(size=n)
    grp = np.array(["a"] * (n - 40) + ["z"] * 40)  # 'z' only in last chunk(s)
    y = (rng.random(n) < 1 / (1 + np.exp(-(0.2 * x + (grp == "z"))))
         ).astype(float)
    p = tmp_path / "lv.csv"
    _write_csv(p, {"y": y, "x": np.round(x, 6), "grp": grp})
    m = sg.glm_from_csv("y ~ x + grp", str(p), family="binomial",
                        chunk_bytes=4 << 10, tol=1e-8, mesh=mesh8)
    assert m.xnames == ("intercept", "x", "grp_z")
    data = sg.read_csv(str(p))
    m_mem = sg.glm("y ~ x + grp", data, family="binomial", tol=1e-8,
                   mesh=mesh8)
    # both fits stop at the f32 deviance resolution (the relative-criterion
    # ulp clamp), so they agree to the f32 floor, not to 1e-8
    np.testing.assert_allclose(m.coefficients, m_mem.coefficients,
                               rtol=1e-5, atol=1e-7)


def test_glm_from_csv_cbind_and_na(tmp_path, mesh8, rng):
    n = 500
    x = rng.normal(size=n)
    msz = rng.integers(4, 20, size=n).astype(float)
    pr = 1 / (1 + np.exp(-(0.3 + 0.6 * x)))
    s = rng.binomial(msz.astype(int), pr).astype(float)
    fails = msz - s
    xs = np.round(x, 6).astype(object)
    xs[7] = ""  # a missing x -> NA-omitted row
    p = tmp_path / "g.csv"
    _write_csv(p, {"s": s, "fails": fails, "x": xs})
    m = sg.glm_from_csv("cbind(s, fails) ~ x", str(p), family="binomial",
                        chunk_bytes=4 << 10, tol=1e-6, criterion="relative",
                        mesh=mesh8)
    assert m.n_obs == n - 1
    data = sg.read_csv(str(p))
    m_mem = sg.glm("cbind(s, fails) ~ x", data, family="binomial",
                   tol=1e-6, criterion="relative", mesh=mesh8)
    np.testing.assert_allclose(m.coefficients, m_mem.coefficients,
                               rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(m.aic, m_mem.aic, rtol=1e-6)


def test_glm_from_csv_interactions(csv_data, mesh8):
    """Interaction terms work through the chunked path: the design recipe
    (incl. factor levels for the crossed dummies) is pinned once and every
    chunk transforms identically."""
    path, data = csv_data
    kw = dict(family="poisson", tol=1e-8, criterion="relative", mesh=mesh8)
    m_csv = sg.glm_from_csv("y ~ x * grp", path, chunk_bytes=16 << 10, **kw)
    m_mem = sg.glm("y ~ x * grp", data, **kw)
    assert m_csv.xnames == m_mem.xnames == (
        "intercept", "x", "grp_b", "grp_c", "x:grp_b", "x:grp_c")
    # both fits stop at the f32 convergence floor; chunked vs resident
    # accumulation order leaves ~2e-5 relative
    np.testing.assert_allclose(m_csv.coefficients, m_mem.coefficients,
                               rtol=1e-4, atol=1e-7)


def test_lm_from_csv_matches_in_memory(csv_data, mesh8):
    path, data = csv_data
    m_csv = sg.lm_from_csv("y ~ x + grp", path, weights="w",
                           chunk_bytes=16 << 10, mesh=mesh8)
    m_mem = sg.lm("y ~ x + grp", data, weights="w", mesh=mesh8)
    # resident (single f32 reduction) vs streaming (f32 chunk passes, f64
    # host accumulation) differ by f32 accumulation order: ~1e-5, as in
    # the GLM parity test above
    np.testing.assert_allclose(m_csv.coefficients, m_mem.coefficients,
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(m_csv.r_squared, m_mem.r_squared, rtol=1e-6)
    np.testing.assert_allclose(m_csv.std_errors, m_mem.std_errors, rtol=1e-5)


def test_lm_from_csv_offset_matches_in_memory(csv_data, mesh8):
    """VERDICT r3 #6: lm(offset=) parity on the from-CSV tier — both the
    offset= column name and offset() formula terms, against the resident
    fit's R-exact offset moments (fitted-based mss)."""
    path, data = csv_data
    m_csv = sg.lm_from_csv("y ~ x + grp", path, weights="w", offset="lt",
                           chunk_bytes=16 << 10, mesh=mesh8)
    m_mem = sg.lm("y ~ x + grp", data, weights="w", offset="lt", mesh=mesh8)
    assert m_csv.has_offset and m_csv.offset_col == "lt"
    # same f32 accumulation-order bound as the no-offset parity test
    np.testing.assert_allclose(m_csv.coefficients, m_mem.coefficients,
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(m_csv.sse, m_mem.sse, rtol=1e-6)
    np.testing.assert_allclose(m_csv.sst, m_mem.sst, rtol=1e-6)
    np.testing.assert_allclose(m_csv.r_squared, m_mem.r_squared, rtol=1e-6)
    np.testing.assert_allclose(m_csv.f_statistic, m_mem.f_statistic,
                               rtol=1e-6)
    np.testing.assert_allclose(m_csv.std_errors, m_mem.std_errors, rtol=1e-5)

    # offset() formula term spells the same model
    m_term = sg.lm_from_csv("y ~ x + grp + offset(lt)", path, weights="w",
                            chunk_bytes=16 << 10, mesh=mesh8)
    np.testing.assert_allclose(m_term.coefficients, m_csv.coefficients,
                               rtol=0, atol=1e-12)
    np.testing.assert_allclose(m_term.sst, m_csv.sst, rtol=1e-12)


def test_lm_streaming_offset_no_intercept(rng, mesh8):
    """Offset mode without an intercept uses the raw fitted moments
    (mss = sum w f^2), matching the resident path."""
    n = 1500
    X = rng.normal(size=(n, 3))
    off = rng.uniform(0.0, 2.0, size=n)
    y = X @ [0.5, -0.3, 0.2] + off + 0.1 * rng.normal(size=n)
    from sparkglm_tpu.models.streaming import lm_fit_streaming
    m_s = lm_fit_streaming((X, y, None, off), chunk_rows=400,
                           has_intercept=False, mesh=mesh8)
    m_r = sg.lm_fit(X, y, offset=off, has_intercept=False, mesh=mesh8)
    np.testing.assert_allclose(m_s.coefficients, m_r.coefficients,
                               rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(m_s.sst, m_r.sst, rtol=1e-7)
    np.testing.assert_allclose(m_s.f_statistic, m_r.f_statistic, rtol=1e-6)


def test_lm_from_csv_residual_quantiles_golden(tmp_path):
    """VERDICT r3 #7: a from-CSV fit streams R's summary.lm 'Residuals:'
    five numbers in the residual pass it already makes, so summary()
    prints the block BY DEFAULT.  Golden: R's printed output for ?lm's
    plant-weight example (summary(lm.D9), quantile type 7 rounded
    half-even to 4 decimals — exactly derivable from the data):

        Residuals:
            Min      1Q  Median      3Q     Max
        -1.0710 -0.4938  0.0685  0.2462  1.3690
    """
    import json as json_mod
    import os as os_mod
    fx = os_mod.path.join(os_mod.path.dirname(__file__), "fixtures",
                          "r_golden.json")
    with open(fx) as fh:
        case = json_mod.load(fh)["formula_cases"]["lm_D9_factor"]
    p = tmp_path / "d9.csv"
    _write_csv(p, case["data"])
    m = sg.lm_from_csv("weight ~ group", str(p), chunk_bytes=1 << 8)
    assert m.resid_quantiles is not None
    np.testing.assert_allclose(
        m.resid_quantiles, [-1.0710, -0.4938, 0.0685, 0.2462, 1.3690],
        rtol=0, atol=5e-5)  # R prints 4 decimals
    text = str(m.summary())
    assert "Residuals:" in text and "Weighted" not in text
    assert "-1.071" in text and "1.369" in text

    # save/load keeps the block
    sp = tmp_path / "m.json"
    m.save(str(sp))
    m2 = sg.load_model(str(sp))
    np.testing.assert_allclose(m2.resid_quantiles, m.resid_quantiles,
                               rtol=0, atol=0)
    assert "Residuals:" in str(m2.summary())


def test_lm_streaming_weighted_residual_quantiles(rng, mesh8):
    """Weighted streams store sqrt(w)*r quantiles and summary() uses R's
    'Weighted Residuals:' header."""
    n = 900
    X = np.column_stack([np.ones(n), rng.normal(size=n)])
    w = rng.uniform(0.5, 2.0, size=n)
    y = X @ [1.0, 0.5] + 0.3 * rng.normal(size=n)
    m = sg.lm_fit_streaming((X, y, w, None), chunk_rows=200, mesh=mesh8)
    beta = m.coefficients
    wr = np.sqrt(w) * (y - X @ beta)
    np.testing.assert_allclose(
        m.resid_quantiles,
        np.quantile(wr.astype(np.float32).astype(np.float64),
                    [0, 0.25, 0.5, 0.75, 1.0]),
        rtol=1e-6, atol=1e-9)
    assert "Weighted Residuals:" in str(m.summary())

    # R's header rule needs weights that VARY: constant weights (even != 1)
    # keep the plain header, though the quantiles are still sqrt(w)*r
    mc = sg.lm_fit_streaming((X, y, np.full(n, 2.0), None), chunk_rows=200,
                             mesh=mesh8)
    sc = str(mc.summary())
    assert "Weighted Residuals:" not in sc and "Residuals:" in sc
    assert mc.has_weights and not mc.weights_vary


def test_from_csv_rejects_array_args(csv_data):
    path, _ = csv_data
    with pytest.raises(ValueError, match="column NAME"):
        sg.glm_from_csv("y ~ x", path, weights=np.ones(2000))
    with pytest.raises(KeyError, match="nope"):
        sg.glm_from_csv("y ~ x", path, weights="nope")


def test_update_on_from_csv_model(tmp_path, rng):
    """VERDICT r2 missing #4: update() works on the out-of-core flagship
    path — a from-CSV model refits by streaming the file again."""
    import sparkglm_tpu as sg
    n = 500
    x = rng.standard_normal(n)
    z = rng.standard_normal(n)
    w = rng.uniform(0.5, 2.0, n)
    y = rng.poisson(np.exp(0.2 + 0.5 * x + 0.2 * z)).astype(float)
    p = tmp_path / "d.csv"
    with open(p, "w") as fh:
        fh.write("y,x,z,w\n")
        for i in range(n):
            fh.write(f"{y[i]},{x[i]},{z[i]},{w[i]}\n")
    m = sg.glm_from_csv("y ~ x", str(p), family="poisson", weights="w",
                        chunk_bytes=4096)
    m2 = sg.update(m, "~ . + z", str(p), chunk_bytes=4096)
    direct = sg.glm("y ~ x + z", {"y": y, "x": x, "z": z, "w": w},
                    family="poisson", weights="w")
    np.testing.assert_allclose(m2.coefficients, direct.coefficients,
                               rtol=1e-6, atol=1e-8)
    assert m2.weights_col == "w"  # provenance carried through the refit


def test_drop1_on_from_csv_model(tmp_path, rng):
    import sparkglm_tpu as sg
    from sparkglm_tpu.models.anova import drop1
    n = 400
    x = rng.standard_normal(n)
    z = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.3 + 0.6 * x)).astype(float)
    p = tmp_path / "d.csv"
    with open(p, "w") as fh:
        fh.write("y,x,z\n")
        for i in range(n):
            fh.write(f"{y[i]},{x[i]},{z[i]}\n")
    m = sg.glm_from_csv("y ~ x + z", str(p), family="poisson",
                        chunk_bytes=2048)
    t_csv = drop1(m, str(p), test="Chisq", chunk_bytes=2048)
    m_res = sg.glm("y ~ x + z", {"y": y, "x": x, "z": z}, family="poisson")
    t_res = drop1(m_res, {"y": y, "x": x, "z": z}, test="Chisq")
    assert t_csv.row_names == t_res.row_names
    for r_csv, r_res in zip(t_csv.rows[1:], t_res.rows[1:]):
        np.testing.assert_allclose(r_csv[1], r_res[1], rtol=1e-6)  # deviance
        np.testing.assert_allclose(r_csv[3], r_res[3], rtol=1e-5)  # LRT


def test_confint_profile_on_from_csv_model(tmp_path, rng):
    import sparkglm_tpu as sg
    n = 300
    x = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.4 + 0.5 * x)).astype(float)
    p = tmp_path / "d.csv"
    with open(p, "w") as fh:
        fh.write("y,x\n")
        for i in range(n):
            fh.write(f"{y[i]},{x[i]}\n")
    m = sg.glm_from_csv("y ~ x", str(p), family="poisson", chunk_bytes=2048)
    ci_csv = sg.confint_profile(m, str(p), chunk_bytes=2048)
    m_res = sg.glm("y ~ x", {"y": y, "x": x}, family="poisson")
    ci_res = sg.confint_profile(m_res, {"y": y, "x": x})
    np.testing.assert_allclose(ci_csv, ci_res, rtol=1e-5, atol=1e-7)


def test_parse_cache_wrap_unit(tmp_path, rng):
    """VERDICT r2 weak #7: the parsed-chunk disk tier — each chunk parses
    ONCE, later passes memory-map; cleanup removes the tier."""
    import os

    from sparkglm_tpu.api import _parse_cache_wrap

    calls = {"n": 0}
    X0 = rng.standard_normal((40, 3))
    y0 = rng.standard_normal(40)

    def extract(i):
        calls["n"] += 1
        return X0 + i, y0 + i, None, None

    wrapped, cleanup = _parse_cache_wrap(extract, True, 10_000)
    for _ in range(3):          # three passes over two chunks
        for i in range(2):
            X, y, w, off = wrapped(i)
            np.testing.assert_allclose(np.asarray(X), X0 + i)
            np.testing.assert_allclose(np.asarray(y), y0 + i)
            assert w is None and off is None
    # first touch skips the write (may be the only extract: the HBM cache
    # pins hot chunks), second touch parses AND persists -> 2 per chunk
    assert calls["n"] == 4
    # mmap-backed on the cached path
    X, _, _, _ = wrapped(0)
    assert isinstance(X, np.memmap)
    assert calls["n"] == 4      # third+ touches load, never parse
    cleanup()
    # disabled mode is a passthrough
    wrapped2, cleanup2 = _parse_cache_wrap(extract, False, 10_000)
    wrapped2(0)
    assert calls["n"] == 5
    cleanup2()


def test_parse_cache_fit_parity(tmp_path, rng):
    """glm_from_csv with the disk tier on vs off: identical models (the
    tier changes WHERE chunks come from, never their content)."""
    import sparkglm_tpu as sg
    n = 400
    x = rng.standard_normal(n)
    w = rng.uniform(0.5, 2.0, n)
    y = rng.poisson(np.exp(0.2 + 0.5 * x)).astype(float)
    p = tmp_path / "d.csv"
    with open(p, "w") as fh:
        fh.write("y,x,w\n")
        for i in range(n):
            fh.write(f"{y[i]},{x[i]},{w[i]}\n")
    kw = dict(family="poisson", weights="w", chunk_bytes=2048, cache="none")
    m_on = sg.glm_from_csv("y ~ x", str(p), parse_cache=True, **kw)
    m_off = sg.glm_from_csv("y ~ x", str(p), parse_cache=False, **kw)
    np.testing.assert_array_equal(m_on.coefficients, m_off.coefficients)
    assert m_on.deviance == m_off.deviance


def test_gzip_csv_parity_and_nonsplittable(tmp_path, rng):
    """Spark-parity compressed ingestion (VERDICT r4 missing #1): a .gz
    twin of a CSV reads, scans and FITS identically to the plain file;
    byte-range sharding is refused (gzip is not splittable)."""
    import gzip

    import sparkglm_tpu as sg

    n = 400
    x = rng.standard_normal(n)
    grp = rng.choice(["a", "b", "c"], size=n)
    y = rng.poisson(np.exp(0.3 + 0.5 * x + 0.2 * (grp == "b"))).astype(float)
    plain = tmp_path / "d.csv"
    lines = ["y,x,grp"] + [f"{y[i]},{x[i]:.10g},{grp[i]}" for i in range(n)]
    plain.write_text("\n".join(lines) + "\n")
    gz = tmp_path / "d.csv.gz"
    with gzip.open(gz, "wt") as fh:
        fh.write(plain.read_text())

    assert sg.scan_csv_schema(str(gz)) == sg.scan_csv_schema(str(plain))
    assert sg.scan_csv_levels(str(gz)) == sg.scan_csv_levels(str(plain))
    cg, cp = sg.read_csv(str(gz)), sg.read_csv(str(plain))
    assert set(cg) == set(cp)
    np.testing.assert_array_equal(cg["x"], cp["x"])
    assert list(cg["grp"]) == list(cp["grp"])
    with pytest.raises(ValueError, match="not splittable"):
        sg.read_csv(str(gz), shard_index=1, num_shards=2)
    # the full streaming fit reads the .gz as ONE chunk, same numbers
    mg = sg.glm_from_csv("y ~ x + grp", str(gz), family="poisson")
    mp = sg.glm_from_csv("y ~ x + grp", str(plain), family="poisson")
    np.testing.assert_allclose(mg.coefficients, mp.coefficients, rtol=1e-10)
    np.testing.assert_allclose(mg.deviance, mp.deviance, rtol=1e-10)
    assert mg.n_obs == mp.n_obs == n


def test_gzip_streaming_stays_chunked(tmp_path, rng):
    """A .gz source must NOT collapse to one whole-file chunk: the
    streaming flow decompresses once, then chunks the PLAIN temp file by
    chunk_bytes (bounded memory — review r5)."""
    import gzip

    import sparkglm_tpu as sg
    from sparkglm_tpu import api as api_mod

    n = 2000
    x = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.3 + 0.5 * x)).astype(float)
    plain = tmp_path / "big.csv"
    plain.write_text("y,x\n" + "\n".join(
        f"{y[i]},{x[i]:.10g}" for i in range(n)) + "\n")
    gz = tmp_path / "big.csv.gz"
    with gzip.open(gz, "wt") as fh:
        fh.write(plain.read_text())
    _, nchunks, read = api_mod._stream_io(str(gz), chunk_bytes=8 << 10,
                                          native=None)
    assert nchunks > 1
    total = sum(len(read(i)["y"]) for i in range(nchunks))
    assert total == n
    mg = sg.glm_from_csv("y ~ x", str(gz), family="poisson",
                         chunk_bytes=8 << 10)
    mp = sg.glm_from_csv("y ~ x", str(plain), family="poisson",
                         chunk_bytes=8 << 10)
    np.testing.assert_allclose(mg.coefficients, mp.coefficients, rtol=1e-10)


def test_gzip_cache_invalidates_on_rewrite(tmp_path, rng):
    """The decompression cache keys on (path, mtime, size): rewriting the
    .gz must serve the NEW contents, never a stale cached copy."""
    import gzip
    import os
    import sparkglm_tpu as sg

    gz = tmp_path / "c.csv.gz"
    with gzip.open(gz, "wt") as fh:
        fh.write("y,x\n1,2\n")
    assert list(sg.read_csv(str(gz))["y"]) == [1.0]
    with gzip.open(gz, "wt") as fh:
        fh.write("y,x\n7,8\n9,10\n")
    os.utime(gz, (os.path.getmtime(gz) + 2, os.path.getmtime(gz) + 2))
    got = sg.read_csv(str(gz))
    assert list(got["y"]) == [7.0, 9.0] and list(got["x"]) == [8.0, 10.0]
