"""OLS/WLS parity + sharding-equivalence tests.

Pattern follows the reference's lmPredict$Test.scala:11-35 (fit on 1 vs 4
partitions, same answers) with actual numeric parity added — the reference
never checks LM.fit coefficients numerically (SURVEY.md §4 coverage gaps).
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from oracle import ols_np


def _data(rng, n=500, p=7):
    X = rng.normal(size=(n, p)).astype(np.float64)
    X[:, 0] = 1.0  # explicit intercept column, as in the reference fixtures
    beta = rng.normal(size=p)
    y = X @ beta + 0.1 * rng.normal(size=n)
    return X, y


def test_ols_matches_numpy_f64(rng, mesh1):
    X, y = _data(rng)
    m = sg.lm_fit(X, y, mesh=mesh1)
    np.testing.assert_allclose(m.coefficients, ols_np(X, y), rtol=1e-8, atol=1e-10)


def test_single_vs_eight_shards_agree(rng, mesh1, mesh8):
    X, y = _data(rng, n=501)  # deliberately not divisible by 8 -> padding path
    m1 = sg.lm_fit(X, y, mesh=mesh1)
    m8 = sg.lm_fit(X, y, mesh=mesh8)
    np.testing.assert_allclose(m1.coefficients, m8.coefficients, rtol=1e-9)
    np.testing.assert_allclose(m1.std_errors, m8.std_errors, rtol=1e-9)
    assert m1.n_obs == m8.n_obs == 501
    assert m8.n_shards == 8


def test_feature_sharded_mesh_agrees(rng, mesh1, mesh42):
    X, y = _data(rng, n=512, p=8)
    m1 = sg.lm_fit(X, y, mesh=mesh1)
    m42 = sg.lm_fit(X, y, mesh=mesh42, shard_features=True)
    np.testing.assert_allclose(m1.coefficients, m42.coefficients, rtol=1e-9)


def test_inference_stats(rng, mesh8):
    X, y = _data(rng, n=400, p=5)
    m = sg.lm_fit(X, y, mesh=mesh8)
    # residual stats recomputed by hand in f64
    beta = ols_np(X, y)
    resid = y - X @ beta
    sse = float(resid @ resid)
    sst = float(((y - y.mean()) ** 2).sum())
    assert m.df_resid == 395 and m.df_model == 4
    np.testing.assert_allclose(m.sse, sse, rtol=1e-8)
    np.testing.assert_allclose(m.r_squared, 1 - sse / sst, rtol=1e-8)
    sigma2 = sse / 395
    se = np.sqrt(sigma2 * np.diag(np.linalg.inv(X.T @ X)))
    np.testing.assert_allclose(m.std_errors, se, rtol=1e-7)
    f_expected = ((sst - sse) / 4) / sigma2
    np.testing.assert_allclose(m.f_statistic, f_expected, rtol=1e-8)


def test_weighted_least_squares(rng, mesh8):
    X, y = _data(rng, n=300, p=4)
    w = rng.uniform(0.5, 2.0, size=300)
    m = sg.lm_fit(X, y, weights=w, mesh=mesh8)
    np.testing.assert_allclose(m.coefficients, ols_np(X, y, w), rtol=1e-8)


def test_predict(rng, mesh8):
    X, y = _data(rng, n=200, p=4)
    m = sg.lm_fit(X, y, mesh=mesh8)
    Xnew = rng.normal(size=(50, 4))
    np.testing.assert_allclose(m.predict(Xnew), Xnew @ m.coefficients, rtol=1e-6)


def test_input_validation(rng, mesh1):
    X, y = _data(rng, n=50, p=3)
    with pytest.raises(ValueError):
        sg.lm_fit(X, y[:-1], mesh=mesh1)  # row mismatch (LM.scala:247-248)
    with pytest.raises(ValueError):
        sg.lm_fit(X, np.stack([y, y], axis=1), mesh=mesh1)  # 2-col y (LM.scala:249)
    with pytest.raises(ValueError):
        sg.lm_fit(X[:3], y[:3], mesh=mesh1)  # n <= p
