"""Influence diagnostics (hatvalues / rstandard / cooks.distance) — R
semantics, validated against the dense hat-matrix computed directly."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def _dense_hat(X, w):
    """diag of W^(1/2) X (X'WX)^-1 X' W^(1/2) — the O(n^2) way."""
    XtWX = X.T @ (w[:, None] * X)
    A = np.linalg.solve(XtWX, X.T)
    return w * np.einsum("ij,ji->i", X, A)


def test_lm_hat_and_cooks(mesh1, rng):
    n, p = 200, 4
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    w = rng.uniform(0.5, 2.0, size=n)
    y = X @ [1.0, 0.5, -0.2, 0.3] + 0.3 * rng.normal(size=n)
    m = sg.lm_fit(X, y, weights=w, mesh=mesh1)
    h = sg.hatvalues(m, X, weights=w)
    np.testing.assert_allclose(h, _dense_hat(X, w), rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(h.sum(), p, rtol=1e-5)  # trace(H) = rank
    rs = sg.rstandard(m, X, y, weights=w)
    resid = y - X @ m.coefficients
    np.testing.assert_allclose(
        rs, resid * np.sqrt(w) / (m.sigma * np.sqrt(1 - h)), rtol=1e-6)
    cd = sg.cooks_distance(m, X, y, weights=w)
    np.testing.assert_allclose(cd, rs ** 2 * h / ((1 - h) * p), rtol=1e-6)


def test_glm_hat_matches_irls_weights(mesh1, rng):
    n, p = 300, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ [0.2, 0.6, -0.4])))
         ).astype(float)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-12,
                   criterion="absolute", mesh=mesh1)
    mu = 1 / (1 + np.exp(-(X @ m.coefficients)))
    w_irls = mu * (1 - mu)  # binomial/logit working weights
    h = sg.hatvalues(m, X)
    np.testing.assert_allclose(h, _dense_hat(X, w_irls), rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(h.sum(), p, rtol=1e-4)
    # rstandard = deviance resid / sqrt(disp * (1 - h))
    d = m.residuals(X, y, type="deviance")
    np.testing.assert_allclose(sg.rstandard(m, X, y),
                               d / np.sqrt(1 - h), rtol=1e-6)
    # cooks from pearson residuals
    pe = m.residuals(X, y, type="pearson")
    np.testing.assert_allclose(sg.cooks_distance(m, X, y),
                               (pe / (1 - h)) ** 2 * h / p, rtol=1e-6)


def test_outlier_has_large_cooks(mesh1, rng):
    n = 150
    x = rng.normal(size=n)
    y = 1.0 + 2.0 * x + 0.1 * rng.normal(size=n)
    x[0], y[0] = 4.0, -10.0  # high-leverage outlier
    X = np.c_[np.ones(n), x]
    m = sg.lm_fit(X, y, mesh=mesh1)
    cd = sg.cooks_distance(m, X, y)
    assert cd[0] == cd.max() and cd[0] > 20 * np.median(cd)


def test_diagnostics_formula_data_and_aliased(rng):
    n = 120
    x = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    d = {"x": x, "grp": grp,
         "y": (rng.random(n) < 1 / (1 + np.exp(-0.5 * x))).astype(float)}
    m = sg.glm("y ~ x + grp", d, family="binomial")
    h = sg.hatvalues(m, d)  # column data through the stored Terms
    assert h.shape == (n,) and np.all((h >= 0) & (h <= 1))
    np.testing.assert_allclose(h.sum(), 3, rtol=1e-3)
    # aliased fits: rank excludes dropped columns
    X = np.c_[np.ones(n), x, x]
    y = d["y"]
    ma = sg.glm_fit(X, y, family="binomial", singular="drop")
    ha = sg.hatvalues(ma, X)
    np.testing.assert_allclose(ha.sum(), 2, rtol=1e-3)  # rank 2, not 3
    cd = sg.cooks_distance(ma, X, y)
    assert np.all(np.isfinite(cd))
