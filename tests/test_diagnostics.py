"""Influence diagnostics (hatvalues / rstandard / cooks.distance) — R
semantics, validated against the dense hat-matrix computed directly."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def _dense_hat(X, w):
    """diag of W^(1/2) X (X'WX)^-1 X' W^(1/2) — the O(n^2) way."""
    XtWX = X.T @ (w[:, None] * X)
    A = np.linalg.solve(XtWX, X.T)
    return w * np.einsum("ij,ji->i", X, A)


def test_lm_hat_and_cooks(mesh1, rng):
    n, p = 200, 4
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    w = rng.uniform(0.5, 2.0, size=n)
    y = X @ [1.0, 0.5, -0.2, 0.3] + 0.3 * rng.normal(size=n)
    m = sg.lm_fit(X, y, weights=w, mesh=mesh1)
    h = sg.hatvalues(m, X, weights=w)
    np.testing.assert_allclose(h, _dense_hat(X, w), rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(h.sum(), p, rtol=1e-5)  # trace(H) = rank
    rs = sg.rstandard(m, X, y, weights=w)
    resid = y - X @ m.coefficients
    np.testing.assert_allclose(
        rs, resid * np.sqrt(w) / (m.sigma * np.sqrt(1 - h)), rtol=1e-6)
    cd = sg.cooks_distance(m, X, y, weights=w)
    np.testing.assert_allclose(cd, rs ** 2 * h / ((1 - h) * p), rtol=1e-6)


def test_glm_hat_matches_irls_weights(mesh1, rng):
    n, p = 300, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ [0.2, 0.6, -0.4])))
         ).astype(float)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-12,
                   criterion="absolute", mesh=mesh1)
    mu = 1 / (1 + np.exp(-(X @ m.coefficients)))
    w_irls = mu * (1 - mu)  # binomial/logit working weights
    h = sg.hatvalues(m, X)
    np.testing.assert_allclose(h, _dense_hat(X, w_irls), rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(h.sum(), p, rtol=1e-4)
    # rstandard = deviance resid / sqrt(disp * (1 - h))
    d = m.residuals(X, y, type="deviance")
    np.testing.assert_allclose(sg.rstandard(m, X, y),
                               d / np.sqrt(1 - h), rtol=1e-6)
    # cooks from pearson residuals
    pe = m.residuals(X, y, type="pearson")
    np.testing.assert_allclose(sg.cooks_distance(m, X, y),
                               (pe / (1 - h)) ** 2 * h / p, rtol=1e-6)


def test_outlier_has_large_cooks(mesh1, rng):
    n = 150
    x = rng.normal(size=n)
    y = 1.0 + 2.0 * x + 0.1 * rng.normal(size=n)
    x[0], y[0] = 4.0, -10.0  # high-leverage outlier
    X = np.c_[np.ones(n), x]
    m = sg.lm_fit(X, y, mesh=mesh1)
    cd = sg.cooks_distance(m, X, y)
    assert cd[0] == cd.max() and cd[0] > 20 * np.median(cd)


def test_diagnostics_formula_data_and_aliased(rng):
    n = 120
    x = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    d = {"x": x, "grp": grp,
         "y": (rng.random(n) < 1 / (1 + np.exp(-0.5 * x))).astype(float)}
    m = sg.glm("y ~ x + grp", d, family="binomial")
    h = sg.hatvalues(m, d)  # column data through the stored Terms
    assert h.shape == (n,) and np.all((h >= 0) & (h <= 1))
    np.testing.assert_allclose(h.sum(), 3, rtol=1e-3)
    # aliased fits: rank excludes dropped columns
    X = np.c_[np.ones(n), x, x]
    y = d["y"]
    ma = sg.glm_fit(X, y, family="binomial", singular="drop")
    ha = sg.hatvalues(ma, X)
    np.testing.assert_allclose(ha.sum(), 2, rtol=1e-3)  # rank 2, not 3
    cd = sg.cooks_distance(ma, X, y)
    assert np.all(np.isfinite(cd))


def test_dfbeta_dffits_lm_exact_vs_deletion(rng, mesh8):
    """The LM rank-one downdate identities are algebraic: dfbeta and
    dffits must match BRUTE-FORCE row deletion to f64 precision."""
    from sparkglm_tpu.config import NumericConfig
    n, p = 300, 4
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    w = rng.uniform(0.5, 2.0, n)
    y = X @ rng.standard_normal(p) + 0.4 * rng.standard_normal(n)
    cfg = NumericConfig(dtype="float64")
    full = sg.lm_fit(X, y, weights=w, config=cfg)
    dfb = sg.dfbeta(full, X, y, weights=w)
    dft = sg.dffits(full, X, y, weights=w)
    dfbs = sg.dfbetas(full, X, y, weights=w)
    h = sg.hatvalues(full, X, weights=w)
    for i in (0, 17, 123, n - 1):
        keep = np.arange(n) != i
        sub = sg.lm_fit(X[keep], y[keep], weights=w[keep], config=cfg)
        np.testing.assert_allclose(dfb[i], full.coefficients - sub.coefficients,
                                   rtol=1e-7, atol=1e-10)
        # dffits_i = (yhat_i - yhat_(i)) / (sigma_(i) sqrt(h_i / w_i))
        yhat_full = float(X[i] @ full.coefficients)
        yhat_del = float(X[i] @ sub.coefficients)
        want = (yhat_full - yhat_del) / (sub.sigma * np.sqrt(h[i] / w[i]))
        np.testing.assert_allclose(dft[i], want, rtol=1e-7)
        # dfbetas scaling: dfbeta / (sigma_(i) * sqrt(cov_jj))
        np.testing.assert_allclose(
            dfbs[i], dfb[i] / (sub.sigma * np.sqrt(np.diag(full.cov_unscaled))),
            rtol=1e-7)


def test_dfbeta_glm_one_step_tracks_deletion(rng, mesh8):
    """The GLM one-step approximations (R's influence.glm) must track the
    actual deletion refits: high rank correlation and the same most
    influential row."""
    from sparkglm_tpu.config import NumericConfig
    n, p = 250, 3
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    eta = X @ np.array([0.3, 0.6, -0.4])
    y = rng.poisson(np.exp(eta)).astype(float)
    y[7] += 25  # plant an outlier
    cfg = NumericConfig(dtype="float64")
    full = sg.glm_fit(X, y, family="poisson", tol=1e-12, config=cfg)
    dfb = sg.dfbeta(full, X, y)
    actual = np.empty_like(dfb)
    for i in range(n):
        keep = np.arange(n) != i
        sub = sg.glm_fit(X[keep], y[keep], family="poisson", tol=1e-12,
                         config=cfg)
        actual[i] = full.coefficients - sub.coefficients
    # R's deviance-residual one-step (digit-for-digit influence.glm) is a
    # hair looser against true deletion than the textbook working-residual
    # one-step; 0.94 still certifies it tracks the refits
    for j in range(p):
        r = np.corrcoef(dfb[:, j], actual[:, j])[0, 1]
        assert r > 0.94, (j, r)
    # the planted outlier dominates both the approximation and the truth
    assert np.argmax(np.abs(sg.dffits(full, X, y))) == 7
    assert np.argmax(np.linalg.norm(actual, axis=1)) == 7


def test_dfbetas_nan_when_scale_undefined(rng):
    """n - p - 1 == 0: sigma_(i) is undefined; dfbetas/dffits report NaN
    (R's behavior), never plausible finite numbers at an arbitrary scale."""
    from sparkglm_tpu.config import NumericConfig
    n, p = 4, 3
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    y = rng.standard_normal(n)
    m = sg.lm_fit(X, y, config=NumericConfig(dtype="float64"))
    assert np.isnan(sg.dfbetas(m, X, y)).all()
    assert np.isnan(sg.dffits(m, X, y)).all()
    # dfbeta itself (unscaled) stays exact and finite
    assert np.isfinite(sg.dfbeta(m, X, y)).all()


def _golden():
    import json
    import os
    with open(os.path.join(os.path.dirname(__file__), "fixtures",
                           "r_golden.json")) as f:
        return json.load(f)


def _influence_all(model, X, y, **kw):
    return dict(
        hat=sg.hatvalues(model, X, **kw),
        dfbeta=sg.dfbeta(model, X, y, **kw),
        dfbetas=sg.dfbetas(model, X, y, **kw),
        dffits=sg.dffits(model, X, y, **kw),
        covratio=sg.covratio(model, X, y, **kw),
        rstudent=sg.rstudent(model, X, y, **kw),
        rstandard=sg.rstandard(model, X, y, **kw),
        cooks_distance=sg.cooks_distance(model, X, y, **kw),
    )


@pytest.mark.parametrize("case", ["dobson_poisson", "clotting_gamma_lot1",
                                  "grouped_binomial_logit",
                                  "gaussian_weighted"])
def test_glm_influence_golden(mesh1, case):
    """Digit-for-digit R: every influence quantity against the committed
    R-semantics goldens (QR-route independent implementation, verifiable
    with real R via make_r_golden.R)."""
    from sparkglm_tpu.config import NumericConfig
    j = _golden()[case]
    d, g = j["data"], j["influence"]
    kw = {}
    if case == "dobson_poisson":
        o = np.tile([(0, 0), (1, 0), (0, 1)], (3, 1))
        t = np.repeat([(0, 0), (1, 0), (0, 1)], 3, axis=0)
        X = np.column_stack([np.ones(9), o, t])
        y = np.asarray(d["counts"], float)
    elif case == "clotting_gamma_lot1":
        u = np.asarray(d["u"], float)
        X = np.column_stack([np.ones(len(u)), np.log(u)])
        y = np.asarray(d["lot1"], float)
    elif case == "grouped_binomial_logit":
        x1 = np.asarray(d["x1"], float)
        X = np.column_stack([np.ones(len(x1)), x1])
        y = np.asarray(d["successes"], float)
        kw["m"] = np.asarray(d["m"], float)
    else:
        x1 = np.asarray(d["x1"], float)
        X = np.column_stack([np.ones(len(x1)), x1])
        y = np.asarray(d["y"], float)
        kw["weights"] = np.asarray(d["w"], float)
    model = sg.glm_fit(X, y, family=j["family"], link=j["link"], tol=1e-12,
                       config=NumericConfig(dtype="float64"), mesh=mesh1, **kw)
    got = _influence_all(model, X, y, **kw)
    # sigma_(i) rides inside dfbetas/dffits; compare the direct outputs
    for key, want in got.items():
        np.testing.assert_allclose(
            want, np.asarray(g[key], float), rtol=5e-6, atol=1e-9,
            err_msg=f"{case}:{key}")
    im = sg.influence_measures(model, X, y, **kw)
    k = X.shape[1]
    np.testing.assert_allclose(
        im.infmat,
        np.column_stack([np.asarray(g["dfbetas"], float),
                         np.asarray(g["dffits"], float),
                         np.asarray(g["covratio"], float),
                         np.asarray(g["cooks_distance"], float),
                         np.asarray(g["hat"], float)]),
        rtol=5e-6, atol=1e-9)
    assert im.infmat.shape[1] == k + 4
    np.testing.assert_array_equal(im.is_inf.astype(int),
                                  np.asarray(g["is_inf"], int))


def test_lm_influence_golden(mesh1):
    """R's ?lm plant-weight fixture through the FORMULA path: the stored
    Terms rebuild the design, and every influence quantity matches the
    R-semantics goldens."""
    j = _golden()["formula_cases"]["lm_D9_factor"]
    d, g = j["data"], j["influence"]
    from sparkglm_tpu.config import NumericConfig
    data = {"weight": np.asarray(d["weight"], float),
            "group": list(d["group"])}
    model = sg.lm(j["formula"], data, config=NumericConfig(dtype="float64"))
    y = data["weight"]
    got = _influence_all(model, data, y)
    for key, want in got.items():
        np.testing.assert_allclose(
            got[key], np.asarray(g[key], float), rtol=5e-6, atol=1e-9,
            err_msg=f"lm_D9:{key}")
    im = sg.influence_measures(model, data, y)
    assert im.columns[-4:] == ["dffit", "cov.r", "cook.d", "hat"]
    assert im.columns[0].startswith("dfb.")
    np.testing.assert_array_equal(im.is_inf.astype(int),
                                  np.asarray(g["is_inf"], int))


def test_leverage_one_row_reports_nan(rng):
    """A factor level observed in exactly one row has h_i = 1: R reports
    NaN for every sigma_(i)-scaled diagnostic there (0/0 through the
    downdate), never a clamp-scaled finite stand-in."""
    from sparkglm_tpu.config import NumericConfig
    n = 40
    x = rng.standard_normal(n)
    d = {"y": 1.0 + 0.5 * x + 0.1 * rng.standard_normal(n),
         "x": x, "g": ["a"] * (n - 1) + ["solo"]}
    m = sg.lm("y ~ x + g", d, config=NumericConfig(dtype="float64"))
    y = d["y"]
    assert sg.hatvalues(m, d)[-1] == 1.0
    assert np.isnan(sg.dffits(m, d, y)[-1])
    assert np.isnan(sg.covratio(m, d, y)[-1])
    assert np.isnan(sg.rstudent(m, d, y)[-1])
    assert np.isnan(sg.dfbetas(m, d, y)[-1]).all()
    # the other rows stay fully defined
    assert np.isfinite(sg.dffits(m, d, y)[:-1]).all()
    im = sg.influence_measures(m, d, y)
    assert np.isnan(im.infmat[-1, -4])  # dffit column
    assert np.isfinite(im.infmat[:-1, -4]).all()


def test_diagnostics_recover_formula_offset(rng):
    """A fit-time offset() column travels with the model: diagnostics on
    COLUMN data recover it automatically (same contract as predict), and
    an unrecoverable array offset is refused, never silently dropped."""
    n = 500
    x = rng.standard_normal(n)
    off = rng.uniform(0.0, 1.0, n)
    y = rng.poisson(np.exp(0.3 + 0.5 * x + off)).astype(float)
    data = {"y": y, "x": x, "lo": off}
    m = sg.glm("y ~ x + offset(lo)", data, family="poisson")
    X = np.column_stack([np.ones(n), x])
    auto = sg.dffits(m, data, y)
    explicit = sg.dffits(m, X, y, offset=off)
    np.testing.assert_allclose(auto, explicit, rtol=1e-10)
    # and they genuinely differ from the (wrong) offset-free values
    m0 = sg.glm("y ~ x", data, family="poisson")
    assert not np.allclose(auto, sg.dffits(m0, data, y))
    # array-offset fits refuse silent offset-free diagnostics
    ma = sg.glm_fit(X, y, family="poisson", offset=off)
    with pytest.raises(ValueError, match="offset"):
        sg.hatvalues(ma, X)
    # two SEPARATE f32 fits (formula vs array path): same hat values up
    # to the fits' own f32 coefficient noise
    np.testing.assert_allclose(sg.hatvalues(ma, X, offset=off),
                               sg.hatvalues(m, data), rtol=5e-3)


def test_influence_list_object(mesh1):
    """R's influence(fit) list: hat / coefficients / sigma plus dev.res +
    pear.res for a GLM (wt.res for an LM) — consistent with the individual
    verbs on the Dobson fixture."""
    from sparkglm_tpu.config import NumericConfig
    j = _golden()["dobson_poisson"]
    o = np.tile([(0, 0), (1, 0), (0, 1)], (3, 1))
    t = np.repeat([(0, 0), (1, 0), (0, 1)], 3, axis=0)
    X = np.column_stack([np.ones(9), o, t])
    y = np.asarray(j["data"]["counts"], float)
    model = sg.glm_fit(X, y, family="poisson", tol=1e-12,
                       config=NumericConfig(dtype="float64"), mesh=mesh1)
    inf = sg.influence(model, X, y)
    g = j["influence"]
    np.testing.assert_allclose(inf.hat, np.asarray(g["hat"]), rtol=1e-6)
    np.testing.assert_allclose(inf.sigma, np.asarray(g["sigma"]), rtol=1e-6)
    np.testing.assert_allclose(inf.coefficients, np.asarray(g["dfbeta"]),
                               rtol=1e-5, atol=1e-10)
    d = model.residuals(X, y, type="deviance")
    np.testing.assert_allclose(inf.dev_res, d, rtol=1e-10)
    assert hasattr(inf, "pear_res")
    # LM flavor carries wt_res instead
    ml = sg.lm_fit(X[:, :3], y, config=NumericConfig(dtype="float64"),
                   mesh=mesh1)
    il = sg.influence(ml, X[:, :3], y)
    assert hasattr(il, "wt_res") and not hasattr(il, "dev_res")


def test_rstudent_quasi_divides_by_sigma(mesh1, rng):
    """R's rstudent.glm special-cases the families NAMED binomial/poisson:
    quasipoisson (same fit, estimated dispersion) DIVIDES by sigma_(i),
    so its rstudent differs from poisson's by exactly that factor."""
    from sparkglm_tpu.config import NumericConfig
    n = 120
    x = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.4 + 0.5 * x)).astype(float)
    X = np.column_stack([np.ones(n), x])
    cfg = NumericConfig(dtype="float64")
    mp = sg.glm_fit(X, y, family="poisson", tol=1e-12, config=cfg,
                    mesh=mesh1)
    mq = sg.glm_fit(X, y, family="quasipoisson", tol=1e-12, config=cfg,
                    mesh=mesh1)
    rp = sg.rstudent(mp, X, y)
    rq = sg.rstudent(mq, X, y)
    # same coefficients -> same deviance/pearson pieces -> same sigma_(i)
    _, _, ew, _, h, om, s_i, _ = \
        __import__("sparkglm_tpu.models.diagnostics",
                   fromlist=["_deletion_pieces"])._deletion_pieces(
            mq, X, y, weights=None, offset=None, m=None)
    np.testing.assert_allclose(rq, rp / s_i, rtol=1e-9)
    assert not np.allclose(rq, rp)
