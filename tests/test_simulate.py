"""R's simulate(): family-faithful response draws at the fitted values.
Distributional parity asserted by moments (numpy streams are not R's;
the distributions are)."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def test_simulate_poisson_moments(rng):
    n = 4000
    x = rng.standard_normal(n)
    d = {"y": rng.poisson(np.exp(0.4 + 0.5 * x)).astype(float), "x": x}
    m = sg.glm("y ~ x", d, family="poisson")
    sims = sg.simulate(m, d, nsim=50, seed=1)
    assert sims.shape == (n, 50)
    mu = sg.predict(m, d)
    np.testing.assert_allclose(sims.mean(axis=1).mean(), mu.mean(), rtol=0.02)
    np.testing.assert_allclose(sims.var(axis=1).mean(), mu.mean(), rtol=0.05)


def test_simulate_binomial_grouped_returns_proportions(rng):
    n = 1500
    x = rng.standard_normal(n)
    msz = rng.integers(5, 30, n).astype(float)
    pr = 1 / (1 + np.exp(-(0.3 + 0.6 * x)))
    s = rng.binomial(msz.astype(int), pr).astype(float)
    d = {"s": s, "f": msz - s, "x": x}
    m = sg.glm("cbind(s, f) ~ x", d, family="binomial")
    sims = sg.simulate(m, d, nsim=40, seed=2, m=msz)
    assert sims.shape == (n, 40)
    assert sims.min() >= 0.0 and sims.max() <= 1.0  # proportions, as in R
    mu = sg.predict(m, d)
    np.testing.assert_allclose(sims.mean(axis=1), mu, atol=0.12)
    # non-integer weights are refused (R's binomial simulate refuses too)
    with pytest.raises(ValueError, match="integer size"):
        sg.simulate(m, d, nsim=2, m=msz + 0.5)


def test_simulate_gamma_lm_and_guards(rng):
    n = 3000
    x = rng.standard_normal(n)
    mu = np.exp(0.4 + 0.3 * x)
    d = {"y": rng.gamma(4.0, mu / 4.0), "x": x}
    g = sg.glm("y ~ x", d, family="gamma", link="log")
    sims = sg.simulate(g, d, nsim=60, seed=3)
    muh = sg.predict(g, d)
    np.testing.assert_allclose(sims.mean(axis=1).mean(), muh.mean(),
                               rtol=0.02)
    # var(Gamma) = disp * mu^2
    np.testing.assert_allclose(sims.var(axis=1).mean(),
                               (g.dispersion * muh ** 2).mean(), rtol=0.12)
    # lm: gaussian at sigma^2
    lmod = sg.lm("y ~ x", d)
    sl = sg.simulate(lmod, d, nsim=60, seed=4)
    np.testing.assert_allclose(sl.std(axis=1).mean(), lmod.sigma, rtol=0.05)
    # quasi refusal
    q = sg.glm("y ~ x", {"y": d["y"].round(), "x": x}, family="quasipoisson")
    with pytest.raises(ValueError, match="quasi"):
        sg.simulate(q, d, nsim=1)


def test_simulate_negbin_and_invgauss_moments(rng):
    n = 5000
    x = rng.standard_normal(n)
    mu = np.exp(0.4 + 0.4 * x)
    y = rng.negative_binomial(2.0, 2.0 / (2.0 + mu)).astype(float)
    d = {"y": y, "x": x}
    m = sg.glm_nb("y ~ x", d)
    sims = sg.simulate(m, d, nsim=40, seed=5)
    muh = sg.predict(m, d)
    th = sg.theta_of(m)
    np.testing.assert_allclose(sims.mean(axis=1).mean(), muh.mean(),
                               rtol=0.03)
    # var(NB) = mu + mu^2/theta
    np.testing.assert_allclose(sims.var(axis=1).mean(),
                               (muh + muh ** 2 / th).mean(), rtol=0.1)
    # inverse gaussian: mean mu, var disp*mu^3
    mu_ig = 1.0 / np.sqrt(0.5 + 0.3 * np.abs(x) + 0.2)
    from sparkglm_tpu.models.simulate import _rinvgauss
    draws = _rinvgauss(np.random.default_rng(0), mu_ig, np.full(n, 5.0), 30)
    np.testing.assert_allclose(draws.mean(axis=1).mean(), mu_ig.mean(),
                               rtol=0.02)
    np.testing.assert_allclose(draws.var(axis=1).mean(),
                               (mu_ig ** 3 / 5.0).mean(), rtol=0.12)


def test_simulate_recovers_fit_time_offset(rng):
    """A fit-time offset() column travels with the model into simulate
    exactly as it does into predict — caught live in review: forwarding
    offset=None was suppressing the recovery."""
    n = 2000
    x = rng.standard_normal(n)
    off = rng.uniform(0, 1, n)
    d = {"y": rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float),
         "x": x, "lo": off}
    m = sg.glm("y ~ x + offset(lo)", d, family="poisson")
    sims = sg.simulate(m, d, nsim=100, seed=1)
    mu = np.asarray(sg.predict(m, d, type="response"))
    np.testing.assert_allclose(sims.mean(), mu.mean(), rtol=0.03)


def test_simulate_carries_fit_weights_and_gamma_ml_shape(rng):
    """Fit-time by-name weights travel into simulate (R uses the stored
    prior.weights); the Gamma shape is the MASS ML estimate from the
    training response, not 1/Pearson-dispersion."""
    from sparkglm_tpu.models.simulate import _gamma_shape_ml
    n = 4000
    x = rng.standard_normal(n)
    w = rng.uniform(0.5, 3.0, n)
    mu = np.exp(0.4 + 0.3 * x)
    # weighted gamma: obs i ~ Gamma(shape 4*w_i, mean mu_i)
    y = rng.gamma(4.0 * w, mu / (4.0 * w))
    d = {"y": y, "x": x, "w": w}
    g = sg.glm("y ~ x", d, family="gamma", link="log", weights="w")
    muh = np.asarray(sg.predict(g, d))
    alpha = _gamma_shape_ml(y, muh, w, g)
    np.testing.assert_allclose(alpha, 4.0, rtol=0.1)  # ML recovers truth
    # simulate auto-recovers the weights column: heavier rows draw tighter
    sims = sg.simulate(g, d, nsim=200, seed=9)
    v = sims.var(axis=1)
    lo, hi = w < np.quantile(w, 0.2), w > np.quantile(w, 0.8)
    # var = mu^2/(alpha w): normalize by mu^2 and compare weight bands
    assert (v[lo] / muh[lo] ** 2).mean() > 2.0 * (v[hi] / muh[hi] ** 2).mean()
    # a FORMULA fit with ARRAY weights refuses silent unweighted draws
    gaw = sg.glm("y ~ x", d, family="gamma", link="log", weights=w)
    with pytest.raises(ValueError, match="array weights"):
        sg.simulate(gaw, d, nsim=1)
    # ...and an array-fit model simulates on its design with explicit
    # weights (provenance is the caller's there)
    ga = sg.glm_fit(np.c_[np.ones(n), x].astype(np.float64), y,
                    family="gamma", link="log", weights=w)
    s2 = sg.simulate(ga, np.c_[np.ones(n), x], nsim=3, weights=w, y=y)
    assert s2.shape == (n, 3) and np.all(s2 > 0)
