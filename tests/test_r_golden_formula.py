"""R-golden parity for the FORMULA front-end (VERDICT r2 weak #5).

Every case goes through data/formula.py -> model_matrix.py -> fit
end-to-end — factors, interactions, transforms, weights + offset(),
cbind() — and is asserted three ways:

  * ``xnames`` — the design the formula must build (coding, order, names);
  * ``fit`` — full-precision R-semantics values (tests/fixtures/
    gen_golden.py oracle64 tier; verify anywhere R is installed with
    tests/fixtures/make_r_golden.R);
  * ``r_doc`` + ``summary_contains`` — numbers R ITSELF prints in its
    ?glm / ?lm documentation (the Dobson poisson, the clotting Gamma,
    the lm.D9 plant-weight example), asserted both numerically at
    printed precision and as substrings of our rendered summary — the
    reference's own golden-string pattern (test_LM.R:44) pointed at
    correct values.
"""

import json
import os

import numpy as np
import pytest

import sparkglm_tpu as sg

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "r_golden.json")

with open(FIXTURES) as f:
    FORMULA_GOLDEN = json.load(f)["formula_cases"]


def _fit(case):
    from sparkglm_tpu.config import NumericConfig
    data = {k: np.asarray(v) for k, v in case["data"].items()}
    cfg = NumericConfig(dtype="float64")  # full-precision golden parity
    if case.get("model") == "lm":
        return sg.lm(case["formula"], data, config=cfg)
    kw = dict(family=case["family"], link=case["link"],
              tol=1e-12, criterion="relative", max_iter=200, config=cfg)
    if "weights" in case:
        kw["weights"] = case["weights"]
    return sg.glm(case["formula"], data, **kw)


@pytest.mark.parametrize("name", sorted(FORMULA_GOLDEN))
def test_formula_golden(name):
    case = FORMULA_GOLDEN[name]
    model = _fit(case)
    g = case["fit"]

    assert list(model.xnames) == case["xnames"]
    np.testing.assert_allclose(model.coefficients, g["coefficients"],
                               rtol=1e-6, atol=1e-8)
    if case.get("model") == "lm":
        assert model.sse == pytest.approx(g["sse"], rel=1e-9)
        assert model.sigma == pytest.approx(g["sigma"], rel=1e-9)
        assert model.r_squared == pytest.approx(g["r_squared"], rel=1e-9)
        assert model.df_resid == g["df_resid"]
    else:
        np.testing.assert_allclose(model.std_errors, g["std_errors"],
                                   rtol=1e-6, atol=1e-10)
        assert model.deviance == pytest.approx(g["deviance"], rel=1e-7,
                                               abs=1e-10)
        assert model.null_deviance == pytest.approx(g["null_deviance"],
                                                    rel=1e-7)
        assert model.dispersion == pytest.approx(g["dispersion"], rel=1e-6)
        assert model.df_residual == g["df_residual"]
        assert model.aic == pytest.approx(g["aic"], rel=1e-7)

    # documentation-printed R values, at printed precision
    rd = case.get("r_doc")
    if rd:
        for got, want in zip(model.coefficients, rd.get("coefficients", [])):
            if want is not None:
                assert got == pytest.approx(want, abs=1.5e-3 * max(
                    1e-3, abs(want)) + 1.5e-6)
        for got, want in zip(model.std_errors, rd.get("std_errors", [])):
            assert got == pytest.approx(want, abs=1.5e-4)
        for key, attr in (("deviance", "deviance"),
                          ("null_deviance", "null_deviance"),
                          ("aic", "aic"), ("sigma", "sigma"),
                          ("r_squared", "r_squared"),
                          ("adj_r_squared", "adj_r_squared"),
                          ("f_statistic", "f_statistic")):
            if key in rd:
                assert getattr(model, attr) == pytest.approx(
                    rd[key], rel=1e-3)

    # golden-STRING summary assertion (the reference's test pattern):
    # the rendered table must contain the R-printed numbers
    text = str(model.summary())
    for snippet in case.get("summary_contains", []):
        assert snippet in text, f"{snippet!r} not in summary:\n{text}"


def test_formula_golden_covers_required_shapes():
    """The case set exercises every front-end feature VERDICT r2 #7 lists."""
    formulas = [c["formula"] for c in FORMULA_GOLDEN.values()]
    assert len(formulas) >= 6
    assert any("*" in f for f in formulas)                  # interaction
    assert any("log(" in f for f in formulas)               # transform
    assert any("I(" in f for f in formulas)                 # power term
    assert any("offset(" in f for f in formulas)            # offset()
    assert any("cbind(" in f for f in formulas)             # cbind response
    assert any("weights" in c for c in FORMULA_GOLDEN.values())  # weights=
    # factors with string levels in at least two cases
    n_factor = sum(any(isinstance(v[0], str) for v in c["data"].values())
                   for c in FORMULA_GOLDEN.values())
    assert n_factor >= 2
