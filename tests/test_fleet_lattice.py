"""The capability lattice + the PR 20 fleet scale axes.

Two subsystems under one marker (``fleet_lattice``):

  * ``sparkglm_tpu/capabilities.py`` — EVERY refusal in the system lives
    in one declarative table.  The exhaustive walk iterates all
    design x engine x penalty x execution cells and asserts
    fit-or-pointed-error: a refused cell's reason names what to do
    instead, a fitting cell has no rule, and the fleet slice is driven
    through :func:`sparkglm_tpu.glm_fit_fleet` for real (no cell is
    silently ignored).
  * the three fleet axes the lattice legalized — ``penalty=ElasticNet``
    (batched lambda-path kernel), ``engine="sketch"`` (per-member
    sketched Gramian), ``mesh=`` (member-sharded fleet) — each proven
    against its solo oracle: penalized members BIT-identical to
    ``fit_path`` at the padded layout with identical lambda grids,
    sketch members matching the solo sketch fit at the same seed,
    mesh fleets bit-identical to the single-device fleet with equal
    iteration counts.  Serving and serialization compose with zero new
    code paths.
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu import capabilities as caps
from sparkglm_tpu.data.groups import stack_groups
from sparkglm_tpu.fleet import (FleetModel, FleetPathModel, glm_fit_fleet,
                                fit_many)
from sparkglm_tpu.penalized.path import fit_path
from sparkglm_tpu.serve import ModelFamily

pytestmark = pytest.mark.fleet_lattice


def _segments(rng, sizes, p=3):
    groups, Xr, yr = [], [], []
    for g, size in enumerate(sizes):
        X = np.column_stack([np.ones(size),
                             rng.normal(size=(size, p - 1))])
        beta = rng.normal(size=p) * (0.3 + 0.6 * g)
        eta = X @ beta
        y = (rng.random(size) < 1 / (1 + np.exp(-eta))).astype(float)
        groups += [f"g{g}"] * size
        Xr.append(X)
        yr.append(y)
    return np.array(groups), np.vstack(Xr), np.concatenate(yr)


def _stacked(rng, K=3, n=80, p=3):
    X = np.zeros((K, n, p))
    X[..., 0] = 1.0
    X[..., 1:] = rng.normal(size=(K, n, p - 1))
    beta = rng.normal(size=(K, p)) * 0.7
    eta = np.einsum("knp,kp->kn", X, beta)
    y = (rng.random((K, n)) < 1 / (1 + np.exp(-eta))).astype(float)
    return X, y


class TestLatticeTable:
    def test_walk_is_exhaustive_and_every_refusal_is_pointed(self):
        cells = dict(caps.lattice())
        # the full cross product, no cell missing
        n_expected = (len(caps.AXES["design"]) * len(caps.AXES["engine"])
                      * len(caps.AXES["penalty"])
                      * len(caps.AXES["execution"]))
        assert len(cells) == n_expected
        for cell, reason in cells.items():
            if reason is None:
                continue  # fits
            # a POINTED refusal: explains the why and names the
            # supported alternative (use/drop/fit/pass/densify/name)
            assert isinstance(reason, str) and len(reason) > 40, cell
            assert any(w in reason for w in (
                "use ", "drop ", "fit ", "pass ", "densify", "name ",
                "stream", "engine=")), (cell, reason)

    def test_known_cells(self):
        # the three combos PR 20 legalized all FIT
        assert caps.refusal(execution="fleet", penalty="elastic-net") is None
        assert caps.refusal(execution="fleet", engine="sketch") is None
        assert caps.refusal(execution="fleet") is None
        # structural identities stay refused
        assert caps.refusal(design="dense", engine="segment-sum")
        assert caps.refusal(design="structured", engine="exact")
        assert caps.refusal(design="structured", engine="sketch")
        # solo exact dense is the origin cell
        assert caps.refusal() is None

    def test_capability_error_is_typed_and_legible(self):
        with pytest.raises(caps.CapabilityError) as ei:
            caps.check(penalty="elastic-net", execution="mesh")
        e = ei.value
        assert isinstance(e, ValueError)  # old match= idioms keep working
        assert e.cell["penalty"] == "elastic-net"
        assert e.cell["execution"] == "mesh"
        assert e.reason in str(e)
        assert "unsupported capability" in str(e)
        # axis vocabulary is validated, not silently accepted
        with pytest.raises(ValueError, match="engine must be one of"):
            caps.refusal(engine="warp-drive")

    def test_package_exports(self):
        assert sg.CapabilityError is caps.CapabilityError
        assert dict(sg.capability_lattice()) == dict(caps.lattice())
        assert sg.capability_refusal(execution="fleet",
                                     design="sparse") is not None

    def test_fleet_slice_fit_or_refuse(self, rng):
        # drive the fleet execution slice for REAL: every
        # (engine, penalty, mesh) combination either fits to the
        # documented model type or raises the table's CapabilityError
        X, y = _stacked(rng, K=2, n=60)
        enet = sg.ElasticNet(alpha=1.0, n_lambda=6)
        mesh = sg.single_device_mesh()
        kw = dict(family="binomial", has_intercept=True)
        # fits
        assert isinstance(glm_fit_fleet(X, y, **kw), FleetModel)
        assert isinstance(glm_fit_fleet(X, y, engine="sketch", **kw),
                          FleetModel)
        assert isinstance(glm_fit_fleet(X, y, mesh=mesh, **kw), FleetModel)
        assert isinstance(glm_fit_fleet(X, y, engine="sketch", mesh=mesh,
                                        **kw), FleetModel)
        assert isinstance(glm_fit_fleet(X, y, penalty=enet, **kw),
                          FleetPathModel)
        # refusals, all through the central table
        with pytest.raises(caps.CapabilityError, match="mesh"):
            glm_fit_fleet(X, y, penalty=enet, mesh=mesh, **kw)
        with pytest.raises(caps.CapabilityError, match="sketch"):
            glm_fit_fleet(X, y, penalty=enet, engine="sketch", **kw)
        with pytest.raises(caps.CapabilityError, match="elastic"):
            glm_fit_fleet(X, y, engine="elastic", **kw)


class TestPenalizedFleetParity:
    def test_members_bit_identical_to_solo_paths_at_padded_layout(
            self, rng):
        # the tentpole contract: the batched lambda-path kernel is the
        # SOLO path kernel vmapped — at float64 with batch="exact" every
        # member's grid, coefficients and deviance equal a solo fit_path
        # of the same padded row layout EXACTLY
        groups, X, y = _segments(rng, [90, 60, 75])
        labels, Xs, ys, ws, offs, n_real = stack_groups(groups, X, y)
        enet = sg.ElasticNet(alpha=0.9, n_lambda=12)
        fleet = glm_fit_fleet(Xs, ys, weights=ws, penalty=enet,
                              family="binomial", has_intercept=True,
                              labels=labels)
        assert isinstance(fleet, FleetPathModel)
        assert fleet.n_lambda == 12
        for k in range(fleet.n_models):
            solo = fit_path(Xs[k], ys[k], weights=ws[k], penalty=enet,
                            family="binomial", has_intercept=True)
            np.testing.assert_array_equal(fleet.lambdas[k], solo.lambdas)
            np.testing.assert_array_equal(fleet.coefficients[k],
                                          solo.coefficients)
            np.testing.assert_array_equal(fleet.deviance[k], solo.deviance)
            np.testing.assert_array_equal(fleet.df[k], solo.df)
            assert fleet.null_deviance[k] == solo.null_deviance
            # the indexed member is an ordinary PathModel with the same
            # path and the same selection behavior
            pm = fleet[k]
            np.testing.assert_array_equal(pm.coefficients,
                                          solo.coefficients)
            for crit in ("aic", "bic"):
                a = pm.select(criterion=crit)
                b = solo.select(criterion=crit)
                np.testing.assert_array_equal(a.coefficients,
                                              b.coefficients)

    def test_gaussian_gram_branch_matches_solo(self, rng):
        # gaussian/identity takes the fused quad-stats + Gramian-path
        # kernel pair; same bit-identity contract
        K, n, p = 3, 70, 4
        X = np.zeros((K, n, p))
        X[..., 0] = 1.0
        X[..., 1:] = rng.normal(size=(K, n, p - 1))
        y = np.einsum("knp,kp->kn", X, rng.normal(size=(K, p)))
        y += 0.3 * rng.normal(size=(K, n))
        enet = sg.ElasticNet(alpha=1.0, n_lambda=10)
        fleet = glm_fit_fleet(X, y, penalty=enet, family="gaussian",
                              has_intercept=True)
        for k in range(K):
            solo = fit_path(X[k], y[k], penalty=enet, family="gaussian",
                            has_intercept=True)
            np.testing.assert_array_equal(fleet.lambdas[k], solo.lambdas)
            np.testing.assert_array_equal(fleet.coefficients[k],
                                          solo.coefficients)

    def test_formula_front_end_matches_solo_glm(self, rng):
        # glm_fleet(penalty=) member vs sg.glm(penalty=) on the member's
        # own rows: lambda grids identical, coefficients <= 1e-10 (the
        # solo fit runs at the UNPADDED layout, so bit-identity is not
        # the claim here — PARITY.md "layout-held bit-identity")
        n = 240
        seg = rng.choice(["a", "b", "c"], n)
        data = {"x1": rng.normal(size=n), "x2": rng.normal(size=n),
                "seg": seg}
        eta = 0.4 + 0.8 * data["x1"] - 0.5 * data["x2"]
        data["y"] = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
        enet = sg.ElasticNet(alpha=1.0, n_lambda=8)
        fleet = sg.glm_fleet("y ~ x1 + x2", data, groups="seg",
                             family="binomial", penalty=enet)
        assert isinstance(fleet, FleetPathModel)
        assert fleet.formula == "y ~ x1 + x2"
        for lbl in fleet.group_names:
            rows = seg == lbl
            sub = {k: np.asarray(v)[rows] for k, v in data.items()}
            solo = sg.glm("y ~ x1 + x2", sub, family="binomial",
                          penalty=enet)
            k = fleet.index_of(lbl)
            np.testing.assert_allclose(fleet.lambdas[k], solo.lambdas,
                                       rtol=1e-10)
            np.testing.assert_allclose(fleet.coefficients[k],
                                       solo.coefficients,
                                       rtol=1e-10, atol=1e-10)

    def test_select_composes_with_serving(self, rng):
        # select() -> FleetModel -> ModelFamily: ZERO new serving code
        groups, X, y = _segments(rng, [100, 80])
        enet = sg.ElasticNet(alpha=1.0, n_lambda=8)
        path = fit_many(y, X, groups=groups, family="binomial",
                        has_intercept=True, penalty=enet)
        best = path.select(criterion="bic")
        assert isinstance(best, FleetModel)
        assert np.isnan(best.std_errors).all()  # no post-selection Wald
        fam = ModelFamily.from_fleet(best, "lasso")
        Xn = np.column_stack([np.ones(6), rng.normal(size=(6, 2))])
        out = fam.scorer(type="link").score(["g1"] * 6, Xn)
        ref = best.predict(Xn, "g1")
        np.testing.assert_allclose(out, ref, rtol=1e-12)

    def test_roundtrip(self, rng, tmp_path):
        groups, X, y = _segments(rng, [80, 60])
        enet = sg.ElasticNet(alpha=0.8, n_lambda=7)
        path = fit_many(y, X, groups=groups, family="binomial",
                        has_intercept=True, penalty=enet)
        fp = tmp_path / "fleetpath.npz"
        path.save(str(fp))
        back = sg.load_model(str(fp))
        assert isinstance(back, FleetPathModel)
        assert back.group_names == path.group_names
        assert back.penalty.alpha == enet.alpha
        np.testing.assert_array_equal(back.lambdas, path.lambdas)
        np.testing.assert_array_equal(back.coefficients, path.coefficients)
        # a member indexed out of the restored path selects identically
        a = back[0].select(criterion="aic")
        b = path[0].select(criterion="aic")
        np.testing.assert_array_equal(a.coefficients, b.coefficients)


class TestSketchFleetParity:
    def test_members_match_solo_sketch_same_seed(self, rng):
        # the fleet shares ONE base sketch key across members (each
        # member folds in its own iteration counter) — exactly the solo
        # seed semantics, so member k equals a solo engine="sketch" fit
        # of the same padded layout at the same config seed
        X, y = _stacked(rng, K=4, n=90, p=4)
        fleet = glm_fit_fleet(X, y, family="binomial", engine="sketch",
                              has_intercept=True)
        assert fleet.engine == "sketch"
        assert fleet.sketch_dim is not None
        for k in range(len(fleet)):
            solo = sg.glm_fit(X[k], y[k], family="binomial",
                              engine="sketch", has_intercept=True)
            np.testing.assert_allclose(fleet.coefficients[k],
                                       solo.coefficients,
                                       rtol=1e-10, atol=1e-12)
            assert int(fleet.iterations[k]) == int(solo.iterations)
            m = fleet[k]
            assert m.gramian_engine == "sketch"
            assert m.sketch_dim == solo.sketch_dim
            assert np.isnan(m.std_errors).all()  # sketch = point estimates
            assert m.cov_unscaled is None

    def test_sketch_fleet_serves(self, rng):
        X, y = _stacked(rng, K=3, n=80)
        fleet = glm_fit_fleet(X, y, family="binomial", engine="sketch",
                              has_intercept=True,
                              labels=("a", "b", "c"))
        fam = ModelFamily.from_fleet(fleet, "sketchy")
        Xn = np.column_stack([np.ones(5), rng.normal(size=(5, 2))])
        out = fam.scorer(type="response").score(["b"] * 5, Xn)
        ref = fleet.predict(Xn, "b", type="response")
        np.testing.assert_allclose(out, ref, rtol=1e-12)


class TestMeshFleetParity:
    def test_mesh_fleet_bit_identical_to_single_device(self, rng):
        # shard_map over the member axis runs the SAME per-member graph
        # as the single-device kernel — coefficients are bit-identical
        # and iteration counts equal, at any member count (the bucket is
        # rounded up to a per-shard power of two)
        X, y = _stacked(rng, K=5, n=70, p=4)
        mesh = sg.make_mesh()
        n_dev = mesh.shape["data"]
        sharded = glm_fit_fleet(X, y, family="binomial",
                                has_intercept=True, mesh=mesh)
        plain = glm_fit_fleet(X, y, family="binomial", has_intercept=True,
                              bucket=sharded.bucket)
        assert sharded.n_member_shards == n_dev
        assert sharded.bucket % n_dev == 0
        np.testing.assert_array_equal(sharded.coefficients,
                                      plain.coefficients)
        np.testing.assert_array_equal(sharded.std_errors, plain.std_errors)
        np.testing.assert_array_equal(sharded.iterations, plain.iterations)
        np.testing.assert_array_equal(sharded.converged, plain.converged)
        # indexing gathers from the owning shard transparently
        for k in (0, 4):
            np.testing.assert_array_equal(sharded[k].coefficients,
                                          plain[k].coefficients)

    def test_mesh_composes_with_sketch_engine(self, rng):
        X, y = _stacked(rng, K=3, n=80)
        mesh = sg.make_mesh()
        ms = glm_fit_fleet(X, y, family="binomial", engine="sketch",
                           has_intercept=True, mesh=mesh)
        ss = glm_fit_fleet(X, y, family="binomial", engine="sketch",
                           has_intercept=True, bucket=ms.bucket)
        np.testing.assert_array_equal(ms.coefficients, ss.coefficients)
        np.testing.assert_array_equal(ms.iterations, ss.iterations)

    def test_mesh_fleet_online_update_composes(self, rng):
        # the online warm-start path (start=) rides the mesh axis with
        # zero new code: refit warm on the same mesh, same answer as the
        # unsharded warm refit
        X, y = _stacked(rng, K=3, n=80)
        mesh = sg.make_mesh()
        cold = glm_fit_fleet(X, y, family="binomial", has_intercept=True,
                             mesh=mesh)
        warm_m = glm_fit_fleet(X, y, family="binomial", has_intercept=True,
                               mesh=mesh, start=cold.coefficients)
        warm_s = glm_fit_fleet(X, y, family="binomial", has_intercept=True,
                               bucket=cold.bucket,
                               start=cold.coefficients)
        np.testing.assert_array_equal(warm_m.coefficients,
                                      warm_s.coefficients)
        np.testing.assert_array_equal(warm_m.iterations, warm_s.iterations)
