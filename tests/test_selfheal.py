"""Self-healing serving plane + crash-durable online learning.

The contracts under test (ISSUE r15):

  * the replica state machine: healthy -> suspect -> ejected -> probing
    -> healthy, driven by dispatch outcomes through per-replica circuit
    breakers with deterministic half-open probing (fake-clock unit
    tests, no sleeps);
  * graceful degradation: the LAST admissible replica is never ejected,
    and killing one of two replicas loses ZERO in-flight requests — the
    survivor serves f64 bit-identical results with zero recompiles
    across ejection, probing, re-warm and recovery;
  * dispatch protection: hedged re-dispatch past the latency budget
    (first result wins, loser discarded), watchdog abandonment of hung
    calls, re-dispatch to untried replicas only;
  * dead-work shedding: per-request ``deadline=`` sheds expired queued
    work at batch-formation time, and a timed-out ``score``/``asubmit``
    caller cancels its request OUT of the queue (never dispatched);
  * ``Overloaded.retry_after_s`` carries a measured drain-rate hint and
    ``close()`` drains without orphaning futures;
  * the flight recorder triggers on ``replica_ejected``/``auto_recovery``
    with one record per episode;
  * the online loop's write-ahead journal: a loop killed between (or
    inside) chunks resumes at the exact chunk boundary with bit-identical
    suffstats, rings, drift state and deploy decisions — including under
    a real ``SIGKILL``.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from sparkglm_tpu import obs
from sparkglm_tpu.fleet import fit_many
from sparkglm_tpu.obs.metrics import MetricsRegistry
from sparkglm_tpu.obs.slo import FlightRecorder
from sparkglm_tpu.obs.trace import FitTracer, RingBufferSink
from sparkglm_tpu.online import OnlineJournal, OnlineLoop
from sparkglm_tpu.robust import (DeadlineExceeded, FaultPlan, Overloaded,
                                 ReplicaUnavailable)
from sparkglm_tpu.serve import (AsyncEngine, CircuitBreaker, EnginePolicy,
                                HealthPolicy, ModelFamily, ReplicaHealth,
                                family_score_cache_size)

pytestmark = pytest.mark.selfheal

P = 3


class _Clock:
    """Injectable monotone clock for breaker tests — no sleeps."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# policy + breaker unit tests (fake clock, no engine)
# ---------------------------------------------------------------------------

def test_health_policy_validation():
    with pytest.raises(ValueError, match="eject_after"):
        HealthPolicy(eject_after=0)
    with pytest.raises(ValueError, match="probe_cooldown_s"):
        HealthPolicy(probe_cooldown_s=-1)
    with pytest.raises(ValueError, match="probe_successes"):
        HealthPolicy(probe_successes=0)
    with pytest.raises(ValueError, match="call_timeout_s"):
        HealthPolicy(call_timeout_s=0)
    with pytest.raises(ValueError, match="hedge_after_s"):
        HealthPolicy(hedge_after_s=-0.5)
    with pytest.raises(ValueError, match="max_attempts"):
        HealthPolicy(max_attempts=0)
    # a hedge firing after the watchdog declared the call hung is dead
    with pytest.raises(ValueError, match="hedge_after_s must be below"):
        HealthPolicy(call_timeout_s=1.0, hedge_after_s=1.0)


def test_breaker_state_machine_deterministic():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=3, cooldown_s=0.25,
                       probe_successes=2, clock=clk)
    assert b.state == "closed" and b.try_probe()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed" and b.consecutive_failures == 2
    b.record_success()                       # success resets the streak
    assert b.consecutive_failures == 0
    for _ in range(3):
        b.record_failure()
    assert b.state == "open"
    assert not b.try_probe(), "no probe before the cooldown elapses"
    assert b.remaining_cooldown() == pytest.approx(0.25)
    clk.t = 0.2
    assert not b.try_probe()
    clk.t = 0.25                             # deterministic flip point
    assert b.try_probe() and b.state == "half_open"
    assert b.try_probe(), "half-open keeps admitting (engine gates 1-max)"
    b.record_success()
    assert b.state == "half_open", "needs probe_successes=2 clean probes"
    b.record_success()
    assert b.state == "closed" and b.consecutive_failures == 0


def test_breaker_failed_probe_reopens_with_fresh_cooldown():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=1, cooldown_s=1.0, clock=clk)
    b.record_failure()
    assert b.state == "open"
    clk.t = 1.0
    assert b.try_probe() and b.state == "half_open"
    b.record_failure()
    assert b.state == "open"
    assert b.remaining_cooldown() == pytest.approx(1.0), "fresh cooldown"
    # the last-replica guard refuses to open even on a failed probe
    clk.t = 2.0
    assert b.try_probe()
    b.record_failure(allow_open=False)
    assert b.state == "closed"


def test_replica_health_transitions_events_and_rewarm():
    clk = _Clock()
    events = []
    h = ReplicaHealth(2, HealthPolicy(eject_after=2, probe_cooldown_s=0.5),
                      emit=lambda kind, **f: events.append((kind, f)),
                      clock=clk)
    boom = ReplicaUnavailable("boom")
    assert h.states() == {0: "healthy", 1: "healthy"}
    h.on_failure(0, boom)
    assert h.state(0) == "suspect"
    h.on_failure(0, boom)
    assert h.state(0) == "ejected" and h.ejections == 1
    assert h.available() == 1
    assert not h.admit(0), "benched during cooldown"
    assert h.retry_delay(0) == pytest.approx(0.5)
    clk.t = 0.5
    assert h.admit(0) and h.state(0) == "probing"
    assert h.take_rewarm(0), "ejected -> probing flags a re-warm"
    assert not h.take_rewarm(0), "flag is consumed atomically"
    h.on_success(0)
    assert h.state(0) == "healthy" and h.recoveries == 1
    kinds = [k for k, _ in events]
    assert kinds == ["replica_suspect", "replica_ejected", "replica_probe",
                     "auto_recovery"]
    eject = dict(events[1][1])
    assert eject["replica"] == 0 and eject["failures"] == 2
    assert eject["error"] == "ReplicaUnavailable"


def test_last_replica_never_ejected():
    clk = _Clock()
    h = ReplicaHealth(2, HealthPolicy(eject_after=1), clock=clk)
    boom = ReplicaUnavailable("boom")
    h.on_failure(0, boom)
    assert h.state(0) == "ejected"
    for _ in range(20):
        h.on_failure(1, boom)
    assert h.state(1) == "suspect", \
        "the last admissible replica must keep serving"
    assert h.available() == 1 and h.ejections == 1
    # once replica 0 recovers, replica 1 becomes ejectable again
    clk.t = 10.0
    assert h.admit(0)
    h.on_success(0)
    h.on_failure(1, boom)
    assert h.state(1) == "ejected"


# ---------------------------------------------------------------------------
# engine-level protection over duck scorers (no jax in the hot path)
# ---------------------------------------------------------------------------

class _GateScorer:
    """Duck scorer whose calls park on per-call events; ``n_replicas``
    is claimed so the engine runs the multi-replica dispatch plane."""

    metrics = None
    name = "gate"
    n_replicas = 1

    def __init__(self, n_replicas=1):
        self.n_replicas = n_replicas
        self.calls = 0
        self.release = threading.Event()
        self.entered = threading.Event()
        self.block_first = False
        self._lock = threading.Lock()

    def score(self, data, *, offset=None):
        with self._lock:
            self.calls += 1
            mine = self.calls
        self.entered.set()
        if self.block_first and mine == 1:
            assert self.release.wait(30)
        return np.full(data.shape[0], float(mine))


def test_deadline_sheds_expired_queued_work():
    sc = _GateScorer()
    sc.block_first = True
    met = MetricsRegistry()
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), metrics=met,
                      name="gate")
    try:
        plug = eng.submit(np.zeros((1, 2)))          # parks the replica
        assert sc.entered.wait(10)
        doomed = eng.submit(np.zeros((2, 2)), deadline=0.05)
        keeper = eng.submit(np.zeros((1, 2)))        # no deadline
        time.sleep(0.15)                             # deadline passes queued
        sc.release.set()
        assert keeper.result(10) is not None
        with pytest.raises(DeadlineExceeded, match="shed before dispatch"):
            doomed.result(10)
        assert plug.result(10) is not None
    finally:
        sc.release.set()
        eng.close()
    assert sc.calls == 2, "the shed request must never reach the scorer"
    assert met.snapshot()["counters"]["serve.gate.shed"] == 1
    with pytest.raises(ValueError, match="deadline"):
        eng2 = AsyncEngine(_GateScorer())
        try:
            eng2.submit(np.zeros((1, 2)), deadline=0.0)
        finally:
            eng2.close()


def test_score_timeout_cancels_queued_request():
    """Satellite 2: a timed-out blocking caller leaves no dead work —
    the request is removed from the queue and never dispatched."""
    sc = _GateScorer()
    sc.block_first = True
    met = MetricsRegistry()
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), metrics=met,
                      name="gate")
    try:
        plug = eng.submit(np.zeros((1, 2)))
        assert sc.entered.wait(10)
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceeded, match="cancelled out of"):
            eng.score(np.zeros((4, 2)), timeout=0.1)
        assert time.perf_counter() - t0 < 5.0
        sc.release.set()
        assert plug.result(10) is not None
        # a later request still flows (queue state stayed consistent)
        assert eng.score(np.zeros((1, 2)), timeout=10) is not None
    finally:
        sc.release.set()
        eng.close()
    assert sc.calls == 2, "the cancelled request must never be dispatched"
    assert met.snapshot()["counters"]["serve.gate.shed"] == 1


def test_asubmit_timeout_cancels_queued_request():
    sc = _GateScorer()
    sc.block_first = True
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), name="gate")

    async def _go():
        plug = asyncio.ensure_future(eng.asubmit(np.zeros((1, 2))))
        await asyncio.sleep(0)
        assert sc.entered.wait(10)
        with pytest.raises(DeadlineExceeded):
            await eng.asubmit(np.zeros((2, 2)), timeout=0.1)
        sc.release.set()
        assert (await plug) is not None

    try:
        asyncio.run(_go())
    finally:
        sc.release.set()
        eng.close()
    assert sc.calls == 1


def test_overloaded_carries_drain_rate_hint():
    """Satellite 1: after the engine has measured throughput, an
    overload rejection tells the caller WHEN to retry."""
    sc = _GateScorer()
    eng = AsyncEngine(sc, EnginePolicy(max_queue=2, max_wait_ms=0),
                      name="gate")
    try:
        # establish a drain rate with served requests
        for _ in range(3):
            assert eng.score(np.zeros((8, 2)), timeout=10) is not None
        sc.block_first = True
        sc.calls = 0                      # re-arm: next call parks
        sc.entered.clear()
        plug = eng.submit(np.zeros((1, 2)))
        assert sc.entered.wait(10)
        held = [eng.submit(np.zeros((64, 2))) for _ in range(2)]
        with pytest.raises(Overloaded) as ei:
            eng.submit(np.zeros((1, 2)))
        assert ei.value.retry_after_s is not None
        assert 0 < ei.value.retry_after_s <= 60.0
    finally:
        sc.release.set()
        eng.close()
    for f in [plug] + held:
        assert f.result(10) is not None
    # without a measured rate the hint is honestly absent
    assert Overloaded("x").retry_after_s is None


def test_close_drains_queue_without_orphaning():
    """Satellite 1: context-manager close serves (or typed-fails) every
    admitted future — none left pending forever."""
    sc = _GateScorer()
    futs = []
    with AsyncEngine(sc, EnginePolicy(max_wait_ms=0), name="gate") as eng:
        futs = [eng.submit(np.zeros((2, 2))) for _ in range(6)]
    for f in futs:
        assert f.done(), "close() must settle every admitted future"
        assert f.result() is not None
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(np.zeros((1, 2)))


def test_hedged_dispatch_first_result_wins():
    sc = _GateScorer(n_replicas=2)
    sc.block_first = True                     # call 1 parks; call 2 fast
    met = MetricsRegistry()
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), metrics=met,
                      name="gate",
                      health=HealthPolicy(hedge_after_s=0.05))
    try:
        f = eng.submit(np.zeros((3, 2)))
        res = f.result(10)
        # call 1 parks; the hedge (call 2) returns first and must win
        np.testing.assert_array_equal(res, np.full(3, 2.0))
        sc.release.set()                      # let the loser finish
        time.sleep(0.1)
    finally:
        sc.release.set()
        eng.close()
    assert sc.calls == 2, "exactly one hedge was launched"
    snap = met.snapshot()["counters"]
    assert snap["serve.gate.hedges"] == 1
    # the loser contributed no throughput bookkeeping (first-wins)
    assert snap["serve.gate.requests_done"] == 1
    assert snap["serve.gate.batches"] == 1


def test_watchdog_abandons_hung_replica_and_redispatches():
    sc = _GateScorer(n_replicas=2)
    sc.block_first = True                     # call 1 hangs past watchdog
    met = MetricsRegistry()
    ring = RingBufferSink(256)
    tracer = FitTracer([ring])
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), metrics=met,
                      name="gate",
                      health=HealthPolicy(call_timeout_s=0.2))
    try:
        from sparkglm_tpu.obs.trace import ambient
        with ambient(tracer):
            f = eng.submit(np.zeros((2, 2)))
            res = f.result(10)
        np.testing.assert_array_equal(res, np.full(2, 2.0))
        # which replica drew the hung first call depends on scheduler
        # queue order; exactly one of them must now be suspect
        states = sorted(eng.health.states().values())
        assert states == ["healthy", "suspect"]
    finally:
        sc.release.set()
        eng.close()
    assert met.snapshot()["counters"]["serve.gate.redispatches"] == 1
    kinds = [e.kind for e in ring.events]
    assert "replica_hung" in kinds and "redispatch" in kinds


# ---------------------------------------------------------------------------
# chaos e2e: kill one of two replicas under load (real scorer, real jax)
# ---------------------------------------------------------------------------

def _gaussian_family(rng, name):
    groups, Xr, yr = [], [], []
    for g in range(3):
        n = 120
        X = np.column_stack([np.ones(n), rng.normal(size=(n, P - 1))])
        beta = rng.normal(size=P) * (0.5 + 0.3 * g)
        groups += [f"g{g}"] * n
        Xr.append(X)
        yr.append(X @ beta + 0.05 * rng.normal(size=n))
    fleet = fit_many(np.concatenate(yr), np.vstack(Xr),
                     groups=np.array(groups), family="gaussian",
                     has_intercept=True)
    return fleet, ModelFamily.from_fleet(fleet, name)


def _serve_all(eng, X, tenants, n):
    futs = [eng.submit(X, tenant=tenants[i % len(tenants)])
            for i in range(n)]
    return [f.result(30) for f in futs]


def test_kill_one_replica_loses_nothing_bit_identical(rng, tmp_path):
    """The tentpole acceptance: one of two replicas dies mid-load —
    zero in-flight requests fail, the survivor's results are f64
    bit-identical to a healthy run, ejection triggers a flight record,
    and NOTHING recompiles across ejection/probing/re-warm."""
    fleet, fam = _gaussian_family(rng, "chaos")
    tenants = ("g0", "g1", "g2")
    X = np.column_stack([np.ones(4), rng.normal(size=(4, P - 1))])
    devices = jax.devices()[:2]
    mk = dict(type="response", devices=devices, min_bucket=8)
    pol = EnginePolicy(max_batch=64, max_wait_ms=1)

    # healthy oracle run
    rsc_h = fam.replicated_scorer(**mk)
    rsc_h.warmup(buckets=(8, 16, 32, 64))
    with AsyncEngine(rsc_h, pol, name="healthy") as eng:
        healthy = _serve_all(eng, X, tenants, 60)

    # chaos run: replica 0 dead from its first dispatch
    plan = FaultPlan(seed=7, replica_dead_from=((0, 0),))
    tel = obs.Telemetry(str(tmp_path), slos=[])
    rsc = fam.replicated_scorer(**mk)
    assert rsc is rsc_h, "family caches the scorer per options"
    base = family_score_cache_size()
    with AsyncEngine(rsc, pol, name="chaos",
                     telemetry=tel, fault_plan=plan,
                     health=HealthPolicy(eject_after=2,
                                         probe_cooldown_s=0.2)) as eng:
        wounded = _serve_all(eng, X, tenants, 60)
        states = eng.health.states()
        ejections = eng.health.ejections
    tel.close()

    # zero lost requests, bit-identical to the healthy run
    assert len(wounded) == 60
    for a, b in zip(healthy, wounded):
        assert np.asarray(a).dtype == np.float64
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), \
            "R-1 serving must be bit-identical"
    # replica 0 may sit in "probing" if a cooldown elapsed right at the
    # end (the probe would fail again) — never admissible-and-healthy
    assert ejections >= 1 and states[0] in ("ejected", "probing")
    assert states[1] == "healthy", "the survivor stays admissible"
    # recovery/ejection never compiles: warmup prepaid every bucket
    assert rsc.compiles == 0
    assert family_score_cache_size() - base == 0
    # the observability plane saw the episode
    rep = tel.report()["serving"]
    assert rep["replica_ejections"] >= 1
    assert rep["redispatches"] >= 1
    assert rep["requests"] == 60, "every chaos request completed a span"
    assert any("replica_ejected" in r for r in tel.flight_records), \
        "an ejection must dump a flight record"


def test_transient_replica_recovers_rewarmed_zero_compiles(rng):
    """Ejection -> cooldown -> deterministic probe -> re-warm ->
    auto_recovery, with the kernel cache untouched end to end."""
    fleet, fam = _gaussian_family(rng, "recov")
    X = np.column_stack([np.ones(4), rng.normal(size=(4, P - 1))])
    devices = jax.devices()[:2]
    rsc = fam.replicated_scorer(type="response", devices=devices,
                                min_bucket=8)
    rsc.warmup(buckets=(8, 16, 32, 64))
    # two injected failures on replica 0, healthy afterwards
    plan = FaultPlan(seed=3, replica_error_at=((0, 0), (0, 1)))
    ring = RingBufferSink(2048)
    tracer = FitTracer([ring])
    from sparkglm_tpu.obs.trace import ambient
    base = family_score_cache_size()
    with AsyncEngine(rsc, EnginePolicy(max_batch=64, max_wait_ms=1),
                     name="recov", fault_plan=plan,
                     health=HealthPolicy(eject_after=2,
                                         probe_cooldown_s=0.1)) as eng:
        with ambient(tracer):
            _serve_all(eng, X, ("g0", "g1", "g2"), 20)
            deadline = time.perf_counter() + 20
            while (eng.health.recoveries == 0
                   and time.perf_counter() < deadline):
                _serve_all(eng, X, ("g0", "g1", "g2"), 6)
                time.sleep(0.05)
        assert eng.health.recoveries >= 1
        assert eng.health.state(0) == "healthy"
    kinds = [e.kind for e in ring.events]
    assert "replica_ejected" in kinds
    assert "replica_probe" in kinds
    assert "replica_rewarm" in kinds
    assert "auto_recovery" in kinds
    assert kinds.index("replica_rewarm") > kinds.index("replica_probe")
    rewarm = next(e for e in ring.events if e.kind == "replica_rewarm")
    assert rewarm.fields["compiles"] == 0, "re-warm must be prepaid"
    assert rsc.compiles == 0
    assert family_score_cache_size() - base == 0


# ---------------------------------------------------------------------------
# fault plan: serving-time kinds
# ---------------------------------------------------------------------------

def test_fault_plan_serving_schedules_are_seeded_and_typed():
    plan = FaultPlan(seed=1, replica_error_at=((0, 1),),
                     replica_dead_from=((1, 2),), replica_slow_at=((0, 2),),
                     slow_s=0.01)
    plan.on_dispatch(0)                       # (0, 0): clean
    with pytest.raises(ReplicaUnavailable, match="replica 0, dispatch 1"):
        plan.on_dispatch(0)                   # (0, 1): injected error
    t0 = time.perf_counter()
    plan.on_dispatch(0)                       # (0, 2): slow straggler
    assert time.perf_counter() - t0 >= 0.01
    plan.on_dispatch(0)                       # errors fire once
    plan.on_dispatch(1)
    plan.on_dispatch(1)                       # (1, 0..1): clean
    for _ in range(3):                        # (1, 2...): dead forever
        with pytest.raises(ReplicaUnavailable, match="dead"):
            plan.on_dispatch(1)
    plan.on_online_chunk(5)                   # empty kill schedule: no-op


def test_run_forwards_fault_plan_to_chunk_boundaries(rng):
    class _Recorder:
        calls = ()

        def __init__(self):
            self.calls = []

        def on_online_chunk(self, idx):
            self.calls.append(idx)

    loop = _tiny_loop(rng)
    chunks = [_tiny_chunk(rng, s) for s in range(3)]
    plan = _Recorder()
    loop.run(lambda: iter(chunks), fault_plan=plan)
    assert plan.calls == [1, 2, 3], "absolute chunk ordinals, pre-apply"


# ---------------------------------------------------------------------------
# flight recorder: ejection/recovery triggers (satellite 3)
# ---------------------------------------------------------------------------

def test_flight_recorder_triggers_on_ejection_and_recovery(tmp_path):
    rec = FlightRecorder(str(tmp_path), capacity=16, cooldown_s=0.0)
    tr = FitTracer([rec])
    tr.emit("batch", rows=4)
    tr.emit("replica_ejected", replica=0, failures=3,
            error="ReplicaUnavailable")
    assert len(rec.records) == 1 and "replica_ejected" in rec.records[0]
    tr.emit("auto_recovery", replica=0, probes=1)
    assert len(rec.records) == 2 and "auto_recovery" in rec.records[1]
    body = [json.loads(ln)
            for ln in open(rec.records[0]).read().splitlines()[1:]]
    assert [e["kind"] for e in body] == ["batch", "replica_ejected"], \
        "the ring holds the dispatches that burned the breaker"


def test_flight_recorder_one_record_per_ejection_episode(tmp_path):
    """Cooldown semantics match slo_violation: an ejection storm dumps
    once per kind per window, not once per event."""
    rec = FlightRecorder(str(tmp_path), capacity=8, cooldown_s=1e6)
    tr = FitTracer([rec])
    tr.emit("replica_ejected", replica=0)
    tr.emit("replica_ejected", replica=1)     # inside cooldown: suppressed
    assert len(rec.records) == 1
    tr.emit("auto_recovery", replica=0)       # different kind: dumps
    assert len(rec.records) == 2
    tr.emit("auto_recovery", replica=1)
    assert len(rec.records) == 2


# ---------------------------------------------------------------------------
# crash-durable online learning: the write-ahead journal
# ---------------------------------------------------------------------------

def _tiny_labels():
    return tuple(f"t{i:02d}" for i in range(4))


def _tiny_beta():
    return np.random.default_rng(11).normal(size=(4, P))


def _tiny_chunk(rng_or_seed, s, shift=0.0):
    r = np.random.default_rng(1000 + s)
    labels, beta = _tiny_labels(), _tiny_beta()
    ten, Xs, ys = [], [], []
    for k, t in enumerate(labels):
        X = r.normal(size=(12, P))
        ten.extend([t] * 12)
        Xs.append(X)
        ys.append(X @ (beta[k] + shift) + 0.05 * r.normal(size=12))
    return np.array(ten), np.concatenate(Xs), np.concatenate(ys)


def _tiny_loop(rng, journal=None, **kw):
    labels, beta = _tiny_labels(), _tiny_beta()
    r = np.random.default_rng(0)
    X = r.normal(size=(4, 48, P))
    y = np.stack([X[k] @ beta[k] + 0.05 * r.normal(size=48)
                  for k in range(4)])
    from sparkglm_tpu.fleet import glm_fit_fleet
    fleet = glm_fit_fleet(X, y, family="gaussian", link="identity",
                          labels=labels)
    fam = ModelFamily.from_fleet(fleet, "j")
    return OnlineLoop(fam, rho=0.9, window_rows=24, journal=journal, **kw)


def _loop_fingerprint(loop):
    t, B = loop.family.deployed_matrix()
    versions = {x: loop.family.deployed_version(x) for x in t}
    return dict(
        chunks=loop._chunks,
        suffstats=loop.suffstats.digest(),
        rings=[getattr(loop, a).tobytes().hex()[:32]
               for a in ("_Xw", "_yw", "_ww", "_ow", "_pos")],
        gate=json.dumps(loop.gate._export(), sort_keys=True),
        watch=json.dumps(loop._watch, sort_keys=True),
        deployed=B.tobytes().hex()[:64], versions=versions)


def test_journal_write_ahead_then_snapshot_prunes(rng, tmp_path):
    d = str(tmp_path / "j")
    loop = _tiny_loop(rng, journal=OnlineJournal(d, snapshot_every=3))
    # attach wrote the base snapshot before any chunk
    assert loop.journal.latest_snapshot()[0] == 0
    for s in range(4):
        loop.step(*_tiny_chunk(rng, s, shift=0.2 * s))
    files = sorted(os.listdir(d))
    # snapshot at chunk 3 pruned records 1..3 and the chunk-0 snapshot;
    # chunk 4's write-ahead record survives
    assert files == ["chunk-000004.npz", "snapshot-000003.npz"]
    ten, X, y, w, off = OnlineJournal.load_record(
        os.path.join(d, "chunk-000004.npz"))
    assert X.shape == (48, P) and w.shape == (48,) and len(ten) == 48
    rep = loop.report()["online"]
    assert rep["journal_appends"] == 4
    assert rep["journal_snapshots"] == 2      # attach + chunk 3


def test_journal_resume_is_bit_identical_to_uninterrupted(rng, tmp_path):
    chunks = [_tiny_chunk(rng, s, shift=0.15 * s) for s in range(9)]
    healthy = _tiny_loop(rng)
    for c in chunks:
        healthy.step(*c)

    d = str(tmp_path / "j")
    doomed = _tiny_loop(rng, journal=OnlineJournal(d, snapshot_every=4))
    for c in chunks[:6]:
        doomed.step(*c)
    del doomed                               # "crash" between chunks 6 and 7

    resumed = OnlineLoop.resume(OnlineJournal(d, snapshot_every=4))
    assert resumed._chunks == 6, "resume lands at the exact chunk boundary"
    for c in chunks[6:]:
        resumed.step(*c)
    assert _loop_fingerprint(resumed) == _loop_fingerprint(healthy), \
        "post-crash resume must be bit-identical (suffstats, rings, " \
        "gate, watches, deploy decisions)"


def test_journal_resume_without_snapshot_is_typed(tmp_path):
    with pytest.raises(FileNotFoundError, match="no snapshot"):
        OnlineLoop.resume(str(tmp_path / "empty"))


_KILL_SCRIPT = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from sparkglm_tpu.fleet import glm_fit_fleet
from sparkglm_tpu.serve import ModelFamily
from sparkglm_tpu.online import OnlineJournal, OnlineLoop
from sparkglm_tpu.robust import FaultPlan

P = 3
labels = tuple(f"t{i:02d}" for i in range(4))
beta = np.random.default_rng(11).normal(size=(4, P))

def chunk(s):
    r = np.random.default_rng(1000 + s)
    ten, Xs, ys = [], [], []
    for k, t in enumerate(labels):
        X = r.normal(size=(12, P))
        ten.extend([t] * 12)
        Xs.append(X)
        ys.append(X @ (beta[k] + 0.15 * s) + 0.05 * r.normal(size=12))
    return np.array(ten), np.concatenate(Xs), np.concatenate(ys)

def seed_loop(journal=None):
    r = np.random.default_rng(0)
    X = r.normal(size=(4, 48, P))
    y = np.stack([X[k] @ beta[k] + 0.05 * r.normal(size=48)
                  for k in range(4)])
    fleet = glm_fit_fleet(X, y, family="gaussian", link="identity",
                          labels=labels)
    return OnlineLoop(ModelFamily.from_fleet(fleet, "j"), rho=0.9,
                      window_rows=24, journal=journal)

def fingerprint(loop):
    t, B = loop.family.deployed_matrix()
    return dict(chunks=loop._chunks, suffstats=loop.suffstats.digest(),
                deployed=B.tobytes().hex(),
                versions={x: loop.family.deployed_version(x) for x in t},
                gate=json.dumps(loop.gate._export(), sort_keys=True))

mode, jdir, out = sys.argv[1], sys.argv[2], sys.argv[3]
N = 8
chunks = [chunk(s) for s in range(N)]
if mode == "healthy":
    loop = seed_loop()
    for c in chunks:
        loop.step(*c)
elif mode == "killed":
    loop = seed_loop(journal=OnlineJournal(jdir, snapshot_every=3))
    # SIGKILL fires at the chunk-5 boundary, BEFORE chunk 5 applies
    loop.run(lambda: iter(chunks), fault_plan=FaultPlan(
        seed=0, kill_chunk_at=(5,)))
    raise SystemExit("unreachable: the kill must fire")
elif mode == "resume":
    loop = OnlineLoop.resume(OnlineJournal(jdir, snapshot_every=3))
    assert loop._chunks == 4, f"expected chunk boundary 4, got {loop._chunks}"
    for c in chunks[loop._chunks:]:
        loop.step(*c)
else:
    raise SystemExit(f"bad mode {mode}")
with open(out, "w") as f:
    json.dump(fingerprint(loop), f, sort_keys=True)
"""


def test_online_loop_survives_sigkill_bit_identical(tmp_path):
    """The ISSUE's kill test, with a REAL ``SIGKILL``: journal a run,
    kill -9 the process between chunks, resume in a fresh process, and
    reproduce the healthy run's statistics and deploy decisions
    bit-for-bit."""
    script = tmp_path / "kill_child.py"
    script.write_text(_KILL_SCRIPT)
    jdir = str(tmp_path / "journal")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    def run(mode, out):
        return subprocess.run(
            [sys.executable, str(script), mode, jdir, str(out)],
            env=env, capture_output=True, text=True, timeout=300)

    h = run("healthy", tmp_path / "healthy.json")
    assert h.returncode == 0, h.stderr[-2000:]

    k = run("killed", tmp_path / "killed.json")
    assert k.returncode == -signal.SIGKILL, \
        f"expected SIGKILL, got rc={k.returncode}: {k.stderr[-2000:]}"
    assert not (tmp_path / "killed.json").exists()
    assert any(f.startswith("snapshot-") for f in os.listdir(jdir))

    r = run("resume", tmp_path / "resumed.json")
    assert r.returncode == 0, r.stderr[-2000:]

    healthy = json.loads((tmp_path / "healthy.json").read_text())
    resumed = json.loads((tmp_path / "resumed.json").read_text())
    assert resumed == healthy, \
        "resume after SIGKILL must reproduce the healthy run bit-for-bit"
