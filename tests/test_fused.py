"""Fused single-pass Fisher engine: parity with the einsum engine.

The Pallas kernel itself needs a TPU; these tests exercise the identical-math
XLA twin (ops/fused.py::fused_fisher_pass_ref) through the same
``_irls_fused_kernel`` shard_map driver on the virtual 8-device CPU mesh,
mirroring the reference's 1-vs-4-partition equivalence tests
(lmPredict$Test.scala:11-35).
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from oracle import irls_np


def _logistic_data(rng, n=4000, p=7):
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    return X, y


@pytest.mark.parametrize("family,link", [
    ("binomial", "logit"),
    ("poisson", "log"),
    ("gamma", "log"),
    ("gaussian", "identity"),
])
def test_fused_matches_einsum(mesh8, rng, family, link):
    X, ybin = _logistic_data(rng)
    n = X.shape[0]
    y = ybin if family == "binomial" else np.abs(X @ np.full(X.shape[1], 0.1)) + rng.uniform(0.5, 1.5, n)
    if family == "poisson":
        y = np.round(y)
    w = rng.uniform(0.5, 2.0, size=n)
    off = 0.05 * rng.normal(size=n)
    # absolute 1e-12: at dev >> 1 it is tighter than relative 1e-12, and the
    # engine-equivalence comparison below needs both fully converged
    kw = dict(family=family, link=link, weights=w, offset=off,
              tol=1e-12, criterion="absolute", max_iter=60, mesh=mesh8)
    m_e = sg.glm_fit(X, y, engine="einsum", **kw)
    m_f = sg.glm_fit(X, y, engine="fused", **kw)
    np.testing.assert_allclose(m_f.coefficients, m_e.coefficients,
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(m_f.std_errors, m_e.std_errors, rtol=1e-8)
    np.testing.assert_allclose(m_f.deviance, m_e.deviance, rtol=1e-10)
    np.testing.assert_allclose(m_f.null_deviance, m_e.null_deviance, rtol=1e-10)
    np.testing.assert_allclose(m_f.aic, m_e.aic, rtol=1e-8)
    assert m_f.converged


def test_fused_1_vs_8_devices(mesh1, mesh8, rng):
    X, y = _logistic_data(rng)
    m1 = sg.glm_fit(X, y, engine="fused", tol=1e-12, mesh=mesh1)
    m8 = sg.glm_fit(X, y, engine="fused", tol=1e-12, mesh=mesh8)
    np.testing.assert_allclose(m1.coefficients, m8.coefficients,
                               rtol=1e-9, atol=1e-12)


def test_fused_matches_numpy_oracle(mesh8, rng):
    X, y = _logistic_data(rng)
    m = sg.glm_fit(X, y, engine="fused", tol=1e-12, max_iter=60, mesh=mesh8)
    beta_ref, dev_ref, _, _ = irls_np(X, y, "binomial", "logit")
    np.testing.assert_allclose(m.coefficients, beta_ref, rtol=1e-7, atol=1e-9)
    np.testing.assert_allclose(m.deviance, dev_ref, rtol=1e-9)


def test_fused_binomial_m_groups(mesh8, rng):
    """Group sizes m through the fused path (the reference dropped to a
    single partition for this, GLM.scala:640-642)."""
    n, p = 3000, 5
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    bt = rng.normal(size=p) / 4
    mgrp = rng.integers(1, 20, size=n).astype(float)
    prob = 1 / (1 + np.exp(-(X @ bt)))
    counts = rng.binomial(mgrp.astype(int), prob).astype(float)
    kw = dict(family="binomial", m=mgrp, tol=1e-12, max_iter=60, mesh=mesh8)
    m_e = sg.glm_fit(X, counts, engine="einsum", **kw)
    m_f = sg.glm_fit(X, counts, engine="fused", **kw)
    np.testing.assert_allclose(m_f.coefficients, m_e.coefficients,
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(m_f.loglik, m_e.loglik, rtol=1e-8)


@pytest.mark.parametrize("family,link,first", [
    ("binomial", "logit", True),
    ("binomial", "logit", False),
    ("poisson", "log", False),
    ("gamma", "inverse", False),
])
def test_pallas_kernel_interpret_matches_ref(rng, family, link, first):
    """The MOSAIC CODE PATH's math, exercised every CI round via the Pallas
    interpreter (VERDICT r1 weak #2: the kernel had never been executed by
    any test) — same grid/BlockSpecs/accumulation as the TPU kernel, checked
    against the XLA twin."""
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.ops.fused import fused_fisher_pass, fused_fisher_pass_ref
    import jax.numpy as jnp

    fam, lnk = resolve(family, link)
    n, p = 1024, 12
    X = rng.normal(size=(n, p)).astype(np.float32)
    X[:, 0] = 1.0
    if family == "binomial":
        y = (rng.random(n) < 0.5).astype(np.float32)
    else:
        y = (np.abs(X @ np.full(p, 0.05)) + rng.uniform(0.5, 1.5, n)).astype(np.float32)
        if family == "poisson":
            y = np.round(y)
    wt = rng.uniform(0.0, 2.0, n).astype(np.float32)  # includes zero weights
    off = (0.05 * rng.normal(size=n)).astype(np.float32)
    beta = (rng.normal(size=p) / 10).astype(np.float32)
    if link == "inverse":
        # keep eta bounded away from 0: mu = 1/eta must stay well-scaled or
        # f32 accumulation-order noise swamps the parity check
        beta[0] = 1.0
    args = (jnp.asarray(X), jnp.asarray(y), jnp.asarray(wt), jnp.asarray(off),
            jnp.asarray(beta))
    got = fused_fisher_pass(*args, family=fam, link=lnk, first=first,
                            block_rows=256, interpret=True)
    ref = fused_fisher_pass_ref(*args, family=fam, link=lnk, first=first,
                                block_rows=256)
    for g, r, tol in zip(got, ref, (2e-5, 2e-5, 2e-5)):
        scale = max(float(jnp.max(jnp.abs(r))), 1.0)
        np.testing.assert_allclose(np.asarray(g, np.float64),
                                   np.asarray(r, np.float64),
                                   atol=tol * scale, rtol=0)


def test_fused_rejects_feature_sharding(mesh42, rng):
    X, y = _logistic_data(rng, n=800)
    with pytest.raises(ValueError, match="fused"):
        sg.glm_fit(X, y, engine="fused", mesh=mesh42, shard_features=True)


def test_engine_validated(mesh1, rng):
    X, y = _logistic_data(rng, n=200)
    with pytest.raises(ValueError, match="engine"):
        sg.glm_fit(X, y, engine="warp", mesh=mesh1)
