"""Fused single-pass Fisher engine: parity with the einsum engine.

The Pallas kernel itself needs a TPU; these tests exercise the identical-math
XLA twin (ops/fused.py::fused_fisher_pass_ref) through the same
``_irls_fused_kernel`` shard_map driver on the virtual 8-device CPU mesh,
mirroring the reference's 1-vs-4-partition equivalence tests
(lmPredict$Test.scala:11-35).
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from oracle import irls_np


def _logistic_data(rng, n=4000, p=7):
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    return X, y


@pytest.mark.parametrize("family,link", [
    ("binomial", "logit"),
    ("poisson", "log"),
    ("gamma", "log"),
    ("gaussian", "identity"),
])
def test_fused_matches_einsum(mesh8, rng, family, link):
    X, ybin = _logistic_data(rng)
    n = X.shape[0]
    y = ybin if family == "binomial" else np.abs(X @ np.full(X.shape[1], 0.1)) + rng.uniform(0.5, 1.5, n)
    if family == "poisson":
        y = np.round(y)
    w = rng.uniform(0.5, 2.0, size=n)
    off = 0.05 * rng.normal(size=n)
    # absolute 1e-12: at dev >> 1 it is tighter than relative 1e-12, and the
    # engine-equivalence comparison below needs both fully converged
    kw = dict(family=family, link=link, weights=w, offset=off,
              tol=1e-12, criterion="absolute", max_iter=60, mesh=mesh8)
    m_e = sg.glm_fit(X, y, engine="einsum", **kw)
    m_f = sg.glm_fit(X, y, engine="fused", **kw)
    np.testing.assert_allclose(m_f.coefficients, m_e.coefficients,
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(m_f.std_errors, m_e.std_errors, rtol=1e-8)
    np.testing.assert_allclose(m_f.deviance, m_e.deviance, rtol=1e-10)
    np.testing.assert_allclose(m_f.null_deviance, m_e.null_deviance, rtol=1e-10)
    np.testing.assert_allclose(m_f.aic, m_e.aic, rtol=1e-8)
    assert m_f.converged


def test_fused_1_vs_8_devices(mesh1, mesh8, rng):
    X, y = _logistic_data(rng)
    m1 = sg.glm_fit(X, y, engine="fused", tol=1e-12, mesh=mesh1)
    m8 = sg.glm_fit(X, y, engine="fused", tol=1e-12, mesh=mesh8)
    np.testing.assert_allclose(m1.coefficients, m8.coefficients,
                               rtol=1e-9, atol=1e-12)


def test_fused_matches_numpy_oracle(mesh8, rng):
    X, y = _logistic_data(rng)
    m = sg.glm_fit(X, y, engine="fused", tol=1e-12, max_iter=60, mesh=mesh8)
    beta_ref, dev_ref, _, _ = irls_np(X, y, "binomial", "logit")
    np.testing.assert_allclose(m.coefficients, beta_ref, rtol=1e-7, atol=1e-9)
    np.testing.assert_allclose(m.deviance, dev_ref, rtol=1e-9)


def test_fused_binomial_m_groups(mesh8, rng):
    """Group sizes m through the fused path (the reference dropped to a
    single partition for this, GLM.scala:640-642)."""
    n, p = 3000, 5
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    bt = rng.normal(size=p) / 4
    mgrp = rng.integers(1, 20, size=n).astype(float)
    prob = 1 / (1 + np.exp(-(X @ bt)))
    counts = rng.binomial(mgrp.astype(int), prob).astype(float)
    kw = dict(family="binomial", m=mgrp, tol=1e-12, max_iter=60, mesh=mesh8)
    m_e = sg.glm_fit(X, counts, engine="einsum", **kw)
    m_f = sg.glm_fit(X, counts, engine="fused", **kw)
    np.testing.assert_allclose(m_f.coefficients, m_e.coefficients,
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(m_f.loglik, m_e.loglik, rtol=1e-8)


@pytest.mark.parametrize("family,link,first", [
    ("binomial", "logit", True),
    ("binomial", "logit", False),
    ("poisson", "log", False),
    ("gamma", "inverse", False),
])
def test_pallas_kernel_interpret_matches_ref(rng, family, link, first):
    """The MOSAIC CODE PATH's math, exercised every CI round via the Pallas
    interpreter (VERDICT r1 weak #2: the kernel had never been executed by
    any test) — same grid/BlockSpecs/accumulation as the TPU kernel, checked
    against the XLA twin."""
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.ops.fused import fused_fisher_pass, fused_fisher_pass_ref
    import jax.numpy as jnp

    fam, lnk = resolve(family, link)
    n, p = 1024, 12
    X = rng.normal(size=(n, p)).astype(np.float32)
    X[:, 0] = 1.0
    if family == "binomial":
        y = (rng.random(n) < 0.5).astype(np.float32)
    else:
        y = (np.abs(X @ np.full(p, 0.05)) + rng.uniform(0.5, 1.5, n)).astype(np.float32)
        if family == "poisson":
            y = np.round(y)
    wt = rng.uniform(0.0, 2.0, n).astype(np.float32)  # includes zero weights
    off = (0.05 * rng.normal(size=n)).astype(np.float32)
    beta = (rng.normal(size=p) / 10).astype(np.float32)
    if link == "inverse":
        # keep eta bounded away from 0: mu = 1/eta must stay well-scaled or
        # f32 accumulation-order noise swamps the parity check
        beta[0] = 1.0
    args = (jnp.asarray(X), jnp.asarray(y), jnp.asarray(wt), jnp.asarray(off),
            jnp.asarray(beta))
    got = fused_fisher_pass(*args, family=fam, link=lnk, first=first,
                            block_rows=256, interpret=True)
    ref = fused_fisher_pass_ref(*args, family=fam, link=lnk, first=first,
                                block_rows=256)
    for g, r, tol in zip(got, ref, (2e-5, 2e-5, 2e-5)):
        scale = max(float(jnp.max(jnp.abs(r))), 1.0)
        np.testing.assert_allclose(np.asarray(g, np.float64),
                                   np.asarray(r, np.float64),
                                   atol=tol * scale, rtol=0)


def test_fused_rejects_feature_sharding(mesh42, rng):
    X, y = _logistic_data(rng, n=800)
    with pytest.raises(ValueError, match="fused"):
        sg.glm_fit(X, y, engine="fused", mesh=mesh42, shard_features=True)


def test_engine_validated(mesh1, rng):
    X, y = _logistic_data(rng, n=200)
    with pytest.raises(ValueError, match="engine"):
        sg.glm_fit(X, y, engine="warp", mesh=mesh1)


def test_bf16_warmup_schedule_matches_plain(rng, mesh8):
    """Mixed-precision schedule (config.bf16_warmup): bf16 warm-up passes
    hand over to f32 at bf16_switch_tol, so the FINAL coefficients match
    the plain fused engine at its normal tolerance — the accuracy
    contract that makes the half-HBM warm-up shippable."""
    from sparkglm_tpu.config import NumericConfig

    n, p = 40_000, 12
    X = np.column_stack([np.ones(n),
                         rng.standard_normal((n, p - 1))]).astype(np.float32)
    bt = (rng.standard_normal(p) / np.sqrt(p)).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float32)

    kw = dict(family="binomial", tol=1e-8, criterion="relative", mesh=mesh8,
              engine="fused")
    plain = sg.glm_fit(X, y, **kw)
    mixed = sg.glm_fit(X, y, config=NumericConfig(bf16_warmup=True), **kw)
    assert mixed.converged
    np.testing.assert_allclose(mixed.coefficients, plain.coefficients,
                               rtol=0, atol=5e-6)
    np.testing.assert_allclose(mixed.std_errors, plain.std_errors,
                               rtol=1e-4)
    assert mixed.deviance == pytest.approx(plain.deviance, rel=1e-6)
    # the schedule runs real warm-up iterations plus >=1 f32 iteration,
    # and reports the total
    assert mixed.iterations >= plain.iterations


def test_bf16_fused_pass_parity(rng):
    """ops-level: the fused pass accepts bf16 X; results match the f32
    pass at bf16 input-rounding tolerance, accumulators are f32."""
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.ops.fused import fused_fisher_pass_ref

    fam, lnk = resolve("binomial", "logit")
    n, p = 4096, 16
    X = rng.standard_normal((n, p)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    wt = np.ones(n, np.float32)
    off = np.zeros(n, np.float32)
    beta = (rng.standard_normal(p) * 0.1).astype(np.float32)
    import jax.numpy as jnp
    G32, b32, d32 = fused_fisher_pass_ref(
        jnp.asarray(X), jnp.asarray(y), jnp.asarray(wt), jnp.asarray(off),
        jnp.asarray(beta), family=fam, link=lnk)
    Gb, bb, db = fused_fisher_pass_ref(
        jnp.asarray(X).astype(jnp.bfloat16), jnp.asarray(y),
        jnp.asarray(wt), jnp.asarray(off), jnp.asarray(beta),
        family=fam, link=lnk)
    assert Gb.dtype == jnp.float32 and bb.dtype == jnp.float32
    assert float(jnp.max(jnp.abs(Gb - G32)) / jnp.max(jnp.abs(G32))) < 5e-3
    assert float(abs(db - d32) / abs(d32)) < 1e-3


def test_bf16_warmup_honours_max_iter(rng, mesh8):
    """A warm-up that spends the whole budget must not run unbudgeted f32
    passes: iterations <= max_iter, converged=False at the user tol."""
    from sparkglm_tpu.config import NumericConfig

    n, p = 20_000, 8
    X = np.column_stack([np.ones(n),
                         rng.standard_normal((n, p - 1))]).astype(np.float32)
    bt = (rng.standard_normal(p) / np.sqrt(p)).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float32)
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = sg.glm_fit(X, y, family="binomial", engine="fused", max_iter=2,
                       tol=1e-12, criterion="relative", mesh=mesh8,
                       config=NumericConfig(bf16_warmup=True))
    assert m.iterations <= 2
    assert not m.converged


def test_pallas_kernel_traced_theta_interpret(rng):
    """Negbin theta rides the Mosaic kernel as a TRACED (1,1) SMEM operand
    (VERDICT r4 #5): the Pallas code path (interpreter) matches the XLA
    twin at two theta values WITHOUT retracing — one jitted kernel serves
    the whole theta search."""
    import jax.numpy as jnp
    from sparkglm_tpu.families.families import resolve
    from sparkglm_tpu.ops.fused import fused_fisher_pass, fused_fisher_pass_ref

    fam, lnk = resolve("negative_binomial(2.0)", "log")
    n, p = 1024, 8
    X = rng.normal(size=(n, p)).astype(np.float32)
    X[:, 0] = 1.0
    mu = np.exp(np.abs(X @ np.full(p, 0.05)))
    y = rng.negative_binomial(2.0, 2.0 / (2.0 + mu)).astype(np.float32)
    wt = rng.uniform(0.0, 2.0, n).astype(np.float32)
    off = (0.05 * rng.normal(size=n)).astype(np.float32)
    beta = (rng.normal(size=p) / 10).astype(np.float32)
    args = (jnp.asarray(X), jnp.asarray(y), jnp.asarray(wt), jnp.asarray(off),
            jnp.asarray(beta))
    for theta in (0.7, 3.5):
        fp = jnp.asarray(theta, jnp.float32)
        got = fused_fisher_pass(*args, family=fam, link=lnk, first=False,
                                block_rows=256, interpret=True, fam_param=fp)
        ref = fused_fisher_pass_ref(*args, family=fam, link=lnk, first=False,
                                    block_rows=256, fam_param=fp)
        for g, r in zip(got, ref):
            scale = max(float(jnp.max(jnp.abs(r))), 1.0)
            np.testing.assert_allclose(np.asarray(g, np.float64),
                                       np.asarray(r, np.float64),
                                       atol=2e-5 * scale, rtol=0)
    # forgetting the param fails loudly at the boundary
    with pytest.raises(ValueError, match="parametric"):
        fused_fisher_pass(*args, family=fam, link=lnk, first=False,
                          block_rows=256, interpret=True)
