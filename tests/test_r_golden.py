"""Golden-output parity against R glm() (VERDICT r1 missing #3/#4).

Two assertion tiers per case from ``tests/fixtures/r_golden.json``:
  * ``r_doc`` values — numbers R itself prints in its ?glm documentation
    (real R provenance, asserted at the precision R printed them);
  * ``fit`` values — full-precision R-semantics outputs from the independent
    float64 generator (tests/fixtures/gen_golden.py; verify with
    tests/fixtures/make_r_golden.R wherever R is installed).

This is the reference's own test pattern — golden-value summary comparison
(/root/reference/R/pkg/tests/testthat/test_LM.R:44) — pointed at correct
oracle numbers instead of its recorded-against-buggy-output string.
"""

import json
import os

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.models import glm as glm_mod

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "r_golden.json")

with open(FIXTURES) as f:
    GOLDEN = json.load(f)


def _design(case):
    """Rebuild (X, y, kwargs) for a fixture case."""
    d = case["data"]
    fam, link = case["family"], case["link"]
    kw = dict(family=fam, link=link, tol=1e-12, criterion="relative",
              max_iter=200)
    if "counts" in d:  # dobson: outcome/treatment dummies
        o = np.tile([(0, 0), (1, 0), (0, 1)], (3, 1))
        t = np.repeat([(0, 0), (1, 0), (0, 1)], 3, axis=0)
        X = np.column_stack([np.ones(9), o, t])
        y = np.asarray(d["counts"], float)
    elif "u" in d:
        u = np.asarray(d["u"], float)
        X = np.column_stack([np.ones(len(u)), np.log(u)])
        y = np.asarray(d.get("lot1", d.get("lot2")), float)
    elif "successes" in d:
        x1 = np.asarray(d["x1"], float)
        X = np.column_stack([np.ones(len(x1)), x1])
        y = np.asarray(d["successes"], float)
        kw["m"] = np.asarray(d["m"], float)
    elif "exposure" in d:
        x1 = np.asarray(d["x1"], float)
        X = np.column_stack([np.ones(len(x1)), x1])
        y = np.asarray(d["y"], float)
        kw["offset"] = np.log(np.asarray(d["exposure"], float))
    else:
        xcol = d.get("x1", d.get("x"))
        x1 = np.asarray(xcol, float)
        if case.get("no_intercept"):
            X = x1[:, None]
            kw["has_intercept"] = False
        else:
            X = np.column_stack([np.ones(len(x1)), x1])
        y = np.asarray(d["y"], float)
        if "w" in d:
            kw["weights"] = np.asarray(d["w"], float)
    return X, y, kw


# formula_cases / penalized_cases / sparse_cases / robust_cases are nested
# case GROUPS with their own suites (test_r_golden_formula.py /
# test_penalized.py / test_sketch.py / test_robustreg.py), not flat cases
@pytest.mark.parametrize("name", sorted(k for k in GOLDEN
                                        if k not in ("formula_cases",
                                                     "penalized_cases",
                                                     "sparse_cases",
                                                     "robust_cases")))
def test_r_golden(name):
    case = GOLDEN[name]
    X, y, kw = _design(case)
    model = glm_mod.fit(X, y, **kw)
    g = case["fit"]

    np.testing.assert_allclose(model.coefficients, g["coefficients"],
                               rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(model.std_errors, g["std_errors"],
                               rtol=1e-6, atol=1e-10)
    assert model.deviance == pytest.approx(g["deviance"], rel=1e-7, abs=1e-10)
    assert model.null_deviance == pytest.approx(g["null_deviance"], rel=1e-7)
    assert model.pearson_chi2 == pytest.approx(g["pearson"], rel=1e-6)
    assert model.dispersion == pytest.approx(g["dispersion"], rel=1e-6)
    assert model.df_residual == g["df_residual"]
    assert model.df_null == g["df_null"]
    if g["aic"] is None:
        assert np.isnan(model.aic)  # R prints AIC: NA for quasi families
    else:
        assert model.loglik == pytest.approx(g["loglik"], rel=1e-7)
        assert model.aic == pytest.approx(g["aic"], rel=1e-7)

    # values R itself printed in its documentation, at printed precision
    rd = case.get("r_doc")
    if rd:
        for got, want in zip(model.coefficients, rd.get("coefficients", [])):
            if want is not None:
                assert got == pytest.approx(want, abs=1.5e-6)
        for got, want in zip(model.std_errors, rd.get("std_errors", [])):
            assert got == pytest.approx(want, abs=1.5e-4)
        if "deviance" in rd:
            assert model.deviance == pytest.approx(rd["deviance"], abs=1e-4)
            assert model.null_deviance == pytest.approx(rd["null_deviance"], abs=1e-4)
            assert model.aic == pytest.approx(rd["aic"], abs=1e-4)


def test_streaming_matches_golden():
    """The streaming engine reports the same R-exact statistics."""
    from sparkglm_tpu.models.streaming import glm_fit_streaming
    case = GOLDEN["gaussian_weighted"]
    X, y, kw = _design(case)
    m = glm_fit_streaming((X, y, kw["weights"]), family="gaussian",
                          link="identity", tol=1e-12, criterion="relative",
                          chunk_rows=16)
    g = case["fit"]
    np.testing.assert_allclose(m.coefficients, g["coefficients"], rtol=1e-6)
    assert m.aic == pytest.approx(g["aic"], rel=1e-6)
    assert m.loglik == pytest.approx(g["loglik"], rel=1e-6)
    assert m.null_deviance == pytest.approx(g["null_deviance"], rel=1e-6)


def test_streaming_gamma_aic_matches_golden():
    from sparkglm_tpu.models.streaming import glm_fit_streaming
    case = GOLDEN["clotting_gamma_lot1"]
    X, y, kw = _design(case)
    m = glm_fit_streaming((X, y), family="gamma", link="inverse",
                          tol=1e-12, criterion="relative", chunk_rows=4)
    g = case["fit"]
    np.testing.assert_allclose(m.coefficients, g["coefficients"], rtol=1e-6)
    assert m.aic == pytest.approx(g["aic"], rel=1e-6)
    assert m.dispersion == pytest.approx(g["dispersion"], rel=1e-6)
