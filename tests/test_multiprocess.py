"""REAL multi-process distributed fit (VERDICT r1 missing #2 / next #5).

Launches 2 or 3 OS processes, each with 2 virtual CPU devices, joined through
``jax.distributed.initialize`` with a localhost coordinator — the analogue
of the reference testing its distributed path by partition count in
local-mode Spark (lmPredict$Test.scala:11-35), but with actual separate
processes exercising ``make_array_from_process_local_data``, the
cross-process psum inside the IRLS while_loop, and the allsum_f64 host
statistics aggregation.

Each worker reads ITS OWN byte-range shard of a shared CSV
(read_csv(shard_index=process_index)), pads to the agreed row count, builds
the global arrays, and fits.  Process 0 writes the model's statistics; the
test asserts parity with a single-process fit of the same file.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

# Every test here joins real OS processes through jax.distributed and runs
# cross-process collectives on the CPU backend.  jaxlib < 0.5 raises
# "Multiprocess computations aren't implemented on the CPU backend" at the
# first psum/allgather — the CPU collectives runtime (gloo) ships with
# jax/jaxlib >= 0.5.  Skip, naming the missing dependency, rather than
# failing on a capability the installed jaxlib does not have.
_JAX_VER = tuple(int(x) for x in jax.__version__.split(".")[:2])
pytestmark = pytest.mark.skipif(
    _JAX_VER < (0, 5),
    reason="cross-process CPU collectives need jax/jaxlib >= 0.5 (gloo CPU "
           f"collectives); installed jax {jax.__version__} raises "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend'")

_WORKER = r"""
import json, sys
port, pid, csv_path, out_path, nproc = sys.argv[1:6]
nproc = int(nproc)
import os, re
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; set the XLA flag before
    # backend init, overriding any device count inherited from the parent
    # test process (conftest.py forces 8 there)
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (flags
                               + " --xla_force_host_platform_device_count=2")
jax.config.update("jax_enable_x64", True)
import numpy as np
import sparkglm_tpu as sg
from sparkglm_tpu.parallel import distributed as dist

dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=int(pid))
assert jax.process_count() == nproc, jax.process_count()
assert len(jax.devices()) == 2 * nproc  # nproc processes x 2 cpu devices
mesh = dist.global_mesh()

cols = sg.read_csv(csv_path, shard_index=dist.process_index(),
                   num_shards=nproc)
# global level discovery (ADVICE r1): level "c" exists only in shard 0's
# byte range — without scan_csv_levels the two hosts would dummy-code
# designs with different column counts
levels = sg.scan_csv_levels(csv_path)
assert levels == {"grp": ["a", "b", "c"]}, levels
terms = sg.build_terms(cols, ["x1", "x2", "grp"], intercept=True,
                       levels=levels)
X = sg.transform(cols, terms).astype(np.float64)
y = np.asarray(cols["y"], np.float64)
sig = terms.signature()

tgt = dist.sync_max_rows(X.shape[0], mesh)
Xp, w = dist.pad_host_shard(X.astype(np.float32), tgt)
yp, _ = dist.pad_host_shard(y.astype(np.float32), tgt)

Xg = dist.host_shard_to_global(Xp, mesh)
yg = dist.host_shard_to_global(yp, mesh)
wg = dist.host_shard_to_global(w.astype(np.float32), mesh)

model = sg.glm_fit(Xg, yg, weights=wg, family="poisson", mesh=mesh,
                   has_intercept=True, xnames=terms.xnames,
                   criterion="relative", tol=1e-10)

# offset variant: exercises _fit_global's intercept+offset null model
# (second collective IRLS on a ones design) and the all-zero-offset check
off = np.full(tgt, 0.1, np.float32); off[len(cols["x1"]):] = 0.0
og = dist.host_shard_to_global(off, mesh)
model_off = sg.glm_fit(Xg, yg, weights=wg, offset=og, family="poisson",
                       mesh=mesh, has_intercept=True, xnames=terms.xnames,
                       criterion="relative", tol=1e-10)
if dist.process_index() == 0:
    with open(out_path, "w") as f:
        json.dump({
            "terms_signature": sig,
            "off_coefficients": model_off.coefficients.tolist(),
            "off_null_deviance": model_off.null_deviance,
            "off_has_offset": model_off.has_offset,
            "coefficients": model.coefficients.tolist(),
            "std_errors": model.std_errors.tolist(),
            "deviance": model.deviance,
            "null_deviance": model.null_deviance,
            "loglik": model.loglik,
            "aic": model.aic,
            "df_residual": model.df_residual,
            "iterations": model.iterations,
            "converged": model.converged,
            "n_shards": model.n_shards,
        }, f)
print("worker", pid, "done", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("nproc", [2, 3])
def test_multi_process_csv_fit(tmp_path, nproc):
    rng = np.random.default_rng(17)
    n = 4001  # odd: byte-range shards are uneven -> exercises padding
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    # factor level "c" confined to the first rows: only shard 0 sees it
    grp = np.where(np.arange(n) < 120, "c",
                   np.where(rng.random(n) < 0.5, "a", "b"))
    eff = {"a": 0.0, "b": 0.2, "c": -0.4}
    y = rng.poisson(np.exp(0.4 + 0.5 * x1 - 0.3 * x2
                           + np.vectorize(eff.get)(grp))).astype(np.float64)
    csv_path = tmp_path / "data.csv"
    with open(csv_path, "w") as f:
        f.write("y,x1,x2,grp\n")
        for i in range(n):
            f.write(f"{y[i]:.1f},{x1[i]:.17g},{x2[i]:.17g},{grp[i]}\n")

    port = _free_port()
    out_path = tmp_path / "result.json"
    worker_file = tmp_path / "worker.py"
    worker_file.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # worker selects cpu via jax.config
    # the worker script lives in tmp; keep any existing entries (the axon
    # plugin site dir must never be clobbered — overwriting PYTHONPATH
    # breaks jax's backend registry)
    env["PYTHONPATH"] = "/root/repo" + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_file), str(port), str(i),
             str(csv_path), str(out_path), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd="/root/repo")
        for i in range(nproc)
    ]
    logs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process workers timed out")
        logs.append(out.decode())
    for i, pr in enumerate(procs):
        assert pr.returncode == 0, f"worker {i} failed:\n{logs[i][-3000:]}"

    with open(out_path) as f:
        got = json.load(f)

    # single-process reference fit on the full file (same Terms recipe)
    import sparkglm_tpu as sg
    cols = sg.read_csv(str(csv_path))
    terms = sg.build_terms(cols, ["x1", "x2", "grp"], intercept=True,
                           levels=sg.scan_csv_levels(str(csv_path)))
    assert got["terms_signature"] == terms.signature()
    X = sg.transform(cols, terms).astype(np.float32)
    ref = sg.glm_fit(X, np.asarray(cols["y"], np.float32), family="poisson",
                     criterion="relative", tol=1e-10, xnames=terms.xnames)

    assert got["converged"]
    assert got["n_shards"] == 2 * nproc
    assert got["df_residual"] == ref.df_residual  # padding rows excluded
    np.testing.assert_allclose(got["coefficients"], ref.coefficients,
                               rtol=0, atol=5e-6)
    np.testing.assert_allclose(got["std_errors"], ref.std_errors, rtol=1e-4)
    assert got["deviance"] == pytest.approx(ref.deviance, rel=1e-5)
    assert got["null_deviance"] == pytest.approx(ref.null_deviance, rel=1e-5)
    assert got["loglik"] == pytest.approx(ref.loglik, rel=1e-5)
    assert got["aic"] == pytest.approx(ref.aic, rel=1e-5)

    # offset variant: parity incl. the offset-aware null deviance (an
    # intercept-only collective IRLS inside _fit_global)
    ref_off = sg.glm_fit(X, np.asarray(cols["y"], np.float32),
                         offset=np.full(n, 0.1, np.float32),
                         family="poisson", criterion="relative", tol=1e-10,
                         xnames=terms.xnames)
    assert got["off_has_offset"] is True
    np.testing.assert_allclose(got["off_coefficients"], ref_off.coefficients,
                               rtol=0, atol=5e-6)
    assert got["off_null_deviance"] == pytest.approx(ref_off.null_deviance,
                                                     rel=1e-5)


_STREAM_WORKER = r"""
import json, sys
port, pid, csv_path, out_path, nproc = sys.argv[1:6]
nproc = int(nproc)
import os, re
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; set the XLA flag before
    # backend init, overriding any device count inherited from the parent
    # test process (conftest.py forces 8 there)
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (flags
                               + " --xla_force_host_platform_device_count=2")
jax.config.update("jax_enable_x64", True)
import numpy as np
import sparkglm_tpu as sg
from sparkglm_tpu.models.streaming import glm_fit_streaming, lm_fit_streaming
from sparkglm_tpu.parallel import distributed as dist

dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=int(pid))
mesh = None  # streaming uses the per-process LOCAL mesh

# each process streams ITS OWN byte-range shard of the file — out-of-core
# and multi-host COMPOSE (VERDICT r2 missing #2)
cols = sg.read_csv(csv_path, shard_index=dist.process_index(),
                   num_shards=nproc)
levels = sg.scan_csv_levels(csv_path)
terms = sg.build_terms(cols, ["x1", "x2", "grp"], intercept=True,
                       levels=levels)
X = sg.transform(cols, terms).astype(np.float32)
y = np.asarray(cols["y"], np.float32)

m = glm_fit_streaming((X, y), family="poisson", chunk_rows=700,
                      xnames=terms.xnames, criterion="relative", tol=1e-10)
ml = lm_fit_streaming((X, y), chunk_rows=700, xnames=terms.xnames)
if dist.process_index() == 0:
    with open(out_path, "w") as f:
        json.dump({
            "coefficients": m.coefficients.tolist(),
            "std_errors": m.std_errors.tolist(),
            "deviance": m.deviance,
            "null_deviance": m.null_deviance,
            "aic": m.aic,
            "df_residual": m.df_residual,
            "converged": m.converged,
            "n_obs": m.n_obs,
            "lm_coefficients": ml.coefficients.tolist(),
            "lm_sse": ml.sse,
            "lm_r2": ml.r_squared,
            "lm_n_obs": ml.n_obs,
        }, f)
print("stream worker", pid, "done", flush=True)
"""


def test_multi_process_streaming_fit(tmp_path):
    """VERDICT r2 missing #2 / next #5: per-process chunk sources feeding
    the global accumulation — a 2-process STREAMING fit must match the
    single-process streamed fit of the same file."""
    nproc = 2
    rng = np.random.default_rng(23)
    n = 3001
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    grp = np.where(np.arange(n) < 100, "c",
                   np.where(rng.random(n) < 0.5, "a", "b"))
    eff = {"a": 0.0, "b": 0.2, "c": -0.4}
    y = rng.poisson(np.exp(0.3 + 0.4 * x1 - 0.2 * x2
                           + np.vectorize(eff.get)(grp))).astype(np.float64)
    csv_path = tmp_path / "data.csv"
    with open(csv_path, "w") as f:
        f.write("y,x1,x2,grp\n")
        for i in range(n):
            f.write(f"{y[i]:.1f},{x1[i]:.17g},{x2[i]:.17g},{grp[i]}\n")

    port = _free_port()
    out_path = tmp_path / "result.json"
    worker_file = tmp_path / "worker.py"
    worker_file.write_text(_STREAM_WORKER)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = "/root/repo" + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_file), str(port), str(i),
             str(csv_path), str(out_path), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd="/root/repo")
        for i in range(nproc)
    ]
    logs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("streaming workers timed out")
        logs.append(out.decode())
    for i, pr in enumerate(procs):
        assert pr.returncode == 0, f"worker {i} failed:\n{logs[i][-3000:]}"

    with open(out_path) as f:
        got = json.load(f)

    # single-process streamed reference on the full file
    import sparkglm_tpu as sg
    from sparkglm_tpu.models.streaming import glm_fit_streaming, lm_fit_streaming
    cols = sg.read_csv(str(csv_path))
    terms = sg.build_terms(cols, ["x1", "x2", "grp"], intercept=True,
                           levels=sg.scan_csv_levels(str(csv_path)))
    X = sg.transform(cols, terms).astype(np.float32)
    yf = np.asarray(cols["y"], np.float32)
    ref = glm_fit_streaming((X, yf), family="poisson", chunk_rows=700,
                            xnames=terms.xnames, criterion="relative",
                            tol=1e-10)
    refl = lm_fit_streaming((X, yf), chunk_rows=700, xnames=terms.xnames)

    assert got["converged"]
    assert got["n_obs"] == n and got["lm_n_obs"] == n
    assert got["df_residual"] == ref.df_residual
    np.testing.assert_allclose(got["coefficients"], ref.coefficients,
                               rtol=0, atol=5e-6)
    np.testing.assert_allclose(got["std_errors"], ref.std_errors, rtol=1e-4)
    assert got["deviance"] == pytest.approx(ref.deviance, rel=1e-6)
    assert got["null_deviance"] == pytest.approx(ref.null_deviance, rel=1e-6)
    assert got["aic"] == pytest.approx(ref.aic, rel=1e-6)
    np.testing.assert_allclose(got["lm_coefficients"], refl.coefficients,
                               rtol=0, atol=5e-6)
    assert got["lm_sse"] == pytest.approx(refl.sse, rel=1e-6)
    assert got["lm_r2"] == pytest.approx(refl.r_squared, rel=1e-6)


_RECOVERY_WORKER = r"""
import json, os, sys
port, pid, csv_path, out_path, nproc, phase, ckpt_path, engine = sys.argv[1:9]
nproc = int(nproc)
import os, re
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; set the XLA flag before
    # backend init, overriding any device count inherited from the parent
    # test process (conftest.py forces 8 there)
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (flags
                               + " --xla_force_host_platform_device_count=2")
jax.config.update("jax_enable_x64", True)
import numpy as np
import sparkglm_tpu as sg
from sparkglm_tpu.parallel import distributed as dist

dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=int(pid))
mesh = dist.global_mesh()
cols = sg.read_csv(csv_path, shard_index=dist.process_index(),
                   num_shards=nproc)
terms = sg.build_terms(cols, ["x1", "x2"], intercept=True)
X = sg.transform(cols, terms).astype(np.float32)
y = np.asarray(cols["y"], np.float32)
tgt = dist.sync_max_rows(X.shape[0], mesh)
Xp, w = dist.pad_host_shard(X, tgt)
yp, _ = dist.pad_host_shard(y, tgt)
Xg = dist.host_shard_to_global(Xp, mesh)
yg = dist.host_shard_to_global(yp, mesh)
wg = dist.host_shard_to_global(w.astype(np.float32), mesh)
kw = dict(family="poisson", mesh=mesh, xnames=terms.xnames,
          has_intercept=True, criterion="relative", tol=1e-10, engine=engine)

def hook(i, beta, dev):
    # every process persists the checkpoint (any copy suffices to resume)
    np.save(f"{ckpt_path}.{pid}.npy", beta)
    if phase == "crash" and i == 2:
        os._exit(3)  # the pod loses a process mid-fit

if phase == "crash":
    sg.glm_fit(Xg, yg, weights=wg, checkpoint_every=1, on_iteration=hook, **kw)
    os._exit(9)  # should never get here
else:
    beta0 = np.load(f"{ckpt_path}.0.npy")
    model = sg.glm_fit(Xg, yg, weights=wg, beta0=beta0, **kw)
    if dist.process_index() == 0:
        with open(out_path, "w") as f:
            json.dump({"coefficients": model.coefficients.tolist(),
                       "deviance": model.deviance,
                       "iterations": model.iterations,
                       "converged": model.converged}, f)
print("recovery worker", pid, phase, "done", flush=True)
"""


@pytest.mark.parametrize("engine", ["einsum", "fused"])
def test_multi_process_crash_resume(tmp_path, engine):
    """VERDICT r2 #8: a multi-host fit that loses a process resumes from
    the last beta checkpoint — costing the iterations since the
    checkpoint, not the fit.  r4: the fused engine warm-starts too, so
    the crash-resume path no longer demotes to einsum."""
    nproc = 2
    rng = np.random.default_rng(29)
    n = 2000
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.4 + 0.5 * x1 - 0.3 * x2)).astype(np.float64)
    csv_path = tmp_path / "data.csv"
    with open(csv_path, "w") as f:
        f.write("y,x1,x2\n")
        for i in range(n):
            f.write(f"{y[i]:.1f},{x1[i]:.17g},{x2[i]:.17g}\n")
    worker_file = tmp_path / "worker.py"
    worker_file.write_text(_RECOVERY_WORKER)
    out_path = tmp_path / "result.json"
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = "/root/repo" + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")

    def launch(phase):
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker_file), str(port), str(i),
                 str(csv_path), str(out_path), str(nproc), phase, str(ckpt),
                 engine],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
                cwd="/root/repo")
            for i in range(nproc)
        ]
        outs = []
        for pr in procs:
            try:
                out, _ = pr.communicate(timeout=300)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail(f"{phase} workers timed out")
            outs.append(out.decode())
        return procs, outs

    procs, outs = launch("crash")
    for i, pr in enumerate(procs):
        assert pr.returncode == 3, f"crash worker {i}: rc={pr.returncode}\n{outs[i][-2000:]}"
    assert (tmp_path / "ckpt.0.npy").exists()

    procs, outs = launch("resume")
    for i, pr in enumerate(procs):
        assert pr.returncode == 0, f"resume worker {i} failed:\n{outs[i][-3000:]}"
    with open(out_path) as f:
        got = json.load(f)

    # single-process fit of the full file as the truth
    import sparkglm_tpu as sg
    cols = sg.read_csv(str(csv_path))
    terms = sg.build_terms(cols, ["x1", "x2"], intercept=True)
    X = sg.transform(cols, terms).astype(np.float32)
    ref = sg.glm_fit(X, np.asarray(cols["y"], np.float32), family="poisson",
                     criterion="relative", tol=1e-10, xnames=terms.xnames)
    assert got["converged"]
    np.testing.assert_allclose(got["coefficients"], ref.coefficients,
                               rtol=0, atol=5e-6)
    assert got["deviance"] == pytest.approx(ref.deviance, rel=1e-5)
    # resume cost: remaining iterations only (2 were done before the crash)
    assert got["iterations"] <= ref.iterations - 1


_POLISH_WORKER = r"""
import json, sys
port, pid, out_path, nproc = sys.argv[1:5]
nproc = int(nproc)
import os, re
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; set the XLA flag before
    # backend init, overriding any device count inherited from the parent
    # test process (conftest.py forces 8 there)
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (flags
                               + " --xla_force_host_platform_device_count=2")
jax.config.update("jax_enable_x64", True)
import numpy as np
import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig
from sparkglm_tpu.parallel import distributed as dist

dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=int(pid))
mesh = dist.global_mesh()

# every process builds the same ill-conditioned design, takes its row slice
rng = np.random.default_rng(31)
n, p, kappa = 20_000, 10, 1e3
Z = rng.standard_normal((n, p - 1))
V, _ = np.linalg.qr(rng.standard_normal((p - 1, p - 1)))
s = np.logspace(0, -np.log10(kappa), p - 1)
X = np.column_stack([np.ones(n), (Z @ V) * s @ V.T])
bt = rng.standard_normal(p) / np.sqrt(p)
y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float64)
lo = int(pid) * (n // nproc); hi = n if int(pid) == nproc - 1 else lo + n // nproc
tgt = dist.sync_max_rows(hi - lo, mesh)
Xp, w = dist.pad_host_shard(X[lo:hi].astype(np.float32), tgt)
yp, _ = dist.pad_host_shard(y[lo:hi].astype(np.float32), tgt)
Xg = dist.host_shard_to_global(Xp, mesh)
yg = dist.host_shard_to_global(yp, mesh)
wg = dist.host_shard_to_global(w.astype(np.float32), mesh)

import warnings
with warnings.catch_warnings(record=True) as wl:
    warnings.simplefilter("always")
    model = sg.glm_fit(Xg, yg, weights=wg, family="binomial", mesh=mesh,
                       has_intercept=True, criterion="relative", tol=1e-10,
                       config=NumericConfig(dtype="float32"))
if dist.process_index() == 0:
    with open(out_path, "w") as f:
        json.dump({"coefficients": model.coefficients.tolist(),
                   "escalated": any("auto-applying the CSNE polish"
                                    in str(w.message) for w in wl)}, f)
print("polish worker", pid, "done", flush=True)
"""


def test_multi_process_auto_polish(tmp_path):
    """The conditioning policy (default-args CSNE escalation) applies to
    GLOBAL multi-process fits too — the polish's TSQR runs collectively."""
    nproc = 2
    port = _free_port()
    out_path = tmp_path / "result.json"
    worker_file = tmp_path / "worker.py"
    worker_file.write_text(_POLISH_WORKER)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = "/root/repo" + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker_file), str(port), str(i),
             str(out_path), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd="/root/repo")
        for i in range(nproc)
    ]
    logs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("polish workers timed out")
        logs.append(out.decode())
    for i, pr in enumerate(procs):
        assert pr.returncode == 0, f"worker {i} failed:\n{logs[i][-3000:]}"
    with open(out_path) as f:
        got = json.load(f)
    assert got["escalated"]

    # single-process default-args fit of the same data is the oracle (it
    # auto-polishes the same way)
    import warnings

    import sparkglm_tpu as sg
    from sparkglm_tpu.config import NumericConfig
    rng = np.random.default_rng(31)
    n, p, kappa = 20_000, 10, 1e3
    Z = rng.standard_normal((n, p - 1))
    V, _ = np.linalg.qr(rng.standard_normal((p - 1, p - 1)))
    s = np.logspace(0, -np.log10(kappa), p - 1)
    X = np.column_stack([np.ones(n), (Z @ V) * s @ V.T])
    bt = rng.standard_normal(p) / np.sqrt(p)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float64)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ref = sg.glm_fit(X.astype(np.float32), y.astype(np.float32),
                         family="binomial", criterion="relative", tol=1e-10,
                         config=NumericConfig(dtype="float32"))
    # two independently polished f32 solutions at kappa=1e3 agree to
    # ~eps32*kappa*|beta| (coefficients here are O(10))
    np.testing.assert_allclose(got["coefficients"], ref.coefficients,
                               rtol=1e-3, atol=5e-4)
