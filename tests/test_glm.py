"""GLM / IRLS parity tests against a float64 numpy oracle (R semantics).

The reference has NO GLM tests at all (SURVEY.md §4: "none at all for
GLM/IRLS") — its stated oracle is R glm() to 1e-6; oracle.irls_np implements
exactly those semantics independently.
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from oracle import irls_np


def _logistic_data(rng, n=2000, p=6):
    X = rng.normal(size=(n, p)).astype(np.float64)
    X[:, 0] = 1.0
    beta = rng.normal(size=p) * 0.7
    prob = 1 / (1 + np.exp(-(X @ beta)))
    y = (rng.uniform(size=n) < prob).astype(np.float64)
    return X, y


@pytest.mark.parametrize("link", ["logit", "probit", "cloglog"])
def test_binomial_links_match_oracle(rng, mesh8, link):
    X, y = _logistic_data(rng)
    m = sg.glm_fit(X, y, family="binomial", link=link, tol=1e-10, mesh=mesh8)
    beta_ref, dev_ref, _, _ = irls_np(X, y, "binomial", link)
    np.testing.assert_allclose(m.coefficients, beta_ref, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(m.deviance, dev_ref, rtol=1e-8)
    assert m.converged


def test_single_vs_eight_shards_agree(rng, mesh1, mesh8):
    X, y = _logistic_data(rng, n=1001)  # padding path
    m1 = sg.glm_fit(X, y, family="binomial", tol=1e-9, mesh=mesh1)
    m8 = sg.glm_fit(X, y, family="binomial", tol=1e-9, mesh=mesh8)
    np.testing.assert_allclose(m1.coefficients, m8.coefficients, rtol=1e-8)
    np.testing.assert_allclose(m1.deviance, m8.deviance, rtol=1e-10)
    np.testing.assert_allclose(m1.loglik, m8.loglik, rtol=1e-10)
    assert m1.iterations == m8.iterations


def test_poisson_log(rng, mesh8):
    n, p = 1500, 5
    X = rng.normal(size=(n, p)) * 0.5
    X[:, 0] = 1.0
    beta = rng.normal(size=p) * 0.4
    y = rng.poisson(np.exp(X @ beta)).astype(np.float64)
    m = sg.glm_fit(X, y, family="poisson", tol=1e-10, mesh=mesh8)
    beta_ref, dev_ref, _, _ = irls_np(X, y, "poisson", "log")
    np.testing.assert_allclose(m.coefficients, beta_ref, rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(m.deviance, dev_ref, rtol=1e-7)
    assert m.dispersion == 1.0  # fixed for poisson


def test_gamma_inverse_with_weights_and_offset(rng, mesh8):
    """BASELINE config 5: gamma + prior weights + offset through the sharded
    path (the reference falls back to single-partition here, GLM.scala:640-642)."""
    n, p = 1200, 4
    X = np.abs(rng.normal(size=(n, p))) + 0.5
    X[:, 0] = 1.0
    beta = np.abs(rng.normal(size=p)) * 0.3 + 0.2
    off = rng.uniform(0.0, 0.3, size=n)
    mu = 1 / (X @ beta + off)
    shape = 5.0
    y = rng.gamma(shape, mu / shape, size=n)
    wt = rng.uniform(0.5, 2.0, size=n)
    m = sg.glm_fit(X, y, family="gamma", link="inverse", weights=wt,
                   offset=off, tol=1e-11, mesh=mesh8)
    beta_ref, dev_ref, _, _ = irls_np(X, y, "gamma", "inverse", wt=wt, offset=off)
    np.testing.assert_allclose(m.coefficients, beta_ref, rtol=1e-6)
    np.testing.assert_allclose(m.deviance, dev_ref, rtol=1e-7)
    assert not np.isnan(m.dispersion) and m.dispersion > 0


def test_gaussian_identity_one_iteration(rng, mesh8):
    """Gaussian/identity IRLS == OLS in a single Fisher step."""
    X = rng.normal(size=(800, 5))
    X[:, 0] = 1.0
    y = X @ rng.normal(size=5) + rng.normal(size=800)
    mg = sg.glm_fit(X, y, family="gaussian", tol=1e-9, mesh=mesh8)
    ml = sg.lm_fit(X, y, mesh=mesh8)
    np.testing.assert_allclose(mg.coefficients, ml.coefficients, rtol=1e-8)


def test_binomial_group_sizes_m(rng, mesh8):
    """Counts y out of group sizes m — the reference's (y, m) surface
    (GLM.scala:254-315), equivalent to R's proportion+weights form."""
    n, p = 600, 4
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    beta = rng.normal(size=p) * 0.5
    mm = rng.integers(1, 20, size=n).astype(np.float64)
    prob = 1 / (1 + np.exp(-(X @ beta)))
    counts = rng.binomial(mm.astype(int), prob).astype(np.float64)
    m = sg.glm_fit(X, counts, family="binomial", m=mm, tol=1e-10, mesh=mesh8)
    beta_ref, dev_ref, _, _ = irls_np(X, counts / mm, "binomial", "logit", wt=mm)
    np.testing.assert_allclose(m.coefficients, beta_ref, rtol=1e-6)
    np.testing.assert_allclose(m.deviance, dev_ref, rtol=1e-7)


def test_std_errors_match_fisher_information(rng, mesh8):
    X, y = _logistic_data(rng, n=1000, p=4)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-11, mesh=mesh8)
    _, _, _, cov = irls_np(X, y, "binomial", "logit")
    np.testing.assert_allclose(m.std_errors, np.sqrt(np.diag(cov)), rtol=1e-5)


def test_relative_tol_ulp_clamp(rng, mesh1):
    """R's relative epsilon is floored at the deviance dtype's resolution
    (config.effective_tol): an f32 fit asked for 1e-12 converges at the f32
    noise floor instead of creeping through no-op iterations, and a
    non-converged fit's warning names the effective threshold."""
    import warnings
    X, y = _logistic_data(rng, n=500, p=4)
    Xf = X.astype(np.float32)
    m = sg.glm_fit(Xf, y.astype(np.float32), family="binomial",
                   criterion="relative", tol=1e-12, mesh=mesh1)
    assert m.converged and m.iterations < 30
    with warnings.catch_warnings(record=True) as wrec:
        warnings.simplefilter("always")
        sg.glm_fit(Xf, y.astype(np.float32), family="binomial",
                   criterion="relative", tol=1e-12, max_iter=2, mesh=mesh1)
    assert any("effective threshold" in str(w.message) for w in wrec)
    # f64 paths keep the requested epsilon untouched
    from sparkglm_tpu.config import effective_tol
    assert effective_tol(1e-8, "relative", np.float64) == 1e-8
    assert effective_tol(1e-12, "relative", np.float32) > 9e-7
    assert effective_tol(1e-12, "absolute", np.float32) == 1e-12


def test_max_iter_guard(rng, mesh1):
    X, y = _logistic_data(rng, n=300, p=3)
    m = sg.glm_fit(X, y, family="binomial", tol=0.0, max_iter=3, mesh=mesh1)
    assert m.iterations == 3
    assert not m.converged  # the guard the reference lacks (GLM.scala:452)


def test_perfect_separation_does_not_nan(rng, mesh1):
    """Saturating logistic fit must stay finite (mu clipping)."""
    n = 200
    x = np.linspace(-2, 2, n)
    X = np.stack([np.ones(n), x], axis=1)
    y = (x > 0).astype(np.float64)
    m = sg.glm_fit(X, y, family="binomial", max_iter=25, mesh=mesh1)
    assert np.all(np.isfinite(m.coefficients))
    assert np.isfinite(m.deviance)


def test_aic_and_loglik_binomial(rng, mesh8):
    X, y = _logistic_data(rng, n=800, p=4)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-10, mesh=mesh8)
    # exact Bernoulli loglik at the fitted probabilities
    eta = X @ m.coefficients
    mu = 1 / (1 + np.exp(-eta))
    ll = float(np.sum(y * np.log(mu) + (1 - y) * np.log1p(-mu)))
    np.testing.assert_allclose(m.loglik, ll, rtol=1e-7)
    np.testing.assert_allclose(m.aic, -2 * ll + 2 * X.shape[1], rtol=1e-7)
