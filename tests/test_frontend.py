"""Formula / model-matrix / frame front-end tests.

Mirrors the reference's modelMatrix$Test.scala (dummy coding on mixed /
numeric-only / string-only frames) and utils$Test.scala (matchCols
zero-fill), plus formula semantics from R/pkg/R/utils.R:8-22.
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.formula import parse_formula
from sparkglm_tpu.data.frame import omit_na


def _mixed(n=9):
    return {
        "y": np.arange(n, dtype=np.float64),
        "x1": np.linspace(0, 1, n),
        "x7": np.array(["a", "b", "c"] * (n // 3)),
    }


# -- formula (utils.R:8-22) ---------------------------------------------------

def test_parse_formula_basic():
    f = parse_formula("y ~ x1 + x2 + cat")
    assert f.response == "y"
    assert f.predictors == ("x1", "x2", "cat")
    assert f.intercept


def test_parse_formula_no_intercept():
    assert not parse_formula("y ~ x1 - 1").intercept
    assert not parse_formula("y ~ 0 + x1").intercept
    assert parse_formula("y ~ 1 + x1").intercept


def test_parse_formula_dot():
    f = parse_formula("y ~ .")
    assert f.resolve_predictors(["y", "a", "b"]) == ["a", "b"]


def test_parse_formula_errors():
    with pytest.raises(ValueError):
        parse_formula("y + x1")
    with pytest.raises(ValueError):
        parse_formula("~ x1")
    with pytest.raises(ValueError):
        parse_formula("y ~ x1 - x2")
    with pytest.raises(KeyError):
        parse_formula("y ~ nope").resolve_predictors(["y", "x1"])


# -- model matrix (modelMatrix.scala:18-85) -----------------------------------

def test_dummy_coding_mixed():
    X, terms = sg.model_matrix(_mixed(), ["x1", "x7"])
    # sorted levels a,b,c -> drop 'a' (modelMatrix.scala:56-58)
    assert terms.xnames == ("x1", "x7_b", "x7_c")
    assert X.shape == (9, 3)
    np.testing.assert_array_equal(X[:3, 1], [0, 1, 0])  # rows a,b,c
    np.testing.assert_array_equal(X[:3, 2], [0, 0, 1])
    assert X.dtype == np.float32  # castAll


def test_numeric_only_passthrough():
    d = {"a": np.arange(4.0), "b": np.arange(4.0) * 2}
    X, terms = sg.model_matrix(d)
    assert terms.xnames == ("a", "b")
    np.testing.assert_allclose(X[:, 1], d["b"])


def test_intercept_column():
    X, terms = sg.model_matrix(_mixed(), ["x1"], intercept=True)
    assert terms.xnames[0] == "intercept"
    np.testing.assert_array_equal(X[:, 0], np.ones(9))


def test_match_cols_zero_fill():
    """utils$Test.scala:10-24: scoring data missing a training category gets
    an all-zero dummy column."""
    train = {"x7": np.array(["a", "b", "c"]), "x1": np.ones(3)}
    _, terms = sg.model_matrix(train, ["x1", "x7"])
    test_d = {"x7": np.array(["a", "b", "b"]), "x1": np.ones(3)}
    Xs = sg.transform(test_d, terms)
    assert Xs.shape == (3, 3)
    np.testing.assert_array_equal(Xs[:, 2], [0, 0, 0])  # x7_c zero-filled


def test_unseen_level_maps_to_baseline():
    train = {"x7": np.array(["a", "b", "c"])}
    _, terms = sg.model_matrix(train)
    Xs = sg.transform({"x7": np.array(["zz"])}, terms)
    np.testing.assert_array_equal(Xs, [[0.0, 0.0]])


def test_missing_column_raises():
    _, terms = sg.model_matrix(_mixed(), ["x1", "x7"])
    with pytest.raises(KeyError):
        sg.transform({"x1": np.ones(2)}, terms)


# -- NA omission (utils.R:24-27) ----------------------------------------------

def test_omit_na():
    cols = {"a": np.array([1.0, np.nan, 3.0]), "b": np.array([1.0, 2.0, 3.0])}
    out, keep = omit_na(cols)
    assert keep.tolist() == [True, False, True]
    np.testing.assert_array_equal(out["a"], [1.0, 3.0])


# -- end-to-end formula API ---------------------------------------------------

def test_lm_formula_end_to_end(mesh8):
    rng = np.random.default_rng(0)
    n = 240
    species = np.array(["setosa", "versicolor", "virginica"])[rng.integers(0, 3, n)]
    x = rng.normal(size=n)
    y = 2.0 + 1.5 * x + (species == "versicolor") * 0.7 + (species == "virginica") * (-0.4) + 0.05 * rng.normal(size=n)
    data = {"y": y, "x": x, "species": species}
    m = sg.lm("y ~ x + species", data, mesh=mesh8)
    assert m.xnames == ("intercept", "x", "species_versicolor", "species_virginica")
    np.testing.assert_allclose(
        m.coefficients, [2.0, 1.5, 0.7, -0.4], atol=0.05)
    pred = sg.predict(m, data)
    assert pred.shape == (n,)
    np.testing.assert_allclose(pred, y, atol=0.25)
    s = str(m.summary())
    assert "Coefficients" in s and "R-Squared" in s


def test_glm_formula_categorical_response(mesh8):
    rng = np.random.default_rng(1)
    n = 400
    x = rng.normal(size=n)
    p = 1 / (1 + np.exp(-(0.5 + 1.2 * x)))
    yes = rng.uniform(size=n) < p
    data = {"outcome": np.where(yes, "yes", "no"), "x": x}
    m = sg.glm("outcome ~ x", data, family="binomial", mesh=mesh8)
    assert m.xnames == ("intercept", "x")
    assert abs(m.coefficients[1] - 1.2) < 0.5
    mu = sg.predict(m, data)
    assert np.all((mu > 0) & (mu < 1))
    eta = sg.predict(m, data, type="link")
    np.testing.assert_allclose(mu, 1 / (1 + np.exp(-eta)), rtol=1e-6)


def test_formula_na_omission_end_to_end(mesh1):
    data = {
        "y": np.array([1.0, 2.0, np.nan, 4.0, 5.0, 6.0]),
        "x": np.array([1.0, 2.0, 3.0, np.nan, 5.0, 6.0]),
    }
    m = sg.lm("y ~ x", data, mesh=mesh1)
    assert m.n_obs == 4


def test_factor_response_binomial(rng):
    """Two-level string response: R's glm treats the FIRST (sorted) level
    as failure, the second as success (api._design)."""
    n = 600
    x = rng.normal(size=n)
    pr = 1 / (1 + np.exp(-(0.4 + 0.9 * x)))
    yy = np.where(rng.random(n) < pr, "yes", "no")  # sorted: no < yes
    m = sg.glm("outcome ~ x", {"outcome": yy, "x": x}, family="binomial")
    # success = "yes": slope positive and near the generating 0.9
    assert 0.5 < m.coefficients[1] < 1.4
    mu = sg.predict(m, {"outcome": yy, "x": x})
    assert np.all((mu > 0) & (mu < 1))
    # numeric check against fitting the 0/1 encoding directly
    m01 = sg.glm("y01 ~ x", {"y01": (yy == "yes").astype(float), "x": x},
                 family="binomial")
    np.testing.assert_allclose(m.coefficients, m01.coefficients, rtol=1e-8)


def test_factor_response_three_levels_rejected(rng):
    yy = np.array(["a", "b", "c"] * 10)
    x = rng.normal(size=30)
    with pytest.raises(ValueError, match="exactly 2 levels"):
        sg.glm("yy ~ x", {"yy": yy, "x": x}, family="binomial")
