"""Native CSV loader (+ Python fallback): parsing, NA, levels, sharding."""

import os

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data import io as sgio

CSV = """y,x1,grp,notes
1.5,2,a,hello
2.5,NA,b,"quoted, not split"
,4.0,a,
3.25,5e-1,NA,world
-1.0,6,c,bye
"""


@pytest.fixture()
def csv_path(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text(CSV)
    return str(p)


@pytest.fixture(params=["native", "python"])
def use_native(request):
    if request.param == "native" and not sg.native_available():
        pytest.skip("native loader unavailable")
    return request.param == "native"


def test_read_csv_basic(csv_path, use_native):
    cols = sg.read_csv(csv_path, native=use_native)
    assert list(cols) == ["y", "x1", "grp", "notes"]
    np.testing.assert_allclose(cols["y"], [1.5, 2.5, np.nan, 3.25, -1.0])
    np.testing.assert_allclose(cols["x1"], [2.0, np.nan, 4.0, 0.5, 6.0])
    assert cols["grp"].dtype == object
    assert list(cols["grp"]) == ["a", "b", "a", None, "c"]
    assert cols["notes"][1] == "quoted, not split"
    assert cols["notes"][2] is None


def test_read_csv_sharded_concat(tmp_path, use_native):
    rng = np.random.default_rng(0)
    n = 997  # awkward size
    p = tmp_path / "big.csv"
    y = rng.normal(size=n)
    g = rng.choice(["aa", "bb", "cc"], size=n)
    with open(p, "w") as f:
        f.write("y,g\n")
        for i in range(n):
            f.write(f"{float(y[i])!r},{g[i]}\n")
    full = sg.read_csv(str(p), native=use_native)
    parts = [sg.read_csv(str(p), shard_index=i, num_shards=4,
                         native=use_native) for i in range(4)]
    assert sum(len(q["y"]) for q in parts) == n
    np.testing.assert_allclose(np.concatenate([q["y"] for q in parts]),
                               full["y"])
    assert list(np.concatenate([q["g"] for q in parts])) == list(full["g"])


def test_native_matches_python(csv_path):
    if not sg.native_available():
        pytest.skip("native loader unavailable")
    a = sg.read_csv(csv_path, native=True)
    b = sg.read_csv(csv_path, native=False)
    assert list(a) == list(b)
    for k in a:
        if a[k].dtype == object:
            assert list(a[k]) == list(b[k])
        else:
            np.testing.assert_allclose(a[k], b[k])


QUOTED_CSV = (
    'name,"v",label\n'
    '"plain",1,"a,b"\n'
    '"esc""aped",2,"say ""hi"" now"\n'
    '  "spaced"  ,3,"  inner kept  "\n'
    '"",4,unquoted\n'
    '"last",5,"x"\n'
)


def test_quoted_field_parity_native_vs_python(tmp_path):
    """Escaped quotes, quoted commas, quoted headers and whitespace around
    quotes must parse identically through both loaders (ADVICE r1: they
    diverged on escaped quotes and strip order)."""
    p = tmp_path / "q.csv"
    p.write_text(QUOTED_CSV)
    expected_name = ["plain", 'esc"aped', "spaced", None, "last"]
    expected_label = ["a,b", 'say "hi" now', "  inner kept  ", "unquoted", "x"]
    for native in (True, False):
        if native and not sg.native_available():
            pytest.skip("native loader unavailable")
        cols = sg.read_csv(str(p), native=native)
        assert list(cols) == ["name", "v", "label"]
        assert list(cols["name"]) == expected_name
        assert list(cols["label"]) == expected_label
        np.testing.assert_allclose(cols["v"], [1, 2, 3, 4, 5])


def test_scan_csv_levels_global(tmp_path, use_native):
    p = tmp_path / "lv.csv"
    p.write_text("y,g,h\n1,zz,5\n2,aa,6\n3,mm,7\n4,aa,8\n")
    lv = sg.scan_csv_levels(str(p), native=use_native)
    assert lv == {"g": ["aa", "mm", "zz"]}


def test_read_csv_to_glm_end_to_end(tmp_path, mesh8, rng):
    """CSV -> formula -> fit: the full ingestion path."""
    n = 400
    x = rng.normal(size=n)
    g = rng.choice(["u", "v"], size=n)
    eta = 0.5 + 0.8 * x + 0.6 * (g == "v")
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(int)
    p = tmp_path / "fit.csv"
    with open(p, "w") as f:
        f.write("y,x,g\n")
        for i in range(n):
            f.write(f"{y[i]},{float(x[i])!r},{g[i]}\n")
    cols = sg.read_csv(str(p))
    m = sg.glm("y ~ x + g", cols, family="binomial", mesh=mesh8)
    assert m.converged
    assert m.xnames == ("intercept", "x", "g_v")
    assert np.all(np.abs(m.coefficients - [0.5, 0.8, 0.6]) < 0.5)


def test_schema_pins_kinds_across_shards(tmp_path, use_native):
    """A column numeric in one shard but stringy in another must type
    identically on every shard when a scanned schema is passed."""
    p = tmp_path / "mixed.csv"
    with open(p, "w") as f:
        f.write("y,v\n")
        for i in range(50):
            f.write(f"{i},{i * 1.5}\n")      # shard 0: v parses numeric
        for i in range(50):
            f.write(f"{i},tag{i % 3}\n")     # shard 1: v is stringy
    schema = sg.scan_csv_schema(str(p), native=use_native)
    assert schema["v"] == 1 and schema["y"] == 0
    parts = [sg.read_csv(str(p), shard_index=i, num_shards=2, schema=schema,
                         native=use_native) for i in range(2)]
    for q in parts:
        assert q["v"].dtype == object
    # without the schema, a shard seeing only the numeric region types v
    # numeric — the inconsistency the schema pin exists to prevent
    solo = sg.read_csv(str(p), shard_index=0, num_shards=4,
                       native=use_native)
    assert solo["v"].dtype != object


def test_schema_forced_numeric_coerces_bad_fields(csv_path, use_native):
    cols = sg.read_csv(csv_path, schema={"grp": 0}, native=use_native)
    assert cols["grp"].dtype == np.float64
    assert np.all(np.isnan(cols["grp"]))  # a/b/c coerce to NaN


def test_read_csv_shard_validation(csv_path):
    with pytest.raises(ValueError):
        sg.read_csv(csv_path, shard_index=2, num_shards=2)
    with pytest.raises(ValueError):
        sg.read_csv(csv_path, num_shards=0)


def test_read_csv_missing_file():
    with pytest.raises(OSError):
        sg.read_csv("/nonexistent/file.csv", native=sgio.native_available())


@pytest.mark.parametrize("seed", [42, 1337, 9001])
def test_native_csv_fuzz_parity(tmp_path, seed):
    """Randomized CSV content — quoted fields with commas and RFC-4180
    doubled quotes, missing-value spellings, mixed numeric/string
    columns, ragged rows — must parse identically through the C++ loader
    and the Python fallback, for whole-file and sharded reads.  Several
    seeds so the corpus actually varies (a single frozen draw could
    miss a divergence trigger forever)."""
    if not sg.native_available():
        pytest.skip("native loader unavailable")
    rng = np.random.default_rng(seed)

    strings = ["plain", "with,comma", 'dou""ble', "sp ace", "-3.5x",
               "NA", "", "0x1A", "tail  "]
    missing = ["", "NA", "NaN", "nan", "null", "NULL"]
    ncol = 5
    names = [f"c{j}" for j in range(ncol)]
    lines = [",".join(names)]
    for _ in range(500):
        fields = []
        for j in range(ncol):
            r = rng.random()
            if r < 0.15:
                fields.append(missing[rng.integers(0, len(missing))])
            elif j < 2 or r < 0.55:   # c0/c1 numeric-leaning
                v = float(rng.normal()) * 10 ** int(rng.integers(-8, 9))
                fields.append(repr(v) if rng.random() < 0.8 else f"{v:.3e}")
            else:
                s = strings[rng.integers(0, len(strings))]
                if '"' in s or "," in s or rng.random() < 0.2:
                    s = '"' + s.replace('"', '""') + '"'
                fields.append(s)
        if rng.random() < 0.1:
            fields = fields[: int(rng.integers(1, ncol))]  # ragged row
        lines.append(",".join(fields))
    p = tmp_path / "fuzz.csv"
    p.write_text("\n".join(lines) + "\n", encoding="utf-8")

    sch_n = sg.scan_csv_schema(str(p), native=True)
    sch_p = sg.scan_csv_schema(str(p), native=False)
    assert sch_n == sch_p
    assert sg.scan_csv_levels(str(p), native=True) == \
        sg.scan_csv_levels(str(p), native=False)
    for num_shards in (1, 4):
        for i in range(num_shards):
            a = sg.read_csv(str(p), shard_index=i, num_shards=num_shards,
                            schema=sch_p, native=True)
            b = sg.read_csv(str(p), shard_index=i, num_shards=num_shards,
                            schema=sch_p, native=False)
            assert list(a) == list(b)
            for k in a:
                if a[k].dtype == object:
                    assert list(a[k]) == list(b[k]), (k, i)
                else:
                    np.testing.assert_array_equal(a[k], b[k], err_msg=k)
