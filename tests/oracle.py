"""Independent float64 numpy/scipy oracles for OLS and GLM-IRLS.

Deliberately does NOT import sparkglm_tpu's family/link code — these are the
textbook formulas implemented separately (scipy.special based), matching R's
glm()/lm() semantics, which is the reference's stated correctness oracle
(SURVEY.md §4: "match R glm() coefficients to 1e-6").
"""

from __future__ import annotations

import numpy as np
from scipy import special as sp


class L:
    @staticmethod
    def make(name):
        return {
            "identity": (lambda m: m, lambda e: e, lambda m: np.ones_like(m)),
            "log": (np.log, np.exp, lambda m: 1 / m),
            "logit": (sp.logit, sp.expit, lambda m: 1 / (m * (1 - m))),
            "probit": (sp.ndtri, sp.ndtr,
                       lambda m: 1 / np.maximum(np.exp(-0.5 * sp.ndtri(m) ** 2) / np.sqrt(2 * np.pi), 1e-300)),
            "cloglog": (lambda m: np.log(-np.log1p(-m)),
                        lambda e: -np.expm1(-np.exp(e)),
                        lambda m: -1 / ((1 - m) * np.log1p(-m))),
            "inverse": (lambda m: 1 / m, lambda e: 1 / e, lambda m: -1 / m**2),
            "sqrt": (np.sqrt, lambda e: e**2, lambda m: 0.5 / np.sqrt(m)),
            "inverse_squared": (lambda m: 1 / m**2, lambda e: 1 / np.sqrt(e),
                                lambda m: -2 / m**3),
        }[name]


class F:
    @staticmethod
    def make(name):
        def xlogy(x, y):
            return sp.xlogy(x, y)

        if name == "gaussian":
            return dict(var=lambda m: np.ones_like(m),
                        dev=lambda y, m, w: w * (y - m) ** 2,
                        init=lambda y, w: y)
        if name == "binomial":
            return dict(var=lambda m: m * (1 - m),
                        dev=lambda y, m, w: 2 * w * (xlogy(y, y) - xlogy(y, m)
                                                     + xlogy(1 - y, 1 - y) - xlogy(1 - y, 1 - m)),
                        init=lambda y, w: (w * y + 0.5) / (w + 1))
        if name == "poisson":
            return dict(var=lambda m: m,
                        dev=lambda y, m, w: 2 * w * (xlogy(y, y) - xlogy(y, m) - (y - m)),
                        init=lambda y, w: y + 0.1)
        if name == "gamma":
            return dict(var=lambda m: m**2,
                        dev=lambda y, m, w: -2 * w * (np.log(np.maximum(y, 1e-300) / m) - (y - m) / m),
                        init=lambda y, w: np.maximum(y, 1e-10))
        if name == "inverse_gaussian":
            return dict(var=lambda m: m**3,
                        dev=lambda y, m, w: w * (y - m) ** 2 / (y * m * m),
                        init=lambda y, w: np.maximum(y, 1e-10))
        raise KeyError(name)


def ols_np(X, y, w=None):
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    if w is None:
        w = np.ones_like(y)
    Xw = X * w[:, None]
    beta = np.linalg.solve(Xw.T @ X, Xw.T @ y)
    return beta


def irls_np(X, y, family, link, wt=None, offset=None, tol=1e-12, max_iter=200):
    """R-style IRLS to tight tolerance; returns (beta, deviance, iters, cov)."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    n = len(y)
    wt = np.ones(n) if wt is None else np.asarray(wt, np.float64)
    off = np.zeros(n) if offset is None else np.asarray(offset, np.float64)
    g, ginv, gprime = L.make(link)
    fam = F.make(family)
    mu = fam["init"](y, wt)
    eta = g(mu)
    dev = fam["dev"](y, mu, wt).sum()
    beta = np.zeros(X.shape[1])
    XtWXi = None
    for it in range(1, max_iter + 1):
        gp = gprime(mu)
        w = wt / (fam["var"](mu) * gp**2)
        z = eta - off + (y - mu) * gp
        Xw = X * w[:, None]
        XtWX = Xw.T @ X
        beta = np.linalg.solve(XtWX, Xw.T @ z)
        XtWXi = np.linalg.inv(XtWX)
        eta = X @ beta + off
        mu = ginv(eta)
        if family == "binomial":
            mu = np.clip(mu, 1e-10, 1 - 1e-10)
        dev_new = fam["dev"](y, mu, wt).sum()
        if abs(dev_new - dev) < tol * (abs(dev_new) + 0.1):
            dev = dev_new
            break
        dev = dev_new
    return beta, dev, it, XtWXi
