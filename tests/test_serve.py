"""Online serving (sparkglm_tpu/serve): registry, compiled-scorer cache,
micro-batching — plus the satellite contracts (serialize schema_version,
histogram quantiles, predict-from-path trace events).

The load-bearing assertion throughout: serving is numerics-NEUTRAL.  A
served request, padded to any power-of-2 bucket and possibly coalesced
into a micro-batch, must be BIT-identical to an offline ``sg.predict`` on
the same rows (PARITY.md).
"""

import json
import threading
import time

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.obs.metrics import Histogram, MetricsRegistry
from sparkglm_tpu.robust import Overloaded, RetryPolicy, TransientSourceError
from sparkglm_tpu.serve import BatchPolicy, MicroBatcher, ModelRegistry, Scorer


@pytest.fixture
def poisson_offset_model(rng):
    """A GLM with a fit-time by-name offset — the offset must travel
    through the serving path exactly as through sg.predict."""
    n = 600
    x = rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    lt = rng.uniform(0.1, 0.9, n)
    y = rng.poisson(np.exp(0.4 + 0.5 * x + 0.6 * (g == "b") + lt)).astype(float)
    d = {"y": y, "x": x, "g": g, "lt": lt}
    return sg.glm("y ~ x + g + offset(lt)", d, family="poisson"), d


@pytest.fixture
def binomial_grouped_model(rng):
    """Grouped binomial (cbind successes/failures) — response scoring goes
    through the logit inverse link."""
    n = 500
    x = rng.standard_normal(n)
    m_tot = rng.integers(5, 30, n).astype(float)
    p = 1.0 / (1.0 + np.exp(-(0.3 + 0.8 * x)))
    s = rng.binomial(m_tot.astype(int), p).astype(float)
    d = {"s": s, "f": m_tot - s, "x": x}
    return sg.glm("cbind(s, f) ~ x", d, family="binomial"), d


def _newdata(rng, d, size):
    idx = rng.integers(0, len(next(iter(d.values()))), size)
    return {k: np.asarray(v)[idx] for k, v in d.items()}


# ---------------------------------------------------------------------------
# registry: register / load / deploy / rollback
# ---------------------------------------------------------------------------

def test_registry_register_deploy_rollback(poisson_offset_model, rng):
    m, d = poisson_offset_model
    m2 = sg.glm("y ~ x + offset(lt)", d, family="poisson")
    reg = ModelRegistry()

    assert reg.register("traffic", m) == 1
    assert reg.deployed_version("traffic") == 1          # first auto-deploys
    assert reg.register("traffic", m2) == 2
    assert reg.deployed_version("traffic") == 1          # staged, not live
    assert reg.versions("traffic") == (1, 2)
    assert reg.model("traffic") is m
    assert reg.model("traffic", 2) is m2

    reg.deploy("traffic", 2)
    assert reg.deployed_version("traffic") == 2
    assert reg.rollback("traffic") == 1
    assert reg.model("traffic") is m
    # rollback is a stack: a fresh single-deployment name cannot roll back
    reg2 = ModelRegistry()
    reg2.register("solo", m)
    with pytest.raises(RuntimeError, match="no prior deployment"):
        reg2.rollback("solo")
    with pytest.raises(KeyError, match="no model registered"):
        reg.scorer("nope")
    with pytest.raises(KeyError, match="no version 9"):
        reg.deploy("traffic", 9)


def test_registry_load_from_disk_and_serve(poisson_offset_model, tmp_path, rng):
    """Artifacts load through serialize.py (terms travel) and serve
    bit-identically to the in-memory model."""
    m, d = poisson_offset_model
    p = str(tmp_path / "m.npz")
    m.save(p)
    reg = ModelRegistry()
    assert reg.load("traffic", p) == 1
    sc = reg.scorer("traffic")
    new = _newdata(rng, d, 23)
    np.testing.assert_array_equal(sc.score(new), sg.predict(m, new))


def test_registry_scorer_cached_per_deployment(poisson_offset_model):
    m, d = poisson_offset_model
    reg = ModelRegistry()
    reg.register("traffic", m)
    assert reg.scorer("traffic") is reg.scorer("traffic")
    reg.register("traffic", m, deploy=True)     # redeploy invalidates cache
    sc2 = reg.scorer("traffic")
    assert sc2 is reg.scorer("traffic")


# ---------------------------------------------------------------------------
# scorer: bit-identity across EVERY padding bucket + zero recompiles
# ---------------------------------------------------------------------------

def test_served_bit_identical_every_bucket_offset_model(
        poisson_offset_model, rng):
    """One request size per padding bucket (plus edges): served ==
    sg.predict exactly, for a model whose offset travels by name."""
    sc = Scorer(poisson_offset_model[0], min_bucket=8)
    buckets = sc.warmup(buckets=(8, 16, 32, 64, 128))
    assert buckets == (8, 16, 32, 64, 128)
    m, d = poisson_offset_model
    for size in (1, 7, 8, 9, 16, 31, 32, 57, 64, 100, 128):
        new = _newdata(rng, d, size)
        np.testing.assert_array_equal(sc.score(new), sg.predict(m, new))
        assert sc.bucket_for(size) in sc.buckets
    assert sc.compiles == 0, "steady-state serving must never recompile"


def test_served_bit_identical_grouped_binomial_se_fit(
        binomial_grouped_model, rng):
    m, d = binomial_grouped_model
    sc = Scorer(m, se_fit=True, min_bucket=8)
    sc.warmup(buckets=(8, 16, 32, 64))
    for size in (3, 8, 20, 33, 64):
        new = _newdata(rng, d, size)
        fit_s, se_s = sc.score(new)
        fit_o, se_o = sg.predict(m, new, se_fit=True)
        np.testing.assert_array_equal(fit_s, fit_o)
        np.testing.assert_array_equal(se_s, se_o)
    assert sc.compiles == 0


def test_scorer_link_scale_and_explicit_offset(poisson_offset_model, rng):
    m, d = poisson_offset_model
    sc = Scorer(m, type="link")
    new = _newdata(rng, d, 11)
    np.testing.assert_array_equal(sc.score(new),
                                  sg.predict(m, new, type="link"))
    ov = rng.uniform(0, 1, 11)
    np.testing.assert_array_equal(
        sc.score(new, offset=ov),
        sg.predict(m, new, type="link", offset=ov))


def test_scorer_design_matrix_requests(rng):
    """Array-fit models (no terms) serve aligned designs; dict data is
    refused with the sg.predict message."""
    X = np.column_stack([np.ones(300), rng.standard_normal((300, 3))])
    y = X @ rng.standard_normal(4) + 0.1 * rng.standard_normal(300)
    m = sg.lm_fit(X, y)
    sc = Scorer(m)
    Xn = np.column_stack([np.ones(17), rng.standard_normal((17, 3))])
    np.testing.assert_array_equal(sc.score(Xn), m.predict(Xn))
    with pytest.raises(ValueError, match="fit from arrays"):
        sc.score({"x": np.zeros(3)})
    with pytest.raises(ValueError, match="model expects"):
        sc.score(np.zeros((5, 9)))
    with pytest.raises(ValueError, match=">= 1 row"):
        sc.score(np.zeros((0, 4)))


def test_scorer_validation():
    d = {"y": np.arange(20.0), "x": np.arange(20.0)}
    m = sg.lm("y ~ x", d)
    with pytest.raises(ValueError, match="type must be"):
        Scorer(m, type="bogus")
    with pytest.raises(ValueError, match="min_bucket"):
        Scorer(m, min_bucket=0)
    sc = Scorer(m, min_bucket=4)
    assert [sc.bucket_for(k) for k in (1, 4, 5, 8, 9)] == [4, 4, 8, 8, 16]


# ---------------------------------------------------------------------------
# micro-batching: coalescing bit-neutrality, ordering, backpressure
# ---------------------------------------------------------------------------

def test_microbatcher_coalesced_results_bit_identical(
        poisson_offset_model, rng):
    """A burst of same-signature requests coalesces (fewer kernel calls
    than requests) and every sliced result equals offline sg.predict."""
    m, d = poisson_offset_model
    met = MetricsRegistry()
    sc = Scorer(m, min_bucket=8, metrics=met, name="traffic")
    sc.warmup(buckets=(8, 16, 32, 64, 128, 256))
    with MicroBatcher(sc, BatchPolicy(max_batch=128, max_delay_ms=20),
                      metrics=met) as mb:
        wants, futs = [], []
        for i in range(30):
            new = _newdata(rng, d, (i % 9) + 1)
            wants.append(sg.predict(m, new))
            futs.append(mb.submit(new))
        for want, fut in zip(wants, futs):
            np.testing.assert_array_equal(fut.result(10), want)
    snap = met.snapshot()
    assert snap["counters"]["serve.traffic.batches"] < 30, \
        "burst should coalesce into fewer kernel calls than requests"
    assert snap["counters"]["serve.traffic.batched_rows"] == \
        sum((i % 9) + 1 for i in range(30))
    lat = snap["histograms"]["serve.traffic.latency_s"]
    assert lat["count"] == 30 and lat["p50"] is not None \
        and lat["p99"] is not None
    assert snap["gauges"]["serve.traffic.rows_per_s"] is None or \
        snap["gauges"]["serve.traffic.rows_per_s"] > 0


def test_microbatcher_error_isolated_in_order(poisson_offset_model, rng):
    """A bad request (unknown level reaches the strict transform? use a
    missing column) fails ITS future; requests before and after still
    serve.  Different signature -> it cannot poison a shared batch."""
    m, d = poisson_offset_model
    sc = Scorer(m)
    with MicroBatcher(sc, BatchPolicy(max_delay_ms=5)) as mb:
        good1 = _newdata(rng, d, 5)
        bad = {"x": np.zeros(4)}                      # missing g / lt
        good2 = _newdata(rng, d, 6)
        f1, fb, f2 = mb.submit(good1), mb.submit(bad), mb.submit(good2)
        np.testing.assert_array_equal(f1.result(10), sg.predict(m, good1))
        with pytest.raises(Exception):
            fb.result(10)
        np.testing.assert_array_equal(f2.result(10), sg.predict(m, good2))


class _BlockingScorer:
    """Scorer stand-in whose score() parks until released — makes the
    queue-full path deterministic."""

    metrics = None
    name = "blocked"

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def score(self, data, *, offset=None):
        self.entered.set()
        assert self.release.wait(10)
        n = (data.shape[0] if isinstance(data, np.ndarray)
             else len(next(iter(data.values()))))
        return np.zeros(n)


def test_microbatcher_overload_is_typed_and_transient():
    bs = _BlockingScorer()
    met = MetricsRegistry()
    mb = MicroBatcher(bs, BatchPolicy(max_queue=2, max_delay_ms=0),
                      metrics=met, name="blocked")
    try:
        first = mb.submit(np.zeros((1, 2)))     # thread takes it, parks
        assert bs.entered.wait(10)
        held = [mb.submit(np.zeros((1, 2))) for _ in range(2)]  # fills queue
        with pytest.raises(Overloaded) as ei:
            mb.submit(np.zeros((1, 2)))
        # typed backpressure: client retry policies classify it transient
        assert isinstance(ei.value, TransientSourceError)
        assert RetryPolicy().is_transient(ei.value)
        assert met.snapshot()["counters"]["serve.blocked.overloaded"] == 1
    finally:
        bs.release.set()
        mb.close()
    for f in [first] + held:
        assert f.result(10) is not None
    with pytest.raises(RuntimeError, match="closed"):
        mb.submit(np.zeros((1, 2)))


def test_batch_policy_validation():
    with pytest.raises(ValueError, match="max_batch"):
        BatchPolicy(max_batch=0)
    with pytest.raises(ValueError, match="max_delay_ms"):
        BatchPolicy(max_delay_ms=-1)
    with pytest.raises(ValueError, match="max_queue"):
        BatchPolicy(max_queue=0)


# ---------------------------------------------------------------------------
# satellites: serialize schema_version, histogram quantiles, path tracing
# ---------------------------------------------------------------------------

def test_serialize_schema_version_roundtrip_and_forward_refusal(
        rng, tmp_path):
    d = {"y": rng.standard_normal(50), "x": rng.standard_normal(50)}
    m = sg.lm("y ~ x", d)
    p = str(tmp_path / "m.npz")
    m.save(p)
    # current artifacts round-trip and carry schema_version
    m2 = sg.load_model(p)
    np.testing.assert_array_equal(m2.coefficients, m.coefficients)
    with np.load(p) as z:
        meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    assert isinstance(meta["schema_version"], int)
    # forge a FUTURE artifact with fields this build does not know
    meta["schema_version"] = meta["schema_version"] + 7
    meta["calibration_curve"] = [1, 2, 3]
    meta["monotone_constraints"] = "auto"
    header = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    fut = str(tmp_path / "future.npz")
    np.savez(fut, __meta__=header, **arrays)
    with pytest.raises(ValueError) as ei:
        sg.load_model(fut)
    msg = str(ei.value)
    assert "schema_version" in msg
    assert "calibration_curve" in msg and "monotone_constraints" in msg
    assert "upgrade" in msg


def test_histogram_quantiles():
    h = Histogram()
    assert h.quantile(0.5) is None                  # empty
    for v in [0.001] * 50 + [0.002] * 45 + [5.0] * 5:
        h.observe(v)
    assert h.quantile(0.0) == pytest.approx(0.001)  # clamps to observed min
    assert h.quantile(1.0) == pytest.approx(5.0)    # clamps to observed max
    p50, p99 = h.quantile(0.5), h.quantile(0.99)
    assert 0.0005 <= p50 <= 0.004                   # within its log2 bucket
    assert 2.0 <= p99 <= 5.0
    assert p50 <= p99
    with pytest.raises(ValueError, match="in \\[0, 1\\]"):
        h.quantile(1.5)
    snap = h.snapshot()
    assert snap["p50"] == p50 and snap["p99"] == p99
    # quantiles survive JSON export (the SLO scrape path)
    reg = MetricsRegistry()
    reg.histogram("lat").observe(0.25)
    out = json.loads(reg.to_json())
    assert out["histograms"]["lat"]["p50"] == 0.25


def test_predict_from_path_emits_read_and_score_events(
        poisson_offset_model, tmp_path):
    """Out-of-core scoring is observable like fitting: reader `read`
    events flow through the ambient tracer and each chunk emits `score`
    with rows/seconds."""
    import csv as csv_mod
    from sparkglm_tpu.obs.trace import FitTracer, RingBufferSink

    m, d = poisson_offset_model
    p = tmp_path / "serve_in.csv"
    with open(p, "w", newline="") as fh:
        w = csv_mod.writer(fh)
        w.writerow(list(d))
        for i in range(len(d["y"])):
            w.writerow([d[k][i] for k in d])
    sink = RingBufferSink(512)
    met = MetricsRegistry()
    out = str(tmp_path / "scored.csv")
    ret = sg.predict(m, str(p), chunk_bytes=1 << 12, out_path=out,
                     trace=FitTracer([sink], metrics=met), metrics=met)
    assert ret == out
    events = list(sink.events)
    reads = [e for e in events if e.kind == "read"]
    scores = [e for e in events if e.kind == "score"]
    assert len(reads) >= 2 and len(scores) >= 2
    assert all(e.fields["rows"] >= 1 for e in scores)
    assert all(e.fields["seconds"] >= 0 for e in scores)
    assert all(e.fields["out"] == "file" for e in scores)
    snap = met.snapshot()
    assert snap["counters"]["events.score"] == len(scores)
    assert snap["counters"]["events.read"] == len(reads)
    # scored rows across chunks == file rows
    assert sum(e.fields["rows"] for e in scores) == len(d["y"])
