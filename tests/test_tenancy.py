"""Elastic tenancy under fire (ISSUE r16, ROADMAP item 2).

The three-legged elasticity plane, chaos-tested:

  * zero-downtime growth — a bucket-crossing tenant registration under
    LIVE traffic: the warm-then-swap coordinator (serve/growth.py)
    compiles the next tenant bucket off the hot path, so post-growth
    serving pays zero recompiles, drops zero requests, and scores the
    old tenants byte-identically;
  * sharded continuous learning — ``ShardedOnlineLoop`` statistics
    combine bit-identically to an unsharded control, and a REAL SIGKILL
    mid-chunk resumes every shard from its own WAL into the same bytes;
  * multi-engine serving — a pool engine dying mid-load (all its
    replicas fail) has its queued futures resubmitted on the survivor:
    every accepted request resolves, zero lost.

The ``ModelFamily`` growth-boundary serialization round-trip (deploy
history, generation counter, sticky A/B splits) rides along.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sparkglm_tpu.fleet import glm_fit_fleet
from sparkglm_tpu.online import OnlineLoop, ShardedOnlineLoop, shard_of
from sparkglm_tpu.robust import FaultPlan
from sparkglm_tpu.serve import (EnginePolicy, EnginePool, FamilyGrowth,
                                FamilyScorer, HealthPolicy, ModelFamily,
                                family_score_cache_size, tenant_bucket)

pytestmark = pytest.mark.tenancy

P = 3


def _labels(K, prefix="t"):
    return tuple(f"{prefix}{i:02d}" for i in range(K))


def _fit_fleet(labels, beta, n=48, seed=0):
    r = np.random.default_rng(seed)
    K = len(labels)
    X = r.normal(size=(K, n, P))
    y = np.stack([X[k] @ beta[k] + 0.05 * r.normal(size=n)
                  for k in range(K)])
    return glm_fit_fleet(X, y, family="gaussian", link="identity",
                         labels=labels)


def _seed_family(labels, beta, name, n=48, seed=0):
    return ModelFamily.from_fleet(_fit_fleet(labels, beta, n=n, seed=seed),
                                  name)


def _chunk(labels, beta, rows_per, seed, noise=0.05):
    r = np.random.default_rng(seed)
    ten, Xs, ys = [], [], []
    for k, t in enumerate(labels):
        X = r.normal(size=(rows_per, P))
        ten.extend([t] * rows_per)
        Xs.append(X)
        ys.append(X @ beta[k] + noise * r.normal(size=rows_per))
    return np.array(ten), np.concatenate(Xs), np.concatenate(ys)


# ---------------------------------------------------------------------------
# satellite: serialization round-trip across a bucket-growth boundary
# ---------------------------------------------------------------------------

def test_family_roundtrip_across_growth_boundary(tmp_path):
    """Grow a family across the power-of-2 tenant bucket, mutate its
    deploy history, then serialize: deploy history, generation counter
    and sticky A/B assignments all survive the round trip byte-for-byte,
    and the artifact itself is byte-deterministic."""
    rng = np.random.default_rng(3)
    labels = _labels(7)
    beta = rng.normal(size=(11, P))
    fleet = _fit_fleet(labels, beta[:7], seed=3)
    fam = ModelFamily.from_fleet(fleet, "boundary")
    # history: a v2 deploy and a rollback before the boundary
    fam.register(labels[0], fleet[1], deploy=True)
    fam.register(labels[1], fleet[2], deploy=True)
    fam.rollback(labels[1])
    assert tenant_bucket(len(fam)) == 8

    new_labels = _labels(4, prefix="u")
    new_fleet = _fit_fleet(new_labels, beta[7:], seed=4)
    FamilyGrowth(fam).grow({t: new_fleet[k]
                            for k, t in enumerate(new_labels)})
    assert len(fam) == 11 and tenant_bucket(len(fam)) == 16
    # and more history AFTER the boundary
    fam.register(new_labels[0], new_fleet[1], deploy=True)
    gen = fam.generation()
    assert gen > 0

    path = str(tmp_path / "grown.npz")
    fam.save(path)
    back = ModelFamily  # loaded via the serialize front-end
    from sparkglm_tpu.models.serialize import load_model
    fam2 = load_model(path)
    assert isinstance(fam2, back)

    # generation counter and the FULL deploy state round-trip
    assert fam2.generation() == gen
    m1, meta1 = fam._export()
    m2, meta2 = fam2._export()
    assert meta1 == meta2  # name, deployed, history, generation
    assert [(t, v) for t, v, _ in m1] == [(t, v) for t, v, _ in m2]
    for (_, _, a), (_, _, b) in zip(m1, m2):
        assert (np.asarray(a.coefficients).tobytes()
                == np.asarray(b.coefficients).tobytes())
    t_a, B_a = fam.deployed_matrix()
    t_b, B_b = fam2.deployed_matrix()
    assert t_a == t_b and B_a.tobytes() == B_b.tobytes()

    # sticky A/B splits: same challenger config over the restored family
    # routes every key to the same arm and serves identical bytes
    ch = {labels[0]: 1, new_labels[0]: 1}
    keys = np.array([f"user-{i}" for i in range(64)])
    tq = np.array(([labels[0], new_labels[0], labels[3], new_labels[2]]
                   * 16))
    Xq = rng.normal(size=(64, P))
    s1 = FamilyScorer(fam, challenger=ch, ab_fraction=0.37)
    s2 = FamilyScorer(fam2, challenger=ch, ab_fraction=0.37)
    assert (s1.assignments(tq, keys).tobytes()
            == s2.assignments(tq, keys).tobytes())
    assert (np.asarray(s1.score(tq, Xq, keys=keys)).tobytes()
            == np.asarray(s2.score(tq, Xq, keys=keys)).tobytes())

    # byte-deterministic artifact: save(load(save(x))) == save(x)
    p2 = str(tmp_path / "again.npz")
    fam2.save(p2)
    assert open(path, "rb").read() == open(p2, "rb").read()


# ---------------------------------------------------------------------------
# chaos leg a: bucket growth during live traffic
# ---------------------------------------------------------------------------

def test_growth_under_live_traffic_zero_lost_zero_recompiles():
    """Cross the tenant bucket while a traffic thread hammers the pool:
    every submitted request resolves (zero lost), the post-growth hot
    path compiles NOTHING (kernel_cache_delta == 0 — the warm phase
    prepaid it), and old-tenant scoring is byte-identical across the
    swap."""
    import jax
    rng = np.random.default_rng(7)
    labels = _labels(6)
    beta = rng.normal(size=(10, P))
    fam = _seed_family(labels, beta[:6], "live-grow", seed=7)
    new_labels = _labels(4, prefix="u")
    new_fleet = _fit_fleet(new_labels, beta[6:], seed=8)

    Xq = rng.normal(size=(16, P))
    tq0 = labels[0]
    pool = EnginePool(fam, 2, policy=EnginePolicy(max_batch=64),
                      devices=jax.devices()[:2])
    try:
        # steady state: both engines warm at batch bucket 16
        for _ in range(4):
            pool.submit(Xq, tenant=tq0).result(timeout=60)
        out_before = np.asarray(pool.submit(Xq, tenant=tq0)
                                .result(timeout=60))
        compiles_before = [sc.compiles for sc in pool.scorers]

        stop = threading.Event()
        futs, submit_errors = [], []

        def traffic():
            i = 0
            while not stop.is_set():
                try:
                    futs.append(pool.submit(Xq, tenant=labels[i % 6]))
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    submit_errors.append(e)
                    return
                i += 1
                time.sleep(0.002)

        t = threading.Thread(target=traffic)
        t.start()
        try:
            growth = FamilyGrowth(fam, scorers=pool.scorers)
            rep = growth.grow({t_: new_fleet[k]
                               for k, t_ in enumerate(new_labels)})
            time.sleep(0.1)  # post-swap traffic on the grown tables
        finally:
            stop.set()
            t.join(timeout=30)
        assert not submit_errors, submit_errors
        assert rep["crossed"] and rep["tenants"] == 10
        assert sum(r["compiles"] for r in rep["prewarm"]) >= 0

        # zero lost: every accepted future resolves with a finite value
        for f in futs:
            assert np.all(np.isfinite(np.asarray(f.result(timeout=60))))
        assert pool.stats()["lost"] == 0
        assert len(futs) > 10  # traffic genuinely overlapped the growth

        # zero steady-state recompiles, measured TWO ways: the scorer
        # counters and the process-wide kernel cache
        cache_after_growth = family_score_cache_size()
        out_after = np.asarray(pool.submit(Xq, tenant=tq0)
                               .result(timeout=60))
        out_new = np.asarray(pool.submit(Xq, tenant=new_labels[0])
                             .result(timeout=60))
        assert [sc.compiles for sc in pool.scorers] == compiles_before
        assert family_score_cache_size() - cache_after_growth == 0

        # bit-identical old-tenant scoring across the swap, correct new
        assert out_before.tobytes() == out_after.tobytes()
        exp = Xq @ np.asarray(fam.model(new_labels[0]).coefficients,
                              np.float64)
        np.testing.assert_allclose(out_new, exp, rtol=0, atol=1e-6)
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# chaos leg c: an engine dies mid-load, its queue resubmits, zero lost
# ---------------------------------------------------------------------------

def test_engine_death_mid_load_reroutes_zero_lost():
    """Kill engine 0 mid-flight (every replica dead after its first
    dispatch): futures already queued there fail inside the engine and
    the pool resubmits each on the survivor — all requests resolve
    correctly, zero lost, and the pool's breaker records the failures."""
    import jax
    rng = np.random.default_rng(11)
    labels = _labels(8)
    beta = rng.normal(size=(8, P))
    fam = _seed_family(labels, beta, "eng-death", seed=11)

    dying = FaultPlan(seed=0, replica_dead_from=((0, 1), (1, 1)))
    pool = EnginePool(
        fam, 2, policy=EnginePolicy(max_batch=8),
        devices=jax.devices()[:2],
        engine_fault_plans={0: dying},
        # fail fast INSIDE the dying engine (no in-engine retry ladder)
        # so its queued futures surface to the pool's resubmit hook; the
        # pool-level breaker keeps the ejection sticky for the assert
        engine_health=HealthPolicy(eject_after=1, probe_cooldown_s=0.05,
                                   max_attempts=1),
        health=HealthPolicy(eject_after=3, probe_cooldown_s=60.0))
    try:
        reqs = []
        for i in range(60):
            t = labels[i % 8]
            Xr = rng.normal(size=(4, P))
            reqs.append((t, Xr, pool.submit(Xr, tenant=t)))
        for t, Xr, f in reqs:
            out = np.asarray(f.result(timeout=120))
            exp = Xr @ np.asarray(fam.model(t).coefficients, np.float64)
            np.testing.assert_allclose(out, exp, rtol=0, atol=1e-6)
        st = pool.stats()
        assert st["lost"] == 0
        assert st["resubmits"] > 0  # the mid-flight queue re-routed
        assert dying.faults_fired > 0
        assert st["states"][0] == "ejected"  # the breaker saw the death
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# chaos leg b: SIGKILL a sharded writer mid-chunk, resume bit-identical
# ---------------------------------------------------------------------------

_SHARD_KILL_SCRIPT = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from sparkglm_tpu.fleet import glm_fit_fleet
from sparkglm_tpu.serve import ModelFamily
from sparkglm_tpu.online import OnlineLoop, ShardedOnlineLoop
from sparkglm_tpu.robust import FaultPlan

P = 3
labels = tuple(f"t{i:02d}" for i in range(8))  # crc32 splits 4/4 over 2
beta = np.random.default_rng(11).normal(size=(8, P))
KW = dict(rho=0.9, window_rows=24, reference_chunks=2, window_chunks=2)

def chunk(s):
    r = np.random.default_rng(1000 + s)
    ten, Xs, ys = [], [], []
    for k, t in enumerate(labels):
        X = r.normal(size=(12, P))
        ten.extend([t] * 12)
        Xs.append(X)
        ys.append(X @ (beta[k] + 0.15 * s) + 0.05 * r.normal(size=12))
    return np.array(ten), np.concatenate(Xs), np.concatenate(ys)

def seed_family(name):
    r = np.random.default_rng(0)
    X = r.normal(size=(8, 48, P))
    y = np.stack([X[k] @ beta[k] + 0.05 * r.normal(size=48)
                  for k in range(8)])
    fleet = glm_fit_fleet(X, y, family="gaussian", link="identity",
                          labels=labels)
    return ModelFamily.from_fleet(fleet, name)

def fingerprint(s):
    t, B = s.family.deployed_matrix()
    # per-SHARD versions: the WAL contract replays each shard family
    # bit-for-bit.  The reassembled MASTER's version counters restart
    # (it is rebuilt from shard champions), but its deployed bytes are
    # asserted identical via `deployed`.
    return dict(chunks=s._chunks, combined=s.digest(),
                shards=list(s.shard_digests()),
                deployed=B.tobytes().hex(),
                versions=[{x: lp.family.deployed_version(x)
                           for x in lp.family.tenants()}
                          for lp in s.loops])

mode, root, out = sys.argv[1], sys.argv[2], sys.argv[3]
N = 8
chunks = [chunk(s) for s in range(N)]
if mode == "healthy":
    s = ShardedOnlineLoop(seed_family("s"), 2, **KW)
    u = OnlineLoop(seed_family("u"), **KW)
    for c in chunks:
        s.step(*c)
        u.step(*c)
    fp = fingerprint(s)
    # the sharded plane's combined statistics ARE the unsharded loop's
    fp["unsharded_combined_equal"] = bool(
        s.digest() == u.suffstats.digest())
elif mode == "killed":
    s = ShardedOnlineLoop(seed_family("s"), 2, journal=root, **KW)
    # SIGKILL fires at the chunk-5 boundary: both shard WALs have 4
    # applied chunks, the 5th never lands anywhere
    s.run(lambda: iter(chunks), fault_plan=FaultPlan(
        seed=0, kill_chunk_at=(5,)))
    raise SystemExit("unreachable: the kill must fire")
elif mode == "resume":
    s = ShardedOnlineLoop.resume(root)
    assert s._chunks == 4, f"expected chunk boundary 4, got {s._chunks}"
    for c in chunks[s._chunks:]:
        s.step(*c)
    fp = fingerprint(s)
else:
    raise SystemExit(f"bad mode {mode}")
with open(out, "w") as f:
    json.dump(fp, f, sort_keys=True)
"""


def test_shard_writer_sigkill_resume_bit_identical(tmp_path):
    """A REAL ``kill -9`` takes the sharded learning plane down
    mid-stream; every shard resumes from its own WAL and the finished
    run's combined digest, per-shard digests and deploy decisions equal
    the uninterrupted sharded run's — which itself matches the unsharded
    control bit-for-bit."""
    script = tmp_path / "shard_kill_child.py"
    script.write_text(_SHARD_KILL_SCRIPT)
    root = str(tmp_path / "wal-root")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    def run(mode, out):
        return subprocess.run(
            [sys.executable, str(script), mode, root, str(out)],
            env=env, capture_output=True, text=True, timeout=300)

    h = run("healthy", tmp_path / "healthy.json")
    assert h.returncode == 0, h.stderr[-2000:]

    k = run("killed", tmp_path / "killed.json")
    assert k.returncode == -signal.SIGKILL, \
        f"expected SIGKILL, got rc={k.returncode}: {k.stderr[-2000:]}"
    assert not (tmp_path / "killed.json").exists()
    # each shard has its own WAL directory with a snapshot base
    shard_dirs = sorted(d for d in os.listdir(root)
                        if d.startswith("shard-"))
    assert len(shard_dirs) == 2
    for d in shard_dirs:
        assert any(f.startswith("snapshot-")
                   for f in os.listdir(os.path.join(root, d))), d

    r = run("resume", tmp_path / "resumed.json")
    assert r.returncode == 0, r.stderr[-2000:]

    healthy = json.loads((tmp_path / "healthy.json").read_text())
    resumed = json.loads((tmp_path / "resumed.json").read_text())
    assert healthy.pop("unsharded_combined_equal") is True
    assert resumed == healthy, \
        "shard resume after SIGKILL must reproduce the healthy run"


# ---------------------------------------------------------------------------
# sharded-vs-unsharded bit-identity and growth routing, in-process
# ---------------------------------------------------------------------------

def test_sharded_loop_combines_bit_identical_and_grows():
    """The sharded plane's combined suffstats equal the unsharded
    control's bytes at every chunk boundary, the information-weighted
    combined solve equals the unsharded solve, and growth routes new
    tenants to their stable hash shards."""
    rng = np.random.default_rng(5)
    labels = _labels(8)
    beta = rng.normal(size=(10, P))
    fam_u = _seed_family(labels, beta[:8], "ctrl", seed=5)
    fam_s = _seed_family(labels, beta[:8], "shrd", seed=5)
    kw = dict(reference_chunks=2, window_chunks=2)
    u = OnlineLoop(fam_u, **kw)
    s = ShardedOnlineLoop(fam_s, 2, **kw)
    for c in range(5):
        ten, Xc, yc = _chunk(labels, beta[:8], 8, seed=100 + c)
        u.step(ten, Xc, yc)
        s.step(ten, Xc, yc)
        assert s.digest() == u.suffstats.digest(), f"chunk {c}"
    lab, bc = s.combined_solve(jitter=0.0)
    assert lab == labels
    np.testing.assert_allclose(bc, u.suffstats.solve(), rtol=0, atol=1e-12)

    new_labels = _labels(2, prefix="u")
    new_fleet = _fit_fleet(new_labels, beta[8:], seed=6)
    rep = s.grow({t: new_fleet[k] for k, t in enumerate(new_labels)})
    assert rep["tenants"] == 10
    for t in new_labels:
        assert t in s.loops[shard_of(t, 2)].labels
        assert t in s.family.tenants()
    # post-growth chunks keep stepping (the grown shard migrated its
    # rings and gate; old tenants' accumulated mass is untouched)
    all_labels = labels + new_labels
    ten, Xc, yc = _chunk(all_labels, beta, 6, seed=300)
    out = s.step(ten, Xc, yc)
    assert out["chunk"] == 6
    comb = s.combined_suffstats()
    assert comb.labels == tuple(sorted(all_labels))
