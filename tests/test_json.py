"""NDJSON ingestion tier — the reference's own fixture format
(testData.scala:10-15 loads test data with Spark's JSON reader).  Same
contracts as the CSV/Parquet readers; closes VERDICT r3 missing #1's
JSON leg."""

import json

import numpy as np
import pytest

import sparkglm_tpu as sg


def _write_ndjson(path, rows):
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")


@pytest.fixture()
def json_data(tmp_path, rng):
    n = 1500
    x = np.round(rng.normal(size=n), 6)
    grp = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    lam = np.exp(0.3 + 0.5 * x - 0.4 * (grp == "b"))
    y = rng.poisson(lam).astype(float)
    rows = [{"y": float(y[i]), "x": float(x[i]), "grp": str(grp[i])}
            for i in range(n)]
    p = tmp_path / "d.jsonl"
    _write_ndjson(p, rows)
    return str(p), {"y": y, "x": x, "grp": grp}


def test_schema_levels_and_shards(json_data):
    path, cols = json_data
    assert sg.scan_json_schema(path) == {"y": 0, "x": 0, "grp": 1}
    assert sg.scan_json_levels(path) == {"grp": sorted(set(cols["grp"]))}
    for num_shards in (1, 3, 7):
        got = [sg.read_json(path, shard_index=i, num_shards=num_shards)
               for i in range(num_shards)]
        np.testing.assert_array_equal(
            np.concatenate([g["y"] for g in got]), cols["y"])
        assert sum(len(g["grp"]) for g in got) == len(cols["grp"])


def test_union_schema_missing_keys_and_bool(tmp_path):
    """Spark-JSON semantics: columns are the UNION of keys; a record
    missing a key reads NaN/None; booleans read as 0/1 indicators; a key
    that is ever a string is categorical everywhere."""
    p = tmp_path / "u.jsonl"
    _write_ndjson(p, [
        {"a": 1.0, "flag": True, "tag": "x"},
        {"a": 2.5, "b": 7},
        {"flag": False, "b": 1, "tag": None},
        {"a": None, "tag": 3},          # number, but tag is str elsewhere
    ])
    schema = sg.scan_json_schema(str(p))
    assert schema == {"a": 0, "flag": 0, "tag": 1, "b": 0}
    cols = sg.read_json(str(p), schema=schema)
    np.testing.assert_array_equal(np.isnan(cols["a"]), [False, False, True, True])
    np.testing.assert_array_equal(cols["flag"][:1], [1.0])
    assert cols["flag"][2] == 0.0 and np.isnan(cols["flag"][1])
    assert list(cols["tag"]) == ["x", None, None, "3"]
    assert sg.scan_json_levels(str(p)) == {"tag": ["3", "x"]}
    with pytest.raises(ValueError, match="flat"):
        _write_ndjson(p, [{"a": {"nested": 1}}])
        sg.scan_json_schema(str(p))


def test_glm_from_json_matches_in_memory(json_data, mesh8):
    path, cols = json_data
    m_js = sg.glm_from_json("y ~ x + grp", path, family="poisson",
                            chunk_bytes=8 << 10, tol=1e-10,
                            criterion="relative", mesh=mesh8)
    m_mem = sg.glm("y ~ x + grp", cols, family="poisson", tol=1e-10,
                   criterion="relative", mesh=mesh8)
    # rtol for the O(1) coefficients, atol for near-zero ones (f32 chunk
    # accumulation noise is absolute, ~1e-6)
    np.testing.assert_allclose(m_js.coefficients, m_mem.coefficients,
                               rtol=1e-5, atol=5e-6)
    np.testing.assert_allclose(m_js.deviance, m_mem.deviance, rtol=1e-6)
    assert m_js.xnames == m_mem.xnames

    # lm twin + the default residual-quantile block on this tier too
    m_lm = sg.lm_from_json("y ~ x + grp", path, chunk_bytes=8 << 10,
                           mesh=mesh8)
    m_lmm = sg.lm("y ~ x + grp", cols, mesh=mesh8)
    np.testing.assert_allclose(m_lm.coefficients, m_lmm.coefficients,
                               rtol=1e-5, atol=5e-6)
    assert m_lm.resid_quantiles is not None


def test_predict_from_json_path(json_data):
    path, cols = json_data
    m = sg.glm("y ~ x + grp", cols, family="poisson")
    np.testing.assert_array_equal(
        np.asarray(sg.predict(m, path, chunk_bytes=8 << 10)),
        np.asarray(sg.predict(m, cols)))




def _native_json_ready() -> bool:
    """Skip gate for native=True JSON tests: the shared .so must load AND
    carry the sgio_read_json entry point (a stale prebuilt library may
    lack it — data/json.py then raises for native=True)."""
    from sparkglm_tpu.data.json import _native_lib
    return _native_lib(None) is not None


def _assert_shard_parity(path, schema, shard_counts):
    """Native and Python readers must agree on every column of every
    shard, including the dict-order contract; numeric columns also keep
    signed zeros."""
    for num_shards in shard_counts:
        for i in range(num_shards):
            a = sg.read_json(path, shard_index=i, num_shards=num_shards,
                             schema=schema, native=True)
            b = sg.read_json(path, shard_index=i, num_shards=num_shards,
                             schema=schema, native=False)
            assert list(a) == list(b)
            for k in a:
                if a[k].dtype == object:
                    assert list(a[k]) == list(b[k]), (k, i)
                else:
                    np.testing.assert_array_equal(a[k], b[k], err_msg=k)
                    np.testing.assert_array_equal(
                        np.signbit(a[k]), np.signbit(b[k]), err_msg=k)


def test_native_json_parity(json_data, tmp_path):
    """The C++ NDJSON parser (native/loader.cpp::sgio_read_json) must
    reproduce the Python twin exactly: schema, levels, and every column
    of every shard — including union-of-keys records, escapes, bools,
    nulls, and numbers landing in categorical columns."""
    if not _native_json_ready():
        pytest.skip("native NDJSON loader unavailable")
    path, _ = json_data
    assert sg.scan_json_schema(path, native=True) == \
        sg.scan_json_schema(path, native=False)
    assert sg.scan_json_levels(path, native=True) == \
        sg.scan_json_levels(path, native=False)
    _assert_shard_parity(path, sg.scan_json_schema(path), (1, 4))

    # adversarial record set: escapes, \u, bools, missing keys, mixed types
    p = tmp_path / "adv.jsonl"
    with open(p, "w") as fh:
        fh.write('{"s": "a\\"b\\\\c\\u00e9", "n": 3, "b": true}\n')
        fh.write('\n')  # blank line skipped
        fh.write('{"n": 2.5, "extra": "only-here"}\n')
        fh.write('{"s": null, "b": false, "n": null}\n')
        fh.write('{"s": 7, "n": "1.5"}\n')  # number in cat col, str in num col
    schema = sg.scan_json_schema(str(p), native=False)
    na = sg.read_json(str(p), schema=schema, native=True)
    py = sg.read_json(str(p), schema=schema, native=False)
    assert list(na) == list(py)
    for k in na:
        if na[k].dtype == object:
            assert list(na[k]) == list(py[k]), (k, list(na[k]), list(py[k]))
        else:
            np.testing.assert_array_equal(na[k], py[k], err_msg=k)

    # CPython str(float) fixed/scientific crossover: numbers interned into
    # categorical columns must produce identical level strings both ways
    fx = tmp_path / "float.jsonl"
    with open(fx, "w") as fh:
        fh.write('{"s": "lvl"}\n')
        for lit in ("100000.0", "1e16", "0.0001", "1e-5", "2.5e16", "3",
                    "NaN", "Infinity", "-Infinity"):
            fh.write('{"s": %s, "x": %s}\n' % (lit, lit))
    sch = sg.scan_json_schema(str(fx), native=False)
    assert sch == {"s": 1, "x": 0}
    nn = sg.read_json(str(fx), schema=sch, native=True)
    pp = sg.read_json(str(fx), schema=sch, native=False)
    assert list(nn["s"]) == list(pp["s"])
    np.testing.assert_array_equal(nn["x"], pp["x"])

    # duplicate keys: json.loads keeps the LAST value — typing must agree
    dup = tmp_path / "dup.jsonl"
    dup.write_text('{"a": "x", "a": 1}\n')
    assert sg.scan_json_schema(str(dup), native=True) == \
        sg.scan_json_schema(str(dup), native=False) == {"a": 0}

    # big ints in categorical columns intern the VERBATIM token (python's
    # arbitrary-precision str(int)); long literals parse; strict JSON
    # number grammar (.5 / +5 / 01 rejected both ways, like json.loads)
    big = tmp_path / "big.jsonl"
    big.write_text('{"s": "lvl", "x": 1.%s1}\n{"s": 10000000000000000}\n'
                   % ("3" * 70))
    sch2 = sg.scan_json_schema(str(big), native=False)
    nn2 = sg.read_json(str(big), schema=sch2, native=True)
    pp2 = sg.read_json(str(big), schema=sch2, native=False)
    assert list(nn2["s"]) == list(pp2["s"]) == ["lvl", "10000000000000000"]
    np.testing.assert_array_equal(nn2["x"], pp2["x"])
    for bad_lit in (".5", "+5", "01"):
        fp = tmp_path / "badnum.jsonl"
        fp.write_text('{"a": %s}\n' % bad_lit)
        with pytest.raises(ValueError):
            sg.read_json(str(fp), native=True)
        with pytest.raises(ValueError):
            sg.read_json(str(fp), native=False)

    # trailing content after the object is python's "Extra data" error,
    # never silent data loss
    tr = tmp_path / "trail.jsonl"
    tr.write_text('{"a": 1}{"a": 2}\n')
    with pytest.raises(ValueError):
        sg.read_json(str(tr), native=True)
    with pytest.raises(ValueError):
        sg.read_json(str(tr), native=False)

    # error parity: nested values refused by both; ALL native parse errors
    # are ValueError (the json.JSONDecodeError contract)
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"a": {"nested": 1}}\n')
    with pytest.raises(ValueError):
        sg.read_json(str(bad), native=True)
    with pytest.raises(ValueError):
        sg.scan_json_schema(str(bad), native=False)
    bad.write_text('{"a": tru}\n')
    with pytest.raises(ValueError):
        sg.read_json(str(bad), native=True)
    with pytest.raises(ValueError):
        sg.read_json(str(bad), native=False)
    # lone surrogates: python json is lenient, but their CESU-8 bytes
    # cannot cross the ctypes boundary — the native parser refuses loudly
    # (documented divergence) instead of corrupting level strings
    bad.write_text('{"a": "\\ud800"}\n')
    with pytest.raises(ValueError, match="surrogate"):
        sg.read_json(str(bad), native=True)


def test_native_json_fuzz_parity(tmp_path, rng):
    """Randomized flat records (unicode, escapes, exotic floats, missing
    keys, bools/nulls, int/float/str mixtures) serialized by json.dumps:
    the native parser must reproduce the Python twin on every column."""
    if not _native_json_ready():
        pytest.skip("native NDJSON loader unavailable")
    import json as json_mod

    keys = ["a", "b", "c", "d\u00e9j\u00e0", "k_5"]
    specials = [0.0, -0.0, 1e-300, 1e300, 123456789.123456789, -7.5e-5,
                1e15, 1e16, 3.14159265358979, float("nan"), float("inf")]
    strs = ["", "x", "a,b", 'q"q', "tab\tnl\n", "\u00e9\u6f22\u5b57",
            "\U0001f389", "NA", "null", "-5", "3.0"]
    rows = []
    for _ in range(400):
        rec = {}
        for k in keys:
            r = rng.random()
            if r < 0.15:
                continue  # missing key
            if r < 0.30:
                rec[k] = None
            elif r < 0.45:
                rec[k] = bool(rng.random() < 0.5)
            elif r < 0.60:
                rec[k] = int(rng.integers(-10**12, 10**12))
            elif r < 0.80:
                rec[k] = float(specials[rng.integers(0, len(specials))])
            else:
                rec[k] = strs[rng.integers(0, len(strs))]
        rows.append(rec)
    p = tmp_path / "fuzz.jsonl"
    with open(p, "w", encoding="utf-8") as fh:
        for rec in rows:
            fh.write(json_mod.dumps(rec, ensure_ascii=bool(rng.random() < 0.5))
                     + "\n")
    schema_n = sg.scan_json_schema(str(p), native=True)
    schema_p = sg.scan_json_schema(str(p), native=False)
    assert schema_n == schema_p
    assert sg.scan_json_levels(str(p), native=True) == \
        sg.scan_json_levels(str(p), native=False)
    _assert_shard_parity(str(p), schema_p, (1, 5))


def test_native_json_rare_token_parity(tmp_path):
    """Review r4 parity gaps: an integral ``-0`` token interning into a
    categorical column must give Python's str(int) level '0', and strings
    coerced into NUMERIC columns must follow Python float() lexing
    (whitespace stripped, PEP-515 underscores) — identical columns whether
    or not the .so is present (the multi-host identical-design contract)."""
    if not _native_json_ready():
        pytest.skip("native NDJSON loader unavailable")
    import sparkglm_tpu as sg
    p = tmp_path / "rare.jsonl"
    body = ('{"cat": -0, "num": 1.5}\n'
            '{"cat": "x", "num": "1_0"}\n'
            '{"cat": -0.0, "num": " 2.5\\t"}\n')
    # the scan types BOTH columns categorical (strings present): the
    # interning path sees the -0 token; levels must agree
    p.write_text(body + '{"cat": 7, "num": "_1"}\n')
    cn = sg.read_json(str(p), native=True)
    cp = sg.read_json(str(p), native=False)
    assert list(cn["cat"]) == list(cp["cat"]) == ["0", "x", "-0.0", "7"]
    assert sg.scan_json_levels(str(p), native=True) == \
        sg.scan_json_levels(str(p), native=False)
    # string -> NUMERIC coercion (an explicit schema forces it, as the
    # streaming fit flow does): Python float() lexing on both loaders
    schema = {"cat": 1, "num": 0}
    with pytest.raises(ValueError, match="could not convert"):
        sg.read_json(str(p), schema=schema, native=True)
    with pytest.raises(ValueError, match="could not convert"):
        sg.read_json(str(p), schema=schema, native=False)
    p.write_text(body + '{"cat": 7, "num": "+3_0.5"}\n')
    cn = sg.read_json(str(p), schema=schema, native=True)
    cp = sg.read_json(str(p), schema=schema, native=False)
    np.testing.assert_array_equal(cn["num"], cp["num"])
    np.testing.assert_allclose(cn["num"], [1.5, 10.0, 2.5, 30.5])
    assert list(cn["cat"]) == list(cp["cat"]) == ["0", "x", "-0.0", "7"]


def test_gzip_ndjson_parity(tmp_path, rng):
    """A .jsonl.gz twin reads/scans/fits identically to the plain NDJSON
    file; sharded reads are refused (Spark's non-splittable semantics)."""
    import gzip

    import sparkglm_tpu as sg

    n = 300
    x = rng.standard_normal(n)
    g = rng.choice(["u", "v"], size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x)).astype(float)
    plain = tmp_path / "d.jsonl"
    import json as json_mod
    with open(plain, "w") as fh:
        for i in range(n):
            fh.write(json_mod.dumps(
                {"y": y[i], "x": x[i], "g": str(g[i])}) + "\n")
    gz = tmp_path / "d.jsonl.gz"
    with gzip.open(gz, "wt") as fh:
        fh.write(plain.read_text())
    assert sg.scan_json_schema(str(gz)) == sg.scan_json_schema(str(plain))
    assert sg.scan_json_levels(str(gz)) == sg.scan_json_levels(str(plain))
    cg, cp = sg.read_json(str(gz)), sg.read_json(str(plain))
    np.testing.assert_array_equal(cg["x"], cp["x"])
    with pytest.raises(ValueError, match="not splittable"):
        sg.read_json(str(gz), shard_index=0, num_shards=4)
    mg = sg.glm_from_json("y ~ x + g", str(gz), family="poisson")
    mp = sg.glm_from_json("y ~ x + g", str(plain), family="poisson")
    np.testing.assert_allclose(mg.coefficients, mp.coefficients, rtol=1e-10)
