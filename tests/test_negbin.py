"""Negative binomial family + glm.nb theta estimation (MASS semantics —
a capability extension; the reference implements binomial only,
GLM.scala:486-490)."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def _nb_data(rng, n=4000, theta=2.5, p=3):
    X = rng.normal(size=(n, p)) * 0.4
    X[:, 0] = 1.0
    bt = np.array([0.8, 0.5, -0.3])[:p]
    mu = np.exp(X @ bt)
    # NB as gamma-poisson mixture
    lam = rng.gamma(theta, mu / theta)
    y = rng.poisson(lam).astype(float)
    return X, y, bt


def test_nb_family_known_theta(mesh8, rng):
    """With theta fixed, NB is an ordinary GLM: coefficients recover the
    truth, dispersion is fixed at 1, deviance/logLik are finite and the
    family name round-trips through persistence."""
    X, y, bt = _nb_data(rng, theta=2.0)
    fam = sg.negative_binomial(2.0)
    m = sg.glm_fit(X, y, family=fam, link="log", tol=1e-10, mesh=mesh8)
    assert m.converged
    np.testing.assert_allclose(m.coefficients, bt, atol=0.08)
    assert m.dispersion == 1.0
    assert np.isfinite(m.loglik) and np.isfinite(m.aic)
    assert sg.get_family(m.family).name == "negative_binomial(2)"


def test_nb_loglik_formula(mesh1, rng):
    """logLik matches the exact NB log-pmf summed in f64."""
    from scipy import special as sp
    X, y, _ = _nb_data(rng, n=600, theta=3.0)
    th = 3.0
    m = sg.glm_fit(X, y, family=sg.negative_binomial(th), link="log",
                   tol=1e-12, criterion="absolute", mesh=mesh1)
    eta = X @ m.coefficients
    mu = np.exp(eta)
    ll = np.sum(sp.gammaln(th + y) - sp.gammaln(th) - sp.gammaln(y + 1)
                + th * np.log(th) + sp.xlogy(y, mu) - (th + y) * np.log(th + mu))
    np.testing.assert_allclose(m.loglik, ll, rtol=1e-8)
    # AIC counts theta as a parameter: -2ll + 2(p+1)
    np.testing.assert_allclose(m.aic, -2 * ll + 2 * (X.shape[1] + 1),
                               rtol=1e-8)


def test_glm_nb_estimates_theta(mesh8, rng):
    """The alternating ML loop recovers the generating theta and beats the
    misspecified poisson fit on likelihood."""
    theta_true = 2.5
    X, y, bt = _nb_data(rng, n=8000, theta=theta_true)
    m = sg.glm_fit_nb(X, y, link="log", mesh=mesh8)
    th = sg.theta_of(m)
    assert 1.8 < th < 3.5  # ML theta near the generating value
    np.testing.assert_allclose(m.coefficients, bt, atol=0.08)
    mp = sg.glm_fit(X, y, family="poisson", mesh=mesh8)
    # overdispersed counts: poisson pearson/df far above 1, NB's ~1
    assert mp.pearson_chi2 / mp.df_residual > 1.5
    assert 0.7 < m.pearson_chi2 / m.df_residual < 1.4


def test_glm_nb_formula_offset_and_tools(rng):
    n = 3000
    x = rng.normal(size=n)
    lt = rng.uniform(0.2, 0.8, size=n)
    mu = np.exp(0.5 + 0.6 * x + lt)
    lam = rng.gamma(2.0, mu / 2.0)
    d = {"x": x, "lt": lt, "y": rng.poisson(lam).astype(float)}
    m = sg.glm_nb("y ~ x + offset(lt)", d)
    assert m.formula == "y ~ x + offset(lt)"
    np.testing.assert_allclose(m.coefficients, [0.5, 0.6], atol=0.1)
    # predict recovers the stored offset; drop1/anova work on NB fits
    pred = sg.predict(m, {"x": np.zeros(2), "lt": np.full(2, 0.5)})
    assert np.all(np.isfinite(pred))
    t = sg.drop1(m, d, test="Chisq")
    assert t.row_names == ("<none>", "x")
    # summary renders with the theta-carrying family name
    assert "negative_binomial" in str(m.summary())


def test_nb_rejects_negative_counts(mesh1, rng):
    X = np.c_[np.ones(50), rng.normal(size=50)]
    y = rng.poisson(2.0, size=50).astype(float)
    y[3] = -1.0
    with pytest.raises(ValueError, match="negative values"):
        sg.glm_fit(X, y, family=sg.negative_binomial(2.0), mesh=mesh1)
    with pytest.raises(ValueError, match="theta"):
        sg.negative_binomial(-1.0)


def test_nb_theta_search_compiles_kernel_once(rng):
    """theta rides the IRLS kernel as a TRACED operand (Family.with_param):
    the whole glm.nb alternation — typically 5-25 theta values — adds at
    most TWO kernel compilations (the poisson start + one shared NB
    kernel), not one per theta (round-2 memory item: 'glm.nb retrace
    cost')."""
    import sparkglm_tpu as sg
    from sparkglm_tpu.models.glm import _irls_kernel

    n = 3000
    x = rng.standard_normal(n)
    mu = np.exp(0.4 + 0.5 * x)
    y = rng.negative_binomial(2.0, 2.0 / (2.0 + mu)).astype(float)
    base = _irls_kernel._cache_size()
    m = sg.glm_nb("y ~ x", {"y": y, "x": x})
    assert m.converged
    added = _irls_kernel._cache_size() - base
    assert added <= 2, f"theta search recompiled the kernel {added} times"
    # and different theta values share the compiled kernel outright
    from sparkglm_tpu.families.families import negative_binomial
    assert negative_binomial(0.5) == negative_binomial(7.0)
    assert hash(negative_binomial(0.5)) == hash(negative_binomial(7.0))
    # ...while the recorded names still carry their theta
    assert negative_binomial(0.5).name != negative_binomial(7.0).name


@pytest.mark.parametrize("engine", ["einsum", "fused"])
def test_nb_fixed_theta_engine_parity(mesh8, rng, engine):
    """VERDICT r4 #5: parametric families ride the fused engine too (theta
    as a traced operand).  Fixed-theta NB fits agree across engines."""
    X, y, _ = _nb_data(rng, n=4096, theta=2.0)
    m = sg.glm_fit(X.astype(np.float32), y, family=sg.negative_binomial(2.0),
                   link="log", tol=1e-8, criterion="relative", mesh=mesh8,
                   engine=engine)
    assert m.converged
    me = sg.glm_fit(X.astype(np.float32), y,
                    family=sg.negative_binomial(2.0), link="log", tol=1e-8,
                    criterion="relative", mesh=mesh8, engine="einsum")
    np.testing.assert_allclose(m.coefficients, me.coefficients, atol=5e-5)
    np.testing.assert_allclose(m.deviance, me.deviance, rtol=1e-4)


def test_glm_nb_rides_fused_engine(rng):
    """The full glm.nb theta search runs on engine='fused' (XLA twin on
    CPU) and agrees with the einsum search."""
    n = 3000
    x = rng.standard_normal(n)
    mu = np.exp(0.4 + 0.5 * x)
    y = rng.negative_binomial(2.0, 2.0 / (2.0 + mu)).astype(float)
    d = {"y": y, "x": x}
    mf = sg.glm_nb("y ~ x", d, engine="fused")
    me = sg.glm_nb("y ~ x", d, engine="einsum")
    np.testing.assert_allclose(mf.coefficients, me.coefficients, atol=1e-4)
    th_f = float(sg.get_family(mf.family).param)
    th_e = float(sg.get_family(me.family).param)
    np.testing.assert_allclose(th_f, th_e, rtol=1e-3)
