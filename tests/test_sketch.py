"""Sketched-IRLS engine + SparseDesign (ISSUE 9; PARITY.md r13).

Four assertion tiers:
  * sketch ops — seeded determinism (same key -> bit-identical sketch),
    E[S'S] = I unbiasedness, and CSR/COO <-> dense agreement at f64;
  * golden parity — ``engine="sketch"`` coefficients against the
    independent f64 oracle (r_golden.json), on existing flat cases and the
    wide sparse fixture, within the PARITY-documented 1e-4 maxdiff (the
    sketch-and-precondition solver lands far inside it: the sketched
    Gramian is only a CG preconditioner, the normal equations stay exact);
  * engine-combination guards — sketch x {penalty, elastic/workers,
    se/vcov, singular="drop", structured designs, exact streaming} all
    refuse with pointed errors;
  * integration — streaming chunk buckets + prefetch pipelining, the
    serve Scorer's sparse warmup/score path, fit_report/trace stamping,
    serialization round-trip, one executable per pass flavor.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.config import DEFAULT
from sparkglm_tpu.data import sparse as sparse_mod
from sparkglm_tpu.models import glm as glm_mod
from sparkglm_tpu.models import streaming
from sparkglm_tpu.obs import FitTracer, RingBufferSink
from sparkglm_tpu.ops import sketch as sk

pytestmark = pytest.mark.sketch

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "r_golden.json")
with open(FIXTURES) as f:
    GOLDEN = json.load(f)
SPARSE_CASE = GOLDEN["sparse_cases"]["wide_sparse_poisson"]


def _sparse_case_design():
    """Rebuild the wide-sparse fixture's exact SparseDesign + response."""
    d = SPARSE_CASE["data"]
    x = np.asarray(d["x"], float)
    spd = sparse_mod.from_coo(
        d["coo_row"], d["coo_col"], d["coo_val"],
        SPARSE_CASE["n"], SPARSE_CASE["n_sparse"],
        dense=np.column_stack([np.ones(len(x)), x]), intercept=True)
    return spd, np.asarray(d["y"], float)


def _rand_sparse(rng, n=400, n_sp=30, d=2, nnz=4):
    """Seeded random SparseDesign with a dense [1, x] block."""
    rows, cols = [np.arange(n_sp) % n], [np.arange(n_sp)]
    for i in range(n):
        c = rng.choice(n_sp, size=nnz, replace=False)
        rows.append(np.full(nnz, i))
        cols.append(c)
    row = np.concatenate(rows)
    col = np.concatenate(cols)
    val = rng.uniform(0.5, 1.5, row.shape[0])
    dense = np.column_stack([np.ones(n), rng.standard_normal((n, d - 1))])
    return sparse_mod.from_coo(row, col, val, n, n_sp, dense=dense,
                               intercept=True)


# ---------------------------------------------------------------------------
# sketch ops: seeded determinism + unbiasedness + dense agreement
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("method", ["countsketch", "srht"])
def test_sketch_seeded_determinism(method, rng):
    X = rng.standard_normal((300, 7))
    w = rng.uniform(0.1, 2.0, 300)
    m = 32
    a = np.asarray(sk.sketch_design(X, w, jax.random.PRNGKey(7), m,
                                    method=method))
    b = np.asarray(sk.sketch_design(X, w, jax.random.PRNGKey(7), m,
                                    method=method))
    c = np.asarray(sk.sketch_design(X, w, jax.random.PRNGKey(8), m,
                                    method=method))
    assert np.array_equal(a, b)  # same seed -> bit-identical
    assert not np.array_equal(a, c)
    assert a.shape == (m, 7)


def test_countsketch_sparse_matches_dense_and_is_seeded(rng):
    spd = _rand_sparse(rng)
    Xd = spd.densify(np.float64)
    w = rng.uniform(0.1, 2.0, Xd.shape[0])
    key = jax.random.PRNGKey(3)
    a = np.asarray(sk.countsketch(spd.astype(np.float64), w, key, 64))
    b = np.asarray(sk.countsketch(spd.astype(np.float64), w, key, 64))
    dense = np.asarray(sk.countsketch(Xd, w, key, 64))
    assert np.array_equal(a, b)
    # the sparse ELL scatter and the dense segment_sum draw the same
    # hashes/signs from the key, so they sketch to the same matrix
    np.testing.assert_allclose(a, dense, rtol=1e-12, atol=1e-12)


@pytest.mark.parametrize("method", ["countsketch", "srht"])
def test_sketch_unbiased_expected_gramian(method):
    """E[(SA)'(SA)] = A'A — averaged over seeds on a fixed design."""
    rng = np.random.default_rng(11)
    X = rng.standard_normal((48, 5))
    w = np.ones(48)
    G = X.T @ X
    acc = np.zeros_like(G)
    reps = 400
    for s in range(reps):
        acc += np.asarray(sk.sketched_gramian(
            X, w, jax.random.PRNGKey(s), 24, method=method,
            accum_dtype=np.float64))
    err = np.abs(acc / reps - G).max() / np.abs(G).max()
    assert err < 0.05  # mean-zero fluctuation shrinks as 1/sqrt(reps)


def test_sparse_ops_agree_with_dense_f64(rng):
    spd = _rand_sparse(rng).astype(np.float64)
    Xd = spd.densify(np.float64)
    n, p = Xd.shape
    beta = rng.standard_normal(p)
    r = rng.standard_normal(n)
    w = rng.uniform(0.1, 2.0, n)
    z = rng.standard_normal(n)
    V = rng.standard_normal((p, p))
    V = V @ V.T
    np.testing.assert_allclose(
        np.asarray(sk.sparse_matvec(spd, beta)), Xd @ beta,
        rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(
        np.asarray(sk.sparse_colsum(spd, r, accum_dtype=np.float64)),
        Xd.T @ r, rtol=1e-12, atol=1e-10)
    G, b = sk.sparse_gramian(spd, z, w, accum_dtype=np.float64)
    np.testing.assert_allclose(np.asarray(G), Xd.T @ (w[:, None] * Xd),
                               rtol=1e-12, atol=1e-9)
    np.testing.assert_allclose(np.asarray(b), Xd.T @ (w * z),
                               rtol=1e-12, atol=1e-10)
    np.testing.assert_allclose(
        np.asarray(sk.sparse_quadform(spd, V)),
        np.sum((Xd @ V) * Xd, axis=1), rtol=1e-11, atol=1e-9)


def test_from_csr_from_coo_agree(rng):
    from scipy import sparse as sp_sparse
    M = sp_sparse.random(60, 15, density=0.15, random_state=4,
                         format="csr")
    a = sparse_mod.from_csr(M.indptr, M.indices, M.data, 15)
    coo = M.tocoo()
    b = sparse_mod.from_coo(coo.row, coo.col, coo.data, 60, 15)
    np.testing.assert_array_equal(a.densify(np.float64),
                                  b.densify(np.float64))
    np.testing.assert_array_equal(a.densify(np.float64), M.toarray())


# ---------------------------------------------------------------------------
# golden parity (PARITY.md r13)
# ---------------------------------------------------------------------------

def _flat_design(case):
    d = case["data"]
    kw = dict(family=case["family"], link=case["link"], tol=1e-12,
              criterion="relative", max_iter=200)
    x1 = np.asarray(d.get("x1", d.get("x")), float)
    X = np.column_stack([np.ones(len(x1)), x1])
    y = np.asarray(d["y"], float)
    if "w" in d:
        kw["weights"] = np.asarray(d["w"], float)
    if "exposure" in d:
        kw["offset"] = np.log(np.asarray(d["exposure"], float))
    return X, y, kw


@pytest.mark.parametrize("name", ["gaussian_weighted", "bernoulli_cloglog",
                                  "poisson_offset"])
def test_sketch_matches_golden_flat_cases(name):
    case = GOLDEN[name]
    X, y, kw = _flat_design(case)
    model = glm_mod.fit(X, y, engine="sketch", **kw)
    gold = np.asarray(case["fit"]["coefficients"])
    assert np.abs(model.coefficients - gold).max() <= 1e-4
    assert model.deviance == pytest.approx(case["fit"]["deviance"],
                                           rel=1e-6)
    assert model.gramian_engine == "sketch"
    assert np.isnan(model.std_errors).all()  # no exact covariance


def test_sketch_matches_golden_wide_sparse():
    spd, y = _sparse_case_design()
    gold = np.asarray(SPARSE_CASE["fit"]["coefficients"])
    kw = dict(family="poisson", link="log", tol=1e-12,
              criterion="relative", max_iter=200)
    exact = glm_mod.fit(spd, y, engine="einsum", singular="error", **kw)
    assert np.abs(exact.coefficients - gold).max() <= 1e-6
    assert exact.gramian_engine == "sparse"
    sketched = glm_mod.fit(spd, y, engine="sketch", **kw)
    # the PARITY r13 contract: <= 1e-4 coef maxdiff at f64 with refinement
    assert np.abs(sketched.coefficients - gold).max() <= 1e-4
    assert sketched.deviance == pytest.approx(
        SPARSE_CASE["fit"]["deviance"], rel=1e-6)
    rep = sketched.fit_report()
    assert rep["gramian_engine"] == "sketch"
    assert rep["sketch_dim"] >= 1
    assert rep["sketch_refine"] == DEFAULT.sketch_refine


def test_sketch_srht_and_seed_determinism():
    spd, y = _sparse_case_design()
    Xd = spd.densify(np.float64)
    kw = dict(family="poisson", link="log", tol=1e-12,
              criterion="relative", max_iter=200)
    gold = np.asarray(SPARSE_CASE["fit"]["coefficients"])
    cfg = dataclasses.replace(DEFAULT, sketch_method="srht")
    m_srht = glm_mod.fit(Xd, y, engine="sketch", config=cfg, **kw)
    assert np.abs(m_srht.coefficients - gold).max() <= 1e-4
    # same seed -> bit-identical refit; different seed still converges to
    # the same solution (the sketch is only a preconditioner)
    a = glm_mod.fit(spd, y, engine="sketch", **kw)
    b = glm_mod.fit(spd, y, engine="sketch", **kw)
    assert np.array_equal(a.coefficients, b.coefficients)
    c = glm_mod.fit(spd, y, engine="sketch", **kw,
                    config=dataclasses.replace(DEFAULT, sketch_seed=123))
    assert np.abs(c.coefficients - gold).max() <= 1e-4


# ---------------------------------------------------------------------------
# streaming: sparse chunk buckets, prefetch pipelining, engine plumbing
# ---------------------------------------------------------------------------

def _sparse_chunk_source(spd, y, n_chunks=4):
    n = spd.shape[0]

    def source():
        for i in range(n_chunks):
            lo, hi = n * i // n_chunks, n * (i + 1) // n_chunks
            yield lambda lo=lo, hi=hi: (spd[lo:hi], y[lo:hi], None, None)

    return source


def test_streaming_sketch_parity_and_prefetch():
    spd, y = _sparse_case_design()
    gold = np.asarray(SPARSE_CASE["fit"]["coefficients"])
    kw = dict(family="poisson", tol=1e-12, criterion="relative",
              max_iter=200, cache="none")
    m0 = streaming.glm_fit_streaming(_sparse_chunk_source(spd, y),
                                     engine="sketch", **kw)
    assert np.abs(m0.coefficients - gold).max() <= 1e-4
    assert m0.gramian_engine == "sketch"
    assert m0.sketch_dim >= 1 and m0.sketch_refine == DEFAULT.sketch_refine
    assert np.isnan(m0.std_errors).all()
    # prefetch=2 pipelines the same passes bit-identically
    m2 = streaming.glm_fit_streaming(_sparse_chunk_source(spd, y),
                                     engine="sketch", prefetch=2, **kw)
    assert np.array_equal(m0.coefficients, m2.coefficients)
    assert float(m0.deviance) == float(m2.deviance)
    # refit determinism: the per-(pass, chunk) fold_in key schedule is
    # part of the fit contract
    m1 = streaming.glm_fit_streaming(_sparse_chunk_source(spd, y),
                                     engine="sketch", **kw)
    assert np.array_equal(m0.coefficients, m1.coefficients)


def test_streaming_sketch_dense_chunks_match_exact():
    """Dense chunks run the sketched solver too — same exact-IRLS fixed
    point as the exact streaming engine."""
    spd, y = _sparse_case_design()
    Xd = spd.densify(np.float64)
    kw = dict(family="poisson", tol=1e-12, criterion="relative",
              max_iter=200, cache="none")
    exact = streaming.glm_fit_streaming((Xd, y), **kw)
    sketched = streaming.glm_fit_streaming((Xd, y), engine="sketch", **kw)
    assert np.abs(sketched.coefficients - exact.coefficients).max() <= 1e-4


def test_streaming_sparse_chunks_require_sketch():
    spd, y = _sparse_case_design()
    with pytest.raises(ValueError, match="engine='sketch'"):
        streaming.glm_fit_streaming(_sparse_chunk_source(spd, y),
                                    family="poisson", cache="none")


# ---------------------------------------------------------------------------
# engine-combination guards (pointed errors, api.py)
# ---------------------------------------------------------------------------

def test_guard_penalty_rejects_sketch(rng):
    data = {"y": rng.standard_normal(50), "x": rng.standard_normal(50)}
    with pytest.raises(ValueError, match="engine='sketch'"):
        sg.glm("y ~ x", data, family="gaussian", link="identity",
               engine="sketch", penalty=sg.ElasticNet(lambdas=[0.1]))


def test_guard_elastic_workers_reject_sketch(tmp_path, rng):
    p = tmp_path / "d.csv"
    y = rng.standard_normal(80)
    x = rng.standard_normal(80)
    with open(p, "w") as fh:
        fh.write("y,x\n")
        for a, b in zip(y, x):
            fh.write(f"{a},{b}\n")
    with pytest.raises(ValueError, match="workers="):
        sg.glm_from_csv("y ~ x", str(p), family="gaussian",
                        link="identity", engine="sketch", workers=2)
    with pytest.raises(ValueError, match="sketch"):
        sg.lm_from_csv("y ~ x", str(p), engine="sketch")


def test_guard_se_vcov_rejects_sketch():
    spd, y = _sparse_case_design()
    model = glm_mod.fit(spd, y, family="poisson", engine="sketch",
                        tol=1e-10)
    with pytest.raises(ValueError, match="engine='sketch'"):
        model.vcov()
    with pytest.raises(ValueError, match="engine='sketch'"):
        model.predict(spd[:8], se_fit=True)
    with pytest.raises(ValueError, match="engine='sketch'"):
        sg.serve.Scorer(model, se_fit=True)


def test_guard_singular_drop_and_structured_reject_sketch(rng):
    spd, y = _sparse_case_design()
    with pytest.raises(ValueError, match="singular='error'"):
        glm_mod.fit(spd, y, family="poisson", engine="sketch",
                    singular="drop")
    n = 300
    data = {"y": rng.standard_normal(n), "x": rng.standard_normal(n),
            "g": rng.integers(0, 8, n).astype(str)}
    with pytest.raises(ValueError, match="no structured form"):
        sg.glm("y ~ x + g", data, family="gaussian", link="identity",
               design="structured", engine="sketch", singular="error")
    with pytest.raises(ValueError, match="countsketch"):
        glm_mod.fit(spd, y, family="poisson", engine="sketch",
                    config=dataclasses.replace(DEFAULT,
                                               sketch_method="srht"))


def test_sketch_never_auto_selected():
    """engine='auto' must keep resolving to the exact path, even on a
    SparseDesign (opt-in contract, PARITY.md r13)."""
    spd, y = _sparse_case_design()
    model = glm_mod.fit(spd, y, family="poisson", engine="auto",
                        singular="error", tol=1e-10)
    assert model.gramian_engine == "sparse"  # exact ELL segment sums
    assert np.isfinite(model.std_errors).all()


# ---------------------------------------------------------------------------
# integration: executables, serving, reporting, persistence
# ---------------------------------------------------------------------------

def test_one_executable_per_pass_flavor():
    spd, y = _sparse_case_design()
    kw = dict(family="poisson", engine="sketch", tol=1e-10)
    glm_mod.fit(spd, y, **kw)
    before = glm_mod._irls_sketch_kernel._cache_size()
    glm_mod.fit(spd, y, **kw)  # identical flavor: zero new executables
    assert glm_mod._irls_sketch_kernel._cache_size() == before


def test_serve_scorer_sparse_warmup_and_score():
    spd, y = _sparse_case_design()
    model = glm_mod.fit(spd, y, family="poisson", engine="sketch",
                        tol=1e-10)
    scorer = sg.serve.Scorer(model, type="response")
    with pytest.raises(ValueError, match="columns"):
        scorer.warmup([8], sparse_layout=dataclasses.replace(
            spd.layout, p=spd.layout.p + 1, n_dense=spd.layout.n_dense + 1))
    assert scorer.warmup([8, 16], sparse_layout=spd.layout) == (8, 16)
    assert scorer.compiles == 0  # warmup resets the steady-state counter
    req = spd[:5]
    out = scorer.score(req)
    np.testing.assert_allclose(out, model.predict(req), rtol=0, atol=0)
    assert scorer.compiles == 0  # bucket 8 was warmed: no live compile
    assert scorer.bucket_for(5) == 8


def test_fit_report_trace_and_serialize(tmp_path):
    spd, y = _sparse_case_design()
    ring = RingBufferSink()
    model = glm_mod.fit(spd, y, family="poisson", engine="sketch",
                        tol=1e-10, trace=FitTracer(sinks=[ring]))
    rep = model.fit_report()
    assert rep["gramian_engine"] == "sketch"
    assert rep["sketch_dim"] == model.sketch_dim
    assert rep["sketch_refine"] == DEFAULT.sketch_refine
    stamped = [e for e in ring.events if e.kind in ("compile", "solve")]
    assert stamped, "sketch fit emitted no compile/solve events"
    for e in stamped:
        assert e.fields["gramian_engine"] == "sketch"
        assert e.fields["sketch_dim"] == model.sketch_dim
        assert e.fields["sketch_refine"] == DEFAULT.sketch_refine
    path = os.path.join(tmp_path, "m.npz")
    sg.save_model(model, path)
    loaded = sg.load_model(path)
    assert loaded.gramian_engine == "sketch"
    assert loaded.sketch_dim == model.sketch_dim
    assert loaded.sketch_refine == model.sketch_refine
    np.testing.assert_array_equal(loaded.coefficients, model.coefficients)
    with pytest.raises(ValueError, match="engine='sketch'"):
        loaded.vcov()
