"""Checkpoint/resume for resident and multi-host fits (VERDICT r2 #8).

The explicit replacement for Spark lineage recovery: checkpoint_every
surfaces (iters, beta, deviance) to on_iteration mid-fit; beta0 resumes
the convergence sequence from the last checkpoint.
"""

import numpy as np
import pytest

import sparkglm_tpu as sg


@pytest.fixture
def prob(rng):
    n, p = 20_000, 8
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    bt = rng.standard_normal(p) / np.sqrt(p)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float64)
    return X, y


def test_segmented_equals_plain(prob, mesh8):
    X, y = prob
    kw = dict(family="binomial", tol=1e-10, criterion="relative", mesh=mesh8)
    plain = sg.glm_fit(X, y, **kw)
    trace = []
    seg = sg.glm_fit(X, y, checkpoint_every=1,
                     on_iteration=lambda i, b, d: trace.append((i, b, d)),
                     **kw)
    assert seg.iterations == plain.iterations
    assert len(trace) == seg.iterations
    np.testing.assert_allclose(seg.coefficients, plain.coefficients,
                               rtol=0, atol=1e-12)
    assert seg.deviance == pytest.approx(plain.deviance, rel=1e-12)
    # the checkpoint stream is monotone in iteration count
    assert [t[0] for t in trace] == list(range(1, seg.iterations + 1))


def test_interrupt_and_resume(prob, mesh8):
    """Kill the fit after 2 iterations; resuming from the checkpointed
    beta reaches the same solution with only the REMAINING iterations."""
    X, y = prob
    kw = dict(family="binomial", tol=1e-10, criterion="relative", mesh=mesh8)
    plain = sg.glm_fit(X, y, **kw)

    ckpt = {}

    class Crash(Exception):
        pass

    def hook(i, b, d):
        ckpt["beta"], ckpt["iters"] = b, i
        if i == 2:
            raise Crash  # the process dies mid-fit

    with pytest.raises(Crash):
        sg.glm_fit(X, y, checkpoint_every=1, on_iteration=hook, **kw)
    assert ckpt["iters"] == 2

    with np.testing.suppress_warnings() as sup:
        sup.filter(UserWarning)
        resumed = sg.glm_fit(X, y, beta0=ckpt["beta"], **kw)
    np.testing.assert_allclose(resumed.coefficients, plain.coefficients,
                               rtol=0, atol=5e-10)
    assert resumed.deviance == pytest.approx(plain.deviance, rel=1e-10)
    assert resumed.converged
    # resume cost: the remaining iterations (+ at most one verification
    # step), not a from-scratch refit
    assert resumed.iterations <= plain.iterations - ckpt["iters"] + 1


def test_segmented_equals_plain_fused(prob, mesh8):
    """r4: the fused engine is warm-startable — a checkpoint_every=1 fused
    fit reproduces the unsegmented fused trajectory exactly (the segment
    driver threads the half-step-lagged deviance baseline across
    boundaries), so long fits no longer demote to einsum."""
    X, y = prob
    kw = dict(family="binomial", tol=1e-10, criterion="relative", mesh=mesh8,
              engine="fused")
    plain = sg.glm_fit(X, y, **kw)
    trace = []
    seg = sg.glm_fit(X, y, checkpoint_every=1,
                     on_iteration=lambda i, b, d: trace.append((i, b, d)),
                     **kw)
    assert seg.iterations == plain.iterations
    assert len(trace) == seg.iterations
    np.testing.assert_allclose(seg.coefficients, plain.coefficients,
                               rtol=0, atol=1e-12)
    assert seg.deviance == pytest.approx(plain.deviance, rel=1e-12)
    assert [t[0] for t in trace] == list(range(1, seg.iterations + 1))


def test_interrupt_and_resume_fused(prob, mesh8):
    """Crash a fused fit after 2 iterations; beta0 resume on the fused
    engine reaches the einsum solution with only the remaining work."""
    X, y = prob
    kw = dict(family="binomial", tol=1e-10, criterion="relative", mesh=mesh8)
    plain = sg.glm_fit(X, y, **kw)  # einsum reference solution

    ckpt = {}

    class Crash(Exception):
        pass

    def hook(i, b, d):
        ckpt["beta"], ckpt["iters"] = b, i
        if i == 2:
            raise Crash

    with pytest.raises(Crash):
        sg.glm_fit(X, y, engine="fused", checkpoint_every=1,
                   on_iteration=hook, **kw)
    assert ckpt["iters"] == 2

    with np.testing.suppress_warnings() as sup:
        sup.filter(UserWarning)
        resumed = sg.glm_fit(X, y, engine="fused", beta0=ckpt["beta"], **kw)
    np.testing.assert_allclose(resumed.coefficients, plain.coefficients,
                               rtol=0, atol=5e-10)
    assert resumed.deviance == pytest.approx(plain.deviance, rel=1e-10)
    assert resumed.converged


def test_fused_checkpoint_segments_cost_no_extra_passes(prob, mesh8):
    """checkpoint_every=2 on fused: segment boundaries add no coefficient
    updates — the trajectory matches checkpoint_every=1 and plain."""
    X, y = prob
    kw = dict(family="binomial", tol=1e-10, criterion="relative", mesh=mesh8,
              engine="fused")
    seg1 = sg.glm_fit(X, y, checkpoint_every=1,
                      on_iteration=lambda *a: None, **kw)
    seg2 = sg.glm_fit(X, y, checkpoint_every=2,
                      on_iteration=lambda *a: None, **kw)
    assert seg1.iterations == seg2.iterations
    np.testing.assert_allclose(seg1.coefficients, seg2.coefficients,
                               rtol=0, atol=1e-12)
