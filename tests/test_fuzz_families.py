"""Property-based sweep over the family x link grid.

For every R-meaningful (family, link) pair (R's ``family()$linkfun``
accepts these combinations), with random weights/offsets, a fit must:
converge, match the independent float64 oracle, produce finite
SEs/deviance/logLik, score its own data finitely, and round-trip through
serialization.  Seeds are fixed; data is generated from the model so the
fits are well-posed."""

import numpy as np
import pytest

import sparkglm_tpu as sg

# R's documented link sets per family (stats::family); probit/cloglog
# covered by dedicated binomial tests elsewhere — here breadth is the point
GRID = [
    ("gaussian", "identity"), ("gaussian", "log"), ("gaussian", "inverse"),
    ("binomial", "logit"), ("binomial", "probit"), ("binomial", "cloglog"),
    ("poisson", "log"), ("poisson", "identity"), ("poisson", "sqrt"),
    ("gamma", "inverse"), ("gamma", "identity"), ("gamma", "log"),
    ("inverse_gaussian", "inverse_squared"), ("inverse_gaussian", "log"),
    ("quasipoisson", "log"), ("quasibinomial", "logit"),
]


def _gen(rng, family, link, n=1500, p=4):
    """Data generated FROM the model so eta stays in the link's domain."""
    X = rng.normal(size=(n, p)) * 0.25
    X[:, 0] = 1.0
    beta = rng.normal(size=p) * 0.2
    if link in ("inverse", "inverse_squared"):
        beta[0] = 1.5  # keep eta (hence mu) positive and away from 0
    elif link in ("identity", "sqrt") and family in ("poisson", "gamma",
                                                     "inverse_gaussian"):
        beta[0] = 3.0  # mu > 0 under identity/sqrt
    eta = X @ beta
    mu = {
        "identity": lambda e: e,
        "log": lambda e: np.exp(e),
        "logit": lambda e: 1 / (1 + np.exp(-e)),
        "probit": lambda e: __import__("scipy.stats", fromlist=["norm"]).norm.cdf(e),
        "cloglog": lambda e: 1 - np.exp(-np.exp(e)),
        "inverse": lambda e: 1 / e,
        "sqrt": lambda e: e ** 2,
        "inverse_squared": lambda e: 1 / np.sqrt(e),
    }[link](eta)
    base = family.replace("quasi", "") if family.startswith("quasi") else family
    if base == "gaussian":
        y = mu + 0.2 * rng.normal(size=n)
    elif base == "binomial":
        y = (rng.random(n) < mu).astype(float)
    elif base == "poisson":
        y = rng.poisson(np.maximum(mu, 1e-6)).astype(float)
    elif base == "gamma":
        y = rng.gamma(5.0, np.maximum(mu, 1e-6) / 5.0) + 1e-9
    else:  # inverse gaussian
        y = np.maximum(rng.wald(np.maximum(mu, 1e-3), 6.0), 1e-9)
    return X, y, beta


@pytest.mark.parametrize("family,link", GRID)
def test_family_link_grid(mesh8, family, link, tmp_path):
    import zlib
    rng = np.random.default_rng(zlib.crc32(f"{family}:{link}".encode()))
    X, y, _ = _gen(rng, family, link)
    n = X.shape[0]
    w = rng.uniform(0.5, 2.0, size=n)
    m = sg.glm_fit(X, y, family=family, link=link, weights=w,
                   tol=1e-10, criterion="relative", max_iter=200, mesh=mesh8)
    assert m.converged, (family, link)
    assert np.all(np.isfinite(m.coefficients))
    assert np.all(np.isfinite(m.std_errors)) and np.all(m.std_errors > 0)
    assert np.isfinite(m.deviance) and m.deviance >= 0
    if not family.startswith("quasi"):
        assert np.isfinite(m.loglik) and np.isfinite(m.aic)

    # float64 oracle parity (CPU x64: the fit above ran f64 too)
    from oracle import irls_np
    beta64 = irls_np(X, y, family.replace("quasi", "")
                     if family.startswith("quasi") else family,
                     link, wt=w)[0]
    # cloglog/identity-link fits differ from the oracle at ~2e-5 relative
    # (different saturation guards); that is agreement, not a bug
    np.testing.assert_allclose(m.coefficients, beta64, rtol=5e-5, atol=1e-6)

    # scoring + residuals stay finite; persistence round-trips
    mu_hat = m.predict(X)
    assert np.all(np.isfinite(mu_hat))
    assert np.all(np.isfinite(m.residuals(X, y, weights=w, type="pearson")))
    path = str(tmp_path / "m.npz")
    sg.save_model(m, path)
    m2 = sg.load_model(path)
    np.testing.assert_array_equal(m2.coefficients, m.coefficients)
    assert m2.family == m.family and m2.link == m.link
