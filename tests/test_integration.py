"""Cross-cutting integration: pandas input, feature-sharded GLM, engine x
mesh matrix, save/load/predict round trips through the formula path."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def _frame(rng, n=1200):
    import pandas as pd
    x = rng.normal(size=n)
    g = rng.choice(["a", "b", "c"], size=n)
    eta = 0.3 + 0.6 * x + 0.4 * (g == "b") - 0.2 * (g == "c")
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
    return pd.DataFrame({"y": y, "x": x, "g": g})


def test_pandas_dataframe_end_to_end(mesh8, rng):
    pd = pytest.importorskip("pandas")
    df = _frame(rng)
    m = sg.glm("y ~ x + g", df, family="binomial", mesh=mesh8, tol=1e-10)
    assert m.converged
    assert m.xnames == ("intercept", "x", "g_b", "g_c")
    # predict on a pandas frame too
    new = pd.DataFrame({"x": [0.0, 1.0], "g": ["a", "b"]})
    mu = sg.predict(m, new)
    assert mu.shape == (2,) and np.all((mu > 0) & (mu < 1))


def test_glm_feature_sharded_matches_data_sharded(mesh8, mesh42, rng):
    """Tensor-parallel (feature-axis) sharding through the einsum engine
    agrees with pure data sharding."""
    n, p = 1600, 8
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ (rng.normal(size=p) / 4))))).astype(float)
    m_dp = sg.glm_fit(X, y, family="binomial", tol=1e-11, mesh=mesh8,
                      engine="einsum")
    m_tp = sg.glm_fit(X, y, family="binomial", tol=1e-11, mesh=mesh42,
                      shard_features=True, engine="einsum")
    np.testing.assert_allclose(m_tp.coefficients, m_dp.coefficients,
                               rtol=1e-8, atol=1e-11)
    np.testing.assert_allclose(m_tp.deviance, m_dp.deviance, rtol=1e-9)


def test_formula_roundtrip_save_load_predict(tmp_path, mesh8, rng):
    df = {"y": rng.normal(size=300), "x": rng.normal(size=300),
          "g": rng.choice(["u", "v"], size=300)}
    m = sg.lm("y ~ x + g", df, mesh=mesh8)
    pred_before = sg.predict(m, df)
    path = str(tmp_path / "m.npz")
    m.save(path)
    m2 = sg.load_model(path)
    np.testing.assert_allclose(sg.predict(m2, df), pred_before, rtol=1e-12)
    assert m2.formula == "y ~ x + g"


def test_predict_se_fit(mesh8, rng):
    """se.fit semantics: link-scale x'Vx, response-scale delta method."""
    n, p = 900, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ [0.2, 0.6, -0.4])))).astype(float)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-11, mesh=mesh8)
    Xnew = np.array([[1.0, 0.0, 0.0], [1.0, 1.0, -1.0]])
    eta, se_l = m.predict(Xnew, type="link", se_fit=True)
    V = m.vcov()
    np.testing.assert_allclose(
        se_l, np.sqrt(np.einsum("np,pq,nq->n", Xnew, V, Xnew)), rtol=1e-10)
    mu, se_r = m.predict(Xnew, type="response", se_fit=True)
    np.testing.assert_allclose(se_r, se_l * mu * (1 - mu), rtol=1e-6)
    # LM version
    yl = X @ [1.0, 0.5, -0.3] + 0.2 * rng.normal(size=n)
    ml = sg.lm_fit(X, yl, mesh=mesh8)
    fit, se = ml.predict(Xnew, se_fit=True)
    np.testing.assert_allclose(
        se, np.sqrt(np.einsum("np,pq,nq->n", Xnew, ml.vcov(), Xnew)),
        rtol=1e-10)


def test_glm_save_load_has_cov(tmp_path, mesh1, rng):
    X = rng.normal(size=(200, 3)); X[:, 0] = 1.0
    y = (rng.random(200) < 0.5).astype(float)
    m = sg.glm_fit(X, y, family="binomial", mesh=mesh1)
    path = str(tmp_path / "g.npz")
    m.save(path)
    m2 = sg.load_model(path)
    np.testing.assert_allclose(m2.vcov(), m.vcov(), rtol=1e-12)
    np.testing.assert_allclose(m2.confint(), m.confint(), rtol=1e-12)
