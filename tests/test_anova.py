"""anova() / drop1() — R's model-comparison tables (the reference has no
model comparison at all; its whole inference surface is the summary
printer, GLM.scala:998-1025)."""

import numpy as np
import pytest
import scipy.stats

import sparkglm_tpu as sg


@pytest.fixture()
def pois_data(rng):
    n = 800
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    lam = np.exp(0.3 + 0.5 * x + 0.4 * (grp == "b"))  # z is null
    return {"x": x, "z": z, "grp": grp,
            "y": rng.poisson(lam).astype(float)}


def test_anova_glm_chisq(pois_data):
    m1 = sg.glm("y ~ x", pois_data, family="poisson")
    m2 = sg.glm("y ~ x + grp", pois_data, family="poisson")
    m3 = sg.glm("y ~ x + grp + z", pois_data, family="poisson")
    t = sg.anova(m1, m2, m3, test="Chisq")
    assert t.columns == ("Resid. Df", "Resid. Dev", "Df", "Deviance",
                         "Pr(>Chi)")
    assert t.rows[0][2] is None  # first row has no comparison
    # row 2: m1 -> m2, df diff 1, deviance drop large, p tiny
    assert t.rows[1][2] == 1
    dd = t.rows[1][3]
    np.testing.assert_allclose(dd, m1.deviance - m2.deviance, rtol=1e-12)
    np.testing.assert_allclose(t.rows[1][4], scipy.stats.chi2.sf(dd, 1),
                               rtol=1e-10)
    assert t.rows[1][4] < 1e-6       # grp is a real effect
    assert t.rows[2][4] > 0.01       # z is null
    s = str(t)
    assert "Analysis of Deviance Table" in s and "Pr(>Chi)" in s


def test_anova_glm_f_gamma(rng):
    """Estimated-dispersion family: F test scaled by the largest model's
    dispersion, as in R."""
    n = 600
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    mu = np.exp(0.5 + 0.4 * x)
    d = {"x": x, "z": z, "y": rng.gamma(4.0, mu / 4.0)}
    m1 = sg.glm("y ~ x", d, family="gamma", link="log")
    m2 = sg.glm("y ~ x + z", d, family="gamma", link="log")
    t = sg.anova(m1, m2, test="F")
    fstat = t.rows[1][4]
    expect = ((m1.deviance - m2.deviance) / 1) / m2.dispersion
    np.testing.assert_allclose(fstat, expect, rtol=1e-10)
    np.testing.assert_allclose(
        t.rows[1][5], scipy.stats.f.sf(expect, 1, m2.df_residual), rtol=1e-9)


def test_anova_lm(rng):
    n = 400
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    d = {"x": x, "z": z, "y": 1.0 + 2.0 * x + 0.3 * rng.normal(size=n)}
    m1 = sg.lm("y ~ x", d)
    m2 = sg.lm("y ~ x + z", d)
    t = sg.anova(m1, m2, test="F")
    assert t.columns[:4] == ("Res.Df", "RSS", "Df", "Sum of Sq")
    s2 = m2.sse / m2.df_resid
    expect_f = (m1.sse - m2.sse) / s2
    np.testing.assert_allclose(t.rows[1][4], expect_f, rtol=1e-10)
    assert t.rows[1][5] > 0.01  # z is noise


def test_anova_validation(pois_data, rng):
    m1 = sg.glm("y ~ x", pois_data, family="poisson")
    with pytest.raises(ValueError, match="sequential anova needs it"):
        sg.anova(m1)  # single-model form without the data
    d2 = {"x": rng.normal(size=100), "y": np.ones(100)}
    m_other = sg.lm("y ~ x", d2)
    with pytest.raises(TypeError, match="mix"):
        sg.anova(m1, m_other)
    m_small = sg.glm("y ~ x", {k: v[:300] for k, v in pois_data.items()},
                     family="poisson")
    with pytest.raises(ValueError, match="different row counts"):
        sg.anova(m1, m_small)


def test_drop1_glm(pois_data):
    m = sg.glm("y ~ x + grp + z", pois_data, family="poisson")
    t = sg.drop1(m, pois_data, test="Chisq")
    assert t.row_names == ("<none>", "x", "grp", "z")
    # each reduced fit's deviance must exceed the full model's
    for row in t.rows[1:]:
        assert row[1] >= m.deviance
        assert row[0] == 1
    # LRT for each dropped term matches an explicit nested-model anova
    m_no_z = sg.glm("y ~ x + grp", pois_data, family="poisson")
    z_row = t.rows[t.row_names.index("z")]
    np.testing.assert_allclose(z_row[3], m_no_z.deviance - m.deviance,
                               rtol=1e-9, atol=1e-9)
    assert z_row[4] > 0.01       # z null
    grp_row = t.rows[t.row_names.index("grp")]
    assert grp_row[4] < 1e-6     # grp real


def test_drop1_respects_marginality(rng):
    """With x:grp in the model, x and grp are marginal and not droppable —
    only the interaction appears in the scope (R's hierarchy rule)."""
    n = 500
    x = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    eta = 0.2 + 0.5 * x + 0.3 * (grp == "b") - 0.4 * x * (grp == "b")
    d = {"x": x, "grp": grp,
         "y": (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)}
    m = sg.glm("y ~ x * grp", d, family="binomial")
    t = sg.drop1(m, d, test="Chisq")
    assert t.row_names == ("<none>", "x:grp")


def test_intercept_only_formula(rng):
    """'y ~ 1' is R's null model; 'y ~ offset(a)' the offset-only variant."""
    n = 300
    y = rng.poisson(3.0, size=n).astype(float)
    m = sg.glm("y ~ 1", {"y": y}, family="poisson")
    assert m.xnames == ("intercept",)
    np.testing.assert_allclose(np.exp(m.coefficients[0]), y.mean(), rtol=1e-6)
    np.testing.assert_allclose(m.deviance, m.null_deviance, rtol=1e-10)
    lt = rng.uniform(0.2, 0.8, size=n)
    m2 = sg.glm("y ~ offset(lt)", {"y": y, "lt": lt}, family="poisson")
    assert m2.xnames == ("intercept",)
    # a no-predictor, no-intercept formula is still an error
    with pytest.raises(ValueError, match="no predictor terms"):
        sg.glm("y ~ -1", {"y": y}, family="poisson")


def test_drop1_single_term_refits_null(rng):
    n = 400
    x = rng.normal(size=n)
    d = {"x": x, "y": rng.poisson(np.exp(0.3 + 0.5 * x)).astype(float)}
    m = sg.glm("y ~ x", d, family="poisson")
    t = sg.drop1(m, d, test="Chisq")
    assert t.row_names == ("<none>", "x")
    # the reduced fit IS the null model
    np.testing.assert_allclose(t.rows[1][1], m.null_deviance, rtol=1e-8)


def test_drop1_refuses_array_offset(rng):
    n = 300
    x = rng.normal(size=n)
    off = rng.uniform(0.1, 0.5, size=n)
    d = {"x": x, "y": rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)}
    m = sg.glm("y ~ x", d, family="poisson", offset=off)
    with pytest.raises(ValueError, match="array offset"):
        sg.drop1(m, d)
    # explicitly passing it back works
    t = sg.drop1(m, d, offset=off, test="Chisq")
    assert t.row_names == ("<none>", "x")


def test_anova_lm_chisq_is_chisq(rng):
    n = 400
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    d = {"x": x, "z": z, "y": 1.0 + 2.0 * x + 0.3 * rng.normal(size=n)}
    m1 = sg.lm("y ~ x", d)
    m2 = sg.lm("y ~ x + z", d)
    t = sg.anova(m1, m2, test="Chisq")
    assert t.columns == ("Res.Df", "RSS", "Df", "Sum of Sq", "Pr(>Chi)")
    s2 = m2.sse / m2.df_resid
    expect = scipy.stats.chi2.sf((m1.sse - m2.sse) / s2, 1)
    np.testing.assert_allclose(t.rows[1][4], expect, rtol=1e-10)


def test_drop1_lm_and_offset(rng):
    n = 400
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    d = {"x": x, "z": z, "y": 1.0 + 2.0 * x + 0.3 * rng.normal(size=n)}
    t = sg.drop1(sg.lm("y ~ x + z", d), d)
    assert t.columns == ("Df", "Sum of Sq", "RSS", "AIC")
    assert t.rows[1][2] > t.rows[0][2]  # dropping x raises RSS a lot
    # a by-name fit-time offset travels into the refits automatically
    lt = rng.uniform(0.2, 0.8, size=n)
    dp = {"x": x, "z": z, "lt": lt,
          "y": rng.poisson(np.exp(0.2 + 0.4 * x + lt)).astype(float)}
    mp = sg.glm("y ~ x + z + offset(lt)", dp, family="poisson")
    tp = sg.drop1(mp, dp, test="Chisq")
    sub = sg.glm("y ~ x + offset(lt)", dp, family="poisson")
    z_row = tp.rows[tp.row_names.index("z")]
    np.testing.assert_allclose(z_row[1], sub.deviance, rtol=1e-9)


def test_add1_glm_matches_explicit_refits(rng, mesh8):
    """R's add1: each scope term refit ADDED; Df/Deviance/AIC/LRT match
    explicit update() refits, and terms already in the model are skipped."""
    n = 3000
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    y = rng.poisson(np.exp(0.4 + 0.5 * x1 + 0.3 * x2
                           - 0.4 * (g == "b"))).astype(float)
    data = {"y": y, "x1": x1, "x2": x2, "g": g}
    m = sg.glm("y ~ x1", data, family="poisson", mesh=mesh8)
    tbl = sg.add1(m, "~ x1 + x2 + g", data, test="Chisq")
    assert tbl.row_names == ("<none>", "x2", "g")
    m_x2 = sg.update(m, "~ . + x2", data)
    m_g = sg.update(m, "~ . + g", data)
    rows = dict(zip(tbl.row_names, tbl.rows))
    assert rows["x2"][0] == 1 and rows["g"][0] == 2
    assert rows["x2"][1] == pytest.approx(m_x2.deviance, rel=1e-10)
    assert rows["g"][2] == pytest.approx(m_g.aic, rel=1e-10)
    # LRT at the original model's dispersion (Poisson: 1)
    assert rows["x2"][3] == pytest.approx(m.deviance - m_x2.deviance,
                                          rel=1e-10)
    assert 0 <= rows["x2"][4] <= 1
    text = str(tbl)
    assert "Single term additions" in text and "<none>" in text

    with pytest.raises(ValueError, match="adds no terms"):
        sg.add1(m, "~ x1", data)


def test_add1_lm_and_from_csv_path(tmp_path, rng, mesh8):
    """add1 on lm uses R's drop1/add1 AIC scale; path data streams the
    refits out-of-core through update()."""
    import csv as csv_mod
    n = 2000
    x1 = np.round(rng.standard_normal(n), 6)
    x2 = np.round(rng.standard_normal(n), 6)
    y = np.round(1.0 + 0.8 * x1 + 0.5 * x2 + 0.3 * rng.standard_normal(n), 6)
    data = {"y": y, "x1": x1, "x2": x2}
    m = sg.lm("y ~ x1", data, mesh=mesh8)
    tbl = sg.add1(m, "~ . + x2", data)
    rows = dict(zip(tbl.row_names, tbl.rows))
    m_full = sg.update(m, "~ . + x2", data)
    assert rows["x2"][0] == 1
    assert rows["x2"][1] == pytest.approx(m.sse - m_full.sse, rel=1e-9)
    assert rows["x2"][2] == pytest.approx(m_full.sse, rel=1e-9)

    p = tmp_path / "d.csv"
    with open(p, "w", newline="") as fh:
        w = csv_mod.writer(fh)
        w.writerow(["y", "x1", "x2"])
        for i in range(n):
            w.writerow([y[i], x1[i], x2[i]])
    m_csv = sg.lm_from_csv("y ~ x1", str(p), chunk_bytes=16 << 10)
    tbl_csv = sg.add1(m_csv, "~ . + x2", str(p))
    rows_csv = dict(zip(tbl_csv.row_names, tbl_csv.rows))
    np.testing.assert_allclose(rows_csv["x2"][2], rows["x2"][2], rtol=1e-6)


def test_add1_guards(rng, mesh8):
    """Scope syntax is validated (no silent misparse), a:b == b:a dedups,
    and a candidate with NAs that shrinks the sample is refused."""
    n = 500
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    x3 = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.3 + 0.5 * x1)).astype(float)
    data = {"y": y, "x1": x1, "x2": x2, "x3": x3}
    m = sg.glm("y ~ x1", data, family="poisson", mesh=mesh8)
    with pytest.raises(ValueError, match="unsupported scope"):
        sg.add1(m, "~ . + x2^2", data)
    tbl = sg.add1(m, "~ x2:x3 + x3:x2", data)
    assert tbl.row_names == ("<none>", "x2:x3")  # canonical dedup
    bad = dict(data, x2=np.where(np.arange(n) < 10, np.nan, x2))
    with pytest.raises(ValueError, match="rows in use changed"):
        sg.add1(m, "~ . + x2", bad)


def test_step_both_directions_recovers_truth(rng, mesh8):
    """R's step(): AIC-guided stepwise selection.  With two real effects,
    two noise columns, and an interaction candidate whose margins gate
    it, 'both' lands on the true model from an overfit start."""
    n = 4000
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    z1 = rng.standard_normal(n)
    z2 = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.4 + 0.6 * x1 - 0.5 * x2)).astype(float)
    data = {"y": y, "x1": x1, "x2": x2, "z1": z1, "z2": z2}

    # backward from the full model
    full = sg.glm("y ~ x1 + x2 + z1 + z2", data, family="poisson", mesh=mesh8)
    back = sg.step(full, data, direction="backward")
    assert set(back.xnames) == {"intercept", "x1", "x2"}

    # forward from the null model over a scope incl. a gated interaction
    null = sg.glm("y ~ 1", data, family="poisson", mesh=mesh8)
    fwd = sg.step(null, data, scope="~ x1 + x2 + z1 + z2 + x1:x2",
                  direction="forward")
    assert {"x1", "x2"} <= set(fwd.xnames)
    assert not ({"z1", "z2"} & set(fwd.xnames))

    # both: same destination from a wrong start
    start = sg.glm("y ~ z1 + z2", data, family="poisson", mesh=mesh8)
    both = sg.step(start, data, scope="~ x1 + x2 + z1 + z2")
    assert set(both.xnames) == {"intercept", "x1", "x2"}
    # the returned object is a normal fitted model
    assert both.converged and "Pr(>|z|)" in str(both.summary())


def test_step_lm_bic_and_guards(rng, mesh8):
    n = 2000
    x1 = rng.standard_normal(n)
    z = rng.standard_normal(n)
    y = 1.0 + 0.8 * x1 + 0.3 * rng.standard_normal(n)
    data = {"y": y, "x1": x1, "z": z}
    full = sg.lm("y ~ x1 + z", data, mesh=mesh8)
    chosen = sg.step(full, data, k=float(np.log(n)))  # BIC drops z
    assert set(chosen.xnames) == {"intercept", "x1"}
    with pytest.raises(ValueError, match="direction"):
        sg.step(full, data, direction="sideways")
    with pytest.raises(ValueError, match="scope"):
        sg.step(full, data, direction="forward")
    # quasi families have no AIC — refuse like R
    yq = rng.poisson(np.exp(0.3 + 0.5 * x1)).astype(float)
    mq = sg.glm("y ~ x1", {"y": yq, "x1": x1}, family="quasipoisson",
                mesh=mesh8)
    with pytest.raises(ValueError, match="AIC is not defined"):
        sg.step(mq, {"y": yq, "x1": x1})


def test_step_scope_dot_allows_reentry_and_minus_rejected(rng, mesh8):
    """'.' in scope keeps the ORIGINAL terms addable (a dropped term can
    re-enter under direction='both'); '-' scope terms are an error, not a
    silent constraint change."""
    n = 3000
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.4 + 0.7 * x1)).astype(float)
    data = {"y": y, "x1": x1, "x2": x2}
    m = sg.glm("y ~ x1 + x2", data, family="poisson", mesh=mesh8)
    with pytest.raises(ValueError, match="'-' terms"):
        sg.step(m, data, scope="~ . - x2")
    # scope "~ ." alone: both-direction selection over the original terms
    sel = sg.step(m, data, scope="~ .")
    assert set(sel.xnames) == {"intercept", "x1"}
    # hierarchy gate: x1:x2 never enters while x2 is out
    sel2 = sg.step(sg.glm("y ~ x1", data, family="poisson", mesh=mesh8),
                   data, scope="~ . + x2 + x1:x2")
    assert "x1:x2" not in sel2.xnames or "x2" in sel2.xnames


# ---------------------------------------------------------------------------
# single-model sequential anova (R's anova(fit)) — round 5
# ---------------------------------------------------------------------------

def _dobson_data():
    counts = [18.0, 17, 15, 20, 10, 20, 25, 13, 12]
    return {"counts": np.array(counts),
            "outcome": [str(1 + i % 3) for i in range(9)],
            "treatment": [str(1 + i // 3) for i in range(9)]}


def test_anova_single_glm_dobson_golden():
    """R's own ?glm example prints anova(glm.D93): the NULL / outcome /
    treatment rows with deviances 10.5814 -> 5.1291.  Sequential values are
    cross-checked against the independent oracle IRLS."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__)))
    from oracle import irls_np
    from sparkglm_tpu.config import NumericConfig

    d = _dobson_data()
    m = sg.glm("counts ~ outcome + treatment", d, family="poisson",
               config=NumericConfig(dtype="float64"), tol=1e-12)
    t = sg.anova(m, d, test="Chisq")
    assert t.columns == ("Df", "Deviance", "Resid. Df", "Resid. Dev",
                         "Pr(>Chi)")
    assert t.row_names == ("NULL", "outcome", "treatment")
    # R's printed table: NULL 8 10.5814; outcome 2 ... 6 5.1291 (treatment
    # adds nothing); treatment 2 ... 4 5.1291
    assert t.rows[0][2] == 8
    np.testing.assert_allclose(t.rows[0][3], 10.5814, atol=5e-5)
    assert t.rows[1][0] == 2 and t.rows[1][2] == 6
    assert t.rows[2][0] == 2 and t.rows[2][2] == 4
    np.testing.assert_allclose(t.rows[2][3], 5.1291, atol=5e-5)
    # oracle cross-check of the outcome-only sub-fit deviance
    y = d["counts"]
    o = np.tile([(0, 0), (1, 0), (0, 1)], (3, 1))
    Xo = np.column_stack([np.ones(9), o])
    from oracle import irls_np as _ir
    import numpy as _np
    beta, dev, *_ = _ir(Xo, y, "poisson", "log", wt=_np.ones(9),
                        offset=_np.zeros(9), tol=1e-13, max_iter=200)
    np.testing.assert_allclose(t.rows[1][3], dev, rtol=1e-7)
    np.testing.assert_allclose(t.rows[1][1], 10.581446 - dev, atol=5e-5)
    s = str(t)
    assert "Terms added sequentially (first to last)" in s
    assert "Model: poisson, link: log" in s and "Response: counts" in s


def test_anova_single_lm_D9_golden():
    """R's ?lm plant-weight example: anova(lm.D9) has group F = 1.4191,
    p = 0.249 (the same F the documented summary prints)."""
    from sparkglm_tpu.config import NumericConfig
    ctl = [4.17, 5.58, 5.18, 6.11, 4.50, 4.61, 5.17, 4.53, 5.33, 5.14]
    trt = [4.81, 4.17, 4.41, 3.59, 5.87, 3.83, 6.03, 4.89, 4.32, 4.69]
    d = {"weight": np.array(ctl + trt),
         "group": ["Ctl"] * 10 + ["Trt"] * 10}
    m = sg.lm("weight ~ group", d, config=NumericConfig(dtype="float64"))
    t = sg.anova(m, d)
    assert t.columns == ("Df", "Sum Sq", "Mean Sq", "F value", "Pr(>F)")
    assert t.row_names == ("group", "Residuals")
    assert t.rows[0][0] == 1 and t.rows[1][0] == 18
    np.testing.assert_allclose(t.rows[0][1], 0.6882, atol=5e-5)   # Sum Sq
    np.testing.assert_allclose(t.rows[0][3], 1.4191, atol=5e-4)   # F
    np.testing.assert_allclose(t.rows[0][4], 0.249, atol=5e-4)    # Pr(>F)
    np.testing.assert_allclose(t.rows[1][1], 8.7293, atol=5e-4)   # RSS
    assert t.rows[1][3] is None and t.rows[1][4] is None
    assert "Analysis of Variance Table" in str(t)


def test_anova_single_sequential_order_matters(pois_data):
    """Type-I tables attribute shared deviance to the FIRST term: the same
    model with reordered formula gives different per-term deviances but the
    same final residual row."""
    m1 = sg.glm("y ~ x + grp", pois_data, family="poisson")
    m2 = sg.glm("y ~ grp + x", pois_data, family="poisson")
    t1 = sg.anova(m1, pois_data)
    t2 = sg.anova(m2, pois_data)
    # the two residual deviances come from IRLS runs over differently
    # ordered designs, so they agree to solver tolerance, not exactly
    # (measured ~3e-9 relative on some BLAS builds)
    np.testing.assert_allclose(t1.rows[-1][3], t2.rows[-1][3], rtol=1e-8)
    assert t1.row_names[1] == "x" and t2.row_names[1] == "grp"
    # deviance rows sum to the same total drop
    np.testing.assert_allclose(
        sum(r[1] for r in t1.rows[1:]), sum(r[1] for r in t2.rows[1:]),
        rtol=1e-8)


def test_anova_single_f_test_and_offset_carry(rng):
    """test='F' on an estimated-dispersion family, with a by-name offset
    carried through every sequential sub-fit automatically."""
    n = 400
    x = rng.standard_normal(n)
    z = rng.standard_normal(n)
    off = rng.uniform(0.0, 1.0, n)
    mu = np.exp(0.4 + 0.6 * x + off)
    d = {"y": rng.gamma(4.0, mu / 4.0), "x": x, "z": z, "lo": off}
    m = sg.glm("y ~ x + z + offset(lo)", d, family="gamma", link="log")
    t = sg.anova(m, d, test="F")
    assert t.columns[-2:] == ("F", "Pr(>F)")
    assert t.rows[1][-1] < 1e-6    # x is real
    assert t.rows[2][-1] > 0.001   # z is null
    # the offset genuinely matters: dropping it shifts the NULL deviance
    m0 = sg.glm("y ~ x + z", d, family="gamma", link="log")
    t0 = sg.anova(m0, d, test="F")
    assert abs(t.rows[0][3] - t0.rows[0][3]) > 1e-3


def test_anova_single_guards(pois_data, rng):
    m = sg.glm("y ~ x", pois_data, family="poisson")
    with pytest.raises(ValueError, match="needs it"):
        sg.anova(m)
    X = np.c_[np.ones(50), rng.standard_normal(50)]
    yv = rng.poisson(np.exp(0.2 + 0.3 * X[:, 1])).astype(float)
    ma = sg.glm_fit(X, yv, family="poisson")
    with pytest.raises(ValueError, match="formula-fitted"):
        sg.anova(ma, {"y": yv})
    m2 = sg.glm("y ~ x + grp", pois_data, family="poisson")
    with pytest.raises(ValueError, match="single-model"):
        sg.anova(m, m2, data=pois_data)


def test_step_trace_r_format(rng, mesh8, capsys):
    """R's printed step trace: 'Start:  AIC=' block, then a per-step move
    table SORTED by AIC ascending with a '<none>' row, then 'Step:  AIC='
    after each accepted move — golden-string structure on a deterministic
    scope."""
    n = 500
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    z = rng.standard_normal(n)
    d = {"y": (3.0 + 2.0 * x1 + 1.0 * x2
               + 0.5 * rng.standard_normal(n)),
         "x1": x1, "x2": x2, "z": z}
    m = sg.lm("y ~ x1 + x2 + z", d)
    out = sg.step(m, d, direction="backward", trace=True)
    s = capsys.readouterr().out
    lines = s.splitlines()
    assert lines[0].startswith("Start:  AIC=")
    assert lines[1] == "y ~ x1 + x2 + z" and lines[2] == ""
    # header of the move table
    assert lines[3].split() == ["Df", "Sum", "of", "Sq", "RSS", "AIC"]
    # first step: dropping the null term z is the best (lowest-AIC) move,
    # so it prints FIRST; <none> next; the real effects last
    assert lines[4].startswith("- z")
    assert lines[5].startswith("<none>")
    # rows are sorted by the AIC column (last number on each line)
    aics = [float(ln.split()[-1]) for ln in lines[4:8]]
    assert aics == sorted(aics)
    # the accepted move prints R's Step block with the new formula
    step_idx = next(i for i, ln in enumerate(lines)
                    if ln.startswith("Step:  AIC="))
    assert lines[step_idx + 1] == "y ~ x1 + x2"
    # final model kept the true effects
    assert set(out.terms.design) == {("x1",), ("x2",)}


def test_step_trace_glm_deviance_columns(pois_data, capsys):
    m = sg.glm("y ~ x + z + grp", pois_data, family="poisson")
    sg.step(m, pois_data, direction="backward", trace=True)
    s = capsys.readouterr().out
    lines = s.splitlines()
    assert lines[3].split() == ["Df", "Deviance", "AIC"]
    assert any(ln.startswith("<none>") for ln in lines)
    assert "- z" in s and "Step:  AIC=" in s


def test_anova_single_refuses_na_shrunk_subfits(rng):
    """Covariate NAs shrink a sub-fit's sample (the null baseline
    included): the sequential table must refuse, never silently mix row
    removal into the differences."""
    n = 40
    x = rng.standard_normal(n)
    x[:5] = np.nan
    d = {"y": 1.0 + 0.5 * np.nan_to_num(x) + 0.1 * rng.standard_normal(n),
         "x": x}
    m = sg.lm("y ~ x", d)           # fits 35 rows (NA-omitted)
    with pytest.raises(ValueError, match="rows in use changed"):
        sg.anova(m, d)
    # GLM: the 'y ~ z' prefix omits the NA column and would fit all 40
    d["z"] = rng.standard_normal(n)
    mp = sg.glm("y ~ z + x", d, family="gaussian", link="identity")
    with pytest.raises(ValueError, match="rows in use changed"):
        sg.anova(mp, d, test="F")


def test_anova_empty_and_df_like_dispatch(pois_data):
    with pytest.raises(ValueError, match="needs a fitted model"):
        sg.anova()

    class FakeFrame(dict):  # attribute-forwarding container, like pandas
        def __getattr__(self, k):
            try:
                return self[k]
            except KeyError:
                raise AttributeError(k)

    m = sg.glm("y ~ x", pois_data, family="poisson")
    df = FakeFrame({k: v for k, v in pois_data.items()})
    df["coefficients"] = np.zeros(len(pois_data["y"]))  # trap column
    t = sg.anova(m, df)  # must dispatch as (model, data), not two models
    assert t.row_names[0] == "NULL"
