"""anova() / drop1() — R's model-comparison tables (the reference has no
model comparison at all; its whole inference surface is the summary
printer, GLM.scala:998-1025)."""

import numpy as np
import pytest
import scipy.stats

import sparkglm_tpu as sg


@pytest.fixture()
def pois_data(rng):
    n = 800
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    lam = np.exp(0.3 + 0.5 * x + 0.4 * (grp == "b"))  # z is null
    return {"x": x, "z": z, "grp": grp,
            "y": rng.poisson(lam).astype(float)}


def test_anova_glm_chisq(pois_data):
    m1 = sg.glm("y ~ x", pois_data, family="poisson")
    m2 = sg.glm("y ~ x + grp", pois_data, family="poisson")
    m3 = sg.glm("y ~ x + grp + z", pois_data, family="poisson")
    t = sg.anova(m1, m2, m3, test="Chisq")
    assert t.columns == ("Resid. Df", "Resid. Dev", "Df", "Deviance",
                         "Pr(>Chi)")
    assert t.rows[0][2] is None  # first row has no comparison
    # row 2: m1 -> m2, df diff 1, deviance drop large, p tiny
    assert t.rows[1][2] == 1
    dd = t.rows[1][3]
    np.testing.assert_allclose(dd, m1.deviance - m2.deviance, rtol=1e-12)
    np.testing.assert_allclose(t.rows[1][4], scipy.stats.chi2.sf(dd, 1),
                               rtol=1e-10)
    assert t.rows[1][4] < 1e-6       # grp is a real effect
    assert t.rows[2][4] > 0.01       # z is null
    s = str(t)
    assert "Analysis of Deviance Table" in s and "Pr(>Chi)" in s


def test_anova_glm_f_gamma(rng):
    """Estimated-dispersion family: F test scaled by the largest model's
    dispersion, as in R."""
    n = 600
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    mu = np.exp(0.5 + 0.4 * x)
    d = {"x": x, "z": z, "y": rng.gamma(4.0, mu / 4.0)}
    m1 = sg.glm("y ~ x", d, family="gamma", link="log")
    m2 = sg.glm("y ~ x + z", d, family="gamma", link="log")
    t = sg.anova(m1, m2, test="F")
    fstat = t.rows[1][4]
    expect = ((m1.deviance - m2.deviance) / 1) / m2.dispersion
    np.testing.assert_allclose(fstat, expect, rtol=1e-10)
    np.testing.assert_allclose(
        t.rows[1][5], scipy.stats.f.sf(expect, 1, m2.df_residual), rtol=1e-9)


def test_anova_lm(rng):
    n = 400
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    d = {"x": x, "z": z, "y": 1.0 + 2.0 * x + 0.3 * rng.normal(size=n)}
    m1 = sg.lm("y ~ x", d)
    m2 = sg.lm("y ~ x + z", d)
    t = sg.anova(m1, m2, test="F")
    assert t.columns[:4] == ("Res.Df", "RSS", "Df", "Sum of Sq")
    s2 = m2.sse / m2.df_resid
    expect_f = (m1.sse - m2.sse) / s2
    np.testing.assert_allclose(t.rows[1][4], expect_f, rtol=1e-10)
    assert t.rows[1][5] > 0.01  # z is noise


def test_anova_validation(pois_data, rng):
    m1 = sg.glm("y ~ x", pois_data, family="poisson")
    with pytest.raises(ValueError, match="at least two"):
        sg.anova(m1)
    d2 = {"x": rng.normal(size=100), "y": np.ones(100)}
    m_other = sg.lm("y ~ x", d2)
    with pytest.raises(TypeError, match="mix"):
        sg.anova(m1, m_other)
    m_small = sg.glm("y ~ x", {k: v[:300] for k, v in pois_data.items()},
                     family="poisson")
    with pytest.raises(ValueError, match="different row counts"):
        sg.anova(m1, m_small)


def test_drop1_glm(pois_data):
    m = sg.glm("y ~ x + grp + z", pois_data, family="poisson")
    t = sg.drop1(m, pois_data, test="Chisq")
    assert t.row_names == ("<none>", "x", "grp", "z")
    # each reduced fit's deviance must exceed the full model's
    for row in t.rows[1:]:
        assert row[1] >= m.deviance
        assert row[0] == 1
    # LRT for each dropped term matches an explicit nested-model anova
    m_no_z = sg.glm("y ~ x + grp", pois_data, family="poisson")
    z_row = t.rows[t.row_names.index("z")]
    np.testing.assert_allclose(z_row[3], m_no_z.deviance - m.deviance,
                               rtol=1e-9, atol=1e-9)
    assert z_row[4] > 0.01       # z null
    grp_row = t.rows[t.row_names.index("grp")]
    assert grp_row[4] < 1e-6     # grp real


def test_drop1_respects_marginality(rng):
    """With x:grp in the model, x and grp are marginal and not droppable —
    only the interaction appears in the scope (R's hierarchy rule)."""
    n = 500
    x = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    eta = 0.2 + 0.5 * x + 0.3 * (grp == "b") - 0.4 * x * (grp == "b")
    d = {"x": x, "grp": grp,
         "y": (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)}
    m = sg.glm("y ~ x * grp", d, family="binomial")
    t = sg.drop1(m, d, test="Chisq")
    assert t.row_names == ("<none>", "x:grp")


def test_intercept_only_formula(rng):
    """'y ~ 1' is R's null model; 'y ~ offset(a)' the offset-only variant."""
    n = 300
    y = rng.poisson(3.0, size=n).astype(float)
    m = sg.glm("y ~ 1", {"y": y}, family="poisson")
    assert m.xnames == ("intercept",)
    np.testing.assert_allclose(np.exp(m.coefficients[0]), y.mean(), rtol=1e-6)
    np.testing.assert_allclose(m.deviance, m.null_deviance, rtol=1e-10)
    lt = rng.uniform(0.2, 0.8, size=n)
    m2 = sg.glm("y ~ offset(lt)", {"y": y, "lt": lt}, family="poisson")
    assert m2.xnames == ("intercept",)
    # a no-predictor, no-intercept formula is still an error
    with pytest.raises(ValueError, match="no predictor terms"):
        sg.glm("y ~ -1", {"y": y}, family="poisson")


def test_drop1_single_term_refits_null(rng):
    n = 400
    x = rng.normal(size=n)
    d = {"x": x, "y": rng.poisson(np.exp(0.3 + 0.5 * x)).astype(float)}
    m = sg.glm("y ~ x", d, family="poisson")
    t = sg.drop1(m, d, test="Chisq")
    assert t.row_names == ("<none>", "x")
    # the reduced fit IS the null model
    np.testing.assert_allclose(t.rows[1][1], m.null_deviance, rtol=1e-8)


def test_drop1_refuses_array_offset(rng):
    n = 300
    x = rng.normal(size=n)
    off = rng.uniform(0.1, 0.5, size=n)
    d = {"x": x, "y": rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)}
    m = sg.glm("y ~ x", d, family="poisson", offset=off)
    with pytest.raises(ValueError, match="array offset"):
        sg.drop1(m, d)
    # explicitly passing it back works
    t = sg.drop1(m, d, offset=off, test="Chisq")
    assert t.row_names == ("<none>", "x")


def test_anova_lm_chisq_is_chisq(rng):
    n = 400
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    d = {"x": x, "z": z, "y": 1.0 + 2.0 * x + 0.3 * rng.normal(size=n)}
    m1 = sg.lm("y ~ x", d)
    m2 = sg.lm("y ~ x + z", d)
    t = sg.anova(m1, m2, test="Chisq")
    assert t.columns == ("Res.Df", "RSS", "Df", "Sum of Sq", "Pr(>Chi)")
    s2 = m2.sse / m2.df_resid
    expect = scipy.stats.chi2.sf((m1.sse - m2.sse) / s2, 1)
    np.testing.assert_allclose(t.rows[1][4], expect, rtol=1e-10)


def test_drop1_lm_and_offset(rng):
    n = 400
    x = rng.normal(size=n)
    z = rng.normal(size=n)
    d = {"x": x, "z": z, "y": 1.0 + 2.0 * x + 0.3 * rng.normal(size=n)}
    t = sg.drop1(sg.lm("y ~ x + z", d), d)
    assert t.columns == ("Df", "Sum of Sq", "RSS", "AIC")
    assert t.rows[1][2] > t.rows[0][2]  # dropping x raises RSS a lot
    # a by-name fit-time offset travels into the refits automatically
    lt = rng.uniform(0.2, 0.8, size=n)
    dp = {"x": x, "z": z, "lt": lt,
          "y": rng.poisson(np.exp(0.2 + 0.4 * x + lt)).astype(float)}
    mp = sg.glm("y ~ x + z + offset(lt)", dp, family="poisson")
    tp = sg.drop1(mp, dp, test="Chisq")
    sub = sg.glm("y ~ x + offset(lt)", dp, family="poisson")
    z_row = tp.rows[tp.row_names.index("z")]
    np.testing.assert_allclose(z_row[1], sub.deviance, rtol=1e-9)


def test_add1_glm_matches_explicit_refits(rng, mesh8):
    """R's add1: each scope term refit ADDED; Df/Deviance/AIC/LRT match
    explicit update() refits, and terms already in the model are skipped."""
    n = 3000
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    y = rng.poisson(np.exp(0.4 + 0.5 * x1 + 0.3 * x2
                           - 0.4 * (g == "b"))).astype(float)
    data = {"y": y, "x1": x1, "x2": x2, "g": g}
    m = sg.glm("y ~ x1", data, family="poisson", mesh=mesh8)
    tbl = sg.add1(m, "~ x1 + x2 + g", data, test="Chisq")
    assert tbl.row_names == ("<none>", "x2", "g")
    m_x2 = sg.update(m, "~ . + x2", data)
    m_g = sg.update(m, "~ . + g", data)
    rows = dict(zip(tbl.row_names, tbl.rows))
    assert rows["x2"][0] == 1 and rows["g"][0] == 2
    assert rows["x2"][1] == pytest.approx(m_x2.deviance, rel=1e-10)
    assert rows["g"][2] == pytest.approx(m_g.aic, rel=1e-10)
    # LRT at the original model's dispersion (Poisson: 1)
    assert rows["x2"][3] == pytest.approx(m.deviance - m_x2.deviance,
                                          rel=1e-10)
    assert 0 <= rows["x2"][4] <= 1
    text = str(tbl)
    assert "Single term additions" in text and "<none>" in text

    with pytest.raises(ValueError, match="adds no terms"):
        sg.add1(m, "~ x1", data)


def test_add1_lm_and_from_csv_path(tmp_path, rng, mesh8):
    """add1 on lm uses R's drop1/add1 AIC scale; path data streams the
    refits out-of-core through update()."""
    import csv as csv_mod
    n = 2000
    x1 = np.round(rng.standard_normal(n), 6)
    x2 = np.round(rng.standard_normal(n), 6)
    y = np.round(1.0 + 0.8 * x1 + 0.5 * x2 + 0.3 * rng.standard_normal(n), 6)
    data = {"y": y, "x1": x1, "x2": x2}
    m = sg.lm("y ~ x1", data, mesh=mesh8)
    tbl = sg.add1(m, "~ . + x2", data)
    rows = dict(zip(tbl.row_names, tbl.rows))
    m_full = sg.update(m, "~ . + x2", data)
    assert rows["x2"][0] == 1
    assert rows["x2"][1] == pytest.approx(m.sse - m_full.sse, rel=1e-9)
    assert rows["x2"][2] == pytest.approx(m_full.sse, rel=1e-9)

    p = tmp_path / "d.csv"
    with open(p, "w", newline="") as fh:
        w = csv_mod.writer(fh)
        w.writerow(["y", "x1", "x2"])
        for i in range(n):
            w.writerow([y[i], x1[i], x2[i]])
    m_csv = sg.lm_from_csv("y ~ x1", str(p), chunk_bytes=16 << 10)
    tbl_csv = sg.add1(m_csv, "~ . + x2", str(p))
    rows_csv = dict(zip(tbl_csv.row_names, tbl_csv.rows))
    np.testing.assert_allclose(rows_csv["x2"][2], rows["x2"][2], rtol=1e-6)


def test_add1_guards(rng, mesh8):
    """Scope syntax is validated (no silent misparse), a:b == b:a dedups,
    and a candidate with NAs that shrinks the sample is refused."""
    n = 500
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    x3 = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.3 + 0.5 * x1)).astype(float)
    data = {"y": y, "x1": x1, "x2": x2, "x3": x3}
    m = sg.glm("y ~ x1", data, family="poisson", mesh=mesh8)
    with pytest.raises(ValueError, match="unsupported scope"):
        sg.add1(m, "~ . + x2^2", data)
    tbl = sg.add1(m, "~ x2:x3 + x3:x2", data)
    assert tbl.row_names == ("<none>", "x2:x3")  # canonical dedup
    bad = dict(data, x2=np.where(np.arange(n) < 10, np.nan, x2))
    with pytest.raises(ValueError, match="rows in use changed"):
        sg.add1(m, "~ . + x2", bad)


def test_step_both_directions_recovers_truth(rng, mesh8):
    """R's step(): AIC-guided stepwise selection.  With two real effects,
    two noise columns, and an interaction candidate whose margins gate
    it, 'both' lands on the true model from an overfit start."""
    n = 4000
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    z1 = rng.standard_normal(n)
    z2 = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.4 + 0.6 * x1 - 0.5 * x2)).astype(float)
    data = {"y": y, "x1": x1, "x2": x2, "z1": z1, "z2": z2}

    # backward from the full model
    full = sg.glm("y ~ x1 + x2 + z1 + z2", data, family="poisson", mesh=mesh8)
    back = sg.step(full, data, direction="backward")
    assert set(back.xnames) == {"intercept", "x1", "x2"}

    # forward from the null model over a scope incl. a gated interaction
    null = sg.glm("y ~ 1", data, family="poisson", mesh=mesh8)
    fwd = sg.step(null, data, scope="~ x1 + x2 + z1 + z2 + x1:x2",
                  direction="forward")
    assert {"x1", "x2"} <= set(fwd.xnames)
    assert not ({"z1", "z2"} & set(fwd.xnames))

    # both: same destination from a wrong start
    start = sg.glm("y ~ z1 + z2", data, family="poisson", mesh=mesh8)
    both = sg.step(start, data, scope="~ x1 + x2 + z1 + z2")
    assert set(both.xnames) == {"intercept", "x1", "x2"}
    # the returned object is a normal fitted model
    assert both.converged and "Pr(>|z|)" in str(both.summary())


def test_step_lm_bic_and_guards(rng, mesh8):
    n = 2000
    x1 = rng.standard_normal(n)
    z = rng.standard_normal(n)
    y = 1.0 + 0.8 * x1 + 0.3 * rng.standard_normal(n)
    data = {"y": y, "x1": x1, "z": z}
    full = sg.lm("y ~ x1 + z", data, mesh=mesh8)
    chosen = sg.step(full, data, k=float(np.log(n)))  # BIC drops z
    assert set(chosen.xnames) == {"intercept", "x1"}
    with pytest.raises(ValueError, match="direction"):
        sg.step(full, data, direction="sideways")
    with pytest.raises(ValueError, match="scope"):
        sg.step(full, data, direction="forward")
    # quasi families have no AIC — refuse like R
    yq = rng.poisson(np.exp(0.3 + 0.5 * x1)).astype(float)
    mq = sg.glm("y ~ x1", {"y": yq, "x1": x1}, family="quasipoisson",
                mesh=mesh8)
    with pytest.raises(ValueError, match="AIC is not defined"):
        sg.step(mq, {"y": yq, "x1": x1})


def test_step_scope_dot_allows_reentry_and_minus_rejected(rng, mesh8):
    """'.' in scope keeps the ORIGINAL terms addable (a dropped term can
    re-enter under direction='both'); '-' scope terms are an error, not a
    silent constraint change."""
    n = 3000
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    y = rng.poisson(np.exp(0.4 + 0.7 * x1)).astype(float)
    data = {"y": y, "x1": x1, "x2": x2}
    m = sg.glm("y ~ x1 + x2", data, family="poisson", mesh=mesh8)
    with pytest.raises(ValueError, match="'-' terms"):
        sg.step(m, data, scope="~ . - x2")
    # scope "~ ." alone: both-direction selection over the original terms
    sel = sg.step(m, data, scope="~ .")
    assert set(sel.xnames) == {"intercept", "x1"}
    # hierarchy gate: x1:x2 never enters while x2 is out
    sel2 = sg.step(sg.glm("y ~ x1", data, family="poisson", mesh=mesh8),
                   data, scope="~ . + x2 + x1:x2")
    assert "x1:x2" not in sel2.xnames or "x2" in sel2.xnames
