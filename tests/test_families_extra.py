"""Quasi families, inverse-gaussian, GLM predict types, count/Bernoulli
equivalence, profiling timer."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def test_quasipoisson_matches_poisson_coefs(mesh8, rng):
    """Same coefficients as poisson; dispersion estimated, AIC NaN (R)."""
    n, p = 1500, 4
    X = rng.normal(size=(n, p)) * 0.5
    X[:, 0] = 1.0
    y = rng.poisson(np.exp(X @ (rng.normal(size=p) * 0.4)) * 2).astype(float)
    mp = sg.glm_fit(X, y, family="poisson", tol=1e-10, mesh=mesh8)
    mq = sg.glm_fit(X, y, family="quasipoisson", tol=1e-10, mesh=mesh8)
    np.testing.assert_allclose(mq.coefficients, mp.coefficients, rtol=1e-9)
    assert mp.dispersion == 1.0
    assert mq.dispersion != 1.0 and np.isfinite(mq.dispersion)
    assert np.isnan(mq.aic)
    # SEs scale by sqrt(dispersion)
    np.testing.assert_allclose(
        mq.std_errors, mp.std_errors * np.sqrt(mq.dispersion), rtol=1e-6)


def test_quasibinomial(mesh8, rng):
    n, p = 1000, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ [0.2, 0.5, -0.3])))).astype(float)
    mq = sg.glm_fit(X, y, family="quasibinomial", tol=1e-10, mesh=mesh8)
    mb = sg.glm_fit(X, y, family="binomial", tol=1e-10, mesh=mesh8)
    np.testing.assert_allclose(mq.coefficients, mb.coefficients, rtol=1e-9)
    assert np.isnan(mq.aic) and np.isfinite(mb.aic)


def test_quasi_constructor(mesh8, rng):
    """R's quasi(variance=..., link=...): same coefficients as the matching
    exponential family, dispersion estimated, AIC and logLik NA."""
    n, p = 1200, 3
    X = rng.normal(size=(n, p)) * 0.3
    X[:, 0] = 1.0
    mu = np.exp(X @ [0.5, 0.4, -0.3])
    y = rng.gamma(4.0, mu / 4.0)
    mg = sg.glm_fit(X, y, family="gamma", link="log", tol=1e-10, mesh=mesh8)
    mq = sg.glm_fit(X, y, family=sg.quasi("mu^2"), link="log", tol=1e-10,
                    mesh=mesh8)
    np.testing.assert_allclose(mq.coefficients, mg.coefficients, rtol=1e-9)
    assert mq.family == "quasi(mu^2)"
    assert np.isnan(mq.aic) and np.isnan(mq.loglik)
    assert np.isfinite(mq.dispersion) and mq.dispersion != 1.0
    np.testing.assert_allclose(mq.deviance, mg.deviance, rtol=1e-9)
    # string round-trip (what serialize stores) and the R default
    assert sg.get_family("quasi(mu^2)").name == "quasi(mu^2)"
    assert sg.get_family("quasi").name == "quasi(constant)"
    assert sg.quasi().default_link == "identity"
    with pytest.raises(ValueError, match="unknown quasi variance"):
        sg.quasi("mu^4")


def test_quasi_constant_matches_wls(mesh8, rng):
    """quasi(constant, identity) is weighted least squares with estimated
    dispersion — coefficients match lm_fit exactly."""
    n, p = 900, 4
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = X @ [1.0, 0.5, -0.2, 0.3] + 0.4 * rng.normal(size=n)
    mq = sg.glm_fit(X, y, family=sg.quasi(), tol=1e-12, mesh=mesh8)
    ml = sg.lm_fit(X, y, mesh=mesh8)
    np.testing.assert_allclose(mq.coefficients, ml.coefficients,
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(mq.std_errors, ml.std_errors, rtol=1e-6)


def test_quasi_loglik_na_for_quasipoisson(mesh8, rng):
    """R's logLik(quasipoisson fit) is NA — reporting the poisson number
    would claim a likelihood the model does not define."""
    n = 600
    X = rng.normal(size=(n, 3)); X[:, 0] = 1.0
    y = rng.poisson(np.exp(X @ [0.3, 0.4, -0.2])).astype(float)
    mq = sg.glm_fit(X, y, family="quasipoisson", tol=1e-10, mesh=mesh8)
    assert np.isnan(mq.loglik) and np.isnan(mq.aic)


def test_response_domain_validation(mesh1, rng):
    """R's family$initialize checks: Gamma rejects y <= 0, poisson rejects
    negatives, binomial demands [0,1]; quasi(variance) skips them like R."""
    n = 64
    X = rng.normal(size=(n, 2)); X[:, 0] = 1.0
    y_pos = rng.gamma(2.0, 1.0, size=n)
    y0 = y_pos.copy(); y0[3] = 0.0
    with pytest.raises(ValueError, match="Gamma"):
        sg.glm_fit(X, y0, family="gamma", link="log", mesh=mesh1)
    with pytest.raises(ValueError, match="negative values"):
        sg.glm_fit(X, np.where(np.arange(n) == 5, -1.0, 2.0),
                   family="poisson", mesh=mesh1)
    with pytest.raises(ValueError, match="0 <= y <= 1"):
        sg.glm_fit(X, np.full(n, 1.5), family="binomial", mesh=mesh1)
    with pytest.raises(ValueError, match="inverse.gaussian"):
        sg.glm_fit(X, y0, family="inverse_gaussian", link="log", mesh=mesh1)
    # streaming path raises too
    with pytest.raises(ValueError, match="Gamma"):
        sg.glm_fit_streaming((X, y0), family="gamma", link="log",
                             chunk_rows=32, mesh=mesh1)


def test_quasi_mu2_zero_response_matches_r(mesh1, rng):
    """quasi(mu^2) permits y == 0 (R's quasi has no initialize check) and
    R's y==0 deviance guard gives exactly -2*wt per zero row at mu — not
    the ~690 an epsilon-clamped log would add."""
    from sparkglm_tpu.models import hoststats
    d = hoststats.dev_resids("quasi(mu^2)", np.array([0.0]),
                             np.array([1.5]), np.array([1.0]))
    np.testing.assert_allclose(d, [-2.0], rtol=1e-12)
    # end-to-end: a quasi(mu^2)/log fit with some zero responses converges
    n = 400
    X = rng.normal(size=(n, 2)) * 0.3; X[:, 0] = 1.0
    mu = np.exp(X @ [0.4, 0.5])
    y = rng.gamma(2.0, mu / 2.0)
    y[::50] = 0.0
    m = sg.glm_fit(X, y, family=sg.quasi("mu^2"), link="log", tol=1e-10,
                   mesh=mesh1)
    assert m.converged and np.all(np.isfinite(m.coefficients))
    assert np.isfinite(m.deviance)


def test_inverse_gaussian_family(mesh8, rng):
    n, p = 1200, 3
    X = np.abs(rng.normal(size=(n, p))) * 0.2 + 0.1
    X[:, 0] = 1.0
    mu_true = 1.0 / np.sqrt(X @ [1.0, 0.5, 0.8])
    y = np.abs(rng.normal(loc=mu_true, scale=0.05 * mu_true))
    m = sg.glm_fit(X, y, family="inverse_gaussian", tol=1e-10, mesh=mesh8)
    assert m.converged
    assert np.all(np.isfinite(m.coefficients))
    assert m.link == "inverse_squared"


def test_counts_m_equals_expanded_bernoulli(mesh8, rng):
    """y successes out of m per row must fit identically to the expanded
    one-row-per-trial Bernoulli data (the classic aggregation identity)."""
    n, p = 120, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    mm = rng.integers(1, 8, size=n)
    prob = 1 / (1 + np.exp(-(X @ [0.3, -0.5, 0.4])))
    counts = rng.binomial(mm, prob).astype(float)
    mg = sg.glm_fit(X, counts, family="binomial", m=mm.astype(float),
                    tol=1e-11, mesh=mesh8)
    Xe = np.repeat(X, mm, axis=0)
    ye = np.concatenate([
        np.r_[np.ones(int(c)), np.zeros(int(t - c))]
        for c, t in zip(counts, mm)])
    me = sg.glm_fit(Xe, ye, family="binomial", tol=1e-11, mesh=mesh8)
    np.testing.assert_allclose(mg.coefficients, me.coefficients,
                               rtol=1e-7, atol=1e-9)
    np.testing.assert_allclose(mg.std_errors, me.std_errors, rtol=1e-6)


def test_quasibinomial_accepts_group_sizes(mesh8, rng):
    n, p = 400, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    mm = rng.integers(1, 10, size=n).astype(float)
    prob = 1 / (1 + np.exp(-(X @ [0.2, 0.4, -0.3])))
    counts = rng.binomial(mm.astype(int), prob).astype(float)
    mq = sg.glm_fit(X, counts, family="quasibinomial", m=mm, tol=1e-10,
                    mesh=mesh8)
    mb = sg.glm_fit(X, counts, family="binomial", m=mm, tol=1e-10, mesh=mesh8)
    np.testing.assert_allclose(mq.coefficients, mb.coefficients, rtol=1e-9)
    with pytest.raises(ValueError, match="binomial"):
        sg.glm_fit(X, counts, family="poisson", m=mm, mesh=mesh8)


def test_glm_predict_types(mesh8, rng):
    n = 800
    d = {"y": (rng.random(n) < 0.4).astype(float), "x": rng.normal(size=n)}
    m = sg.glm("y ~ x", d, family="binomial", mesh=mesh8)
    new = {"x": np.linspace(-2, 2, 9)}
    eta = sg.predict(m, new, type="link")
    mu = sg.predict(m, new, type="response")
    np.testing.assert_allclose(mu, 1 / (1 + np.exp(-eta)), rtol=1e-6)
    assert np.all((mu > 0) & (mu < 1))
    tp = sg.predict(m, new, type="terms")  # supported since r3
    np.testing.assert_allclose(tp.matrix.sum(axis=1) + tp.constant, eta,
                               rtol=1e-5)
    with pytest.raises(ValueError, match="type"):
        sg.predict(m, new, type="bogus")


def test_glm_vcov_confint_residuals(mesh8, rng):
    from oracle import irls_np
    n, p = 1000, 4
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ [0.3, 0.5, -0.4, 0.2])))).astype(float)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-11, mesh=mesh8)
    _, _, _, cov = irls_np(X, y, "binomial", "logit")
    np.testing.assert_allclose(m.vcov(), cov, rtol=1e-4, atol=1e-10)
    ci = m.confint(0.95)
    np.testing.assert_allclose(ci[:, 1] - ci[:, 0],
                               2 * 1.959963985 * m.std_errors, rtol=1e-9)
    # residual identities
    mu = 1 / (1 + np.exp(-(X @ m.coefficients)))
    np.testing.assert_allclose(m.residuals(X, y, type="response"), y - mu,
                               rtol=1e-6, atol=1e-9)
    rp = m.residuals(X, y, type="pearson")
    np.testing.assert_allclose(np.sum(rp ** 2), m.pearson_chi2, rtol=1e-6)
    rd = m.residuals(X, y, type="deviance")
    np.testing.assert_allclose(np.sum(rd ** 2), m.deviance, rtol=1e-6)


def test_lm_vcov_confint_residuals(mesh8, rng):
    n, p = 800, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = X @ [1.0, 0.5, -0.3] + 0.2 * rng.normal(size=n)
    m = sg.lm_fit(X, y, mesh=mesh8)
    np.testing.assert_allclose(np.sqrt(np.diag(m.vcov())), m.std_errors,
                               rtol=1e-9)
    ci = m.confint()
    assert np.all(ci[:, 0] < m.coefficients) and np.all(ci[:, 1] > m.coefficients)
    r = m.residuals(X, y)
    np.testing.assert_allclose(np.sum(r ** 2), m.sse, rtol=1e-6)


def test_residuals_column_y_and_grouped_m(mesh1, rng):
    """(n,1) y must not broadcast to (n,n); grouped-binomial residuals need
    the m argument to reproduce training stats."""
    n, p = 150, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = (rng.random(n) < 0.5).astype(float)
    m = sg.glm_fit(X, y.reshape(-1, 1), family="binomial", tol=1e-10,
                   mesh=mesh1)
    r = m.residuals(X, y.reshape(-1, 1), type="response")
    assert r.shape == (n,)
    ml = sg.lm_fit(X, y.reshape(-1, 1), mesh=mesh1)
    assert ml.residuals(X, y.reshape(-1, 1)).shape == (n,)
    # grouped binomial
    mm = rng.integers(1, 9, size=n).astype(float)
    counts = rng.binomial(mm.astype(int),
                          1 / (1 + np.exp(-(X @ [0.2, 0.4, -0.3])))).astype(float)
    mg = sg.glm_fit(X, counts, family="binomial", m=mm, tol=1e-11, mesh=mesh1)
    rp = mg.residuals(X, counts, type="pearson", m=mm)
    np.testing.assert_allclose(np.sum(rp ** 2), mg.pearson_chi2, rtol=1e-5)
    rd = mg.residuals(X, counts, type="deviance", m=mm)
    np.testing.assert_allclose(np.sum(rd ** 2), mg.deviance, rtol=1e-5)


def test_profiling_timer(mesh1, rng):
    import jax.numpy as jnp
    t = sg.profiling.Timer().start()
    out = jnp.ones((64, 64)) @ jnp.ones((64, 64))
    dt = t.stop(out)
    assert dt > 0 and t.elapsed == dt


def test_gaussian_log_negative_y_fits_where_r_needs_mustart(mesh1, rng):
    """gaussian/log with negative responses: R's glm errors ('cannot find
    valid starting values') because its init takes log(y); our guarded init
    self-starts and converges to the true nonlinear-LS optimum (verified
    against scipy.optimize.least_squares to 1e-9 in r2)."""
    import warnings as _w
    from scipy.optimize import least_squares
    n = 1000
    X = np.column_stack([np.ones(n), rng.normal(size=(n, 2))])
    bt = np.array([-0.5, 0.4, -0.3])
    y = np.exp(X @ bt) + 0.5 * rng.normal(size=n)
    assert (y <= 0).sum() > 50  # the regime R cannot self-start in
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        m = sg.glm_fit(X, y, family="gaussian", link="log", tol=1e-12,
                       criterion="relative", max_iter=200, mesh=mesh1)
    r = least_squares(lambda b: np.exp(X @ b) - y, np.zeros(3),
                      xtol=1e-15, ftol=1e-15)
    np.testing.assert_allclose(m.coefficients, r.x, atol=1e-6)
    assert m.deviance == pytest.approx(float(np.sum(r.fun ** 2)), rel=1e-9)
