"""Structured fit telemetry (sparkglm_tpu.obs): trace events, metrics,
device-aware spans — and the numerics-neutrality contract: traced and
untraced fits produce bit-identical coefficients (events are host-side;
device code is unchanged)."""

import collections
import io
import json
import os

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.models import glm as glm_mod
from sparkglm_tpu.models import lm as lm_mod
from sparkglm_tpu.models import streaming
from sparkglm_tpu.obs import (FitTracer, JsonlSink, MetricsRegistry,
                              RingBufferSink, Span, StderrSink, as_tracer)
from sparkglm_tpu.obs import trace as obs_trace
from sparkglm_tpu.robust import FaultPlan, RetryPolicy, SimulatedPreemption
from sparkglm_tpu.robust import faulty_source, retrying_source

NOSLEEP = RetryPolicy(sleep=lambda s: None)


def _binomial_data(rng, n=4000, p=4):
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    return X, y


def _chunk_factory(X, y, n_chunks=5):
    n = X.shape[0]

    def source():
        for i in range(n_chunks):
            lo = n * i // n_chunks
            hi = n * (i + 1) // n_chunks
            yield lambda lo=lo, hi=hi: (X[lo:hi], y[lo:hi], None, None)

    return source


def _ring_tracer():
    ring = RingBufferSink()
    return ring, FitTracer(sinks=[ring])


# ---------------------------------------------------------------------------
# trace primitives
# ---------------------------------------------------------------------------

def test_tracer_events_ordered_and_typed():
    ring, tr = _ring_tracer()
    tr.emit("fit_start", model="x")
    tr.iter(1, 10.0, 1.0)
    tr.iter(2, 9.5, 0.5, halvings=2)
    tr.pass_start("irls", 1)
    tr.pass_end("irls", 1, chunks=3, rows=300, bytes=1200, io_s=0.1,
                compute_s=0.2)
    evs = ring.events
    assert [e.seq for e in evs] == list(range(len(evs)))
    assert ring.kinds() == ["fit_start", "iter", "iter", "pass_start",
                            "pass_end"]
    rep = tr.report()
    assert rep["iterations"] == 2
    assert rep["halvings"] == 2
    assert rep["chunks"] == 3 and rep["rows_streamed"] == 300
    assert rep["io_s"] == pytest.approx(0.1)
    # key() excludes the wall timestamp: two tracers emitting the same
    # events have identical keys even though t differs
    ring2, tr2 = _ring_tracer()
    tr2.emit("fit_start", model="x")
    assert ring2.events[0].key() == evs[0].key()


def test_jsonl_sink_roundtrip(tmp_path):
    path = tmp_path / "sub" / "trace.jsonl"  # parent dir created lazily
    tr = FitTracer(sinks=[JsonlSink(path)])
    tr.emit("fit_start", model="glm")
    tr.iter(1, 2.5, 0.5)
    tr.close()
    lines = [json.loads(s) for s in path.read_text().splitlines()]
    assert [d["kind"] for d in lines] == ["fit_start", "iter"]
    assert lines[1]["deviance"] == 2.5 and lines[1]["seq"] == 1


def test_stderr_sink_formats_legacy_lines():
    buf = io.StringIO()
    tr = FitTracer(sinks=[StderrSink(stream=buf)])
    tr.iter(3, 123.456, 0.01)
    tr.iter(4, 120.0, 0.002, halvings=1)
    tr.emit("fit_end", iterations=4, deviance=120.0, converged=True)
    tr.emit("solve", target="x")  # not printed unless all_events
    out = buf.getvalue()
    assert "iter 3\tdeviance 123.456\tddev 0.01" in out
    assert "halvings 1" in out
    assert "IRLS finished: 4 iterations" in out
    assert "solve" not in out


def test_as_tracer_coercions(tmp_path):
    assert as_tracer(None) is None
    assert isinstance(as_tracer(True).sinks[0], StderrSink)
    assert isinstance(as_tracer(str(tmp_path / "t.jsonl")).sinks[0],
                      JsonlSink)
    tr = FitTracer()
    assert as_tracer(tr) is tr
    # verbose=True is the stderr preset — added to an existing tracer once
    as_tracer(tr, verbose=True)
    as_tracer(tr, verbose=True)
    assert sum(isinstance(s, StderrSink) for s in tr.sinks) == 1
    with pytest.raises(TypeError):
        as_tracer(12345)


def test_metrics_registry_snapshot_and_json():
    m = MetricsRegistry()
    m.counter("a").inc()
    m.counter("a").inc(2)
    m.gauge("g").set(1.5)
    h = m.histogram("h")
    for v in (0.1, 0.2, 0.4):
        h.observe(v)
    snap = m.snapshot()
    assert snap["counters"]["a"] == 3
    assert snap["gauges"]["g"] == 1.5
    assert snap["histograms"]["h"]["count"] == 3
    assert snap["histograms"]["h"]["mean"] == pytest.approx(0.7 / 3)
    json.loads(m.to_json())  # serializable
    with pytest.raises(TypeError):
        m.gauge("a")  # name already a Counter


def test_span_emits_into_ambient():
    ring, tr = _ring_tracer()
    with obs_trace.ambient(tr):
        with Span("work") as sp:
            pass
    assert ring.kinds() == ["span"]
    assert ring.events[0].fields["name"] == "work"
    assert sp.seconds >= 0.0
    # exceptions suppress the emit (no half-measured spans)
    with pytest.raises(RuntimeError):
        with obs_trace.ambient(tr), Span("bad"):
            raise RuntimeError("x")
    assert ring.kinds() == ["span"]


def test_ambient_restores_previous():
    t1, t2 = FitTracer(), FitTracer()
    assert obs_trace.current_tracer() is None
    with obs_trace.ambient(t1):
        assert obs_trace.current_tracer() is t1
        with obs_trace.ambient(t2):
            assert obs_trace.current_tracer() is t2
        assert obs_trace.current_tracer() is t1
    assert obs_trace.current_tracer() is None


# ---------------------------------------------------------------------------
# numerics neutrality: traced == untraced, bit for bit
# ---------------------------------------------------------------------------

def test_resident_glm_traced_bit_identical(rng):
    """The overhead guard of the acceptance criteria: tracing must not
    change a single bit of the resident fit (events ride jax.debug.callback
    outside the dataflow)."""
    X, y = _binomial_data(rng)
    m0 = glm_mod.fit(X, y, family="binomial")
    ring, tr = _ring_tracer()
    m1 = glm_mod.fit(X, y, family="binomial", trace=tr)
    assert np.array_equal(np.asarray(m0.coefficients),
                          np.asarray(m1.coefficients))
    assert float(m0.deviance) == float(m1.deviance)
    assert np.array_equal(np.asarray(m0.std_errors),
                          np.asarray(m1.std_errors))
    kinds = set(ring.kinds())
    assert {"fit_start", "iter", "solve", "fit_end"} <= kinds
    rep = m1.fit_report()
    assert rep["iterations"] == m1.iterations
    assert rep["solves"] >= 1
    assert m0.fit_info is None  # untraced fits carry no report payload


def test_streaming_glm_traced_bit_identical(rng):
    X, y = _binomial_data(rng)
    src = _chunk_factory(X, y)
    m0 = streaming.glm_fit_streaming(src, family="binomial", cache="none")
    ring, tr = _ring_tracer()
    m1 = streaming.glm_fit_streaming(src, family="binomial", cache="none",
                                     trace=tr)
    assert np.array_equal(np.asarray(m0.coefficients),
                          np.asarray(m1.coefficients))
    assert float(m0.deviance) == float(m1.deviance)
    # iteration events mirror the untraced fit's trajectory exactly
    iters = [e for e in ring.events if e.kind == "iter"]
    assert len(iters) == m0.iterations
    # (approx: the stats pass re-measures deviance, which can move the
    # last ulp relative to the in-loop measurement the iter event carries)
    assert iters[-1].fields["deviance"] == pytest.approx(
        float(m0.deviance), rel=1e-12)
    rep = m1.fit_report()
    assert rep["passes"] >= m0.iterations + 2  # init + irls + stats
    assert rep["rows_streamed"] >= X.shape[0]
    assert rep["chunks"] > 0 and rep["bytes_to_device"] > 0


def test_lm_traced_bit_identical(rng):
    X, y = _binomial_data(rng)
    m0 = lm_mod.fit(X, y)
    ring, tr = _ring_tracer()
    m1 = lm_mod.fit(X, y, trace=tr)
    assert np.array_equal(np.asarray(m0.coefficients),
                          np.asarray(m1.coefficients))
    assert float(m0.sse) == float(m1.sse)
    assert {"fit_start", "solve", "span", "fit_end"} <= set(ring.kinds())
    assert m1.fit_report()["model"] == "lm"


# ---------------------------------------------------------------------------
# deterministic event sequences under seeded faults
# ---------------------------------------------------------------------------

def _eager_chunk_factory(X, y, n_chunks=5):
    """Chunks yielded as materialized tuples: a fault injected by
    faulty_source then raises out of the generator itself (``next``),
    driving retrying_source's mid-pass reopen + fast-forward path."""
    n = X.shape[0]

    def source():
        for i in range(n_chunks):
            lo = n * i // n_chunks
            hi = n * (i + 1) // n_chunks
            yield (X[lo:hi], y[lo:hi], None, None)

    return source


def _faulted_fit(rng_seed, trace):
    rng = np.random.default_rng(rng_seed)
    X, y = _binomial_data(rng)
    src = faulty_source(_eager_chunk_factory(X, y),
                        FaultPlan(transient_at=(7,)))
    return streaming.glm_fit_streaming(src, family="binomial", cache="none",
                                       retry=NOSLEEP, trace=trace)


# events whose fields carry no wall-clock measurements; their full key()
# (seq, kind, fields) must match bit-for-bit across runs.  pass_end /
# solve / span / compile carry seconds — for those only (seq, kind) is
# stable, which still pins the event SEQUENCE.
_STABLE_KINDS = {"fit_start", "fit_end", "iter", "retry", "pass_start",
                 "budget_exhausted"}


def _sequence_keys(events):
    return [e.key() if e.kind in _STABLE_KINDS else (e.seq, e.kind)
            for e in events]


def test_seeded_fault_event_sequence_deterministic():
    """Two runs of the same seeded FaultPlan fit produce the same event
    sequence — retries included (RetryPolicy jitter is hash-seeded, so
    even delay_s matches) — and the same coefficients as an untraced
    faulted run."""
    r1, t1 = _ring_tracer()
    m1 = _faulted_fit(5, t1)
    r2, t2 = _ring_tracer()
    m2 = _faulted_fit(5, t2)
    assert _sequence_keys(r1.events) == _sequence_keys(r2.events)
    assert "retry" in r1.kinds()
    retry = next(e for e in r1.events if e.kind == "retry")
    assert retry.fields["skipped"] == 2  # mid-pass reopen skipped 2 chunks
    assert np.array_equal(np.asarray(m1.coefficients),
                          np.asarray(m2.coefficients))
    m0 = _faulted_fit(5, None)
    assert np.array_equal(np.asarray(m0.coefficients),
                          np.asarray(m1.coefficients))
    assert m1.fit_report()["retries"] == 1
    assert m1.fit_report()["chunks_skipped"] == 2


def test_retrying_source_records_skip_count():
    """Satellite fix: the silent mid-pass fast-forward now reports how many
    chunks were skipped on reopen."""
    ring, tr = _ring_tracer()
    calls = {"n": 0}

    def chunks():
        def gen():
            yield "a"
            yield "b"
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("flaky")
            yield "c"
        return gen()

    with obs_trace.ambient(tr):
        got = list(retrying_source(chunks, NOSLEEP)())
    assert got == ["a", "b", "c"]
    retries = [e for e in ring.events if e.kind == "retry"]
    assert len(retries) == 1
    assert retries[0].fields["skipped"] == 2


def test_preempt_resume_emits_checkpoint_and_resume_events(rng, tmp_path):
    """The acceptance scenario: a preempted checkpointed fit resumed to
    completion records checkpoint_write events before the preemption and a
    resume event (plus iter events continuing the trajectory) after."""
    X, y = _binomial_data(rng)
    ck = str(tmp_path / "fit.ckpt")
    src = _chunk_factory(X, y)
    plan = FaultPlan(preempt_at=(12,))
    r1, t1 = _ring_tracer()
    with pytest.raises(SimulatedPreemption):
        streaming.glm_fit_streaming(faulty_source(src, plan),
                                    family="binomial", cache="none",
                                    checkpoint=ck, trace=t1)
    assert "checkpoint_write" in r1.kinds()
    r2, t2 = _ring_tracer()
    m = streaming.glm_fit_streaming(src, family="binomial", cache="none",
                                    checkpoint=ck, resume=True, trace=t2)
    kinds = collections.Counter(r2.kinds())
    assert kinds["resume"] == 1
    assert kinds["iter"] >= 1
    assert m.fit_report()["resumes"] == 1
    # resumed trajectory matches the uninterrupted fit bit-for-bit
    m0 = streaming.glm_fit_streaming(src, family="binomial", cache="none")
    assert np.array_equal(np.asarray(m.coefficients),
                          np.asarray(m0.coefficients))


# ---------------------------------------------------------------------------
# end-to-end: JSONL acceptance, fit_report persistence, front-ends
# ---------------------------------------------------------------------------

def test_jsonl_trace_acceptance(rng, tmp_path):
    """ISSUE acceptance: a streaming fit under an injected transient fault
    yields a JSONL trace with iteration, retry and checkpoint events, and
    fit_report() summarizes them."""
    X, y = _binomial_data(rng)
    ck = str(tmp_path / "fit.ckpt")
    jl = str(tmp_path / "trace.jsonl")
    src = faulty_source(_chunk_factory(X, y), FaultPlan(transient_at=(7,)))
    m = streaming.glm_fit_streaming(src, family="binomial", cache="none",
                                    retry=NOSLEEP, checkpoint=ck, trace=jl)
    events = [json.loads(s) for s in open(jl, encoding="utf-8")]
    kinds = collections.Counter(d["kind"] for d in events)
    assert kinds["iter"] == m.iterations
    assert kinds["retry"] == 1
    assert kinds["checkpoint_write"] == m.iterations
    assert kinds["fit_start"] == 1 and kinds["fit_end"] == 1
    rep = m.fit_report()
    assert rep["retries"] == 1
    assert rep["checkpoint_writes"] == m.iterations
    assert rep["wall_s"] > 0


def test_fit_info_survives_save_load(rng, tmp_path):
    X, y = _binomial_data(rng)
    ring, tr = _ring_tracer()
    m = glm_mod.fit(X, y, family="binomial", trace=tr)
    path = str(tmp_path / "m.model")
    sg.save_model(m, path)
    m2 = sg.load_model(path)
    assert m2.fit_info["schema"] == "sparkglm.fit_report.v1"
    assert m2.fit_report()["iterations"] == m.iterations


def test_formula_frontends_take_trace(tmp_path):
    rng = np.random.default_rng(3)
    n = 400
    data = {"x": rng.normal(size=n), "z": rng.normal(size=n)}
    eta = 0.4 * data["x"] - 0.3 * data["z"]
    data["y"] = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
    ring, tr = _ring_tracer()
    m = sg.glm("y ~ x + z", data, family="binomial", trace=tr)
    assert m.fit_info is not None and "fit_start" in ring.kinds()
    ring2, tr2 = _ring_tracer()
    m2 = sg.lm("y ~ x + z", data, trace=tr2)
    assert m2.fit_info is not None and "fit_start" in ring2.kinds()


def test_metrics_only_fit_populates_registry(rng):
    X, y = _binomial_data(rng)
    reg = MetricsRegistry()
    m = glm_mod.fit(X, y, family="binomial", metrics=reg)
    snap = reg.snapshot()
    assert snap["counters"]["events.iter"] == m.iterations
    assert snap["gauges"]["irls.deviance"] == pytest.approx(
        float(m.deviance))
    assert m.fit_info is not None  # metrics= alone still buys the report


def test_read_csv_emits_read_event(tmp_path):
    path = tmp_path / "d.csv"
    path.write_text("a,b\n1,2\n3,4\n5,6\n")
    ring, tr = _ring_tracer()
    cols = sg.read_csv(str(path), trace=tr)
    assert set(cols) == {"a", "b"}
    ev = ring.events[-1]
    assert ev.kind == "read"
    assert ev.fields["rows"] == 3 and ev.fields["format"] == "csv"
    assert ev.fields["bytes"] > 0 and ev.fields["seconds"] >= 0
    # ambient inheritance: a plain call inside ambient() lands in the tracer
    with obs_trace.ambient(tr):
        sg.read_csv(str(path))
    assert ring.kinds().count("read") == 2


def test_anova_step_out_sink(rng, capsys):
    n = 300
    data = {"x1": rng.normal(size=n), "x2": rng.normal(size=n)}
    data["y"] = (1.0 + 2.0 * data["x1"] + 0.01 * rng.normal(size=n))
    buf = io.StringIO()
    m = sg.step(sg.lm("y ~ x1 + x2", data), data, trace=True, out=buf)
    out = buf.getvalue()
    assert "Start:  AIC=" in out
    assert "<none>" in out
    assert m is not None
    assert capsys.readouterr().out == ""  # nothing leaked to stdout
