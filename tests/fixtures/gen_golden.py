"""Generate tests/fixtures/r_golden.json — R-semantics golden outputs.

Provenance (two tiers, marked per-case in the JSON):
  * ``r_doc``  — numbers printed in R's own documentation (?glm examples:
    the Dobson (1990) randomized-trial poisson fit and the McCullagh &
    Nelder clotting-time Gamma fit).  These are REAL R outputs, committed at
    the precision R prints.  ``tests/fixtures/make_r_golden.R`` re-derives
    every case with R itself (R is not installed in this build image; run
    the script anywhere R is to refresh/verify).
  * ``oracle64`` — float64 IRLS (tests/oracle.py — an implementation
    independent of sparkglm_tpu) extended here with R's exact aggregate
    formulas (stats::family()$aic etc.) for SEs, dispersion, deviances,
    logLik and AIC.

Run:  python tests/fixtures/gen_golden.py   (rewrites r_golden.json)
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
from scipy import special as sp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from oracle import irls_np  # noqa: E402  (independent f64 IRLS)

HERE = os.path.dirname(__file__)


# ---------------------------------------------------------------------------
# R-exact aggregate statistics (independent of sparkglm_tpu)
# ---------------------------------------------------------------------------

def _linkinv(link, eta):
    return {
        "identity": lambda e: e,
        "log": np.exp,
        "logit": sp.expit,
        "probit": sp.ndtr,
        "cloglog": lambda e: -np.expm1(-np.exp(e)),
        "inverse": lambda e: 1.0 / e,
        "sqrt": lambda e: e * e,
        "inverse_squared": lambda e: 1.0 / np.sqrt(e),
    }[link](eta)


def _variance(family, mu):
    return {
        "gaussian": lambda m: np.ones_like(m),
        "binomial": lambda m: m * (1 - m),
        "poisson": lambda m: m,
        "gamma": lambda m: m * m,
        "inverse_gaussian": lambda m: m ** 3,
    }[family](mu)


def _dev_resids(family, y, mu, wt):
    if family == "gaussian":
        return wt * (y - mu) ** 2
    if family == "binomial":
        return 2 * wt * (sp.xlogy(y, np.where(y > 0, y / mu, 1.0))
                         + sp.xlogy(1 - y, np.where(y < 1, (1 - y) / (1 - mu), 1.0)))
    if family == "poisson":
        return 2 * wt * (sp.xlogy(y, np.where(y > 0, y / mu, 1.0)) - (y - mu))
    if family == "gamma":
        return -2 * wt * (np.log(y / mu) - (y - mu) / mu)
    if family == "inverse_gaussian":
        return wt * (y - mu) ** 2 / (y * mu * mu)
    raise KeyError(family)


def _loglik(family, y, mu, wt, dev):
    n = len(y)
    wt_sum = wt.sum()
    if family == "gaussian":
        return 0.5 * (np.sum(np.log(wt)) - n * (np.log(2 * np.pi * dev / n) + 1))
    if family == "binomial":
        k = wt * y
        return float(np.sum(sp.gammaln(wt + 1) - sp.gammaln(k + 1)
                            - sp.gammaln(wt - k + 1)
                            + sp.xlogy(k, mu) + sp.xlogy(wt - k, 1 - mu)))
    if family == "poisson":
        return float(np.sum(wt * (sp.xlogy(y, mu) - mu - sp.gammaln(y + 1))))
    if family == "gamma":
        disp = dev / wt_sum
        a = 1 / disp
        # -2*sum(wt*dgamma(y, shape=a, scale=mu*disp, log=TRUE)): direct form
        return float(np.sum(wt * ((a - 1) * np.log(y) - a * y / mu
                                  - a * np.log(mu * disp) - sp.gammaln(a))))
    if family == "inverse_gaussian":
        return float(-0.5 * (wt_sum * (np.log(2 * np.pi * dev / wt_sum) + 1)
                             + 3 * np.sum(wt * np.log(y))))
    raise KeyError(family)


def _aic(family, ll, p, quasi=False):
    if quasi:
        return None
    extra = 1 if family in ("gaussian", "gamma", "inverse_gaussian") else 0
    return -2 * ll + 2 * (p + extra)


def r_fit(X, y, family, link, wt=None, offset=None, m=None,
          has_intercept=True, quasi=False):
    """Full R glm() output set from the independent f64 IRLS."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    n = len(y)
    wt = np.ones(n) if wt is None else np.asarray(wt, np.float64)
    if m is not None:
        m = np.asarray(m, np.float64)
        y = y / m
        wt = wt * m
    off = np.zeros(n) if offset is None else np.asarray(offset, np.float64)
    beta, dev, iters, XtWXi = irls_np(X, y, family, link, wt=wt, offset=off,
                                      tol=1e-13, max_iter=200)
    eta = X @ beta + off
    mu = _linkinv(link, eta)
    p = X.shape[1]
    dev = float(np.sum(_dev_resids(family, y, mu, wt)))
    pearson = float(np.sum(wt * (y - mu) ** 2 / _variance(family, mu)))
    df_resid = n - p
    fixed_disp = family in ("binomial", "poisson") and not quasi
    dispersion = 1.0 if fixed_disp else pearson / df_resid
    se = np.sqrt(dispersion * np.diag(XtWXi))
    # null deviance
    if has_intercept and offset is not None and np.any(off != 0):
        b0, _, _, _ = irls_np(np.ones((n, 1)), y, family, link, wt=wt,
                              offset=off, tol=1e-13, max_iter=200)
        mu0 = _linkinv(link, np.ones(n) * b0[0] + off)
    elif has_intercept:
        mu0 = np.full(n, np.sum(wt * y) / np.sum(wt))
    else:
        mu0 = _linkinv(link, off)
    null_dev = float(np.sum(_dev_resids(family, y, mu0, wt)))
    ll = None if quasi else float(_loglik(family, y, mu, wt, dev))
    return dict(
        coefficients=beta.tolist(), std_errors=se.tolist(),
        deviance=dev, null_deviance=null_dev, pearson=pearson,
        dispersion=float(dispersion), loglik=ll,
        aic=_aic(family, ll, p, quasi=quasi) if ll is not None else None,
        df_residual=int(df_resid),
        df_null=int(n - (1 if has_intercept else 0)))


# ---------------------------------------------------------------------------
# cases
# ---------------------------------------------------------------------------

def main():
    cases = {}

    # -- 1. Dobson (1990) poisson — R ?glm example ---------------------------
    counts = [18, 17, 15, 20, 10, 20, 25, 13, 12]
    # outcome = gl(3,1,9), treatment = gl(3,3): treatment-contrast dummies
    o = np.tile([(0, 0), (1, 0), (0, 1)], (3, 1))
    t = np.repeat([(0, 0), (1, 0), (0, 1)], 3, axis=0)
    X = np.column_stack([np.ones(9), o, t])
    cases["dobson_poisson"] = dict(
        data=dict(counts=counts),
        family="poisson", link="log",
        fit=r_fit(X, counts, "poisson", "log"),
        r_doc=dict(  # printed by summary(glm.D93) in ?glm
            coefficients=[3.044522, -0.454255, -0.292987, None, None],
            std_errors=[0.170875, 0.202171, 0.192742, 0.2, 0.2],
            deviance=5.1291, null_deviance=10.5814, aic=56.76132,
            df_residual=4, df_null=8),
        provenance="R ?glm 'Dobson (1990) Page 93: Randomized Controlled Trial'")

    # -- 2. clotting gamma — R ?glm example ---------------------------------
    u = np.array([5, 10, 15, 20, 30, 40, 60, 80, 100], float)
    lot1 = [118, 58, 42, 35, 27, 25, 21, 19, 18]
    lot2 = [69, 35, 26, 21, 18, 16, 13, 12, 9]
    Xc = np.column_stack([np.ones(9), np.log(u)])
    cases["clotting_gamma_lot1"] = dict(
        data=dict(u=u.tolist(), lot1=lot1),
        family="gamma", link="inverse",
        fit=r_fit(Xc, lot1, "gamma", "inverse"),
        r_doc=dict(coefficients=[-0.01655438, 0.01534311],
                   std_errors=[0.00092754, 0.00041496]),
        provenance="R ?glm 'McCullagh & Nelder (1989, pp. 300-2)' summary(glm(lot1 ~ log(u), family = Gamma))")
    cases["clotting_gamma_lot2"] = dict(
        data=dict(u=u.tolist(), lot2=lot2),
        family="gamma", link="inverse",
        fit=r_fit(Xc, lot2, "gamma", "inverse"),
        provenance="R ?glm clotting lot2 (values from oracle64; verify with make_r_golden.R)")

    # -- 3. grouped binomial with m (counts out of group sizes) -------------
    rng = np.random.default_rng(20260729)
    n = 40
    x1 = rng.standard_normal(n)
    m_sz = rng.integers(5, 40, n).astype(float)
    pr = sp.expit(-0.3 + 0.8 * x1)
    succ = rng.binomial(m_sz.astype(int), pr).astype(float)
    Xb = np.column_stack([np.ones(n), x1])
    cases["grouped_binomial_logit"] = dict(
        data=dict(x1=x1.tolist(), m=m_sz.tolist(), successes=succ.tolist()),
        family="binomial", link="logit",
        fit=r_fit(Xb, succ, "binomial", "logit", m=m_sz),
        provenance="synthetic; R: glm(cbind(s, m-s) ~ x1, binomial)")

    # -- 4. poisson with offset ---------------------------------------------
    expo = rng.uniform(0.5, 4.0, n)
    lam = expo * np.exp(0.2 + 0.6 * x1)
    yp = rng.poisson(lam).astype(float)
    cases["poisson_offset"] = dict(
        data=dict(x1=x1.tolist(), exposure=expo.tolist(), y=yp.tolist()),
        family="poisson", link="log",
        fit=r_fit(Xb, yp, "poisson", "log", offset=np.log(expo)),
        provenance="synthetic; R: glm(y ~ x1 + offset(log(exposure)), poisson)")

    # -- 5. quasipoisson (same fit, Pearson dispersion, AIC = NA) -----------
    cases["quasipoisson"] = dict(
        data=dict(x1=x1.tolist(), y=yp.tolist()),
        family="quasipoisson", link="log",
        fit=r_fit(Xb, yp, "poisson", "log", quasi=True),
        provenance="synthetic; R: glm(y ~ x1, quasipoisson)")

    # -- 6. weighted gaussian glm (AIC carries -sum(log wt)) ----------------
    wts = rng.uniform(0.5, 3.0, n)
    yg = 1.5 + 2.0 * x1 + rng.standard_normal(n) / np.sqrt(wts)
    cases["gaussian_weighted"] = dict(
        data=dict(x1=x1.tolist(), w=wts.tolist(), y=yg.tolist()),
        family="gaussian", link="identity",
        fit=r_fit(Xb, yg, "gaussian", "identity", wt=wts),
        provenance="synthetic; R: glm(y ~ x1, gaussian, weights = w)")

    # -- 7. inverse gaussian ------------------------------------------------
    mu_ig = 1.0 / np.sqrt(0.5 + 0.3 * np.abs(x1) + 0.2)
    lam_ig = 5.0
    nu = rng.standard_normal(n) ** 2
    xi = mu_ig + mu_ig ** 2 * nu / (2 * lam_ig) - mu_ig / (2 * lam_ig) * np.sqrt(
        4 * mu_ig * lam_ig * nu + mu_ig ** 2 * nu ** 2)
    zu = rng.uniform(size=n)
    yig = np.where(zu <= mu_ig / (mu_ig + xi), xi, mu_ig ** 2 / xi)
    Xig = np.column_stack([np.ones(n), np.abs(x1)])
    cases["inverse_gaussian"] = dict(
        data=dict(x=np.abs(x1).tolist(), y=yig.tolist()),
        family="inverse_gaussian", link="inverse_squared",
        fit=r_fit(Xig, yig, "inverse_gaussian", "inverse_squared"),
        provenance="synthetic; R: glm(y ~ x, inverse.gaussian)")

    # -- 8. binomial cloglog (bernoulli) ------------------------------------
    n2 = 200
    x2 = rng.standard_normal(n2)
    pr2 = -np.expm1(-np.exp(-0.2 + 0.7 * x2))
    yb = (rng.uniform(size=n2) < pr2).astype(float)
    X2 = np.column_stack([np.ones(n2), x2])
    cases["bernoulli_cloglog"] = dict(
        data=dict(x=x2.tolist(), y=yb.tolist()),
        family="binomial", link="cloglog",
        fit=r_fit(X2, yb, "binomial", "cloglog"),
        provenance="synthetic; R: glm(y ~ x, binomial(cloglog))")

    # -- 9. grouped binomial probit ------------------------------------------
    from scipy.stats import norm as _norm
    m9 = rng.integers(8, 30, n).astype(float)
    pr9 = _norm.cdf(-0.2 + 0.6 * x1)
    s9 = rng.binomial(m9.astype(int), pr9).astype(float)
    cases["grouped_binomial_probit"] = dict(
        data=dict(x1=x1.tolist(), m=m9.tolist(), successes=s9.tolist()),
        family="binomial", link="probit",
        fit=r_fit(Xb, s9, "binomial", "probit", m=m9),
        provenance="synthetic; R: glm(cbind(s, m-s) ~ x1, binomial(probit))")

    # -- 10. no-intercept binomial (null model is mu = linkinv(0)) ----------
    xn = rng.standard_normal(n) + 0.5
    prn = sp.expit(0.8 * xn)
    yn = (rng.uniform(size=n) < prn).astype(float)
    cases["binomial_no_intercept"] = dict(
        data=dict(x=xn.tolist(), y=yn.tolist()),
        family="binomial", link="logit", no_intercept=True,
        fit=r_fit(xn[:, None], yn, "binomial", "logit", has_intercept=False),
        provenance="synthetic; R: glm(y ~ x - 1, binomial)")

    # -- 11. poisson sqrt link ----------------------------------------------
    mu_s = (1.5 + 0.4 * x1) ** 2
    ys = rng.poisson(np.clip(mu_s, 0, 60)).astype(float)
    cases["poisson_sqrt"] = dict(
        data=dict(x1=x1.tolist(), y=ys.tolist()),
        family="poisson", link="sqrt",
        fit=r_fit(Xb, ys, "poisson", "sqrt"),
        provenance="synthetic; R: glm(y ~ x1, poisson(sqrt))")

    # -- 12. weighted gamma log link ----------------------------------------
    wg = rng.uniform(0.5, 3.0, n)
    mu_g = np.exp(0.4 + 0.3 * x1)
    yg2 = rng.gamma(4.0, mu_g / 4.0)
    cases["gamma_log_weighted"] = dict(
        data=dict(x1=x1.tolist(), w=wg.tolist(), y=yg2.tolist()),
        family="gamma", link="log",
        fit=r_fit(Xb, yg2, "gamma", "log", wt=wg),
        provenance="synthetic; R: glm(y ~ x1, Gamma(log), weights = w)")

    out = os.path.join(HERE, "r_golden.json")
    with open(out, "w") as f:
        json.dump(cases, f, indent=1)
    print(f"wrote {out} with {len(cases)} cases")


if __name__ == "__main__":
    main()
