"""Generate tests/fixtures/r_golden.json — R-semantics golden outputs.

Provenance (two tiers, marked per-case in the JSON):
  * ``r_doc``  — numbers printed in R's own documentation (?glm examples:
    the Dobson (1990) randomized-trial poisson fit and the McCullagh &
    Nelder clotting-time Gamma fit).  These are REAL R outputs, committed at
    the precision R prints.  ``tests/fixtures/make_r_golden.R`` re-derives
    every case with R itself (R is not installed in this build image; run
    the script anywhere R is to refresh/verify).
  * ``oracle64`` — float64 IRLS (tests/oracle.py — an implementation
    independent of sparkglm_tpu) extended here with R's exact aggregate
    formulas (stats::family()$aic etc.) for SEs, dispersion, deviances,
    logLik and AIC.

Run:  python tests/fixtures/gen_golden.py   (rewrites r_golden.json)
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np
from scipy import special as sp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
from oracle import irls_np  # noqa: E402  (independent f64 IRLS)

HERE = os.path.dirname(__file__)


# ---------------------------------------------------------------------------
# R-exact aggregate statistics (independent of sparkglm_tpu)
# ---------------------------------------------------------------------------

def _linkinv(link, eta):
    return {
        "identity": lambda e: e,
        "log": np.exp,
        "logit": sp.expit,
        "probit": sp.ndtr,
        "cloglog": lambda e: -np.expm1(-np.exp(e)),
        "inverse": lambda e: 1.0 / e,
        "sqrt": lambda e: e * e,
        "inverse_squared": lambda e: 1.0 / np.sqrt(e),
    }[link](eta)


def _variance(family, mu):
    return {
        "gaussian": lambda m: np.ones_like(m),
        "binomial": lambda m: m * (1 - m),
        "poisson": lambda m: m,
        "gamma": lambda m: m * m,
        "inverse_gaussian": lambda m: m ** 3,
    }[family](mu)


def _dev_resids(family, y, mu, wt):
    if family == "gaussian":
        return wt * (y - mu) ** 2
    if family == "binomial":
        return 2 * wt * (sp.xlogy(y, np.where(y > 0, y / mu, 1.0))
                         + sp.xlogy(1 - y, np.where(y < 1, (1 - y) / (1 - mu), 1.0)))
    if family == "poisson":
        return 2 * wt * (sp.xlogy(y, np.where(y > 0, y / mu, 1.0)) - (y - mu))
    if family == "gamma":
        return -2 * wt * (np.log(y / mu) - (y - mu) / mu)
    if family == "inverse_gaussian":
        return wt * (y - mu) ** 2 / (y * mu * mu)
    raise KeyError(family)


def _loglik(family, y, mu, wt, dev):
    n = len(y)
    wt_sum = wt.sum()
    if family == "gaussian":
        return 0.5 * (np.sum(np.log(wt)) - n * (np.log(2 * np.pi * dev / n) + 1))
    if family == "binomial":
        k = wt * y
        return float(np.sum(sp.gammaln(wt + 1) - sp.gammaln(k + 1)
                            - sp.gammaln(wt - k + 1)
                            + sp.xlogy(k, mu) + sp.xlogy(wt - k, 1 - mu)))
    if family == "poisson":
        return float(np.sum(wt * (sp.xlogy(y, mu) - mu - sp.gammaln(y + 1))))
    if family == "gamma":
        disp = dev / wt_sum
        a = 1 / disp
        # -2*sum(wt*dgamma(y, shape=a, scale=mu*disp, log=TRUE)): direct form
        return float(np.sum(wt * ((a - 1) * np.log(y) - a * y / mu
                                  - a * np.log(mu * disp) - sp.gammaln(a))))
    if family == "inverse_gaussian":
        return float(-0.5 * (wt_sum * (np.log(2 * np.pi * dev / wt_sum) + 1)
                             + 3 * np.sum(wt * np.log(y))))
    raise KeyError(family)


def _aic(family, ll, p, quasi=False):
    if quasi:
        return None
    extra = 1 if family in ("gaussian", "gamma", "inverse_gaussian") else 0
    return -2 * ll + 2 * (p + extra)


def r_fit(X, y, family, link, wt=None, offset=None, m=None,
          has_intercept=True, quasi=False):
    """Full R glm() output set from the independent f64 IRLS."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    n = len(y)
    wt = np.ones(n) if wt is None else np.asarray(wt, np.float64)
    if m is not None:
        m = np.asarray(m, np.float64)
        y = y / m
        wt = wt * m
    off = np.zeros(n) if offset is None else np.asarray(offset, np.float64)
    beta, dev, iters, XtWXi = irls_np(X, y, family, link, wt=wt, offset=off,
                                      tol=1e-13, max_iter=200)
    eta = X @ beta + off
    mu = _linkinv(link, eta)
    p = X.shape[1]
    dev = float(np.sum(_dev_resids(family, y, mu, wt)))
    pearson = float(np.sum(wt * (y - mu) ** 2 / _variance(family, mu)))
    df_resid = n - p
    fixed_disp = family in ("binomial", "poisson") and not quasi
    dispersion = 1.0 if fixed_disp else pearson / df_resid
    se = np.sqrt(dispersion * np.diag(XtWXi))
    # null deviance
    if has_intercept and offset is not None and np.any(off != 0):
        b0, _, _, _ = irls_np(np.ones((n, 1)), y, family, link, wt=wt,
                              offset=off, tol=1e-13, max_iter=200)
        mu0 = _linkinv(link, np.ones(n) * b0[0] + off)
    elif has_intercept:
        mu0 = np.full(n, np.sum(wt * y) / np.sum(wt))
    else:
        mu0 = _linkinv(link, off)
    null_dev = float(np.sum(_dev_resids(family, y, mu0, wt)))
    ll = None if quasi else float(_loglik(family, y, mu, wt, dev))
    return dict(
        coefficients=beta.tolist(), std_errors=se.tolist(),
        deviance=dev, null_deviance=null_dev, pearson=pearson,
        dispersion=float(dispersion), loglik=ll,
        aic=_aic(family, ll, p, quasi=quasi) if ll is not None else None,
        df_residual=int(df_resid),
        df_null=int(n - (1 if has_intercept else 0)))


def _pearson_resid(family, y, mu, wt):
    return (y - mu) * np.sqrt(wt) / np.sqrt(_variance(family, mu))


def r_influence(X, y, family=None, link=None, wt=None, offset=None, m=None,
                quasi=False):
    """R's lm.influence / influence.glm / influence.measures, re-derived
    independently of sparkglm_tpu via the QR route R itself uses
    (stats/R/lm.influence.R, src/library/stats/src/lminfl.f):

      * QR of sqrt(W) X, W the converged IRLS working weights (prior
        weights for gaussian/identity == an LM);
      * e = weighted.residuals: sqrt(w) resid (LM), deviance resid (GLM);
      * hat_i = ||Q_i||^2;  dfbeta = (Q R^-T) * e/(1-h);
      * sigma_(i)^2 = (sum e^2 - e_i^2/(1-h_i)) / (n - p - 1);
      * dfbetas, dffits, covratio, rstudent, rstandard, cooks.distance and
        the influence.measures flag matrix per the R source formulas.
    """
    from scipy.stats import f as fdist

    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    n, p = X.shape
    wt = np.ones(n) if wt is None else np.asarray(wt, np.float64)
    if m is not None:
        m = np.asarray(m, np.float64)
        y = y / m
        wt = wt * m
    off = np.zeros(n) if offset is None else np.asarray(offset, np.float64)
    is_lm = family in (None, "lm")
    if is_lm:
        sw = np.sqrt(wt)
        beta, *_ = np.linalg.lstsq(sw[:, None] * X, sw * (y - off),
                                   rcond=None)
        w_work = wt
        ew = sw * (y - X @ beta - off)
        dispersion = None
    else:
        beta, _, _, _ = irls_np(X, y, family, link, wt=wt, offset=off,
                                tol=1e-13, max_iter=200)
        eta = X @ beta + off
        mu = _linkinv(link, eta)
        gp = {  # d eta / d mu
            "identity": lambda m_: np.ones_like(m_),
            "log": lambda m_: 1.0 / m_,
            "logit": lambda m_: 1.0 / (m_ * (1 - m_)),
            "probit": lambda m_: 1.0 / np.maximum(
                np.exp(-0.5 * sp.ndtri(m_) ** 2) / np.sqrt(2 * np.pi), 1e-300),
            "cloglog": lambda m_: 1.0 / np.maximum(-(1 - m_) * np.log(1 - m_),
                                                   1e-300),
            "inverse": lambda m_: -1.0 / m_ ** 2,
            "sqrt": lambda m_: 0.5 / np.sqrt(m_),
            "inverse_squared": lambda m_: -2.0 / m_ ** 3,
        }[link](mu)
        w_work = wt / (_variance(family, mu) * gp * gp)
        dev_i = _dev_resids(family, y, mu, wt)
        ew = np.sign(y - mu) * np.sqrt(np.maximum(dev_i, 0.0))
        pear = _pearson_resid(family, y, mu, wt)
        fixed_disp = family in ("binomial", "poisson") and not quasi
        dispersion = (1.0 if fixed_disp
                      else float(np.sum(pear ** 2) / (n - p)))
    # R: e[abs(e) < 100 eps median|e|] <- 0 before the downdate
    med = float(np.median(np.abs(ew)))
    ew = np.where(np.abs(ew) < 100 * np.finfo(float).eps * med, 0.0, ew)
    Q, R = np.linalg.qr(np.sqrt(w_work)[:, None] * X)
    h = np.sum(Q * Q, axis=1)
    om = 1.0 - h
    Rinv = np.linalg.inv(R)
    xxi = Rinv @ Rinv.T            # chol2inv(qr): (X'WX)^-1
    dfbeta = (Q @ Rinv.T) * (ew / om)[:, None]
    df_resid = n - p
    rss = float(np.sum(ew * ew))
    s2_i = (rss - ew * ew / om) / (df_resid - 1)
    sigma_i = np.sqrt(np.where(s2_i > 0, s2_i, np.nan))
    s = np.sqrt(rss / df_resid)
    dfbetas = dfbeta / np.outer(sigma_i, np.sqrt(np.diag(xxi)))
    dffits_v = ew * np.sqrt(h) / (sigma_i * om)
    cov_r = (sigma_i / s) ** (2 * p) / om
    if is_lm:
        rstud = ew / (sigma_i * np.sqrt(om))
        rstand = ew / (s * np.sqrt(om))
        cooks = (ew / (s * om)) ** 2 * h / p
    else:
        rstud = np.sign(ew) * np.sqrt(ew ** 2 + h * pear ** 2 / om)
        if not (family in ("binomial", "poisson") and not quasi):
            rstud = rstud / sigma_i
        rstand = ew / np.sqrt(dispersion * om)
        cooks = (pear / om) ** 2 * h / (dispersion * p)
    infmat = np.column_stack([dfbetas, dffits_v, cov_r, cooks, h])
    infmat[np.isinf(infmat)] = np.nan
    n_used, k = int(np.sum(h > 0)), p
    is_inf = np.column_stack([
        np.abs(dfbetas) > 1.0,
        np.abs(dffits_v) > 3.0 * np.sqrt(k / (n_used - k)),
        np.abs(1.0 - cov_r) > 3.0 * k / (n_used - k),
        fdist.cdf(cooks, k, n_used - k) > 0.5,
        h > 3.0 * k / n_used,
    ])
    out = dict(hat=h.tolist(), sigma=sigma_i.tolist(),
               dfbeta=dfbeta.tolist(), dfbetas=dfbetas.tolist(),
               dffits=dffits_v.tolist(), covratio=cov_r.tolist(),
               rstudent=rstud.tolist(), rstandard=rstand.tolist(),
               cooks_distance=cooks.tolist(),
               is_inf=is_inf.astype(int).tolist())
    if dispersion is not None:
        out["dispersion"] = dispersion
    return out


# ---------------------------------------------------------------------------
# cases
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# glmnet-semantics elastic-net path oracle (independent of sparkglm_tpu)
# ---------------------------------------------------------------------------

_DLINK = {  # d eta / d mu
    "identity": lambda m: np.ones_like(m),
    "logit": lambda m: 1.0 / (m * (1 - m)),
    "log": lambda m: 1.0 / m,
}


def _enet_cd(A, b, beta, lam, alpha, pf, tol=1e-14, sweeps=100000):
    """Cyclic coordinate descent for
    min 0.5 b'Ab - b'b_vec + lam sum_j pf_j (alpha |b_j| + (1-alpha)/2 b_j^2)
    — the glmnet covariance-update form on an (averaged) Gramian."""
    diag = np.diag(A).copy()
    p = len(b)
    for _ in range(sweeps):
        dmax = 0.0
        for j in range(p):
            g = b[j] - A[j] @ beta + diag[j] * beta[j]
            t = lam * alpha * pf[j]
            bj = (np.sign(g) * max(abs(g) - t, 0.0)
                  / max(diag[j] + lam * (1.0 - alpha) * pf[j], 1e-300))
            dmax = max(dmax, diag[j] * (bj - beta[j]) ** 2)
            beta[j] = bj
        if dmax < tol:
            break
    return beta


def glmnet_path(X, y, family, link, alpha, lambdas, wt=None,
                standardize=True):
    """Elastic-net lambda path with glmnet's exact semantics, derived
    independently of sparkglm_tpu:

      * prior weights normalized to sum 1 (every Gramian is an observation
        average — glmnet's internal ``w = w/sum(w)``);
      * objective  sum_i (w_i/sum w) nll_i
                   + lam sum_j pf_j (alpha |b_j| + (1-alpha)/2 b_j^2);
      * ``standardize=TRUE``: columns scaled by the weighted sd about the
        weighted mean (1/n denominator) WITHOUT centering — the unpenalized
        intercept absorbs centering exactly; coefficients are reported on
        the ORIGINAL x scale;
      * the intercept (column 0 in every fixture) is never penalized.

    Full cyclic CD (no screening) + IRLS to tight tolerance per lambda,
    warm-started along the descending grid.  Returns
    (coefs (n_lambda, p), deviances, null_deviance) with deviance on the
    RAW prior weights — R/glmnet's ``dev.ratio`` denominator scale."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    n, p = X.shape
    wt = np.ones(n) if wt is None else np.asarray(wt, np.float64)
    wp = wt / wt.sum()
    pf = np.ones(p)
    pf[0] = 0.0
    xm = wp @ X
    x2 = wp @ (X * X)
    if standardize:
        sdv = np.sqrt(np.maximum(x2 - xm ** 2, 0.0))
        sd = np.where((pf > 0) & (sdv > 1e-10), sdv, 1.0)
    else:
        sd = np.ones(p)
    Xs = X / sd

    # null model: intercept-only IRLS (the warm start for the first lambda)
    mubar = float(wp @ y)
    if family == "binomial":
        mubar = min(max(mubar, 1e-10), 1 - 1e-10)
    elif family == "poisson":
        mubar = max(mubar, 1e-10)
    b0 = {"identity": lambda m: m, "logit": sp.logit,
          "log": np.log}[link](mubar)
    for _ in range(200):
        eta0 = np.full(n, b0)
        mu0 = _linkinv(link, eta0)
        gp = _DLINK[link](mu0)
        w0 = wp / (_variance(family, mu0) * gp * gp)
        z0 = eta0 + (y - mu0) * gp
        b0_new = float(np.sum(w0 * z0) / np.sum(w0))
        if abs(b0_new - b0) < 1e-14:
            b0 = b0_new
            break
        b0 = b0_new
    null_dev = float(np.sum(_dev_resids(family, y, _linkinv(
        link, np.full(n, b0)), wt)))

    beta = np.zeros(p)
    beta[0] = b0           # sd[0] == 1 (unpenalized), so scales coincide
    coefs, devs = [], []
    for lam in lambdas:
        for _ in range(200):
            eta = Xs @ beta
            mu = _linkinv(link, eta)
            gp = _DLINK[link](mu)
            w = wp / (_variance(family, mu) * gp * gp)
            z = eta + (y - mu) * gp
            A = (Xs * w[:, None]).T @ Xs
            bvec = Xs.T @ (w * z)
            prev = beta.copy()
            beta = _enet_cd(A, bvec, beta.copy(), float(lam), alpha, pf)
            if np.max(np.diag(A) * (beta - prev) ** 2) < 1e-14:
                break
        mu = _linkinv(link, Xs @ beta)
        devs.append(float(np.sum(_dev_resids(family, y, mu, wt))))
        coefs.append((beta / sd).tolist())
    return coefs, devs, null_dev


def main():
    cases = {}

    # -- 1. Dobson (1990) poisson — R ?glm example ---------------------------
    counts = [18, 17, 15, 20, 10, 20, 25, 13, 12]
    # outcome = gl(3,1,9), treatment = gl(3,3): treatment-contrast dummies
    o = np.tile([(0, 0), (1, 0), (0, 1)], (3, 1))
    t = np.repeat([(0, 0), (1, 0), (0, 1)], 3, axis=0)
    X = np.column_stack([np.ones(9), o, t])
    dobson_fit = r_fit(X, counts, "poisson", "log")
    dobson_r_doc = dict(  # printed by summary(glm.D93) in ?glm; shared by
        # the matrix-tier case and the formula-tier dobson_factors case
        coefficients=[3.044522, -0.454255, -0.292987, None, None],
        std_errors=[0.170875, 0.202171, 0.192742, 0.2, 0.2],
        deviance=5.1291, null_deviance=10.5814, aic=56.76132,
        df_residual=4, df_null=8)
    cases["dobson_poisson"] = dict(
        data=dict(counts=counts),
        family="poisson", link="log",
        fit=dobson_fit,
        r_doc=dobson_r_doc,
        influence=r_influence(X, counts, "poisson", "log"),
        provenance="R ?glm 'Dobson (1990) Page 93: Randomized Controlled Trial'")

    # -- 2. clotting gamma — R ?glm example ---------------------------------
    u = np.array([5, 10, 15, 20, 30, 40, 60, 80, 100], float)
    lot1 = [118, 58, 42, 35, 27, 25, 21, 19, 18]
    lot2 = [69, 35, 26, 21, 18, 16, 13, 12, 9]
    Xc = np.column_stack([np.ones(9), np.log(u)])
    clotting_fit = r_fit(Xc, lot1, "gamma", "inverse")
    clotting_r_doc = dict(coefficients=[-0.01655438, 0.01534311],
                          std_errors=[0.00092754, 0.00041496])
    cases["clotting_gamma_lot1"] = dict(
        data=dict(u=u.tolist(), lot1=lot1),
        family="gamma", link="inverse",
        fit=clotting_fit,
        r_doc=clotting_r_doc,
        influence=r_influence(Xc, lot1, "gamma", "inverse"),
        provenance="R ?glm 'McCullagh & Nelder (1989, pp. 300-2)' summary(glm(lot1 ~ log(u), family = Gamma))")
    cases["clotting_gamma_lot2"] = dict(
        data=dict(u=u.tolist(), lot2=lot2),
        family="gamma", link="inverse",
        fit=r_fit(Xc, lot2, "gamma", "inverse"),
        provenance="R ?glm clotting lot2 (values from oracle64; verify with make_r_golden.R)")

    # -- 3. grouped binomial with m (counts out of group sizes) -------------
    rng = np.random.default_rng(20260729)
    n = 40
    x1 = rng.standard_normal(n)
    m_sz = rng.integers(5, 40, n).astype(float)
    pr = sp.expit(-0.3 + 0.8 * x1)
    succ = rng.binomial(m_sz.astype(int), pr).astype(float)
    Xb = np.column_stack([np.ones(n), x1])
    cases["grouped_binomial_logit"] = dict(
        data=dict(x1=x1.tolist(), m=m_sz.tolist(), successes=succ.tolist()),
        family="binomial", link="logit",
        fit=r_fit(Xb, succ, "binomial", "logit", m=m_sz),
        influence=r_influence(Xb, succ, "binomial", "logit", m=m_sz),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(cbind(s, m-s) ~ x1, binomial)")

    # -- 4. poisson with offset ---------------------------------------------
    expo = rng.uniform(0.5, 4.0, n)
    lam = expo * np.exp(0.2 + 0.6 * x1)
    yp = rng.poisson(lam).astype(float)
    cases["poisson_offset"] = dict(
        data=dict(x1=x1.tolist(), exposure=expo.tolist(), y=yp.tolist()),
        family="poisson", link="log",
        fit=r_fit(Xb, yp, "poisson", "log", offset=np.log(expo)),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x1 + offset(log(exposure)), poisson)")

    # -- 5. quasipoisson (same fit, Pearson dispersion, AIC = NA) -----------
    cases["quasipoisson"] = dict(
        data=dict(x1=x1.tolist(), y=yp.tolist()),
        family="quasipoisson", link="log",
        fit=r_fit(Xb, yp, "poisson", "log", quasi=True),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x1, quasipoisson)")

    # -- 6. weighted gaussian glm (AIC carries -sum(log wt)) ----------------
    wts = rng.uniform(0.5, 3.0, n)
    yg = 1.5 + 2.0 * x1 + rng.standard_normal(n) / np.sqrt(wts)
    cases["gaussian_weighted"] = dict(
        data=dict(x1=x1.tolist(), w=wts.tolist(), y=yg.tolist()),
        family="gaussian", link="identity",
        fit=r_fit(Xb, yg, "gaussian", "identity", wt=wts),
        influence=r_influence(Xb, yg, "gaussian", "identity", wt=wts),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x1, gaussian, weights = w)")

    # -- 7. inverse gaussian ------------------------------------------------
    mu_ig = 1.0 / np.sqrt(0.5 + 0.3 * np.abs(x1) + 0.2)
    lam_ig = 5.0
    nu = rng.standard_normal(n) ** 2
    xi = mu_ig + mu_ig ** 2 * nu / (2 * lam_ig) - mu_ig / (2 * lam_ig) * np.sqrt(
        4 * mu_ig * lam_ig * nu + mu_ig ** 2 * nu ** 2)
    zu = rng.uniform(size=n)
    yig = np.where(zu <= mu_ig / (mu_ig + xi), xi, mu_ig ** 2 / xi)
    Xig = np.column_stack([np.ones(n), np.abs(x1)])
    cases["inverse_gaussian"] = dict(
        data=dict(x=np.abs(x1).tolist(), y=yig.tolist()),
        family="inverse_gaussian", link="inverse_squared",
        fit=r_fit(Xig, yig, "inverse_gaussian", "inverse_squared"),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x, inverse.gaussian)")

    # -- 8. binomial cloglog (bernoulli) ------------------------------------
    n2 = 200
    x2 = rng.standard_normal(n2)
    pr2 = -np.expm1(-np.exp(-0.2 + 0.7 * x2))
    yb = (rng.uniform(size=n2) < pr2).astype(float)
    X2 = np.column_stack([np.ones(n2), x2])
    cases["bernoulli_cloglog"] = dict(
        data=dict(x=x2.tolist(), y=yb.tolist()),
        family="binomial", link="cloglog",
        fit=r_fit(X2, yb, "binomial", "cloglog"),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x, binomial(cloglog))")

    # -- 9. grouped binomial probit ------------------------------------------
    from scipy.stats import norm as _norm
    m9 = rng.integers(8, 30, n).astype(float)
    pr9 = _norm.cdf(-0.2 + 0.6 * x1)
    s9 = rng.binomial(m9.astype(int), pr9).astype(float)
    cases["grouped_binomial_probit"] = dict(
        data=dict(x1=x1.tolist(), m=m9.tolist(), successes=s9.tolist()),
        family="binomial", link="probit",
        fit=r_fit(Xb, s9, "binomial", "probit", m=m9),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(cbind(s, m-s) ~ x1, binomial(probit))")

    # -- 10. no-intercept binomial (null model is mu = linkinv(0)) ----------
    xn = rng.standard_normal(n) + 0.5
    prn = sp.expit(0.8 * xn)
    yn = (rng.uniform(size=n) < prn).astype(float)
    cases["binomial_no_intercept"] = dict(
        data=dict(x=xn.tolist(), y=yn.tolist()),
        family="binomial", link="logit", no_intercept=True,
        fit=r_fit(xn[:, None], yn, "binomial", "logit", has_intercept=False),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x - 1, binomial)")

    # -- 11. poisson sqrt link ----------------------------------------------
    mu_s = (1.5 + 0.4 * x1) ** 2
    ys = rng.poisson(np.clip(mu_s, 0, 60)).astype(float)
    cases["poisson_sqrt"] = dict(
        data=dict(x1=x1.tolist(), y=ys.tolist()),
        family="poisson", link="sqrt",
        fit=r_fit(Xb, ys, "poisson", "sqrt"),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x1, poisson(sqrt))")

    # -- 12. weighted gamma log link ----------------------------------------
    wg = rng.uniform(0.5, 3.0, n)
    mu_g = np.exp(0.4 + 0.3 * x1)
    yg2 = rng.gamma(4.0, mu_g / 4.0)
    cases["gamma_log_weighted"] = dict(
        data=dict(x1=x1.tolist(), w=wg.tolist(), y=yg2.tolist()),
        family="gamma", link="log",
        fit=r_fit(Xb, yg2, "gamma", "log", wt=wg),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x1, Gamma(log), weights = w)")

    # ------------------------------------------------------------------
    # FORMULA-driven cases (VERDICT r2 weak #5): golden fits that go
    # through data/formula.py -> model_matrix.py -> fit end-to-end —
    # factors, interactions, transforms, weights+offset, cbind.  Each case
    # stores raw COLUMNS + formula + the design the formula must build
    # (xnames asserted) + full fit values; r_doc/summary_contains carry
    # numbers R itself prints where documentation provides them.
    # make_r_golden.R re-derives every case with real R formulas.
    # ------------------------------------------------------------------
    fcases = {}

    # F1: Dobson poisson THROUGH factors (the exact ?glm example: outcome
    # and treatment are gl() factors in R's own code)
    outcome = [str(1 + i % 3) for i in range(9)]
    treatment = [str(1 + i // 3) for i in range(9)]
    fcases["dobson_factors"] = dict(
        data=dict(counts=[float(c) for c in counts], outcome=outcome,
                  treatment=treatment),
        formula="counts ~ outcome + treatment",
        family="poisson", link="log",
        xnames=["intercept", "outcome_2", "outcome_3",
                "treatment_2", "treatment_3"],
        fit=dobson_fit,
        r_doc=dobson_r_doc,
        summary_contains=["3.045", "0.1709", "-0.4543", "0.2022", "-2.247",
                          "0.02465", "-0.2930", "10.58", "5.129", "56.76"],
        provenance="R ?glm Dobson: glm(counts ~ outcome + treatment, poisson)")

    # F2: clotting Gamma with the log(u) TRANSFORM in the formula (R's own
    # code is glm(lot1 ~ log(u), Gamma))
    fcases["clotting_log_transform"] = dict(
        data=dict(u=u.tolist(), lot1=[float(v) for v in lot1]),
        formula="lot1 ~ log(u)",
        family="gamma", link="inverse",
        xnames=["intercept", "log(u)"],
        fit=clotting_fit,
        r_doc=clotting_r_doc,
        summary_contains=["-0.01655", "0.01534"],
        provenance="R ?glm clotting: glm(lot1 ~ log(u), Gamma)")

    # F3: R's ?lm example (lm.D9): weight ~ group with a Ctl/Trt factor —
    # the printed summary is in R's own documentation
    ctl = [4.17, 5.58, 5.18, 6.11, 4.50, 4.61, 5.17, 4.53, 5.33, 5.14]
    trt = [4.81, 4.17, 4.41, 3.59, 5.87, 3.83, 6.03, 4.89, 4.32, 4.69]
    w9 = np.array(ctl + trt)
    g9 = np.array([0.0] * 10 + [1.0] * 10)
    X9 = np.column_stack([np.ones(20), g9])
    b9, *_ = np.linalg.lstsq(X9, w9, rcond=None)
    r9 = w9 - X9 @ b9
    sig9 = float(np.sqrt(r9 @ r9 / 18))
    fcases["lm_D9_factor"] = dict(
        data=dict(weight=w9.tolist(),
                  group=["Ctl"] * 10 + ["Trt"] * 10),
        formula="weight ~ group", model="lm",
        xnames=["intercept", "group_Trt"],
        fit=dict(coefficients=b9.tolist(),
                 sse=float(r9 @ r9), sigma=sig9,
                 r_squared=float(1 - (r9 @ r9)
                                 / np.sum((w9 - w9.mean()) ** 2)),
                 df_resid=18),
        r_doc=dict(coefficients=[5.032, -0.371], sigma=0.6964,
                   r_squared=0.07308, adj_r_squared=0.02158,
                   f_statistic=1.419),
        influence=r_influence(X9, w9, "lm"),
        summary_contains=["5.032", "0.2202", "22.85", "-0.3710", "0.3114",
                          "-1.191", "0.6964", "0.07308", "0.02158", "1.419"],
        provenance="R ?lm 'Annette Dobson ... Plant Weight Data' lm.D9")

    # F4: interaction x * g (numeric x factor) — oracle64 values
    n4 = 120
    x4 = rng.standard_normal(n4)
    g4 = np.where(rng.random(n4) < 0.5, "a", "b")
    gb = (g4 == "b").astype(float)
    mu4 = np.exp(0.3 + 0.5 * x4 - 0.4 * gb + 0.6 * x4 * gb)
    y4 = rng.poisson(np.clip(mu4, 0, 50)).astype(float)
    X4 = np.column_stack([np.ones(n4), x4, gb, x4 * gb])
    fcases["interaction_poisson"] = dict(
        data=dict(y=y4.tolist(), x=x4.tolist(), g=g4.tolist()),
        formula="y ~ x * g",
        family="poisson", link="log",
        xnames=["intercept", "x", "g_b", "x:g_b"],
        fit=r_fit(X4, y4, "poisson", "log"),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x * g, poisson)")

    # F5: weights + offset() by name through the formula — oracle64 values
    n5 = 150
    x5 = rng.standard_normal(n5)
    w5 = rng.uniform(0.5, 2.5, n5)
    e5 = rng.uniform(0.5, 3.0, n5)
    mu5 = np.exp(0.4 + 0.5 * x5) * e5
    y5 = rng.gamma(3.0, mu5 / 3.0)
    X5 = np.column_stack([np.ones(n5), x5])
    fcases["gamma_weights_offset"] = dict(
        data=dict(y=y5.tolist(), x=x5.tolist(), w=w5.tolist(),
                  log_e=np.log(e5).tolist()),
        formula="y ~ x + offset(log_e)",
        family="gamma", link="log", weights="w",
        xnames=["intercept", "x"],
        fit=r_fit(X5, y5, "gamma", "log", wt=w5, offset=np.log(e5)),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ x + offset(log_e), Gamma(log), "
                   "weights = w)")

    # F6: cbind(successes, failures) response — oracle64 values
    n6 = 60
    x6a = rng.standard_normal(n6)
    x6b = rng.standard_normal(n6)
    m6 = rng.integers(4, 30, n6).astype(float)
    pr6 = sp.expit(-0.2 + 0.7 * x6a - 0.4 * x6b)
    s6 = rng.binomial(m6.astype(int), pr6).astype(float)
    X6 = np.column_stack([np.ones(n6), x6a, x6b])
    fcases["cbind_binomial"] = dict(
        data=dict(s=s6.tolist(), f=(m6 - s6).tolist(), x1=x6a.tolist(),
                  x2=x6b.tolist()),
        formula="cbind(s, f) ~ x1 + x2",
        family="binomial", link="logit",
        xnames=["intercept", "x1", "x2"],
        fit=r_fit(X6, s6, "binomial", "logit", m=m6),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(cbind(s, f) ~ x1 + x2, binomial)")

    # F7: transform + power term — oracle64 values
    n7 = 100
    u7 = rng.uniform(1.0, 8.0, n7)
    y7 = 2.0 + 1.5 * np.log(u7) - 0.05 * u7 ** 2 + 0.3 * rng.standard_normal(n7)
    X7 = np.column_stack([np.ones(n7), np.log(u7), u7 ** 2])
    fcases["gaussian_transforms"] = dict(
        data=dict(y=y7.tolist(), u=u7.tolist()),
        formula="y ~ log(u) + I(u^2)",
        family="gaussian", link="identity",
        xnames=["intercept", "log(u)", "I(u^2)"],
        fit=r_fit(X7, y7, "gaussian", "identity"),
        provenance="synthetic; oracle64-verified (not run through R); R cross-check: glm(y ~ log(u) + I(u^2), gaussian)")

    # F8: categorical-heavy — a 48-level factor crosses WIDE_FACTOR_LEVELS,
    # so design="auto" fits this case through the STRUCTURED (segment-sum)
    # Gramian engine while the oracle stays dense one-hot f64: the golden
    # assertion pins the structured path to the independent oracle.
    n8 = 2400
    lv8 = 48
    x8 = rng.standard_normal(n8)
    f8 = rng.integers(0, lv8, n8)
    f8[:lv8] = np.arange(lv8)  # every level appears: deterministic coding
    eff8 = rng.standard_normal(lv8) * 0.5
    mu8 = np.exp(0.2 + 0.3 * x8 + eff8[f8])
    y8 = rng.poisson(np.clip(mu8, 0, 60)).astype(float)
    onehot8 = (f8[:, None] == np.arange(1, lv8)[None, :]).astype(float)
    X8 = np.column_stack([np.ones(n8), x8, onehot8])
    fcases["wide_factor_poisson"] = dict(
        data=dict(y=y8.tolist(), x=x8.tolist(),
                  f=[f"L{i:02d}" for i in f8]),
        formula="y ~ x + f",
        family="poisson", link="log",
        xnames=["intercept", "x"] + [f"f_L{i:02d}" for i in range(1, lv8)],
        fit=r_fit(X8, y8, "poisson", "log"),
        provenance="synthetic; oracle64-verified (not run through R); "
                   "48-level factor exercises the structured Gramian auto "
                   "path; R cross-check: glm(y ~ x + f, poisson)")

    cases["formula_cases"] = fcases
    cases["penalized_cases"] = penalized_cases()
    cases["sparse_cases"] = sparse_cases()
    cases["robust_cases"] = robust_cases()

    out = os.path.join(HERE, "r_golden.json")
    with open(out, "w") as f:
        json.dump(cases, f, indent=1)
    print(f"wrote {out} with {len(cases) - 2} cases + "
          f"{len(fcases)} formula cases + "
          f"{len(cases['penalized_cases'])} penalized cases")


def penalized_cases():
    """Elastic-net golden paths (glmnet semantics).  A fresh seeded stream,
    callable standalone: when only this section changes, splice it into the
    committed r_golden.json rather than regenerating the whole file — float
    last-ulp noise across BLAS builds would churn the byte-identical
    legacy cases (``python gen_golden.py --splice-penalized``)."""
    prng = np.random.default_rng(20260805)
    pcases = {}

    def _pen_case(name, family, link, X, y, data, formula, xnames,
                  lambdas, wt=None, weights_col=None, r_family=None):
        fits = {}
        for alpha in (1.0, 0.5, 0.0):
            coefs, devs, nulldev = glmnet_path(X, y, family, link, alpha,
                                               lambdas, wt=wt)
            fits[f"alpha_{alpha:g}"] = dict(
                alpha=alpha, coefficients=coefs, deviance=devs,
                null_deviance=nulldev)
        pcases[name] = dict(
            data=data, formula=formula, family=family, link=link,
            xnames=xnames, lambdas=list(lambdas), standardize=True,
            weights=weights_col, fits=fits,
            provenance="synthetic; oracle64 elastic-net CD+IRLS (glmnet "
                       "semantics: sum-1 weight normalization, weighted-sd "
                       "standardization without centering, coefficients on "
                       "the original scale, unpenalized intercept); R "
                       f"cross-check: glmnet(x, y, family='{r_family or family}'"
                       ", alpha=a, lambda=c(...), standardize=TRUE, "
                       "thresh=1e-14) for a in c(1, 0.5, 0)")

    # P1: gaussian/identity with non-uniform weights (exercises the sum-1
    # weight normalization and the Gramian-level gaussian path kernel)
    np1 = 150
    Xp1 = prng.standard_normal((np1, 4))
    wp1 = prng.uniform(0.5, 2.0, np1)
    yp1 = (0.5 + 1.2 * Xp1[:, 0] - 0.8 * Xp1[:, 1] + 0.3 * Xp1[:, 2]
           + 0.4 * prng.standard_normal(np1))
    _pen_case(
        "gaussian_enet", "gaussian", "identity",
        np.column_stack([np.ones(np1), Xp1]), yp1,
        data=dict(y=yp1.tolist(), x1=Xp1[:, 0].tolist(),
                  x2=Xp1[:, 1].tolist(), x3=Xp1[:, 2].tolist(),
                  x4=Xp1[:, 3].tolist(), w=wp1.tolist()),
        formula="y ~ x1 + x2 + x3 + x4",
        xnames=["intercept", "x1", "x2", "x3", "x4"],
        lambdas=[0.5, 0.2, 0.05, 0.01, 0.002], wt=wp1, weights_col="w")

    # P2: binomial/logit
    np2 = 200
    Xp2 = prng.standard_normal((np2, 4))
    pr2 = sp.expit(-0.3 + 1.0 * Xp2[:, 0] - 0.7 * Xp2[:, 1])
    yp2 = prng.binomial(1, pr2).astype(float)
    _pen_case(
        "binomial_enet", "binomial", "logit",
        np.column_stack([np.ones(np2), Xp2]), yp2,
        data=dict(y=yp2.tolist(), x1=Xp2[:, 0].tolist(),
                  x2=Xp2[:, 1].tolist(), x3=Xp2[:, 2].tolist(),
                  x4=Xp2[:, 3].tolist()),
        formula="y ~ x1 + x2 + x3 + x4",
        xnames=["intercept", "x1", "x2", "x3", "x4"],
        lambdas=[0.1, 0.05, 0.02, 0.008, 0.002])

    # P3: poisson/log
    np3 = 180
    Xp3 = prng.standard_normal((np3, 4))
    mu3 = np.exp(0.3 + 0.5 * Xp3[:, 0] - 0.4 * Xp3[:, 1])
    yp3 = prng.poisson(np.clip(mu3, 0, 40)).astype(float)
    _pen_case(
        "poisson_enet", "poisson", "log",
        np.column_stack([np.ones(np3), Xp3]), yp3,
        data=dict(y=yp3.tolist(), x1=Xp3[:, 0].tolist(),
                  x2=Xp3[:, 1].tolist(), x3=Xp3[:, 2].tolist(),
                  x4=Xp3[:, 3].tolist()),
        formula="y ~ x1 + x2 + x3 + x4",
        xnames=["intercept", "x1", "x2", "x3", "x4"],
        lambdas=[0.3, 0.1, 0.04, 0.01, 0.003])

    return pcases


def sparse_cases():
    """Wide-sparse golden fixture for the sketched-IRLS engine (PARITY r13).
    A fresh seeded stream like :func:`penalized_cases`, spliceable
    standalone (``python gen_golden.py --splice-sparse``).

    The design is the ultra-wide shape the sketch engine targets, scaled
    to fixture size: a 2-column dense block ([1, x]) plus an 80-column
    sparse block with ~5 nonzeros per row (hashed-feature shape), stored
    as COO triplets so the test rebuilds the exact SparseDesign.  The
    oracle densifies and runs the independent f64 IRLS — the sketch
    engine's coefficients must land within the PARITY-documented 1e-4
    maxdiff of it, and the exact sparse (einsum) engine within solver
    precision."""
    prng = np.random.default_rng(20260806)
    n, n_sp = 1200, 80
    x = prng.standard_normal(n)
    # every sparse column appears in a deterministic anchor row (full
    # column rank, so the sketch engine's singular="error" contract holds)
    rows, cols = [np.arange(n_sp)], [np.arange(n_sp)]
    nnz = prng.integers(3, 7, n)
    for i in range(n):
        c = prng.choice(n_sp, size=int(nnz[i]), replace=False)
        rows.append(np.full(c.shape, i))
        cols.append(c)
    row = np.concatenate(rows)
    col = np.concatenate(cols)
    val = prng.uniform(0.5, 1.5, row.shape[0])
    eff = prng.standard_normal(n_sp) * 0.15
    Xd = np.column_stack([np.ones(n), x])
    Xs = np.zeros((n, n_sp))
    np.add.at(Xs, (row, col), val)  # duplicates accumulate (COO contract)
    X = np.column_stack([Xd, Xs])
    mu = np.exp(0.4 + 0.25 * x + Xs @ eff)
    y = prng.poisson(np.clip(mu, 0, 80)).astype(float)
    return {
        "wide_sparse_poisson": dict(
            data=dict(y=y.tolist(), x=x.tolist(),
                      coo_row=row.tolist(), coo_col=col.tolist(),
                      coo_val=val.tolist()),
            n=n, n_sparse=n_sp, family="poisson", link="log",
            xnames=["intercept", "x"] + [f"s{j:02d}" for j in range(n_sp)],
            fit=r_fit(X, y, "poisson", "log"),
            provenance="synthetic; oracle64-verified (not run through R); "
                       "dense [1, x] + 80-col ~5nnz/row sparse block, COO-"
                       "stored; the sketch-engine parity fixture (PARITY "
                       "r13); R cross-check: glm(y ~ x + S, poisson) with "
                       "S the densified sparse block")}


# ---------------------------------------------------------------------------
# robust/quantile oracle (independent of sparkglm_tpu)
# ---------------------------------------------------------------------------

def _quantile_lp(X, y, tau):
    """EXACT quantile regression via the primal LP (scipy HiGHS):

        min  tau 1'u + (1-tau) 1'v   s.t.  X b + u - v = y,  u, v >= 0

    — a genuinely independent oracle: no IRLS, no smoothing, no shared
    code with the epsilon-smoothed pseudo-family under test.  Returns
    ``(beta, objective)`` with the objective the exact check loss
    ``sum rho_tau(y - X beta)``."""
    from scipy.optimize import linprog

    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    n, p = X.shape
    # variables: [b (free, split b+ - b-), u, v]
    c = np.concatenate([np.zeros(2 * p), np.full(n, tau),
                        np.full(n, 1.0 - tau)])
    A_eq = np.hstack([X, -X, np.eye(n), -np.eye(n)])
    res = linprog(c, A_eq=A_eq, b_eq=y, bounds=[(0, None)] * (2 * p + 2 * n),
                  method="highs")
    if not res.success:  # pragma: no cover - fixture generation guard
        raise RuntimeError(f"quantile LP failed: {res.message}")
    beta = res.x[:p] - res.x[p:2 * p]
    r = y - X @ beta
    obj = float(np.sum(np.where(r >= 0, tau * r, (tau - 1.0) * r)))
    return beta, obj


def _huber_irls(X, y, k, tol=1e-13, max_iter=500):
    """Huber M-estimate at an ABSOLUTE threshold ``k`` (response units):
    exact-weight IRLS ``w = min(1, k/|r|)`` on host f64 — independent of
    the library's epsilon-smoothed rule, and convex, so both must land on
    the same optimum.

    NOTE this is NOT MASS::rlm's default: rlm rescales ``k`` by a robust
    scale estimate (MAD/Huber proposal 2) re-estimated every iteration,
    so its tuning constant is in sigma units.  The library's ``huber(k)``
    pseudo-family deliberately takes ``k`` in RESPONSE units (no scale
    estimation inside the compiled loop) — to reproduce an rlm fit, pass
    ``k = 1.345 * sigma_hat`` yourself (PARITY.md)."""
    X = np.asarray(X, np.float64)
    y = np.asarray(y, np.float64)
    beta = np.linalg.lstsq(X, y, rcond=None)[0]
    for _ in range(max_iter):
        r = y - X @ beta
        a = np.abs(r)
        w = np.where(a <= k, 1.0, k / np.maximum(a, 1e-300))
        Xw = X * w[:, None]
        new = np.linalg.solve(Xw.T @ X, Xw.T @ y)
        if np.max(np.abs(new - beta)) <= tol * (1.0 + np.max(np.abs(beta))):
            beta = new
            break
        beta = new
    r = y - X @ beta
    a = np.abs(r)
    obj = float(np.sum(np.where(a <= k, 0.5 * r * r, k * a - 0.5 * k * k)))
    return beta, obj


def robust_cases():
    """Quantile/Huber golden fits (host-f64, implementation-independent).
    A fresh seeded stream like :func:`penalized_cases`, spliceable
    standalone (``python gen_golden.py --splice-robust``) so the existing
    cases stay byte-identical.

    Two error regimes — symmetric gaussian and right-skewed (centered
    exponential), where the tau levels genuinely separate — with tau in
    {0.5, 0.9, 0.99} (the per-tenant p99 target) and Huber at the
    classical 1.345 plus a wider 2.0.  Each entry stores the exact
    minimizer AND the exact objective: the epsilon-smoothed fits under
    test are compared on BOTH (coefficients within the documented
    smoothing tolerance, objective within a near-optimality margin that
    is robust to flat directions in the check loss)."""
    prng = np.random.default_rng(20260807)
    rcases = {}
    n = 600
    x1 = prng.standard_normal(n)
    x2 = prng.uniform(-1.0, 1.0, n)
    X = np.column_stack([np.ones(n), x1, x2])
    errs = {
        "gaussian": prng.standard_normal(n),
        "skewed": prng.exponential(1.0, n) - 1.0,
    }
    for label, e in errs.items():
        y = 1.0 + 0.8 * x1 - 0.5 * x2 + e
        quant = {}
        for tau in (0.5, 0.9, 0.99):
            beta, obj = _quantile_lp(X, y, tau)
            quant[f"tau_{tau:g}"] = dict(tau=tau,
                                         coefficients=beta.tolist(),
                                         objective=obj)
        hub = {}
        for k in (1.345, 2.0):
            beta, obj = _huber_irls(X, y, k)
            hub[f"k_{k:g}"] = dict(k=k, coefficients=beta.tolist(),
                                   objective=obj)
        rcases[f"robust_{label}"] = dict(
            data=dict(y=y.tolist(), x1=x1.tolist(), x2=x2.tolist()),
            formula="y ~ x1 + x2",
            xnames=["intercept", "x1", "x2"],
            quantile=quant, huber=hub,
            provenance="synthetic; exact-LP quantile (scipy HiGHS primal) "
                       "and exact-weight Huber IRLS, both host f64 and "
                       "independent of the smoothed pseudo-families; R "
                       "cross-check: quantreg::rq(y ~ x1 + x2, tau) and "
                       "MASS::rlm(y ~ x1 + x2, k = <k>, scale.est = "
                       "'fixed', scale = 1)")
    return rcases


def splice_robust():
    """Rewrite ONLY the robust_cases key of the committed r_golden.json
    (same byte-stability rationale as :func:`splice_penalized`)."""
    out = os.path.join(HERE, "r_golden.json")
    with open(out) as f:
        cases = json.load(f)
    cases["robust_cases"] = robust_cases()
    with open(out, "w") as f:
        json.dump(cases, f, indent=1)
    print(f"spliced robust_cases "
          f"({len(cases['robust_cases'])} cases) into {out}")


def splice_sparse():
    """Rewrite ONLY the sparse_cases key of the committed r_golden.json
    (same byte-stability rationale as :func:`splice_penalized`)."""
    out = os.path.join(HERE, "r_golden.json")
    with open(out) as f:
        cases = json.load(f)
    cases["sparse_cases"] = sparse_cases()
    with open(out, "w") as f:
        json.dump(cases, f, indent=1)
    print(f"spliced sparse_cases "
          f"({len(cases['sparse_cases'])} cases) into {out}")


def splice_penalized():
    """Rewrite ONLY the penalized_cases key of the committed r_golden.json,
    leaving every other case's bytes untouched (json round-trips Python
    floats through their shortest repr, so load -> dump is byte-stable)."""
    out = os.path.join(HERE, "r_golden.json")
    with open(out) as f:
        cases = json.load(f)
    cases["penalized_cases"] = penalized_cases()
    with open(out, "w") as f:
        json.dump(cases, f, indent=1)
    print(f"spliced penalized_cases "
          f"({len(cases['penalized_cases'])} cases) into {out}")


if __name__ == "__main__":
    if "--splice-penalized" in sys.argv:
        splice_penalized()
    elif "--splice-sparse" in sys.argv:
        splice_sparse()
    elif "--splice-robust" in sys.argv:
        splice_robust()
    else:
        main()
