# Regenerate / verify tests/fixtures/r_golden.json with real R.
#
# R is not installed in the build image, so the committed JSON was produced
# by gen_golden.py (an independent float64 IRLS with R's exact family
# formulas), anchored by the two cases whose outputs are printed in R's own
# ?glm documentation (dobson_poisson, clotting_gamma_lot1).  Run this script
# anywhere R exists to confirm every number:
#
#   Rscript tests/fixtures/make_r_golden.R
#
# and compare the printed values against r_golden.json.

show <- function(name, fit, quasi = FALSE) {
  s <- summary(fit)
  cat("== ", name, "\n")
  cat("coefficients:", format(coef(fit), digits = 10), "\n")
  cat("std_errors:  ", format(s$coefficients[, 2], digits = 10), "\n")
  cat("deviance:    ", format(deviance(fit), digits = 10), "\n")
  cat("null_dev:    ", format(fit$null.deviance, digits = 10), "\n")
  cat("dispersion:  ", format(s$dispersion, digits = 10), "\n")
  if (!quasi) {
    cat("loglik:      ", format(as.numeric(logLik(fit)), digits = 10), "\n")
    cat("aic:         ", format(AIC(fit), digits = 10), "\n")
  }
  cat("df_residual: ", fit$df.residual, " df_null:", fit$df.null, "\n\n")
}

# locate r_golden.json next to this script under Rscript OR source(); fall
# back to the repo-relative path when neither reveals a file name
args <- commandArgs(trailingOnly = FALSE)
script <- sub("^--file=", "", grep("^--file=", args, value = TRUE))
if (length(script) == 0) script <- NULL
if (is.null(script)) script <- tryCatch(sys.frame(1)$ofile, error = function(e) NULL)
dir <- if (is.null(script)) "tests/fixtures" else dirname(script)
j <- jsonlite::fromJSON(file.path(dir, "r_golden.json"))

# 1. Dobson poisson (?glm)
counts <- c(18, 17, 15, 20, 10, 20, 25, 13, 12)
outcome <- gl(3, 1, 9); treatment <- gl(3, 3)
show("dobson_poisson", glm(counts ~ outcome + treatment, family = poisson()))

# 2. clotting gamma (?glm)
clotting <- data.frame(u = c(5, 10, 15, 20, 30, 40, 60, 80, 100),
                       lot1 = c(118, 58, 42, 35, 27, 25, 21, 19, 18),
                       lot2 = c(69, 35, 26, 21, 18, 16, 13, 12, 9))
show("clotting_gamma_lot1", glm(lot1 ~ log(u), data = clotting, family = Gamma))
show("clotting_gamma_lot2", glm(lot2 ~ log(u), data = clotting, family = Gamma))

# 3-8. synthetic cases: data vectors live in r_golden.json$<case>$data
d <- j$grouped_binomial_logit$data
show("grouped_binomial_logit",
     glm(cbind(d$successes, d$m - d$successes) ~ d$x1, family = binomial()))

d <- j$poisson_offset$data
show("poisson_offset",
     glm(d$y ~ d$x1 + offset(log(d$exposure)), family = poisson()))

d <- j$quasipoisson$data
show("quasipoisson", glm(d$y ~ d$x1, family = quasipoisson()), quasi = TRUE)

d <- j$gaussian_weighted$data
show("gaussian_weighted", glm(d$y ~ d$x1, family = gaussian(), weights = d$w))

d <- j$inverse_gaussian$data
show("inverse_gaussian", glm(d$y ~ d$x, family = inverse.gaussian()))

d <- j$bernoulli_cloglog$data
show("bernoulli_cloglog", glm(d$y ~ d$x, family = binomial(link = "cloglog")))

# ---------------------------------------------------------------------------
# formula_cases (round 3): verify the FORMULA-driven golden tier — run the
# same R formulas the fixtures promise and compare summary() output with
# r_golden.json$formula_cases$<name>$fit / $r_doc / $summary_contains
# ---------------------------------------------------------------------------

fc <- j$formula_cases

# F1 Dobson through factors (the exact ?glm code)
d <- fc$dobson_factors$data
show("dobson_factors",
     glm(d$counts ~ factor(d$outcome) + factor(d$treatment),
         family = poisson()))

# F2 clotting with the log(u) transform in the formula
d <- fc$clotting_log_transform$data
show("clotting_log_transform", glm(d$lot1 ~ log(d$u), family = Gamma))

# F3 R's ?lm plant-weight example (lm.D9)
d <- fc$lm_D9_factor$data
print(summary(lm(d$weight ~ factor(d$group))))

# F4 numeric x factor interaction
d <- fc$interaction_poisson$data
show("interaction_poisson",
     glm(d$y ~ d$x * factor(d$g), family = poisson()))

# F5 weights + offset() by name
d <- fc$gamma_weights_offset$data
show("gamma_weights_offset",
     glm(d$y ~ d$x + offset(d$log_e), family = Gamma(link = "log"),
         weights = d$w))

# F6 cbind response
d <- fc$cbind_binomial$data
show("cbind_binomial",
     glm(cbind(d$s, d$f) ~ d$x1 + d$x2, family = binomial()))

# F7 transforms: log + power
d <- fc$gaussian_transforms$data
show("gaussian_transforms",
     glm(d$y ~ log(d$u) + I(d$u^2), family = gaussian()))

# ---------------------------------------------------------------------------
# influence goldens (round 5): verify the case-deletion / influence tier —
# compare against r_golden.json$<case>$influence (hat, sigma, dfbeta(s),
# dffits, covratio, rstudent, rstandard, cooks_distance, is_inf).
# ---------------------------------------------------------------------------

show_influence <- function(name, fit) {
  infl <- influence(fit)
  im <- influence.measures(fit)
  cat("== influence ", name, "\n")
  cat("hat:       ", format(unname(infl$hat), digits = 10), "\n")
  cat("sigma:     ", format(unname(infl$sigma), digits = 10), "\n")
  cat("dfbeta:    ", format(unname(infl$coefficients), digits = 10), "\n")
  cat("dfbetas:   ", format(unname(dfbetas(fit)), digits = 10), "\n")
  cat("dffits:    ", format(unname(dffits(fit)), digits = 10), "\n")
  cat("covratio:  ", format(unname(covratio(fit)), digits = 10), "\n")
  cat("rstudent:  ", format(unname(rstudent(fit)), digits = 10), "\n")
  cat("rstandard: ", format(unname(rstandard(fit)), digits = 10), "\n")
  cat("cooks:     ", format(unname(cooks.distance(fit)), digits = 10), "\n")
  cat("is.inf:    ", as.integer(im$is.inf), "\n\n")
}

counts <- c(18, 17, 15, 20, 10, 20, 25, 13, 12)
outcome <- gl(3, 1, 9); treatment <- gl(3, 3)
show_influence("dobson_poisson",
               glm(counts ~ outcome + treatment, family = poisson()))

clotting <- data.frame(u = c(5, 10, 15, 20, 30, 40, 60, 80, 100),
                       lot1 = c(118, 58, 42, 35, 27, 25, 21, 19, 18))
show_influence("clotting_gamma_lot1",
               glm(lot1 ~ log(u), data = clotting, family = Gamma))

d <- j$grouped_binomial_logit$data
show_influence("grouped_binomial_logit",
               glm(cbind(d$successes, d$m - d$successes) ~ d$x1,
                   family = binomial()))

d <- j$gaussian_weighted$data
show_influence("gaussian_weighted",
               glm(d$y ~ d$x1, family = gaussian(), weights = d$w))

d <- fc$lm_D9_factor$data
show_influence("lm_D9_factor", lm(d$weight ~ factor(d$group)))

# ---------------------------------------------------------------------------
# single-model sequential anova (round 5): verify against the framework's
# anova(model, data) tables for the two documentation fixtures.
# ---------------------------------------------------------------------------

cat("== anova dobson_poisson\n")
print(anova(glm(counts ~ outcome + treatment, family = poisson()),
            test = "Chisq"))
d <- fc$lm_D9_factor$data
cat("== anova lm_D9\n")
print(anova(lm(d$weight ~ factor(d$group))))
