"""Summary rendering + persistence tests.

Golden-substring summaries follow the reference's testing pattern
(R/pkg/tests/testthat/test_LM.R:40-45 asserts summary strings) — mechanism,
not its recorded-against-buggy-output values (SURVEY.md §4).  Persistence is
new capability: the reference keeps models only as live JVM objects.
"""

import numpy as np

import sparkglm_tpu as sg


def _lm(mesh):
    rng = np.random.default_rng(7)
    X = rng.normal(size=(120, 3))
    X[:, 0] = 1.0
    y = X @ [1.0, -2.0, 0.5] + 0.1 * rng.normal(size=120)
    return sg.lm_fit(X, y, xnames=("intercept", "a", "b"), mesh=mesh)


def test_lm_summary_blocks(mesh1):
    s = _lm(mesh1).summary()
    text = str(s)
    for needle in ("Model:", "Coefficients:", "Estimate", "Std. Error",
                   "t value", "Pr(>|t|)", "Residual standard error",
                   "Multiple R-Squared", "F-statistic"):
        assert needle in text, needle
    arr = s.summary_array()
    assert len(arr) == 5  # the R bridge contract (R/pkg/R/LM.R:122-127)
    d = s.as_dict()
    assert 0.9 < d["r_squared"] <= 1.0
    assert d["f_p_value"] < 1e-10


def test_glm_summary_blocks(mesh1):
    rng = np.random.default_rng(8)
    X = rng.normal(size=(300, 3))
    X[:, 0] = 1.0
    y = (rng.uniform(size=300) < 1 / (1 + np.exp(-X[:, 1]))).astype(float)
    m = sg.glm_fit(X, y, family="binomial", mesh=mesh1)
    text = str(m.summary())
    for needle in ("Coefficients:", "z value", "Pr(>|z|)", "Null deviance",
                   "Residual deviance", "AIC", "Fisher Scoring iterations"):
        assert needle in text, needle


def test_coefficient_correlation_matrix(mesh1, rng):
    """R's summary(fit, correlation=TRUE): vcov scaled to unit diagonal —
    validated against a direct dense computation for LM and GLM."""
    n = 300
    X = rng.normal(size=(n, 3)); X[:, 0] = 1.0
    y = X @ [1.0, 0.5, -0.2] + 0.3 * rng.normal(size=n)
    m = sg.lm_fit(X, y, mesh=mesh1)
    C = m.correlation()
    np.testing.assert_allclose(np.diag(C), 1.0, rtol=1e-12)
    # independent dense computation: corr of inv(X'X) (sigma^2 cancels)
    Vi = np.linalg.inv(X.T @ X)
    di = np.sqrt(np.diag(Vi))
    np.testing.assert_allclose(C, Vi / np.outer(di, di),
                               rtol=1e-6, atol=1e-9)
    assert np.all(np.abs(C) <= 1 + 1e-12)
    yb = (rng.random(n) < 0.5).astype(float)
    g = sg.glm_fit(X, yb, family="binomial", mesh=mesh1)
    Cg = g.correlation()
    np.testing.assert_allclose(np.diag(Cg), 1.0, rtol=1e-12)
    assert Cg.shape == (3, 3) and np.allclose(Cg, Cg.T)


def test_glm_summary_t_tests_for_estimated_dispersion(mesh1, rng):
    """R's summary.glm: t value / Pr(>|t|) with df_residual for families
    with estimated dispersion (gamma, quasi*), z for fixed (poisson);
    quasi AIC prints NA, not nan."""
    import scipy.stats
    n = 150
    X = rng.normal(size=(n, 3)); X[:, 0] = 1.0
    yg = rng.gamma(3.0, np.exp(X @ [0.4, 0.3, -0.2]) / 3.0)
    mg = sg.glm_fit(X, yg, family="gamma", link="log", mesh=mesh1)
    sg_text = str(mg.summary())
    assert "t value" in sg_text and "Pr(>|t|)" in sg_text
    expect = 2 * scipy.stats.t.sf(np.abs(mg.z_values()), mg.df_residual)
    np.testing.assert_allclose(mg.p_values(), expect, rtol=1e-12)
    yq = rng.poisson(np.exp(X @ [0.4, 0.3, -0.2])).astype(float)
    mq = sg.glm_fit(X, yq, family="quasipoisson", mesh=mesh1)
    text = str(mq.summary())
    assert "t value" in text and "AIC: NA" in text and "nan" not in text
    mp = sg.glm_fit(X, yq, family="poisson", mesh=mesh1)
    assert "z value" in str(mp.summary())


def test_save_load_roundtrip_lm(tmp_path, mesh1):
    m = _lm(mesh1)
    path = str(tmp_path / "model.npz")
    m.save(path)
    m2 = sg.load_model(path)
    np.testing.assert_array_equal(m.coefficients, m2.coefficients)
    assert m2.xnames == m.xnames
    assert m2.r_squared == m.r_squared
    # loaded model predicts
    X = np.random.default_rng(0).normal(size=(5, 3))
    np.testing.assert_allclose(m2.predict(X), m.predict(X))


def test_save_load_roundtrip_glm_with_terms(tmp_path, mesh1):
    rng = np.random.default_rng(9)
    n = 200
    data = {
        "y": (rng.uniform(size=n) < 0.5).astype(float),
        "x": rng.normal(size=n),
        "g": np.array(["u", "v"])[rng.integers(0, 2, n)],
    }
    m = sg.glm("y ~ x + g", data, family="binomial", mesh=mesh1)
    path = str(tmp_path / "glm.npz")
    m.save(path)
    m2 = sg.load_model(path)
    assert m2.family == "binomial" and m2.link == "logit"
    assert m2.terms is not None and m2.terms.xnames == m.terms.xnames
    np.testing.assert_allclose(sg.predict(m2, data), sg.predict(m, data))


def test_glm_summary_golden_layout_dobson(mesh1):
    """Golden-string summary check on the Dobson ?glm fixture — the
    reference's own test mechanism (test_LM.R:44), pointed at output that
    matches R's summary.glm layout and numbers at print precision."""
    counts = np.array([18, 17, 15, 20, 10, 20, 25, 13, 12], float)
    o = np.array(["1", "2", "3"] * 3)
    t = np.array(["1"] * 3 + ["2"] * 3 + ["3"] * 3)
    m = sg.glm("counts ~ o + t", {"counts": counts, "o": o, "t": t},
               family="poisson", mesh=mesh1)
    text = str(m.summary())
    for needle in (
        "Family: poisson  Link: log",
        "Coefficients:",
        "Estimate  Std. Error",
        "Pr(>|z|)",
        "3.045",      # intercept estimate (R: 3.0445)
        "0.1709",     # its SE (R: 0.1709)
        "-0.4543",    # o_2 (R outcome2: -0.4543)
        "Signif. codes:",
        "(Dispersion parameter for poisson family taken to be 1",
        "Null deviance: 10.58",
        "Residual deviance: 5.129",
        "AIC: 56.76",
        "Number of Fisher Scoring iterations:",
    ):
        assert needle in text, f"summary missing {needle!r}:\n{text}"
