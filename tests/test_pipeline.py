"""Pipelined streaming engine (sparkglm_tpu/data/pipeline.py): prefetch
producer, fixed-shape chunk buckets, deferred accumulation — and the
contract that makes it shippable: ``prefetch>=2`` is BIT-identical to the
sequential path (coefficients, std errors, deviance, trace-event order),
faults included, and every pass flavor compiles exactly one executable
despite ragged chunks."""

import time
from collections import Counter

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data import pipeline
from sparkglm_tpu.models import streaming
from sparkglm_tpu.obs import FitTracer, RingBufferSink
from sparkglm_tpu.obs import trace as obs_trace
from sparkglm_tpu.robust import (FaultPlan, RetryPolicy,
                                 SimulatedPreemption, faulty_source)

NOSLEEP = RetryPolicy(sleep=lambda s: None)

# events whose fields are fully deterministic; the rest carry seconds, so
# only (seq, kind) is compared (same contract as tests/test_obs.py)
_STABLE_KINDS = {"fit_start", "fit_end", "iter", "retry", "pass_start",
                 "budget_exhausted"}


def _binomial_data(rng, n=4000, p=4):
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    return X, y


def _ragged_factory(X, y, w=None, off=None, rows=997):
    """Chunk factory with a ragged last chunk (n % rows != 0)."""
    n = X.shape[0]

    def source():
        for lo in range(0, n, rows):
            hi = min(lo + rows, n)
            yield (X[lo:hi], y[lo:hi],
                   None if w is None else w[lo:hi],
                   None if off is None else off[lo:hi])
    return source


def _ring_tracer():
    ring = RingBufferSink()
    return ring, FitTracer(sinks=[ring])


# ---------------------------------------------------------------------------
# prefetch_iter primitives
# ---------------------------------------------------------------------------

def test_prefetch_iter_in_order_and_bounded():
    produced = []

    def make_iter():
        for i in range(20):
            produced.append(i)
            yield i

    stats = pipeline.PassStats()
    got = []
    for item in pipeline.prefetch_iter(make_iter, prefetch=3, stats=stats):
        # bounded: at most prefetch finished items + 1 being produced may
        # exist beyond what the consumer has taken
        assert len(produced) - len(got) <= 3 + 2
        got.append(item)
        time.sleep(0.001)  # slow consumer: the producer must stall
    assert got == list(range(20))
    assert stats.items > 0
    assert stats.depth_max <= 3


def test_prefetch_iter_reraises_error_at_position():
    def make_iter():
        yield 0
        yield 1
        raise OSError("boom at 2")

    it = pipeline.prefetch_iter(make_iter, prefetch=4)
    assert next(it) == 0
    assert next(it) == 1
    with pytest.raises(OSError, match="boom at 2"):
        next(it)


def test_prefetch_iter_propagates_base_exception():
    def make_iter():
        yield 0
        raise SimulatedPreemption("preempted")

    it = pipeline.prefetch_iter(make_iter, prefetch=2)
    assert next(it) == 0
    with pytest.raises(SimulatedPreemption):
        next(it)


def test_prefetch_iter_early_close_stops_producer():
    produced = []

    def make_iter():
        for i in range(1000):
            produced.append(i)
            yield i

    it = pipeline.prefetch_iter(make_iter, prefetch=2)
    assert next(it) == 0
    it.close()  # abandon: the finally block must stop and join the producer
    time.sleep(0.05)
    n1 = len(produced)
    time.sleep(0.05)
    assert len(produced) == n1  # no further production after close
    assert n1 < 1000


def test_prefetch_iter_replays_producer_events_in_order():
    """Tracer events emitted while producing item k land on the consumer
    in item order with consecutive seq numbers — identical to a
    sequential run of the same generator."""
    def make_iter(tracer):
        def gen():
            for i in range(5):
                tracer.emit("read", index=i)
                yield i
        return gen

    ring_seq, tr_seq = _ring_tracer()
    list(make_iter(tr_seq)())
    ring_pipe, tr_pipe = _ring_tracer()
    list(pipeline.prefetch_iter(make_iter(tr_pipe), prefetch=3))
    assert [e.key() for e in ring_pipe.events] \
        == [e.key() for e in ring_seq.events]


def test_capture_diverts_only_current_thread():
    ring, tr = _ring_tracer()
    with obs_trace.capture() as buf:
        tr.emit("read", index=0)
    assert ring.events == []  # diverted, not sequenced
    obs_trace.replay(buf)
    assert ring.kinds() == ["read"]
    assert ring.events[0].fields == {"index": 0}


def test_prefetch_validation():
    X, y = _binomial_data(np.random.default_rng(0))
    with pytest.raises(ValueError, match="prefetch"):
        sg.glm_fit_streaming(_ragged_factory(X, y), family="binomial",
                             prefetch=-1)
    with pytest.raises(ValueError, match="prefetch"):
        pipeline.prefetch_iter(lambda: iter(()), prefetch=0)


# ---------------------------------------------------------------------------
# pipelined vs sequential bit-identity
# ---------------------------------------------------------------------------

def test_glm_pipelined_bit_identical(rng):
    X, y = _binomial_data(rng, n=5000, p=5)
    seq = sg.glm_fit_streaming(_ragged_factory(X, y), family="binomial",
                               cache="none")
    pipe = sg.glm_fit_streaming(_ragged_factory(X, y), family="binomial",
                                cache="none", prefetch=3)
    np.testing.assert_array_equal(seq.coefficients, pipe.coefficients)
    np.testing.assert_array_equal(seq.std_errors, pipe.std_errors)
    assert seq.deviance == pipe.deviance
    assert seq.null_deviance == pipe.null_deviance


def test_lm_pipelined_bit_identical_with_weights_offset(rng):
    n, p = 5000, 5
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    y = X @ rng.normal(size=p) + rng.normal(size=n)
    w = rng.uniform(0.5, 2.0, size=n)
    off = rng.normal(size=n) / 10
    seq = sg.lm_fit_streaming(_ragged_factory(X, y, w, off))
    pipe = sg.lm_fit_streaming(_ragged_factory(X, y, w, off), prefetch=2)
    np.testing.assert_array_equal(seq.coefficients, pipe.coefficients)
    np.testing.assert_array_equal(seq.std_errors, pipe.std_errors)
    assert seq.sse == pipe.sse and seq.sst == pipe.sst
    assert seq.resid_quantiles == pipe.resid_quantiles


def test_glm_pipelined_matches_device_cache_modes(rng):
    """prefetch composes with the device chunk cache: cached prefix on
    later passes, pipelined overflow — still bit-identical."""
    X, y = _binomial_data(rng, n=5000, p=5)
    base = sg.glm_fit_streaming(_ragged_factory(X, y), family="binomial",
                                cache="none")
    cached = sg.glm_fit_streaming(_ragged_factory(X, y), family="binomial",
                                  cache="device", prefetch=2)
    np.testing.assert_array_equal(base.coefficients, cached.coefficients)
    assert base.deviance == cached.deviance


# ---------------------------------------------------------------------------
# faults inside the producer: retries and preemption stay deterministic
# ---------------------------------------------------------------------------

def _faulted_fit(rng_seed, prefetch, trace=None):
    rng = np.random.default_rng(rng_seed)
    X, y = _binomial_data(rng, n=4000, p=4)
    src = faulty_source(_ragged_factory(X, y, rows=800),
                        FaultPlan(transient_at=(7,)))
    return sg.glm_fit_streaming(src, family="binomial", cache="none",
                                retry=NOSLEEP, prefetch=prefetch,
                                trace=trace)


def test_pipelined_fault_retry_bit_identical():
    """Mid-pass transient faults retried INSIDE the producer thread give
    the same model as the sequential retry path."""
    r_seq, t_seq = _ring_tracer()
    m_seq = _faulted_fit(7, prefetch=0, trace=t_seq)
    r_pipe, t_pipe = _ring_tracer()
    m_pipe = _faulted_fit(7, prefetch=3, trace=t_pipe)
    np.testing.assert_array_equal(m_seq.coefficients, m_pipe.coefficients)
    np.testing.assert_array_equal(m_seq.std_errors, m_pipe.std_errors)
    assert m_seq.deviance == m_pipe.deviance
    assert m_seq.fit_report()["retries"] == m_pipe.fit_report()["retries"] > 0
    # the retry fired on the producer thread but was replayed in order:
    # the STABLE event subsequence matches the sequential run's exactly
    # (pipelined runs additionally carry queue_wait/prefetch_depth events)
    stable = lambda ring: [  # noqa: E731
        (e.kind, tuple(sorted(e.fields.items())))
        for e in ring.events if e.kind in _STABLE_KINDS]
    assert stable(r_pipe) == stable(r_seq)


def test_pipelined_event_sequence_deterministic():
    """Two identical pipelined faulted fits emit the same event sequence
    — seq numbers included (producer events are replayed, not raced)."""
    r1, t1 = _ring_tracer()
    _faulted_fit(11, prefetch=2, trace=t1)
    r2, t2 = _ring_tracer()
    _faulted_fit(11, prefetch=2, trace=t2)
    k1, k2 = r1.events, r2.events
    assert [(e.seq, e.kind) for e in k1] == [(e.seq, e.kind) for e in k2]
    assert [e.key() for e in k1 if e.kind in _STABLE_KINDS] \
        == [e.key() for e in k2 if e.kind in _STABLE_KINDS]


def test_pipelined_preempt_resume_bit_identical(rng, tmp_path):
    """A pipelined fit preempted mid-stream (BaseException through the
    producer) resumes from its checkpoint to the same model as an
    uninterrupted sequential fit."""
    X, y = _binomial_data(rng, n=4000, p=4)
    baseline = sg.glm_fit_streaming(_ragged_factory(X, y, rows=800),
                                    family="binomial", cache="none")
    ck = str(tmp_path / "ck.npz")
    plan = FaultPlan(preempt_at=(12,))
    with pytest.raises(SimulatedPreemption):
        sg.glm_fit_streaming(
            faulty_source(_ragged_factory(X, y, rows=800), plan),
            family="binomial", cache="none", checkpoint=ck, prefetch=2)
    resumed = sg.glm_fit_streaming(_ragged_factory(X, y, rows=800),
                                   family="binomial", cache="none",
                                   checkpoint=ck, resume=True, prefetch=2)
    np.testing.assert_array_equal(baseline.coefficients, resumed.coefficients)
    np.testing.assert_array_equal(baseline.std_errors, resumed.std_errors)
    assert baseline.deviance == resumed.deviance


# ---------------------------------------------------------------------------
# first-chunk fingerprint probe: no double read
# ---------------------------------------------------------------------------

def test_first_chunk_probe_does_not_double_read(rng):
    X, y = _binomial_data(rng, n=100, p=3)
    opens = [0]
    mats = Counter()

    def chunks():
        opens[0] += 1

        def gen():
            for i in range(4):
                def thunk(i=i):
                    mats[i] += 1
                    lo, hi = 25 * i, 25 * (i + 1)
                    return (X[lo:hi], y[lo:hi], None, None)
                yield thunk
        return gen()

    fp, p, structured, wrapped = streaming._source_first_chunk(chunks)
    assert p == 3
    assert structured is False
    assert mats[0] == 1
    got = [streaming._materialize(c) for c in wrapped()]
    # the probe's open AND materialized chunk 0 are handed to the first
    # pass: still one open, chunk 0 still parsed exactly once
    assert opens[0] == 1
    assert mats[0] == 1
    assert len(got) == 4 and mats[3] == 1
    # later passes re-open the source as usual
    [streaming._materialize(c) for c in wrapped()]
    assert opens[0] == 2
    assert mats[0] == 2


# ---------------------------------------------------------------------------
# fixed-shape buckets: one compile per pass flavor despite ragged chunks
# ---------------------------------------------------------------------------

def test_bucket_pad_inert_rows():
    X = np.arange(12.0).reshape(6, 2)
    y = np.arange(6.0)
    bucket = {}
    X0, y0, w0, o0 = streaming._bucket_pad(X, y, None, None, bucket)
    assert X0.shape == (6, 2) and bucket["rows"] == 6
    assert np.all(w0 == 1.0)  # explicit weights keep the pass arity fixed
    Xp, yp, wp, op = streaming._bucket_pad(X[:4], y[:4], None, None, bucket)
    assert Xp.shape == (6, 2)  # ragged tail padded up to the bucket
    assert np.all(wp[4:] == 0.0) and np.all(Xp[4:] == 0.0)
    assert np.all(yp[4:] == 0.0) and op is None
    # oversized chunk: next multiple of the bucket, not a fresh shape zoo
    Xb = np.ones((8, 2))
    Xq, _, wq, _ = streaming._bucket_pad(Xb, np.ones(8), None, None, bucket)
    assert Xq.shape == (12, 2) and np.all(wq[8:] == 0.0)


def test_glm_one_compile_event_per_pass_flavor(rng):
    """Multi-pass streaming fit over ragged chunks: exactly ONE compile
    per pass flavor (init / irls), because every chunk is padded to the
    fit's shape bucket.  Dims are deliberately unusual so the jit cache is
    cold for this shape within the test process."""
    X, y = _binomial_data(rng, n=1234, p=11)
    ring, tracer = _ring_tracer()
    m = sg.glm_fit_streaming(_ragged_factory(X, y, rows=237),
                             family="binomial", cache="none",
                             prefetch=2, trace=tracer)
    assert m.iterations >= 2  # multi-pass: irls flavor ran more than once
    comp = Counter(e.fields["target"]
                   for e in ring.events if e.kind == "compile")
    assert comp == {"glm_pass:init": 1, "glm_pass:irls": 1}


def test_lm_one_compile_event_despite_ragged_chunks(rng):
    n, p = 1077, 9
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    y = X @ rng.normal(size=p) + rng.normal(size=n)
    ring, tracer = _ring_tracer()
    sg.lm_fit_streaming(_ragged_factory(X, y, rows=250), trace=tracer)
    comp = Counter(e.fields["target"]
                   for e in ring.events if e.kind == "compile")
    assert comp == {"lm_gramian": 1}


# ---------------------------------------------------------------------------
# telemetry: queue_wait / prefetch_depth / overlap_ratio
# ---------------------------------------------------------------------------

def test_pipelined_pass_telemetry(rng):
    X, y = _binomial_data(rng, n=5000, p=5)
    ring, tracer = _ring_tracer()
    m = sg.glm_fit_streaming(_ragged_factory(X, y), family="binomial",
                             cache="none", prefetch=3, trace=tracer)
    kinds = Counter(ring.kinds())
    # one queue_wait + one prefetch_depth per pipelined pass, emitted
    # right before its pass_end
    assert kinds["queue_wait"] == kinds["pass_end"]
    assert kinds["prefetch_depth"] == kinds["pass_end"]
    pos = {k: [i for i, e in enumerate(ring.events) if e.kind == k]
           for k in ("queue_wait", "prefetch_depth", "pass_end")}
    for qw, pd, pe in zip(*pos.values()):
        assert qw == pe - 2 and pd == pe - 1
    rep = m.fit_report()
    assert rep["queue_wait_s"] >= 0.0
    assert rep["prefetch_depth_max"] >= 1
    assert 0.0 <= rep["overlap_ratio"] <= 1.0
    # pipelined pass_end events carry wall_s (io/compute ran concurrently)
    for e in ring.events:
        if e.kind == "pass_end":
            assert "wall_s" in e.fields


def test_sequential_fit_has_no_pipeline_events(rng):
    X, y = _binomial_data(rng, n=3000, p=4)
    ring, tracer = _ring_tracer()
    m = sg.glm_fit_streaming(_ragged_factory(X, y), family="binomial",
                             cache="none", trace=tracer)
    kinds = set(ring.kinds())
    assert "queue_wait" not in kinds and "prefetch_depth" not in kinds
    assert m.fit_report()["overlap_ratio"] == 0.0


# ---------------------------------------------------------------------------
# auto-degrade: the pipeline A/B-tests itself against its sequential probe
# ---------------------------------------------------------------------------

def test_auto_degrade_when_overlap_does_not_pay():
    """Produce-dominated stream with nothing to overlap (zero consumer
    compute): pipelining cannot beat sequential, so after the probe the
    producer hands its iterator back and the pass finishes sequentially."""
    def make_iter():
        for i in range(6):
            time.sleep(0.15)
            yield i

    stats = pipeline.PassStats()
    got = list(pipeline.prefetch_iter(make_iter, prefetch=2, stats=stats))
    assert got == list(range(6))
    assert stats.degraded
    assert stats.items == 6
    assert stats.produce_s > 0.8  # every item's production was timed


def test_no_degrade_when_overlap_pays():
    """Balanced produce/compute: the pipelined rate is ~2x sequential, so
    the pass keeps its producer thread."""
    def make_iter():
        for i in range(6):
            time.sleep(0.15)
            yield i

    stats = pipeline.PassStats()
    got = []
    for item in pipeline.prefetch_iter(make_iter, prefetch=2, stats=stats):
        got.append(item)
        time.sleep(0.15)  # consumer compute the producer can hide under
    assert got == list(range(6))
    assert not stats.degraded


def test_auto_degrade_off_pipelines_unconditionally():
    def make_iter():
        for i in range(6):
            time.sleep(0.12)
            yield i

    stats = pipeline.PassStats()
    got = list(pipeline.prefetch_iter(make_iter, prefetch=2, stats=stats,
                                      auto_degrade=False))
    assert got == list(range(6))
    assert not stats.degraded


def test_fast_streams_never_degrade():
    """Sub-_PROBE_MIN_S streams take no degrade decision (deterministic
    event sequences for the comparison tests stay intact)."""
    stats = pipeline.PassStats()
    got = list(pipeline.prefetch_iter(lambda: iter(range(50)), prefetch=3,
                                      stats=stats))
    assert got == list(range(50))
    assert not stats.degraded


def test_degrade_then_restore_when_overlap_pays_again():
    """Continuous controller: a produce-dominated head degrades the pass,
    but once consumer compute appears the rolling sequential window
    re-prices the trade-off and pipelining is restored mid-pass."""
    def make_iter():
        for i in range(20):
            time.sleep(0.15)
            yield i

    stats = pipeline.PassStats()
    got = []
    for item in pipeline.prefetch_iter(make_iter, prefetch=2, stats=stats):
        got.append(item)
        if item >= 4:
            time.sleep(0.15)  # compute returns: overlap pays again
    assert got == list(range(20))
    assert stats.degraded and stats.degrades >= 1
    assert stats.restores >= 1


def test_failed_restore_backs_off_exponentially():
    """A stream where overlap NEVER pays re-degrades right after each
    restore trial, and each failed restore doubles the sequential window
    before the next trial — the controller's thrash bound.  Degrades can
    exceed restores by at most one (the currently-open degraded phase)."""
    def make_iter():
        for i in range(18):
            time.sleep(0.14)
            yield i

    stats = pipeline.PassStats()
    got = list(pipeline.prefetch_iter(make_iter, prefetch=2, stats=stats))
    assert got == list(range(18))
    assert stats.degrades >= 2 and stats.restores >= 1
    assert stats.degrades <= stats.restores + 1


def test_degraded_pass_emits_prefetch_degraded_event():
    """Streaming surfaces PassStats.degraded as a prefetch_degraded trace
    event right before the queue_wait/prefetch_depth pair, and
    fit_report()'s event_counts picks it up with no aggregate changes."""
    ring, tracer = _ring_tracer()
    stats = pipeline.PassStats()
    stats.items, stats.produce_s, stats.degraded = 7, 1.25, True
    streaming._emit_pipeline_events(tracer, stats, label="pass", index=0)
    assert ring.kinds() == ["prefetch_degraded", "queue_wait",
                            "prefetch_depth"]
    ev = ring.events[0]
    assert ev.fields["items"] == 7 and ev.fields["label"] == "pass"
    assert tracer.report()["event_counts"]["prefetch_degraded"] == 1
