"""Regression tests for review findings on the foundation commit."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from oracle import irls_np
from sparkglm_tpu.data.formula import parse_formula


def test_formula_rejects_unsupported_syntax():
    # interactions ':'/'*' and whitelisted transforms (log(x), I(x^2)) are
    # supported since r2; bare '^', numerals, free parentheses and unknown
    # functions still fail loudly
    for bad in ("y ~ x^2", "y ~ x + 2", "y ~ (a + b)", "y ~ poly(x)"):
        with pytest.raises(ValueError):
            parse_formula(bad)


def test_formula_rejects_multidigit_numerals():
    """'10' must not tokenize as '1','0' and silently drop the intercept."""
    for bad in ("y ~ x + 10", "y ~ x + 11", "y ~ x - 10", "y ~ 100 + x"):
        with pytest.raises(ValueError, match="numeric term"):
            parse_formula(bad)
    assert parse_formula("y ~ x + 1").intercept
    assert not parse_formula("y ~ x - 1").intercept
    assert not parse_formula("y ~ x + 0").intercept


def test_nan_weight_column_row_dropped(mesh1, rng):
    """A NaN in a by-name weights column drops the row (R model-frame
    semantics) instead of producing all-NaN coefficients."""
    n = 200
    d = {"y": rng.normal(size=n), "x": rng.normal(size=n),
         "w": rng.uniform(0.5, 2.0, size=n)}
    d["w"][7] = np.nan
    m = sg.lm("y ~ x", d, weights="w", mesh=mesh1)
    assert np.all(np.isfinite(m.coefficients))
    assert m.n_obs == n - 1
    keep = np.ones(n, bool)
    keep[7] = False
    m_ref = sg.lm("y ~ x", {k: v[keep] for k, v in d.items()},
                  weights="w", mesh=mesh1)
    np.testing.assert_allclose(m.coefficients, m_ref.coefficients, rtol=1e-12)


def test_array_offset_realigned_after_na_omit(mesh8, rng):
    """Array-valued offset/weights get the same keep-mask as the design."""
    n = 200
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    d = {"y": y, "x": x.copy()}
    d["x"][5] = np.nan
    m = sg.glm("y ~ x", d, family="poisson", offset=off, mesh=mesh8)
    keep = np.ones(n, bool)
    keep[5] = False
    m_ref = sg.glm("y ~ x", {k: v[keep] for k, v in d.items()},
                   family="poisson", offset=off[keep], mesh=mesh8)
    np.testing.assert_allclose(m.coefficients, m_ref.coefficients, rtol=1e-10)
    # wrong-length extras fail loudly at both API levels
    with pytest.raises(ValueError, match="offset"):
        sg.glm("y ~ x", d, family="poisson", offset=off[:-3], mesh=mesh8)
    with pytest.raises(ValueError, match="weights"):
        sg.glm_fit(np.stack([np.ones(n), x], 1), y,
                   family="poisson", weights=np.ones(n + 1), mesh=mesh8)


def test_nan_offset_column_row_dropped(mesh1, rng):
    n = 300
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    d = {"y": y, "x": x, "off": off}
    d["off"][11] = np.nan
    m = sg.glm("y ~ x", d, family="poisson", offset="off", mesh=mesh1)
    assert np.all(np.isfinite(m.coefficients))
    assert m.n_obs == n - 1


def test_predict_int_design(mesh1, rng):
    X = rng.normal(size=(50, 2))
    X[:, 0] = 1.0
    y = X @ [0.5, 0.25] + 0.01 * rng.normal(size=50)
    m = sg.lm_fit(X, y, mesh=mesh1)
    Xi = np.array([[1, 25], [1, 30]])  # int64 design
    np.testing.assert_allclose(m.predict(Xi), Xi.astype(float) @ m.coefficients,
                               rtol=1e-5)


def test_r_squared_large_offset_mean(mesh8, rng):
    """float32-unsafe one-pass SST would destroy R^2 at mean >> std."""
    n = 4000
    x = rng.normal(size=n)
    y = 1000.0 + 0.5 * x + 0.1 * rng.normal(size=n)
    X = np.stack([np.ones(n), x], axis=1).astype(np.float32)
    m = sg.lm_fit(X, y.astype(np.float32), mesh=mesh8)
    assert 0.9 < m.r_squared <= 1.0


def test_intercept_detection_scans_all_rows(mesh1, rng):
    n = 3000
    flag = np.zeros(n)
    flag[:2000] = 1.0  # first 1024+ rows all ones, but NOT constant overall
    X = np.stack([flag, rng.normal(size=n)], axis=1)
    y = X @ [1.0, 2.0] + 0.1 * rng.normal(size=n)
    m = sg.lm_fit(X, y, mesh=mesh1)
    assert not m.has_intercept


def test_criterion_validated(mesh1, rng):
    X = rng.normal(size=(50, 2))
    y = rng.normal(size=50)
    with pytest.raises(ValueError, match="criterion"):
        sg.glm_fit(X, y, family="gaussian", criterion="rel", mesh=mesh1)


def test_lm_weights_by_column_name(mesh1, rng):
    n = 200
    d = {"y": rng.normal(size=n), "x": rng.normal(size=n),
         "w": rng.uniform(0.5, 2.0, size=n)}
    m = sg.lm("y ~ x", d, weights="w", mesh=mesh1)
    m2 = sg.lm("y ~ x", d, weights=d["w"], mesh=mesh1)
    np.testing.assert_allclose(m.coefficients, m2.coefficients, rtol=1e-12)


def test_null_deviance_no_intercept(mesh1, rng):
    """R: null mu = linkinv(0) for a no-intercept, no-offset model."""
    n = 400
    x = rng.normal(size=n)
    y = rng.poisson(np.exp(0.3 * x)).astype(float)
    m = sg.glm("y ~ 0 + x", {"y": y, "x": x}, family="poisson", mesh=mesh1)
    # null deviance at mu = exp(0) = 1 for every row
    from oracle import F
    expected = F.make("poisson")["dev"](y, np.ones(n), np.ones(n)).sum()
    np.testing.assert_allclose(m.null_deviance, expected, rtol=1e-6)
    assert m.df_null == n


def test_null_deviance_with_offset(mesh1, rng):
    """R: with an offset, the null model is intercept-only IRLS honouring it."""
    n = 500
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    X = np.stack([np.ones(n), x], axis=1)
    m = sg.glm_fit(X, y, family="poisson", offset=off, tol=1e-10, mesh=mesh1)
    # oracle: intercept-only fit with the offset
    _, null_dev_ref, _, _ = irls_np(np.ones((n, 1)), y, "poisson", "log", offset=off)
    np.testing.assert_allclose(m.null_deviance, null_dev_ref, rtol=1e-7)


# ---------------------------------------------------------------------------
# r15 review findings: serving-plane dispatch protection + WAL ordering
# ---------------------------------------------------------------------------

import asyncio
import threading
import time

from sparkglm_tpu.obs.metrics import MetricsRegistry
from sparkglm_tpu.online import OnlineJournal, OnlineLoop
from sparkglm_tpu.robust import ReplicaUnavailable
from sparkglm_tpu.serve import AsyncEngine, EnginePolicy, HealthPolicy


class _ParkScorer:
    """Duck scorer: calls in ``park`` (by call number) block on the
    shared release event; calls in ``slow`` sleep ``slow_s`` first."""

    metrics = None
    name = "park"

    def __init__(self, n_replicas=2, park=(), slow=(), slow_s=0.0):
        self.n_replicas = n_replicas
        self.park = set(park)
        self.slow = set(slow)
        self.slow_s = slow_s
        self.calls = 0
        self.release = threading.Event()
        self._lock = threading.Lock()

    def score(self, data, *, offset=None):
        with self._lock:
            self.calls += 1
            mine = self.calls
        if mine in self.park:
            assert self.release.wait(30)
        elif mine in self.slow:
            time.sleep(self.slow_s)
        return np.full(data.shape[0], float(mine))


def test_acquire_retry_reoffers_mid_cooldown_replica():
    """Review high: _acquire_retry must not hold an untried mid-cooldown
    replica forever — it is re-offered by timer, so a re-dispatch whose
    only untried replica is ejected waits out the cooldown and probes it
    instead of deadlocking the scheduler."""
    sc = _ParkScorer(n_replicas=2)
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), name="park",
                      health=HealthPolicy(eject_after=1,
                                          probe_cooldown_s=0.3))
    try:
        # eject replica 0 (replica 1 healthy, so the breaker may open)
        eng.health.on_failure(0, RuntimeError("boom"))
        assert eng.health.state(0) == "ejected"

        async def drive():
            # simulate the moment right after replica 1 failed a batch:
            # the free queue holds only the ejected replica 0
            while True:
                try:
                    eng._free.get_nowait()
                except asyncio.QueueEmpty:
                    break
            eng._free.put_nowait(0)
            return await eng._acquire_retry([1])

        t0 = time.perf_counter()
        got = asyncio.run_coroutine_threadsafe(drive(), eng._loop).result(10)
        waited = time.perf_counter() - t0
        assert got == 0, "the probing replica must be acquired"
        assert waited < 5.0
        assert eng.health.state(0) == "probing"

        async def restore():
            eng._free.put_nowait(0)
            eng._free.put_nowait(1)

        asyncio.run_coroutine_threadsafe(restore(), eng._loop).result(10)
    finally:
        eng.close()


def test_hedge_gets_its_own_watchdog_deadline():
    """Review medium: a hedge launched at start+hedge_after_s gets a
    full call_timeout_s of runtime — it is not abandoned at the
    PRIMARY's deadline, and a slow-but-healthy hedge replica is not
    charged a spurious watchdog failure."""
    sc = _ParkScorer(n_replicas=2, park={1}, slow={2}, slow_s=1.0)
    met = MetricsRegistry()
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), metrics=met,
                      name="park",
                      health=HealthPolicy(call_timeout_s=1.2,
                                          hedge_after_s=0.3))
    try:
        f = eng.submit(np.zeros((2, 2)))
        # primary (call 1) hangs; hedge (call 2) runs 1.0s — past the
        # primary's deadline-anchored leftover (1.2 - 0.3 = 0.9s) but
        # inside its own 1.2s budget, so it must win
        res = f.result(10)
        np.testing.assert_array_equal(res, np.full(2, 2.0))
        states = sorted(eng.health.states().values())
        assert states == ["healthy", "suspect"], \
            "only the hung primary is charged a watchdog failure"
    finally:
        sc.release.set()
        eng.close()
    snap = met.snapshot()["counters"]
    assert snap["serve.park.hedges"] == 1
    assert snap.get("serve.park.redispatches", 0) == 0
    assert sc.calls == 2


def test_abandoned_calls_beyond_slack_hold_their_index():
    """Review low: the worker pool has n_replicas + slack workers; once
    ``slack`` abandoned calls are running, the next abandonment HOLDS
    its replica index until the hung call returns, so dispatches queue
    on the index (visible, bounded) instead of on an exhausted pool."""
    sc = _ParkScorer(n_replicas=1, park={1, 2, 3, 4})
    eng = AsyncEngine(sc, EnginePolicy(max_wait_ms=0), name="park",
                      health=HealthPolicy(call_timeout_s=0.15,
                                          eject_after=100))
    assert eng._abandon_slack == 3
    assert eng._pool._max_workers == 4
    doomed = []
    try:
        # sequential: each request hangs alone (no batching) and is
        # abandoned before the next is admitted
        for k in range(1, 5):
            doomed.append(eng.submit(np.zeros((1, 2))))
            deadline = time.time() + 20
            while eng._abandoned < k and time.time() < deadline:
                time.sleep(0.02)
        assert eng._abandoned == 4
        assert eng._abandoned_recycled == 3, \
            "the 4th abandonment is past the slack bound"
        for f in doomed:
            with pytest.raises(ReplicaUnavailable):
                f.result(10)
        # the single replica index is held by the 4th hung call: new
        # work stays queued rather than dispatching into a full pool
        late = eng.submit(np.zeros((1, 2)))
        time.sleep(0.3)
        assert not late.done()
        sc.release.set()                  # hung calls return, index freed
        np.testing.assert_array_equal(late.result(10), np.full(1, 5.0))
        deadline = time.time() + 10
        while eng._abandoned > 0 and time.time() < deadline:
            time.sleep(0.02)
        assert eng._abandoned == 0 and eng._abandoned_recycled == 0
    finally:
        sc.release.set()
        eng.close()


def test_journal_withdraws_record_for_rejected_chunk(rng, tmp_path):
    """Review low: a chunk step() rejects before mutating state must not
    leave a WAL record — resume would replay input the live run never
    absorbed."""
    from test_selfheal import _tiny_chunk, _tiny_loop

    d = str(tmp_path / "j")
    loop = _tiny_loop(rng, journal=OnlineJournal(d, snapshot_every=100))
    loop.step(*_tiny_chunk(rng, 0))
    ten, X, y = _tiny_chunk(rng, 1)
    bad = np.array(["nope"] * len(ten))
    with pytest.raises(KeyError, match="unknown tenant"):
        loop.step(bad, X, y)
    assert loop._chunks == 1
    assert loop.journal.withdrawals == 1
    recs = [c for c, _ in loop.journal.records()]
    assert recs == [1], "the rejected chunk's record must be withdrawn"
    # the next good chunk reuses the chunk number cleanly
    loop.step(ten, X, y)
    assert [c for c, _ in loop.journal.records()] == [1, 2]
    resumed = OnlineLoop.resume(OnlineJournal(d, snapshot_every=100))
    assert resumed._chunks == 2
    assert resumed.suffstats.digest() == loop.suffstats.digest()
