"""Regression tests for review findings on the foundation commit."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from oracle import irls_np
from sparkglm_tpu.data.formula import parse_formula


def test_formula_rejects_unsupported_syntax():
    # interactions ':'/'*' and whitelisted transforms (log(x), I(x^2)) are
    # supported since r2; bare '^', numerals, free parentheses and unknown
    # functions still fail loudly
    for bad in ("y ~ x^2", "y ~ x + 2", "y ~ (a + b)", "y ~ poly(x)"):
        with pytest.raises(ValueError):
            parse_formula(bad)


def test_formula_rejects_multidigit_numerals():
    """'10' must not tokenize as '1','0' and silently drop the intercept."""
    for bad in ("y ~ x + 10", "y ~ x + 11", "y ~ x - 10", "y ~ 100 + x"):
        with pytest.raises(ValueError, match="numeric term"):
            parse_formula(bad)
    assert parse_formula("y ~ x + 1").intercept
    assert not parse_formula("y ~ x - 1").intercept
    assert not parse_formula("y ~ x + 0").intercept


def test_nan_weight_column_row_dropped(mesh1, rng):
    """A NaN in a by-name weights column drops the row (R model-frame
    semantics) instead of producing all-NaN coefficients."""
    n = 200
    d = {"y": rng.normal(size=n), "x": rng.normal(size=n),
         "w": rng.uniform(0.5, 2.0, size=n)}
    d["w"][7] = np.nan
    m = sg.lm("y ~ x", d, weights="w", mesh=mesh1)
    assert np.all(np.isfinite(m.coefficients))
    assert m.n_obs == n - 1
    keep = np.ones(n, bool)
    keep[7] = False
    m_ref = sg.lm("y ~ x", {k: v[keep] for k, v in d.items()},
                  weights="w", mesh=mesh1)
    np.testing.assert_allclose(m.coefficients, m_ref.coefficients, rtol=1e-12)


def test_array_offset_realigned_after_na_omit(mesh8, rng):
    """Array-valued offset/weights get the same keep-mask as the design."""
    n = 200
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    d = {"y": y, "x": x.copy()}
    d["x"][5] = np.nan
    m = sg.glm("y ~ x", d, family="poisson", offset=off, mesh=mesh8)
    keep = np.ones(n, bool)
    keep[5] = False
    m_ref = sg.glm("y ~ x", {k: v[keep] for k, v in d.items()},
                   family="poisson", offset=off[keep], mesh=mesh8)
    np.testing.assert_allclose(m.coefficients, m_ref.coefficients, rtol=1e-10)
    # wrong-length extras fail loudly at both API levels
    with pytest.raises(ValueError, match="offset"):
        sg.glm("y ~ x", d, family="poisson", offset=off[:-3], mesh=mesh8)
    with pytest.raises(ValueError, match="weights"):
        sg.glm_fit(np.stack([np.ones(n), x], 1), y,
                   family="poisson", weights=np.ones(n + 1), mesh=mesh8)


def test_nan_offset_column_row_dropped(mesh1, rng):
    n = 300
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    d = {"y": y, "x": x, "off": off}
    d["off"][11] = np.nan
    m = sg.glm("y ~ x", d, family="poisson", offset="off", mesh=mesh1)
    assert np.all(np.isfinite(m.coefficients))
    assert m.n_obs == n - 1


def test_predict_int_design(mesh1, rng):
    X = rng.normal(size=(50, 2))
    X[:, 0] = 1.0
    y = X @ [0.5, 0.25] + 0.01 * rng.normal(size=50)
    m = sg.lm_fit(X, y, mesh=mesh1)
    Xi = np.array([[1, 25], [1, 30]])  # int64 design
    np.testing.assert_allclose(m.predict(Xi), Xi.astype(float) @ m.coefficients,
                               rtol=1e-5)


def test_r_squared_large_offset_mean(mesh8, rng):
    """float32-unsafe one-pass SST would destroy R^2 at mean >> std."""
    n = 4000
    x = rng.normal(size=n)
    y = 1000.0 + 0.5 * x + 0.1 * rng.normal(size=n)
    X = np.stack([np.ones(n), x], axis=1).astype(np.float32)
    m = sg.lm_fit(X, y.astype(np.float32), mesh=mesh8)
    assert 0.9 < m.r_squared <= 1.0


def test_intercept_detection_scans_all_rows(mesh1, rng):
    n = 3000
    flag = np.zeros(n)
    flag[:2000] = 1.0  # first 1024+ rows all ones, but NOT constant overall
    X = np.stack([flag, rng.normal(size=n)], axis=1)
    y = X @ [1.0, 2.0] + 0.1 * rng.normal(size=n)
    m = sg.lm_fit(X, y, mesh=mesh1)
    assert not m.has_intercept


def test_criterion_validated(mesh1, rng):
    X = rng.normal(size=(50, 2))
    y = rng.normal(size=50)
    with pytest.raises(ValueError, match="criterion"):
        sg.glm_fit(X, y, family="gaussian", criterion="rel", mesh=mesh1)


def test_lm_weights_by_column_name(mesh1, rng):
    n = 200
    d = {"y": rng.normal(size=n), "x": rng.normal(size=n),
         "w": rng.uniform(0.5, 2.0, size=n)}
    m = sg.lm("y ~ x", d, weights="w", mesh=mesh1)
    m2 = sg.lm("y ~ x", d, weights=d["w"], mesh=mesh1)
    np.testing.assert_allclose(m.coefficients, m2.coefficients, rtol=1e-12)


def test_null_deviance_no_intercept(mesh1, rng):
    """R: null mu = linkinv(0) for a no-intercept, no-offset model."""
    n = 400
    x = rng.normal(size=n)
    y = rng.poisson(np.exp(0.3 * x)).astype(float)
    m = sg.glm("y ~ 0 + x", {"y": y, "x": x}, family="poisson", mesh=mesh1)
    # null deviance at mu = exp(0) = 1 for every row
    from oracle import F
    expected = F.make("poisson")["dev"](y, np.ones(n), np.ones(n)).sum()
    np.testing.assert_allclose(m.null_deviance, expected, rtol=1e-6)
    assert m.df_null == n


def test_null_deviance_with_offset(mesh1, rng):
    """R: with an offset, the null model is intercept-only IRLS honouring it."""
    n = 500
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    X = np.stack([np.ones(n), x], axis=1)
    m = sg.glm_fit(X, y, family="poisson", offset=off, tol=1e-10, mesh=mesh1)
    # oracle: intercept-only fit with the offset
    _, null_dev_ref, _, _ = irls_np(np.ones((n, 1)), y, "poisson", "log", offset=off)
    np.testing.assert_allclose(m.null_deviance, null_dev_ref, rtol=1e-7)
