"""Regression tests for review findings on the foundation commit."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from oracle import irls_np
from sparkglm_tpu.data.formula import parse_formula


def test_formula_rejects_interactions():
    for bad in ("y ~ x1*x2", "y ~ x1:x2", "y ~ x^2", "y ~ x + 2"):
        with pytest.raises(ValueError):
            parse_formula(bad)


def test_predict_int_design(mesh1, rng):
    X = rng.normal(size=(50, 2))
    X[:, 0] = 1.0
    y = X @ [0.5, 0.25] + 0.01 * rng.normal(size=50)
    m = sg.lm_fit(X, y, mesh=mesh1)
    Xi = np.array([[1, 25], [1, 30]])  # int64 design
    np.testing.assert_allclose(m.predict(Xi), Xi.astype(float) @ m.coefficients,
                               rtol=1e-5)


def test_r_squared_large_offset_mean(mesh8, rng):
    """float32-unsafe one-pass SST would destroy R^2 at mean >> std."""
    n = 4000
    x = rng.normal(size=n)
    y = 1000.0 + 0.5 * x + 0.1 * rng.normal(size=n)
    X = np.stack([np.ones(n), x], axis=1).astype(np.float32)
    m = sg.lm_fit(X, y.astype(np.float32), mesh=mesh8)
    assert 0.9 < m.r_squared <= 1.0


def test_intercept_detection_scans_all_rows(mesh1, rng):
    n = 3000
    flag = np.zeros(n)
    flag[:2000] = 1.0  # first 1024+ rows all ones, but NOT constant overall
    X = np.stack([flag, rng.normal(size=n)], axis=1)
    y = X @ [1.0, 2.0] + 0.1 * rng.normal(size=n)
    m = sg.lm_fit(X, y, mesh=mesh1)
    assert not m.has_intercept


def test_criterion_validated(mesh1, rng):
    X = rng.normal(size=(50, 2))
    y = rng.normal(size=50)
    with pytest.raises(ValueError, match="criterion"):
        sg.glm_fit(X, y, family="gaussian", criterion="rel", mesh=mesh1)


def test_lm_weights_by_column_name(mesh1, rng):
    n = 200
    d = {"y": rng.normal(size=n), "x": rng.normal(size=n),
         "w": rng.uniform(0.5, 2.0, size=n)}
    m = sg.lm("y ~ x", d, weights="w", mesh=mesh1)
    m2 = sg.lm("y ~ x", d, weights=d["w"], mesh=mesh1)
    np.testing.assert_allclose(m.coefficients, m2.coefficients, rtol=1e-12)


def test_null_deviance_no_intercept(mesh1, rng):
    """R: null mu = linkinv(0) for a no-intercept, no-offset model."""
    n = 400
    x = rng.normal(size=n)
    y = rng.poisson(np.exp(0.3 * x)).astype(float)
    m = sg.glm("y ~ 0 + x", {"y": y, "x": x}, family="poisson", mesh=mesh1)
    # null deviance at mu = exp(0) = 1 for every row
    from oracle import F
    expected = F.make("poisson")["dev"](y, np.ones(n), np.ones(n)).sum()
    np.testing.assert_allclose(m.null_deviance, expected, rtol=1e-6)
    assert m.df_null == n


def test_null_deviance_with_offset(mesh1, rng):
    """R: with an offset, the null model is intercept-only IRLS honouring it."""
    n = 500
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    X = np.stack([np.ones(n), x], axis=1)
    m = sg.glm_fit(X, y, family="poisson", offset=off, tol=1e-10, mesh=mesh1)
    # oracle: intercept-only fit with the offset
    _, null_dev_ref, _, _ = irls_np(np.ones((n, 1)), y, "poisson", "log", offset=off)
    np.testing.assert_allclose(m.null_deviance, null_dev_ref, rtol=1e-7)
