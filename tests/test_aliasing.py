"""Rank-deficient designs: R's aliasing rule (drop later dependent columns,
NaN coefficients) vs the explicit singular error."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def _collinear(rng, n=600):
    X = rng.normal(size=(n, 5))
    X[:, 0] = 1.0
    X[:, 3] = X[:, 1] + X[:, 2]  # aliased: later column dependent
    return X


def test_lm_singular_error_default(mesh8, rng):
    X = _collinear(rng)
    y = X[:, :3] @ [1.0, 0.5, -0.3] + 0.1 * rng.normal(size=len(X))
    with pytest.raises(np.linalg.LinAlgError, match="singular"):
        sg.lm_fit(X, y, mesh=mesh8)


def test_lm_drop_matches_reduced_fit(mesh8, rng):
    X = _collinear(rng)
    n = len(X)
    y = X[:, :3] @ [1.0, 0.5, -0.3] + 0.1 * rng.normal(size=n)
    m = sg.lm_fit(X, y, mesh=mesh8, singular="drop")
    assert np.isnan(m.coefficients[3]) and np.isnan(m.std_errors[3])
    assert list(m.aliased) == [False, False, False, True, False]
    keep = [0, 1, 2, 4]
    m_red = sg.lm_fit(X[:, keep], y, mesh=mesh8)
    np.testing.assert_allclose(m.coefficients[keep], m_red.coefficients,
                               rtol=1e-8)
    np.testing.assert_allclose(m.std_errors[keep], m_red.std_errors, rtol=1e-8)
    assert m.df_resid == n - 4  # rank, not p
    # predict ignores the NaN coefficient (reduced-basis semantics)
    pred = m.predict(X[:5])
    np.testing.assert_allclose(pred, m_red.predict(X[:5][:, keep]), rtol=1e-6)
    assert m.n_params == 5 and m.xnames == ("x0", "x1", "x2", "x3", "x4")


def test_glm_drop_aliased(mesh8, rng):
    X = _collinear(rng)
    n = len(X)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X[:, :3] @ [0.3, 0.5, -0.4])))).astype(float)
    with pytest.raises(np.linalg.LinAlgError):
        sg.glm_fit(X, y, family="binomial", mesh=mesh8)
    m = sg.glm_fit(X, y, family="binomial", mesh=mesh8, singular="drop",
                   tol=1e-10)
    assert np.isnan(m.coefficients[3])
    keep = [0, 1, 2, 4]
    m_red = sg.glm_fit(X[:, keep], y, family="binomial", mesh=mesh8, tol=1e-10)
    np.testing.assert_allclose(m.coefficients[keep], m_red.coefficients,
                               rtol=1e-7)
    np.testing.assert_allclose(m.deviance, m_red.deviance, rtol=1e-9)
    assert m.converged


def test_formula_api_drops_by_default(mesh8, rng):
    """Duplicated predictor through the formula front-end: R drops it."""
    n = 400
    x = rng.normal(size=n)
    d = {"y": x * 2 + 0.1 * rng.normal(size=n), "a": x, "b": x}  # b aliased
    m = sg.lm("y ~ a + b", d, mesh=mesh8)
    assert np.isnan(m.coefficients[list(m.xnames).index("b")])
    assert abs(m.coefficients[list(m.xnames).index("a")] - 2.0) < 0.1


def test_aliased_model_se_fit_not_nan(mesh8, rng):
    """se.fit on an aliased model uses the reduced basis, not NaN."""
    X = _collinear(rng)
    y = X[:, :3] @ [1.0, 0.5, -0.3] + 0.1 * rng.normal(size=len(X))
    m = sg.lm_fit(X, y, mesh=mesh8, singular="drop")
    fit, se = m.predict(X[:7], se_fit=True)
    assert np.all(np.isfinite(se)) and np.all(se > 0)
    keep = [0, 1, 2, 4]
    m_red = sg.lm_fit(X[:, keep], y, mesh=mesh8)
    _, se_red = m_red.predict(X[:7][:, keep], se_fit=True)
    np.testing.assert_allclose(se, se_red, rtol=1e-7)


def test_glm_drop_float64_derived_collinear(mesh1, rng):
    """f64 fits must detect a derived collinear column too (f64-accumulated
    rank check)."""
    n = 500
    X = rng.normal(size=(n, 4))
    X[:, 0] = 1.0
    X[:, 3] = 2.0 * X[:, 1] - X[:, 2]
    y = (rng.random(n) < 0.5).astype(float)
    m = sg.glm_fit(X, y, family="binomial", mesh=mesh1, singular="drop")
    assert np.isnan(m.coefficients[3])
    assert np.all(np.isfinite(m.coefficients[:3]))


def test_singular_validated(mesh1, rng):
    X = rng.normal(size=(50, 2))
    y = rng.normal(size=50)
    with pytest.raises(ValueError, match="singular"):
        sg.lm_fit(X, y, mesh=mesh1, singular="maybe")
    with pytest.raises(ValueError, match="singular"):
        sg.glm_fit(X, y, family="gaussian", mesh=mesh1, singular="whatever")
