"""Online continuous learning (sparkglm_tpu/online).

What must hold (ISSUE r13 / ROADMAP item 3):

  * closed form == refit: the decayed-suffstat gaussian re-solve equals a
    full fleet refit of the equivalent decayed-weight dataset to 1e-10;
  * warm == cold: a fleet refit warm-started via ``start=`` reaches the
    same f64 fixed point as a cold fit, and repeat warm refits at the
    fixed bucket compile nothing;
  * the e2e loop: a 64-tenant family served by an AsyncEngine while the
    loop ingests drifting chunks — the drift gate fires, refreshed
    members auto-deploy with ZERO steady-state recompiles, a seeded
    regression auto-rolls-back, and the trace-event sequence is
    deterministic;
  * resume: an OnlineLoop serialized mid-stream and resumed under
    ``prefetch=2`` is bit-identical to one that never stopped;
  * the deploy-history bound and the chunk tee ride along.
"""

import dataclasses

import jax
import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.pipeline import tee_source
from sparkglm_tpu.fleet import glm_fit_fleet
from sparkglm_tpu.fleet.kernel import fleet_kernel_cache_size
from sparkglm_tpu.obs import RingBufferSink
from sparkglm_tpu.obs.metrics import Histogram, tv_distance
from sparkglm_tpu.online import DriftGate, OnlineLoop, OnlineSuffStats
from sparkglm_tpu.serve import (AsyncEngine, EnginePolicy, ModelFamily,
                                family_score_cache_size)

pytestmark = pytest.mark.online

P = 3


def _labels(K):
    return tuple(f"t{i:02d}" for i in range(K))


def _chunk(labels, beta, rows_per, seed, noise=0.05):
    """One long-format chunk: ``rows_per`` gaussian rows per tenant."""
    r = np.random.default_rng(seed)
    ten, Xs, ys = [], [], []
    for k, t in enumerate(labels):
        X = r.normal(size=(rows_per, P))
        ten.extend([t] * rows_per)
        Xs.append(X)
        ys.append(X @ beta[k] + noise * r.normal(size=rows_per))
    return np.array(ten), np.concatenate(Xs), np.concatenate(ys)


def _seed_family(labels, beta, name, n=64, seed=0):
    r = np.random.default_rng(seed)
    K = len(labels)
    X = r.normal(size=(K, n, P))
    y = np.stack([X[k] @ beta[k] + 0.05 * r.normal(size=n)
                  for k in range(K)])
    fleet = glm_fit_fleet(X, y, family="gaussian", link="identity",
                          labels=labels)
    return ModelFamily.from_fleet(fleet, name)


# ---------------------------------------------------------------------------
# sufficient statistics: closed form == decayed-weight full refit
# ---------------------------------------------------------------------------

def test_closed_form_solve_matches_decayed_refit():
    labels = _labels(6)
    rng = np.random.default_rng(3)
    beta = rng.normal(size=(6, P))
    rho = 0.7
    ss = OnlineSuffStats.init(labels, P, rho=rho)
    chunks = [_chunk(labels, beta + 0.3 * c, 24, seed=50 + c)
              for c in range(5)]
    for ten, X, y in chunks:
        ss.update(ten, X, y)
    # the equivalent static dataset: chunk c's rows carry weight
    # rho^(C-1-c) — what C decay ticks leave behind
    C = len(chunks)
    ta = np.concatenate([c[0] for c in chunks])
    Xa = np.concatenate([c[1] for c in chunks])
    ya = np.concatenate([c[2] for c in chunks])
    wa = np.concatenate([np.full(len(c[2]), rho ** (C - 1 - i))
                         for i, c in enumerate(chunks)])
    full = glm_fit_fleet(
        np.stack([Xa[ta == t] for t in labels]),
        np.stack([ya[ta == t] for t in labels]),
        weights=np.stack([wa[ta == t] for t in labels]),
        family="gaussian", link="identity", labels=labels)
    np.testing.assert_allclose(ss.solve(),
                               np.asarray(full.coefficients, np.float64),
                               rtol=0, atol=1e-10)


def test_suffstats_decay_offset_and_guards():
    labels = _labels(3)
    ss = OnlineSuffStats.init(labels, P, rho=0.5)
    ten, X, y = _chunk(labels, np.zeros((3, P)), 8, seed=1)
    off = np.full(len(y), 0.25)
    ss.update(ten, X, y, offset=off)
    ss2 = OnlineSuffStats.init(labels, P, rho=0.5)
    ss2.update(ten, X, y - off)
    np.testing.assert_array_equal(ss.r, ss2.r)
    # a tenant absent from a chunk still forgets (one global clock)
    w0 = ss.wsum.copy()
    ss.update(ten[:8], X[:8], y[:8])  # only t00 present
    assert np.all(ss.wsum[1:] == 0.5 * w0[1:])
    with pytest.raises(KeyError, match="unknown tenant"):
        ss.update(["nope"] * 4, X[:4], y[:4])
    with pytest.raises(ValueError, match="rho"):
        OnlineSuffStats.init(labels, P, rho=1.5)
    # no-mass tenants come back NaN from solve, never garbage
    fresh = OnlineSuffStats.init(labels, P)
    assert np.all(np.isnan(fresh.solve()))


# ---------------------------------------------------------------------------
# warm-start legalization: warm == cold at the f64 fixed point
# ---------------------------------------------------------------------------

def test_fleet_warm_start_matches_cold_fixed_point():
    labels = _labels(6)
    rng = np.random.default_rng(7)
    K, n = len(labels), 96
    X = rng.normal(size=(K, n, P))
    beta = rng.normal(scale=0.8, size=(K, P))
    y = np.stack([(rng.uniform(size=n)
                   < 1 / (1 + np.exp(-X[k] @ beta[k]))).astype(float)
                  for k in range(K)])
    kw = dict(family="binomial", link="logit", labels=labels, tol=1e-12)
    cold = glm_fit_fleet(X, y, **kw)
    b_cold = np.asarray(cold.coefficients, np.float64)
    # warm from the cold solution: already at the fixed point
    warm = glm_fit_fleet(X, y, start=b_cold, **kw)
    np.testing.assert_allclose(np.asarray(warm.coefficients, np.float64),
                               b_cold, rtol=0, atol=1e-9)
    # warm from a perturbed start: converges to the SAME fixed point
    warm2 = glm_fit_fleet(X, y, start=b_cold + 0.3, **kw)
    np.testing.assert_allclose(np.asarray(warm2.coefficients, np.float64),
                               b_cold, rtol=0, atol=1e-9)
    # repeat warm refit at the same shapes compiles nothing
    base = fleet_kernel_cache_size()
    glm_fit_fleet(X, y, start=b_cold + 0.1, **kw)
    assert fleet_kernel_cache_size() - base == 0
    # shape validation stays loud
    with pytest.raises(ValueError, match=r"stacked \(K, p\)"):
        glm_fit_fleet(X, y, start=b_cold[:, :2], **kw)


def test_api_fleet_beta0_redirects_to_start():
    data = {"y": np.arange(8.0), "x": np.arange(8.0),
            "g": np.repeat(["a", "b"], 4)}
    with pytest.raises(ValueError, match="start="):
        sg.glm_fleet("y ~ x", data, groups="g", family="gaussian",
                     beta0=np.zeros(2))


# ---------------------------------------------------------------------------
# drift gate
# ---------------------------------------------------------------------------

def test_drift_gate_reference_freeze_fire_and_rearm():
    ring = RingBufferSink(64)
    from sparkglm_tpu.obs.trace import FitTracer
    tracer = FitTracer(sinks=[ring])
    gate = DriftGate(["a", "b"], threshold=0.5, reference_chunks=2,
                     window_chunks=2, min_count=4, tracer=tracer)
    r = np.random.default_rng(0)
    small = lambda: (np.abs(0.05 * r.normal(size=16)), 0.1, 16.0)
    big = lambda: (np.abs(5.0 + r.normal(size=16)), 50.0, 16.0)
    for _ in range(2):           # reference fills, then freezes
        assert gate.observe_chunk({"a": small(), "b": small()}) == ()
    assert gate.reference_frozen
    # stable live window: no fire
    for _ in range(2):
        out = gate.observe_chunk({"a": small(), "b": small()})
    assert out == ()
    # drifted live window: tenant b fires, a stays
    gate.observe_chunk({"a": small(), "b": big()})
    out = gate.observe_chunk({"a": small(), "b": big()})
    assert out == ("b",)
    assert [e.kind for e in ring.events].count("drift_detected") == 1
    ev = [e for e in ring.events if e.kind == "drift_detected"][0]
    assert ev.fields["first"] == "b" and ev.fields["tenants"] == 1
    # rearm: reference refills before anything can fire again
    gate.rearm()
    assert not gate.reference_frozen
    assert gate.observe_chunk({"a": big(), "b": big()}) == ()


def test_tv_distance_histograms():
    a, b = Histogram(), Histogram()
    assert tv_distance(a, b) == 0.0          # both empty: no evidence
    for v in (0.1, 0.2, 0.4):
        a.observe(v)
    assert tv_distance(a, b) == 1.0          # one empty: maximal
    for v in (0.1, 0.2, 0.4):
        b.observe(v)
    assert tv_distance(a, b) == 0.0
    b.observe(100.0)
    assert 0.0 < tv_distance(a, b) < 1.0


# ---------------------------------------------------------------------------
# the e2e loop: served family + drifting chunks + seeded regression
# ---------------------------------------------------------------------------

def test_online_loop_e2e_64_tenants():
    K = 64
    labels = _labels(K)
    rng = np.random.default_rng(11)
    beta_a = rng.normal(size=(K, P))
    beta_b = beta_a + 2.5
    beta_c = beta_b - 5.0
    fam = _seed_family(labels, beta_a, "e2e", seed=11)
    ring = RingBufferSink(4096)
    loop = OnlineLoop(fam, rho=0.4, window_rows=64, drift_threshold=0.6,
                      reference_chunks=2, window_chunks=2, min_count=4,
                      watch_chunks=2, trace=ring)

    rsc = fam.replicated_scorer(devices=jax.devices()[:2], min_bucket=8)
    rsc.warmup(buckets=(8,))
    assert rsc.compiles == 0
    Xq = rng.normal(size=(5, P))

    def served(tenant):
        with AsyncEngine(rsc, EnginePolicy(max_wait_ms=2)) as eng:
            return eng.submit(Xq, tenant=tenant).result(30)

    # phase 1: reference + stable traffic, then drift episode 1
    for c in range(4):
        out = loop.step(*_chunk(labels, beta_a, 16, seed=100 + c))
        assert out["drifted"] == () and out["rolled_back"] == ()
    np.testing.assert_allclose(served(labels[0]),
                               Xq @ fam.deployed_matrix()[1][0], rtol=1e-12)
    deployed1 = ()
    for c in range(2):
        out = loop.step(*_chunk(labels, beta_b, 16, seed=200 + c))
        deployed1 = deployed1 or out["deployed"]
    assert deployed1, "drift episode 1 never deployed"
    v1 = {t: fam.deployed_version(t) for t in deployed1}
    assert all(v > 1 for v in v1.values())
    # the engine follows the deploy recompile-free, mid-flight
    np.testing.assert_allclose(served(deployed1[0]),
                               Xq @ fam.deployed_matrix()[1][
                                   labels.index(deployed1[0])], rtol=1e-12)

    # phase 2 is the steady state: everything below must compile NOTHING
    kernel_base = fleet_kernel_cache_size()
    score_base = family_score_cache_size()
    compiles_base = rsc.compiles

    # re-reference (post-rearm) + stable window, then drift episode 2
    for c in range(4):
        out = loop.step(*_chunk(labels, beta_b, 16, seed=300 + c))
        assert out["drifted"] == ()
    deployed2 = ()
    for c in range(2):
        out = loop.step(*_chunk(labels, beta_c, 16, seed=400 + c))
        deployed2 = deployed2 or out["deployed"]
    assert deployed2, "drift episode 2 never deployed"
    np.testing.assert_allclose(served(deployed2[0]),
                               Xq @ fam.deployed_matrix()[1][
                                   labels.index(deployed2[0])], rtol=1e-12)
    # let the episode-2 watch expire on healthy chunks
    for c in range(2):
        loop.step(*_chunk(labels, beta_c, 16, seed=500 + c))

    # seeded regression: a manually deployed bad champion rolls back on
    # the next chunk that shows it regressing
    bad_t = labels[0]
    good_v = fam.deployed_version(bad_t)
    bad = dataclasses.replace(
        fam.model(bad_t),
        coefficients=np.asarray(fam.model(bad_t).coefficients) + 25.0)
    loop.deploy(bad_t, bad)
    out = loop.step(*_chunk(labels, beta_c, 16, seed=600))
    assert out["rolled_back"] == (bad_t,)
    assert fam.deployed_version(bad_t) == good_v

    assert fleet_kernel_cache_size() - kernel_base == 0, \
        "steady-state refresh must not compile"
    assert family_score_cache_size() - score_base == 0, \
        "steady-state scoring/gating must not compile"
    assert rsc.compiles == compiles_base == 0

    # deterministic trace-event sequence: collapse runs of equal kinds
    online_kinds = ("chunk_ingested", "drift_detected", "refresh_start",
                    "refresh_end", "auto_deploy", "auto_rollback")
    seq = [e for e in ring.events if e.kind in online_kinds]
    collapsed = [k for i, k in enumerate(e.kind for e in seq)
                 if i == 0 or seq[i - 1].kind != k]
    assert collapsed == [
        "chunk_ingested", "drift_detected", "refresh_start", "refresh_end",
        "auto_deploy",                                   # episode 1
        "chunk_ingested", "drift_detected", "refresh_start", "refresh_end",
        "auto_deploy",                                   # episode 2
        "chunk_ingested", "auto_rollback",               # seeded regression
    ]
    refresh_ends = [e for e in seq if e.kind == "refresh_end"]
    assert [e.fields["mode"] for e in refresh_ends] == ["closed_form"] * 2
    assert refresh_ends[1].fields["executables"] == 0
    rb = [e for e in seq if e.kind == "auto_rollback"]
    assert len(rb) == 1 and rb[0].fields["tenant"] == bad_t
    deploys = [e for e in seq if e.kind == "auto_deploy"]
    assert {e.fields["tenant"] for e in deploys} >= set(deployed2)
    rep = loop.report()["online"]
    assert rep["drift_detected"] == 2 and rep["refreshes"] == 2
    assert rep["auto_rollbacks"] == 1
    assert rep["auto_deploys"] == len(deploys)


# ---------------------------------------------------------------------------
# persistence: mid-stream resume under prefetch=2 is bit-identical
# ---------------------------------------------------------------------------

def test_loop_resume_bit_identical_under_prefetch(tmp_path):
    K = 8
    labels = _labels(K)
    rng = np.random.default_rng(23)
    beta_a = rng.normal(size=(K, P))
    beta_b = beta_a + 2.5

    def make_loop(name):
        fam = _seed_family(labels, beta_a, name, seed=23)
        return OnlineLoop(fam, rho=0.4, window_rows=32,
                          drift_threshold=0.45, reference_chunks=2,
                          window_chunks=2, min_count=4, watch_chunks=2)

    chunks = ([_chunk(labels, beta_a, 16, seed=700 + c) for c in range(4)]
              + [_chunk(labels, beta_b, 16, seed=800 + c)
                 for c in range(4)])

    # the uninterrupted oracle
    loop_full = make_loop("full")
    for ch in chunks:
        loop_full.step(*ch)

    # interrupted twin: 4 chunks, serialize, resume, stream the rest
    # through run(prefetch=2)
    loop_a = make_loop("twin")
    for ch in chunks[:4]:
        loop_a.step(*ch)
    path = str(tmp_path / "loop.npz")
    loop_a.save(path)
    loop_b = OnlineLoop.load(path)
    loop_b.run(lambda: iter(chunks[4:]), prefetch=2)

    assert loop_b.suffstats.G.tobytes() == loop_full.suffstats.G.tobytes()
    assert loop_b.suffstats.r.tobytes() == loop_full.suffstats.r.tobytes()
    assert (loop_b.suffstats.wsum.tobytes()
            == loop_full.suffstats.wsum.tobytes())
    for attr in ("_Xw", "_yw", "_ww", "_ow", "_pos"):
        assert (getattr(loop_b, attr).tobytes()
                == getattr(loop_full, attr).tobytes()), attr
    assert loop_b.gate._export() == loop_full.gate._export()
    assert loop_b._watch == loop_full._watch
    tb, Bb = loop_b.family.deployed_matrix()
    tf, Bf = loop_full.family.deployed_matrix()
    assert tb == tf and Bb.tobytes() == Bf.tobytes()
    assert ({t: loop_b.family.deployed_version(t) for t in labels}
            == {t: loop_full.family.deployed_version(t) for t in labels})
    # and the artifact itself is byte-deterministic across a round trip
    p2 = str(tmp_path / "again.npz")
    loop_b.save(p2)
    OnlineLoop.load(p2).save(str(tmp_path / "thrice.npz"))
    assert (open(p2, "rb").read()
            == open(str(tmp_path / "thrice.npz"), "rb").read())


# ---------------------------------------------------------------------------
# journal compaction safety: snapshot-prune vs in-flight append
# ---------------------------------------------------------------------------

def test_journal_snapshot_prune_never_drops_inflight_append(tmp_path):
    """Hammer concurrent append/snapshot on one journal: the snapshot's
    prune scan must never unlink a record newer than the snapshot's
    chunk, no matter how the two writers interleave — after every
    snapshot the journal still covers [snap+1 .. newest] gap-free, so
    resume never loses an applied-but-unsnapshotted chunk."""
    import threading

    from sparkglm_tpu.online import OnlineJournal

    labels = _labels(4)
    fam = _seed_family(labels, np.zeros((4, P)), "race", n=16, seed=5)
    loop = OnlineLoop(fam, window_rows=8)
    j = OnlineJournal(tmp_path / "wal", snapshot_every=1)

    rng = np.random.default_rng(0)
    X = rng.normal(size=(4, P))
    y = np.zeros(4)
    tenants = np.array([labels[0]] * 4)

    appended = []               # append order == chunk order (one writer)
    errors = []
    stop = threading.Event()

    def writer():
        try:
            c = 0
            while not stop.is_set() and c < 400:
                c += 1
                j.append(c, tenants, X, y)
                appended.append(c)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
        finally:
            stop.set()

    def snapshotter():
        try:
            while not stop.is_set():
                if not appended:
                    continue
                snap_c = appended[-1]
                loop._chunks = snap_c
                j.snapshot(loop)
                # the invariant under fire: everything newer than the
                # snapshot survived the prune that just ran
                newest = appended[-1]
                have = {c for c, _ in j.records(after=snap_c)}
                missing = set(range(snap_c + 1, newest + 1)) - have
                assert not missing, (snap_c, newest, missing)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
            stop.set()

    ts = [threading.Thread(target=writer),
          threading.Thread(target=snapshotter)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errors, errors
    assert j.snapshots > 3  # the hammer genuinely interleaved
    # terminal state: latest snapshot + surviving records cover the
    # stream gap-free up to the newest append
    snap_c, _ = j.latest_snapshot()
    recs = [c for c, _ in j.records(after=snap_c)]
    assert recs == list(range(snap_c + 1, appended[-1] + 1))


# ---------------------------------------------------------------------------
# satellites: history bound, chunk tee, front-end
# ---------------------------------------------------------------------------

def test_family_history_bound_and_unbounded_opt_in():
    labels = _labels(2)
    beta = np.zeros((2, P))
    fam = _seed_family(labels, beta, "bound", seed=1)
    capped = ModelFamily("capped", history_cap=4)
    unbounded = ModelFamily("unbounded", history_cap=None)
    mdl = fam.model(labels[0])
    for f in (capped, unbounded):
        f.register("a", mdl)
        for _ in range(20):
            f.register("a", mdl, deploy=True)
    _, meta_c = capped._export()
    _, meta_u = unbounded._export()
    assert len(meta_c["history"]["a"]) == 4          # bounded
    assert len(meta_u["history"]["a"]) == 21         # opt-in: everything
    # rollback still works at the bound
    capped.rollback("a")
    with pytest.raises(ValueError, match="history_cap"):
        ModelFamily("tiny", history_cap=1)
    # the cap round-trips through serialization
    members, meta = capped._export()
    assert meta["history_cap"] == 4
    restored = ModelFamily._restore(members, dict(meta))
    assert restored.history_cap == 4


def test_tee_source_splits_one_stream():
    pulls = []

    def source():
        def it():
            for i in range(5):
                pulls.append(i)
                yield (np.array([f"t{i}"]), np.ones((1, P)),
                       np.array([float(i)]))
        return it()

    a, b = tee_source(source, 2)
    ia, ib = a(), b()
    for i in range(5):
        ta, Xa, ya = next(ia)
        tb, Xb, yb = next(ib)
        assert ta[0] == tb[0] == f"t{i}"
        np.testing.assert_array_equal(ya, yb)
    assert pulls == [0, 1, 2, 3, 4]  # the underlying stream ran ONCE
    with pytest.raises(StopIteration):
        next(ia)
    # a branch lagging past max_lag fails loudly instead of buffering
    # without bound
    c, d = tee_source(source, 2, max_lag=2)
    ic = c()
    next(ic), next(ic)
    with pytest.raises(RuntimeError, match="max_lag"):
        next(ic)


def test_online_fleet_frontend(rng):
    n = 240
    g = np.repeat([f"g{i}" for i in range(6)], n // 6)
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    y = 1.0 + 2.0 * x1 - x2 + 0.05 * rng.normal(size=n)
    loop = sg.online_fleet("y ~ x1 + x2", {"y": y, "x1": x1, "x2": x2,
                                           "seg": g},
                           groups="seg", family="gaussian", rho=0.5,
                           window_rows=32, reference_chunks=2,
                           window_chunks=2, min_count=4)
    assert isinstance(loop, sg.OnlineLoop)
    assert loop.is_closed_form and loop.K == 6 and loop.p == 3
    X = np.column_stack([np.ones(12), rng.normal(size=(12, 2))])
    out = loop.step(np.repeat(["g0", "g1"], 6), X,
                    X @ [1.0, 2.0, -1.0])
    assert out["chunk"] == 1
    assert loop.report()["online"]["chunks"] == 1
    # the family is the serving handle
    assert loop.family.deployed_matrix()[1].shape == (6, 3)
