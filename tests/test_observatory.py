"""Capacity observatory: cost-model gauges, memory/compile ledgers,
cross-process spool aggregation, and longitudinal bench history.

Covers the obs/profile.py + obs/aggregate.py + obs/history.py stack and
its Telemetry facade wiring, including the acceptance contracts:

  * prometheus_text edge cases — empty registry, label escaping,
    histogram cumulative-bucket monotonicity;
  * cross-process aggregation with TWO REAL OS PROCESSES spooling
    concurrently: merged stream seq-coherent per process, no
    interleaving corruption, rollups equal per-process sums;
  * bench_history flags a synthetically injected regression and stays
    quiet on the repo's real BENCH_r*.json trajectory.
"""

import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu import obs
from sparkglm_tpu.obs.aggregate import merge_spools, rollup_snapshots
from sparkglm_tpu.obs.history import (BLOCKS, bench_history, extract_block,
                                      regression_gate, render_report)
from sparkglm_tpu.obs.metrics import MetricsRegistry
from sparkglm_tpu.obs.profile import (CompileLedger, CostModel, MemoryLedger,
                                      Profiler, kernel_bytes, kernel_flops)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- cost model ---------------------------------------------------------------

def test_kernel_flops_orderings():
    kw = dict(rows=65536, cols=32, iters=4)
    einsum = kernel_flops("einsum", **kw)
    fused = kernel_flops("fused", **kw)
    qr = kernel_flops("qr", **kw)
    assert einsum == fused > 0          # same arithmetic, fewer X passes
    assert qr > einsum                  # householder beats the Gramian
    # fused streams X once per iteration, einsum twice
    assert kernel_bytes("fused", **kw) < kernel_bytes("einsum", **kw)
    # fleet scales with the padded model bucket
    assert (kernel_flops("fleet", rows=512, cols=8, iters=4, models=256)
            == 256 * kernel_flops("fleet", rows=512, cols=8, iters=4))
    # scorer dispatch is a matvec, linear in both dims
    assert (kernel_flops("scorer", rows=256, cols=32)
            == 2 * kernel_flops("scorer", rows=128, cols=32))


def test_sketch_flops_scale_with_sketch_dim_and_refine():
    base = kernel_flops("sketch", rows=40000, cols=1024, sketch_dim=4096)
    refined = kernel_flops("sketch", rows=40000, cols=1024,
                           sketch_dim=4096, sketch_refine=8)
    assert refined > base
    assert kernel_bytes("sketch", rows=40000, cols=1024,
                        sketch_refine=8) > kernel_bytes(
        "sketch", rows=40000, cols=1024)


def test_cost_model_fractions_are_finite_and_positive():
    cm = CostModel("cpu")
    flops = kernel_flops("einsum", rows=4096, cols=16, iters=4)
    assert 0 < cm.mfu(flops, 0.01) < 1e6
    assert cm.mfu(flops, 0.0) == 0.0
    assert cm.bandwidth_frac(1e6, 0.001) > 0
    # explicit peaks override the platform table
    assert CostModel("cpu", peak_flops=2e11).mfu(flops, 0.01) == \
        pytest.approx(cm.mfu(flops, 0.01) / 2)


# -- profiler + ledgers through the facade ------------------------------------

def test_profiler_prices_solve_and_scorer_events():
    tel = obs.Telemetry()
    tel.tracer.emit("solve", target="irls_kernel", gramian_engine="einsum",
                    rows=65536, cols=32, iters=4, seconds=0.02)
    tel.tracer.emit("scorer_kernel", target="serve:t", rows=100, cols=32,
                    bucket=128, seconds=0.001)
    # unpriceable events are skipped silently (no shape stamp)
    tel.tracer.emit("solve", target="irls_kernel", gramian_engine="einsum",
                    seconds=0.02)
    prom = tel.prometheus()
    for needle in ("profile_mfu_einsum", "profile_mfu_scorer",
                   "profile_bandwidth_frac_einsum", "profile_mfu_last",
                   "profile_flops_einsum", "profile_solve_s_einsum"):
        assert needle in prom, needle
    rep = tel.profiler.report()
    assert rep["flavors"]["einsum"]["calls"] == 1
    assert rep["flavors"]["scorer"]["calls"] == 1
    assert rep["flavors"]["einsum"]["mfu_avg"] > 0
    # the scorer priced the padded bucket (128), not the live rows (100)
    assert rep["flavors"]["scorer"]["flops"] == kernel_flops(
        "scorer", rows=128, cols=32)


def test_compile_ledger_attribution_and_steady_gauge():
    reg = MetricsRegistry()
    led = CompileLedger(reg)
    tr = obs.FitTracer([led], metrics=reg)
    tr.emit("compile", target="irls_kernel", gramian_engine="fused",
            bucket=65536, seconds=0.4)
    tr.emit("compile", target="fleet_kernel", gramian_engine="fleet",
            bucket=256, seconds=0.2)
    tr.emit("compile", target="serve:pool-e0", flavor="exact",
            bucket=128, seconds=0.1)
    assert led.steady_state_compiles == 0
    assert reg.gauge("compile_ledger.steady_state_compiles").value == 0
    keys = {(e["subsystem"], e["bucket"], e["flavor"])
            for e in led.report()["entries"]}
    assert ("models", "65536", "fused") in keys
    assert ("fleet", "256", "fleet") in keys
    assert ("serve", "128", "exact") in keys
    led.mark_steady()
    tr.emit("compile", target="irls_kernel", gramian_engine="fused",
            bucket=131072, seconds=0.3)
    assert led.steady_state_compiles == 1
    assert reg.gauge("compile_ledger.steady_state_compiles").value == 1
    assert led.report()["steady_events"][0]["subsystem"] == "models"


def test_memory_ledger_samples_and_scope():
    reg = MetricsRegistry()
    led = MemoryLedger(reg)
    s = led.sample("fit")
    assert s["bytes_in_use"] >= 0 and s["source"] in ("device", "host")
    with led.scope("engine"):
        _ = np.zeros(1000)
    snap = reg.snapshot()["gauges"]
    for g in ("memory.live_bytes", "memory.peak_bytes",
              "memory.fit.live_bytes", "memory.engine.delta_bytes",
              "memory.engine.peak_bytes"):
        assert g in snap, g


def test_glm_fit_populates_profile_gauges_end_to_end():
    rng = np.random.default_rng(0)
    X = np.column_stack([np.ones(512), rng.normal(size=(512, 3))])
    y = (rng.uniform(size=512) < 0.5).astype(float)
    tel = obs.Telemetry()
    sg.glm_fit(X, y, family="binomial", trace=tel.tracer)
    rep = tel.profiler.report()
    assert rep["flavors"], "no priced solve events from a real fit"
    assert "profile_mfu_last" in tel.prometheus()
    # compiles (if any, on a cold cache) were attributed, none steady
    assert tel.compile_ledger.steady_state_compiles == 0
    tel.mark_steady()
    # the models layer stamps every fit's first segment as "compile"
    # (wall incl. compilation); after mark_steady the ledger attributes
    # it — the zero-steady contract is enforced on the SERVING emitters,
    # which gate on the real executable-cache delta
    sg.glm_fit(X, y, family="binomial", trace=tel.tracer)
    ev = tel.compile_ledger.report()["steady_events"]
    assert all(e["subsystem"] == "models" for e in ev)


# -- prometheus_text edge cases (satellite 3) ---------------------------------

def test_prometheus_empty_registry():
    assert obs.prometheus_text(MetricsRegistry()) == "\n"


def test_prometheus_label_rendering_and_escaping():
    reg = MetricsRegistry()
    reg.gauge('profile.mfu{flavor=ein"s\\um,host=a\nb}').set(0.25)
    reg.counter("plain.counter").inc(2)
    txt = obs.prometheus_text(reg)
    assert ('profile_mfu{flavor="ein\\"s\\\\um",host="a\\nb"} 0.25'
            in txt)
    assert "# TYPE profile_mfu gauge" in txt
    assert "plain_counter 2" in txt  # unlabelled names render as before


def test_prometheus_type_line_once_per_family():
    reg = MetricsRegistry()
    reg.gauge("mfu{flavor=a}").set(1)
    reg.gauge("mfu{flavor=b}").set(2)
    txt = obs.prometheus_text(reg)
    assert txt.count("# TYPE mfu gauge") == 1
    assert 'mfu{flavor="a"} 1' in txt and 'mfu{flavor="b"} 2' in txt


def test_prometheus_histogram_buckets_cumulative_monotone():
    reg = MetricsRegistry()
    h = reg.histogram("lat{tenant=x}")
    for v in (0.5, 1.5, 3.0, 3.5, 100.0, 0.25):
        h.observe(v)
    txt = obs.prometheus_text(reg)
    counts = [int(m.group(2)) for m in re.finditer(
        r'lat_bucket\{tenant="x",le="([^"]+)"\} (\d+)', txt)]
    assert counts, "no bucket lines rendered"
    assert counts == sorted(counts), "cumulative buckets must be monotone"
    assert counts[-1] == 6  # +Inf bucket equals the observation count
    assert 'lat_count{tenant="x"} 6' in txt
    assert 'lat_sum{tenant="x"}' in txt


# -- cross-process aggregation (satellite 4) ----------------------------------

_SPOOL_WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
root, label, n = sys.argv[1], sys.argv[2], int(sys.argv[3])
from sparkglm_tpu import obs
tel = obs.Telemetry(spool=root, spool_label=label, profile=False)
for i in range(n):
    tel.metrics.counter("work.chunks").inc()
    tel.metrics.gauge("work.last").set(i)
    tel.metrics.histogram("work.ms").observe(float(i + 1))
    tel.export_now()
tel.close()
print("done", label)
"""


def test_two_real_processes_spool_and_merge(tmp_path):
    root = tmp_path / "spools"
    n = {"shard-a": 7, "shard-b": 5}
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _SPOOL_WORKER, str(root), label,
             str(count)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, cwd=REPO)
        for label, count in n.items()]
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err.decode()
    merged = merge_spools(root)
    assert merged["seq_coherent"], merged["errors"]
    # every process's full spool arrived, labelled and ordered
    assert {k: v["lines"] for k, v in merged["processes"].items()} == n
    for label, count in n.items():
        seqs = [r["seq"] for r in merged["stream"] if r["proc"] == label]
        assert seqs == list(range(count)), "per-process order corrupted"
    # rollups equal per-process sums
    roll = merged["rollup"]
    assert roll["counters"]["work.chunks"] == sum(n.values())
    assert roll["histograms"]["work.ms"]["count"] == sum(n.values())
    assert roll["histograms"]["work.ms"]["sum"] == pytest.approx(
        sum(sum(range(1, c + 1)) for c in n.values()))
    assert roll["gauges"]["work.last"]["by_proc"]["shard-a"] == 6
    assert roll["gauges"]["work.last"]["max"] == 6


def test_merge_flags_seq_gap_as_incoherent(tmp_path):
    root = tmp_path / "spools"
    os.makedirs(root)
    lines = [{"t": 1.0 + i, "proc": "p0", "seq": s, "metrics":
              {"counters": {}, "gauges": {}, "histograms": {}}}
             for i, s in enumerate([0, 1, 3])]  # seq 2 lost
    with open(root / "p0.jsonl", "w") as f:
        f.writelines(json.dumps(line) + "\n" for line in lines)
    merged = merge_spools(root)
    assert not merged["seq_coherent"]
    assert "p0" in merged["errors"][0]


def test_read_spool_raises_on_torn_write(tmp_path):
    path = tmp_path / "p.jsonl"
    path.write_text('{"t": 1, "proc": "p", "seq": 0, "metrics": {}}\n'
                    '{"t": 2, "proc": "p", "se')  # torn mid-line
    with pytest.raises(ValueError, match="corrupt spool line"):
        merge_spools(tmp_path)


def test_rollup_histogram_merge_matches_single_registry():
    # two shards' histograms merged == one registry fed both streams
    a, b, whole = (MetricsRegistry() for _ in range(3))
    for v in (0.5, 2.0, 9.0):
        a.histogram("h").observe(v)
        whole.histogram("h").observe(v)
    for v in (1.0, 33.0):
        b.histogram("h").observe(v)
        whole.histogram("h").observe(v)
    merged = rollup_snapshots({"a": a.snapshot(), "b": b.snapshot()})
    want = whole.snapshot()["histograms"]["h"]
    got = merged["histograms"]["h"]
    for key in ("count", "sum", "min", "max", "bucket_le", "p50", "p99"):
        assert got[key] == want[key], key


# -- bench history (tentpole part 3) ------------------------------------------

def test_extract_block_from_truncated_tail():
    tail = ('...m": 0.12}  ,"fleet_fit": {"speedup_s_per_model": 5.0, '
            '"note": "braces {inside} strings", "ok": true}, '
            '"cut_block": {"x": 1')
    b = extract_block(tail, "fleet_fit")
    assert b == {"speedup_s_per_model": 5.0,
                 "note": "braces {inside} strings", "ok": True}
    assert extract_block(tail, "cut_block") is None  # truncated mid-block
    assert extract_block(tail, "absent") is None


def test_regression_gate_flags_injected_cliff():
    # healthy wobble, then a cliff: throughput halves
    hist = [100.0, 104.0, 98.0, 101.0]
    gate = regression_gate(hist, 50.0, direction="higher", kind="value")
    assert gate["regressed"] and gate["p"] <= 0.15
    # the same wobble without the cliff stays quiet
    assert not regression_gate(hist, 97.0, direction="higher",
                               kind="value")["regressed"]
    # frac metrics gate on absolute delta (median here is ~0)
    fhist = [-0.02, 0.01, -0.03, 0.02]
    assert regression_gate(fhist, 0.40, direction="lower",
                           kind="frac")["regressed"]
    assert not regression_gate(fhist, 0.03, direction="lower",
                               kind="frac")["regressed"]


def test_regression_gate_respects_observed_noise_floor():
    # a metric that historically swings 30% needs more than 30% to alarm
    hist = [100.0, 70.0, 105.0, 72.0, 103.0]
    gate = regression_gate(hist, 69.0, direction="higher", kind="value")
    assert not gate["regressed"]
    assert gate["noise_floor"] >= 0.3


def test_regression_gate_needs_three_rounds():
    # with 2 history points the minimum sign-test p is 0.25 > alpha
    gate = regression_gate([100.0, 101.0], 10.0, direction="higher",
                           kind="value")
    assert not gate["regressed"] and gate["p"] > 0.15


def test_bench_history_flags_synthetic_regression():
    rounds = {
        r: {"serving_scaleout": {"rows_per_s": v, "ok": True},
            "fleet_fit": {"speedup_s_per_model": 5.0, "ok": True}}
        for r, v in zip((12, 13, 14, 15), (600e3, 610e3, 590e3, 605e3))}
    rounds[16] = {"serving_scaleout": {"rows_per_s": 150e3, "ok": True},
                  "fleet_fit": {"speedup_s_per_model": 5.1, "ok": True}}
    report = bench_history(rounds=rounds)
    assert report["regressions"] == ["serving_scaleout"]
    assert not report["ok"]
    text = render_report(report)
    assert "REGRESSION at r16" in text and "serving_scaleout" in text


def test_bench_history_quiet_on_real_trajectory():
    report = bench_history(REPO)
    assert report["rounds"], "no BENCH_r*.json rounds found"
    assert 16 in report["rounds"]
    assert report["regressions"] == [], render_report(report)
    assert report["ok"]
    # trajectories were actually mined out of the truncated tails
    assert len(report["blocks"]) >= 8
    assert any(len(b.get("trajectory", [])) >= 4
               for b in report["blocks"].values())


def test_bench_history_reports_ok_flips_as_warnings_only():
    rounds = {1: {"hotloop_mfu": {"ok": True}},
              2: {"hotloop_mfu": {"ok": True}},
              3: {"hotloop_mfu": {"ok": False}}}
    report = bench_history(rounds=rounds)
    assert report["ok_flips"] == [
        {"block": "hotloop_mfu", "round": 3, "last_ok_round": 2}]
    assert report["regressions"] == [] and report["ok"]


def test_blocks_registry_matches_r20_detail():
    with open(os.path.join(REPO, "benchmarks", "BENCH_r20.json")) as f:
        detail = json.load(f)
    for name, spec in BLOCKS.items():
        if spec["metric"] is None:
            continue
        assert name in detail, name
        assert spec["metric"] in detail[name], (name, spec["metric"])


# -- facade wiring (satellite 1) ----------------------------------------------

def test_growth_emits_consolidated_event():
    from sparkglm_tpu.serve import ModelFamily
    from sparkglm_tpu.serve.growth import FamilyGrowth
    rng = np.random.default_rng(1)
    X = np.column_stack([np.ones(64), rng.normal(size=(64, 2))])
    models = {}
    for t in range(3):
        y = (rng.uniform(size=64) < 0.5).astype(float)
        models[f"t{t}"] = sg.glm_fit(X, y, family="binomial")
    fam = ModelFamily("obs-growth")
    for k in ("t0", "t1"):
        fam.register(k, models[k])
    tel = obs.Telemetry()
    FamilyGrowth(fam, telemetry=tel).grow({"t2": models["t2"]})
    ev = [e for e in tel.events() if e.kind == "growth"]
    assert len(ev) == 1
    f = ev[0].fields
    assert {"crossed", "warm_s", "swap_s", "total_s"} <= set(f)
    assert f["added"] == 1 and f["tenants"] == 3


def test_sharded_loop_cycle_traces_carry_shard_label():
    from sparkglm_tpu.online.sharding import ShardedOnlineLoop, shard_of
    from sparkglm_tpu.serve import ModelFamily
    rng = np.random.default_rng(2)
    X = np.column_stack([np.ones(96), rng.normal(size=(96, 2))])
    # pick 2 labels per shard under the stable hash assignment
    by_shard = {0: [], 1: []}
    for i in range(256):
        t = f"tenant-{i}"
        s = shard_of(t, 2)
        if len(by_shard[s]) < 2:
            by_shard[s].append(t)
        if all(len(v) == 2 for v in by_shard.values()):
            break
    labels = by_shard[0] + by_shard[1]
    models = {}
    for t in labels:
        y = rng.poisson(2.0, size=96).astype(float)
        models[t] = sg.glm_fit(X, y, family="poisson")
    fam = ModelFamily("obs-shard")
    for k, m in models.items():
        fam.register(k, m)
    tel = obs.Telemetry()
    sharded = ShardedOnlineLoop(fam, 2, telemetry=tel)
    tenants = np.array([labels[i % len(labels)] for i in range(32)])
    Xc = np.column_stack([np.ones(32), rng.normal(size=(32, 2))])
    yc = rng.poisson(2.0, size=32).astype(float)
    sharded.step(tenants, Xc, yc)
    traces = {e.fields.get("trace") for e in tel.events()
              if "trace" in e.fields}
    assert "shard-00-cycle-000001" in traces
    assert "shard-01-cycle-000001" in traces
