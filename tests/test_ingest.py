"""Process-parallel sharded ingest (sparkglm_tpu/data/ingest.py + the
multi-file ``_stream_io`` front-ends): the data plane's contract is that
parallelism is INVISIBLE in the results — coefficients, std errors and
deviance are bit-identical at any ``ingest_workers`` count because chunks
reassemble in deterministic plan order and f64 accumulation order never
changes.  Also pinned here: column pruning to design-referenced variables
(a 200-column file with a 5-column formula reads 6 columns), resume
fingerprinting on process-parallel sources, and the worker-death re-read
path (a killed reader costs one typed retry, not the fit)."""

import os

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.ingest import ShardedSource
from sparkglm_tpu.data.model_matrix import wants_structured
from sparkglm_tpu.obs import FitTracer
from sparkglm_tpu.robust import FaultPlan, RetryPolicy, SimulatedPreemption

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402

pytestmark = pytest.mark.ingest

NOSLEEP = RetryPolicy(sleep=lambda s: None)


def _write_parquet(path, cols, row_group_size=500):
    table = pa.table({k: list(v) for k, v in cols.items()})
    pq.write_table(table, str(path), row_group_size=row_group_size)


def _coef_identical(a, b):
    np.testing.assert_array_equal(np.asarray(a.coefficients),
                                  np.asarray(b.coefficients))
    np.testing.assert_array_equal(np.asarray(a.std_errors),
                                  np.asarray(b.std_errors))


# ---------------------------------------------------------------------------
# ShardedSource unit contracts


def test_sharded_source_contract(rng):
    """Plan order, subset/with_workers derivation, and the two iteration
    modes: workers=0 yields lazy thunks, workers>=1 yields materialized
    chunks — both in identical global order."""
    def read(i):
        return (np.full(3, float(i)),)

    src = ShardedSource(5, read, label="t")
    assert len(src) == 5 and not src.process_parallel
    out = list(src())
    assert all(callable(t) for t in out)  # sequential tier stays lazy
    assert [t()[0][0] for t in out] == [0.0, 1.0, 2.0, 3.0, 4.0]

    sub = src.subset([4, 1])
    assert len(sub) == 2
    assert [t()[0][0] for t in sub()] == [4.0, 1.0]

    src2 = src.with_workers(2)
    assert src2 is not src and src2.process_parallel and len(src2) == 5
    items = list(src2())
    assert all(not callable(it) for it in items)  # materialized
    assert [it[0][0] for it in items] == [0.0, 1.0, 2.0, 3.0, 4.0]
    st = src2.last_stats
    assert st["workers"] == 2 and st["reads"] == 5
    assert st["workers_died"] == 0 and st["inline_rereads"] == 0
    assert st["rows"] == 15 and st["wall_s"] > 0.0


def test_ingest_workers_needs_sharded_source(rng):
    """A plain generator source cannot re-shard: the override is a typed
    error, not a silent sequential fallback."""
    X = rng.normal(size=(64, 3))
    y = rng.normal(size=64)

    def gen():
        yield (X, y, None, None)

    with pytest.raises(ValueError, match="ShardedSource"):
        sg.lm_fit_streaming(gen, ingest_workers=2)


# ---------------------------------------------------------------------------
# bit-identity across worker counts, single- and multi-file


@pytest.fixture()
def pq_files(tmp_path, rng):
    """Four parquet files of one schema — the multi-file ingest plan."""
    paths, frames = [], []
    for j in range(4):
        n = 700 + 100 * j
        x = np.round(rng.normal(size=n), 6)
        g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
        lam = np.exp(0.4 + 0.5 * x - 0.3 * (g == "b"))
        y = rng.poisson(lam).astype(float)
        cols = {"y": y, "x": x, "g": g}
        p = tmp_path / f"part{j}.parquet"
        _write_parquet(p, cols, row_group_size=256)
        paths.append(str(p))
        frames.append(cols)
    pooled = {c: np.concatenate([f[c] for f in frames]) for c in frames[0]}
    return paths, pooled


def test_bit_identity_workers_0_1_4_multi_file(pq_files):
    """The acceptance contract: ingest_workers ∈ {0, 1, 4} over a 4-file
    parquet plan produce byte-identical fits (reassembly is deterministic
    global chunk order; f64 accumulation order never changes)."""
    paths, pooled = pq_files
    kw = dict(family="poisson", chunk_bytes=1 << 14, retry=NOSLEEP)
    m0 = sg.glm_from_parquet("y ~ x + g", paths, ingest_workers=0, **kw)
    m1 = sg.glm_from_parquet("y ~ x + g", paths, ingest_workers=1, **kw)
    m4 = sg.glm_from_parquet("y ~ x + g", paths, ingest_workers=4, **kw)
    _coef_identical(m0, m1)
    _coef_identical(m0, m4)
    assert m0.deviance == m1.deviance == m4.deviance
    assert m0.iterations == m1.iterations == m4.iterations
    # sanity against the resident oracle (different accumulation path, so
    # close, not bit-equal)
    mr = sg.glm("y ~ x + g", data=pooled, family="poisson")
    np.testing.assert_allclose(m0.coefficients, mr.coefficients,
                               rtol=0, atol=1e-6)


def test_multi_file_csv_union_levels(tmp_path, rng):
    """Per-file level scans merge union-sorted: a factor level present in
    only ONE file still codes consistently everywhere, and the multi-file
    fit matches the resident fit on the concatenation."""
    def mk(path, glevels, n=600):
        x = np.round(rng.normal(size=n), 6)
        g = np.array(glevels)[rng.integers(0, len(glevels), n)]
        y = np.round(1.0 + 0.5 * x + 0.7 * (g == "b") + 0.1
                     * rng.normal(size=n), 6)
        path.write_text("y,x,g\n" + "\n".join(
            f"{yi:.10g},{xi:.10g},{gi}" for yi, xi, gi in zip(y, x, g))
            + "\n")
        return {"y": y, "x": x, "g": g}

    fa = mk(tmp_path / "a.csv", ["a", "b"])
    fb = mk(tmp_path / "b.csv", ["b", "c"])  # "c" exists only here
    paths = [str(tmp_path / "a.csv"), str(tmp_path / "b.csv")]
    pooled = {c: np.concatenate([fa[c], fb[c]]) for c in fa}

    m0 = sg.lm_from_csv("y ~ x + g", paths, chunk_bytes=8_000)
    m2 = sg.lm_from_csv("y ~ x + g", paths, chunk_bytes=8_000,
                        ingest_workers=2)
    _coef_identical(m0, m2)
    assert m0.xnames == ("intercept", "x", "g_b", "g_c")
    mr = sg.lm("y ~ x + g", data=pooled)
    np.testing.assert_allclose(m0.coefficients, mr.coefficients,
                               rtol=0, atol=1e-5)


# ---------------------------------------------------------------------------
# resume fingerprinting on process-parallel sources


def test_resume_sharded_structured_prefetch(tmp_path, rng):
    """The r18 regression: ingest_workers=4 × prefetch=2 × a structured
    (wide-factor) design, preempted mid-fit and resumed.  The resume
    fingerprint probes the source INLINE (workers=0 subset of chunk 0) —
    no reader fleet spawns just to validate a checkpoint — and the
    resumed fit is bit-identical to the unbroken one."""
    n = 4000
    x = np.round(rng.normal(size=n), 6)
    g = np.array([f"s{k:02d}" for k in range(40)])[rng.integers(0, 40, n)]
    eta = 0.3 + 0.8 * x
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
    p = tmp_path / "wide.parquet"
    _write_parquet(p, {"y": y, "x": x, "g": g}, row_group_size=500)

    kw = dict(family="binomial", tol=1e-10, chunk_bytes=1 << 14,
              ingest_workers=4, prefetch=2, retry=NOSLEEP)
    full = sg.glm_from_parquet("y ~ x + g", str(p), **kw)
    assert wants_structured(full.terms)  # 40 levels → structured design

    ckpt = tmp_path / "fit.ckpt"

    def preempt(it, beta, dev):
        if it >= 2:
            raise SimulatedPreemption("killed")

    with pytest.raises(SimulatedPreemption):
        sg.glm_from_parquet("y ~ x + g", str(p), checkpoint=ckpt,
                            on_iteration=preempt, **kw)
    m = sg.glm_from_parquet("y ~ x + g", str(p), checkpoint=ckpt,
                            resume=True, **kw)
    _coef_identical(m, full)
    assert m.deviance == full.deviance
    assert m.iterations == full.iterations


# ---------------------------------------------------------------------------
# column pruning


def test_column_pruning_200_col_parquet(tmp_path, rng):
    """A 200-column file with a 5-predictor formula reads exactly the 6
    referenced columns — every read, including the chunk-0 schema probe —
    and the pruned fit is bit-identical across worker counts."""
    from sparkglm_tpu.data import parquet as pq_io

    n = 2000
    cols = {"y": rng.poisson(2.0, n).astype(float)}
    for j in range(199):
        cols[f"c{j}"] = np.round(rng.normal(size=n), 6)
    p = tmp_path / "wide200.parquet"
    _write_parquet(p, cols, row_group_size=500)

    formula = "y ~ c0 + c1 + c2 + c3 + c4"
    used = {"y", "c0", "c1", "c2", "c3", "c4"}

    seen = []
    orig = pq_io.read_parquet

    def spy(path, **kw):
        seen.append(kw.get("columns"))
        return orig(path, **kw)

    pq_io.read_parquet = spy
    try:
        m0 = sg.lm_from_parquet(formula, str(p), chunk_bytes=1 << 14)
    finally:
        pq_io.read_parquet = orig
    assert seen, "no reads recorded"
    for c in seen:
        assert c is not None and set(c) == used, \
            f"unpruned read: {None if c is None else sorted(c)[:8]}"

    # the parallel tier re-parses the same pruned plan in workers — same
    # bytes, same answer (children are forked, so the spy cannot observe
    # them; bit-identity is the cross-tier proof)
    m4 = sg.lm_from_parquet(formula, str(p), chunk_bytes=1 << 14,
                            ingest_workers=4)
    _coef_identical(m0, m4)


# ---------------------------------------------------------------------------
# worker death mid-pass


def test_ingest_worker_death_reread(rng):
    """Kill one reader process mid-pass (os._exit inside the fork — a real
    OOM stand-in): the consumer detects the starved queue, spends one
    typed retry, re-reads the lost shard's chunks in-order inline, and the
    fit is bit-identical to the undisturbed one."""
    n, p = 3000, 4
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    y = X @ (rng.normal(size=p) / 2) + 0.1 * rng.normal(size=n)
    rows = 500
    n_chunks = n // rows

    def read(i):
        lo = i * rows
        return (X[lo:lo + rows], y[lo:lo + rows], None, None)

    base = sg.lm_fit_streaming(ShardedSource(n_chunks, read, label="kill"))

    # worker 0 dies just before its 2nd assigned read (global seq 2)
    plan = FaultPlan(ingest_worker_dead_at=((0, 1),))
    src = ShardedSource(n_chunks, read, workers=2, label="kill",
                        fault_plan=plan, retry=NOSLEEP)
    tr = FitTracer([])
    m = sg.lm_fit_streaming(src, trace=tr)
    _coef_identical(m, base)

    st = src.last_stats
    assert st["workers_died"] >= 1
    assert st["inline_rereads"] >= 1
    assert st["reads"] == n_chunks  # every chunk delivered exactly once
    rep = tr.report()["ingest"]
    assert rep["workers_died"] >= 1 and rep["rereads"] >= 1
    # the tracer accumulates across the fit's passes (LM makes more than
    # one); each pass delivers the full plan exactly once
    assert rep["reads"] % n_chunks == 0 and rep["reads"] >= n_chunks


def test_ingest_worker_death_budget_exhaustion(rng):
    """Worker deaths are TYPED transients: a retry budget of zero turns
    the death into the policy's escalation, not a hang or a wrong
    answer."""
    from sparkglm_tpu.robust import RetryBudgetExhausted

    def read(i):
        return (np.full((8, 2), float(i)), np.zeros(8), None, None)

    plan = FaultPlan(ingest_worker_dead_at=((0, 0),))
    src = ShardedSource(4, read, workers=2, label="kill0",
                        fault_plan=plan,
                        retry=RetryPolicy(budget=0, sleep=lambda s: None))
    with pytest.raises(RetryBudgetExhausted):
        list(src())
