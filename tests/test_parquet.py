"""Parquet ingestion tier (VERDICT r3 #4) — the reference's Spark-reader
role (SURVEY §2.3: "Arrow/Parquet reader feeding per-host shards"; the
reference's own fixtures are JSON, testData.scala:10-15, and its DataFrames
arrive from any Spark source).  Contracts mirror the CSV trio exactly:
schema scan, global level scan, shard-contract reads (row-group bands in
place of newline byte ranges), the same streaming fits on top — plus a
REAL 2-process fit sharded by row-group band."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import sparkglm_tpu as sg

pa = pytest.importorskip("pyarrow")
import pyarrow.parquet as pq  # noqa: E402


def _write_parquet(path, cols, row_group_size=256):
    table = pa.table({k: list(v) for k, v in cols.items()})
    pq.write_table(table, str(path), row_group_size=row_group_size)


@pytest.fixture()
def pq_data(tmp_path, rng):
    n = 2000
    x = np.round(rng.normal(size=n), 6)
    grp = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    lt = np.round(rng.uniform(0.2, 0.8, n), 6)
    lam = np.exp(0.3 + 0.5 * x - 0.4 * (grp == "b") + lt)
    y = rng.poisson(lam).astype(float)
    w = np.round(rng.uniform(0.5, 2.0, n), 6)
    cols = {"y": y, "x": x, "grp": grp, "lt": lt, "w": w}
    p = tmp_path / "d.parquet"
    _write_parquet(p, cols)
    return str(p), cols


def test_schema_and_levels(pq_data):
    path, cols = pq_data
    schema = sg.scan_parquet_schema(path)
    assert schema == {"y": 0, "x": 0, "grp": 1, "lt": 0, "w": 0}
    levels = sg.scan_parquet_levels(path)
    assert levels == {"grp": sorted(set(cols["grp"]))}


def test_read_parquet_shards_cover_exactly(pq_data):
    """Row-group bands partition the file: every row exactly once, in
    order — the read_csv(shard_index=) contract."""
    path, cols = pq_data
    for num_shards in (1, 3, 4, 16):
        got = [sg.read_parquet(path, shard_index=i, num_shards=num_shards)
               for i in range(num_shards)]
        y = np.concatenate([g["y"] for g in got])
        np.testing.assert_array_equal(y, cols["y"])
        grp = np.concatenate([g["grp"] for g in got])
        assert list(grp) == list(cols["grp"])
    # more shards than row groups: trailing shards are empty, total intact
    n_groups = pq.ParquetFile(path).metadata.num_row_groups
    many = n_groups + 3
    got = [sg.read_parquet(path, shard_index=i, num_shards=many)
           for i in range(many)]
    assert sum(len(g["y"]) for g in got) == len(cols["y"])


def test_read_parquet_nulls_and_dictionary(tmp_path):
    """Nulls follow the io.py contract (NaN numeric, None categorical);
    dictionary-encoded strings decode to plain str."""
    t = pa.table({
        "v": pa.array([1.5, None, 3.0], pa.float64()),
        "g": pa.array(["u", None, "v"]).dictionary_encode(),
    })
    p = tmp_path / "nulls.parquet"
    pq.write_table(t, str(p))
    cols = sg.read_parquet(str(p))
    assert np.isnan(cols["v"][1]) and cols["v"][2] == 3.0
    assert list(cols["g"]) == ["u", None, "v"]
    assert sg.scan_parquet_schema(str(p)) == {"v": 0, "g": 1}
    assert sg.scan_parquet_levels(str(p)) == {"g": ["u", "v"]}


def test_glm_from_parquet_matches_in_memory(pq_data, mesh8):
    path, cols = pq_data
    m_pq = sg.glm_from_parquet("y ~ x + grp + offset(lt)", path,
                               weights="w", family="poisson",
                               chunk_bytes=16 << 10, tol=1e-10,
                               criterion="relative", mesh=mesh8)
    m_mem = sg.glm("y ~ x + grp", cols, family="poisson", weights="w",
                   offset="lt", tol=1e-10, criterion="relative", mesh=mesh8)
    np.testing.assert_allclose(m_pq.coefficients, m_mem.coefficients,
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(m_pq.deviance, m_mem.deviance, rtol=1e-6)
    np.testing.assert_allclose(m_pq.std_errors, m_mem.std_errors, rtol=1e-5)
    assert m_pq.xnames == m_mem.xnames


def test_lm_from_parquet_offset_and_quantiles(pq_data, mesh8):
    path, cols = pq_data
    m_pq = sg.lm_from_parquet("y ~ x + grp", path, weights="w", offset="lt",
                              chunk_bytes=16 << 10, mesh=mesh8)
    m_mem = sg.lm("y ~ x + grp", cols, weights="w", offset="lt", mesh=mesh8)
    # streaming f32 chunk Gramians vs the resident single reduction
    np.testing.assert_allclose(m_pq.coefficients, m_mem.coefficients,
                               rtol=1e-5, atol=1e-8)
    np.testing.assert_allclose(m_pq.r_squared, m_mem.r_squared, rtol=1e-6)
    # residual quantile block streams on the parquet tier too
    assert m_pq.resid_quantiles is not None
    assert "Weighted Residuals:" in str(m_pq.summary())


def test_glm_from_parquet_equals_from_csv(pq_data, tmp_path, mesh8):
    """Same data through both ingestion tiers -> the same model."""
    import csv as csv_mod
    path, cols = pq_data
    cp = tmp_path / "d.csv"
    with open(cp, "w", newline="") as fh:
        w = csv_mod.writer(fh)
        w.writerow(list(cols))
        for i in range(len(cols["y"])):
            w.writerow([cols[k][i] for k in cols])
    kw = dict(weights="w", family="poisson", chunk_bytes=16 << 10,
              tol=1e-10, criterion="relative", mesh=mesh8)
    m_pq = sg.glm_from_parquet("y ~ x + grp", path, **kw)
    m_csv = sg.glm_from_csv("y ~ x + grp", str(cp), **kw)
    # same values, different chunk BOUNDARIES (row-group bands vs newline
    # byte ranges) -> f32 accumulation order differs at ~1e-7
    np.testing.assert_allclose(m_pq.coefficients, m_csv.coefficients,
                               rtol=1e-5, atol=1e-8)
    assert m_pq.n_obs == m_csv.n_obs


def test_predict_from_parquet_path(pq_data, mesh8):
    """predict(model, 'x.parquet') streams row-group bands, bit-identical
    to scoring the loaded columns."""
    path, cols = pq_data
    m = sg.glm("y ~ x + grp + offset(lt)", cols, family="poisson")
    whole = sg.predict(m, cols)
    chunked = sg.predict(m, path, chunk_bytes=16 << 10)
    np.testing.assert_array_equal(np.asarray(chunked), np.asarray(whole))


_PQ_WORKER = r"""
import json, sys
port, pid, pq_path, out_path, nproc = sys.argv[1:6]
nproc = int(nproc)
import os, re
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; set the XLA flag before
    # backend init, overriding any device count inherited from the parent
    # test process (conftest.py forces 8 there)
    flags = re.sub(r"--xla_force_host_platform_device_count=\S+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (flags
                               + " --xla_force_host_platform_device_count=2")
jax.config.update("jax_enable_x64", True)
import numpy as np
import sparkglm_tpu as sg
from sparkglm_tpu.parallel import distributed as dist

dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                num_processes=nproc, process_id=int(pid))
mesh = dist.global_mesh()
# each process reads its OWN row-group band — the per-host shard contract
cols = sg.read_parquet(pq_path, shard_index=dist.process_index(),
                       num_shards=nproc)
# global level discovery: level "c" lives only in shard 0's row groups
levels = sg.scan_parquet_levels(pq_path)
assert levels == {"grp": ["a", "b", "c"]}, levels
terms = sg.build_terms(cols, ["x1", "x2", "grp"], intercept=True,
                       levels=levels)
X = sg.transform(cols, terms).astype(np.float64)
y = np.asarray(cols["y"], np.float64)
tgt = dist.sync_max_rows(X.shape[0], mesh)
Xp, w = dist.pad_host_shard(X.astype(np.float32), tgt)
yp, _ = dist.pad_host_shard(y.astype(np.float32), tgt)
Xg = dist.host_shard_to_global(Xp, mesh)
yg = dist.host_shard_to_global(yp, mesh)
wg = dist.host_shard_to_global(w.astype(np.float32), mesh)
model = sg.glm_fit(Xg, yg, weights=wg, family="poisson", mesh=mesh,
                   has_intercept=True, xnames=terms.xnames,
                   criterion="relative", tol=1e-10)
if dist.process_index() == 0:
    with open(out_path, "w") as f:
        json.dump({"coefficients": model.coefficients.tolist(),
                   "deviance": model.deviance,
                   "n_obs": model.n_obs,
                   "converged": model.converged}, f)
print("pq worker", pid, "done", flush=True)
"""


def test_multi_process_parquet_fit(tmp_path):
    """VERDICT r3 #4 done-criterion: a REAL 2-process fit sharded by
    row-group band, mirroring test_multiprocess.py's CSV flow."""
    import jax
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        pytest.skip(
            "cross-process CPU collectives need jax/jaxlib >= 0.5 (gloo "
            "CPU collectives); installed jaxlib raises 'Multiprocess "
            "computations aren't implemented on the CPU backend'")
    from tests.test_multiprocess import _free_port

    rng = np.random.default_rng(23)
    n = 3001
    x1 = rng.standard_normal(n)
    x2 = rng.standard_normal(n)
    grp = np.where(np.arange(n) < 150, "c",
                   np.where(rng.random(n) < 0.5, "a", "b"))
    eff = {"a": 0.0, "b": 0.2, "c": -0.4}
    y = rng.poisson(np.exp(0.4 + 0.5 * x1 - 0.3 * x2
                           + np.vectorize(eff.get)(grp))).astype(np.float64)
    path = tmp_path / "mp.parquet"
    _write_parquet(path, {"y": y, "x1": x1, "x2": x2, "grp": grp},
                   row_group_size=500)
    worker = tmp_path / "worker.py"
    worker.write_text(_PQ_WORKER)
    out_path = tmp_path / "out.json"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = "/root/repo" + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(port), str(i), str(path),
         str(out_path), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        cwd="/root/repo") for i in range(2)]
    outs = []
    for pr in procs:
        try:
            out, _ = pr.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("parquet workers timed out")
        outs.append(out.decode())
    for i, pr in enumerate(procs):
        assert pr.returncode == 0, f"worker {i}:\n{outs[i][-3000:]}"
    with open(out_path) as f:
        got = json.load(f)

    cols = sg.read_parquet(str(path))
    terms = sg.build_terms(cols, ["x1", "x2", "grp"], intercept=True,
                           levels=sg.scan_parquet_levels(str(path)))
    X = sg.transform(cols, terms).astype(np.float32)
    ref = sg.glm_fit(X, np.asarray(cols["y"], np.float32), family="poisson",
                     criterion="relative", tol=1e-10, xnames=terms.xnames)
    assert got["converged"] and got["n_obs"] == n
    np.testing.assert_allclose(got["coefficients"], ref.coefficients,
                               rtol=0, atol=5e-6)
    assert got["deviance"] == pytest.approx(ref.deviance, rel=1e-5)


def test_r_verbs_on_parquet_path(pq_data, mesh8):
    """update()/drop1() accept the training PARQUET path — the from-file
    verbs dispatch by extension through the shared _stream_io backend."""
    path, cols = pq_data
    m = sg.glm_from_parquet("y ~ x + grp", path, family="poisson",
                            chunk_bytes=16 << 10, mesh=mesh8)
    m2 = sg.update(m, "~ . - grp", data=path)
    ref = sg.glm("y ~ x", cols, family="poisson", mesh=mesh8)
    np.testing.assert_allclose(m2.coefficients, ref.coefficients,
                               rtol=1e-5, atol=5e-6)
    tbl = sg.drop1(m, data=path)
    assert {"x", "grp"} <= set(tbl.row_names)
