"""Penalized GLM subsystem (sparkglm_tpu/penalized) — elastic-net paths.

The contracts under test, in the order the subsystem promises them:

  * glmnet-semantics golden parity (tests/fixtures/r_golden.json
    ``penalized_cases``: an independent f64 CD+IRLS oracle with glmnet's
    weight normalization / no-centering standardization — PARITY.md r11
    documents the correspondence and these tolerances);
  * the ONE-EXECUTABLE lambda path: the whole grid is a lax.scan with
    lambda traced, so a second same-shape fit adds ZERO executables and
    the first adds exactly one per pass flavor (jit cache-size deltas,
    the data/pipeline.py counting idiom);
  * warm-start determinism: the scan carry is forward-only, so fitting an
    explicit prefix of the auto grid reproduces the full path's prefix
    BIT-identically;
  * ``penalty=None`` keeps the ordinary fits byte-identical;
  * a PathModel selects back into an ordinary LMModel/GLMModel that
    predicts, serializes, and serves;
  * the streaming drivers (``*_from_csv(penalty=...)``) agree with the
    resident path.
"""

import json
import os

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu import ElasticNet
from sparkglm_tpu.config import NumericConfig
from sparkglm_tpu.obs import FitTracer, RingBufferSink


def _ring():
    ring = RingBufferSink()
    return ring, FitTracer(sinks=[ring])

pytestmark = pytest.mark.penalized

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "r_golden.json")
with open(FIXTURES) as f:
    PEN_GOLDEN = json.load(f)["penalized_cases"]

F64 = NumericConfig(dtype="float64")


def _golden_params():
    return [(name, akey) for name in sorted(PEN_GOLDEN)
            for akey in sorted(PEN_GOLDEN[name]["fits"])]


# ---------------------------------------------------------------------------
# glmnet-semantics golden parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,akey", _golden_params())
def test_penalized_golden(name, akey):
    case = PEN_GOLDEN[name]
    fit = case["fits"][akey]
    data = {k: np.asarray(v) for k, v in case["data"].items()}
    pen = ElasticNet(alpha=fit["alpha"], lambdas=case["lambdas"])
    pm = sg.glm(case["formula"], data, family=case["family"],
                link=case["link"], weights=case.get("weights"),
                penalty=pen, config=F64)
    assert list(pm.xnames) == case["xnames"]
    assert len(pm) == len(case["lambdas"])
    # PARITY.md r11 tolerances: f32/f64 solver vs the f64 oracle, both
    # stopping at their own cd_tol
    np.testing.assert_allclose(pm.coefficients, fit["coefficients"],
                               atol=2e-4, rtol=1e-4)
    np.testing.assert_allclose(pm.deviance, fit["deviance"], rtol=1e-4)
    assert pm.null_deviance == pytest.approx(fit["null_deviance"],
                                             rel=1e-6)
    assert pm.converged and pm.kkt_clean


def test_gaussian_kind_lm_matches_glm_kernel():
    """The lm front-end and the gaussian glm front-end share the Gramian
    path kernel — identical numbers, different selected-model class."""
    case = PEN_GOLDEN["gaussian_enet"]
    data = {k: np.asarray(v) for k, v in case["data"].items()}
    pen = ElasticNet(alpha=0.5, lambdas=case["lambdas"])
    pl = sg.lm(case["formula"], data, weights="w", penalty=pen, config=F64)
    pg = sg.glm(case["formula"], data, family="gaussian", link="identity",
                weights="w", penalty=pen, config=F64)
    np.testing.assert_array_equal(pl.coefficients, pg.coefficients)
    assert pl.kind == "lm" and pg.kind == "glm"
    assert type(pl.select(criterion="bic")).__name__ == "LMModel"
    assert type(pg.select(criterion="bic")).__name__ == "GLMModel"


# ---------------------------------------------------------------------------
# the one-executable contract + warm-start determinism
# ---------------------------------------------------------------------------


def _sim(seed, n=300, p=6, family="binomial"):
    r = np.random.default_rng(seed)
    X = r.standard_normal((n, p))
    eta = 0.4 + X[:, 0] - 0.6 * X[:, 1]
    if family == "binomial":
        y = r.binomial(1, 1 / (1 + np.exp(-eta))).astype(float)
    else:
        y = eta + r.normal(scale=0.5, size=n)
    cols = {f"x{i}": X[:, i] for i in range(p)}
    cols["y"] = y
    return cols


FORMULA6 = "y ~ x0 + x1 + x2 + x3 + x4 + x5"


def test_glm_path_is_one_executable():
    """The whole binomial lambda path — grid generation, strong rules,
    KKT loops, every IRLS solve — compiles as ONE executable; a second
    same-shape fit on different data adds zero."""
    from sparkglm_tpu.penalized.path import _glm_path_kernel

    pen = ElasticNet(alpha=0.8, n_lambda=25)
    # warm the traced flavor (trace= is a static: it bakes in the debug
    # callbacks), then a same-shape different-data fit must add ZERO
    sg.glm(FORMULA6, _sim(0), family="binomial", penalty=pen,
           trace=_ring()[1], config=F64)
    base = _glm_path_kernel._cache_size()
    pm = sg.glm(FORMULA6, _sim(1), family="binomial", penalty=pen,
                trace=_ring()[1], config=F64)
    assert _glm_path_kernel._cache_size() - base == 0
    assert pm.fit_info["path"]["executables"] == 0
    # a COLD shape compiles exactly ONE executable for the whole path
    before = _glm_path_kernel._cache_size()
    pm3 = sg.glm("y ~ x0 + x1 + x2", _sim(2, p=3), family="binomial",
                 penalty=pen, trace=_ring()[1], config=F64)
    assert _glm_path_kernel._cache_size() - before == 1
    assert pm3.fit_info["path"]["executables"] == 1


def test_gram_path_is_two_executables():
    """Gaussian/identity: one stats pass + one Gramian-level path kernel
    (the acceptance bound: a full path compiles <= 2 executables)."""
    from sparkglm_tpu.penalized.path import (_gram_path_kernel,
                                             _quad_stats_kernel)

    pen = ElasticNet(alpha=0.5, n_lambda=30)
    pm = sg.lm(FORMULA6, _sim(3, family="gaussian"), penalty=pen,
               trace=_ring()[1], config=F64)
    assert pm.fit_info["path"]["executables"] <= 2     # acceptance bound
    bs, bp = _quad_stats_kernel._cache_size(), _gram_path_kernel._cache_size()
    sg.lm(FORMULA6, _sim(4, family="gaussian"), penalty=pen,
          trace=_ring()[1], config=F64)
    assert _quad_stats_kernel._cache_size() - bs == 0
    assert _gram_path_kernel._cache_size() - bp == 0


def test_lambda_is_traced_across_grids():
    """Different explicit lambda VALUES (same grid length) reuse the same
    executable — lambda is a traced operand, not a static."""
    from sparkglm_tpu.penalized.path import _glm_path_kernel

    data = _sim(5)
    sg.glm(FORMULA6, data, family="binomial",
           penalty=ElasticNet(lambdas=[0.3, 0.1, 0.03]), config=F64)
    base = _glm_path_kernel._cache_size()
    sg.glm(FORMULA6, data, family="binomial",
           penalty=ElasticNet(lambdas=[0.25, 0.08, 0.02]), config=F64)
    assert _glm_path_kernel._cache_size() - base == 0


def test_warm_start_prefix_property():
    """Fitting the first k auto-grid lambdas explicitly reproduces the
    full path's first k rows BIT-identically: the scan carry is
    forward-only, so the path up to lambda_k cannot depend on anything
    after it."""
    data = _sim(6)
    full = sg.glm(FORMULA6, data, family="binomial",
                  penalty=ElasticNet(alpha=0.7, n_lambda=20), config=F64)
    k = 5
    prefix = sg.glm(FORMULA6, data, family="binomial",
                    penalty=ElasticNet(alpha=0.7,
                                       lambdas=full.lambdas[:k].tolist()),
                    config=F64)
    np.testing.assert_array_equal(prefix.coefficients,
                                  full.coefficients[:k])
    np.testing.assert_array_equal(prefix.deviance, full.deviance[:k])
    np.testing.assert_array_equal(prefix.df, full.df[:k])


def test_path_shape_and_monotonicity():
    pm = sg.glm(FORMULA6, _sim(7), family="binomial",
                penalty=ElasticNet(alpha=1.0, n_lambda=30), config=F64)
    assert pm.coefficients.shape == (30, 7)
    assert np.all(np.diff(pm.lambdas) < 0)          # descending grid
    assert pm.df[0] == 0                            # lambda_max: all zero
    assert np.all(np.diff(pm.deviance) <= 1e-6)     # deviance decreases
    assert pm.dev_ratio[-1] > pm.dev_ratio[0]


def test_penalty_none_is_bit_identical():
    """penalty=None must not perturb the ordinary fits at all."""
    data = _sim(8)
    a = sg.glm(FORMULA6, data, family="binomial", config=F64)
    b = sg.glm(FORMULA6, data, family="binomial", penalty=None, config=F64)
    assert type(b) is type(a)
    np.testing.assert_array_equal(a.coefficients, b.coefficients)
    np.testing.assert_array_equal(a.std_errors, b.std_errors)
    assert a.deviance == b.deviance


def test_unsupported_options_raise():
    data = _sim(9)
    pen = ElasticNet(n_lambda=5)
    with pytest.raises(ValueError, match="mesh"):
        sg.glm(FORMULA6, data, family="binomial", penalty=pen, mesh=object())
    with pytest.raises(ValueError, match="beta0"):
        sg.glm(FORMULA6, data, family="binomial", penalty=pen,
               beta0=np.zeros(7))
    with pytest.raises(ValueError, match="engine"):
        sg.lm(FORMULA6, data, penalty=pen, engine="qr")


def test_elasticnet_validation():
    with pytest.raises(ValueError):
        ElasticNet(alpha=1.5)
    with pytest.raises(ValueError):
        ElasticNet(n_lambda=0)
    with pytest.raises(ValueError):
        ElasticNet(lambdas=[0.1, -0.5])
    with pytest.raises(ValueError):
        ElasticNet(lambda_min_ratio=2.0)
    # lambdas are stored sorted descending regardless of input order
    assert ElasticNet(lambdas=[0.01, 1.0, 0.1]).resolved_lambdas().tolist() \
        == [1.0, 0.1, 0.01]
    with pytest.raises(TypeError):
        sg.glm(FORMULA6, _sim(10), family="binomial", penalty="lasso")


# ---------------------------------------------------------------------------
# PathModel -> ordinary model: select / predict / serialize / serve
# ---------------------------------------------------------------------------


def test_select_and_criteria():
    pm = sg.glm(FORMULA6, _sim(11), family="binomial",
                penalty=ElasticNet(alpha=1.0, n_lambda=25), config=F64)
    with pytest.raises(ValueError):
        pm.select()                                   # exactly one required
    with pytest.raises(ValueError):
        pm.select(lambda_=0.1, criterion="aic")
    with pytest.raises(ValueError):
        pm.select(criterion="cp")
    m_aic = pm.select(criterion="aic")
    m_bic = pm.select(criterion="bic")
    i_aic = m_aic.fit_info["penalized"]["lambda_index"]
    assert i_aic == int(np.argmin(pm.criterion_values("aic")))
    # BIC penalizes df harder: never selects a denser model than AIC
    assert (m_bic.fit_info["penalized"]["df"]
            <= m_aic.fit_info["penalized"]["df"])
    # select by lambda_ lands on the nearest grid point
    m_at = pm.select(lambda_=float(pm.lambdas[3]) * 1.01)
    assert m_at.fit_info["penalized"]["lambda_index"] == 3
    np.testing.assert_array_equal(m_at.coefficients, pm.coefficients[3])
    # no post-selection sampling theory: NaN SEs, real deviance
    assert np.all(np.isnan(m_at.std_errors))
    assert m_at.deviance == pytest.approx(float(pm.deviance[3]))


def test_selected_model_predicts_and_serializes(tmp_path):
    data = _sim(12)
    pm = sg.glm(FORMULA6, data, family="binomial",
                penalty=ElasticNet(alpha=0.5, n_lambda=20), config=F64)
    m = pm.select(criterion="bic")
    mu = sg.predict(m, data, type="response")
    assert mu.shape == (300,) and np.all((mu > 0) & (mu < 1))
    path = os.path.join(tmp_path, "selected.json")
    sg.save_model(m, path)
    m2 = sg.load_model(path)
    np.testing.assert_array_equal(m2.coefficients, m.coefficients)
    assert m2.fit_info["penalized"]["alpha"] == 0.5
    np.testing.assert_allclose(sg.predict(m2, data, type="response"), mu,
                               rtol=1e-12)


def test_pathmodel_round_trips(tmp_path):
    """The PATH itself serializes too — coefficient matrix, grid, penalty
    spec and all — and select() works identically after reload."""
    pm = sg.glm(FORMULA6, _sim(21), family="binomial",
                penalty=ElasticNet(alpha=0.4, n_lambda=10,
                                   penalty_factor=[1, 1, 1, 0, 1, 1]),
                config=F64)
    path = os.path.join(tmp_path, "path_model")
    sg.save_model(pm, path)
    pm2 = sg.load_model(path)
    assert type(pm2).__name__ == "PathModel"
    np.testing.assert_array_equal(pm2.coefficients, pm.coefficients)
    np.testing.assert_array_equal(pm2.lambdas, pm.lambdas)
    assert pm2.penalty == pm.penalty
    m, m2 = pm.select(criterion="bic"), pm2.select(criterion="bic")
    np.testing.assert_array_equal(m2.coefficients, m.coefficients)
    assert m2.fit_info["penalized"] == m.fit_info["penalized"]


def test_selected_model_serves():
    from sparkglm_tpu.serve import Scorer

    data = _sim(13)
    pm = sg.glm(FORMULA6, data, family="binomial",
                penalty=ElasticNet(alpha=1.0, n_lambda=15), config=F64)
    m = pm.select(criterion="aic")
    sc = Scorer(m, min_bucket=8)
    req = {k: v[:5] for k, v in data.items() if k != "y"}
    out = sc.score(req)
    np.testing.assert_allclose(
        out, sg.predict(m, req, type="response"), rtol=1e-12)


def test_trace_and_fit_report():
    ring, tr = _ring()
    pm = sg.glm(FORMULA6, _sim(14), family="binomial",
                penalty=ElasticNet(alpha=0.9, n_lambda=12), trace=tr,
                config=F64)
    kinds = ring.kinds()
    assert kinds.count("path_point") == 12
    assert "fit_start" in kinds and "fit_end" in kinds
    pts = [e for e in ring.events if e.kind == "path_point"]
    assert [p.fields["index"] for p in pts] == list(range(12))
    solves = [e for e in ring.events if e.kind == "solve"
              and e.fields.get("target") == "path_lambda"]
    assert len(solves) == 12
    rep = pm.fit_report()
    assert rep["path"]["n_lambda"] == 12
    assert rep["path"]["lambda_max"] == pytest.approx(float(pm.lambdas[0]))
    assert rep["path"]["cd_sweeps_total"] > 0


# ---------------------------------------------------------------------------
# structured designs + streaming drivers
# ---------------------------------------------------------------------------


def test_structured_design_path():
    """A wide factor routes the path through the segment-sum Gramian;
    numbers match the dense one-hot route."""
    r = np.random.default_rng(15)
    n, L = 2000, 40
    data = {"x": r.standard_normal(n),
            "f": np.array([f"L{i:02d}" for i in r.integers(0, L, n)]),
            }
    eta = 0.3 + 0.5 * data["x"]
    data["y"] = r.binomial(1, 1 / (1 + np.exp(-eta))).astype(float)
    pen = ElasticNet(alpha=0.5, n_lambda=10)
    ps = sg.glm("y ~ x + f", data, family="binomial", penalty=pen,
                design="structured", config=F64)
    pd = sg.glm("y ~ x + f", data, family="binomial", penalty=pen,
                design="dense", config=F64)
    assert ps.gramian_engine == "structured"
    assert pd.gramian_engine == "einsum"
    np.testing.assert_allclose(ps.coefficients, pd.coefficients,
                               atol=1e-8)
    np.testing.assert_allclose(ps.deviance, pd.deviance, rtol=1e-10)


def _write_csv(tmp_path, data, name="pen.csv"):
    import csv
    path = os.path.join(tmp_path, name)
    keys = list(data)
    with open(path, "w", newline="") as fh:
        w = csv.writer(fh)
        w.writerow(keys)
        for i in range(len(data[keys[0]])):
            w.writerow([data[k][i] for k in keys])
    return path


def test_streaming_glm_path_matches_resident(tmp_path):
    data = _sim(16)
    pen = ElasticNet(alpha=0.6, n_lambda=12)
    res = sg.glm(FORMULA6, data, family="binomial", penalty=pen, config=F64)
    path = _write_csv(tmp_path, data)
    ring, tr = _ring()
    strm = sg.glm_from_csv(FORMULA6, path, family="binomial", penalty=pen,
                           chunk_bytes=8192, trace=tr, config=F64)
    np.testing.assert_allclose(strm.coefficients, res.coefficients,
                               atol=1e-7)
    np.testing.assert_allclose(strm.deviance, res.deviance, rtol=1e-8)
    np.testing.assert_allclose(strm.lambdas, res.lambdas, rtol=1e-10)
    # the chunked passes + lambda-traced CD solve are a FIXED executable
    # set: compile events happen once per flavor, not per chunk or lambda
    assert [e.fields["index"] for e in ring.events
            if e.kind == "path_point"] == list(range(12))


def test_streaming_lm_path_matches_resident(tmp_path):
    data = _sim(17, family="gaussian")
    pen = ElasticNet(alpha=0.5, n_lambda=15)
    res = sg.lm(FORMULA6, data, penalty=pen, config=F64)
    path = _write_csv(tmp_path, data)
    strm = sg.lm_from_csv(FORMULA6, path, penalty=pen, chunk_bytes=8192,
                          config=F64)
    # ONE data pass accumulates the Gramian; the path then runs on it —
    # host-f64 left-to-right accumulation vs the resident one-shot kernel
    np.testing.assert_allclose(strm.coefficients, res.coefficients,
                               atol=1e-7)
    assert strm.kind == "lm"
    assert type(strm.select(criterion="bic")).__name__ == "LMModel"


def test_streaming_rejects_unsupported(tmp_path):
    data = _sim(18)
    path = _write_csv(tmp_path, data)
    pen = ElasticNet(n_lambda=5)
    with pytest.raises(ValueError, match="prefetch"):
        sg.glm_from_csv(FORMULA6, path, family="binomial", penalty=pen,
                        prefetch=2)
    # resume=True still needs a checkpoint= target to resume FROM
    with pytest.raises(ValueError, match="resume"):
        sg.lm_from_csv(FORMULA6, path, penalty=pen, resume=True)


def test_streaming_path_checkpoints(tmp_path):
    """checkpoint= is LEGAL on the penalized streaming drivers: the GLM
    path saves at every lambda boundary, the gaussian path after its one
    Gramian data pass, and resume= reproduces the uninterrupted fit
    bit-for-bit (the deep parity tests live in test_robustreg.py)."""
    data = _sim(21)
    pen = ElasticNet(alpha=0.6, n_lambda=6)
    path = _write_csv(tmp_path, data)
    ck = os.path.join(tmp_path, "path.npz")
    full = sg.glm_from_csv(FORMULA6, path, family="binomial", penalty=pen,
                           checkpoint=ck, config=F64)
    assert os.path.exists(ck)
    again = sg.glm_from_csv(FORMULA6, path, family="binomial", penalty=pen,
                            checkpoint=ck, resume=True, config=F64)
    np.testing.assert_array_equal(again.coefficients, full.coefficients)
    np.testing.assert_array_equal(again.deviance, full.deviance)
    np.testing.assert_array_equal(again.lambdas, full.lambdas)


def test_streaming_path_honors_retry(tmp_path):
    """retry= IS wired through the penalized drivers: transient chunk
    failures are absorbed on every pass of the lambda/IRLS loops and the
    path is bit-identical to the undisturbed one."""
    from sparkglm_tpu.robust import FaultPlan, RetryPolicy, faulty_source
    from sparkglm_tpu.penalized import stream as pen_stream
    from sparkglm_tpu.data.model_matrix import build_terms, transform

    nosleep = RetryPolicy(sleep=lambda s: None)
    data = _sim(19, family="gaussian")
    terms = build_terms(data, columns=[f"x{i}" for i in range(6)],
                        intercept=True)
    X = np.asarray(transform(data, terms), np.float64)
    y = np.asarray(data["y"], np.float64)

    def factory():
        def source():
            for i in range(4):
                lo, hi = 75 * i, 75 * (i + 1)
                yield lambda lo=lo, hi=hi: (X[lo:hi], y[lo:hi], None, None)
        return source

    pen = ElasticNet(alpha=0.6, n_lambda=8)
    kw = dict(penalty=pen, xnames=terms.xnames, has_intercept=True,
              config=F64)
    # gaussian driver: one Gramian pass
    clean = pen_stream.lm_path_streaming(factory(), **kw)
    plan = FaultPlan(transient_at=(1,))
    m = pen_stream.lm_path_streaming(
        faulty_source(factory(), plan), retry=nosleep, **kw)
    assert plan.faults_fired == 1
    np.testing.assert_array_equal(m.coefficients, clean.coefficients)
    # general-family driver: many passes, each under a fresh budget
    gkw = dict(family="binomial", penalty=pen, xnames=terms.xnames,
               has_intercept=True, config=F64)
    yb = (np.asarray(data["y"]) > np.median(data["y"])).astype(float)

    def bfactory():
        def source():
            for i in range(4):
                lo, hi = 75 * i, 75 * (i + 1)
                yield lambda lo=lo, hi=hi: (X[lo:hi], yb[lo:hi], None, None)
        return source

    gclean = pen_stream.glm_path_streaming(bfactory(), **gkw)
    gplan = FaultPlan(transient_at=(2, 9, 17))
    gm = pen_stream.glm_path_streaming(
        faulty_source(bfactory(), gplan), retry=nosleep, **gkw)
    assert gplan.faults_fired == 3
    np.testing.assert_array_equal(gm.coefficients, gclean.coefficients)
    np.testing.assert_array_equal(gm.deviance, gclean.deviance)


# ---------------------------------------------------------------------------
# solver details
# ---------------------------------------------------------------------------


def test_ridge_matches_closed_form():
    """alpha=0 gaussian with standardize: CD must land on the exact ridge
    normal-equation solution."""
    r = np.random.default_rng(19)
    n, p = 400, 5
    X = r.standard_normal((n, p))
    y = X @ np.array([1.0, -0.5, 0.3, 0.0, 0.2]) + r.normal(scale=0.4,
                                                            size=n)
    data = {f"x{i}": X[:, i] for i in range(p)}
    data["y"] = y
    lam = 0.7
    pm = sg.lm("y ~ x0 + x1 + x2 + x3 + x4", data,
               penalty=ElasticNet(alpha=0.0, lambdas=[lam], cd_tol=1e-13),
               config=F64)
    # reproduce on the standardized, weight-averaged scale
    Xf = np.column_stack([np.ones(n), X])
    wp = np.full(n, 1.0 / n)
    A = (Xf * wp[:, None]).T @ Xf
    b = Xf.T @ (wp * y)
    sd = np.sqrt(np.maximum(np.diag(A) - (wp @ Xf) ** 2, 0.0))
    sd[0] = 1.0
    As = A / sd[:, None] / sd[None, :]
    bs = b / sd
    pf = np.ones(p + 1)
    pf[0] = 0.0
    beta_s = np.linalg.solve(As + lam * np.diag(pf), bs)
    np.testing.assert_allclose(pm.coefficients[0], beta_s / sd, atol=5e-6)


def test_penalty_factor_and_offset():
    """penalty_factor=0 unpenalizes a column (always active); offsets
    shift the linear predictor exactly as in the unpenalized fit."""
    r = np.random.default_rng(20)
    n = 500
    data = {"x0": r.standard_normal(n), "x1": r.standard_normal(n),
            "e": r.uniform(0.5, 2.0, n)}
    mu = np.exp(0.2 + 0.8 * data["x0"] - 0.3 * data["x1"]) * data["e"]
    data["y"] = r.poisson(mu).astype(float)
    data["log_e"] = np.log(data["e"])
    pen = ElasticNet(alpha=1.0, n_lambda=8, penalty_factor=[0.0, 1.0])
    pm = sg.glm("y ~ x0 + x1 + offset(log_e)", data, family="poisson",
                penalty=pen, config=F64)
    # x0 is unpenalized: nonzero at EVERY lambda including lambda_max
    j = list(pm.xnames).index("x0")
    assert np.all(pm.coefficients[:, j] != 0.0)
    assert pm.has_offset
    # at the smallest lambda the fit approaches the unpenalized MLE
    ref = sg.glm("y ~ x0 + x1 + offset(log_e)", data, family="poisson",
                 config=F64)
    np.testing.assert_allclose(pm.coefficients[-1], ref.coefficients,
                               atol=5e-3)
