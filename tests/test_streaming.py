"""Streaming (out-of-HBM) fits: parity with the resident engines."""

import numpy as np
import pytest

import sparkglm_tpu as sg


def _data(rng, n=6000, p=6):
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    return X, bt


def test_lm_streaming_matches_resident(mesh8, rng):
    X, bt = _data(rng)
    n = X.shape[0]
    y = X @ bt + 0.3 * rng.normal(size=n)
    w = rng.uniform(0.5, 2.0, size=n)
    m_r = sg.lm_fit(X, y, weights=w, mesh=mesh8)
    m_s = sg.lm_fit_streaming((X, y, w), chunk_rows=1000, mesh=mesh8)
    np.testing.assert_allclose(m_s.coefficients, m_r.coefficients,
                               rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(m_s.std_errors, m_r.std_errors, rtol=1e-5)
    np.testing.assert_allclose(m_s.r_squared, m_r.r_squared, rtol=1e-6)
    np.testing.assert_allclose(m_s.sigma, m_r.sigma, rtol=1e-6)
    assert m_s.n_obs == n


@pytest.mark.parametrize("family,link", [
    ("binomial", "logit"), ("poisson", "log"), ("gamma", "log"),
])
def test_glm_streaming_matches_resident(mesh8, rng, family, link):
    X, bt = _data(rng)
    n = X.shape[0]
    eta = X @ bt
    if family == "binomial":
        y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
    elif family == "poisson":
        y = rng.poisson(np.exp(eta)).astype(float)
    else:
        y = rng.gamma(2.0, np.exp(eta) / 2.0)
    w = rng.uniform(0.5, 2.0, size=n)
    off = 0.05 * rng.normal(size=n)
    kw = dict(family=family, link=link, tol=1e-12, max_iter=60)
    m_r = sg.glm_fit(X, y, weights=w, offset=off, mesh=mesh8,
                     engine="fused", **kw)
    m_s = sg.glm_fit_streaming((X, y, w, off), chunk_rows=1024,
                               mesh=mesh8, **kw)
    np.testing.assert_allclose(m_s.coefficients, m_r.coefficients,
                               rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(m_s.std_errors, m_r.std_errors, rtol=1e-6)
    # scalar stats: f32 per-chunk sums differ from the resident single f32
    # reduction by accumulation order
    np.testing.assert_allclose(m_s.deviance, m_r.deviance, rtol=1e-6)
    np.testing.assert_allclose(m_s.pearson_chi2, m_r.pearson_chi2, rtol=1e-6)
    np.testing.assert_allclose(m_s.loglik, m_r.loglik, rtol=1e-6)
    assert m_s.converged


def test_glm_streaming_callable_source(mesh8, rng):
    """A generator-factory source (synthetic data, nothing materialized)."""
    p, n_chunks, rows = 5, 7, 512
    bt = np.array([0.3, -0.4, 0.2, 0.5, -0.1])

    def make_chunk(i):
        r = np.random.default_rng(100 + i)
        X = r.normal(size=(rows, p)); X[:, 0] = 1.0
        y = (r.random(rows) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
        return X, y

    def source():
        for i in range(n_chunks):
            X, y = make_chunk(i)
            yield X, y, None, None

    m_s = sg.glm_fit_streaming(source, family="binomial", tol=1e-12,
                               mesh=mesh8)
    Xs, ys = zip(*(make_chunk(i) for i in range(n_chunks)))
    m_r = sg.glm_fit(np.concatenate(Xs), np.concatenate(ys),
                     family="binomial", tol=1e-12, mesh=mesh8)
    np.testing.assert_allclose(m_s.coefficients, m_r.coefficients,
                               rtol=1e-7, atol=1e-9)
    assert m_s.n_obs == n_chunks * rows


def test_streaming_memmap_source(tmp_path, mesh8, rng):
    """np.memmap source — the on-disk bigger-than-RAM pattern."""
    n, p = 4096, 4
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = X @ [0.5, -0.2, 0.3, 0.1] + 0.1 * rng.normal(size=n)
    xp = tmp_path / "X.dat"
    Xm = np.memmap(xp, dtype=np.float64, mode="w+", shape=(n, p))
    Xm[:] = X
    Xm.flush()
    m = sg.lm_fit_streaming((np.memmap(xp, dtype=np.float64, shape=(n, p)), y),
                            chunk_rows=777, mesh=mesh8)
    m_r = sg.lm_fit(X, y, mesh=mesh8)
    np.testing.assert_allclose(m.coefficients, m_r.coefficients,
                               rtol=1e-6, atol=1e-9)


def test_streaming_checkpoint_resume(mesh8, rng):
    """Interrupt-and-resume via the on_iteration checkpoint hook + beta0
    warm start: the resumed fit reaches the same solution as an unbroken
    one (SURVEY.md §5: the reference has no recovery story at all)."""
    X, bt = _data(rng, n=3000)
    lam = np.exp(np.clip(X @ (bt / 4), -4, 4))
    y = rng.poisson(lam).astype(np.float64)
    kw = dict(family="poisson", tol=1e-12, criterion="relative",
              chunk_rows=512, mesh=mesh8)

    full = sg.glm_fit_streaming((X, y), **kw)

    # run 1: "crash" after two iterations, keeping the checkpoint
    ckpt = {}

    class Crash(Exception):
        pass

    def hook(it, beta, dev):
        ckpt.update(it=it, beta=beta, dev=dev)
        if it == 2:
            raise Crash

    try:
        sg.glm_fit_streaming((X, y), on_iteration=hook, **kw)
        raise AssertionError("hook should have interrupted the fit")
    except Crash:
        pass
    assert ckpt["it"] == 2

    # run 2: resume from the checkpointed beta
    resumed = sg.glm_fit_streaming((X, y), beta0=ckpt["beta"], **kw)
    np.testing.assert_allclose(resumed.coefficients, full.coefficients,
                               rtol=1e-10, atol=1e-12)
    assert resumed.deviance == pytest.approx(full.deviance, rel=1e-12)
    assert resumed.iterations < full.iterations  # warm start saved work


def test_streaming_device_cache_parity(mesh8, rng):
    """cache='none' / 'auto' / 'device' are pure transport settings — bitwise
    the same passes run on the same device arrays, so results are identical.
    The reference re-ships every partition every iteration (no .persist()
    anywhere, SURVEY.md §2.4); the cache is the TPU-first fix."""
    X, bt = _data(rng, n=4000)
    n = X.shape[0]
    eta = X @ bt
    y = rng.poisson(np.exp(eta)).astype(float)
    off = np.full(n, 0.02)
    kw = dict(family="poisson", tol=1e-12, criterion="relative",
              chunk_rows=640, mesh=mesh8)
    m_none = sg.glm_fit_streaming((X, y, None, off), cache="none", **kw)
    m_auto = sg.glm_fit_streaming((X, y, None, off), cache="auto", **kw)
    m_dev = sg.glm_fit_streaming((X, y, None, off), cache="device", **kw)
    for m in (m_auto, m_dev):
        np.testing.assert_array_equal(m.coefficients, m_none.coefficients)
        np.testing.assert_array_equal(m.std_errors, m_none.std_errors)
        assert m.deviance == m_none.deviance
        assert m.null_deviance == m_none.null_deviance
        assert m.iterations == m_none.iterations
        assert m.n_obs == m_none.n_obs == n


def test_streaming_partial_cache_hybrid(mesh8, rng):
    """A budget too small for the whole dataset caches a prefix and
    re-streams the rest — results still identical to uncached."""
    X, bt = _data(rng, n=4096)
    y = (rng.random(4096) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    kw = dict(family="binomial", tol=1e-12, chunk_rows=512, mesh=mesh8)
    m_none = sg.glm_fit_streaming((X, y), cache="none", **kw)
    # each 512 x 6 f64 chunk is ~28 KB on device; budget of 100 KB caches
    # ~3 of the 8 chunks
    m_part = sg.glm_fit_streaming((X, y), cache="auto",
                                  cache_budget_bytes=100_000, **kw)
    np.testing.assert_array_equal(m_part.coefficients, m_none.coefficients)
    assert m_part.deviance == m_none.deviance
    assert m_part.n_obs == m_none.n_obs


def test_streaming_cache_skips_source_regeneration(mesh8):
    """With a complete cache, IRLS iterations never re-invoke the source:
    chunk generation runs for the first pass and the two host stats passes
    only — not once per iteration."""
    p, n_chunks, rows = 4, 3, 512
    bt = np.array([0.2, -0.3, 0.1, 0.4])
    calls = {"chunks": 0, "passes": 0}

    def source():
        calls["passes"] += 1
        for i in range(n_chunks):
            r = np.random.default_rng(200 + i)
            X = r.normal(size=(rows, p)); X[:, 0] = 1.0
            y = (r.random(rows) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
            calls["chunks"] += 1
            yield X, y, None, None

    m = sg.glm_fit_streaming(source, family="binomial", tol=1e-12,
                             cache="device", mesh=mesh8)
    assert m.iterations >= 3
    # pass 1 (init+cache) + final stats pass + null-deviance pass = 3 source
    # invocations regardless of iteration count; cache="none" would add one
    # per IRLS iteration
    assert calls["passes"] == 3
    assert calls["chunks"] == 3 * n_chunks


def test_streaming_thunk_source_skips_lazily(mesh8):
    """A source may yield zero-arg thunks; with a complete device cache the
    cached-prefix skip never CALLS them, so per-chunk production cost
    (e.g. a CSV parse in glm_from_csv) is paid for the first pass and the
    two host stats passes only."""
    p, n_chunks, rows = 4, 3, 512
    bt = np.array([0.2, -0.3, 0.1, 0.4])
    calls = {"made": 0}

    def make_chunk(i):
        calls["made"] += 1
        r = np.random.default_rng(300 + i)
        X = r.normal(size=(rows, p)); X[:, 0] = 1.0
        y = (r.random(rows) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
        return X, y, None, None

    def source():
        for i in range(n_chunks):
            yield lambda i=i: make_chunk(i)

    m = sg.glm_fit_streaming(source, family="binomial", tol=1e-12,
                             cache="device", mesh=mesh8)
    assert m.iterations >= 3
    # init pass + final stats pass + null-deviance pass; IRLS iterations
    # read from HBM without ever calling the thunks
    assert calls["made"] == 3 * n_chunks
    # tuple-yielding parity: identical fit
    def source_tuples():
        for i in range(n_chunks):
            yield make_chunk(i)
    m2 = sg.glm_fit_streaming(source_tuples, family="binomial", tol=1e-12,
                              cache="none", mesh=mesh8)
    np.testing.assert_array_equal(m.coefficients, m2.coefficients)


def test_streaming_cache_invalid_mode(mesh1, rng):
    X, bt = _data(rng, n=64)
    y = (rng.random(64) < 0.5).astype(float)
    with pytest.raises(ValueError, match="cache"):
        sg.glm_fit_streaming((X, y), family="binomial", cache="hbm",
                             mesh=mesh1)


def test_streaming_zero_weight_rows_match_resident(mesh8, rng):
    """User zero-weight rows must count toward n_obs/df exactly as the
    resident engines count them (they are not shard padding)."""
    X, bt = _data(rng, n=2000)
    n = X.shape[0]
    y = X @ bt + 0.2 * rng.normal(size=n)
    w = rng.uniform(0.5, 2.0, size=n)
    w[::10] = 0.0
    m_r = sg.lm_fit(X, y, weights=w, mesh=mesh8)
    m_s = sg.lm_fit_streaming((X, y, w), chunk_rows=300, mesh=mesh8)
    assert m_s.n_obs == m_r.n_obs == n
    assert m_s.df_resid == m_r.df_resid
    np.testing.assert_allclose(m_s.std_errors, m_r.std_errors, rtol=1e-5)
    yb = (rng.random(n) < 0.5).astype(float)
    g_r = sg.glm_fit(X, yb, weights=np.maximum(w, 1e-9), mesh=mesh8, tol=1e-10)
    g_s = sg.glm_fit_streaming((X, yb, np.maximum(w, 1e-9)), chunk_rows=300,
                               mesh=mesh8, tol=1e-10)
    assert g_s.n_obs == g_r.n_obs == n
    assert g_s.df_residual == g_r.df_residual


def test_glm_streaming_null_deviance_semantics(mesh8, rng):
    """Null deviance matches the resident engine for offset and
    no-intercept models (R semantics)."""
    n = 1500
    x = rng.normal(size=n)
    off = rng.uniform(0, 1, size=n)
    y = rng.poisson(np.exp(0.2 + 0.4 * x + off)).astype(float)
    X = np.stack([np.ones(n), x], axis=1)
    m_r = sg.glm_fit(X, y, family="poisson", offset=off, tol=1e-10, mesh=mesh8)
    m_s = sg.glm_fit_streaming((X, y, None, off), family="poisson",
                               tol=1e-10, chunk_rows=400, mesh=mesh8)
    np.testing.assert_allclose(m_s.null_deviance, m_r.null_deviance, rtol=1e-6)
    # no-intercept: null mu = linkinv(0)
    Xn = x.reshape(-1, 1)
    m_rn = sg.glm_fit(Xn, y, family="poisson", tol=1e-10, mesh=mesh8,
                      has_intercept=False)
    m_sn = sg.glm_fit_streaming((Xn, y), family="poisson", tol=1e-10,
                                chunk_rows=400, mesh=mesh8,
                                has_intercept=False)
    np.testing.assert_allclose(m_sn.null_deviance, m_rn.null_deviance,
                               rtol=1e-6)


def test_lm_streaming_offset_parity(mesh8, rng):
    """r4 (VERDICT r3 #6): streaming lm supports offsets — weighted,
    with intercept, against the resident lm(offset=)'s R-exact moments."""
    X, bt = _data(rng, n=1200)
    off = rng.uniform(-1.0, 1.0, size=1200)
    w = rng.uniform(0.5, 2.0, size=1200)
    y = X @ bt + off + 0.2 * rng.normal(size=1200)
    m_s = sg.lm_fit_streaming((X, y, w, off), chunk_rows=300, mesh=mesh8)
    m_r = sg.lm_fit(X, y, weights=w, offset=off, mesh=mesh8)
    assert m_s.has_offset
    np.testing.assert_allclose(m_s.coefficients, m_r.coefficients,
                               rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(m_s.sse, m_r.sse, rtol=1e-6)
    np.testing.assert_allclose(m_s.sst, m_r.sst, rtol=1e-6)
    np.testing.assert_allclose(m_s.r_squared, m_r.r_squared, rtol=1e-6)
    np.testing.assert_allclose(m_s.f_statistic, m_r.f_statistic, rtol=1e-6)
    np.testing.assert_allclose(m_s.std_errors, m_r.std_errors, rtol=1e-5)


def test_streaming_intercept_scans_all_chunks(mesh8, rng):
    """A column constant-1 in early chunks but not later must NOT be taken
    for an intercept (the resident engines scan the full matrix)."""
    n = 2000
    flag = np.zeros(n)
    flag[:1500] = 1.0  # first chunks all-ones, later chunks not
    X = np.stack([flag, rng.normal(size=n)], axis=1)
    y = X @ [1.0, 2.0] + 0.1 * rng.normal(size=n)
    m_r = sg.lm_fit(X, y, mesh=mesh8)
    m_s = sg.lm_fit_streaming((X, y), chunk_rows=500, mesh=mesh8)
    assert m_s.has_intercept == m_r.has_intercept == False  # noqa: E712
    np.testing.assert_allclose(m_s.r_squared, m_r.r_squared, rtol=1e-6)


def test_streaming_honors_float64(mesh1, rng):
    """float64 input + x64 stays float64 through the chunks, matching the
    resident engine's precision."""
    n, p = 3000, 4
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = X @ [1e5, 0.5, -0.2, 0.1] + 1e-3 * rng.normal(size=n)
    m_r = sg.lm_fit(X, y, mesh=mesh1)
    m_s = sg.lm_fit_streaming((X, y), chunk_rows=512, mesh=mesh1)
    np.testing.assert_allclose(m_s.coefficients, m_r.coefficients,
                               rtol=1e-10, atol=1e-8)


def test_streaming_accepts_list_weights(mesh1, rng):
    X, bt = _data(rng, n=300)
    y = X @ bt
    m = sg.lm_fit_streaming((X, y, [1.0] * 300), mesh=mesh1)
    assert np.all(np.isfinite(m.coefficients))


def test_streaming_validation(mesh1, rng):
    X = rng.normal(size=(100, 3))
    y = rng.normal(size=99)
    with pytest.raises(ValueError, match="rows"):
        sg.lm_fit_streaming((X, y), mesh=mesh1)
    with pytest.raises(TypeError, match="source"):
        sg.glm_fit_streaming(X, mesh=mesh1)
    with pytest.raises(ValueError, match="criterion"):
        sg.glm_fit_streaming((X, rng.normal(size=100)), criterion="bogus",
                             mesh=mesh1)


def test_cache_prefix_skip_detects_reordered_chunks(rng):
    """ADVICE r2: a generator that yields the same chunks in a DIFFERENT
    order on a later pass must error, not silently double-count the cached
    prefix.  Budget admits only the first chunk, so passes 2+ skip one and
    re-read the rest."""
    from sparkglm_tpu.models.streaming import glm_fit_streaming

    n, p = 600, 4
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    y = (rng.random(n) < 0.5).astype(np.float64)
    chunks = [(X[i:i + 200], y[i:i + 200], None, None)
              for i in range(0, n, 200)]
    calls = {"k": 0}

    def source():
        calls["k"] += 1
        order = [0, 1, 2] if calls["k"] == 1 else [1, 0, 2]  # prefix swapped
        for i in order:
            yield chunks[i]

    # budget sized to admit exactly one device chunk (X + y + w + off)
    one_chunk = X[0:200].nbytes + 3 * y[0:200].nbytes
    with pytest.raises(ValueError, match="different chunk at position"):
        glm_fit_streaming(source, family="binomial",
                          cache_budget_bytes=one_chunk + 1000)

    # the same budget with a STABLE order fits fine (the check is not
    # tripping on correct sources)
    calls["k"] = 0

    def stable():
        calls["k"] += 1
        for c in chunks:
            yield c

    m = glm_fit_streaming(stable, family="binomial",
                          cache_budget_bytes=one_chunk + 1000)
    assert m.converged


def test_device_chunk_source_matches_host_source(rng):
    """Device-resident chunks (jax arrays, e.g. on-device synthetic
    generators) pass through the streaming engine with no host round-trip
    of the design — and produce the SAME model as the host-array source
    (the config-5 benchmark path, benchmarks/config5_full.py)."""
    import jax
    import jax.numpy as jnp
    from sparkglm_tpu.models.streaming import glm_fit_streaming

    n, p, chunk = 1200, 6, 400
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    off = rng.uniform(-0.3, 0.3, n)
    wt = rng.uniform(0.5, 2.0, n)
    bt = rng.standard_normal(p) / 5
    y = rng.gamma(3.0, np.exp(X @ bt + off) / 3.0)

    def device_source():
        for lo in range(0, n, chunk):
            hi = lo + chunk
            yield (jnp.asarray(X[lo:hi], jnp.float64),
                   jnp.asarray(y[lo:hi], jnp.float64),
                   jnp.asarray(wt[lo:hi], jnp.float64),
                   jnp.asarray(off[lo:hi], jnp.float64))

    m_dev = glm_fit_streaming(device_source, family="gamma", link="log",
                              tol=1e-10, criterion="relative")
    m_host = glm_fit_streaming((X, y, wt, off), family="gamma", link="log",
                               chunk_rows=chunk, tol=1e-10,
                               criterion="relative")
    np.testing.assert_allclose(m_dev.coefficients, m_host.coefficients,
                               rtol=1e-9, atol=1e-12)
    assert m_dev.deviance == pytest.approx(m_host.deviance, rel=1e-9)
    assert m_dev.null_deviance == pytest.approx(m_host.null_deviance,
                                                rel=1e-9)
    assert m_dev.aic == pytest.approx(m_host.aic, rel=1e-9)
    assert m_dev.has_offset and m_dev.has_intercept
    # non-finite device chunks get the device-side model-frame error
    def bad_source():
        Xb = X.copy()
        Xb[5, 2] = np.inf
        yield (jnp.asarray(Xb[:chunk]), jnp.asarray(y[:chunk]), None, None)
    with pytest.raises(ValueError, match="NA/NaN/Inf"):
        glm_fit_streaming(bad_source, family="gamma", link="log")
