"""Fault-tolerant fitting (sparkglm_tpu.robust): retrying chunk sources,
preemption-safe streaming checkpoint/resume, and IRLS step-halving
recovery.  Faults are injected deterministically (robust.faults) so every
recovery path runs in CI, not just in real outages."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.robust import (CheckpointManager, FatalSourceError,
                                 FaultPlan, RetryBudgetExhausted, RetryPolicy,
                                 SimulatedPreemption, TransientSourceError,
                                 as_checkpoint, call_with_retry,
                                 faulty_reader, faulty_source,
                                 retrying_source)

# no real sleeping in tests: the backoff schedule is asserted on, not waited
NOSLEEP = RetryPolicy(sleep=lambda s: None)


def _binomial_data(rng, n=4000, p=4):
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    return X, y


def _chunk_factory(X, y, n_chunks=5):
    """A lazy thunk source over row slices (the from-CSV source shape)."""
    n = X.shape[0]

    def source():
        for i in range(n_chunks):
            lo = n * i // n_chunks
            hi = n * (i + 1) // n_chunks
            yield lambda lo=lo, hi=hi: (X[lo:hi], y[lo:hi], None, None)

    return source


# ---------------------------------------------------------------------------
# retry policy + budget
# ---------------------------------------------------------------------------

def test_retry_policy_deterministic_capped_backoff():
    pol = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.25, seed=7)
    # deterministic: same (seed, key, attempt) -> same delay
    assert pol.delay(2, "k") == pol.delay(2, "k")
    # de-correlated across keys, bounded by the jitter band around the cap
    d1, d2 = pol.delay(9, "a"), pol.delay(9, "b")
    assert d1 != d2
    for d in (d1, d2):
        assert 0.75 <= d <= 1.25  # min(0.1 * 2^9, 1.0) * (1 +/- 0.25)
    # transient classification: typed + registered types, fatal never
    assert pol.is_transient(TransientSourceError("x"))
    assert pol.is_transient(OSError("x"))
    assert not pol.is_transient(FatalSourceError("x"))
    assert not pol.is_transient(ValueError("x"))


def test_call_with_retry_transient_then_success():
    sleeps = []
    pol = RetryPolicy(max_retries=4, sleep=sleeps.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientSourceError("blip")
        return 42

    assert call_with_retry(flaky, policy=pol, key="t") == 42
    assert calls["n"] == 3
    # one backoff sleep per retry, on the deterministic schedule
    assert sleeps == [pol.delay(0, "t"), pol.delay(1, "t")]


def test_call_with_retry_fatal_and_max_retries():
    pol = RetryPolicy(max_retries=2, sleep=lambda s: None)
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise FatalSourceError("corrupt row")

    with pytest.raises(FatalSourceError):
        call_with_retry(fatal, policy=pol)
    assert calls["n"] == 1  # fatal is never retried

    calls["n"] = 0

    def always():
        calls["n"] += 1
        raise TransientSourceError("down")

    with pytest.raises(TransientSourceError):
        call_with_retry(always, policy=pol)
    assert calls["n"] == 3  # initial + max_retries


def test_retry_budget_exhausted_raises():
    pol = RetryPolicy(max_retries=10, budget=3, sleep=lambda s: None)
    budget = pol.new_budget()

    def always():
        raise TransientSourceError("down")

    with pytest.raises(RetryBudgetExhausted) as ei:
        call_with_retry(always, policy=pol, budget=budget)
    assert isinstance(ei.value.__cause__, TransientSourceError)


# ---------------------------------------------------------------------------
# retrying sources end-to-end through the streaming fit
# ---------------------------------------------------------------------------

def test_streaming_fit_retries_transients_and_matches_clean(mesh8, rng):
    X, y = _binomial_data(rng)
    clean = sg.glm_fit_streaming(_chunk_factory(X, y), family="binomial",
                                 tol=1e-10, mesh=mesh8)
    plan = FaultPlan(transient_at=(1, 4, 9))
    m = sg.glm_fit_streaming(
        faulty_source(_chunk_factory(X, y), plan), family="binomial",
        tol=1e-10, mesh=mesh8, retry=NOSLEEP)
    assert plan.faults_fired == 3  # every scheduled fault actually fired
    # retried chunks are re-materialized identically: bit-for-bit fit
    np.testing.assert_array_equal(m.coefficients, clean.coefficients)
    assert m.deviance == clean.deviance
    assert m.iterations == clean.iterations


def test_streaming_fit_budget_exhaustion_and_fatal(mesh8, rng):
    X, y = _binomial_data(rng, n=1200)
    # a source that is down hard: every touch transient -> the per-pass
    # budget (tighter than the per-call retry cap) exhausts
    pol = RetryPolicy(max_retries=4, budget=2, sleep=lambda s: None)
    with pytest.raises(RetryBudgetExhausted):
        sg.glm_fit_streaming(
            faulty_source(_chunk_factory(X, y), FaultPlan(p_transient=1.0)),
            family="binomial", mesh=mesh8, retry=pol)
    # fatal errors are never absorbed, with or without a retry policy
    with pytest.raises(FatalSourceError):
        sg.glm_fit_streaming(
            faulty_source(_chunk_factory(X, y), FaultPlan(fatal_at=(2,))),
            family="binomial", mesh=mesh8, retry=NOSLEEP)


def test_preemption_passes_through_retry(mesh8, rng):
    """SimulatedPreemption is a BaseException: the retry layer must not
    absorb it (a real preemption signal cannot be retried away)."""
    X, y = _binomial_data(rng, n=1200)
    with pytest.raises(SimulatedPreemption):
        sg.glm_fit_streaming(
            faulty_source(_chunk_factory(X, y), FaultPlan(preempt_at=(3,))),
            family="binomial", mesh=mesh8, retry=NOSLEEP)


def test_faulty_reader_with_reader_retry(tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("a,b\n1.0,2.0\n3.0,4.0\n")
    plan = FaultPlan(transient_at=(0,))
    reader = faulty_reader(sg.read_csv, plan)
    cols = call_with_retry(lambda: reader(str(p)), policy=NOSLEEP)
    assert plan.faults_fired == 1
    np.testing.assert_allclose(cols["a"], [1.0, 3.0])


def test_read_csv_retry_param(tmp_path, monkeypatch):
    import sparkglm_tpu.data.io as io_mod
    p = tmp_path / "d.csv"
    p.write_text("a,b\n1.0,2.0\n3.0,4.0\n")
    calls = {"n": 0}
    orig = io_mod.resolve_gz

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("flaky mount")
        return orig(*a, **k)

    monkeypatch.setattr(io_mod, "resolve_gz", flaky)
    cols = io_mod.read_csv(str(p), retry=NOSLEEP)
    assert calls["n"] == 2  # one transient absorbed
    np.testing.assert_allclose(cols["b"], [2.0, 4.0])
    # without retry= the same failure propagates
    calls["n"] = 0
    with pytest.raises(OSError):
        io_mod.read_csv(str(p))


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

def test_as_checkpoint_contract(tmp_path):
    assert as_checkpoint(None) is None
    assert as_checkpoint(False) is None
    ck = as_checkpoint(tmp_path / "c.npz")
    assert isinstance(ck, CheckpointManager)
    assert as_checkpoint(ck) is ck
    with pytest.raises(ValueError, match="checkpoint="):
        as_checkpoint(True)


def test_checkpoint_roundtrip_and_validation(tmp_path):
    ck = CheckpointManager(tmp_path / "state.npz")
    assert not ck.exists()
    fp = (100.0, 3.0, 1.5, None, 0.25, None)  # None = absent w/o samples
    ck.save(kind="glm", fingerprint=fp, p=3,
            beta=np.array([1.0, -2.0, 0.5]), iters=4, dev=12.5)
    assert ck.exists()
    st = ck.load()
    assert st["kind"] == "glm" and st["p"] == 3 and int(st["iters"]) == 4
    np.testing.assert_array_equal(st["beta"], [1.0, -2.0, 0.5])
    ck.validate(st, kind="glm", fingerprint=fp, p=3)  # matches: no raise
    with pytest.raises(ValueError, match="'lm'"):
        ck.validate(st, kind="lm", fingerprint=fp, p=3)
    with pytest.raises(ValueError, match="coefficients"):
        ck.validate(st, kind="glm", fingerprint=fp, p=4)
    with pytest.raises(ValueError, match="fingerprint"):
        ck.validate(st, kind="glm", fingerprint=(100.0, 3.0, 9.9, None,
                                                 0.25, None), p=3)
    # atomic overwrite: a newer save fully replaces the record
    ck.save(kind="glm", fingerprint=fp, p=3,
            beta=np.zeros(3), iters=9, dev=1.0)
    assert int(ck.load()["iters"]) == 9
    ck.remove()
    assert not ck.exists()
    ck.remove()  # idempotent


def test_glm_checkpoint_resume_bit_identical(mesh8, rng, tmp_path):
    """The acceptance test: a fit killed mid-run by an injected preemption
    resumes from its checkpoint and finishes with ITERATION-IDENTICAL
    state — same remaining passes, same coefficients, same deviance."""
    X, y = _binomial_data(rng)
    src = _chunk_factory(X, y)
    kw = dict(family="binomial", tol=1e-10, mesh=mesh8)
    full = sg.glm_fit_streaming(src, **kw)
    assert full.iterations > 3  # the preemption below lands mid-fit

    ckpt = tmp_path / "glm.ckpt"

    def preempt(it, beta, dev):
        if it >= 2:
            raise SimulatedPreemption("killed after iteration 2")

    with pytest.raises(SimulatedPreemption):
        sg.glm_fit_streaming(src, checkpoint=ckpt, on_iteration=preempt, **kw)
    assert CheckpointManager(ckpt).exists()

    m = sg.glm_fit_streaming(src, checkpoint=ckpt, resume=True, **kw)
    np.testing.assert_array_equal(m.coefficients, full.coefficients)
    np.testing.assert_array_equal(m.std_errors, full.std_errors)
    assert m.deviance == full.deviance
    assert m.iterations == full.iterations
    assert m.converged


def test_glm_resume_refuses_wrong_source_and_missing_file(mesh8, rng,
                                                          tmp_path):
    X, y = _binomial_data(rng)
    ckpt = tmp_path / "glm.ckpt"
    kw = dict(family="binomial", tol=1e-10, mesh=mesh8)
    sg.glm_fit_streaming(_chunk_factory(X, y), checkpoint=ckpt, **kw)
    # a perturbed source no longer matches the recorded fingerprint
    y2 = y.copy()
    y2[0] = 1.0 - y2[0]
    with pytest.raises(ValueError, match="fingerprint"):
        sg.glm_fit_streaming(_chunk_factory(X, y2), checkpoint=ckpt,
                             resume=True, **kw)
    # missing checkpoint file: resume starts fresh (the restart-loop
    # contract — pass checkpoint=/resume= unconditionally)
    m = sg.glm_fit_streaming(_chunk_factory(X, y),
                             checkpoint=tmp_path / "absent.ckpt",
                             resume=True, **kw)
    assert m.converged


def test_lm_checkpoint_resume_identical(mesh8, rng, tmp_path):
    X, _ = _binomial_data(rng)
    bt = rng.normal(size=X.shape[1])
    y = X @ bt + 0.3 * rng.normal(size=X.shape[0])

    def src():
        for i in range(4):
            lo, hi = 1000 * i, 1000 * (i + 1)
            yield lambda lo=lo, hi=hi: (X[lo:hi], y[lo:hi], None, None)

    full = sg.lm_fit_streaming(src, mesh=mesh8)
    ckpt = tmp_path / "lm.ckpt"
    sg.lm_fit_streaming(src, mesh=mesh8, checkpoint=ckpt)
    assert CheckpointManager(ckpt).exists()
    # resume skips the Gramian pass entirely and reproduces the fit
    m = sg.lm_fit_streaming(src, mesh=mesh8, checkpoint=ckpt, resume=True)
    np.testing.assert_array_equal(m.coefficients, full.coefficients)
    assert m.r_squared == full.r_squared
    assert m.sigma == full.sigma


def test_from_csv_preempt_resume_roundtrip(tmp_path, mesh8, rng):
    """End-to-end through the api plumbing: glm_from_csv with
    retry=/checkpoint=/resume= recovers a preempted out-of-core fit."""
    n = 3000
    x = rng.standard_normal(n)
    y = (rng.random(n) < 1 / (1 + np.exp(-(0.3 + 0.8 * x)))).astype(float)
    p = tmp_path / "d.csv"
    p.write_text("y,x\n" + "\n".join(f"{yi:.0f},{xi:.10g}"
                                     for yi, xi in zip(y, x)) + "\n")
    kw = dict(family="binomial", tol=1e-10, chunk_bytes=20_000, mesh=mesh8,
              retry=NOSLEEP)
    full = sg.glm_from_csv("y ~ x", str(p), **kw)
    ckpt = tmp_path / "csvfit.ckpt"

    def preempt(it, beta, dev):
        if it >= 2:
            raise SimulatedPreemption("killed")

    with pytest.raises(SimulatedPreemption):
        sg.glm_from_csv("y ~ x", str(p), checkpoint=ckpt,
                        on_iteration=preempt, **kw)
    m = sg.glm_from_csv("y ~ x", str(p), checkpoint=ckpt, resume=True, **kw)
    np.testing.assert_array_equal(m.coefficients, full.coefficients)
    assert m.deviance == full.deviance
    assert m.iterations == full.iterations


# ---------------------------------------------------------------------------
# IRLS step-halving
# ---------------------------------------------------------------------------

def _diverging_gamma(rng=None):
    """gamma/inverse with an overshooting warm start: the unhalved Fisher
    step drives eta through 0 (singular working weights) — the seed
    kernels raise/diverge here; step-halving recovers it."""
    r = np.random.default_rng(3)
    xg = np.linspace(0.2, 3.0, 40)
    mug = 1.0 / (0.5 + 0.8 * xg)
    yg = mug * r.gamma(8.0, 1 / 8.0, 40)
    return np.column_stack([np.ones_like(xg), xg]), yg


@pytest.mark.parametrize("engine", ["einsum", "fused"])
def test_step_halving_recovers_diverging_fit(engine):
    X, y = _diverging_gamma()
    m = sg.glm_fit(X, y, family="gamma", link="inverse",
                   beta0=np.array([6.0, -1.5]), engine=engine)
    assert m.converged
    assert np.all(np.isfinite(m.coefficients))
    # both engines land on the true optimum (cross-checked in the probe:
    # the cold-started fit reaches the same fixed point)
    cold = sg.glm_fit(X, y, family="gamma", link="inverse", engine=engine)
    np.testing.assert_allclose(m.coefficients, cold.coefficients,
                               rtol=1e-5, atol=1e-8)


def test_step_halving_deviance_monotone():
    """R glm.fit semantics: once iterating, deviance never increases —
    a worse step is halved toward the previous iterate instead."""
    X, y = _diverging_gamma()
    devs = []
    m = sg.glm_fit(X, y, family="gamma", link="inverse",
                   beta0=np.array([6.0, -1.5]), engine="einsum",
                   checkpoint_every=1,
                   on_iteration=lambda it, beta, dev: devs.append(float(dev)))
    assert m.converged and len(devs) >= 2
    slack = 1e-4 * (np.abs(devs) + 0.1)  # the kernels' own _HALF_SLACK band
    assert np.all(np.diff(devs) <= slack[:-1])


def test_step_halving_leaves_healthy_fits_alone(rng):
    """A well-posed fit must take full Fisher steps — same trajectory and
    iteration count as before halving existed."""
    X, y = _binomial_data(rng, n=2000)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-10, engine="einsum")
    f = sg.glm_fit(X, y, family="binomial", tol=1e-10, engine="fused")
    assert m.converged and f.converged
    np.testing.assert_allclose(m.coefficients, f.coefficients,
                               rtol=1e-6, atol=1e-9)


# ---------------------------------------------------------------------------
# fault plan mechanics
# ---------------------------------------------------------------------------

def test_fault_plan_touch_semantics():
    plan = FaultPlan(transient_at=(1,), fatal_at=(3,))
    plan.on_touch()  # touch 0: clean
    with pytest.raises(TransientSourceError):
        plan.on_touch()  # touch 1: scheduled transient fires once
    plan.on_touch()  # touch 2: clean (the retry's re-touch)
    with pytest.raises(FatalSourceError):
        plan.on_touch()  # touch 3: fatal
    assert plan.faults_fired == 2
    plan.reset()
    plan.on_touch()
    with pytest.raises(TransientSourceError):
        plan.on_touch()  # schedule rewound


def test_fault_plan_preempt_chunk_coordinates():
    """The worker-kill fault kind: fires at a seeded (pass, chunk)
    coordinate, ONCE — passes count source openings monotonically over the
    plan's lifetime, so a restarted fit's fresh passes never re-die at the
    same coordinate."""
    plan = FaultPlan(preempt_chunk_at=((1, 2),))
    src = faulty_source(lambda: iter([(i,) for i in range(4)]), plan)

    def drain():
        return [c[0] for c in src()]

    assert drain() == [0, 1, 2, 3]  # pass 0: clean
    got = []
    with pytest.raises(SimulatedPreemption):  # pass 1 dies AT chunk 2
        for c in src():
            got.append(c[0])
    assert got == [0, 1]
    assert plan.faults_fired == 1
    # the "restarted worker" re-opens the source: pass 2, no re-fire
    assert drain() == [0, 1, 2, 3]
    # distinct from transient source errors: a kill is a BaseException
    # (never absorbed by retry) and is positioned, not touch-counted
    assert issubclass(SimulatedPreemption, BaseException)
    assert not issubclass(SimulatedPreemption, Exception)


def test_retrying_source_mid_iteration_generator_failure(mesh8, rng):
    """A generator raising mid-pass (not in a thunk) is re-opened and
    fast-forwarded past the delivered prefix."""
    X, y = _binomial_data(rng, n=1500)
    state = {"opens": 0}

    def source():
        state["opens"] += 1
        fail_this_open = state["opens"] == 2
        for i in range(3):
            lo, hi = 500 * i, 500 * (i + 1)
            if fail_this_open and i == 1:
                raise TransientSourceError("iterator died mid-pass")
            yield X[lo:hi], y[lo:hi], None, None

    clean = sg.glm_fit_streaming(_chunk_factory(X, y, 3), family="binomial",
                                 tol=1e-10, mesh=mesh8, cache="none")
    m = sg.glm_fit_streaming(source, family="binomial", tol=1e-10,
                             mesh=mesh8, cache="none", retry=NOSLEEP)
    assert state["opens"] >= 3  # the failed pass re-opened the source
    np.testing.assert_array_equal(m.coefficients, clean.coefficients)
