"""TSQR + CSNE polish (ops/tsqr.py): the f32 conditioning lever.

SURVEY.md §7 hard part #1: f32 normal equations lose ~eps*kappa(X)^2 —
measured garbage past kappa ~1e2 (benchmarks/parity_sweep.py).  The polish
must recover ~eps*kappa accuracy, and must be a no-op-or-better everywhere.
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig
from oracle import irls_np, ols_np


def _conditioned(rng, n, p, kappa):
    Z = rng.standard_normal((n, p - 1))
    V, _ = np.linalg.qr(rng.standard_normal((p - 1, p - 1)))
    s = np.logspace(0, -np.log10(kappa), p - 1)
    return np.column_stack([np.ones(n), (Z @ V) * s @ V.T])


@pytest.mark.parametrize("kappa", [1e1, 1e3, 3e4])
def test_tsqr_r_accurate_across_cholqr2_fallback(mesh1, rng, kappa):
    """tsqr_r's CholeskyQR2 fast path covers kappa up to ~1/sqrt(eps) and
    must hand off to Householder beyond it (f32: the first Gramian goes
    numerically non-PD around kappa ~3e3).  Either way R'R must reproduce
    Xw'Xw at ~eps*kappa accuracy."""
    import jax.numpy as jnp
    from sparkglm_tpu.ops.tsqr import tsqr_r
    from sparkglm_tpu.parallel import mesh as meshlib
    n, p = 8192, 10
    X = _conditioned(rng, n, p, kappa).astype(np.float32)
    Xd = meshlib.shard_rows(X, mesh1)
    R = np.asarray(tsqr_r(Xd, mesh1), np.float64)
    assert np.all(np.isfinite(R))
    assert np.all(np.diag(R) >= 0)  # sign-normalized
    G64 = X.astype(np.float64).T @ X.astype(np.float64)
    scale = np.max(np.abs(G64))
    assert np.max(np.abs(R.T @ R - G64)) / scale < 3e-6
    # FORWARD error vs the true f64 QR factor — the property CSNE's error
    # bound needs; backward error alone is satisfied even by a degraded
    # normal-equations factor (r2 review finding)
    R64 = np.linalg.qr(X.astype(np.float64), mode="r")
    R64 = R64 * np.where(np.diag(R64) < 0, -1.0, 1.0)[:, None]
    fwd = np.max(np.abs(R - R64)) / np.max(np.abs(R64))
    assert fwd < 3e-7 * max(kappa, 10.0)  # ~eps32 * kappa with slack


def test_tsqr_r_matches_host_qr(mesh8, rng):
    import jax.numpy as jnp
    from sparkglm_tpu.ops.tsqr import tsqr_r
    from sparkglm_tpu.parallel import mesh as meshlib
    X = rng.standard_normal((4096, 12))
    Xd = meshlib.shard_rows(X, mesh8)
    R = np.asarray(tsqr_r(Xd, mesh8), np.float64)
    Rh = np.linalg.qr(X, mode="r")
    # R is unique up to row signs; compare R'R
    np.testing.assert_allclose(R.T @ R, Rh.T @ Rh, rtol=1e-10, atol=1e-10)


def test_csne_rescues_ill_conditioned_logistic_f32(mesh8, rng):
    n, p, kappa = 40_000, 12, 1e3
    X = _conditioned(rng, n, p, kappa)
    bt = rng.standard_normal(p) / np.sqrt(p)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float64)
    b64, _, _, _ = irls_np(X, y, "binomial", "logit", tol=1e-14)
    kw = dict(family="binomial", tol=1e-12, criterion="relative", mesh=mesh8)
    # polish="off" pins the UNpolished baseline (default args now
    # auto-escalate to the polish at this conditioning — see
    # test_default_args_auto_polish_at_kappa_1e3)
    m0 = sg.glm_fit(X.astype(np.float32), y.astype(np.float32),
                    config=NumericConfig(dtype="float32", polish="off"), **kw)
    m1 = sg.glm_fit(X.astype(np.float32), y.astype(np.float32),
                    config=NumericConfig(dtype="float32", polish="csne"), **kw)
    e0 = np.max(np.abs(m0.coefficients - b64))
    e1 = np.max(np.abs(m1.coefficients - b64))
    assert e1 <= e0          # never worse
    assert e1 < 5e-3         # and absolutely tight (measured ~1e-3)


def test_csne_rescues_ill_conditioned_ols_f32(mesh1, rng):
    n, p, kappa = 40_000, 12, 1e3
    X = _conditioned(rng, n, p, kappa)
    bt = rng.standard_normal(p)
    y = X @ bt + 0.1 * rng.standard_normal(n)
    b64 = ols_np(X, y)
    m0 = sg.lm_fit(X.astype(np.float32), y.astype(np.float32),
                   config=NumericConfig(dtype="float32", polish="off"),
                   mesh=mesh1)
    m1 = sg.lm_fit(X.astype(np.float32), y.astype(np.float32),
                   config=NumericConfig(dtype="float32", polish="csne"),
                   mesh=mesh1)
    e0 = np.max(np.abs(m0.coefficients - b64))
    e1 = np.max(np.abs(m1.coefficients - b64))
    assert e1 < e0 / 5
    # polished residual stats are host-f64 exact at the polished beta (and
    # the f32-rounded X the fit actually saw)
    Xf = X.astype(np.float32).astype(np.float64)
    yf = y.astype(np.float32).astype(np.float64)
    resid = yf - Xf @ m1.coefficients
    assert m1.sse == pytest.approx(float(np.sum(resid**2)), rel=1e-9)


def test_csne_noop_on_well_conditioned(mesh8, rng):
    n, p = 20_000, 8
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    bt = rng.standard_normal(p) / np.sqrt(p)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float64)
    b64, _, _, _ = irls_np(X, y, "binomial", "logit", tol=1e-14)
    m1 = sg.glm_fit(X.astype(np.float32), y.astype(np.float32),
                    family="binomial", tol=1e-12, criterion="relative",
                    mesh=mesh8,
                    config=NumericConfig(dtype="float32", polish="csne"))
    assert np.max(np.abs(m1.coefficients - b64)) < 5e-5
    assert m1.converged


def test_polish_f64_path_unharmed(mesh8, rng):
    # x64 CPU fits are already ~1e-12; polish must not degrade them
    n, p = 5_000, 6
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    bt = rng.standard_normal(p)
    y = X @ bt + 0.5 * rng.standard_normal(n)
    b64 = ols_np(X, y)
    m = sg.lm_fit(X, y, mesh=mesh8,
                  config=NumericConfig(dtype="float64", polish="csne"))
    np.testing.assert_allclose(m.coefficients, b64, rtol=1e-10, atol=1e-12)


def test_qr_engine_matches_oracle_where_gramian_refuses(mesh8, rng):
    """engine='qr' (per-iteration TSQR+CSNE) fits designs whose f32 Gramian
    is numerically singular, at ~eps*kappa accuracy."""
    n, p, kappa = 40_000, 12, 1e4
    X = _conditioned(rng, n, p, kappa)
    bt = rng.standard_normal(p) / np.sqrt(p)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float64)
    b64, _, _, _ = irls_np(X, y, "binomial", "logit", tol=1e-14)
    m = sg.glm_fit(X.astype(np.float32), y.astype(np.float32),
                   family="binomial", engine="qr", tol=1e-12,
                   criterion="relative", mesh=mesh8,
                   config=NumericConfig(dtype="float32"))
    assert m.converged
    # eps_f32 * kappa * |beta| scale tolerance, with slack
    assert np.max(np.abs(m.coefficients - b64)) < 0.3


def test_qr_engine_well_conditioned_parity(mesh8, rng):
    """On well-conditioned data the qr engine agrees with einsum tightly
    (f64 x64 path here: both near-exact), including SEs from R^-1 R^-T."""
    n, p = 5_000, 6
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    bt = rng.standard_normal(p) / np.sqrt(p)
    y = rng.poisson(np.exp(np.clip(X @ bt, -4, 4))).astype(np.float64)
    kw = dict(family="poisson", tol=1e-12, criterion="relative", mesh=mesh8)
    m_e = sg.glm_fit(X, y, engine="einsum", **kw)
    m_q = sg.glm_fit(X, y, engine="qr", **kw)
    np.testing.assert_allclose(m_q.coefficients, m_e.coefficients,
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(m_q.std_errors, m_e.std_errors, rtol=1e-8)
    assert m_q.deviance == pytest.approx(m_e.deviance, rel=1e-10)


def test_qr_engine_rejects_feature_sharding(mesh42, rng):
    X = np.column_stack([np.ones(800), rng.standard_normal((800, 7))])
    y = (rng.random(800) < 0.5).astype(float)
    with pytest.raises(ValueError, match="qr"):
        sg.glm_fit(X, y, engine="qr", mesh=mesh42, shard_features=True)


def test_lm_qr_engine_public_api(mesh8, rng):
    n, p, kappa = 40_000, 12, 1e3
    X = _conditioned(rng, n, p, kappa)
    bt = rng.standard_normal(p)
    y = X @ bt + 0.1 * rng.standard_normal(n)
    b64 = ols_np(X, y)
    m0 = sg.lm_fit(X.astype(np.float32), y.astype(np.float32), mesh=mesh8,
                   config=NumericConfig(dtype="float32", polish="off"))
    mq = sg.lm_fit(X.astype(np.float32), y.astype(np.float32), mesh=mesh8,
                   engine="qr", config=NumericConfig(dtype="float32"))
    e0 = np.max(np.abs(m0.coefficients - b64))
    eq = np.max(np.abs(mq.coefficients - b64))
    assert eq < e0 / 5
    with pytest.raises(ValueError, match="engine"):
        sg.lm_fit(X.astype(np.float32), y.astype(np.float32), engine="lu")


def test_ill_conditioned_f32_warns(mesh1, rng):
    """kappa beyond f32 normal-equations fidelity (> ~1e2) must not pass
    silently — at kappa=1e3 the measured coefficient error is ~3e-2."""
    n, p, kappa = 20_000, 10, 1e3
    X = _conditioned(rng, n, p, kappa)
    bt = rng.standard_normal(p)
    y = X @ bt + 0.1 * rng.standard_normal(n)
    with pytest.warns(UserWarning, match="ill-conditioned"):
        sg.lm_fit(X.astype(np.float32), y.astype(np.float32), mesh=mesh1,
                  config=NumericConfig(dtype="float32"))
    # opting out of the auto-polish still warns (warn-only r02 behaviour)
    with pytest.warns(UserWarning, match="may lose digits"):
        sg.lm_fit(X.astype(np.float32), y.astype(np.float32), mesh=mesh1,
                  config=NumericConfig(dtype="float32", polish="off"))
    # the qr engine on the same data does NOT warn (its accuracy is ~eps*kappa)
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("error")
        sg.lm_fit(X.astype(np.float32), y.astype(np.float32), mesh=mesh1,
                  engine="qr", config=NumericConfig(dtype="float32"))


def test_polished_ses_consistent_with_qr_covariance(mesh1, rng):
    """polish='csne' must rebuild the covariance from the TSQR factor, not
    keep the kappa^2-noise Cholesky inverse (review r2 finding)."""
    n, p, kappa = 40_000, 10, 1e3
    X = _conditioned(rng, n, p, kappa)
    bt = rng.standard_normal(p)
    y = X @ bt + 0.5 * rng.standard_normal(n)
    mq = sg.lm_fit(X.astype(np.float32), y.astype(np.float32), mesh=mesh1,
                   engine="qr", config=NumericConfig(dtype="float32"))
    mp = sg.lm_fit(X.astype(np.float32), y.astype(np.float32), mesh=mesh1,
                   config=NumericConfig(dtype="float32", polish="csne"))
    # both covariance routes come from a TSQR factor now: SEs agree closely
    np.testing.assert_allclose(mp.std_errors, mq.std_errors, rtol=1e-3)


def test_streaming_rejects_bad_polish(rng):
    from sparkglm_tpu.models.streaming import glm_fit_streaming
    X = np.column_stack([np.ones(100), rng.standard_normal(100)])
    y = np.abs(rng.standard_normal(100)) + 1
    with pytest.raises(ValueError, match="polish"):
        glm_fit_streaming((X, y), family="gamma", link="log",
                          config=NumericConfig(polish="bogus"))
    # explicit polish='csne' runs the chunked TSQR polish (r4) — no
    # "not applicable" warning, and the fit still matches the unpolished
    # one on this well-conditioned design
    m_p = glm_fit_streaming((X, y), family="gamma", link="log",
                            config=NumericConfig(polish="csne"))
    m_0 = glm_fit_streaming((X, y), family="gamma", link="log",
                            config=NumericConfig(polish="off"))
    np.testing.assert_allclose(m_p.coefficients, m_0.coefficients,
                               rtol=1e-5, atol=1e-7)


def test_polish_validated():
    X = np.column_stack([np.ones(50), np.arange(50.0)])
    y = np.arange(50.0)
    with pytest.raises(ValueError, match="polish"):
        sg.lm_fit(X, y, config=NumericConfig(polish="nope"))


def test_streaming_auto_polish_recovers_digits(rng):
    """r4: the AUTO conditioning policy ESCALATES streaming fits to the
    chunked TSQR + CSNE polish (previously warn-only — the one place the
    resident accuracy contract ended).  The chunk Gramians are f32 on
    device (~eps32*kappa^2 error); the chunked f32 QR + host-f64
    seminormal correction recovers ~eps32*kappa."""
    from sparkglm_tpu.models.streaming import lm_fit_streaming
    n, p, kappa = 20_000, 10, 1e3
    X = _conditioned(rng, n, p, kappa).astype(np.float32)
    yl = (X @ rng.standard_normal(p)
          + 0.1 * rng.standard_normal(n)).astype(np.float32)
    truth = np.linalg.lstsq(X.astype(np.float64),
                            np.asarray(yl, np.float64), rcond=None)[0]

    with pytest.warns(UserWarning, match="auto-applying"):
        m_auto = lm_fit_streaming((X, yl), chunk_rows=4096,
                                  config=NumericConfig(dtype="float32"))
    with pytest.warns(UserWarning, match="may lose digits"):
        m_off = lm_fit_streaming((X, yl), chunk_rows=4096,
                                 config=NumericConfig(dtype="float32",
                                                      polish="off"))
    err_auto = np.max(np.abs(m_auto.coefficients - truth))
    err_off = np.max(np.abs(m_off.coefficients - truth))
    assert err_auto < err_off / 5, (err_auto, err_off)
    assert err_auto < 1e-3


def test_streaming_glm_auto_polish(rng):
    """The GLM streaming path escalates too — z/w rebuilt at the
    converged beta from the host-f64 family math."""
    from sparkglm_tpu.models.streaming import glm_fit_streaming
    n, p, kappa = 20_000, 10, 1e3
    X = _conditioned(rng, n, p, kappa).astype(np.float32)
    yg = (rng.random(n) < 1 / (1 + np.exp(
        -np.clip(X @ rng.standard_normal(p), -8, 8)))).astype(np.float32)
    with pytest.warns(UserWarning, match="auto-applying"):
        m_auto = glm_fit_streaming((X, yg), family="binomial",
                                   chunk_rows=4096,
                                   config=NumericConfig(dtype="float32"))
    # f64 oracle on the identical data (module-level import)
    truth = irls_np(X.astype(np.float64), np.asarray(yg, np.float64),
                    "binomial", "logit")[0]
    with pytest.warns(UserWarning, match="may lose digits"):
        m_off = glm_fit_streaming((X, yg), family="binomial",
                                  chunk_rows=4096,
                                  config=NumericConfig(dtype="float32",
                                                       polish="off"))
    err_auto = np.max(np.abs(m_auto.coefficients - truth))
    err_off = np.max(np.abs(m_off.coefficients - truth))
    assert err_auto <= err_off, (err_auto, err_off)
    assert err_auto < 5e-3


def test_default_args_auto_polish_at_kappa_1e3(mesh8, rng):
    """VERDICT r2 #6: with DEFAULT arguments an f32 fit at kappa=1e3 must
    auto-escalate to the CSNE polish and land within ~1e-3 of the f64
    oracle (the r02 warn-only default measured ~3.6e-2), for both the GLM
    and LM paths.  Hopeless conditioning (kappa beyond ~3e5) still errors
    via factor_singular — unchanged."""
    n, p, kappa = 40_000, 12, 1e3
    X = _conditioned(rng, n, p, kappa)
    bt = rng.standard_normal(p) / np.sqrt(p)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float64)
    b64, _, _, _ = irls_np(X, y, "binomial", "logit", tol=1e-14)
    with pytest.warns(UserWarning, match="auto-applying the CSNE polish"):
        mg = sg.glm_fit(X.astype(np.float32), y.astype(np.float32),
                        family="binomial", tol=1e-12, criterion="relative",
                        mesh=mesh8, config=NumericConfig(dtype="float32"))
    # same absolute bound as test_csne_rescues_ill_conditioned_logistic_f32:
    # ~1e-3 typical, up to ~2.4e-3 across BLAS builds
    assert np.max(np.abs(mg.coefficients - b64)) < 5e-3

    yl = X @ bt + 0.1 * rng.standard_normal(n)
    bl = ols_np(X, yl)
    with pytest.warns(UserWarning, match="auto-applying the CSNE polish"):
        ml = sg.lm_fit(X.astype(np.float32), yl.astype(np.float32),
                       mesh=mesh8, config=NumericConfig(dtype="float32"))
    assert np.max(np.abs(ml.coefficients - bl)) < 1e-3
