"""Profile-likelihood confidence intervals (R's default confint.glm —
the reference has no interval tooling at all)."""

import numpy as np
import pytest
import scipy.stats

import sparkglm_tpu as sg
from sparkglm_tpu.models.profile import confint_profile


def test_profile_gaussian_identity_equals_wald_t(mesh1, rng):
    """For gaussian/identity the deviance is exactly quadratic in beta, so
    the profile interval equals the t-quantile Wald interval — a closed-form
    correctness anchor."""
    n, p = 400, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    y = X @ [1.0, 0.5, -0.3] + 0.4 * rng.normal(size=n)
    m = sg.glm_fit(X, y, family="gaussian", link="identity", tol=1e-12,
                   criterion="absolute", mesh=mesh1)
    ci = confint_profile(m, X, y, mesh=mesh1)
    tq = scipy.stats.t.ppf(0.975, m.df_residual)
    expect = np.stack([m.coefficients - tq * m.std_errors,
                       m.coefficients + tq * m.std_errors], axis=1)
    np.testing.assert_allclose(ci, expect, rtol=2e-3)


def test_profile_logistic_properties(mesh1, rng):
    """Logistic profiles: endpoints bracket the estimate, the deviance at
    each endpoint sits at the chi-square cutoff, and the interval is
    asymmetric the way the likelihood is."""
    n, p = 500, 3
    X = rng.normal(size=(n, p)); X[:, 0] = 1.0
    bt = np.array([0.3, 0.8, -0.5])
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    m = sg.glm_fit(X, y, family="binomial", tol=1e-12,
                   criterion="absolute", mesh=mesh1)
    ci = confint_profile(m, X, y, mesh=mesh1)
    assert np.all(ci[:, 0] < m.coefficients) and np.all(
        m.coefficients < ci[:, 1])
    # endpoint correctness: refit with beta_1 fixed at the upper bound; the
    # deviance rise must equal the 95% chi-square cutoff (z*^2)
    from sparkglm_tpu.models import glm as glm_mod
    zstar2 = scipy.stats.norm.ppf(0.975) ** 2
    keep = [0, 2]
    sub = glm_mod.fit(X[:, keep], y, family="binomial",
                      offset=X[:, 1] * ci[1, 1], tol=1e-12,
                      criterion="absolute", has_intercept=False, mesh=mesh1)
    np.testing.assert_allclose(sub.deviance - m.deviance, zstar2, rtol=0.02)
    # profile and Wald agree loosely at this n, but not exactly
    wald = m.confint()
    assert np.max(np.abs(ci - wald)) < 0.25
    assert np.max(np.abs(ci - wald)) > 1e-4


def test_profile_formula_api_and_which(rng):
    n = 300
    x = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    eta = 0.2 + 0.7 * x + 0.4 * (grp == "b")
    d = {"x": x, "grp": grp,
         "y": (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)}
    m = sg.glm("y ~ x + grp", d, family="binomial", tol=1e-10)
    ci = sg.confint_profile(m, d, which=["x"])
    assert ci.shape == (3, 2)
    assert np.isfinite(ci[1]).all()          # x profiled
    assert np.isnan(ci[0]).all() and np.isnan(ci[2]).all()  # others skipped
    assert ci[1, 0] < m.coefficients[1] < ci[1, 1]


def test_profile_recovers_stored_offset(rng):
    """A by-name fit-time offset enters every constrained refit (omitting
    it would profile the wrong likelihood); array offsets are refused like
    predict()."""
    n = 400
    x = rng.normal(size=n)
    lt = rng.uniform(0.2, 0.8, size=n)
    d = {"x": x, "lt": lt,
         "y": rng.poisson(np.exp(0.3 + 0.5 * x + lt)).astype(float)}
    m = sg.glm("y ~ x + offset(lt)", d, family="poisson", tol=1e-10)
    ci = sg.confint_profile(m, d, which=["x"])
    assert ci[1, 0] < m.coefficients[1] < ci[1, 1]
    # the offset() term and the named offset= spelling recover identically
    m2 = sg.glm("y ~ x", d, family="poisson", offset="lt", tol=1e-10)
    ci2 = sg.confint_profile(m2, d, which=["x"])
    np.testing.assert_allclose(ci2[1], ci[1], rtol=1e-6)
    # and the offset genuinely matters: a no-offset model's interval differs
    m0 = sg.glm("y ~ x", d, family="poisson", tol=1e-10)
    ci0 = sg.confint_profile(m0, d, which=["x"])
    assert np.max(np.abs(ci0[1] - ci[1])) > 1e-3
    m_arr = sg.glm("y ~ x", d, family="poisson", offset=lt, tol=1e-10)
    with pytest.raises(ValueError, match="array offset"):
        sg.confint_profile(m_arr, d)


def test_profile_na_omission_and_error_surfacing(rng):
    n = 200
    x = rng.normal(size=n)
    d = {"x": x.copy(),
         "y": (rng.random(n) < 1 / (1 + np.exp(-0.5 * x))).astype(float)}
    d["x"][7] = np.nan
    m = sg.glm("y ~ x", d, family="binomial", tol=1e-10)
    ci = sg.confint_profile(m, d, which=["x"])  # NA row dropped, not NaN-X
    assert np.isfinite(ci[1]).all()
    # real input errors surface instead of becoming 'flat likelihood' NaNs
    from sparkglm_tpu.models.profile import confint_profile
    X = np.c_[np.ones(100), rng.normal(size=100)]
    y = (rng.random(100) < 0.5).astype(float)
    mm = sg.glm_fit(X, y, family="binomial")
    with pytest.raises(ValueError):
        confint_profile(mm, X, y, weights=np.ones(7))


def test_profile_aliased_model(mesh1, rng):
    """Aliased (dropped) columns stay out of the constrained refits; their
    own rows are NaN like R's confint on aliased fits."""
    n = 300
    x = rng.normal(size=n)
    X = np.c_[np.ones(n), x, x]  # duplicated column -> aliased
    y = (rng.random(n) < 1 / (1 + np.exp(-0.5 * x))).astype(float)
    m = sg.glm_fit(X, y, family="binomial", singular="drop", mesh=mesh1)
    assert m.aliased[2]
    ci = confint_profile(m, X, y, mesh=mesh1)
    assert np.isfinite(ci[1]).all()       # the kept copy profiles fine
    assert np.isnan(ci[2]).all()          # the aliased one is NaN


def test_profile_offset_col_na_scan(rng):
    """A NaN in the stored offset column must drop its row exactly as the
    fit did — not crash every constrained refit."""
    n = 200
    x = rng.normal(size=n)
    lt = rng.uniform(0.2, 0.8, size=n)
    lt[7] = np.nan
    d = {"x": x, "lt": lt,
         "y": rng.poisson(np.exp(0.2 + 0.4 * x
                                 + np.nan_to_num(lt))).astype(float)}
    m = sg.glm("y ~ x", d, family="poisson", offset="lt", tol=1e-10)
    assert m.n_obs == n - 1
    ci = sg.confint_profile(m, d, which=["x"])
    assert np.isfinite(ci[1]).all()


def test_theta_ml_nonfinite_mu_raises():
    from sparkglm_tpu.models.negbin import _theta_ml
    with pytest.raises(FloatingPointError, match="non-finite"):
        _theta_ml(np.array([1.0, 2.0, 3.0]),
                  np.array([1.0, np.inf, 2.0]), np.ones(3), 1.0)


def test_profile_validation(mesh1, rng):
    n = 100
    X = rng.normal(size=(n, 2)); X[:, 0] = 1.0
    y = (rng.random(n) < 0.5).astype(float)
    m = sg.glm_fit(X, y, family="binomial", mesh=mesh1)
    with pytest.raises(ValueError, match="level"):
        confint_profile(m, X, y, level=1.5, mesh=mesh1)
    with pytest.raises(ValueError, match="columns"):
        confint_profile(m, X[:, :1], y, mesh=mesh1)
