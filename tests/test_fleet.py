"""Fleet fitting + model-family serving (fleet/, serve.ModelFamily).

The contracts under test, in the order the subsystem makes them:

  * bit-identity: at float64 with ``batch="exact"``, every fleet member
    equals a solo ``glm_fit`` of the SAME padded row layout on a single-
    device mesh — coefficients, std errors, and iteration counts exactly
    (convergence masks make early-converged members inert, so one slow
    member cannot perturb its neighbors);
  * one executable: a whole fleet compiles exactly one IRLS executable
    per pass flavor, and a warm refit of any K <= bucket compiles ZERO;
  * serving: a ModelFamily scores mixed (tenant, x) batches in one
    dispatch, with sticky A/B splits and shadow scoring, and round-trips
    through models/serialize.py with its deploy history.
"""

import os

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.groups import next_bucket, stack_groups
from sparkglm_tpu.fleet import fit_many, glm_fit_fleet, fleet_kernel_cache_size
from sparkglm_tpu.serve import ModelFamily, family_score_cache_size

pytestmark = pytest.mark.fleet


def _segments(rng, sizes, p=3, seed_sep=None):
    """Long-format logistic data with per-group sizes (ragged) and
    per-group coefficients (so iteration counts differ)."""
    groups, Xr, yr = [], [], []
    for g, size in enumerate(sizes):
        X = np.column_stack([np.ones(size),
                             rng.normal(size=(size, p - 1))])
        beta = rng.normal(size=p) * (0.3 + 0.9 * g)
        eta = X @ beta
        if seed_sep is not None and g == seed_sep:
            # perfectly separated member: IRLS walks toward the boundary
            # and cannot converge in few iterations
            y = (X[:, 1] > 0).astype(float)
        else:
            y = (rng.random(size) < 1 / (1 + np.exp(-eta))).astype(float)
        groups += [f"g{g}"] * size
        Xr.append(X)
        yr.append(y)
    return np.array(groups), np.vstack(Xr), np.concatenate(yr)


def _solo(Xk, yk, wk, **kw):
    """The parity oracle: a solo fit of the same padded row layout on a
    single-device mesh (fleet members are unsharded per-model fits)."""
    return sg.glm_fit(Xk, yk, weights=wk, family="binomial",
                      has_intercept=True, mesh=sg.single_device_mesh(),
                      **kw)


class TestBitIdentity:
    def test_members_match_solo_fits_exactly(self, rng):
        groups, X, y = _segments(rng, [210, 140, 90, 180])
        labels, Xs, ys, ws, offs, n_real = stack_groups(groups, X, y)
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True)
        assert fleet.group_names == tuple(labels)
        # ragged groups genuinely pad: all sizes differ from the layout
        assert fleet.n_obs == 210 and set(n_real) == {210, 140, 90, 180}
        iters = set()
        for k in range(len(fleet)):
            solo = _solo(Xs[k], ys[k], ws[k])
            m = fleet[k]
            np.testing.assert_array_equal(m.coefficients, solo.coefficients)
            np.testing.assert_array_equal(m.std_errors, solo.std_errors)
            np.testing.assert_array_equal(m.cov_unscaled, solo.cov_unscaled)
            assert m.iterations == solo.iterations
            assert m.converged and solo.converged
            assert m.deviance == solo.deviance
            assert m.null_deviance == solo.null_deviance
            assert m.loglik == solo.loglik
            assert m.aic == solo.aic
            assert m.dispersion == solo.dispersion
            assert m.df_residual == solo.df_residual
            assert m.df_null == solo.df_null
            iters.add(m.iterations)
        # the masked-update claim is only interesting if members genuinely
        # stop at different iterations
        assert len(iters) > 1

    def test_nonconverging_member_does_not_poison_neighbors(self, rng):
        groups, X, y = _segments(rng, [150, 150, 150], seed_sep=1)
        labels, Xs, ys, ws, _, _ = stack_groups(groups, X, y)
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True, max_iter=6)
        assert not fleet.converged[1]          # separated member runs out
        for k in (0, 2):
            solo = _solo(Xs[k], ys[k], ws[k], max_iter=6)
            assert fleet.converged[k] and solo.converged
            np.testing.assert_array_equal(fleet[k].coefficients,
                                          solo.coefficients)
            np.testing.assert_array_equal(fleet[k].std_errors,
                                          solo.std_errors)
            assert fleet[k].iterations == solo.iterations
        # the separated member itself still matches ITS solo fit exactly
        solo1 = _solo(Xs[1], ys[1], ws[1], max_iter=6)
        np.testing.assert_array_equal(fleet[1].coefficients,
                                      solo1.coefficients)

    def test_vmap_mode_same_iterations_roundoff_coefs(self, rng):
        groups, X, y = _segments(rng, [160, 120, 200])
        exact = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True, batch="exact")
        vm = fit_many(y, X, groups=groups, family="binomial",
                      has_intercept=True, batch="vmap")
        # the while_loop batching rule masks per-model carries, so the
        # iteration trajectory is identical; only GEMM reduction order
        # differs (roundoff)
        np.testing.assert_array_equal(exact.iterations, vm.iterations)
        np.testing.assert_array_equal(exact.converged, vm.converged)
        np.testing.assert_allclose(exact.coefficients, vm.coefficients,
                                   rtol=1e-9, atol=1e-12)


class TestCompileContract:
    def test_one_executable_then_warm_refits_free(self, rng):
        # unique row count so no earlier test has warmed these shapes
        n_rows, p = 173, 3
        def fleet_of(K, seed):
            r = np.random.default_rng(seed)
            X = np.zeros((K, n_rows, p))
            X[..., 0] = 1.0
            X[..., 1:] = r.normal(size=(K, n_rows, p - 1))
            y = (r.random((K, n_rows)) < 0.5).astype(float)
            return X, y
        X, y = fleet_of(5, 0)
        before = fleet_kernel_cache_size()
        f1 = glm_fit_fleet(X, y, family="binomial", has_intercept=True)
        assert fleet_kernel_cache_size() - before == 1  # ONE executable
        assert f1.bucket == 8
        # warm refits at any K <= bucket: zero compiles
        for K in (3, 7, 8):
            X, y = fleet_of(K, K)
            before = fleet_kernel_cache_size()
            fk = glm_fit_fleet(X, y, family="binomial", has_intercept=True)
            assert fleet_kernel_cache_size() - before == 0
            assert fk.bucket == 8 and len(fk) == K
        # K over the bucket compiles the next bucket once, then is warm
        X, y = fleet_of(9, 9)
        before = fleet_kernel_cache_size()
        glm_fit_fleet(X, y, family="binomial", has_intercept=True)
        assert fleet_kernel_cache_size() - before == 1

    def test_offset_adds_exactly_one_null_pass_flavor(self, rng):
        # with an intercept AND a nonzero offset the null deviance needs
        # its own fleet pass on the ones design — exactly one more flavor
        n_rows, p, K = 91, 3, 4
        X = np.zeros((K, n_rows, p))
        X[..., 0] = 1.0
        X[..., 1:] = rng.normal(size=(K, n_rows, p - 1))
        y = (rng.random((K, n_rows)) < 0.5).astype(float)
        off = np.full((K, n_rows), 0.25)
        before = fleet_kernel_cache_size()
        glm_fit_fleet(X, y, offset=off, family="binomial",
                      has_intercept=True)
        assert fleet_kernel_cache_size() - before == 2
        before = fleet_kernel_cache_size()
        glm_fit_fleet(X, y, offset=off * 2, family="binomial",
                      has_intercept=True)
        assert fleet_kernel_cache_size() - before == 0

    def test_report_records_executables_and_inertness(self, rng):
        groups, X, y = _segments(rng, [100, 100, 100])
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True, trace=sg.FitTracer())
        blk = fleet.fit_report()["fleet"]
        assert blk["models"] == 3 and blk["bucket"] == 8
        assert blk["executables"] >= 0
        assert blk["models_converged"] == int(fleet.converged.sum())
        # the inert fraction is a nondecreasing ramp ending below 1
        ramp = blk["inert_fraction_per_iter"]
        assert ramp == sorted(ramp) and len(ramp) == blk["iters_max"]


class TestIngestion:
    def test_stack_groups_pads_with_inert_rows(self, rng):
        groups, X, y = _segments(rng, [50, 30])
        labels, Xs, ys, ws, offs, n_real = stack_groups(groups, X, y)
        assert labels == ("g0", "g1")
        assert Xs.shape == (2, 50, 3)
        assert list(n_real) == [50, 30]
        assert (ws[1, 30:] == 0).all() and (Xs[1, 30:] == 0).all()
        # weight-0 padding is exactly inert: same model as the raw rows
        # fitted at the same layout
        fleet = glm_fit_fleet(Xs, ys, weights=ws, family="binomial",
                              has_intercept=True, labels=labels)
        solo = _solo(Xs[1], ys[1], ws[1])
        np.testing.assert_array_equal(fleet["g1"].coefficients,
                                      solo.coefficients)
        assert fleet["g1"].n_obs == 50  # layout rows, like a padded solo
        assert int(fleet.n_ok[1]) == 30  # but only the real rows count

    def test_next_bucket(self):
        assert [next_bucket(k) for k in (1, 8, 9, 250)] == [8, 8, 16, 256]

    def test_glm_fleet_formula_front_end(self, rng):
        n = 300
        data = {"y": (rng.random(n) < 0.4).astype(float),
                "x1": rng.normal(size=n),
                "seg": rng.choice(["a", "b", "c"], n)}
        fleet = sg.glm_fleet("y ~ x1", data, groups="seg",
                             family="binomial")
        assert fleet.group_names == ("a", "b", "c")
        assert fleet.group_name == "seg"
        assert fleet.formula == "y ~ x1"
        assert fleet.terms is not None
        # label and index access agree
        np.testing.assert_array_equal(fleet["b"].coefficients,
                                      fleet[1].coefficients)

    def test_front_end_guards(self, rng):
        # PR 20 legalized engine="sketch", penalty= and mesh= as fleet
        # axes; what REMAINS refused flows through the capability table
        # (sparkglm_tpu/capabilities.py) as a typed CapabilityError —
        # still a ValueError, so existing match= idioms keep working.
        n = 60
        data = {"y": (rng.random(n) < 0.5).astype(float),
                "x1": rng.normal(size=n),
                "seg": rng.choice(["a", "b"], n)}
        enet = sg.ElasticNet(alpha=1.0)
        with pytest.raises(sg.CapabilityError, match="elastic"):
            sg.glm_fleet("y ~ x1", data, groups="seg", engine="elastic")
        with pytest.raises(sg.CapabilityError, match="engine"):
            sg.glm_fleet("y ~ x1", data, groups="seg", engine="qr")
        with pytest.raises(sg.CapabilityError, match="structured"):
            sg.glm_fleet("y ~ x1", data, groups="seg", design="structured")
        # the still-refused PAIRWISE combos of the new axes
        with pytest.raises(sg.CapabilityError, match="mesh"):
            sg.glm_fleet("y ~ x1", data, groups="seg", penalty=enet,
                         mesh=sg.single_device_mesh())
        with pytest.raises(sg.CapabilityError, match="sketch"):
            sg.glm_fleet("y ~ x1", data, groups="seg", penalty=enet,
                         engine="sketch")
        with pytest.raises(sg.CapabilityError, match="start"):
            sg.glm_fleet("y ~ x1", data, groups="seg", penalty=enet,
                         start=np.zeros((2, 2)))
        with pytest.raises(sg.CapabilityError, match="beta0"):
            sg.glm_fleet("y ~ x1", data, groups="seg",
                         beta0=np.zeros(2))
        with pytest.raises(KeyError, match="nope"):
            sg.glm_fleet("y ~ x1", data, groups="nope")


class TestSerialization:
    def test_fleet_roundtrip_members_byte_identical(self, rng, tmp_path):
        groups, X, y = _segments(rng, [120, 80, 100])
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True)
        fp = tmp_path / "fleet.npz"
        fleet.save(str(fp))
        back = sg.load_model(str(fp))
        assert back.group_names == fleet.group_names
        np.testing.assert_array_equal(back.coefficients, fleet.coefficients)
        # indexing a DESERIALIZED fleet serializes byte-identically to
        # indexing the live one (np.savez is byte-deterministic)
        for k in range(len(fleet)):
            a, b = tmp_path / f"a{k}.npz", tmp_path / f"b{k}.npz"
            sg.save_model(fleet[k], str(a))
            sg.save_model(back[k], str(b))
            assert a.read_bytes() == b.read_bytes()

    def test_mesh_fleet_members_serialize_byte_identical(self, rng,
                                                         tmp_path):
        # the r14 byte-determinism contract extended to the mesh axis
        # (PR 20): a MEMBER-sharded fleet gathers its results to host at
        # fit time, so indexing and serialization never see the sharding
        # — sg.save_model(mesh_fleet[k]) is byte-for-byte the unsharded
        # fleet's member at the same bucket
        groups, X, y = _segments(rng, [120, 80, 100])
        mesh = sg.make_mesh()
        n_dev = mesh.shape["data"]
        bucket = max(8, n_dev)  # divisible by the shard count
        sharded = fit_many(y, X, groups=groups, family="binomial",
                           has_intercept=True, mesh=mesh, bucket=bucket)
        plain = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True, bucket=bucket)
        assert sharded.n_member_shards == n_dev
        assert plain.n_member_shards == 1
        np.testing.assert_array_equal(sharded.coefficients,
                                      plain.coefficients)
        np.testing.assert_array_equal(sharded.iterations, plain.iterations)
        for k in range(len(plain)):
            a, b = tmp_path / f"m{k}.npz", tmp_path / f"u{k}.npz"
            sg.save_model(sharded[k], str(a))
            sg.save_model(plain[k], str(b))
            assert a.read_bytes() == b.read_bytes()

    def test_family_roundtrip_with_deploy_history(self, rng, tmp_path):
        groups, X, y = _segments(rng, [100, 100])
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True)
        fam = ModelFamily.from_fleet(fleet, "churn")
        v2 = fam.register("g0", fleet[0], deploy=True)
        assert (fam.deployed_version("g0"), v2) == (2, 2)
        fp = tmp_path / "fam.npz"
        fam.save(str(fp))
        back = sg.load_model(str(fp))
        assert isinstance(back, ModelFamily)
        assert back.tenants() == ("g0", "g1")
        assert back.versions("g0") == (1, 2)
        assert back.deployed_version("g0") == 2
        np.testing.assert_array_equal(back.model("g1").coefficients,
                                      fam.model("g1").coefficients)
        # the deploy HISTORY round-trips: rollback works on the restored
        # family exactly as it would have on the live one
        assert back.rollback("g0") == 1

    def test_schema_version_guard(self, rng, tmp_path):
        import json
        groups, X, y = _segments(rng, [60, 60])
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True)
        fp = tmp_path / "fleet.npz"
        fleet.save(str(fp))
        with np.load(str(fp)) as z:
            meta = json.loads(bytes(z["__meta__"].tobytes()).decode())
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta["schema_version"] = 99
        meta["from_the_future"] = True
        header = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
        np.savez(str(fp), __meta__=header, **arrays)
        with pytest.raises(ValueError, match="schema_version 99"):
            sg.load_model(str(fp))

    def test_mixed_versions_reject_signature_drift(self, rng):
        groups, X, y = _segments(rng, [80, 80])
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True)
        fam = ModelFamily.from_fleet(fleet, "churn")
        other = sg.glm_fit(np.column_stack([np.ones(50),
                                            rng.normal(size=(50, 3))]),
                           (rng.random(50) < 0.5).astype(float),
                           family="binomial")
        with pytest.raises(ValueError, match="signature"):
            fam.register("g0", other)


class TestFamilyScoring:
    @pytest.fixture()
    def family(self, rng):
        groups, X, y = _segments(rng, [200, 150, 180])
        fleet = fit_many(y, X, groups=groups, family="binomial",
                         has_intercept=True)
        return fleet, ModelFamily.from_fleet(fleet, "churn")

    def test_batched_scoring_matches_per_model_predict(self, family, rng):
        fleet, fam = family
        sc = fam.scorer(type="link")
        n = 17
        X = np.column_stack([np.ones(n), rng.normal(size=(n, 2))])
        tenants = rng.choice(fam.tenants(), n)
        out = sc.score(list(tenants), X)
        ref = np.array([fleet.predict(X[i:i + 1], str(tenants[i]))[0]
                        for i in range(n)])
        np.testing.assert_allclose(out, ref, rtol=1e-12)
        resp = fam.scorer(type="response").score(list(tenants), X)
        assert ((0 <= resp) & (resp <= 1)).all()

    def test_padding_rows_inert_and_warm_path_compiles_nothing(
            self, family, rng):
        fleet, fam = family
        sc = fam.scorer(type="link", min_bucket=8)
        X = np.column_stack([np.ones(11), rng.normal(size=(11, 2))])
        tenants = ["g0"] * 11
        out11 = sc.score(tenants, X)        # bucket 16
        out5 = sc.score(tenants[:5], X[:5])  # bucket 8 — different pad
        np.testing.assert_array_equal(out11[:5], out5[:5])
        before = family_score_cache_size()
        again = sc.score(tenants, X)
        assert family_score_cache_size() - before == 0
        np.testing.assert_array_equal(again, out11)

    def test_warmup_prepays_compiles(self, family, rng):
        _, fam = family
        sc = fam.scorer(type="response", min_bucket=8)
        sc.warmup(buckets=(8, 16))
        assert sc.compiles == 0
        X = np.column_stack([np.ones(6), rng.normal(size=(6, 2))])
        sc.score(["g1"] * 6, X)
        assert sc.compiles == 0  # steady state: zero recompiles

    def test_ab_split_sticky_and_scoped_to_challenger(self, family, rng):
        fleet, fam = family
        fam.register("g0", fleet[1])  # v2 for g0: a genuinely different row
        sc = fam.scorer(type="link", challenger={"g0": 2}, ab_fraction=0.5)
        n = 40
        X = np.column_stack([np.ones(n), rng.normal(size=(n, 2))])
        tenants = ["g0"] * (n // 2) + ["g1"] * (n // 2)
        keys = [f"user{i % 10}" for i in range(n)]
        with pytest.raises(ValueError, match="keys"):
            sc.score(tenants, X)
        arm = sc.assignments(tenants, keys)
        assert arm.any() and not arm.all()
        assert not arm[n // 2:].any()  # g1 has no challenger: all champion
        out = sc.score(tenants, X, keys=keys)
        np.testing.assert_array_equal(out, sc.score(tenants, X, keys=keys))
        plain = fam.scorer(type="link").score(tenants, X)
        chall = fleet.predict(X, "g1")  # v2 of g0 IS g1's model
        np.testing.assert_allclose(out[arm], chall[arm], rtol=1e-12)
        np.testing.assert_array_equal(out[~arm], plain[~arm])

    def test_shadow_scores_in_same_dispatch(self, family, rng):
        fleet, fam = family
        fam.register("g2", fleet[0])
        sc = fam.scorer(type="link", shadow={"g2": 2})
        X = np.column_stack([np.ones(8), rng.normal(size=(8, 2))])
        fit, shadow = sc.score(["g2"] * 8, X)
        plain = fam.scorer(type="link").score(["g2"] * 8, X)
        np.testing.assert_array_equal(fit, plain)      # serving unchanged
        np.testing.assert_allclose(shadow, fleet.predict(X, "g0"),
                                   rtol=1e-12)

    def test_deploy_invalidates_scorer_cache(self, family, rng):
        fleet, fam = family
        sc1 = fam.scorer(type="link")
        assert fam.scorer(type="link") is sc1      # cached per generation
        v = fam.register("g1", fleet[0], deploy=True)
        sc2 = fam.scorer(type="link")
        assert sc2 is not sc1
        X = np.column_stack([np.ones(4), rng.normal(size=(4, 2))])
        np.testing.assert_allclose(sc2.score(["g1"] * 4, X),
                                   fleet.predict(X, "g0"), rtol=1e-12)
        fam.rollback("g1")
        np.testing.assert_allclose(
            fam.scorer(type="link").score(["g1"] * 4, X),
            fleet.predict(X, "g1"), rtol=1e-12)
        assert v == 2

    def test_unknown_tenant_is_legible(self, family, rng):
        _, fam = family
        sc = fam.scorer()
        X = np.ones((2, 3))
        with pytest.raises(KeyError, match="not a tenant"):
            sc.score(["nope", "g0"], X)
