"""Test harness: virtual 8-device CPU mesh + float64.

The reference simulates a cluster with local-mode Spark and explicit
partition counts (testData.scala:82, lmPredict$Test.scala:11-35 fits on 1 vs
4 partitions).  Our analogue (SURVEY.md §4): force 8 virtual CPU devices via
XLA_FLAGS and assert 1-device and 8-device meshes agree.  x64 is enabled so
CPU tests can check 1e-6+ parity against float64 oracles; the TPU path runs
float32 (bench.py exercises that).
"""

import os

# belt-and-braces for subprocesses; the in-process settings below are what
# actually matter (this image preloads jax via sitecustomize, so env vars
# alone are too late)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; the XLA flag achieves
    # the same 8 virtual CPU devices as long as the backend has not
    # initialized yet (importing jax alone does not initialize it)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'` under JAX_PLATFORMS=cpu: everything
    # unmarked (including the structured-design suite) is tier-1 by
    # default.  `multichip` tags tests that exercise the 8-virtual-device
    # mesh — they still run in tier-1 on the CPU mesh, and the marker lets
    # real-hardware runs select them (`-m multichip`).  `slow` opts OUT of
    # tier-1 entirely.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 command (-m 'not slow')")
    config.addinivalue_line(
        "markers", "multichip: exercises a multi-device mesh (virtual CPU "
        "devices in tier-1; selectable for real-pod runs)")
    config.addinivalue_line(
        "markers", "penalized: the elastic-net path subsystem "
        "(`make penalized` selects these; still tier-1 by default)")
    config.addinivalue_line(
        "markers", "sketch: the sketched-IRLS engine + sparse designs "
        "(`make sketch` selects these; still tier-1 by default)")
    config.addinivalue_line(
        "markers", "fleet: batched per-segment fleet fitting + model-"
        "family serving (`make fleet` selects these; still tier-1 by "
        "default)")
    config.addinivalue_line(
        "markers", "asyncio: the async replicated serving engine "
        "(`make serve_async` selects these; still tier-1 by default)")
    config.addinivalue_line(
        "markers", "online: the continuous-learning subsystem — decayed "
        "suffstats, drift gates, auto-deploy/rollback (`make online` "
        "selects these; still tier-1 by default)")
    config.addinivalue_line(
        "markers", "obsplane: the runtime observability plane — request-"
        "scoped tracing, SLO flight recorder, telemetry export (`make "
        "obsplane` selects these; still tier-1 by default)")
    config.addinivalue_line(
        "markers", "selfheal: the self-healing serving plane + crash-"
        "durable online journal — replica health, deadlines, hedging, "
        "WAL resume (`make chaos` selects these; still tier-1 by "
        "default)")
    config.addinivalue_line(
        "markers", "tenancy: elastic tenancy under fire — zero-downtime "
        "family growth, sharded online learning, the multi-engine pool "
        "(`make elastic_tenancy` selects these; still tier-1 by default)")
    config.addinivalue_line(
        "markers", "ingest: the process-parallel sharded ingest plane — "
        "worker-count bit-identity, column pruning, sharded-source "
        "resume, reader-death re-reads (`make ingest` selects these; "
        "still tier-1 by default)")
    config.addinivalue_line(
        "markers", "fleet_lattice: the capability lattice + PR 20 fleet "
        "axes — exhaustive fit-or-pointed-error walk, penalized/sketch/"
        "mesh fleet parity (`make fleet_lattice` selects these; still "
        "tier-1 by default)")
    config.addinivalue_line(
        "markers", "robustreg: robust/quantile pseudo-families, the "
        "batched tau path, and differentially private Gramians (`make "
        "robustreg` selects these; still tier-1 by default — distinct "
        "from `robust`, the fault-tolerance suite)")


@pytest.fixture(scope="session")
def mesh1():
    import sparkglm_tpu as sg
    return sg.make_mesh(n_data=1, devices=jax.devices()[:1])


@pytest.fixture(scope="session")
def mesh8():
    import sparkglm_tpu as sg
    return sg.make_mesh(n_data=8)


@pytest.fixture(scope="session")
def mesh42():
    """4-way data x 2-way feature sharding."""
    import sparkglm_tpu as sg
    return sg.make_mesh(n_data=4, n_model=2)


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
