"""Interaction terms (a:b, a*b) — an extension over the reference's
'+'-only grammar (R/pkg/R/utils.R:8-22), with R model.matrix semantics:
products of the component codings, first component varying fastest,
names joined with ':'."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.formula import parse_formula
from sparkglm_tpu.data.model_matrix import Terms, build_terms, transform


# ---------------------------------------------------------------- parser ----

def test_parse_colon_and_star():
    f = parse_formula("y ~ a + b + a:b")
    assert f.predictors == ("a", "b", "a:b")
    f2 = parse_formula("y ~ a*b")
    assert f2.predictors == ("a", "b", "a:b")
    f3 = parse_formula("y ~ a*b*c")
    assert f3.predictors == ("a", "b", "c", "a:b", "a:c", "b:c", "a:b:c")


def test_parse_duplicate_terms_collapse():
    # b:a duplicates a:b (R collapses); a:a collapses to a
    f = parse_formula("y ~ a + b + a:b + b:a")
    assert f.predictors == ("a", "b", "a:b")
    assert parse_formula("y ~ a:a + b").predictors == ("a", "b")
    # a*b after a + b only adds the interaction
    assert parse_formula("y ~ a + b + a*b").predictors == ("a", "b", "a:b")


def test_parse_rejections():
    with pytest.raises(ValueError, match="mixed"):
        parse_formula("y ~ a:b*c")
    with pytest.raises(ValueError, match="invalid name|numeric component"):
        parse_formula("y ~ a:2")
    with pytest.raises(ValueError, match="unsupported formula syntax"):
        parse_formula("y ~ (a + b)*c")
    with pytest.raises(ValueError, match="term removal"):
        parse_formula("y ~ a*b - a")


def test_na_scan_sources_flatten():
    f = parse_formula("y ~ a + a:b + c*d")
    flat = list(dict.fromkeys(c for t in f.predictors for c in t.split(":")))
    assert flat == ["a", "b", "c", "d"]


# ---------------------------------------------------------- model matrix ----

def _mixed_data(n=60, seed=0):
    r = np.random.default_rng(seed)
    return {
        "x": r.normal(size=n),
        "z": r.normal(size=n),
        "cat": r.choice(["a", "b", "c"], size=n),
        "grp": r.choice(["u", "v"], size=n),
    }


def test_numeric_numeric_interaction():
    d = _mixed_data()
    t = build_terms(d, ["x", "z", "x:z"], intercept=True)
    assert t.xnames == ("intercept", "x", "z", "x:z")
    X = transform(d, t, dtype=np.float64)
    np.testing.assert_allclose(X[:, 3], d["x"] * d["z"])


def test_numeric_factor_interaction():
    d = _mixed_data()
    t = build_terms(d, ["x", "cat", "x:cat"], intercept=True)
    assert t.xnames == ("intercept", "x", "cat_b", "cat_c", "x:cat_b", "x:cat_c")
    X = transform(d, t, dtype=np.float64)
    np.testing.assert_allclose(X[:, 4], d["x"] * (d["cat"] == "b"))
    np.testing.assert_allclose(X[:, 5], d["x"] * (d["cat"] == "c"))


def test_factor_factor_interaction_layout():
    """First component varies fastest — R's model.matrix column order."""
    d = _mixed_data()
    t = build_terms(d, ["cat", "grp", "cat:grp"], intercept=True)
    assert t.xnames == ("intercept", "cat_b", "cat_c", "grp_v",
                        "cat_b:grp_v", "cat_c:grp_v")
    X = transform(d, t, dtype=np.float64)
    np.testing.assert_allclose(
        X[:, 4], (d["cat"] == "b") * (d["grp"] == "v"))
    np.testing.assert_allclose(
        X[:, 5], (d["cat"] == "c") * (d["grp"] == "v"))


def test_three_way_interaction():
    d = _mixed_data()
    t = build_terms(d, ["x", "z", "x:z", "cat", "x:z:cat"], intercept=True)
    assert t.xnames == ("intercept", "x", "z", "x:z", "cat_b", "cat_c",
                        "x:z:cat_b", "x:z:cat_c")
    X = transform(d, t, dtype=np.float64)
    np.testing.assert_allclose(X[:, 6], d["x"] * d["z"] * (d["cat"] == "b"))


def test_no_intercept_first_factor_full_k():
    """R's '- 1' rule: the first factor main effect keeps all k levels
    (cell-means coding); later factors stay k-1.  The formula path applies
    it; bare model_matrix keeps the reference's always-k-1 contract."""
    d = _mixed_data()
    t = build_terms(d, ["cat", "grp", "x"], intercept=False,
                    no_intercept_coding="full_k_first")
    assert t.xnames == ("cat_a", "cat_b", "cat_c", "grp_v", "x")
    X = transform(d, t, dtype=np.float64)
    np.testing.assert_allclose(X[:, 0], (d["cat"] == "a").astype(float))
    # reference contract unchanged by default
    t_ref = build_terms(d, ["cat", "grp", "x"], intercept=False)
    assert t_ref.xnames == ("cat_b", "cat_c", "grp_v", "x")
    # formula end-to-end: cell means recover per-group rates
    d["y"] = np.where(d["cat"] == "a", 0.2, 0.9) + 0.0 * d["x"]
    m = sg.lm("y ~ cat - 1", d)
    assert m.xnames == ("cat_a", "cat_b", "cat_c")
    np.testing.assert_allclose(
        m.coefficients, [0.2, 0.9, 0.9], atol=1e-6)


def test_no_intercept_factor_interaction_refused():
    d = _mixed_data()
    with pytest.raises(ValueError, match="no-intercept"):
        build_terms(d, ["x", "cat", "x:cat"], intercept=False,
                    no_intercept_coding="full_k_first")
    with pytest.raises(ValueError, match="no_intercept_coding"):
        build_terms(d, ["x"], intercept=False, no_intercept_coding="bogus")
    # the default reference contract (always k-1) keeps working without an
    # intercept — only the R-coding mode refuses
    t = build_terms(d, ["x", "cat", "x:cat"], intercept=False)
    assert t.xnames == ("x", "cat_b", "cat_c", "x:cat_b", "x:cat_c")


def test_factor_interaction_requires_margins():
    """R's marginality rule: missing margins flip the factor to full-k
    coding; we refuse non-hierarchical formulas instead of silently
    fitting different contrasts."""
    d = _mixed_data()
    with pytest.raises(ValueError, match="missing the term 'cat'"):
        build_terms(d, ["x", "x:cat"], intercept=True)
    with pytest.raises(ValueError, match="missing the term 'x'"):
        build_terms(d, ["cat", "x:cat"], intercept=True)
    with pytest.raises(ValueError, match="missing the term 'x:z'"):
        build_terms(d, ["x", "z", "cat", "x:z:cat"], intercept=True)
    # numeric-only interactions don't need mains (R codes them identically)
    t = build_terms(d, ["x:z"], intercept=True)
    assert t.xnames == ("intercept", "x:z")


def test_terms_roundtrip_with_design():
    d = _mixed_data()
    t = build_terms(d, ["x", "cat", "x:cat"], intercept=True)
    t2 = Terms.from_dict(t.to_dict())
    assert t2 == t
    np.testing.assert_array_equal(transform(d, t2, dtype=np.float64),
                                  transform(d, t, dtype=np.float64))
    # legacy dicts (r1/r2 models serialized without 'design') still load:
    # every column is its own main-effect term
    legacy = t.to_dict()
    legacy.pop("design")
    legacy["columns"] = ["x", "cat"]
    legacy["xnames"] = ["intercept", "x", "cat_b", "cat_c"]
    t3 = Terms.from_dict(legacy)
    assert t3.design == (("x",), ("cat",))
    assert transform(d, t3, dtype=np.float64).shape[1] == 4


# ------------------------------------------------------------ end to end ----

def test_glm_interaction_matches_manual_design(mesh8, rng):
    n = 3000
    d = _mixed_data(n, seed=3)
    eta = (0.4 + 0.5 * d["x"] - 0.3 * d["z"] + 0.6 * d["x"] * d["z"]
           + 0.5 * (d["cat"] == "b") - 0.2 * (d["cat"] == "c")
           + 0.7 * d["x"] * (d["cat"] == "b"))
    y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
    d["y"] = y
    m = sg.glm("y ~ x*z + cat + x:cat", d, family="binomial",
               tol=1e-10, mesh=mesh8)
    # manual design in the same column order
    Xm = np.column_stack([
        np.ones(n), d["x"], d["z"], d["x"] * d["z"],
        (d["cat"] == "b").astype(float), (d["cat"] == "c").astype(float),
        d["x"] * (d["cat"] == "b"), d["x"] * (d["cat"] == "c")])
    mm = sg.glm_fit(Xm, y, family="binomial", tol=1e-10, mesh=mesh8)
    # the formula path materialises X at f32 (config.dtype); the manual
    # design is f64 under the test harness's x64 — hence ~1e-6 not 1e-10
    np.testing.assert_allclose(m.coefficients, mm.coefficients,
                               rtol=1e-4, atol=1e-7)
    assert m.xnames == ("intercept", "x", "z", "x:z", "cat_b", "cat_c",
                        "x:cat_b", "x:cat_c")


def test_lm_interaction_predict_roundtrip(mesh8, rng, tmp_path):
    n = 500
    d = _mixed_data(n, seed=5)
    d["y"] = (1.0 + 2.0 * d["x"] + 0.5 * (d["grp"] == "v")
              - 1.5 * d["x"] * (d["grp"] == "v") + 0.1 * rng.normal(size=n))
    m = sg.lm("y ~ x * grp", d, mesh=mesh8)
    assert m.xnames == ("intercept", "x", "grp_v", "x:grp_v")
    # scoring new data, including a category absent from the new batch
    new = {"x": np.array([1.0, 2.0]), "grp": np.array(["u", "u"])}
    pred = sg.predict(m, new)
    b = dict(zip(m.xnames, m.coefficients))
    np.testing.assert_allclose(
        pred, b["intercept"] + b["x"] * new["x"], rtol=1e-6)
    # persistence keeps the interaction recipe
    path = str(tmp_path / "m.npz")
    sg.save_model(m, path)
    m2 = sg.load_model(path)
    np.testing.assert_allclose(sg.predict(m2, new), pred, rtol=0, atol=0)


def test_interaction_na_omission_scans_components(mesh8):
    import warnings
    d = _mixed_data(40, seed=7)
    d["z"][5] = np.nan  # z only appears inside the interaction
    d["y"] = np.ones(40)
    d["y"][0] = 0.0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # tiny near-separated fixture
        m = sg.glm("y ~ x + x:z + z", d, family="binomial", max_iter=5,
                   mesh=mesh8)
    assert m.n_obs == 39  # the NaN-z row was dropped
