"""Robust & private fitting (sparkglm_tpu/robustreg) — `make robustreg`.

Four contract groups:

  * ORACLE PARITY — ``sg.quantreg`` / ``family="huber(k)"`` against the
    exact f64 oracles spliced into ``tests/fixtures/r_golden.json``
    (``gen_golden.py --splice-robust``): an exact-LP quantile solve
    (scipy HiGHS primal) and an exact-weight Huber IRLS, both genuinely
    independent of the smoothed pseudo-families.  Coefficients agree
    within the documented smoothing tolerance (PARITY.md "Robust
    pseudo-families"); the sharper check is NEAR-OPTIMALITY — our
    beta's exact loss sits within a hair of the oracle optimum, which
    is robust to the flat directions extreme taus create.
  * TAU PATH — the batched simultaneous-tau driver matches solo fits
    and the oracle on the same grid; the ``TauPath`` surface.
  * PRIVACY — the zCDP accountant's exact conversions, the calibration
    record, ``privacy=None`` bit-identity, the fixed release schedule
    (``1 + max_iter`` GLM / 1 LM ``dp_noise`` events), NaN statistics,
    seeded reproducibility, and every composition refusal.
  * COMPOSITION — streaming-vs-resident robust parity, fleet-vs-solo
    quantile parity, the OnlineLoop driving a quantile fleet through a
    gated deploy cycle, RetryingSource forwarding the sharded-source
    surface, and mid-path checkpoint/resume bit-identity for the
    penalized streaming drivers.
"""

import json
import math
import os

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig
from sparkglm_tpu.obs import FitTracer, RingBufferSink
from sparkglm_tpu.robustreg import (DPSpec, HUBER_K_DEFAULT, Smoothing,
                                    TauPath, ZCDPAccountant, huber_family,
                                    linf_family, quantile_family,
                                    robust_family, robust_spec)
from sparkglm_tpu.robustreg.privacy import calibrate_sigma

pytestmark = pytest.mark.robustreg

F64 = NumericConfig(dtype="float64")
FIX = os.path.join(os.path.dirname(__file__), "fixtures", "r_golden.json")


def _golden():
    with open(FIX) as fh:
        return json.load(fh)["robust_cases"]


def _case_design(case):
    d = {k: np.asarray(v, np.float64) for k, v in case["data"].items()}
    X = np.column_stack([np.ones(len(d["y"])), d["x1"], d["x2"]])
    return d, X, d["y"]


def _check_loss(X, y, b, tau):
    r = y - X @ b
    return float(np.sum(np.where(r >= 0, tau * r, (tau - 1.0) * r)))


def _huber_loss(X, y, b, k):
    a = np.abs(y - X @ b)
    return float(np.sum(np.where(a <= k, 0.5 * a * a, k * a - 0.5 * k * k)))


# ---- oracle parity ----------------------------------------------------------


@pytest.mark.parametrize("cname", ["robust_gaussian", "robust_skewed"])
def test_quantreg_matches_lp_oracle(cname):
    """Solo quantile fits vs the exact-LP oracle: near-optimal exact
    check loss (<= 1e-4 relative) and coefficient agreement within the
    smoothing tolerance.  The reported deviance is 2x the exact
    (eps-free) check loss by contract."""
    case = _golden()[cname]
    d, X, y = _case_design(case)
    for qc in case["quantile"].values():
        tau = qc["tau"]
        m = sg.quantreg(case["formula"], d, tau=tau, max_iter=300,
                        config=F64)
        assert m.converged
        assert m.family == f"quantile({tau:.10g})"
        b = np.asarray(m.coefficients)
        obj = _check_loss(X, y, b, tau)
        assert obj >= qc["objective"] * (1.0 - 1e-9)  # oracle is optimal
        assert obj - qc["objective"] <= 1e-4 * qc["objective"]
        np.testing.assert_allclose(b, qc["coefficients"], atol=5e-2)
        assert m.deviance == pytest.approx(2.0 * obj, rel=1e-5)
        # pseudo-stat contract: loglik/AIC are NaN for robust fits
        assert math.isnan(m.loglik) and math.isnan(m.aic)


@pytest.mark.parametrize("cname", ["robust_gaussian", "robust_skewed"])
def test_huber_matches_exact_irls_oracle(cname):
    """``family="huber(k)"`` (ABSOLUTE k, response units) vs the
    exact-weight Huber IRLS oracle — the smoothed optimum lands on the
    exact one to near machine precision (the Huber loss is smooth at
    the floor, unlike the check loss)."""
    case = _golden()[cname]
    d, X, y = _case_design(case)
    for hc in case["huber"].values():
        k = hc["k"]
        m = sg.glm(case["formula"], d, family=f"huber({k:.10g})",
                   config=F64)
        assert m.converged
        b = np.asarray(m.coefficients)
        np.testing.assert_allclose(b, hc["coefficients"], atol=1e-8)
        obj = _huber_loss(X, y, b, k)
        assert abs(obj - hc["objective"]) <= 1e-9 * hc["objective"] + 1e-12


def test_robust_family_parsing():
    assert robust_spec("quantile(0.9)") == ("quantile", 0.9)
    assert robust_spec("huber") == ("huber", HUBER_K_DEFAULT)
    assert robust_spec("huber(2.5)") == ("huber", 2.5)
    assert robust_spec("l1") == ("l1", 0.0)
    assert robust_spec("gaussian") is None
    assert robust_family("l1").name == "l1"
    with pytest.raises(ValueError, match="not a robust family"):
        robust_family("binomial")
    with pytest.raises(ValueError, match="tau must be in"):
        quantile_family(1.5)
    with pytest.raises(ValueError, match="k must be positive"):
        huber_family(-1.0)
    with pytest.raises(ValueError, match="Smoothing needs"):
        Smoothing(eps0=-0.1)
    with pytest.raises(ValueError, match="Smoothing needs"):
        Smoothing(eps0=1e-8, eps_min=1e-6)


def test_l1_equals_median_quantile():
    """l1 is quantile(0.5) up to a uniform weight scale IRLS is
    invariant to — same coefficients on the same data."""
    case = _golden()["robust_skewed"]
    d, _, _ = _case_design(case)
    m_l1 = sg.glm(case["formula"], d, family="l1", config=F64,
                  max_iter=300)
    m_q = sg.quantreg(case["formula"], d, tau=0.5, config=F64,
                      max_iter=300)
    np.testing.assert_allclose(np.asarray(m_l1.coefficients),
                               np.asarray(m_q.coefficients), atol=1e-6)


@pytest.mark.filterwarnings("ignore:IRLS did not converge")
def test_linf_bounds_residuals(rng):
    """Chebyshev fit: the minimax residual must undercut the OLS max
    residual on data with asymmetric outliers."""
    n = 300
    x = rng.standard_normal(n)
    y = 1.0 + 2.0 * x + rng.uniform(-1.0, 1.0, n)
    y[:8] += 4.0  # one-sided outliers pull OLS, bound linf
    m = sg.glm("y ~ x", {"y": y, "x": x}, family="linf", config=F64,
               max_iter=300)
    ols = sg.lm("y ~ x", {"y": y, "x": x}, config=F64)
    X = np.column_stack([np.ones(n), x])
    r_inf = np.max(np.abs(y - X @ np.asarray(m.coefficients)))
    r_ols = np.max(np.abs(y - X @ np.asarray(ols.coefficients)))
    assert r_inf < r_ols
    # reported deviance for linf IS the max |r| (host f64, eps-free)
    assert m.deviance == pytest.approx(r_inf, rel=1e-6)


# ---- the batched tau path ---------------------------------------------------


@pytest.mark.parametrize("cname", ["robust_gaussian", "robust_skewed"])
def test_tau_path_matches_solo_and_oracle(cname):
    case = _golden()[cname]
    d, X, y = _case_design(case)
    taus = [0.5, 0.9, 0.99]
    tp = sg.quantreg(case["formula"], d, tau=taus, max_iter=300,
                     config=F64)
    assert isinstance(tp, TauPath)
    assert tp.taus == tuple(taus)
    assert tp.converged.all()
    assert tp.xnames == ("intercept", "x1", "x2")
    for qc in case["quantile"].values():
        tau = qc["tau"]
        coef = tp.coef(tau)
        assert set(coef) == set(tp.xnames)
        b = np.asarray([coef[nm] for nm in tp.xnames])
        obj = _check_loss(X, y, b, tau)
        assert obj - qc["objective"] <= 1e-4 * qc["objective"]
        # batched path vs the solo fit: both are eps_min-smoothed optima
        solo = sg.quantreg(case["formula"], d, tau=tau, max_iter=300,
                           config=F64)
        np.testing.assert_allclose(b, np.asarray(solo.coefficients),
                                   atol=5e-2)
        k = tp._index(tau)
        assert tp.deviance[k] == pytest.approx(2.0 * obj, rel=1e-5)
    with pytest.raises(KeyError, match="not on the fitted grid"):
        tp.coef(0.42)


def test_tau_path_grid_refusals():
    case = _golden()["robust_gaussian"]
    d, _, _ = _case_design(case)
    with pytest.raises(ValueError, match="mesh=None"):
        sg.quantreg(case["formula"], d, tau=[0.5, 0.9], mesh=object())
    with pytest.raises(ValueError, match="non-empty"):
        sg.quantreg(case["formula"], d, tau=[])


# ---- privacy: accountant + calibration --------------------------------------


def test_zcdp_accountant_conversions():
    # rho_for is the EXACT inverse of epsilon_of
    for eps in (0.25, 1.0, 4.0):
        for delta in (1e-5, 1e-8):
            rho = ZCDPAccountant.rho_for(eps, delta)
            assert ZCDPAccountant.epsilon_of(rho, delta) == \
                pytest.approx(eps, rel=1e-12)
    # hand-checked point: L = ln(1e6), rho = (sqrt(L+1) - sqrt(L))^2
    L = math.log(1e6)
    assert ZCDPAccountant.rho_for(1.0, 1e-6) == \
        pytest.approx((math.sqrt(L + 1) - math.sqrt(L)) ** 2)
    acc = ZCDPAccountant(delta=1e-6)
    assert acc.epsilon() == 0.0
    acc.spend(0.01)
    acc.spend(0.01)
    assert acc.releases == 2
    assert acc.rho == pytest.approx(0.02)
    assert acc.epsilon() == pytest.approx(
        ZCDPAccountant.epsilon_of(0.02, 1e-6))
    with pytest.raises(ValueError, match="non-negative"):
        acc.spend(-1.0)
    with pytest.raises(ValueError, match="delta must be in"):
        ZCDPAccountant(delta=2.0)
    with pytest.raises(ValueError, match="epsilon must be positive"):
        ZCDPAccountant.rho_for(0.0, 1e-6)


def test_calibrate_sigma_record():
    spec = DPSpec(epsilon=2.0, delta=1e-6, clip=3.0, seed=11)
    rec = calibrate_sigma(spec, 6)
    rho = ZCDPAccountant.rho_for(2.0, 1e-6)
    assert rec["mechanism"] == "gaussian-zcdp"
    assert rec["releases"] == 6
    assert rec["rho"] == pytest.approx(rho)
    assert rec["rho_per_release"] == pytest.approx(rho / 6)
    assert rec["sigma"] == pytest.approx(9.0 * math.sqrt(6 / (2 * rho)))
    # the spent rho converts back to exactly the requested budget
    assert rec["epsilon_spent"] == pytest.approx(2.0, rel=1e-12)
    # more releases under the same budget => more noise per release
    assert calibrate_sigma(spec, 12)["sigma"] > rec["sigma"]
    with pytest.raises(ValueError, match="releases"):
        calibrate_sigma(spec, 0)


def test_dpspec_validation():
    with pytest.raises(ValueError, match="epsilon must be positive"):
        DPSpec(epsilon=0.0, delta=1e-6, clip=1.0)
    with pytest.raises(ValueError, match="delta must be in"):
        DPSpec(epsilon=1.0, delta=1.0, clip=1.0)
    with pytest.raises(ValueError, match="clip must be positive"):
        DPSpec(epsilon=1.0, delta=1e-6, clip=0.0)


# ---- privacy: streaming fits ------------------------------------------------


def _dp_design(n=2000, seed=2):
    rng = np.random.default_rng(seed)
    X = np.column_stack([np.ones(n), rng.standard_normal((n, 2))])
    eta = X @ np.array([0.3, 0.8, -0.5])
    yb = (rng.uniform(size=n) < 1 / (1 + np.exp(-eta))).astype(np.float64)
    yg = eta + rng.standard_normal(n)

    def src(y):
        def s():
            for i in range(0, n, 500):
                yield (X[i:i + 500], y[i:i + 500], None, None)
        return s
    return X, yb, yg, src


def test_privacy_none_bit_identical():
    """``privacy=None`` takes none of the DP code paths: byte-identical
    coefficients to a call that never mentions privacy, and no privacy
    record in fit_info."""
    _, yb, yg, src = _dp_design()
    plain = sg.glm_fit_streaming(src(yb), family="binomial", config=F64)
    none = sg.glm_fit_streaming(src(yb), family="binomial", privacy=None,
                                config=F64)
    assert np.asarray(plain.coefficients).tobytes() == \
        np.asarray(none.coefficients).tobytes()
    assert "privacy" not in (none.fit_info or {})
    lp = sg.lm_fit_streaming(src(yg), config=F64)
    ln = sg.lm_fit_streaming(src(yg), privacy=None, config=F64)
    assert np.asarray(lp.coefficients).tobytes() == \
        np.asarray(ln.coefficients).tobytes()


def test_dp_glm_streaming():
    """A DP GLM fit: composed (eps, delta) recorded, the FIXED
    ``1 + max_iter`` release schedule (one ``dp_noise`` event each),
    NaN data-dependent statistics, seeded reproducibility."""
    _, yb, _, src = _dp_design()
    spec = DPSpec(epsilon=4.0, delta=1e-6, clip=2.0, seed=7)
    ring = RingBufferSink(4096)
    m = sg.glm_fit_streaming(src(yb), family="binomial", privacy=spec,
                             max_iter=5, trace=FitTracer(sinks=[ring]),
                             config=F64)
    priv = m.fit_info["privacy"]
    assert priv["epsilon"] == 4.0 and priv["delta"] == 1e-6
    assert priv["releases"] == 6  # init pass + max_iter IRLS passes
    assert priv["epsilon_spent"] == pytest.approx(4.0, rel=1e-12)
    noise_ev = [e for e in ring.events if e.kind == "dp_noise"]
    assert len(noise_ev) == 6
    assert {e.fields["release"] for e in noise_ev} == set(range(6))
    # a data-dependent stop would be an unaccounted release: DP fits run
    # the whole budgeted schedule and report NaN exact statistics
    assert not m.converged and m.iterations == 5
    assert math.isnan(m.deviance) and math.isnan(m.loglik)
    assert np.all(np.isnan(m.std_errors))
    # deterministic (seed, release) noise stream: refits are identical,
    # a different seed is not
    m2 = sg.glm_fit_streaming(src(yb), family="binomial", privacy=spec,
                              max_iter=5, config=F64)
    assert np.asarray(m.coefficients).tobytes() == \
        np.asarray(m2.coefficients).tobytes()
    m3 = sg.glm_fit_streaming(
        src(yb), family="binomial", max_iter=5, config=F64,
        privacy=DPSpec(epsilon=4.0, delta=1e-6, clip=2.0, seed=8))
    assert np.asarray(m.coefficients).tobytes() != \
        np.asarray(m3.coefficients).tobytes()
    # accuracy sanity at this generous budget: near the non-private fit
    plain = sg.glm_fit_streaming(src(yb), family="binomial", max_iter=25,
                                 config=F64)
    np.testing.assert_allclose(np.asarray(m.coefficients),
                               np.asarray(plain.coefficients), atol=0.1)


def test_dp_lm_streaming():
    """The one-pass LM release: a single noised Gramian (releases=1,
    one dp_noise event), NaN summary statistics."""
    _, _, yg, src = _dp_design()
    ring = RingBufferSink(1024)
    m = sg.lm_fit_streaming(
        src(yg), privacy=DPSpec(epsilon=2.0, delta=1e-6, clip=3.0, seed=3),
        trace=FitTracer(sinks=[ring]), config=F64)
    priv = m.fit_info["privacy"]
    assert priv["releases"] == 1
    assert len([e for e in ring.events if e.kind == "dp_noise"]) == 1
    assert math.isnan(m.r_squared) and np.all(np.isnan(m.std_errors))
    plain = sg.lm_fit_streaming(src(yg), config=F64)
    np.testing.assert_allclose(np.asarray(m.coefficients),
                               np.asarray(plain.coefficients), atol=0.3)


def test_dp_and_robust_refusals(tmp_path):
    _, yb, yg, src = _dp_design(n=600)
    spec = DPSpec(epsilon=1.0, delta=1e-6, clip=2.0)
    with pytest.raises(ValueError, match="cannot combine with robust"):
        sg.glm_fit_streaming(src(yb), family="quantile(0.5)",
                             privacy=spec, config=F64)
    with pytest.raises(ValueError, match="checkpoint/resume"):
        sg.glm_fit_streaming(src(yb), family="binomial", privacy=spec,
                             checkpoint=str(tmp_path / "ck.npz"),
                             config=F64)
    with pytest.raises(ValueError, match="checkpoint/resume"):
        sg.lm_fit_streaming(src(yg), privacy=spec,
                            checkpoint=str(tmp_path / "ck2.npz"),
                            config=F64)
    with pytest.raises(TypeError, match="DPSpec"):
        sg.glm_fit_streaming(src(yb), family="binomial", privacy=1.0,
                             config=F64)
    with pytest.raises(ValueError, match="exact streaming engine"):
        sg.glm_fit_streaming(src(yb), family="binomial", privacy=spec,
                             engine="sketch", config=F64)
    with pytest.raises(ValueError, match="cannot stream"):
        sg.glm_fit_streaming(src(yb), family="linf", config=F64)
    with pytest.raises(ValueError, match="engine='sketch'"):
        sg.glm_fit_streaming(src(yb), family="quantile(0.5)",
                             engine="sketch", config=F64)


# ---- composition ------------------------------------------------------------


def test_streaming_robust_matches_resident():
    """The per-host-pass eps schedule (streaming) and the in-loop
    schedule (resident) land on the same eps_min optimum."""
    rng = np.random.default_rng(11)
    n = 900
    x = rng.standard_normal(n)
    y = 0.5 + 1.2 * x + 0.4 * (rng.exponential(1.0, n) - 1.0)
    res = sg.glm("y ~ x", {"y": y, "x": x}, family="quantile(0.9)",
                 config=F64, max_iter=200)
    X = np.column_stack([np.ones(n), x])

    def src():
        for i in range(0, n, 300):
            yield (X[i:i + 300], y[i:i + 300], None, None)

    stream = sg.glm_fit_streaming(src, family="quantile(0.9)", config=F64,
                                  max_iter=200)
    assert res.converged and stream.converged
    np.testing.assert_allclose(np.asarray(stream.coefficients),
                               np.asarray(res.coefficients), atol=1e-4)
    assert stream.deviance == pytest.approx(res.deviance, rel=1e-5)


def test_fleet_quantile_matches_solo():
    """``glm_fleet(..., family="quantile", tau=)`` — each tenant's
    batched fit agrees with its solo ``sg.quantreg`` (same pseudo-family,
    same schedule; the vmapped kernel vs the sharded resident one)."""
    rng = np.random.default_rng(3)
    K, per = 4, 500
    g = np.repeat([f"t{k}" for k in range(K)], per)
    x = rng.standard_normal(K * per)
    scale = np.repeat([0.5, 1.0, 1.5, 2.0], per)
    y = 1.0 + 0.7 * x + scale * (rng.exponential(1.0, K * per) - 1.0)
    data = {"y": y, "x": x, "tenant": g}
    fleet = sg.glm_fleet("y ~ x", data, groups="tenant",
                         family="quantile", tau=0.9, config=F64)
    assert fleet["t0"].family == "quantile(0.9)"
    for k in range(K):
        m = g == f"t{k}"
        solo = sg.quantreg("y ~ x", {"y": y[m], "x": x[m]}, tau=0.9,
                           config=F64)
        fc = np.asarray(fleet[f"t{k}"].coefficients)
        np.testing.assert_allclose(fc, np.asarray(solo.coefficients),
                                   atol=5e-4)


def test_fleet_tau_misuse_refused():
    data = {"y": np.arange(8.0), "x": np.arange(8.0),
            "g": ["a"] * 4 + ["b"] * 4}
    with pytest.raises(ValueError, match="not twice"):
        sg.glm_fleet("y ~ x", data, groups="g", family="quantile(0.9)",
                     tau=0.9)
    with pytest.raises(ValueError, match="robust pseudo-family"):
        sg.glm_fleet("y ~ x", data, groups="g", family="binomial",
                     tau=0.9)


@pytest.mark.filterwarnings("ignore:.*fleet members did not converge")
def test_online_loop_refreshes_quantile_fleet():
    """A quantile(0.9) fleet served through the online loop: drifted
    tenants take the warm-refit path (no closed form for robust
    families), pass the gate, and auto-deploy a new version."""
    from sparkglm_tpu.fleet import glm_fit_fleet
    from sparkglm_tpu.online import OnlineLoop
    from sparkglm_tpu.serve import ModelFamily

    P, K = 3, 4
    labels = tuple(f"t{i}" for i in range(K))
    rng = np.random.default_rng(5)
    beta_a = rng.normal(size=(K, P))
    beta_b = beta_a + 2.5

    def chunk(beta, rows_per, seed):
        r = np.random.default_rng(seed)
        ten, Xs, ys = [], [], []
        for k, t in enumerate(labels):
            Xk = r.normal(size=(rows_per, P))
            ten.extend([t] * rows_per)
            Xs.append(Xk)
            ys.append(Xk @ beta[k]
                      + 0.3 * (r.exponential(1.0, rows_per) - 1.0))
        return np.array(ten), np.concatenate(Xs), np.concatenate(ys)

    X0 = rng.normal(size=(K, 64, P))
    y0 = np.stack([X0[k] @ beta_a[k]
                   + 0.3 * (rng.exponential(1.0, 64) - 1.0)
                   for k in range(K)])
    fleet = glm_fit_fleet(X0, y0, family="quantile(0.9)", link="identity",
                          labels=labels)
    fam = ModelFamily.from_fleet(fleet, "p90")
    ring = RingBufferSink(4096)
    loop = OnlineLoop(fam, rho=0.4, window_rows=64, drift_threshold=0.6,
                      reference_chunks=2, window_chunks=2, min_count=4,
                      watch_chunks=2, trace=ring)
    assert not loop.is_closed_form  # robust => warm refit, never suffstat
    for c in range(4):
        out = loop.step(*chunk(beta_a, 16, 100 + c))
        assert out["drifted"] == ()
    deployed = ()
    for c in range(4):
        out = loop.step(*chunk(beta_b, 16, 200 + c))
        deployed = deployed or out["deployed"]
    assert deployed, "quantile fleet never redeployed under drift"
    kinds = [e.kind for e in ring.events]
    assert "refresh_end" in kinds and "auto_deploy" in kinds
    assert all(fam.deployed_version(t) > 1 for t in deployed)


def test_retrying_source_forwards_sharded_surface():
    """robust/retry.py: wrapping a ShardedSource must come back as a
    RetryingSource that FORWARDS subset/with_workers/__len__/
    process_parallel (narrowing re-wraps, keeping retry), and streams
    the identical chunks."""
    from sparkglm_tpu.data.ingest import ShardedSource
    from sparkglm_tpu.robust import (RetryPolicy, RetryingSource,
                                     retrying_source)

    rng = np.random.default_rng(9)
    n, p, nchunks = 800, 3, 8
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p - 1))])
    y = X @ np.array([0.2, 1.0, -0.7]) + rng.standard_normal(n)
    rows = n // nchunks

    def read_chunk(i):
        s = i * rows
        return (X[s:s + rows], y[s:s + rows], None, None)

    base = ShardedSource(nchunks, read_chunk)
    policy = RetryPolicy(max_retries=2, base_delay=0.0)
    wrapped = retrying_source(base, policy)
    assert isinstance(wrapped, RetryingSource)
    assert len(wrapped) == nchunks
    assert wrapped.process_parallel == base.process_parallel
    sub = wrapped.subset([0, 2, 4])
    assert isinstance(sub, RetryingSource) and len(sub) == 3
    rebound = wrapped.with_workers(0)
    assert isinstance(rebound, RetryingSource)
    assert rebound.with_workers(1).process_parallel
    # a plain generator factory still gets the generator wrapper
    assert not isinstance(retrying_source(lambda: iter(()), policy),
                          RetryingSource)
    # and the wrapped source streams the same fit, byte for byte
    ref = sg.lm_fit_streaming(base, config=F64)
    out = sg.lm_fit_streaming(wrapped, config=F64)
    assert np.asarray(ref.coefficients).tobytes() == \
        np.asarray(out.coefficients).tobytes()


def test_glm_path_midpath_resume_bit_identical(tmp_path):
    """Penalized streaming checkpoint/resume: kill the fit mid-path
    (after a few lambda boundaries), resume, and match the
    uninterrupted run bit for bit."""
    from sparkglm_tpu.penalized import ElasticNet
    from sparkglm_tpu.penalized import stream as pen_stream

    rng = np.random.default_rng(7)
    n, p = 1200, 6
    X = np.column_stack([np.ones(n), rng.standard_normal((n, p))])
    beta = np.array([-0.3, 1.0, -0.5, 0, 0, 0.8, 0])
    eta = X @ beta
    yb = (rng.uniform(size=n) < 1 / (1 + np.exp(-eta))).astype(np.float64)
    xnames = ("(Intercept)",) + tuple(f"x{i}" for i in range(p))

    def factory():
        for i in range(0, n, 300):
            yield (X[i:i + 300], yb[i:i + 300], None, None)

    class Bomb(Exception):
        pass

    def bomb_factory():
        count = [0]

        def src():
            for i in range(0, n, 300):
                count[0] += 1
                if count[0] > 60:  # several lambdas in, then die
                    raise Bomb("interrupted")
                yield (X[i:i + 300], yb[i:i + 300], None, None)
        return src

    gkw = dict(family="binomial", penalty=ElasticNet(alpha=0.6, n_lambda=8),
               xnames=xnames, has_intercept=True, config=F64)
    ref = pen_stream.glm_path_streaming(factory, **gkw)
    ck = str(tmp_path / "glm_path.npz")
    with pytest.raises(Bomb):
        pen_stream.glm_path_streaming(bomb_factory(), checkpoint=ck, **gkw)
    st = np.load(ck)
    k_saved = int(st["k"])
    st.close()
    assert 0 < k_saved < 8  # genuinely mid-path
    res = pen_stream.glm_path_streaming(factory, checkpoint=ck,
                                        resume=True, **gkw)
    np.testing.assert_array_equal(np.asarray(res.coefficients),
                                  np.asarray(ref.coefficients))
    np.testing.assert_array_equal(np.asarray(res.deviance),
                                  np.asarray(ref.deviance))
    np.testing.assert_array_equal(np.asarray(res.lambdas),
                                  np.asarray(ref.lambdas))
    # resuming under a different family is an identity violation
    with pytest.raises(ValueError, match="binomial/logit path"):
        pen_stream.glm_path_streaming(
            factory, family="poisson", link="log",
            penalty=ElasticNet(alpha=0.6, n_lambda=8), xnames=xnames,
            has_intercept=True, config=F64, checkpoint=ck, resume=True)
