"""Fused Fisher pass v2: trajectory-exact parity + the engine autotuner.

The v2 driver (models/glm.py::_irls_fused_kernel) carries (G, r) in its
loop state, solves first, then measures the deviance of the UPDATED beta
inside the same single data pass — killing the v1 half-step-lagged
deviance.  The acceptance contract here is the strongest one a CPU tier
can state: at float64 the fused engine's XLA twin uses the einsum
kernel's exact ops (design_matvec / design_gramian / shared irls_weights,
ops/fused.py), so coefficients AND iteration counts must be BIT-IDENTICAL
— not close — on every golden case, including prior weights, offsets and
step-halving trajectories.  That bit-identity is also what makes
``engine="auto"`` safe: the autotuner (ops/autotune.py) picks which
engine runs, never what it computes, so probe-timing nondeterminism
cannot leak into results.
"""

import warnings

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig, resolve_precision_schedule
from sparkglm_tpu.obs.trace import FitTracer, RingBufferSink
from sparkglm_tpu.ops import autotune


@pytest.fixture(autouse=True)
def _fresh_autotune_cache():
    """Every test sees an empty process-wide probe cache and leaves none
    behind — seeded verdicts must never bleed between tests."""
    autotune.clear_cache()
    yield
    autotune.clear_cache()


def _traced_fit(X, y, **kw):
    tr = FitTracer([RingBufferSink()])
    m = sg.glm_fit(X, y, trace=tr, **kw)
    return m, tr


def _golden_case(rng, family, link, n=3000, p=6):
    """An f64 design with prior weights and a non-zero offset — the
    ingredients the v1 driver's lagged deviance was most sensitive to."""
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    eta = X @ bt
    if family == "binomial":
        y = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
    elif family == "poisson":
        y = rng.poisson(np.exp(np.clip(eta, -20, 3))).astype(float)
    elif family == "gamma":
        mu = np.exp(np.clip(eta, -10, 3))
        y = rng.gamma(2.0, mu / 2.0)
    else:  # gaussian
        y = eta + rng.normal(size=n)
    w = rng.uniform(0.5, 2.0, size=n)
    off = 0.05 * rng.normal(size=n)
    return X, y, dict(weights=w, offset=off)


# -- tentpole acceptance: f64 bit-identity of coefficients AND iteration
# counts (ISSUE 12: "no lagged-deviance extra iteration") -----------------

@pytest.mark.parametrize("family,link", [
    ("binomial", "logit"),
    ("binomial", "probit"),
    ("poisson", "log"),
    ("gamma", "log"),
    ("gaussian", "identity"),
])
def test_f64_bit_identity_and_iteration_parity(mesh1, rng, family, link):
    X, y, kw = _golden_case(rng, family, link)
    kw.update(family=family, link=link, tol=1e-12, criterion="relative",
              max_iter=100, mesh=mesh1)
    m_e, tr_e = _traced_fit(X, y, engine="einsum", **kw)
    m_f, tr_f = _traced_fit(X, y, engine="fused", **kw)
    # bitwise, not allclose: the ref twin runs the einsum kernel's ops
    assert np.array_equal(np.asarray(m_f.coefficients),
                          np.asarray(m_e.coefficients))
    assert m_f.iterations == m_e.iterations
    assert m_f.deviance == m_e.deviance
    assert tr_f.report()["halvings"] == tr_e.report()["halvings"]
    assert m_f.converged and m_e.converged


def test_step_halving_trajectory_bit_identity(mesh1, rng):
    """A deliberately bad beta0 warm start forces dozens of step-halvings
    (empirically ~45 over 10 iterations at this seed): the halving inner
    loop re-runs the FULL pass at each midpoint, so this pins the entire
    halving trajectory — counts, iterations, coefficients — bitwise."""
    n, p = 1000, 4
    X = np.column_stack([np.ones(n), rng.normal(size=(n, p - 1))])
    bt = np.array([0.3, 0.8, -0.5, 0.4])
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    b0 = np.array([5.0, -8.0, 9.0, -7.0])
    kw = dict(family="binomial", tol=1e-12, criterion="relative",
              max_iter=100, beta0=b0, mesh=mesh1)
    m_e, tr_e = _traced_fit(X, y, engine="einsum", **kw)
    m_f, tr_f = _traced_fit(X, y, engine="fused", **kw)
    assert tr_e.report()["halvings"] > 0  # the trigger actually fired
    assert tr_f.report()["halvings"] == tr_e.report()["halvings"]
    assert m_f.iterations == m_e.iterations
    assert np.array_equal(np.asarray(m_f.coefficients),
                          np.asarray(m_e.coefficients))


def test_binomial_m_groups_bit_identity(mesh1, rng):
    n, p = 2000, 5
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / 4
    mgrp = rng.integers(1, 20, size=n).astype(float)
    prob = 1 / (1 + np.exp(-(X @ bt)))
    counts = rng.binomial(mgrp.astype(int), prob).astype(float)
    kw = dict(family="binomial", m=mgrp, tol=1e-12, max_iter=60, mesh=mesh1)
    m_e = sg.glm_fit(X, counts, engine="einsum", **kw)
    m_f = sg.glm_fit(X, counts, engine="fused", **kw)
    assert np.array_equal(np.asarray(m_f.coefficients),
                          np.asarray(m_e.coefficients))
    assert m_f.iterations == m_e.iterations


def test_f64_iteration_parity_8_devices(mesh8, rng):
    """On the 8-device mesh the fused engine's per-shard psum accumulates
    in a different order than GSPMD's einsum reduction, so coefficients
    agree to f64 roundoff rather than bitwise — but the iteration COUNT
    (the v1 lagged-deviance regression this PR kills) must still match
    exactly, as must the halving trajectory."""
    X, y, kw = _golden_case(rng, "binomial", "logit")
    kw.update(family="binomial", tol=1e-12, criterion="relative",
              max_iter=100, mesh=mesh8)
    m_e, tr_e = _traced_fit(X, y, engine="einsum", **kw)
    m_f, tr_f = _traced_fit(X, y, engine="fused", **kw)
    assert m_f.iterations == m_e.iterations
    assert tr_f.report()["halvings"] == tr_e.report()["halvings"]
    np.testing.assert_allclose(m_f.coefficients, m_e.coefficients,
                               rtol=1e-10, atol=1e-12)


# -- engine="auto": the measured autotuner --------------------------------

def test_auto_selects_fused_when_probe_says_so(mesh1, rng):
    """ISSUE 12 acceptance: engine='auto' provably selects fused at a
    shape where the probe says it wins — seeded verdict, so the test pins
    the selection logic, not this host's timing."""
    n, p = 4000, 24
    X = rng.normal(size=(n, p))
    X[:, 0] = 1.0
    bt = rng.normal(size=p) / (2 * np.sqrt(p))
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(float)
    autotune.seed_cache(p, np.float64, "cpu", dict(
        engine="fused", p_bucket=autotune.p_bucket(p), dtype="float64",
        platform="cpu", probed=True, einsum_s=1.0, fused_s=0.1,
        use_pallas=False))
    m, tr = _traced_fit(X, y, family="binomial", tol=1e-10, mesh=mesh1)
    assert m.gramian_engine == "fused"
    rec = tr.report()["engine_autotune"]
    assert rec["engine"] == "fused" and rec["cached"] is True
    assert rec["einsum_s"] == 1.0 and rec["fused_s"] == 0.1
    # the chosen engine + probe timings ride the compile/solve events
    evs = {e.kind: e.fields for e in tr.ring().events
           if e.kind in ("compile", "solve")}
    for f in evs.values():
        assert f["gramian_engine"] == "fused"
        assert f["autotune_engine"] == "fused"
        assert f["autotune_fused_s"] == 0.1
    # and the verdict cannot change the numbers: bit-identical to einsum
    m_e = sg.glm_fit(X, y, family="binomial", tol=1e-10, mesh=mesh1,
                     engine="einsum")
    assert np.array_equal(np.asarray(m.coefficients),
                          np.asarray(m_e.coefficients))
    assert m.iterations == m_e.iterations


def test_auto_small_p_skips_probe(mesh8, rng):
    n, p = 500, 3
    X = np.column_stack([np.ones(n), rng.normal(size=(n, p - 1))])
    y = (rng.random(n) < 0.5).astype(float)
    m, tr = _traced_fit(X, y, family="binomial", mesh=mesh8)
    rec = tr.report()["engine_autotune"]
    assert rec["engine"] == "einsum" and rec["probed"] is False
    assert m.gramian_engine == "einsum"


def test_auto_probe_runs_once_per_bucket(monkeypatch):
    calls = []
    real_probe = autotune._probe

    def counting_probe(*a, **k):
        calls.append(a)
        return real_probe(*a, **k)

    monkeypatch.setattr(autotune, "_probe", counting_probe)
    r1 = autotune.choose_engine(20, np.float32)
    r2 = autotune.choose_engine(30, np.float32)  # same 32-bucket
    assert len(calls) == 1
    assert r1["cached"] is False and r2["cached"] is True
    assert r1["engine"] == r2["engine"]
    assert {r1["engine"]}.issubset({"einsum", "fused"})


def test_p_bucket_octaves():
    assert autotune.p_bucket(1) == autotune.AUTOTUNE_MIN_P
    assert autotune.p_bucket(16) == 16
    assert autotune.p_bucket(17) == 32
    assert autotune.p_bucket(512) == 512
    assert autotune.p_bucket(513) == 1024


def test_auto_structured_design_skips_probe_and_stays_einsum(rng):
    """Designs with no fused form must not probe (the probe could pick an
    engine the structured validation would then reject)."""
    from sparkglm_tpu import api

    n = 2000
    df = {"y": rng.normal(size=n), "x1": rng.normal(size=n),
          "f": np.array([f"lv{i:02d}" for i in rng.integers(0, 30, n)])}
    tr = FitTracer([RingBufferSink()])
    m = api.glm("y ~ x1 + f", df, family="gaussian", design="structured",
                trace=tr)
    assert m.gramian_engine == "structured"
    assert tr.report()["engine_autotune"] is None


# -- precision schedule (config.precision_schedule) -----------------------

def test_precision_schedule_resolution():
    assert resolve_precision_schedule(NumericConfig(), on_tpu=True) == "bf16"
    assert resolve_precision_schedule(NumericConfig(), on_tpu=False) == "f32"
    assert resolve_precision_schedule(
        NumericConfig(precision_schedule="f32"), on_tpu=True) == "f32"
    assert resolve_precision_schedule(
        NumericConfig(precision_schedule="bf16"), on_tpu=False) == "bf16"
    with pytest.raises(ValueError, match="precision_schedule"):
        resolve_precision_schedule(
            NumericConfig(precision_schedule="fp8"), on_tpu=True)


def test_precision_schedule_bf16_matches_documented_bound(mesh8, rng):
    """Explicit precision_schedule='bf16' engages the warm-up anywhere
    eligible (CPU included, so tier-1 exercises the exact schedule the
    TPU default runs): coefficients inside the documented 5e-6 bound
    (PARITY.md r16 / benchmarks/BF16_DECISION_r05.md decision rule)."""
    n, p = 40_000, 12
    X = np.column_stack([np.ones(n),
                         rng.standard_normal((n, p - 1))]).astype(np.float32)
    bt = (rng.standard_normal(p) / np.sqrt(p)).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float32)
    kw = dict(family="binomial", tol=1e-8, criterion="relative",
              mesh=mesh8, engine="fused")
    plain = sg.glm_fit(X, y, **kw)
    sched = sg.glm_fit(
        X, y, config=NumericConfig(precision_schedule="bf16"), **kw)
    assert sched.converged
    np.testing.assert_allclose(sched.coefficients, plain.coefficients,
                               rtol=0, atol=5e-6)


def test_precision_schedule_f32_optout_is_plain(mesh8, rng):
    n, p = 10_000, 8
    X = np.column_stack([np.ones(n),
                         rng.standard_normal((n, p - 1))]).astype(np.float32)
    bt = (rng.standard_normal(p) / np.sqrt(p)).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ bt)))).astype(np.float32)
    kw = dict(family="binomial", tol=1e-8, mesh=mesh8, engine="fused")
    plain = sg.glm_fit(X, y, **kw)
    opted = sg.glm_fit(
        X, y, config=NumericConfig(precision_schedule="f32"), **kw)
    assert np.array_equal(np.asarray(plain.coefficients),
                          np.asarray(opted.coefficients))
    assert plain.iterations == opted.iterations


def test_precision_schedule_explicit_warns_when_unhonourable(mesh8, rng):
    """precision_schedule='bf16' on an einsum fit warns like the legacy
    bf16_warmup lever; the AUTO default must stay silent on the same fit
    (a default that warned would spam every CPU einsum fit)."""
    n, p = 2000, 6
    X = np.column_stack([np.ones(n), rng.normal(size=(n, p - 1))])
    y = (rng.random(n) < 0.5).astype(float)
    kw = dict(family="binomial", mesh=mesh8, engine="einsum")
    with pytest.warns(UserWarning, match="cannot honour"):
        sg.glm_fit(X, y, config=NumericConfig(precision_schedule="bf16"),
                   **kw)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        sg.glm_fit(X, y, **kw)  # AUTO: no warning
