"""The driver contract: ``python bench.py`` must print EXACTLY one JSON
line on stdout with {metric, value, unit, vs_baseline} — even when the TPU
tunnel is unreachable (the CPU fallback path).  A malformed line loses the
round's benchmark record, so the contract is CI-enforced."""

import json
import os
import subprocess
import sys


def test_bench_cpu_fallback_contract():
    env = dict(os.environ)
    env["BENCH_FORCE_CPU"] = "1"
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py")],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [ln for ln in r.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected ONE stdout line, got: {lines}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    assert rec["unit"] == "s" and rec["value"] > 0
    assert rec["metric"].endswith("_cpu_fallback")
    # the fallback must not clobber the committed TPU capture
    detail = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                          "bench_detail_latest.json")
    with open(detail) as f:
        assert json.load(f)["platform"] == "tpu"
