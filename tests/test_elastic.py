"""Elastic shard-parallel fitting (sparkglm_tpu.elastic).

The ISSUE-7 contract: round-robin shard fits on preemptible in-process
workers, one-shot combine (exact Gramian addition for LM,
information-weighted averaging for GLM), polishing pass over the
surviving data — with deterministic recovery (a killed worker resumes its
shard bit-for-bit) and graceful degradation (a permanently lost shard
flags ``fit_info["elastic"]["degraded"]`` instead of failing the fit).
"""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.shards import shard_source, surviving_source
from sparkglm_tpu.models import streaming as st
from sparkglm_tpu.obs import FitTracer, RingBufferSink
from sparkglm_tpu.robust import (CheckpointManager, FaultPlan, RetryPolicy,
                                 faulty_source)

NOSLEEP = RetryPolicy(sleep=lambda s: None)
XN = ["(Intercept)", "x1", "x2", "x3"]


def _data(rng, n=600):
    X = np.concatenate([np.ones((n, 1)), rng.normal(size=(n, 3))], axis=1)
    bt = np.array([0.5, -1.0, 0.3, 0.8])
    eta = X @ bt
    yb = (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)
    yl = eta + rng.normal(size=n)
    return X, yb, yl


def _factory(X, y, n_chunks=6):
    n = X.shape[0]

    def source():
        for i in range(n_chunks):
            lo, hi = n * i // n_chunks, n * (i + 1) // n_chunks
            yield lambda lo=lo, hi=hi: (X[lo:hi], y[lo:hi], None, None)

    return source


def _ring():
    ring = RingBufferSink()
    return ring, FitTracer([ring])


# ---------------------------------------------------------------------------
# shard sources
# ---------------------------------------------------------------------------

def test_shard_source_round_robin_and_lazy():
    mats = []

    def chunks():
        for i in range(7):
            yield lambda i=i: mats.append(i) or (i,)

    # shard k gets chunks k, k+3, ... and NEVER materializes the others
    got = [t() for t in shard_source(chunks, 1, 3)()]
    assert [g[0] for g in got] == [1, 4] and mats == [1, 4]
    mats.clear()
    got = [t() for t in surviving_source(chunks, [0, 2], 3)()]
    assert [g[0] for g in got] == [0, 2, 3, 5, 6] and mats == [0, 2, 3, 5, 6]
    with pytest.raises(ValueError):
        shard_source(chunks, 3, 3)
    with pytest.raises(ValueError):
        surviving_source(chunks, [], 3)
    with pytest.raises(ValueError):
        surviving_source(chunks, [5], 3)


# ---------------------------------------------------------------------------
# undisturbed elastic fits vs the single controller
# ---------------------------------------------------------------------------

def test_lm_elastic_matches_single_controller(rng):
    X, _, yl = _data(rng)
    single = st.lm_fit_streaming(_factory(X, yl), xnames=XN,
                                 has_intercept=True)
    m = sg.lm_fit_elastic(_factory(X, yl), workers=3, xnames=XN,
                          has_intercept=True)
    # the combine is EXACT Gramian addition: shard sums agree with the
    # single controller's left-to-right accumulation to summation-order
    # tolerance, and the residual polish runs on the identical chunks
    np.testing.assert_allclose(m.coefficients, single.coefficients,
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(m.std_errors, single.std_errors, rtol=1e-10)
    assert m.n_obs == single.n_obs
    ei = m.fit_info["elastic"]
    assert ei["engine"] == "elastic" and ei["shards"] == 3
    assert ei["shards_fitted"] == 3 and not ei["degraded"]
    assert ei["rows_fitted"] == 600 and ei["lost_row_fraction"] == 0.0
    rb = m.fit_info["robustness"]
    assert rb["shards"] == 3 and rb["shards_lost"] == 0
    assert rb["checkpoint_writes"] >= 3  # one durable state per shard


def test_glm_elastic_matches_single_and_is_deterministic(rng):
    X, yb, _ = _data(rng)
    single = st.glm_fit_streaming(_factory(X, yb), family="binomial",
                                  xnames=XN, has_intercept=True)
    kw = dict(family="binomial", workers=3, xnames=XN, has_intercept=True)
    m1 = sg.glm_fit_elastic(_factory(X, yb), **kw)
    m2 = sg.glm_fit_elastic(_factory(X, yb), **kw)
    # combine + warm-started polish converges to the same optimum
    np.testing.assert_allclose(m1.coefficients, single.coefficients,
                               atol=1e-6)
    assert m1.converged
    # ... and the elastic fit itself is bit-reproducible run-to-run
    np.testing.assert_array_equal(m1.coefficients, m2.coefficients)
    assert m1.deviance == m2.deviance
    assert m1.iterations == m2.iterations
    assert not m1.fit_info["elastic"]["degraded"]


def test_elastic_empty_shards_when_workers_exceed_chunks(rng):
    X, _, yl = _data(rng)
    single = st.lm_fit_streaming(_factory(X, yl), xnames=XN,
                                 has_intercept=True)
    m = sg.lm_fit_elastic(_factory(X, yl), workers=8, xnames=XN,
                          has_intercept=True)
    ei = m.fit_info["elastic"]
    # shards 6,7 see no chunks: empty, NOT lost — nothing degrades
    assert ei["shards_empty"] == [6, 7] and ei["shards_fitted"] == 6
    assert not ei["degraded"]
    np.testing.assert_allclose(m.coefficients, single.coefficients,
                               rtol=1e-12, atol=1e-12)


# ---------------------------------------------------------------------------
# the LM combine rule
# ---------------------------------------------------------------------------

def test_lm_merge_checkpoints(rng, tmp_path):
    X, _, yl = _data(rng)
    chunks = _factory(X, yl)
    states = []
    for k in range(2):
        ck = tmp_path / f"s{k}.npz"
        st.lm_fit_streaming(shard_source(chunks, k, 2), xnames=XN,
                            has_intercept=True, checkpoint=ck)
        states.append(CheckpointManager(ck).load())
    merged = st.lm_merge_checkpoints(states)
    full = tmp_path / "full.npz"
    st.lm_fit_streaming(chunks, xnames=XN, has_intercept=True,
                        checkpoint=full)
    ref = CheckpointManager(full).load()
    # additivity: shard accumulators sum to the full-data accumulators
    np.testing.assert_allclose(merged["XtWX"], ref["XtWX"], rtol=1e-12)
    np.testing.assert_allclose(merged["XtWy"], ref["XtWy"], rtol=1e-12)
    assert int(merged["n"]) == int(ref["n"])
    # the merged fingerprint is shard 0's = the full source's first chunk
    np.testing.assert_array_equal(merged["fingerprint"],
                                  ref["fingerprint"])
    # validation: mixed kinds and mismatched p are refused
    bad = dict(states[0], kind="glm")
    with pytest.raises(ValueError, match="kind"):
        st.lm_merge_checkpoints([states[0], bad])
    with pytest.raises(ValueError, match="design width"):
        st.lm_merge_checkpoints([states[0], dict(states[1], p=99)])
    with pytest.raises(ValueError, match="at least one"):
        st.lm_merge_checkpoints([])


# ---------------------------------------------------------------------------
# preemption: deterministic recovery (the acceptance test)
# ---------------------------------------------------------------------------

def test_elastic_preempted_worker_resumes_bit_identical(rng):
    """Seeded mid-fit worker kill: the shard restarts from its checkpoint
    on a surviving worker and the final coefficients are BIT-IDENTICAL to
    the undisturbed elastic fit."""
    X, yb, _ = _data(rng)
    kw = dict(family="binomial", workers=3, xnames=XN, has_intercept=True)
    base = sg.glm_fit_elastic(_factory(X, yb), **kw)
    # pass 3 = an IRLS pass of some shard fit, after its first durable
    # checkpoint — the restart genuinely RESUMES rather than refitting
    plan = FaultPlan(preempt_chunk_at=((3, 0),))
    ring, tr = _ring()
    m = sg.glm_fit_elastic(faulty_source(_factory(X, yb), plan),
                           trace=tr, **kw)
    assert plan.faults_fired == 1
    np.testing.assert_array_equal(m.coefficients, base.coefficients)
    np.testing.assert_array_equal(m.std_errors, base.std_errors)
    assert m.deviance == base.deviance
    ei = m.fit_info["elastic"]
    assert ei["preemptions"] == 1 and ei["shard_retries"] == 1
    assert not ei["degraded"]
    rb = m.fit_info["robustness"]
    assert rb["shard_retries"] == 1 and rb["resumes"] >= 1
    kinds = [e.kind for e in ring.events]
    assert "retry" in kinds and "combine" in kinds and "polish" in kinds
    # the preempted worker left the pool: its shard restarted elsewhere
    retry = next(e for e in ring.events if e.kind == "retry")
    assert retry.fields["scope"] == "shard"


def test_elastic_preemption_exhausts_budget_degrades(rng):
    """With no retry allowance the preempted shard is LOST, not retried —
    and the fit still completes, degraded."""
    X, yb, _ = _data(rng)
    plan = FaultPlan(preempt_chunk_at=((0, 0),))
    m = sg.glm_fit_elastic(
        faulty_source(_factory(X, yb), plan), family="binomial", workers=3,
        xnames=XN, has_intercept=True,
        retry=RetryPolicy(max_retries=0, sleep=lambda s: None))
    ei = m.fit_info["elastic"]
    assert ei["degraded"] and ei["shards_lost"] == [0]
    assert "preemption_budget" in ei["lost_reasons"]["0"]
    assert m.converged


# ---------------------------------------------------------------------------
# permanent loss: graceful degradation (the acceptance test)
# ---------------------------------------------------------------------------

def test_elastic_fatal_shard_lost_degrades_gracefully(rng):
    X, yb, _ = _data(rng)
    full = st.glm_fit_streaming(_factory(X, yb), family="binomial",
                                xnames=XN, has_intercept=True)
    kw = dict(family="binomial", workers=3, xnames=XN, has_intercept=True)
    plan = FaultPlan(fatal_at=(2,))
    ring, tr = _ring()
    m = sg.glm_fit_elastic(faulty_source(_factory(X, yb), plan),
                           retry=NOSLEEP, trace=tr, **kw)
    ei = m.fit_info["elastic"]
    assert ei["degraded"] and len(ei["shards_lost"]) == 1
    assert ei["lost_reasons"][str(ei["shards_lost"][0])].startswith("fatal")
    # round-robin keeps shards within one chunk of each other: losing one
    # of three drops about a third of the rows
    assert 0.2 < ei["lost_row_fraction"] < 0.45
    assert ei["rows_fitted"] == 400
    assert m.converged
    # the degraded fit IS the fit on the surviving shards ...
    k = ei["shards_lost"][0]
    survivors = [s for s in range(3) if s != k]
    ref = st.glm_fit_streaming(
        surviving_source(_factory(X, yb), survivors, 3), family="binomial",
        xnames=XN, has_intercept=True)
    np.testing.assert_allclose(m.coefficients, ref.coefficients, atol=1e-6)
    # ... and stays within the documented tolerance of the full-data fit
    # (PARITY r12: O(1/sqrt(n)) statistical noise, not a numerical gap)
    assert np.max(np.abs(np.asarray(m.coefficients)
                         - np.asarray(full.coefficients))) < 0.25
    assert [e.kind for e in ring.events].count("shard_lost") == 1
    assert m.fit_info["robustness"]["shards_lost"] == 1


def test_elastic_transient_retry_layers(rng):
    """A transient chunk failure is absorbed at the innermost layer that
    has a policy: chunk-level retry when ``retry=`` is given (the shard
    never restarts), the scheduler's whole-shard restart otherwise — the
    final fit is bit-identical either way."""
    X, _, yl = _data(rng)
    base = sg.lm_fit_elastic(_factory(X, yl), workers=3, xnames=XN,
                             has_intercept=True)
    plan = FaultPlan(transient_at=(1,))
    m = sg.lm_fit_elastic(faulty_source(_factory(X, yl), plan), workers=3,
                          xnames=XN, has_intercept=True, retry=NOSLEEP)
    assert plan.faults_fired == 1
    assert m.fit_info["robustness"]["retries"] >= 1  # chunk-level
    assert m.fit_info["elastic"]["shard_retries"] == 0
    np.testing.assert_array_equal(m.coefficients, base.coefficients)
    # no retry= -> the shard fit has no chunk-level policy, the failure
    # bubbles to the scheduler, and the shard restarts from checkpoint
    # under the default policy's shared budget (one short real backoff)
    plan2 = FaultPlan(transient_at=(1,))
    m2 = sg.lm_fit_elastic(faulty_source(_factory(X, yl), plan2), workers=3,
                           xnames=XN, has_intercept=True)
    assert plan2.faults_fired == 1
    assert m2.fit_info["elastic"]["shard_retries"] == 1
    np.testing.assert_array_equal(m2.coefficients, base.coefficients)


def test_elastic_no_survivor_raises(rng):
    X, yb, _ = _data(rng)
    plan = FaultPlan(fatal_at=tuple(range(12)))
    with pytest.raises(RuntimeError, match="no shard survived"):
        sg.glm_fit_elastic(faulty_source(_factory(X, yb), plan),
                           family="binomial", workers=2, xnames=XN,
                           has_intercept=True, retry=NOSLEEP)


def test_elastic_deterministic_event_sequence(rng):
    X, yb, _ = _data(rng)
    seqs = []
    for _ in range(2):
        ring, tr = _ring()
        sg.glm_fit_elastic(_factory(X, yb), family="binomial", workers=3,
                           xnames=XN, has_intercept=True, trace=tr)
        seqs.append([(e.seq, e.kind) for e in ring.events])
    assert seqs[0] == seqs[1]
    kinds = [k for _, k in seqs[0]]
    assert kinds.count("shard_start") == 3 == kinds.count("shard_end")
    assert kinds.count("combine") == 1 == kinds.count("polish")


# ---------------------------------------------------------------------------
# a named checkpoint directory survives a controller restart
# ---------------------------------------------------------------------------

def test_elastic_named_checkpoint_dir_resumes_finished_shards(rng,
                                                              tmp_path):
    X, yb, _ = _data(rng)
    kw = dict(family="binomial", workers=3, xnames=XN, has_intercept=True,
              checkpoint=tmp_path / "shards")
    m1 = sg.glm_fit_elastic(_factory(X, yb), **kw)
    # a restarted controller reuses the durable per-shard states: every
    # shard fit resumes from its converged checkpoint (one confirming
    # IRLS step each — the converged solution is a fixpoint to roundoff)
    m2 = sg.glm_fit_elastic(_factory(X, yb), **kw)
    np.testing.assert_allclose(m1.coefficients, m2.coefficients,
                               rtol=1e-12, atol=1e-14)
    assert m2.fit_info["robustness"]["resumes"] >= 3


def test_elastic_validation(rng):
    X, yb, _ = _data(rng)
    with pytest.raises(ValueError, match="workers"):
        sg.glm_fit_elastic(_factory(X, yb), workers=0)
    with pytest.raises(ValueError, match="shards"):
        sg.lm_fit_elastic(_factory(X, yb), workers=2, shards=0)
    with pytest.raises(TypeError, match="DIRECTORY"):
        sg.lm_fit_elastic(_factory(X, yb), workers=2,
                          checkpoint=CheckpointManager("x.npz"))


# ---------------------------------------------------------------------------
# the from-CSV front-end and serving
# ---------------------------------------------------------------------------

def _write_csv(tmp_path, rng, n=400):
    import csv
    X, yb, yl = _data(rng, n=n)
    p = tmp_path / "d.csv"
    with open(p, "w", newline="") as fh:
        w = csv.writer(fh)
        w.writerow(["y", "yl", "x1", "x2", "x3"])
        for i in range(n):
            w.writerow([yb[i], yl[i], X[i, 1], X[i, 2], X[i, 3]])
    return str(p)


def test_from_csv_elastic_parity_predict_and_serve(rng, tmp_path):
    path = _write_csv(tmp_path, rng)
    kw = dict(family="binomial", chunk_bytes=4096)
    single = sg.glm_from_csv("y ~ x1 + x2 + x3", path, **kw)
    m = sg.glm_from_csv("y ~ x1 + x2 + x3", path, engine="elastic",
                        workers=3, **kw)
    np.testing.assert_allclose(m.coefficients, single.coefficients,
                               atol=1e-6)
    assert m.fit_info["elastic"]["shards"] == 3
    assert m.formula == single.formula
    # workers= alone implies elastic
    m2 = sg.glm_from_csv("y ~ x1 + x2 + x3", path, workers=2, **kw)
    assert m2.fit_info["elastic"]["shards"] == 2
    # the fitted model carries Terms: predict and serve work as usual
    new = {"x1": np.array([0.1, -0.2]), "x2": np.array([1.0, 0.0]),
           "x3": np.array([0.5, -0.5])}
    mu = sg.predict(m, new)
    np.testing.assert_allclose(mu, sg.predict(single, new), atol=1e-6)
    sc = sg.Scorer(m)
    np.testing.assert_array_equal(np.asarray(sc.score(new)), np.asarray(mu))
    reg = sg.ModelRegistry()
    reg.register("elastic", m, deploy=True)
    np.testing.assert_array_equal(
        np.asarray(reg.scorer("elastic").score(new)), np.asarray(mu))


def test_from_csv_lm_elastic_parity(rng, tmp_path):
    path = _write_csv(tmp_path, rng)
    single = sg.lm_from_csv("yl ~ x1 + x2 + x3", path, chunk_bytes=4096)
    m = sg.lm_from_csv("yl ~ x1 + x2 + x3", path, chunk_bytes=4096,
                       workers=3)
    # the CSV path parses at the configured (float32 by default) dtype, so
    # shard-order vs controller-order accumulation differs at f32 roundoff
    np.testing.assert_allclose(m.coefficients, single.coefficients,
                               rtol=1e-6, atol=1e-7)
    assert m.fit_info["elastic"]["engine"] == "elastic"


def test_from_csv_elastic_rejections(rng, tmp_path):
    path = _write_csv(tmp_path, rng, n=60)
    with pytest.raises(ValueError, match="engine"):
        sg.glm_from_csv("y ~ x1", path, engine="qr")
    with pytest.raises(ValueError, match="elastic"):
        sg.glm_from_csv("y ~ x1", path, engine="elastic",
                        penalty=sg.ElasticNet(n_lambda=3))
    with pytest.raises(ValueError, match="resume"):
        sg.lm_from_csv("yl ~ x1", path, workers=2, resume=True)
    with pytest.raises(ValueError, match="beta0"):
        sg.glm_from_csv("y ~ x1", path, workers=2, beta0=np.zeros(2))
