"""UX-correctness tests (VERDICT r1 weak #6-#8): non-convergence warnings,
streaming/resident default parity, and fit-time offsets carried into
formula-based prediction (R's ``predict.glm`` model-frame offset semantics).
"""

import inspect
import warnings

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.models import glm as glm_mod
from sparkglm_tpu.models.streaming import glm_fit_streaming


def _poisson_data(rng, n=400, p=4):
    X = rng.standard_normal((n, p))
    X[:, 0] = 1.0
    beta = rng.standard_normal(p) / np.sqrt(p)
    y = rng.poisson(np.exp(np.clip(X @ beta, -5, 5))).astype(np.float64)
    return X, y


def test_nonconvergence_warns(rng):
    X, y = _poisson_data(rng)
    with pytest.warns(UserWarning, match="did not converge"):
        m = glm_mod.fit(X, y, family="poisson", max_iter=1)
    assert not m.converged


def test_streaming_nonconvergence_warns(rng):
    X, y = _poisson_data(rng)
    with pytest.warns(UserWarning, match="did not converge"):
        m = glm_fit_streaming((X, y), family="poisson", max_iter=1,
                              chunk_rows=128)
    assert not m.converged


def test_converged_fit_does_not_warn(rng):
    X, y = _poisson_data(rng)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        m = glm_mod.fit(X, y, family="poisson")
    assert m.converged


def test_streaming_resident_max_iter_defaults_agree():
    # same model family, silently different convergence behavior otherwise
    res = inspect.signature(glm_mod.fit).parameters["max_iter"].default
    stream = inspect.signature(glm_fit_streaming).parameters["max_iter"].default
    assert res == stream == 100


def test_formula_offset_carried_into_predict(rng):
    n = 500
    expo = rng.uniform(0.5, 3.0, n)
    x1 = rng.standard_normal(n)
    y = rng.poisson(expo * np.exp(0.3 + 0.5 * x1)).astype(np.float64)
    data = {"y": y, "x1": x1, "log_expo": np.log(expo)}
    m = sg.glm("y ~ x1", data, family="poisson", offset="log_expo")
    assert m.offset_col == "log_expo"

    pred = sg.predict(m, data)  # must honour the stored offset column
    # identical to passing the offset explicitly through the same path
    expected = sg.predict(m, data, offset=np.log(expo))
    np.testing.assert_allclose(pred, expected, rtol=1e-12)
    # and distinct from silently dropping it (the r1 bug)
    pred0 = sg.predict(m, data, offset=np.zeros(n))
    assert np.max(np.abs(pred - pred0)) > 1e-3


def test_formula_offset_missing_column_raises(rng):
    n = 200
    expo = rng.uniform(0.5, 3.0, n)
    x1 = rng.standard_normal(n)
    y = rng.poisson(expo * np.exp(0.2 * x1)).astype(np.float64)
    m = sg.glm("y ~ x1", {"y": y, "x1": x1, "log_expo": np.log(expo)},
               family="poisson", offset="log_expo")
    with pytest.raises(ValueError, match="offset column"):
        sg.predict(m, {"y": y[:10], "x1": x1[:10]})


def test_array_offset_predict_refuses_silently_dropping(rng):
    # fit-time ARRAY offset cannot be recovered from new data; predicting
    # without it would be off by the exposure factor — must raise
    n = 200
    expo = rng.uniform(0.5, 3.0, n)
    x1 = rng.standard_normal(n)
    y = rng.poisson(expo * np.exp(0.2 * x1)).astype(np.float64)
    m = sg.glm("y ~ x1", {"y": y, "x1": x1}, family="poisson",
               offset=np.log(expo))
    assert m.has_offset and m.offset_col is None
    with pytest.raises(ValueError, match="array offset"):
        sg.predict(m, {"y": y, "x1": x1})
    # explicit offset works
    out = sg.predict(m, {"y": y, "x1": x1}, offset=np.log(expo))
    assert np.all(np.isfinite(out))


def test_zero_weight_rows_do_not_poison_host_stats(rng):
    # a zero-weight row whose linear predictor leaves the valid link domain
    # (gamma inverse link, eta < 0) must not inject NaN into reported stats
    n = 200
    X = np.column_stack([np.ones(n), rng.standard_normal(n)])
    y = rng.gamma(2.0, 2.0, n)
    w = np.ones(n)
    w[0] = 0.0
    X[0, 1] = -50.0
    m = sg.glm_fit(X, y, family="gamma", link="inverse", weights=w)
    for v in (m.deviance, m.null_deviance, m.pearson_chi2, m.loglik, m.aic):
        assert np.isfinite(v)
    # and the excluded row genuinely does not influence the fit
    m2 = sg.glm_fit(X[1:], y[1:], family="gamma", link="inverse",
                    weights=w[1:])
    np.testing.assert_allclose(m.coefficients, m2.coefficients, rtol=1e-8)
    assert m.deviance == pytest.approx(m2.deviance, rel=1e-10)
    # R's glm.fit subsets on weights > 0: df, dispersion, SEs and AIC must
    # all match the fit with the row physically removed
    assert m.df_residual == m2.df_residual
    assert m.dispersion == pytest.approx(m2.dispersion, rel=1e-8)
    np.testing.assert_allclose(m.std_errors, m2.std_errors, rtol=1e-6)
    assert m.aic == pytest.approx(m2.aic, rel=1e-8)


def test_verbose_trace_runs_under_jit(rng, capfd):
    """verbose=True turns on the in-loop iteration trace (the reference's
    only progress signal, GLM.scala:304,461) — it must compile and emit
    per-iteration lines, plus the host-side completion summary.  Since the
    obs rework verbose is the tracer's stderr-sink preset (obs/trace.py),
    so the lines land on stderr via jax.debug.callback."""
    X, y = _poisson_data(rng, n=300)
    m = glm_mod.fit(X, y, family="poisson", verbose=True, max_iter=50)
    import jax
    jax.effects_barrier()
    res = capfd.readouterr()
    out = res.out + res.err
    assert "IRLS finished" in out
    assert "deviance" in out and "iter" in out
    assert m.converged


def test_separation_warns_like_r(rng):
    """Complete separation: R warns 'fitted probabilities numerically 0 or
    1 occurred'; so do we (resident and streaming engines)."""
    n = 400
    x = np.concatenate([rng.uniform(-2, -0.5, n // 2),
                        rng.uniform(0.5, 2, n // 2)])
    y = (x > 0).astype(np.float64)  # perfectly separated
    X = np.column_stack([np.ones(n), x])
    with pytest.warns(UserWarning, match="numerically 0 or 1"):
        glm_mod.fit(X, y, family="binomial", max_iter=30)
    from sparkglm_tpu.models.streaming import glm_fit_streaming
    with pytest.warns(UserWarning, match="numerically 0 or 1"):
        glm_fit_streaming((X, y), family="binomial", max_iter=30,
                          chunk_rows=128)


def test_no_separation_warning_on_clean_fit(rng):
    n = 500
    x = rng.standard_normal(n)
    y = (rng.random(n) < 1 / (1 + np.exp(-0.5 * x))).astype(np.float64)
    X = np.column_stack([np.ones(n), x])
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        glm_mod.fit(X, y, family="binomial")


def test_no_separation_warning_on_rare_events(rng):
    """Legit rare-event model (all fitted p ~ 1e-8): R stays silent — the
    detection threshold is R's ~2e-15 on the UNCLIPPED mu, not the 1e-7
    display clamp (r2 review finding)."""
    n = 5000
    x = rng.standard_normal(n)
    y = np.zeros(n)
    y[:3] = 1.0  # a few events, no separation structure
    X = np.column_stack([np.ones(n), 0.01 * x])
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        m = glm_mod.fit(X, y, family="binomial", max_iter=60, tol=1e-8,
                        criterion="relative")
    assert m.coefficients[0] < -5  # intercept ~ log(3/n), fitted p tiny


def test_offset_col_roundtrips_through_save(tmp_path, rng):
    n = 200
    expo = rng.uniform(0.5, 3.0, n)
    x1 = rng.standard_normal(n)
    y = rng.poisson(expo * np.exp(0.2 * x1)).astype(np.float64)
    data = {"y": y, "x1": x1, "log_expo": np.log(expo)}
    m = sg.glm("y ~ x1", data, family="poisson", offset="log_expo")
    path = str(tmp_path / "m.npz")
    m.save(path)
    from sparkglm_tpu.models.serialize import load_model
    m2 = load_model(path)
    assert m2.offset_col == "log_expo"
    np.testing.assert_allclose(sg.predict(m2, data), sg.predict(m, data))


def test_nan_inputs_get_r_style_messages(rng):
    """Non-finite inputs must be named like R's 'NA/NaN/Inf in ...', not
    misreported as a singular design."""
    n = 60
    X = np.column_stack([np.ones(n), rng.standard_normal(n)])
    y = rng.standard_normal(n)
    y_bad = y.copy()
    y_bad[3] = np.nan
    from sparkglm_tpu.models import lm as lm_mod
    with pytest.raises(ValueError, match="NA/NaN/Inf in 'y'"):
        lm_mod.fit(X, y_bad)
    X_bad = X.copy()
    X_bad[5, 1] = np.inf
    with pytest.raises(ValueError, match="design matrix"):
        lm_mod.fit(X_bad, y)
    yp = np.abs(y) + 1
    with pytest.raises(ValueError, match="NA/NaN/Inf in 'y'"):
        glm_mod.fit(X, np.where(np.arange(n) == 2, np.nan, yp),
                    family="gamma", link="log")
    with pytest.raises(ValueError, match="design matrix"):
        glm_mod.fit(X_bad, yp, family="gamma", link="log")


def test_streaming_nan_inputs_error_and_m_named_correctly(rng):
    """Streaming engines share the R-style NA errors (r2 review: they
    silently excluded NaN rows); a NaN in m must be blamed on 'm', not on
    the y/weights it blends into."""
    n = 200
    X = np.column_stack([np.ones(n), rng.standard_normal(n)])
    y = np.abs(rng.standard_normal(n)) + 1
    y_bad = y.copy()
    y_bad[7] = np.nan
    from sparkglm_tpu.models.streaming import glm_fit_streaming, lm_fit_streaming
    with pytest.raises(ValueError, match="NA/NaN/Inf in 'y'"):
        glm_fit_streaming((X, y_bad), family="gamma", link="log",
                          chunk_rows=64)
    with pytest.raises(ValueError, match="NA/NaN/Inf in 'y'"):
        lm_fit_streaming((X, y_bad), chunk_rows=64)
    X_bad = X.copy()
    X_bad[3, 1] = np.nan
    with pytest.raises(ValueError, match="design matrix"):
        glm_fit_streaming((X_bad, y), family="gamma", link="log",
                          chunk_rows=64)
    # NaN in m blamed on m (it is divided into y and multiplied into wt)
    mg = rng.integers(2, 9, n).astype(float)
    succ = rng.binomial(mg.astype(int), 0.4).astype(float)
    mg[5] = np.nan
    with pytest.raises(ValueError, match="NA/NaN/Inf in 'm'"):
        glm_mod.fit(X, succ, family="binomial", m=mg)
