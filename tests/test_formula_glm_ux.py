"""cbind(successes, failures) responses and offset() formula terms —
R's canonical glm() formula surface (extensions over the reference's
'+'-only parseFormula, R/pkg/R/utils.R:8-22)."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.formula import parse_formula


def test_parse_cbind_and_offset():
    f = parse_formula("cbind(s, fails) ~ x + offset(lt) + grp")
    assert f.response == "s" and f.response2 == "fails"
    assert f.offsets == ("lt",)
    assert f.predictors == ("x", "grp")
    # duplicates collapse; offset anywhere in the chain
    f2 = parse_formula("y ~ offset(a) + x + offset(b) + offset(a)")
    assert f2.offsets == ("a", "b") and f2.predictors == ("x",)


def test_parse_cbind_rejections():
    with pytest.raises(ValueError, match="invalid response"):
        parse_formula("cbind(s) ~ x")
    with pytest.raises(ValueError, match="offset\\(\\) takes a single"):
        parse_formula("y ~ x + offset(log(t))")
    # identifiers merely ENDING in 'offset' are not offset() calls — the
    # call-like residue must fail loudly, not parse as offset + predictor
    with pytest.raises(ValueError,
                       match="unsupported (formula syntax|transform)"):
        parse_formula("y ~ x + my_offset(z)")
    f = parse_formula("y ~ my_offset + x")  # plain column named *_offset
    assert f.predictors == ("my_offset", "x") and f.offsets == ()


def _grouped_data(rng, n=400):
    x = rng.normal(size=n)
    grp = rng.choice(["a", "b"], size=n)
    m = rng.integers(5, 30, size=n).astype(float)
    eta = 0.3 + 0.8 * x - 0.5 * (grp == "b")
    p = 1 / (1 + np.exp(-eta))
    s = rng.binomial(m.astype(int), p).astype(float)
    return {"x": x, "grp": grp, "s": s, "fails": m - s, "m": m}


def test_cbind_matches_m_argument(mesh8, rng):
    d = _grouped_data(rng)
    m1 = sg.glm("cbind(s, fails) ~ x + grp", d, family="binomial", tol=1e-10,
                mesh=mesh8)
    m2 = sg.glm("s ~ x + grp", d, family="binomial", m="m", tol=1e-10,
                mesh=mesh8)
    np.testing.assert_allclose(m1.coefficients, m2.coefficients,
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(m1.deviance, m2.deviance, rtol=1e-10)
    assert m1.yname == "cbind(s, fails)"
    with pytest.raises(ValueError, match="drop the m="):
        sg.glm("cbind(s, fails) ~ x", d, family="binomial", m="m", mesh=mesh8)


def test_cbind_dot_excludes_response_columns(mesh8, rng):
    d = _grouped_data(rng)
    del d["m"]
    m = sg.glm("cbind(s, fails) ~ .", d, family="binomial", tol=1e-10,
               mesh=mesh8)
    assert m.xnames == ("intercept", "x", "grp_b")


def test_offset_term_matches_offset_argument(mesh8, rng):
    n = 500
    x = rng.normal(size=n)
    lt = rng.uniform(0.5, 1.5, size=n)
    lam = np.exp(0.2 + 0.6 * x + lt)
    y = rng.poisson(lam).astype(float)
    d = {"x": x, "y": y, "lt": lt}
    m1 = sg.glm("y ~ x + offset(lt)", d, family="poisson", tol=1e-12,
                mesh=mesh8)
    m2 = sg.glm("y ~ x", d, family="poisson", offset="lt", tol=1e-12,
                mesh=mesh8)
    np.testing.assert_allclose(m1.coefficients, m2.coefficients,
                               rtol=1e-10, atol=1e-12)
    # offset() term + offset= argument SUM, like R
    d["half"] = 0.5 * lt
    m3 = sg.glm("y ~ x + offset(half)", d, family="poisson", offset="half",
                tol=1e-12, mesh=mesh8)
    np.testing.assert_allclose(m3.coefficients, m1.coefficients,
                               rtol=1e-10, atol=1e-12)


def test_offset_term_travels_to_predict(mesh8, rng, tmp_path):
    n = 300
    x = rng.normal(size=n)
    lt = rng.uniform(0.2, 1.0, size=n)
    y = rng.poisson(np.exp(0.3 * x + lt)).astype(float)
    d = {"x": x, "y": y, "lt": lt}
    m = sg.glm("y ~ x + offset(lt)", d, family="poisson", tol=1e-10,
               mesh=mesh8)
    new = {"x": np.array([0.0, 1.0]), "lt": np.array([0.5, 0.5])}
    pred = sg.predict(m, new)
    b = dict(zip(m.xnames, m.coefficients))
    expect = np.exp(b["intercept"] + b["x"] * new["x"] + new["lt"])
    np.testing.assert_allclose(pred, expect, rtol=1e-6)
    # persists through save/load
    path = str(tmp_path / "m.npz")
    sg.save_model(m, path)
    np.testing.assert_allclose(sg.predict(sg.load_model(path), new), pred)
    # missing offset column at scoring is an error, not a silent zero
    with pytest.raises(ValueError, match="offset column"):
        sg.predict(m, {"x": np.array([0.0])})


def test_lm_rejects_cbind_and_supports_offset(rng):
    d = {"y": rng.normal(size=10), "y2": rng.normal(size=10),
         "x": rng.normal(size=10), "t": rng.normal(size=10)}
    with pytest.raises(ValueError, match="cbind"):
        sg.lm("cbind(y, y2) ~ x", d)
    # offset() is SUPPORTED in lm since r3 (R's lm(offset=) semantics —
    # test_lm_inference_extras.py::test_lm_offset_r_semantics)
    m = sg.lm("y ~ x + offset(t)", d)
    assert m.has_offset and m.offset_col == "t"


def test_cbind_na_omission(mesh8, rng):
    d = _grouped_data(rng, n=100)
    d["fails"][3] = np.nan
    # relative criterion: the f32 deviance granularity (~2^-16 at dev~110)
    # cannot meet an absolute 1e-8 under 8-shard summation
    m = sg.glm("cbind(s, fails) ~ x + grp", d, family="binomial", tol=1e-6,
               criterion="relative", mesh=mesh8)
    assert m.converged and m.n_obs == 99
