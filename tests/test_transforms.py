"""Formula transforms — log/sqrt/exp/abs/log2/log10(col) and I(col^k),
evaluated in the model frame like R, usable inside interactions."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.data.formula import parse_formula


def test_parse_transforms():
    f = parse_formula("y ~ log(x) + I(x^2)*g + sqrt(z):w")
    assert f.predictors == ("log(x)", "I(x^2)", "g", "I(x^2):g",
                            "sqrt(z):w")
    # poly is SUPPORTED since r3 — but requires a degree
    with pytest.raises(ValueError, match="needs a degree"):
        parse_formula("y ~ poly(x)")
    assert parse_formula("y ~ poly(x, 3)").predictors == ("poly(x, 3)",)
    with pytest.raises(ValueError, match="unsupported transform"):
        parse_formula("y ~ sin(x)")
    with pytest.raises(ValueError, match="power form"):
        parse_formula("y ~ I(x)")
    with pytest.raises(ValueError, match="2 <= k <= 9"):
        parse_formula("y ~ I(x^12)")


def test_fit_with_transforms_matches_manual(mesh8, rng):
    n = 2000
    x = rng.uniform(0.5, 3.0, size=n)
    z = rng.normal(size=n)
    eta = 0.3 + 0.8 * np.log(x) - 0.2 * x ** 2 + 0.5 * z
    d = {"x": x, "z": z,
         "y": (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)}
    m = sg.glm("y ~ log(x) + I(x^2) + z", d, family="binomial", tol=1e-10,
               mesh=mesh8)
    assert m.xnames == ("intercept", "log(x)", "I(x^2)", "z")
    Xm = np.column_stack([np.ones(n), np.log(x), x ** 2, z])
    mm = sg.glm_fit(Xm, d["y"], family="binomial", tol=1e-10, mesh=mesh8)
    # the formula path materialises the transformed design at f32; the
    # manual design is f64 — near-zero coefficients differ at ~1e-6 abs
    np.testing.assert_allclose(m.coefficients, mm.coefficients,
                               rtol=1e-4, atol=1e-5)
    # scoring new data evaluates the transforms through the stored Terms
    new = {"x": np.array([1.0, 2.0]), "z": np.zeros(2)}
    b = dict(zip(m.xnames, m.coefficients))
    eta_new = (b["intercept"] + b["log(x)"] * np.log(new["x"])
               + b["I(x^2)"] * new["x"] ** 2)
    np.testing.assert_allclose(sg.predict(m, new, type="link"), eta_new,
                               rtol=1e-5)


def test_transform_interaction_with_factor(mesh8, rng):
    n = 1000
    x = rng.uniform(0.5, 2.0, size=n)
    g = rng.choice(["a", "b"], size=n)
    eta = 0.2 + 0.6 * np.log(x) + 0.4 * (g == "b") - 0.7 * np.log(x) * (g == "b")
    d = {"x": x, "g": g,
         "y": (rng.random(n) < 1 / (1 + np.exp(-eta))).astype(float)}
    m = sg.glm("y ~ log(x) * g", d, family="binomial", tol=1e-10, mesh=mesh8)
    assert m.xnames == ("intercept", "log(x)", "g_b", "log(x):g_b")
    Xm = np.column_stack([np.ones(n), np.log(x), (g == "b").astype(float),
                          np.log(x) * (g == "b")])
    mm = sg.glm_fit(Xm, d["y"], family="binomial", tol=1e-10, mesh=mesh8)
    # the formula path materialises the transformed design at f32; the
    # manual design is f64 — near-zero coefficients differ at ~1e-6 abs
    np.testing.assert_allclose(m.coefficients, mm.coefficients,
                               rtol=1e-4, atol=1e-5)


def test_transform_errors(rng):
    n = 50
    d = {"x": rng.uniform(0.5, 2.0, size=n), "g": rng.choice(["a", "b"], n),
         "y": rng.normal(size=n)}
    with pytest.raises(ValueError, match="categorical"):
        sg.lm("y ~ log(g)", d)
    # R's na.action runs after model-frame evaluation: rows where log(x)
    # is undefined drop with a warning (na_omit=False errors instead)
    d2 = {"x": np.linspace(-1.0, 1.0, n), "y": rng.normal(size=n)}
    with pytest.warns(UserWarning, match="non-finite"):
        m = sg.lm("y ~ log(x)", d2)
    assert m.n_obs == np.sum(d2["x"] > 0)
    assert np.all(np.isfinite(m.coefficients))
    with pytest.raises(ValueError, match="non-finite"):
        sg.lm("y ~ log(x)", d2, na_omit=False)


def test_transforms_from_csv(tmp_path, mesh8, rng):
    """Transforms flow through the chunked CSV path with the same
    na.action-after-evaluation semantics as the in-memory fit."""
    import csv as csv_mod
    n = 600
    x = rng.uniform(0.5, 3.0, size=n)
    x[5] = -1.0  # log undefined for one row
    y = rng.poisson(np.exp(0.3 + 0.6 * np.log(np.abs(x)))).astype(float)
    p = tmp_path / "t.csv"
    with open(p, "w", newline="") as fh:
        w = csv_mod.writer(fh)
        w.writerow(["y", "x"])
        for i in range(n):
            w.writerow([y[i], round(x[i], 6)])
    with pytest.warns(UserWarning, match="non-finite"):
        m = sg.glm_from_csv("y ~ log(x)", str(p), family="poisson",
                            chunk_bytes=4 << 10, mesh=mesh8)
    assert m.n_obs == n - 1
    data = sg.read_csv(str(p))
    with pytest.warns(UserWarning, match="non-finite"):
        m_mem = sg.glm("y ~ log(x)", data, family="poisson", mesh=mesh8)
    np.testing.assert_allclose(m.coefficients, m_mem.coefficients,
                               rtol=1e-4, atol=1e-6)


def test_transform_roundtrip_and_update(rng, tmp_path):
    n = 500
    x = rng.uniform(0.5, 3.0, size=n)
    d = {"x": x, "z": rng.normal(size=n),
         "y": 1.0 + 2.0 * np.log(x) + 0.1 * rng.normal(size=n)}
    m = sg.lm("y ~ log(x)", d)
    path = str(tmp_path / "m.npz")
    sg.save_model(m, path)
    m2 = sg.load_model(path)
    new = {"x": np.array([2.0]), "z": np.zeros(1)}
    np.testing.assert_allclose(sg.predict(m2, new), sg.predict(m, new))
    mu = sg.update(m, "~ . + I(x^2)", d)
    assert mu.xnames == ("intercept", "log(x)", "I(x^2)")
    t = sg.drop1(mu, d)
    assert t.row_names == ("<none>", "log(x)", "I(x^2)")
