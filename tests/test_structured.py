"""Factor-aware Gramian engine (data/structured.py + ops/factor_gramian.py).

Covers the ISSUE-5 contract: structured-vs-dense Gramian block equality at
f64 (f32 tolerance documented inline), full fit coefficient agreement for
gaussian/binomial/poisson with interactions crossing a factor, streaming
prefetch=2 bit-identity, the one-executable-per-pass-flavor compile
accounting, 8-device mesh parity, and the superset-categories scoring
regression (matchCols zero-fill, O(1) level lookup).

Accumulation-order note (PARITY.md r10): the segment-sum engine forms the
SAME products as the dense einsum but accumulates them per level instead of
in a row-major MXU contraction, so f32 block agreement is ~eps32-scale
noise, while f64 agreement is ~1e-13 at these sizes.
"""

import dataclasses
import os
import subprocess
import sys
import types

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu import api
from sparkglm_tpu.config import DEFAULT
from sparkglm_tpu.data.model_matrix import (WIDE_FACTOR_LEVELS, build_terms,
                                            transform, transform_structured,
                                            wants_structured)
from sparkglm_tpu.data.structured import StructuredDesign
from sparkglm_tpu.models import glm as glm_mod
from sparkglm_tpu.models import lm as lm_mod
from sparkglm_tpu.obs import FitTracer, MetricsRegistry, RingBufferSink
from sparkglm_tpu.ops.factor_gramian import structured_gramian
from sparkglm_tpu.ops.gramian import weighted_gramian

F64 = dataclasses.replace(DEFAULT, dtype=np.float64)


@pytest.fixture()
def einsum_auto():
    """Pin engine='auto' to the einsum verdict for the widths used here.

    auto resolves via a TIMED probe cached process-wide; on a loaded
    host the probe can misrank einsum vs fused once and the verdict
    sticks for the whole run.  These tests assert dense-vs-structured
    agreement, not this host's timing (the test_fused_v2_parity idiom)."""
    from sparkglm_tpu.ops import autotune
    for p in (64, 128, 256):
        autotune.seed_cache(p, np.float64, "cpu", dict(
            engine="einsum", p_bucket=autotune.p_bucket(p),
            dtype="float64", platform="cpu", probed=True,
            einsum_s=0.1, fused_s=1.0, use_pallas=False))
    yield
    autotune.clear_cache()


def _frame(rng, n=3000, levels=40, levels2=0, dtype=np.float64):
    df = {
        "y": rng.normal(size=n).astype(dtype),
        "x1": rng.normal(size=n).astype(dtype),
        "x2": rng.uniform(0.5, 2.0, size=n).astype(dtype),
        "f": np.array([f"lv{i:03d}" for i in rng.integers(0, levels, n)]),
    }
    if levels2:
        df["g"] = np.array(
            [f"g{i:03d}" for i in rng.integers(0, levels2, n)])
    return df


def _designs(df, formula_cols, rng, dtype=np.float64, intercept=True):
    terms = build_terms(df, columns=formula_cols, intercept=intercept)
    Xd = transform(df, terms, dtype=dtype)
    Xs = transform_structured(df, terms, dtype=dtype)
    return terms, Xd, Xs


# ---------------------------------------------------------------- transform

def test_transform_structured_densify_matches_transform(rng):
    df = _frame(rng, levels2=35)
    terms, Xd, Xs = _designs(df, ["x1", "x2", "f", "g", "x1:f"], rng)
    assert isinstance(Xs, StructuredDesign)
    assert Xs.shape == Xd.shape
    np.testing.assert_array_equal(Xs.densify(), Xd)


def test_wants_structured_threshold(rng):
    n = 500
    narrow = {"y": rng.normal(size=n), "x": rng.normal(size=n),
              "f": np.array([f"l{i}" for i in rng.integers(
                  0, WIDE_FACTOR_LEVELS - 1, n)])}
    # force every level to appear so the kept count is deterministic
    narrow["f"][:WIDE_FACTOR_LEVELS - 1] = [
        f"l{i}" for i in range(WIDE_FACTOR_LEVELS - 1)]
    t_narrow = build_terms(narrow, columns=["x", "f"], intercept=True)
    assert not wants_structured(t_narrow)

    wide = dict(narrow)
    wide["f"] = np.array([f"l{i}" for i in rng.integers(
        0, WIDE_FACTOR_LEVELS + 4, n)])
    wide["f"][:WIDE_FACTOR_LEVELS + 4] = [
        f"l{i}" for i in range(WIDE_FACTOR_LEVELS + 4)]
    t_wide = build_terms(wide, columns=["x", "f"], intercept=True)
    assert wants_structured(t_wide)
    # a wide factor appearing ONLY inside an interaction densifies anyway.
    # build_terms refuses such models (marginality), so exercise the rule
    # on a shim exposing the two attributes wants_structured reads
    t_inter = types.SimpleNamespace(design=(("x",), ("x", "f")),
                                    levels=t_wide.levels)
    assert not wants_structured(t_inter)


# ------------------------------------------------------------------ gramian

def test_structured_gramian_matches_dense_f64(rng):
    df = _frame(rng, levels2=35)
    terms, Xd, Xs = _designs(df, ["x1", "x2", "f", "g", "x1:f"], rng)
    n = Xd.shape[0]
    z = rng.normal(size=n)
    w = rng.uniform(0.1, 2.0, size=n)
    w[::7] = 0.0  # weight-0 rows must be exactly inert
    import jax.numpy as jnp
    Gd, bd = weighted_gramian(jnp.asarray(Xd), jnp.asarray(z),
                              jnp.asarray(w), accum_dtype=jnp.float64)
    Gs, bs = structured_gramian(
        StructuredDesign(jnp.asarray(Xs.dense),
                         tuple(jnp.asarray(i) for i in Xs.idx), Xs.layout),
        jnp.asarray(z), jnp.asarray(w), accum_dtype=jnp.float64)
    assert float(jnp.max(jnp.abs(Gs - Gd))) < 1e-10
    assert float(jnp.max(jnp.abs(bs - bd))) < 1e-10


def test_structured_gramian_f32_tolerance(rng):
    # f32: identical products, different accumulation order (segment
    # scatter-adds vs row-major contraction) — agreement is eps32-scale
    # relative noise, NOT bitwise.  Documented in PARITY.md r10.
    df = _frame(rng, n=5000, dtype=np.float32)
    terms, Xd, Xs = _designs(df, ["x1", "x2", "f"], rng, dtype=np.float32)
    n = Xd.shape[0]
    z = rng.normal(size=n).astype(np.float32)
    w = np.ones(n, np.float32)
    import jax.numpy as jnp
    Gd, bd = weighted_gramian(jnp.asarray(Xd), jnp.asarray(z),
                              jnp.asarray(w), accum_dtype=jnp.float32)
    Gs, bs = structured_gramian(
        StructuredDesign(jnp.asarray(Xs.dense),
                         tuple(jnp.asarray(i) for i in Xs.idx), Xs.layout),
        jnp.asarray(z), jnp.asarray(w), accum_dtype=jnp.float32)
    scale = float(jnp.max(jnp.abs(Gd)))
    assert float(jnp.max(jnp.abs(Gs - Gd))) < 1e-4 * scale
    assert float(jnp.max(jnp.abs(bs - bd))) < 1e-4 * float(
        jnp.max(jnp.abs(bd)) + 1.0)


def test_zero_weight_rows_exactly_inert(rng):
    # corrupting a weight-0 row (dense values AND level index) must not
    # change any Gramian entry — the streaming pad-bucket contract
    df = _frame(rng, n=800)
    terms, Xd, Xs = _designs(df, ["x1", "f"], rng)
    n = Xd.shape[0]
    z = rng.normal(size=n)
    w = np.ones(n)
    w[-50:] = 0.0
    import jax.numpy as jnp

    def gram(sd):
        return structured_gramian(
            StructuredDesign(jnp.asarray(sd.dense),
                             tuple(jnp.asarray(i) for i in sd.idx),
                             sd.layout),
            jnp.asarray(z), jnp.asarray(w), accum_dtype=jnp.float64)

    G0, b0 = gram(Xs)
    D2 = np.array(Xs.dense, copy=True)
    D2[-50:] = 1e9
    ix2 = np.array(Xs.idx[0], copy=True)
    L = Xs.layout.factors[0][1]
    ix2[-50:] = L  # trash bucket, as _bucket_pad/shard_rows pad
    G1, b1 = gram(StructuredDesign(D2, (ix2,), Xs.layout))
    # the trash-bucket index change is free; the dense corruption is
    # annihilated by w=0 (0.0 * 1e9 == 0.0 exactly)
    np.testing.assert_array_equal(np.asarray(G0), np.asarray(G1))
    np.testing.assert_array_equal(np.asarray(b0), np.asarray(b1))


# ----------------------------------------------------------------- full fits

@pytest.mark.parametrize("family", ["gaussian", "binomial", "poisson"])
def test_fit_agreement_across_families(rng, family, einsum_auto):
    df = _frame(rng, n=4000, levels=40)
    eta = (0.3 + 0.5 * df["x1"]
           + 0.02 * np.char.count(df["f"].astype(str), "1"))
    if family == "gaussian":
        df["resp"] = eta + rng.normal(size=len(eta))
    elif family == "binomial":
        df["resp"] = (rng.random(len(eta)) < 1 / (1 + np.exp(-eta))).astype(
            float)
    else:
        df["resp"] = rng.poisson(np.exp(eta)).astype(float)
    # interaction crossing the factor exercises the mixed dense/index layout
    formula = "resp ~ x1 + f + x1:f"
    md = api.glm(formula, df, family=family, design="dense", config=F64)
    ms = api.glm(formula, df, family=family, design="structured", config=F64)
    assert md.gramian_engine == "einsum"
    assert ms.gramian_engine == "structured"
    assert md.iterations == ms.iterations
    assert np.max(np.abs(md.coefficients - ms.coefficients)) < 1e-8
    assert np.max(np.abs(md.std_errors - ms.std_errors)) < 1e-8
    # fit_report carries the engine
    assert ms.fit_report()["gramian_engine"] == "structured"


def test_lm_fit_agreement_with_weights_offset(rng):
    df = _frame(rng, n=3000, levels=36)
    w = rng.uniform(0.2, 3.0, size=3000)
    off = rng.normal(size=3000) * 0.1
    md = api.lm("y ~ x1 + x2 + f", df, weights=w, offset=off,
                design="dense", config=F64)
    ms = api.lm("y ~ x1 + x2 + f", df, weights=w, offset=off,
                design="structured", config=F64)
    assert ms.gramian_engine == "structured"
    assert np.max(np.abs(md.coefficients - ms.coefficients)) < 1e-10
    assert np.max(np.abs(md.std_errors - ms.std_errors)) < 1e-10
    assert abs(md.r_squared - ms.r_squared) < 1e-10


def test_design_auto_picks_structured_when_wide(rng):
    df = _frame(rng, n=2000, levels=WIDE_FACTOR_LEVELS + 8)
    m = api.lm("y ~ x1 + f", df)
    assert m.gramian_engine == "structured"
    df_narrow = _frame(rng, n=2000, levels=6)
    m2 = api.lm("y ~ x1 + f", df_narrow)
    assert m2.gramian_engine == "einsum"


def test_structured_engine_refusals(rng):
    df = _frame(rng, n=500)
    terms, Xd, Xs = _designs(df, ["x1", "f"], rng)
    y = df["y"]
    with pytest.raises(ValueError, match="no structured form"):
        lm_mod.fit(Xs, y, engine="qr")
    with pytest.raises(ValueError, match="no structured form"):
        glm_mod.fit(Xs, (y > 0).astype(float), family="binomial",
                    engine="fused")


# ------------------------------------------------- scoring / superset levels

def test_scoring_superset_categories(rng):
    """Score a frame whose categories strictly superset training's: unseen
    levels take the trash index (the all-zero one-hot row — matchCols
    zero-fill), identically in the dense and structured paths."""
    df = _frame(rng, n=2500, levels=40)
    m = api.lm("y ~ x1 + f", df, config=F64)
    assert m.gramian_engine == "structured"
    new = {
        # f32-representable values: api.predict transforms at the default
        # float32, so the f64 references below stay exact
        "x1": rng.normal(size=200).astype(np.float32).astype(np.float64),
        "f": np.array([f"lv{i:03d}" for i in rng.integers(0, 55, 200)]),
    }
    unseen = np.array([f not in set(df["f"]) for f in new["f"]])
    assert unseen.any(), "fixture must actually contain unseen levels"
    got = api.predict(m, new)
    # dense reference: transform under the SAME fitted terms (and the same
    # default dtype api.predict uses) zero-fills unseen levels
    Xd = transform(new, m.terms)
    want = m.predict(Xd)
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)
    # an unseen-level row's prediction uses only intercept + numerics
    beta = m.coefficients
    base = beta[0] + beta[1] * np.asarray(new["x1"], np.float64)
    np.testing.assert_allclose(got[unseen], base[unseen], rtol=0, atol=1e-12)


def test_structured_predict_pad_to_and_se(rng):
    from sparkglm_tpu.models.scoring import predict_sharded
    df = _frame(rng, n=1500, levels=40)
    m = api.lm("y ~ x1 + f", df, config=F64)
    Xs = transform_structured(df, m.terms, dtype=np.float64)
    full = predict_sharded(Xs, m.coefficients)
    padded = predict_sharded(Xs[:100], m.coefficients, pad_to=256)
    np.testing.assert_array_equal(padded, full[:100])
    # the structured se quadform (blockwise gathers of V, no one-hot
    # materialization) agrees with the dense design's quadform to
    # summation-order noise
    fit_s, se_s = predict_sharded(Xs[:64], m.coefficients, vcov=m.vcov(),
                                  se_fit=True)
    Xd = transform(df, m.terms, dtype=np.float64)[:64]
    fit_d, se_d = predict_sharded(Xd, m.coefficients, vcov=m.vcov(),
                                  se_fit=True)
    np.testing.assert_allclose(fit_s, fit_d, rtol=1e-13, atol=1e-15)
    np.testing.assert_allclose(se_s, se_d, rtol=1e-12, atol=1e-15)


def test_structured_se_512_levels_no_densify(rng):
    """The satellite contract: se_fit on a 512-level factor runs the
    structured quadform — never a (n, 512+) one-hot densification — and
    matches the dense reference through the PUBLIC predict path."""
    from sparkglm_tpu.data.structured import StructuredDesign

    n, L = 4000, 512
    # f32-representable numerics: api.predict transforms at the default
    # float32, so the f64 dense reference below sees identical designs
    df = {
        "x1": rng.normal(size=n).astype(np.float32).astype(np.float64),
        "x2": rng.normal(size=n).astype(np.float32).astype(np.float64),
        "f": np.array([f"lv{i:03d}" for i in rng.integers(0, L, n)]),
    }
    df["y"] = (0.5 + 0.3 * df["x1"] - 0.2 * df["x2"]
               + rng.normal(scale=0.1, size=n))
    m = api.lm("y ~ x1 + x2 + f", df, config=F64)
    assert m.gramian_engine == "structured"
    assert len(np.unique(df["f"])) == L
    fit_s, se_s = api.predict(m, df, se_fit=True)
    # densify() is the ONLY way a StructuredDesign becomes a dense matrix;
    # the scoring path must never call it
    calls = []
    orig = StructuredDesign.densify

    def counting(self, *a, **k):
        calls.append(1)
        return orig(self, *a, **k)

    StructuredDesign.densify = counting
    try:
        fit_s2, se_s2 = api.predict(m, df, se_fit=True)
    finally:
        StructuredDesign.densify = orig
    assert not calls, "structured se_fit densified the design"
    np.testing.assert_array_equal(fit_s2, fit_s)
    np.testing.assert_array_equal(se_s2, se_s)
    # dense reference through the same kernel
    from sparkglm_tpu.models.scoring import predict_sharded
    Xd = transform(df, m.terms, dtype=np.float64)
    fit_d, se_d = predict_sharded(Xd, m.coefficients, vcov=m.vcov(),
                                  se_fit=True)
    np.testing.assert_allclose(fit_s, fit_d, rtol=1e-12, atol=1e-13)
    np.testing.assert_allclose(se_s, se_d, rtol=1e-10, atol=1e-14)


def test_serve_structured_bit_identical_and_no_recompiles(rng):
    from sparkglm_tpu.serve import Scorer
    df = _frame(rng, n=2000, levels=40)
    df["yp"] = rng.poisson(np.exp(0.2 + 0.1 * df["x1"])).astype(float)
    m = api.glm("yp ~ x1 + f", df, family="poisson", config=F64)
    assert m.gramian_engine == "structured"
    sc = Scorer(m, min_bucket=8)
    sc.warmup(buckets=(8, 64))
    req = {"x1": df["x1"][:50], "f": df["f"][:50]}
    out = sc.score(req)
    assert sc.compiles == 0  # bucket 64 was warmed with the structured rep
    np.testing.assert_array_equal(out, api.predict(m, req))


# ------------------------------------------------------------------ streaming

def _chunk_source(df, yname, n_chunks, terms, dtype=np.float64):
    n = len(df[yname])

    def source():
        for c in range(n_chunks):
            lo, hi = n * c // n_chunks, n * (c + 1) // n_chunks

            def thunk(lo=lo, hi=hi):
                sub = {k: v[lo:hi] for k, v in df.items()}
                return (transform_structured(sub, terms, dtype=dtype),
                        np.asarray(sub[yname], np.float64), None, None)
            yield thunk
    return source


def test_streaming_prefetch2_bit_identical(rng):
    df = _frame(rng, n=4096, levels=40)
    df["yb"] = (rng.random(4096) < 0.4).astype(float)
    terms = build_terms(df, columns=["x1", "f"], intercept=True)
    src = _chunk_source(df, "yb", 5, terms)
    kw = dict(family="binomial", xnames=terms.xnames, cache="none",
              config=F64)
    m_seq = sg.glm_fit_streaming(src, **kw)
    m_pre = sg.glm_fit_streaming(src, prefetch=2, **kw)
    assert m_seq.gramian_engine == m_pre.gramian_engine == "structured"
    np.testing.assert_array_equal(m_seq.coefficients, m_pre.coefficients)
    np.testing.assert_array_equal(m_seq.std_errors, m_pre.std_errors)

    src_lm = _chunk_source(df, "y", 5, terms)
    l_seq = sg.lm_fit_streaming(src_lm, xnames=terms.xnames, config=F64)
    l_pre = sg.lm_fit_streaming(src_lm, xnames=terms.xnames, prefetch=2,
                                config=F64)
    assert l_seq.gramian_engine == "structured"
    np.testing.assert_array_equal(l_seq.coefficients, l_pre.coefficients)


def test_streaming_resume_prefetch_structured_bit_identical(rng, tmp_path):
    """Checkpoint ``resume=`` x ``prefetch>=2`` x structured design in ONE
    fit: a structured pipelined fit killed mid-run by a positioned worker
    preemption resumes bit-identically to the undisturbed sequential
    structured run — and agrees with the dense engine to solver tolerance."""
    from sparkglm_tpu.robust import (FaultPlan, SimulatedPreemption,
                                     faulty_source)

    df = _frame(rng, n=4096, levels=40)
    df["yb"] = (rng.random(4096) < 0.4).astype(float)
    terms = build_terms(df, columns=["x1", "f"], intercept=True)
    src = _chunk_source(df, "yb", 5, terms)
    kw = dict(family="binomial", xnames=terms.xnames, cache="none",
              config=F64)
    seq = sg.glm_fit_streaming(src, **kw)
    assert seq.gramian_engine == "structured"

    ck = str(tmp_path / "structured.ckpt")
    plan = FaultPlan(preempt_chunk_at=((3, 1),))  # mid-IRLS worker kill
    with pytest.raises(SimulatedPreemption):
        sg.glm_fit_streaming(faulty_source(src, plan), checkpoint=ck,
                             prefetch=2, **kw)
    assert plan.faults_fired == 1
    m = sg.glm_fit_streaming(src, checkpoint=ck, resume=True, prefetch=2,
                             **kw)
    assert m.gramian_engine == "structured"
    np.testing.assert_array_equal(m.coefficients, seq.coefficients)
    np.testing.assert_array_equal(m.std_errors, seq.std_errors)
    assert m.deviance == seq.deviance

    # structured vs dense: same fit to solver tolerance (different
    # Gramian kernels — bit-identity is within each engine, not across)
    Xd = transform(df, terms, dtype=np.float64)
    dense = glm_mod.fit(Xd, df["yb"], family="binomial",
                        xnames=terms.xnames, config=F64)
    assert np.max(np.abs(m.coefficients - dense.coefficients)) < 1e-8


def test_streaming_matches_resident_structured(rng):
    df = _frame(rng, n=4000, levels=40)
    df["yb"] = (rng.random(4000) < 0.35).astype(float)
    terms = build_terms(df, columns=["x1", "x2", "f"], intercept=True)
    src = _chunk_source(df, "yb", 4, terms)
    ms = sg.glm_fit_streaming(src, family="binomial", xnames=terms.xnames,
                              cache="none", config=F64)
    Xs = transform_structured(df, terms, dtype=np.float64)
    mr = glm_mod.fit(Xs, df["yb"], family="binomial", xnames=terms.xnames,
                     config=F64)
    assert ms.gramian_engine == mr.gramian_engine == "structured"
    assert np.max(np.abs(ms.coefficients - mr.coefficients)) < 1e-8


def test_streaming_structured_chunk_counter(rng):
    df = _frame(rng, n=2048, levels=40)
    df["yb"] = (rng.random(2048) < 0.4).astype(float)
    terms = build_terms(df, columns=["x1", "f"], intercept=True)
    src = _chunk_source(df, "yb", 4, terms)
    reg = MetricsRegistry()
    m = sg.glm_fit_streaming(src, family="binomial", xnames=terms.xnames,
                             cache="none", config=F64,
                             trace=FitTracer([RingBufferSink()],
                                             metrics=reg))
    got = reg.snapshot()["counters"]["streaming.structured_chunks"]
    # 4 chunks per pass x (init pass + iteration passes)
    assert got == 4 * (1 + m.iterations)


def test_streaming_one_executable_per_pass_flavor():
    """Compile-event accounting (acceptance criterion): a structured
    streaming GLM fit compiles exactly ONE executable per pass flavor
    (init + irls), regardless of chunk count.  Runs in a fresh process —
    the chunk-pass jit caches are module-level, so an in-process check
    would be blinded by earlier fits."""
    code = r"""
import numpy as np
import sparkglm_tpu as sg
from sparkglm_tpu.data.model_matrix import build_terms, transform_structured
from sparkglm_tpu.obs import FitTracer, RingBufferSink

rng = np.random.default_rng(0)
n = 4096
df = {"x1": rng.normal(size=n),
      "f": np.array([f"l{i:03d}" for i in rng.integers(0, 40, n)]),
      "yb": (rng.random(n) < 0.4).astype(float)}
terms = build_terms(df, columns=["x1", "f"], intercept=True)

# 5 x 700-row chunks + a 596-row ragged tail: _bucket_pad sizes the bucket
# from the FIRST chunk, so the tail pads up to 700 and every chunk runs the
# same 700-row executable (uneven leading chunks would mint extra shapes)
bounds = [0, 700, 1400, 2100, 2800, 3500, 4096]

def source():
    for lo, hi in zip(bounds, bounds[1:]):
        def thunk(lo=lo, hi=hi):
            sub = {k: v[lo:hi] for k, v in df.items()}
            return (transform_structured(sub, terms, dtype=np.float32),
                    sub["yb"], None, None)
        yield thunk

ring = RingBufferSink()
m = sg.glm_fit_streaming(source, family="binomial", xnames=terms.xnames,
                         cache="none", trace=FitTracer([ring]))
events = [e for e in ring.events if e.kind == "compile"]
targets = sorted(e.fields["target"] for e in events)
assert targets == ["glm_pass:init", "glm_pass:irls"], targets
assert all(e.fields.get("gramian_engine") == "structured" for e in events), [
    e.fields for e in events]
assert m.gramian_engine == "structured"
print("OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=300,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-2000:]
    assert "OK" in r.stdout


# ------------------------------------------------------------------ meshes

@pytest.mark.multichip
def test_mesh8_structured_fit_matches_single_device(rng, mesh1, mesh8):
    df = _frame(rng, n=4096, levels=40)
    df["yb"] = (rng.random(4096) < 0.4).astype(float)
    terms = build_terms(df, columns=["x1", "x2", "f"], intercept=True)
    Xs = transform_structured(df, terms, dtype=np.float64)
    kw = dict(family="binomial", xnames=terms.xnames, config=F64)
    m1 = glm_mod.fit(Xs, df["yb"], mesh=mesh1, **kw)
    m8 = glm_mod.fit(Xs, df["yb"], mesh=mesh8, **kw)
    assert m1.gramian_engine == m8.gramian_engine == "structured"
    assert m1.iterations == m8.iterations
    assert np.max(np.abs(m1.coefficients - m8.coefficients)) < 1e-10
    assert np.max(np.abs(m1.std_errors - m8.std_errors)) < 1e-10

    l1 = lm_mod.fit(Xs, df["y"], mesh=mesh1, xnames=terms.xnames, config=F64)
    l8 = lm_mod.fit(Xs, df["y"], mesh=mesh8, xnames=terms.xnames, config=F64)
    assert np.max(np.abs(l1.coefficients - l8.coefficients)) < 1e-10


@pytest.mark.multichip
def test_shard_rows_structured_pads_trash(rng, mesh8):
    df = _frame(rng, n=1001, levels=40)  # 1001 % 8 != 0 — forces padding
    terms = build_terms(df, columns=["x1", "f"], intercept=True)
    Xs = transform_structured(df, terms, dtype=np.float64)
    from sparkglm_tpu.parallel import mesh as meshlib
    Xdev = meshlib.shard_rows(Xs, mesh8)
    L = Xs.layout.factors[0][1]
    idx_host = np.asarray(Xdev.idx[0])
    assert idx_host.shape[0] == meshlib.padded_rows(1001, mesh8)
    assert (idx_host[1001:] == L).all()  # pad rows sit in the trash bucket
    assert (np.asarray(Xdev.dense)[1001:] == 0.0).all()
