"""The runtime observability plane (ISSUE 14).

Three planes, each with a hard contract:

  * REQUEST TRACING — under a seeded 64-tenant load every served request
    yields a COMPLETE span chain (request_start -> queued -> batched ->
    dispatched -> request_end) that is monotone in the tracer's global
    sequence, with deterministic ids (two seeded runs mint identical
    trace ids).  Traced serving stays bit-identical to untraced and
    compiles nothing after warmup.
  * SLO ENGINE + FLIGHT RECORDER — an injected SLO violation and an
    injected drift episode each produce EXACTLY ONE flight record whose
    header pins the triggering event; the ring dump is deterministic and
    complete for the last N events under wraparound and concurrent
    writers.
  * EXPORT — Prometheus text rendering and the JSONL time-series
    appender read the same registry the engines feed.

Satellites ride along: instrument thread-safety under a hammer
(obs/metrics.py), deterministic span sampling (obs/timing.py
``sample_rate=``), and the shared paired-run gating helper is exercised
by bench.py's contract tests, not here.
"""

import json
import os
import threading

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu import obs
from sparkglm_tpu.fleet import fit_many
from sparkglm_tpu.obs.metrics import MetricsRegistry
from sparkglm_tpu.obs.slo import FlightRecorder, SLOMonitor, SLOSpec
from sparkglm_tpu.obs.timing import reset_span_sampling
from sparkglm_tpu.obs.trace import FitTracer, RingBufferSink, TraceEvent
from sparkglm_tpu.serve import EnginePolicy, ModelFamily

pytestmark = pytest.mark.obsplane

_CHAIN = ["request_start", "queued", "batched", "dispatched", "request_end"]


def _family_64(rng):
    """A 64-tenant gaussian family (closed-form fits keep this fast)."""
    K, p, per = 64, 3, 12
    groups, Xs, ys = [], [], []
    for k in range(K):
        X = np.column_stack([np.ones(per), rng.normal(size=(per, p - 1))])
        y = X @ rng.normal(size=p) + 0.01 * rng.normal(size=per)
        groups += [f"t{k:02d}"] * per
        Xs.append(X)
        ys.append(y)
    fleet = fit_many(np.concatenate(ys), np.vstack(Xs),
                     groups=np.array(groups), family="gaussian",
                     has_intercept=True)
    return ModelFamily.from_fleet(fleet, "fam64")


def _drive(engine, rng, n_requests=150, K=64, p=3):
    futs = []
    for i in range(n_requests):
        t = f"t{int(rng.integers(0, K)):02d}"
        X = rng.normal(size=(int(rng.integers(1, 9)), p))
        X[:, 0] = 1.0
        futs.append((engine.submit(X, tenant=t), X))
    return [(f.result(30), X) for f, X in futs]


def _chains(events, prefix="req-"):
    by_trace = {}
    for e in events:
        tr = e.fields.get("trace", "")
        if isinstance(tr, str) and tr.startswith(prefix):
            by_trace.setdefault(tr, []).append((e.seq, e.kind))
    return by_trace


# ---------------------------------------------------------------------------
# request tracing: the acceptance load test
# ---------------------------------------------------------------------------

def test_seeded_64_tenant_load_complete_ordered_chains(rng):
    fam = _family_64(rng)
    drive_rng = np.random.default_rng(7)
    with obs.Telemetry(slos=[SLOSpec(p99_ms=60000.0)]) as tel:
        eng = fam.async_engine(
            EnginePolicy(max_batch=256, max_wait_ms=0, max_queue=4096,
                         quantum=64),
            telemetry=tel, min_bucket=8)
        eng.scorer.warmup()
        with eng:
            results = _drive(eng, drive_rng)
        assert len(results) == 150
        # traced serving compiles NOTHING after warmup
        assert eng.scorer.compiles == 0
        chains = _chains(tel.events())
        assert len(chains) == 150
        for tr, chain in chains.items():
            chain = sorted(chain)
            # complete AND monotone in the global seq: each request's five
            # stages appear exactly once, in canonical order
            assert [k for _, k in chain] == _CHAIN, (tr, chain)
        # ids are minted from the per-engine admission counter:
        # dense, deterministic, in admission order
        ids = sorted(chains)
        assert ids[0].endswith("-00000001")
        assert ids[-1].endswith(f"-{150:08d}")
        # the report's serving block saw every request
        rep = tel.report()["serving"]
        assert rep["requests"] == 150
        assert rep["batches"] >= 1
    # every request_end carries its batch/replica/queue_wait
    ends = [e for e in tel.events() if e.kind == "request_end"]
    assert all(e.fields["queue_wait"] >= 0 for e in ends)
    assert all(e.fields["batch"].startswith("batch-") for e in ends)


def test_trace_ids_deterministic_across_runs(rng):
    fam = _family_64(rng)

    def run():
        drive_rng = np.random.default_rng(11)
        with obs.Telemetry() as tel:
            with fam.async_engine(telemetry=tel, min_bucket=8) as eng:
                _drive(eng, drive_rng, n_requests=40)
            return sorted(_chains(tel.events()))

    assert run() == run()


def test_traced_serving_bit_identical_to_untraced(rng):
    fam = _family_64(rng)
    X = rng.normal(size=(13, 3))
    X[:, 0] = 1.0
    with fam.async_engine(min_bucket=8) as eng:
        untraced = eng.score(X, tenant="t03")
    with obs.Telemetry(slos=[SLOSpec(p50_ms=30000.0)]) as tel:
        with fam.async_engine(telemetry=tel, min_bucket=8) as eng:
            traced = eng.score(X, tenant="t03")
    assert np.array_equal(np.asarray(untraced), np.asarray(traced))


def test_overload_admission_lands_in_flight_record(tmp_path):
    from sparkglm_tpu.robust import Overloaded

    class _Blocked:
        metrics = None
        name = "blk"

        def __init__(self):
            self.release = threading.Event()

        def score(self, data, *, offset=None):
            assert self.release.wait(10)
            return np.zeros(len(data))

    sc = _Blocked()
    tel = obs.Telemetry(str(tmp_path), slos=[], cooldown_s=0.0)
    from sparkglm_tpu.serve import AsyncEngine
    eng = AsyncEngine(sc, EnginePolicy(max_queue=2, max_batch=4),
                      telemetry=tel)
    try:
        f1 = eng.submit(np.zeros((1, 2)))
        import time as _t
        _t.sleep(0.1)  # let the scheduler park the first batch in-flight
        eng.submit(np.zeros((1, 2)))
        eng.submit(np.zeros((1, 2)))
        with pytest.raises(Overloaded):
            eng.submit(np.zeros((1, 2)))
    finally:
        sc.release.set()
        eng.close()
        tel.close()
    recs = [p for p in tel.flight_records if "admission" in p]
    assert len(recs) == 1
    lines = open(recs[0]).read().splitlines()
    head = json.loads(lines[0])
    assert head["trigger_kind"] == "admission"
    trigger = [json.loads(ln) for ln in lines[1:]
               if json.loads(ln)["seq"] == head["trigger_seq"]]
    assert trigger and trigger[0]["outcome"] == "overloaded"


# ---------------------------------------------------------------------------
# SLO engine: exactly one flight record per injected episode
# ---------------------------------------------------------------------------

def test_injected_slo_violation_exactly_one_flight_record(rng, tmp_path):
    fam = _family_64(rng)
    drive_rng = np.random.default_rng(3)
    # p99 budget of 1 microsecond: every batch violates immediately
    tel = obs.Telemetry(str(tmp_path), slos=[SLOSpec(p99_ms=1e-3)],
                        window_s=60.0)
    with tel, fam.async_engine(telemetry=tel, min_bucket=8) as eng:
        _drive(eng, drive_rng, n_requests=60)
        tel.evaluate_slos(force=True)
        # keep violating: further evaluations must NOT re-fire
        _drive(eng, drive_rng, n_requests=20)
        tel.evaluate_slos(force=True)
        tel.evaluate_slos(force=True)
    viol = [e for e in tel.events() if e.kind == "slo_violation"]
    assert len(viol) == 1
    assert viol[0].fields["objective"] == "p99_ms"
    recs = [p for p in tel.flight_records if "slo_violation" in p]
    assert len(recs) == 1
    lines = open(recs[0]).read().splitlines()
    head = json.loads(lines[0])
    assert head["schema"] == "sparkglm.flight_record.v1"
    assert head["trigger_kind"] == "slo_violation"
    body = [json.loads(ln) for ln in lines[1:]]
    # the triggering event is pinned and present, and the dump is in seq
    # order (sinks run under the tracer lock)
    assert body[-1]["seq"] == head["trigger_seq"]
    assert body[-1]["kind"] == "slo_violation"
    assert [e["seq"] for e in body] == sorted(e["seq"] for e in body)


def test_slo_recovery_transition(rng):
    reg = MetricsRegistry()
    tr = FitTracer([ring := RingBufferSink(64)], metrics=reg)
    mon = SLOMonitor([SLOSpec(p99_ms=100.0, min_count=1)], metrics=reg,
                     tracer=tr, window_s=0.5)
    mon.watch_engine("e")
    h = reg.histogram("serve.e.latency_s")
    h.observe(10.0)  # 10 s >> 100 ms
    assert mon.evaluate(now=100.0, force=True)
    assert mon.violating == (("*", "p99_ms"),)
    # a later window with only fast observations recovers
    h.observe(0.001)
    assert not mon.evaluate(now=101.0, force=True)
    assert mon.violating == ()
    kinds = [e.kind for e in ring.events]
    assert kinds.count("slo_violation") == 1
    assert kinds.count("slo_recovered") == 1


def test_staleness_objective(rng):
    tr = FitTracer([ring := RingBufferSink(16)])
    mon = SLOMonitor([SLOSpec(staleness_s=5.0)], tracer=tr)
    tr.add_sink(mon)
    assert not mon.evaluate(now=0.0, force=True)  # never fresh: unknown
    tr.emit("chunk_ingested", chunk=1, rows=4, tenants=1)
    import time as _t
    t0 = _t.time()
    assert not mon.evaluate(now=t0 + 1.0, force=True)
    fired = mon.evaluate(now=t0 + 60.0, force=True)
    assert fired and fired[0]["objective"] == "staleness_s"


# ---------------------------------------------------------------------------
# drift episode -> one flight record, cycle-scoped traces
# ---------------------------------------------------------------------------

def _online_loop_with_drift(tmp_path, shift):
    """A tiny gaussian online fleet driven into (or not into) drift."""
    rng = np.random.default_rng(5)
    n, K = 240, 3
    g = [f"g{i % K}" for i in range(n)]
    x = rng.normal(size=n)
    y = 1.0 + 2.0 * x + 0.05 * rng.normal(size=n)
    tel = obs.Telemetry(str(tmp_path), slos=[], cooldown_s=0.0)
    loop = sg.online_fleet("y ~ x", dict(g=g, x=x, y=y), groups="g",
                           telemetry=tel, reference_chunks=2,
                           window_chunks=2, min_count=4,
                           drift_threshold=0.2)
    chunk_rng = np.random.default_rng(9)
    for c in range(8):
        m = 60
        tk = np.array([f"g{i % K}" for i in range(m)])
        Xc = np.column_stack([np.ones(m), chunk_rng.normal(size=m)])
        drifted = shift if c >= 4 else 0.0
        yc = ((1.0 + drifted) + (2.0 + drifted) * Xc[:, 1]
              + 0.05 * chunk_rng.normal(size=m))
        loop.step(tk, Xc, yc)
    return tel, loop


def test_injected_drift_episode_exactly_one_flight_record(tmp_path):
    tel, loop = _online_loop_with_drift(tmp_path, shift=8.0)
    drift = [e for e in tel.events() if e.kind == "drift_detected"]
    assert len(drift) >= 1
    recs = [p for p in tel.flight_records if "drift_detected" in p]
    assert len(recs) == len(drift)  # one record per episode, no extras
    lines = open(recs[0]).read().splitlines()
    head = json.loads(lines[0])
    assert head["trigger_kind"] == "drift_detected"
    body = [json.loads(ln) for ln in lines[1:]]
    trig = [e for e in body if e["seq"] == head["trigger_seq"]]
    assert trig and trig[0]["kind"] == "drift_detected"
    # every cycle event carries its deterministic cycle trace id
    assert trig[0]["trace"].startswith("cycle-")
    # the drift gauge exported
    snap = tel.metrics.snapshot()
    assert snap["gauges"]["online.drift.tv_max"] is not None
    tel.close()


def test_online_cycle_traces_are_deterministic(tmp_path):
    tel, _ = _online_loop_with_drift(tmp_path / "a", shift=0.0)
    cyc = sorted({e.fields["trace"] for e in tel.events()
                  if str(e.fields.get("trace", "")).startswith("cycle-")})
    assert cyc[0] == "cycle-000001" and cyc[-1] == "cycle-000008"
    tel.close()


# ---------------------------------------------------------------------------
# elastic: parent/child span structure
# ---------------------------------------------------------------------------

def test_elastic_shard_fits_are_child_spans(rng):
    n, p = 400, 3
    X = np.column_stack([np.ones(n), rng.normal(size=(n, p - 1))])
    y = X @ np.array([0.5, -0.2, 0.3]) + 0.01 * rng.normal(size=n)

    def source():
        for i in range(0, n, 100):
            lo, hi = i, i + 100
            yield lambda lo=lo, hi=hi: (X[lo:hi], y[lo:hi], None, None)

    ring = RingBufferSink(4096)
    sg.lm_fit_elastic(source, workers=2, shards=2,
                      xnames=["(Intercept)", "x1", "x2"],
                      trace=FitTracer([ring]))
    evs = ring.events
    root = [e for e in evs if e.kind == "fit_start"
            and e.fields.get("model") == "lm_elastic"][0]
    assert root.fields["trace"] == "elastic-000001"
    assert root.fields["span"] == "fit"
    for k in (0, 1):
        shard = [e for e in evs if e.kind == "shard_start"
                 and e.fields["shard"] == k][0]
        assert shard.fields["trace"] == "elastic-000001"
        assert shard.fields["span"] == f"shard-{k:04d}"
        assert shard.fields["parent_span"] == "fit"
    # the INNER streaming fit's events inherit the shard span
    inner = [e for e in evs if e.kind == "fit_start"
             and e.fields.get("model") == "lm_streaming"
             and e.fields.get("span") == "shard-0000"]
    assert inner and inner[0].fields["parent_span"] == "fit"


# ---------------------------------------------------------------------------
# satellite 1: instrument thread-safety hammer
# ---------------------------------------------------------------------------

def test_metrics_hammer_loses_no_increments():
    reg = MetricsRegistry()
    c = reg.counter("hammer")
    h = reg.histogram("hammer_h")
    T, N = 8, 5000

    def work():
        for i in range(N):
            c.inc()
            h.observe(0.5 + (i % 7) * 0.25)

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == T * N
    snap = h.snapshot()
    assert snap["count"] == T * N
    assert sum(snap["bucket_le"].values()) == T * N


def test_histogram_readers_see_consistent_state():
    h = MetricsRegistry().histogram("x")
    stop = threading.Event()
    bad = []

    def writer():
        i = 0
        while not stop.is_set():
            h.observe(2.0 ** (i % 5))
            i += 1

    def reader():
        while not stop.is_set():
            count, total, mn, mx, buckets = h._state()
            if sum(buckets.values()) != count:
                bad.append((count, buckets))

    ts = [threading.Thread(target=writer) for _ in range(3)] + \
        [threading.Thread(target=reader) for _ in range(2)]
    for t in ts:
        t.start()
    import time as _t
    _t.sleep(0.3)
    stop.set()
    for t in ts:
        t.join()
    assert not bad


# ---------------------------------------------------------------------------
# satellite 2: deterministic span sampling
# ---------------------------------------------------------------------------

def test_span_sample_rate_deterministic_stride():
    reset_span_sampling()
    ring = RingBufferSink(256)
    tr = FitTracer([ring])
    for _ in range(12):
        with obs.span("hot", tr, sample_rate=0.25):
            pass
    spans = [e for e in ring.events if e.kind == "span"]
    assert len(spans) == 3  # every 4th: indices 0, 4, 8
    assert all(e.fields["sample_rate"] == 0.25 for e in spans)
    # same seeded run, same sampled spans
    reset_span_sampling()
    ring2 = RingBufferSink(256)
    tr2 = FitTracer([ring2])
    for _ in range(12):
        with obs.span("hot", tr2, sample_rate=0.25):
            pass
    # structurally identical (seconds is wall time and excluded)
    strip = lambda e: (e.seq, e.kind, e.fields["name"],  # noqa: E731
                       e.fields["sample_rate"])
    assert [strip(e) for e in ring2.events] == [strip(e) for e in ring.events]


def test_span_sample_rate_edges():
    reset_span_sampling()
    ring = RingBufferSink(64)
    tr = FitTracer([ring])
    for _ in range(5):
        with obs.span("a", tr):            # default 1.0: every span
            pass
        with obs.span("b", tr, sample_rate=0.0):   # 0: never
            pass
    kinds = [(e.kind, e.fields["name"]) for e in ring.events]
    assert kinds == [("span", "a")] * 5
    # default-rate events do NOT carry a sample_rate field (byte-stable
    # with pre-existing traces)
    assert all("sample_rate" not in e.fields for e in ring.events)
    with pytest.raises(ValueError):
        obs.span("c", tr, sample_rate=1.5)


# ---------------------------------------------------------------------------
# satellite 3: ring determinism under wraparound + concurrent writers
# ---------------------------------------------------------------------------

def test_flight_ring_wraparound_keeps_exactly_last_n(tmp_path):
    rec = FlightRecorder(str(tmp_path), capacity=16, cooldown_s=0.0)
    tr = FitTracer([rec])
    for i in range(100):
        tr.emit("tick", i=i)
    path = rec.dump()
    body = [json.loads(ln) for ln in open(path).read().splitlines()[1:]]
    assert len(body) == 16
    assert [e["seq"] for e in body] == list(range(84, 100))
    assert [e["i"] for e in body] == list(range(84, 100))


def test_flight_ring_complete_under_concurrent_writers(tmp_path):
    rec = FlightRecorder(str(tmp_path), capacity=64, cooldown_s=0.0)
    tr = FitTracer([rec])
    T, N = 6, 300

    def work(w):
        for i in range(N):
            tr.emit("tick", w=w, i=i)

    ts = [threading.Thread(target=work, args=(w,)) for w in range(T)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    path = rec.dump()
    body = [json.loads(ln) for ln in open(path).read().splitlines()[1:]]
    # deterministic and complete: exactly the last 64 seqs, contiguous,
    # in order — possible only because sinks run under the tracer's
    # sequencing lock
    total = T * N
    assert [e["seq"] for e in body] == list(range(total - 64, total))


def test_flight_dump_atomic_and_cooldown(tmp_path):
    rec = FlightRecorder(str(tmp_path), capacity=8, cooldown_s=1e6)
    tr = FitTracer([rec])
    tr.emit("drift_detected", tenants=1, first="a")
    tr.emit("drift_detected", tenants=2, first="b")  # inside cooldown
    assert len(rec.records) == 1
    assert not any(p.endswith(".tmp") for p in os.listdir(tmp_path))


# ---------------------------------------------------------------------------
# export plane
# ---------------------------------------------------------------------------

def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("serve.eng.requests").inc(3)
    reg.gauge("fleet.models").set(64.0)
    reg.histogram("serve.eng.latency_s").observe(0.3)
    reg.histogram("serve.eng.latency_s").observe(1.7)
    text = obs.prometheus_text(reg)
    assert "# TYPE serve_eng_requests counter\nserve_eng_requests 3" in text
    assert "# TYPE fleet_models gauge\nfleet_models 64" in text
    # log2 buckets render cumulative with numeric le bounds + +Inf
    assert 'serve_eng_latency_s_bucket{le="0.5"} 1' in text
    assert 'serve_eng_latency_s_bucket{le="2"} 2' in text
    assert 'serve_eng_latency_s_bucket{le="+Inf"} 2' in text
    assert "serve_eng_latency_s_count 2" in text


def test_exporter_appends_jsonl(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c").inc()
    ex = obs.TelemetryExporter(str(tmp_path / "m.jsonl"), reg,
                               interval_s=60.0)
    ex.export_now()
    reg.counter("c").inc()
    ex.export_now()
    lines = [json.loads(ln)
             for ln in open(tmp_path / "m.jsonl").read().splitlines()]
    assert [ln["metrics"]["counters"]["c"] for ln in lines] == [1, 2]
    assert ex.exports == 2


def test_telemetry_facade_wiring(tmp_path):
    with obs.Telemetry(str(tmp_path), slos=[SLOSpec(p99_ms=50.0)]) as tel:
        assert tel.recorder is not None and tel.exporter is not None
        tel.tracer.emit("iter", i=1, deviance=2.0, ddev=0.1)
        assert tel.events()[-1].kind == "iter"
        assert "events_iter 1" in tel.prometheus()
        tel.export_now()
        assert tel.mint("x") == "x-000001"
    # close() flushed the exporter thread state; the file exists
    assert os.path.exists(tmp_path / "metrics.jsonl")


def test_context_merging_and_precedence():
    from sparkglm_tpu.obs import context as ctx_mod
    ring = RingBufferSink(16)
    tr = FitTracer([ring])
    root = ctx_mod.TraceContext(trace="t1", span="root")
    with ctx_mod.use(root):
        tr.emit("a")
        with ctx_mod.use(root.child("kid")):
            tr.emit("b")
            tr.emit("c", trace="explicit-wins")
        tr.emit("d")
    tr.emit("e")
    ev = {e.kind: e.fields for e in ring.events}
    assert ev["a"] == {"trace": "t1", "span": "root"}
    assert ev["b"] == {"trace": "t1", "span": "kid", "parent_span": "root"}
    assert ev["c"]["trace"] == "explicit-wins"
    assert ev["d"] == {"trace": "t1", "span": "root"}
    assert ev["e"] == {}  # no context -> no extra fields


def test_trace_event_roundtrip_unchanged():
    # guard: the context machinery must not perturb plain events
    e = TraceEvent(0, "k", 0.0, {"x": 1})
    assert e.key() == (0, "k", (("x", 1),))
