"""predict(type="terms") — R's per-term link-scale decomposition."""

import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.config import NumericConfig

F64 = NumericConfig(dtype="float64")


def test_terms_sum_to_link_prediction(rng):
    n = 400
    x = rng.standard_normal(n)
    z = rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    mu = np.exp(0.2 + 0.5 * x - 0.3 * z + (g == "b") * 0.4 - (g == "c") * 0.2)
    y = rng.poisson(mu).astype(float)
    d = {"y": y, "x": x, "z": z, "g": g}
    m = sg.glm("y ~ x + z + g", d, family="poisson", config=F64)
    new = {"x": x[:50], "z": z[:50], "g": g[:50]}
    tp = sg.predict(m, new, type="terms")
    assert tp.columns == ("x", "z", "g")
    eta = sg.predict(m, new, type="link")
    np.testing.assert_allclose(tp.matrix.sum(axis=1) + tp.constant, eta,
                               rtol=1e-6)
    # each term column is centered at the TRAINING design means: on the
    # training data itself every column has (near) zero mean
    tp_train = sg.predict(m, d, type="terms")
    np.testing.assert_allclose(tp_train.matrix.mean(axis=0), 0.0, atol=1e-6)


def test_terms_lm_manual(rng):
    n = 200
    x = rng.uniform(0, 2, n)
    y = 1.0 + 2.0 * x + 0.1 * rng.standard_normal(n)
    m = sg.lm("y ~ x", {"y": y, "x": x}, config=F64)
    tp = sg.predict(m, {"x": x[:5]}, type="terms")
    # manual R semantics: (x - mean(x_train)) * beta_x; constant =
    # beta0 + mean(x_train) * beta_x
    want = (x[:5].astype(np.float32).astype(np.float64)
            - np.float64(m.terms.col_means[1])) * m.coefficients[1]
    np.testing.assert_allclose(tp.matrix[:, 0], want, rtol=1e-5)
    assert tp.constant == pytest.approx(
        m.coefficients[0] + m.terms.col_means[1] * m.coefficients[1],
        rel=1e-9)


def test_terms_with_interaction_and_poly(rng):
    n = 300
    x = rng.uniform(-1, 1, n)
    g = np.array(["u", "v"])[rng.integers(0, 2, n)]
    y = 1 + x + 0.5 * x * x + (g == "v") * (0.3 + 0.4 * x) \
        + 0.1 * rng.standard_normal(n)
    d = {"y": y, "x": x, "g": g}
    m = sg.lm("y ~ poly(x, 2) + g + poly(x, 2):g", d, config=F64)
    tp = sg.predict(m, d, type="terms")
    assert tp.columns == ("poly(x, 2)", "g", "poly(x, 2):g")
    np.testing.assert_allclose(tp.matrix.sum(axis=1) + tp.constant,
                               sg.predict(m, d), rtol=1e-5)


def test_terms_validation(rng):
    x = rng.standard_normal(60)
    y = x + 0.1 * rng.standard_normal(60)
    m = sg.lm("y ~ x", {"y": y, "x": x})
    with pytest.raises(ValueError, match="takes no other"):
        sg.predict(m, {"x": x}, type="terms", se_fit=True)


def test_terms_no_intercept_uncentered(rng):
    """R centers type='terms' only when the model HAS an intercept; a
    no-intercept fit returns raw x*beta with constant 0."""
    x = rng.uniform(0.5, 2.0, 120)
    y = 2.0 * x + 0.05 * rng.standard_normal(120)
    m = sg.lm("y ~ x - 1", {"y": y, "x": x}, config=F64)
    tp = sg.predict(m, {"x": x[:4]}, type="terms")
    assert tp.constant == 0.0
    np.testing.assert_allclose(
        tp.matrix[:, 0],
        x[:4].astype(np.float32).astype(np.float64) * m.coefficients[0],
        rtol=1e-5)
