"""Distributed scoring (models/scoring.py) — VERDICT r2 missing #1.

The reference scores on the cluster (predictMultiple, LM.scala:52-61) and
tests 1-vs-4-partition equivalence (lmPredict$Test.scala:11-35); here the
same contract is 1-vs-8-device: the sharded SPMD pass must reproduce the
host predict bit-for-bit-ish (f64 on the CPU x64 mesh) including response
scale, offsets, se.fit, and aliased (NaN) coefficients.
"""

import numpy as np
import pytest

import sparkglm_tpu as sg


@pytest.fixture
def lm_model(rng):
    X = np.column_stack([np.ones(4000), rng.standard_normal((4000, 5))])
    y = X @ rng.standard_normal(6) + 0.3 * rng.standard_normal(4000)
    return sg.lm_fit(X, y), X


def test_lm_predict_sharded_matches_host(lm_model, mesh8, mesh1, rng):
    m, _ = lm_model
    Xn = np.column_stack([np.ones(1003), rng.standard_normal((1003, 5))])
    host = m.predict(Xn)
    np.testing.assert_allclose(m.predict(Xn, mesh=mesh8), host,
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(m.predict(Xn, mesh=mesh1), host,
                               rtol=1e-12, atol=1e-12)


def test_lm_predict_sharded_se_fit(lm_model, mesh8, rng):
    m, _ = lm_model
    Xn = np.column_stack([np.ones(997), rng.standard_normal((997, 5))])
    fit_h, se_h = m.predict(Xn, se_fit=True)
    fit_d, se_d = m.predict(Xn, mesh=mesh8, se_fit=True)
    np.testing.assert_allclose(fit_d, fit_h, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(se_d, se_h, rtol=1e-9, atol=1e-12)


def test_glm_predict_sharded_matches_host(mesh8, rng):
    X = np.column_stack([np.ones(3000), rng.standard_normal((3000, 4))])
    bt = rng.standard_normal(5) / 3
    y = rng.poisson(np.exp(np.clip(X @ bt, -4, 4))).astype(np.float64)
    off = rng.uniform(0, 0.5, 3000)
    m = sg.glm_fit(X, y, family="poisson", offset=off)
    Xn = np.column_stack([np.ones(1001), rng.standard_normal((1001, 4))])
    offn = rng.uniform(0, 0.5, 1001)
    for type_ in ("link", "response"):
        host = m.predict(Xn, type=type_, offset=offn)
        dev = m.predict(Xn, type=type_, offset=offn, mesh=mesh8)
        np.testing.assert_allclose(dev, host, rtol=1e-12, atol=1e-12)
    fit_h, se_h = m.predict(Xn, type="response", offset=offn, se_fit=True)
    fit_d, se_d = m.predict(Xn, type="response", offset=offn,
                            mesh=mesh8, se_fit=True)
    np.testing.assert_allclose(fit_d, fit_h, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(se_d, se_h, rtol=1e-9, atol=1e-12)


def test_sharded_predict_aliased_nan_coefficients(mesh8, rng):
    """Aliased models carry NaN coefficients and NaN covariance rows; the
    sharded path must reproduce R's reduced-basis prediction (NaNs as
    zeros), not propagate NaN through the matvec."""
    Xb = np.column_stack([np.ones(2000), rng.standard_normal((2000, 3))])
    X = np.column_stack([Xb, Xb[:, 1]])          # exact duplicate column
    y = Xb @ rng.standard_normal(4) + 0.1 * rng.standard_normal(2000)
    m = sg.lm_fit(X, y, singular="drop")
    assert np.isnan(m.coefficients).any()
    host = m.predict(X)
    fit_d, se_d = m.predict(X, mesh=mesh8, se_fit=True)
    np.testing.assert_allclose(fit_d, host, rtol=1e-12, atol=1e-12)
    assert np.all(np.isfinite(se_d))


def test_api_predict_through_mesh(mesh8, rng):
    """The formula front-end forwards mesh= to the sharded scorer."""
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, 2000)]
    x = rng.standard_normal(2000)
    y = 1.0 + 0.5 * x + (g == "b") * 0.7 + 0.2 * rng.standard_normal(2000)
    m = sg.lm("y ~ x + g", {"y": y, "x": x, "g": g})
    new = {"x": x[:500], "g": g[:500]}
    host = sg.predict(m, new)
    np.testing.assert_allclose(sg.predict(m, new, mesh=mesh8), host,
                               rtol=1e-12, atol=1e-12)


# ---------------------------------------------------------------------------
# out-of-core predict: predict(model, "path.csv") — VERDICT r3 #5
# ---------------------------------------------------------------------------

@pytest.fixture
def score_csv(tmp_path, rng):
    import csv as csv_mod
    n = 3000
    x = np.round(rng.standard_normal(n), 6)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    lt = np.round(rng.uniform(0.1, 0.9, n), 6)
    lam = np.exp(0.4 + 0.5 * x + 0.6 * (g == "b") + lt)
    y = rng.poisson(lam).astype(float)
    cols = {"y": y, "x": x, "g": g, "lt": lt}
    p = tmp_path / "score.csv"
    with open(p, "w", newline="") as fh:
        w = csv_mod.writer(fh)
        w.writerow(list(cols))
        for i in range(n):
            w.writerow([cols[nm][i] for nm in cols])
    return str(p), sg.read_csv(str(p))


def test_predict_from_csv_bit_parity(score_csv):
    """Chunked file scoring is BIT-identical to loading the file whole:
    every chunk runs the same resident per-row path."""
    path, data = score_csv
    m = sg.glm("y ~ x + g + offset(lt)", data, family="poisson")
    whole = sg.predict(m, data)
    chunked = sg.predict(m, path, chunk_bytes=1 << 12)  # many small chunks
    np.testing.assert_array_equal(np.asarray(chunked), np.asarray(whole))


def test_predict_from_csv_se_fit_and_link(score_csv):
    path, data = score_csv
    m = sg.glm("y ~ x + g + offset(lt)", data, family="poisson")
    fit_w, se_w = sg.predict(m, data, se_fit=True)
    fit_c, se_c = sg.predict(m, path, se_fit=True, chunk_bytes=1 << 12)
    np.testing.assert_array_equal(fit_c, fit_w)
    np.testing.assert_array_equal(se_c, se_w)
    np.testing.assert_array_equal(
        sg.predict(m, path, type="link", chunk_bytes=1 << 12),
        sg.predict(m, data, type="link"))


def test_predict_from_csv_lm_terms_and_offset_override(score_csv):
    path, data = score_csv
    m = sg.lm("y ~ x + g", data)
    tp_w = sg.predict(m, data, type="terms")
    tp_c = sg.predict(m, path, type="terms", chunk_bytes=1 << 12)
    np.testing.assert_array_equal(tp_c.matrix, tp_w.matrix)
    assert tp_c.columns == tp_w.columns and tp_c.constant == tp_w.constant
    # explicit by-name offset override on the path flow
    m2 = sg.lm("y ~ x + g", data, offset="lt")
    np.testing.assert_array_equal(
        sg.predict(m2, path, chunk_bytes=1 << 12),
        sg.predict(m2, data))
    with pytest.raises(ValueError, match="column NAME"):
        sg.predict(m2, path, offset=np.zeros(3000))


def test_predict_from_csv_out_path(score_csv, tmp_path):
    """out_path streams fit/se to disk for scoring runs whose output is
    also too big to hold; written values round-trip exactly (%.17g)."""
    path, data = score_csv
    m = sg.glm("y ~ x + g + offset(lt)", data, family="poisson")
    out = str(tmp_path / "scored.csv")
    ret = sg.predict(m, path, se_fit=True, chunk_bytes=1 << 12, out_path=out)
    assert ret == out
    got = sg.read_csv(out)
    fit_w, se_w = sg.predict(m, data, se_fit=True)
    np.testing.assert_array_equal(np.asarray(got["fit"]), fit_w)
    np.testing.assert_array_equal(np.asarray(got["se_fit"]), se_w)


# ---------------------------------------------------------------------------
# direct predict_sharded: offset= and vcov= together on a multi-device mesh
# ---------------------------------------------------------------------------

def test_predict_sharded_offset_and_vcov_together(mesh8, mesh1, rng):
    """The serving-era kernel signature exercised directly: an offset AND a
    coefficient covariance in the same call (se_fit through the quadform
    with the offset shifting eta), sharded over 8 devices, must match the
    single-device run bit-for-bit and the host composition to 1e-12."""
    from sparkglm_tpu.families.links import get_link
    from sparkglm_tpu.models.scoring import predict_sharded

    X = np.column_stack([np.ones(1003), rng.standard_normal((1003, 4))])
    beta = rng.standard_normal(5) / 3
    off = rng.uniform(0.0, 0.5, 1003)
    A = rng.standard_normal((5, 5))
    V = A @ A.T / 50.0
    lnk = get_link("log")

    for type_ in ("link", "response"):
        fit8, se8 = predict_sharded(X, beta, mesh=mesh8, offset=off, vcov=V,
                                    link=lnk, type=type_, se_fit=True)
        fit1, se1 = predict_sharded(X, beta, mesh=mesh1, offset=off, vcov=V,
                                    link=lnk, type=type_, se_fit=True)
        fit0, se0 = predict_sharded(X, beta, mesh=None, offset=off, vcov=V,
                                    link=lnk, type=type_, se_fit=True)
        np.testing.assert_array_equal(fit8, fit1)
        np.testing.assert_array_equal(se8, se1)
        np.testing.assert_array_equal(fit8, fit0)
        np.testing.assert_array_equal(se8, se0)
        # host composition: eta = X beta + off; se via quadform
        eta = X @ beta + off
        se_link = np.sqrt(np.maximum(np.einsum("ij,jk,ik->i", X, V, X), 0))
        if type_ == "link":
            np.testing.assert_allclose(fit8, eta, rtol=1e-12, atol=1e-12)
            np.testing.assert_allclose(se8, se_link, rtol=1e-9, atol=1e-12)
        else:
            mu = np.exp(eta)
            np.testing.assert_allclose(fit8, mu, rtol=1e-12, atol=1e-12)
            np.testing.assert_allclose(se8, se_link * np.abs(mu),
                                       rtol=1e-9, atol=1e-12)


def test_predict_sharded_pad_to_is_inert(mesh8, rng):
    """Zero-padding rows to a bucket (the serving contract) cannot change
    any real row, padded or sharded: outputs are row-local."""
    from sparkglm_tpu.models.scoring import predict_sharded

    X = np.column_stack([np.ones(37), rng.standard_normal((37, 3))])
    beta = rng.standard_normal(4)
    off = rng.uniform(0.0, 0.5, 37)
    V = np.eye(4) * 0.01
    plain = predict_sharded(X, beta, offset=off, vcov=V, se_fit=True)
    for pad in (37, 64, 128):
        padded = predict_sharded(X, beta, offset=off, vcov=V, se_fit=True,
                                 pad_to=pad)
        np.testing.assert_array_equal(padded[0], plain[0])
        np.testing.assert_array_equal(padded[1], plain[1])
    meshed = predict_sharded(X, beta, mesh=mesh8, offset=off, vcov=V,
                             se_fit=True, pad_to=64)
    np.testing.assert_array_equal(meshed[0], plain[0])
    np.testing.assert_array_equal(meshed[1], plain[1])
