"""Distributed scoring (models/scoring.py) — VERDICT r2 missing #1.

The reference scores on the cluster (predictMultiple, LM.scala:52-61) and
tests 1-vs-4-partition equivalence (lmPredict$Test.scala:11-35); here the
same contract is 1-vs-8-device: the sharded SPMD pass must reproduce the
host predict bit-for-bit-ish (f64 on the CPU x64 mesh) including response
scale, offsets, se.fit, and aliased (NaN) coefficients.
"""

import numpy as np
import pytest

import sparkglm_tpu as sg


@pytest.fixture
def lm_model(rng):
    X = np.column_stack([np.ones(4000), rng.standard_normal((4000, 5))])
    y = X @ rng.standard_normal(6) + 0.3 * rng.standard_normal(4000)
    return sg.lm_fit(X, y), X


def test_lm_predict_sharded_matches_host(lm_model, mesh8, mesh1, rng):
    m, _ = lm_model
    Xn = np.column_stack([np.ones(1003), rng.standard_normal((1003, 5))])
    host = m.predict(Xn)
    np.testing.assert_allclose(m.predict(Xn, mesh=mesh8), host,
                               rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(m.predict(Xn, mesh=mesh1), host,
                               rtol=1e-12, atol=1e-12)


def test_lm_predict_sharded_se_fit(lm_model, mesh8, rng):
    m, _ = lm_model
    Xn = np.column_stack([np.ones(997), rng.standard_normal((997, 5))])
    fit_h, se_h = m.predict(Xn, se_fit=True)
    fit_d, se_d = m.predict(Xn, mesh=mesh8, se_fit=True)
    np.testing.assert_allclose(fit_d, fit_h, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(se_d, se_h, rtol=1e-9, atol=1e-12)


def test_glm_predict_sharded_matches_host(mesh8, rng):
    X = np.column_stack([np.ones(3000), rng.standard_normal((3000, 4))])
    bt = rng.standard_normal(5) / 3
    y = rng.poisson(np.exp(np.clip(X @ bt, -4, 4))).astype(np.float64)
    off = rng.uniform(0, 0.5, 3000)
    m = sg.glm_fit(X, y, family="poisson", offset=off)
    Xn = np.column_stack([np.ones(1001), rng.standard_normal((1001, 4))])
    offn = rng.uniform(0, 0.5, 1001)
    for type_ in ("link", "response"):
        host = m.predict(Xn, type=type_, offset=offn)
        dev = m.predict(Xn, type=type_, offset=offn, mesh=mesh8)
        np.testing.assert_allclose(dev, host, rtol=1e-12, atol=1e-12)
    fit_h, se_h = m.predict(Xn, type="response", offset=offn, se_fit=True)
    fit_d, se_d = m.predict(Xn, type="response", offset=offn,
                            mesh=mesh8, se_fit=True)
    np.testing.assert_allclose(fit_d, fit_h, rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(se_d, se_h, rtol=1e-9, atol=1e-12)


def test_sharded_predict_aliased_nan_coefficients(mesh8, rng):
    """Aliased models carry NaN coefficients and NaN covariance rows; the
    sharded path must reproduce R's reduced-basis prediction (NaNs as
    zeros), not propagate NaN through the matvec."""
    Xb = np.column_stack([np.ones(2000), rng.standard_normal((2000, 3))])
    X = np.column_stack([Xb, Xb[:, 1]])          # exact duplicate column
    y = Xb @ rng.standard_normal(4) + 0.1 * rng.standard_normal(2000)
    m = sg.lm_fit(X, y, singular="drop")
    assert np.isnan(m.coefficients).any()
    host = m.predict(X)
    fit_d, se_d = m.predict(X, mesh=mesh8, se_fit=True)
    np.testing.assert_allclose(fit_d, host, rtol=1e-12, atol=1e-12)
    assert np.all(np.isfinite(se_d))


def test_api_predict_through_mesh(mesh8, rng):
    """The formula front-end forwards mesh= to the sharded scorer."""
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, 2000)]
    x = rng.standard_normal(2000)
    y = 1.0 + 0.5 * x + (g == "b") * 0.7 + 0.2 * rng.standard_normal(2000)
    m = sg.lm("y ~ x + g", {"y": y, "x": x, "g": g})
    new = {"x": x[:500], "g": g[:500]}
    host = sg.predict(m, new)
    np.testing.assert_allclose(sg.predict(m, new, mesh=mesh8), host,
                               rtol=1e-12, atol=1e-12)
