"""Async replicated serving (sparkglm_tpu/serve/async_engine.py).

The contracts under test:

  * backpressure stays TYPED under synthetic overload (Overloaded is a
    TransientSourceError a RetryPolicy classifies transient);
  * per-tenant deficit round-robin: a tenant flooding at well over
    capacity cannot starve a light tenant — the light tenant's requests
    ride the first few batches;
  * family deploy/rollback under live load are RECOMPILE-FREE (tables are
    runtime kernel args; refresh() re-snapshots, same shapes, same
    executables);
  * the default precision tier serves scores f64 BIT-identical to
    ``sg.predict`` (the engine is numerics-neutral, like every serving
    layer before it), and the bf16 tier's eta error respects the
    documented bound (PARITY.md).
"""

import asyncio
import threading
import time

import jax
import numpy as np
import pytest

import sparkglm_tpu as sg
from sparkglm_tpu.fleet import fit_many
from sparkglm_tpu.obs.metrics import MetricsRegistry
from sparkglm_tpu.robust import Overloaded, RetryPolicy, TransientSourceError
from sparkglm_tpu.serve import (AsyncEngine, EnginePolicy, ModelFamily,
                                ReplicatedScorer, family_score_cache_size)

pytestmark = pytest.mark.asyncio


def _segments(rng, sizes, p=3):
    groups, Xr, yr = [], [], []
    for g, size in enumerate(sizes):
        X = np.column_stack([np.ones(size), rng.normal(size=(size, p - 1))])
        beta = rng.normal(size=p) * (0.3 + 0.9 * g)
        y = (rng.random(size) < 1 / (1 + np.exp(-(X @ beta)))).astype(float)
        groups += [f"g{g}"] * size
        Xr.append(X)
        yr.append(y)
    return np.array(groups), np.vstack(Xr), np.concatenate(yr)


@pytest.fixture()
def family(rng):
    groups, X, y = _segments(rng, [200, 150, 180])
    fleet = fit_many(y, X, groups=groups, family="binomial",
                     has_intercept=True)
    return fleet, ModelFamily.from_fleet(fleet, "churn")


# ---------------------------------------------------------------------------
# backpressure + policy validation
# ---------------------------------------------------------------------------

class _BlockingScorer:
    """Duck scorer whose score() parks until released — makes the
    queue-full path deterministic (single implicit replica)."""

    metrics = None
    name = "blocked"

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def score(self, data, *, offset=None):
        self.entered.set()
        assert self.release.wait(10)
        return np.zeros(data.shape[0])


def test_engine_overload_typed_and_transient():
    bs = _BlockingScorer()
    met = MetricsRegistry()
    eng = AsyncEngine(bs, EnginePolicy(max_queue=2, max_wait_ms=0),
                      metrics=met, name="blocked")
    try:
        first = eng.submit(np.zeros((1, 2)))    # replica takes it, parks
        assert bs.entered.wait(10)
        held = [eng.submit(np.zeros((1, 2))) for _ in range(2)]
        with pytest.raises(Overloaded) as ei:
            eng.submit(np.zeros((1, 2)))
        assert isinstance(ei.value, TransientSourceError)
        assert RetryPolicy().is_transient(ei.value)
        assert met.snapshot()["counters"]["serve.blocked.overloaded"] == 1
    finally:
        bs.release.set()
        eng.close()
    for f in [first] + held:
        assert f.result(10) is not None
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(np.zeros((1, 2)))


def test_engine_row_cap_overload():
    bs = _BlockingScorer()
    eng = AsyncEngine(bs, EnginePolicy(max_queue=100, max_queue_rows=10,
                                       max_wait_ms=0), name="blocked")
    try:
        first = eng.submit(np.zeros((1, 2)))
        assert bs.entered.wait(10)
        held = eng.submit(np.zeros((10, 2)))   # fills the row budget
        with pytest.raises(Overloaded):
            eng.submit(np.zeros((1, 2)))
    finally:
        bs.release.set()
        eng.close()
    assert first.result(10) is not None and held.result(10) is not None


def test_engine_policy_validation():
    with pytest.raises(ValueError, match="max_batch"):
        EnginePolicy(max_batch=0)
    with pytest.raises(ValueError, match="max_wait_ms"):
        EnginePolicy(max_wait_ms=-1)
    with pytest.raises(ValueError, match="max_queue"):
        EnginePolicy(max_queue=0)
    with pytest.raises(ValueError, match="max_queue_rows"):
        EnginePolicy(max_queue_rows=0)
    with pytest.raises(ValueError, match="quantum"):
        EnginePolicy(quantum=0)


# ---------------------------------------------------------------------------
# fairness: deficit round-robin under a flooding tenant
# ---------------------------------------------------------------------------

class _StepFamilyScorer:
    """Family-duck scorer that blocks each batch on a semaphore and records
    per-tenant row counts — lets the test step batches one by one while
    the admission queue holds everything."""

    family_mode = True
    n_replicas = 1
    metrics = None
    name = "step"

    def __init__(self):
        self.step = threading.Semaphore(0)
        self.entered = threading.Event()
        self.batches = []

    def refresh(self):
        return False

    def tenant_indices(self, tenants):
        return np.array([{"A": 0, "B": 1}[t] for t in tenants], np.int32)

    def score_family(self, tidx, X, *, offset=None, replica=0):
        self.entered.set()
        assert self.step.acquire(timeout=10)
        self.batches.append(np.bincount(tidx, minlength=2))
        return np.zeros(len(tidx))


def test_tenant_fairness_no_starvation():
    """Tenant A floods 10x tenant B's traffic; DRR still serves B's whole
    queue within the first few batches instead of after A drains."""
    sc = _StepFamilyScorer()
    eng = AsyncEngine(sc, EnginePolicy(max_batch=8, quantum=4,
                                       max_queue=1000, max_wait_ms=0))
    try:
        plug = eng.submit(np.zeros((1, 2)), tenant="A")  # occupies replica
        assert sc.entered.wait(10)
        a = [eng.submit(np.zeros((2, 2)), tenant="A") for _ in range(40)]
        b = [eng.submit(np.zeros((2, 2)), tenant="B") for _ in range(4)]
        for _ in range(1 + 40 + 4):     # over-release; spare permits inert
            sc.step.release()
        for f in [plug] + a + b:
            assert f.result(20) is not None
    finally:
        eng.close()
    last_a = max(i for i, c in enumerate(sc.batches) if c[0])
    last_b = max(i for i, c in enumerate(sc.batches) if c[1])
    assert last_b < last_a, "flooded tenant finished before the light one"
    assert last_b <= 3, f"light tenant starved until batch {last_b}"
    total = np.sum(sc.batches, axis=0)
    assert total[0] == 81 and total[1] == 8  # every row served exactly once


def test_unknown_tenant_fails_alone(family):
    _, fam = family
    rsc = fam.replicated_scorer(type="link", devices=jax.devices()[:1])
    with AsyncEngine(rsc, EnginePolicy(max_wait_ms=5)) as eng:
        X = np.column_stack([np.ones(4), np.zeros((4, 2))])
        good = eng.submit(X, tenant="g0")
        bad = eng.submit(X, tenant="nope")
        assert good.result(10) is not None
        with pytest.raises(KeyError, match="nope"):
            bad.result(10)
    # family serving requires a tenant on every request
    with AsyncEngine(rsc) as eng2:
        with pytest.raises(ValueError, match="tenant"):
            eng2.submit(np.zeros((4, 3)))


# ---------------------------------------------------------------------------
# deploy/rollback under live load: recompile-free
# ---------------------------------------------------------------------------

def test_family_deploy_rollback_mid_load_recompile_free(family, rng):
    fleet, fam = family
    rsc = fam.replicated_scorer(type="link", devices=jax.devices()[:2],
                                min_bucket=8)
    # cover every bucket a coalesced batch of the phase loads can land in
    rsc.warmup(buckets=(8, 16, 32, 64, 128))
    assert rsc.compiles == 0
    base = family_score_cache_size()
    X = np.column_stack([np.ones(5), rng.normal(size=(5, 2))])
    with AsyncEngine(rsc, EnginePolicy(max_wait_ms=2)) as eng:
        # phase 1: champion serves v1 on every tenant, both replicas busy
        futs = [eng.submit(X, tenant=t) for t in ("g0", "g1", "g2") * 4]
        for i, f in enumerate(futs):
            np.testing.assert_allclose(
                f.result(10), fleet.predict(X, ("g0", "g1", "g2")[i % 3]),
                rtol=1e-12)
        # deploy v2 for g0 (fleet[1] IS g1's model) while the engine is up
        fam.register("g0", fleet[1], deploy=True)
        f2 = eng.submit(X, tenant="g0")
        np.testing.assert_allclose(f2.result(10), fleet.predict(X, "g1"),
                                   rtol=1e-12)
        # rollback restores v1, still mid-load
        fam.rollback("g0")
        f3 = eng.submit(X, tenant="g0")
        np.testing.assert_allclose(f3.result(10), fleet.predict(X, "g0"),
                                   rtol=1e-12)
    assert family_score_cache_size() - base == 0, \
        "deploy/rollback must not recompile (tables are runtime args)"
    assert rsc.compiles == 0
    # the family-side cache returns the SAME generation-following scorer
    assert fam.replicated_scorer(type="link", devices=jax.devices()[:2],
                                 min_bucket=8) is rsc


# ---------------------------------------------------------------------------
# precision tiers
# ---------------------------------------------------------------------------

@pytest.fixture
def poisson_offset_model(rng):
    n = 600
    x = rng.standard_normal(n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    lt = rng.uniform(0.1, 0.9, n)
    y = rng.poisson(np.exp(0.4 + 0.5 * x + 0.6 * (g == "b") + lt))
    d = {"y": y.astype(float), "x": x, "g": g, "lt": lt}
    return sg.glm("y ~ x + g + offset(lt)", d, family="poisson"), d


def _newdata(rng, d, size):
    idx = rng.integers(0, len(next(iter(d.values()))), size)
    return {k: np.asarray(v)[idx] for k, v in d.items()}


def test_async_default_tier_bit_identical_to_predict(
        poisson_offset_model, rng):
    """f64 scores served through the async engine == sg.predict, bit for
    bit — including the fit-time by-name offset recovery."""
    m, d = poisson_offset_model
    rsc = ReplicatedScorer(m, devices=[jax.devices()[0]], min_bucket=8)
    rsc.warmup(buckets=(8, 16, 32, 64, 128))
    with AsyncEngine(rsc, EnginePolicy(max_wait_ms=5)) as eng:
        wants, futs = [], []
        for i in range(12):
            new = _newdata(rng, d, (i % 9) + 1)
            wants.append(sg.predict(m, new))
            futs.append(eng.submit(new))
        for want, fut in zip(wants, futs):
            np.testing.assert_array_equal(fut.result(10), want)
    assert rsc.compiles == 0


def test_bf16_tier_bounded_error(poisson_offset_model, rng):
    """The opt-in bf16 tier: eta error within the documented PARITY bound
    (~2^-7 of the row's absolute-sum inner product); the default tier is
    untouched.  Both run the SAME bucketed executables shape-wise."""
    m, d = poisson_offset_model
    new = _newdata(rng, d, 50)
    exact = ReplicatedScorer(m, devices=[jax.devices()[0]],
                             type="link").score(new)
    fast = ReplicatedScorer(m, devices=[jax.devices()[0]], type="link",
                            precision="bf16").score(new)
    X = np.asarray(sg.transform(new, m.terms), np.float64)
    bound = 2.0 ** -6 * np.max(
        np.abs(X) @ np.abs(np.nan_to_num(m.coefficients)))
    err = np.max(np.abs(fast - exact))
    assert err <= max(bound, 1e-12), (err, bound)
    with pytest.raises(ValueError, match="precision"):
        ReplicatedScorer(m, precision="fp8")


# ---------------------------------------------------------------------------
# asyncio front door
# ---------------------------------------------------------------------------

def test_asubmit_from_event_loop(poisson_offset_model, rng):
    m, d = poisson_offset_model
    rsc = ReplicatedScorer(m, devices=[jax.devices()[0]])
    news = [_newdata(rng, d, 5) for _ in range(6)]
    wants = [sg.predict(m, new) for new in news]

    async def drive(eng):
        return await asyncio.gather(
            *[eng.asubmit(new) for new in news])

    with AsyncEngine(rsc, EnginePolicy(max_wait_ms=5)) as eng:
        got = asyncio.run(drive(eng))
    for want, out in zip(wants, got):
        np.testing.assert_array_equal(out, want)


def test_blocking_score_and_latency_metrics(poisson_offset_model, rng):
    m, d = poisson_offset_model
    met = MetricsRegistry()
    rsc = ReplicatedScorer(m, devices=[jax.devices()[0]], metrics=met,
                           name="traffic")
    with AsyncEngine(rsc, metrics=met, name="traffic") as eng:
        new = _newdata(rng, d, 7)
        np.testing.assert_array_equal(eng.score(new), sg.predict(m, new))
    snap = met.snapshot()
    assert snap["histograms"]["serve.traffic.latency_s"]["count"] == 1
    assert snap["counters"]["serve.traffic.batches"] == 1
    assert snap["counters"]["serve.traffic.batched_rows"] == 7
    assert snap["histograms"]["serve.traffic.queue_depth"]["count"] == 1
